"""Effective potential generation (reference: src/potential/potential.cpp:236
Potential::generate): Poisson -> XC (unpolarized or collinear) -> V_eff
assembly, plus the energy integrals the reference reports (energy.hpp:280).

Collinear magnetism follows the reference's Field4D layout: charge rho and
magnetization m_z; the XC potential splits into the charge part V_xc and the
field B_z = (V_up - V_dn)/2 applied with opposite sign per spin
(potential/xc.cpp). Spin-independent pieces (V_loc, V_H) enter both spin
channels.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.context import SimulationContext
from sirius_tpu.core.fftgrid import g_to_r, r_to_g
from sirius_tpu.dft.density import symmetrize_pw, symmetrize_pw_device
from sirius_tpu.dft.poisson import hartree_potential_g
from sirius_tpu.dft.xc import XCFunctional


@dataclasses.dataclass
class PotentialResult:
    veff_g: np.ndarray  # fine G: charge part (V_loc + V_H + V_xc)
    bz_g: np.ndarray | None  # fine G: z field B_z (collinear) or None
    veff_r_coarse: np.ndarray  # [ns, coarse box] per-spin V for H application
    vha_g: np.ndarray
    vxc_g: np.ndarray  # fine G: XC potential alone (forces/NLCC)
    energies: dict
    # mGGA only: per-spin v_tau = de/dtau on the COARSE box for the
    # -1/2 div(v_tau grad) operator (ops/mgga.py); None otherwise
    vtau_r_coarse: np.ndarray | None = None


def _to_r(ctx, f_g):
    return np.asarray(
        g_to_r(jnp.asarray(f_g), jnp.asarray(ctx.gvec.fft_index), ctx.gvec.fft.dims)
    ).real


def _to_g(ctx, f_r):
    return np.asarray(
        r_to_g(
            jnp.asarray(f_r.astype(np.complex128)),
            jnp.asarray(ctx.gvec.fft_index),
            ctx.gvec.fft.dims,
        )
    )


def _inner_rr(ctx: SimulationContext, f_r: np.ndarray, g_r: np.ndarray) -> float:
    """Real-space integral over the cell: (Omega/N) sum_r f g."""
    return float(np.sum(f_r * g_r) * ctx.unit_cell.omega / f_r.size)


def _gradient_r(ctx, f_g):
    """grad f as three real-space fields."""
    return [
        _to_r(ctx, 1j * ctx.gvec.gcart[:, i] * f_g) for i in range(3)
    ]


def _divergence_g(ctx, vec_r):
    """div of a real-space vector field, returned in G space."""
    out = np.zeros(ctx.gvec.num_gvec, dtype=np.complex128)
    for i in range(3):
        out += 1j * ctx.gvec.gcart[:, i] * _to_g(ctx, vec_r[i])
    return out


def generate_potential(
    ctx: SimulationContext,
    rho_g: np.ndarray,
    xc: XCFunctional,
    mag_g: np.ndarray | None = None,
    tau_g: np.ndarray | None = None,
) -> PotentialResult:
    """tau_g (mGGA only): per-spin kinetic-energy density [ns, num_gvec]
    on the fine G set (ops/mgga.tau_kset through density_from_coarse_acc)."""
    dims = ctx.gvec.fft.dims
    polarized = mag_g is not None
    if xc.is_mgga and tau_g is None:
        raise ValueError("mGGA functional needs tau_g")
    tau_r = (
        None if tau_g is None
        else np.stack([_to_r(ctx, t) for t in np.atleast_2d(tau_g)])
    )

    vha_g = np.asarray(
        hartree_potential_g(jnp.asarray(rho_g), jnp.asarray(ctx.gvec.glen2))
    )
    rho_r = _to_r(ctx, rho_g)
    rho_core_r = (
        _to_r(ctx, ctx.rho_core_g) if np.any(ctx.rho_core_g) else np.zeros(dims)
    )

    if polarized:
        mag_r = _to_r(ctx, mag_g)
        # clip |m| <= rho_xc (reference density guard) and split channels;
        # the core charge is unpolarized and split evenly
        rho_xc = np.maximum(rho_r + rho_core_r, 1e-20)
        m = np.clip(mag_r, -rho_xc, rho_xc)
        n_up = 0.5 * (rho_xc + m)
        n_dn = 0.5 * (rho_xc - m)
        if xc.is_gga:
            gu = _gradient_r(ctx, 0.5 * (rho_g + ctx.rho_core_g + mag_g))
            gd = _gradient_r(ctx, 0.5 * (rho_g + ctx.rho_core_g - mag_g))
            suu = sum(g * g for g in gu)
            sdd = sum(g * g for g in gd)
            sud = sum(a * b for a, b in zip(gu, gd))
            taus = {}
            if xc.is_mgga:
                taus = dict(
                    tau_up=jnp.asarray(tau_r[0].ravel()),
                    tau_dn=jnp.asarray(tau_r[1].ravel()),
                )
            out = xc.evaluate_polarized(
                jnp.asarray(n_up.ravel()), jnp.asarray(n_dn.ravel()),
                jnp.asarray(suu.ravel()), jnp.asarray(sud.ravel()), jnp.asarray(sdd.ravel()),
                **taus,
            )
            v_up = np.asarray(out["v_up"]).reshape(dims)
            v_dn = np.asarray(out["v_dn"]).reshape(dims)
            vsuu = np.asarray(out["vsigma_uu"]).reshape(dims)
            vsud = np.asarray(out["vsigma_ud"]).reshape(dims)
            vsdd = np.asarray(out["vsigma_dd"]).reshape(dims)
            # v_s -= div(2 vs_ss grad n_s + vs_sd grad n_other)
            div_u = _to_r(ctx, _divergence_g(ctx, [2 * vsuu * a + vsud * b for a, b in zip(gu, gd)]))
            div_d = _to_r(ctx, _divergence_g(ctx, [2 * vsdd * b + vsud * a for a, b in zip(gu, gd)]))
            v_up = v_up - div_u
            v_dn = v_dn - div_d
        else:
            out = xc.evaluate_polarized(jnp.asarray(n_up.ravel()), jnp.asarray(n_dn.ravel()))
            v_up = np.asarray(out["v_up"]).reshape(dims)
            v_dn = np.asarray(out["v_dn"]).reshape(dims)
        e_r = np.asarray(out["e"]).reshape(dims)
        vxc_r = 0.5 * (v_up + v_dn)
        bz_r = 0.5 * (v_up - v_dn)
    else:
        rho_xc = np.maximum(rho_r + rho_core_r, 0.0)
        if xc.is_gga:
            g = _gradient_r(ctx, rho_g + ctx.rho_core_g)
            sigma = g[0] ** 2 + g[1] ** 2 + g[2] ** 2
            out = xc.evaluate(
                jnp.asarray(rho_xc.ravel()), jnp.asarray(sigma.ravel()),
                tau=None if not xc.is_mgga else jnp.asarray(tau_r[0].ravel()),
            )
            vxc_r = np.asarray(out["v"]).reshape(dims)
            vs = np.asarray(out["vsigma"]).reshape(dims)
            vxc_r = vxc_r - _to_r(ctx, _divergence_g(ctx, [2.0 * vs * gi for gi in g]))
        else:
            out = xc.evaluate(jnp.asarray(rho_xc.ravel()))
            vxc_r = np.asarray(out["v"]).reshape(dims)
        e_r = np.asarray(out["e"]).reshape(dims)
        bz_r = None

    exc_r = e_r / np.maximum(rho_xc, 1e-25)

    vxc_g = _to_g(ctx, vxc_r)
    veff_g = ctx.vloc_g + vha_g + vxc_g
    bz_g = _to_g(ctx, bz_r) if polarized else None
    if ctx.symmetry is not None and ctx.symmetry.num_ops > 1 and ctx.cfg.parameters.use_symmetry:
        veff_g = symmetrize_pw(ctx, veff_g)
        if bz_g is not None:
            bz_g = symmetrize_pw(ctx, bz_g, axial_z=True)

    # per-spin potentials on the coarse box for the local operator
    def to_coarse(f_g):
        return np.asarray(
            g_to_r(
                jnp.asarray(f_g[ctx.coarse_to_fine]),
                jnp.asarray(ctx.gvec_coarse.fft_index),
                ctx.fft_coarse.dims,
            )
        ).real

    if polarized:
        v_r = to_coarse(veff_g)
        b_r = to_coarse(bz_g)
        veff_r_coarse = np.stack([v_r + b_r, v_r - b_r])
    else:
        veff_r_coarse = to_coarse(veff_g)[None]

    # mGGA: v_tau per spin, smoothed through the coarse G set for the
    # -1/2 div(v_tau grad) operator; plus the int v_tau tau integral that
    # the eval_sum double-counting correction needs
    vtau_r_coarse = None
    e_vtau_tau = 0.0
    if xc.is_mgga:
        if polarized:
            vt = [
                np.asarray(out["vtau_up"]).reshape(dims),
                np.asarray(out["vtau_dn"]).reshape(dims),
            ]
        else:
            vt = [np.asarray(out["vtau"]).reshape(dims)]
        vtau_r_coarse = np.stack([to_coarse(_to_g(ctx, v)) for v in vt])
        e_vtau_tau = sum(
            _inner_rr(ctx, tau_r[s], vt[s]) for s in range(len(vt))
        )

    # energy integrals (reference names; valence rho except exc)
    vloc_r = _to_r(ctx, ctx.vloc_g)
    vha_r = _to_r(ctx, vha_g)
    veff_r_fine = _to_r(ctx, veff_g)
    energies = {
        "vha": _inner_rr(ctx, rho_r, vha_r),
        "vxc": _inner_rr(ctx, rho_r, vxc_r),
        "vloc": _inner_rr(ctx, rho_r, vloc_r),
        "veff": _inner_rr(ctx, rho_r, veff_r_fine),
        "exc": _inner_rr(ctx, rho_r + rho_core_r, exc_r),
        "bxc": _inner_rr(ctx, mag_r, _to_r(ctx, bz_g)) if polarized else 0.0,
        "vtau_tau": e_vtau_tau,
    }
    return PotentialResult(
        veff_g=veff_g,
        bz_g=bz_g,
        veff_r_coarse=veff_r_coarse,
        vha_g=vha_g,
        vxc_g=vxc_g,
        energies=energies,
        vtau_r_coarse=vtau_r_coarse,
    )


# ---------------------------------------------------------------------------
# Device-resident potential generation (jit twin of generate_potential for
# the fused SCF step, LDA/GGA; mGGA stays on the host fallback). All
# transforms and the XC evaluation run as traced jnp ops so the whole
# Poisson -> XC -> assembly chain compiles into the fused iteration; the
# context tables arrive as a device-array dict so nothing host-resident is
# captured in the compiled program.
# ---------------------------------------------------------------------------


def build_potential_device_tables(ctx: SimulationContext) -> dict:
    """Constant context tables (numpy) for generate_potential_device."""
    return {
        "glen2": ctx.gvec.glen2,
        "gcart": ctx.gvec.gcart,
        "fft_index": ctx.gvec.fft_index,
        "fft_index_coarse": ctx.gvec_coarse.fft_index,
        "c2f": ctx.coarse_to_fine,
        "vloc_re": np.real(ctx.vloc_g),
        "vloc_im": np.imag(ctx.vloc_g),
        "core_re": np.real(ctx.rho_core_g),
        "core_im": np.imag(ctx.rho_core_g),
    }


def generate_potential_device(
    xc: XCFunctional,
    rho_g: jnp.ndarray,  # [ng] complex (inside the compiled program)
    mag_g: jnp.ndarray | None,
    tb: dict,
    dims: tuple,
    dims_coarse: tuple,
    omega: float,
    sym_tb: dict | None = None,
) -> dict:
    """Traced generate_potential: returns veff_g/bz_g/vha_g/vxc_g (complex,
    program-internal), veff_r_coarse [ns, coarse box] real and the energy
    integrals as traced scalars. sym_tb (density.build_sym_pw_tables)
    enables the in-program PW symmetrization of veff/bz."""
    if xc.is_mgga:
        raise ValueError("device potential path does not support mGGA")
    polarized = mag_g is not None
    n = dims[0] * dims[1] * dims[2]
    cdt = rho_g.dtype

    def to_r(f_g):
        return jnp.real(g_to_r(f_g, tb["fft_index"], tuple(dims)))

    def to_g(f_r):
        return r_to_g(f_r.astype(cdt), tb["fft_index"], tuple(dims))

    def gradient_r(f_g):
        return [to_r(1j * tb["gcart"][:, i] * f_g) for i in range(3)]

    def divergence_g(vec_r):
        return sum(
            1j * tb["gcart"][:, i] * to_g(vec_r[i]) for i in range(3)
        )

    def inner_rr(f_r, g_r):
        return jnp.sum(f_r * g_r) * (omega / n)

    vloc_g = jax.lax.complex(tb["vloc_re"], tb["vloc_im"]).astype(cdt)
    rho_core_g = jax.lax.complex(tb["core_re"], tb["core_im"]).astype(cdt)
    vha_g = hartree_potential_g(rho_g, tb["glen2"])
    rho_r = to_r(rho_g)
    rho_core_r = to_r(rho_core_g)

    if polarized:
        mag_r = to_r(mag_g)
        rho_xc = jnp.maximum(rho_r + rho_core_r, 1e-20)
        m = jnp.clip(mag_r, -rho_xc, rho_xc)
        n_up = 0.5 * (rho_xc + m)
        n_dn = 0.5 * (rho_xc - m)
        if xc.is_gga:
            gu = gradient_r(0.5 * (rho_g + rho_core_g + mag_g))
            gd = gradient_r(0.5 * (rho_g + rho_core_g - mag_g))
            suu = sum(g * g for g in gu)
            sdd = sum(g * g for g in gd)
            sud = sum(a * b for a, b in zip(gu, gd))
            out = xc.evaluate_polarized(
                n_up.ravel(), n_dn.ravel(),
                suu.ravel(), sud.ravel(), sdd.ravel(),
            )
            v_up = out["v_up"].reshape(dims)
            v_dn = out["v_dn"].reshape(dims)
            vsuu = out["vsigma_uu"].reshape(dims)
            vsud = out["vsigma_ud"].reshape(dims)
            vsdd = out["vsigma_dd"].reshape(dims)
            v_up = v_up - to_r(divergence_g(
                [2 * vsuu * a + vsud * b for a, b in zip(gu, gd)]))
            v_dn = v_dn - to_r(divergence_g(
                [2 * vsdd * b + vsud * a for a, b in zip(gu, gd)]))
        else:
            out = xc.evaluate_polarized(n_up.ravel(), n_dn.ravel())
            v_up = out["v_up"].reshape(dims)
            v_dn = out["v_dn"].reshape(dims)
        e_r = out["e"].reshape(dims)
        vxc_r = 0.5 * (v_up + v_dn)
        bz_r = 0.5 * (v_up - v_dn)
    else:
        rho_xc = jnp.maximum(rho_r + rho_core_r, 0.0)
        if xc.is_gga:
            g = gradient_r(rho_g + rho_core_g)
            sigma = g[0] ** 2 + g[1] ** 2 + g[2] ** 2
            out = xc.evaluate(rho_xc.ravel(), sigma.ravel())
            vxc_r = out["v"].reshape(dims)
            vs = out["vsigma"].reshape(dims)
            vxc_r = vxc_r - to_r(divergence_g([2.0 * vs * gi for gi in g]))
        else:
            out = xc.evaluate(rho_xc.ravel())
            vxc_r = out["v"].reshape(dims)
        e_r = out["e"].reshape(dims)
        bz_r = None

    exc_r = e_r / jnp.maximum(rho_xc, 1e-25)

    vxc_g = to_g(vxc_r)
    veff_g = vloc_g + vha_g + vxc_g
    bz_g = to_g(bz_r) if polarized else None
    if sym_tb is not None:
        veff_g = symmetrize_pw_device(veff_g, sym_tb)
        if bz_g is not None:
            bz_g = symmetrize_pw_device(bz_g, sym_tb, axial_z=True)

    def to_coarse(f_g):
        return jnp.real(g_to_r(
            f_g[tb["c2f"]], tb["fft_index_coarse"], tuple(dims_coarse)))

    if polarized:
        v_r = to_coarse(veff_g)
        b_r = to_coarse(bz_g)
        veff_r_coarse = jnp.stack([v_r + b_r, v_r - b_r])
    else:
        veff_r_coarse = to_coarse(veff_g)[None]

    energies = {
        "vha": inner_rr(rho_r, to_r(vha_g)),
        "vxc": inner_rr(rho_r, vxc_r),
        "vloc": inner_rr(rho_r, to_r(vloc_g)),
        "veff": inner_rr(rho_r, to_r(veff_g)),
        "exc": inner_rr(rho_r + rho_core_r, exc_r),
        "bxc": (inner_rr(mag_r, to_r(bz_g)) if polarized
                else jnp.zeros((), dtype=jnp.float64)),
    }
    return {
        "veff_g": veff_g,
        "bz_g": bz_g,
        "veff_r_coarse": veff_r_coarse,
        "vha_g": vha_g,
        "vxc_g": vxc_g,
        "energies": energies,
    }
