"""Band occupations: smearing functions and Fermi-level search.

Reference: src/dft/smearing.cpp (definitions copied exactly, argument
x = E_F - e) and K_point_set::find_band_occupancies
(k_point_set.cpp:171-378, Newton with bisection fallback). Here the search
is a fixed-count bisection, fully vectorized over (k, spin, band) and
jit-able inside the SCF step.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

SQRT2 = 1.4142135623730951
SQRT_PI = 1.7724538509055159


def occupancy(kind: str, x: jnp.ndarray, w: float) -> jnp.ndarray:
    """f(x) in [0, 1] with x = mu - eps (reference smearing.cpp)."""
    t = x / w
    if kind == "gaussian":
        return 0.5 * (1.0 + jax.scipy.special.erf(t))
    if kind == "fermi_dirac":
        return 1.0 - 1.0 / (1.0 + jnp.exp(jnp.clip(t, -200, 200)))
    if kind == "cold":
        y = t - 1.0 / SQRT2
        return 0.5 * (1.0 + jax.scipy.special.erf(y)) + jnp.exp(
            -jnp.minimum(y * y, 200.0)
        ) / jnp.sqrt(2.0 * jnp.pi)
    if kind == "methfessel_paxton":
        # order-1 MP: reference smearing.cpp evaluates A1*H1(z)*e^{-z^2} at
        # z = -t with A1 = -1/(4 sqrt(pi)), H1(z) = 2z, so the term is
        # +2t e^{-t^2}/(4 sqrt(pi)) in terms of t = (mu - eps)/w.
        e = jnp.exp(-jnp.minimum(t * t, 200.0))
        return 0.5 * (1.0 + jax.scipy.special.erf(t)) + (2.0 * t) * e / (4.0 * SQRT_PI)
    raise ValueError(f"unknown smearing '{kind}'")


def entropy_term(kind: str, x: jnp.ndarray, w: float) -> jnp.ndarray:
    """Per-state entropy contribution (reference conventions; sums to the
    'entropy_sum' output; free energy = E_tot + entropy_sum)."""
    t = x / w
    if kind == "gaussian":
        return -jnp.exp(-jnp.minimum(t * t, 200.0)) * w / (2.0 * SQRT_PI)
    if kind == "fermi_dirac":
        f = 1.0 / (1.0 + jnp.exp(jnp.clip(t, -200, 200)))  # = 1 - occupancy
        fl = jnp.clip(f, 1e-30, 1.0)
        gl = jnp.clip(1.0 - f, 1e-30, 1.0)
        return w * (f * jnp.log(fl) + (1.0 - f) * jnp.log(gl))
    if kind == "cold":
        y = t - 1.0 / SQRT2
        return -jnp.exp(-jnp.minimum(y * y, 200.0)) * (w - SQRT2 * x) / (2.0 * SQRT_PI)
    if kind == "methfessel_paxton":
        # order-1 MP entropy: w (2t^2-1) e^{-t^2} / (4 sqrt(pi)), the QE
        # w1gauss(n=1) form; satisfies s'(x) = x f'(x) against the MP1
        # occupancy above. (reference smearing.cpp:200 has a typo in the
        # recursion coefficient, `i+4` for QE's `i*4`; we follow the
        # thermodynamically consistent QE form.) Unlike the other kinds this
        # term is not negative-definite (positive for |t| > 1/sqrt(2)).
        e = jnp.exp(-jnp.minimum(t * t, 200.0))
        return w * (2.0 * t * t - 1.0) * e / (4.0 * SQRT_PI)
    raise ValueError(f"unknown smearing '{kind}'")


@partial(jax.jit, static_argnames=("kind", "num_iter"))
def find_fermi(
    evals: jnp.ndarray,  # [nk, nspin, nb]
    kweights: jnp.ndarray,  # [nk]
    num_electrons: float,
    width: float,
    kind: str = "gaussian",
    max_occupancy: float = 2.0,
    num_iter: int = 80,
) -> tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Bisection for mu such that sum_k w_k sum_{s,b} max_occ * f(mu-e) = N.

    Returns (mu, occupations [nk, nspin, nb], entropy_sum)."""

    def count(mu):
        f = occupancy(kind, mu - evals, width)
        return jnp.sum(kweights[:, None, None] * f) * max_occupancy

    lo = jnp.min(evals) - 10.0
    hi = jnp.max(evals) + 10.0

    def body(_, bounds):
        lo, hi = bounds
        mid = 0.5 * (lo + hi)
        too_low = count(mid) < num_electrons
        return jnp.where(too_low, mid, lo), jnp.where(too_low, hi, mid)

    lo, hi = jax.lax.fori_loop(0, num_iter, body, (lo, hi))
    mu = 0.5 * (lo + hi)
    occ = max_occupancy * occupancy(kind, mu - evals, width)
    ent = max_occupancy * jnp.sum(
        kweights[:, None, None] * entropy_term(kind, mu - evals, width)
    )
    return mu, occ, ent
