"""Density mixers (reference: src/mixer/ — Linear, Anderson, Anderson_stable,
Broyden2 over a tuple of function spaces with configurable inner products,
mixer.hpp:37-63, mixer_factory.hpp:40-47 where "broyden1" is a
backward-compatibility alias of Anderson).

The mixed vector is rho(G) on the fine set (complex) plus optional trailing
components, with either the plain l2 inner product or the Hartree-weighted
G-space metric (4 pi / G^2, reference mixer_functions.cpp use_hartree) which
preconditions long-wavelength charge sloshing.

Algorithms (all limited-memory quasi-Newton on x_{n+1} = x_n - G_n f_n):
  linear           G_n = -beta I
  anderson         type-II multisecant, normal-equations least squares
                   (reference anderson_mixer.hpp; "broyden1" aliases here)
  anderson_stable  same least-squares problem solved through a
                   metric-weighted QR of the residual-difference block
                   (reference anderson_stable_mixer.hpp, Fang & Saad 2009)
  broyden2         recursive rank-1 inverse-Jacobian updates; the alpha_i
                   recursion of broyden2_mixer.hpp:63-80
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np


class Mixer:
    KNOWN = ("linear", "anderson", "anderson_stable", "broyden1", "broyden2")

    def __init__(
        self,
        cfg,
        glen2: np.ndarray | None = None,
        num_components: int = 1,
        extra_len: int = 0,
        omega: float | None = None,
        weight: np.ndarray | None = None,
        rms_weight: np.ndarray | None = None,
    ):
        """num_components: G-sized components (charge first, then
        magnetization); extra_len: trailing flat entries (occupation/density
        matrices, PAW) that are mixed passively — the reference gives them a
        ZERO inner product (mixer_functions.cpp density_function_property
        "do not contribute to mixing"), so they never steer the Anderson/
        Broyden coefficients or the rms.

        Channel metrics (reference mixer_functions.cpp): the plain inner
        product of two periodic functions is the real-space integral
        int f g dr = Omega sum_G f*(G) g(G); with use_hartree the CHARGE
        channel instead gets 4 pi sum_{G!=0} f* g / G^2. Both the metric and
        the rms normalization (inner / Omega per channel,
        mixer.hpp update_rms) need Omega — pass it with glen2. Without glen2
        (FP-LAPW mixed vector) a plain unweighted l2 over the whole vector
        is used.
        """
        if cfg.type not in self.KNOWN:
            raise ValueError(
                f"unknown mixer type '{cfg.type}' (supported: {self.KNOWN})"
            )
        self.beta = cfg.beta
        self.max_history = cfg.max_history
        self.kind = "anderson" if cfg.type == "broyden1" else cfg.type
        self.use_hartree = bool(cfg.use_hartree)
        self.weight = None
        self.rms_weight = None  # per-coefficient weight of the normalized rms
        self._eha_w = None  # 2 pi Omega / G^2 over the charge channel
        if glen2 is not None:
            if omega is None:
                raise ValueError("Mixer needs omega together with glen2")
            ng = len(glen2)
            g2 = np.where(glen2 > 1e-12, glen2, np.inf)
            self._eha_w = 2.0 * np.pi * omega / g2
            if cfg.use_hartree:
                w_charge = 4.0 * np.pi / g2
                # normalized by size = 1/Omega (mixer_functions.cpp
                # periodic_function_property_modified) -> MULTIPLIED by Omega
                rms_charge = omega * w_charge
            else:
                w_charge = np.full(ng, omega)
                rms_charge = np.ones(ng)
            self.weight = np.concatenate(
                [w_charge]
                + [np.full(ng, omega)] * (num_components - 1)
                + [np.zeros(extra_len)]
            )
            # plain channels: inner = Omega sum|d_G|^2, size = Omega -> 1/coeff
            self.rms_weight = np.concatenate(
                [rms_charge]
                + [np.ones(ng)] * (num_components - 1)
                + [np.zeros(extra_len)]
            )
        if weight is not None:
            # explicit metric (FP-LAPW mixed vector: real integration
            # measures per coefficient instead of the G-space construction)
            self.weight = np.asarray(weight)
            self.rms_weight = (
                self.weight if rms_weight is None else np.asarray(rms_weight)
            )
        self._x: list[np.ndarray] = []  # input history
        self._f: list[np.ndarray] = []  # residual history f = x_out - x_in
        # transferred secant pairs (import_secants), materialized into
        # (_x, _f) at the next mix() once the first residual is known
        self._sx: list[np.ndarray] = []
        self._sf: list[np.ndarray] = []

    def _inner(self, a: np.ndarray, b: np.ndarray) -> float:
        w = self.weight if self.weight is not None else 1.0
        return float(np.real(np.sum(w * np.conj(a) * b)))

    def residual_hartree_energy(self, x_mixed: np.ndarray, x_new: np.ndarray):
        """Hartree energy of the charge-channel residual (mixed - new):
        2 pi Omega sum_{G!=0} |drho_G|^2 / G^2 — the quantity the reference
        tests against density_tol when use_hartree is on (poisson.cpp
        density_residual_hartree_energy, dft_ground_state.cpp:251,353).
        None when the mixer has no G-space charge channel (FP-LAPW vector)."""
        if self._eha_w is None:
            return None
        n = len(self._eha_w)
        d = x_mixed[:n] - x_new[:n]
        return float(np.real(np.sum(self._eha_w * np.conj(d) * d)))

    def rms(self, x_in: np.ndarray, x_out: np.ndarray) -> float:
        """sqrt of the sum over channels of inner(d,d)/size (reference
        mixer.hpp update_rms with normalize=true)."""
        d = x_out - x_in
        if self.rms_weight is None:
            return float(np.sqrt(np.real(np.vdot(d, d)) / d.size))
        return float(
            np.sqrt(max(np.real(np.sum(self.rms_weight * np.conj(d) * d)), 0.0))
        )

    def _mix_anderson(self, x_in, f):
        # type-II Anderson: minimize ||f - sum g_j df_j|| in the metric,
        # df_j/dx_j spanned against the current point. Solved through a
        # truncated eigendecomposition of the Gram matrix: near machine-
        # precision residuals the df_j become numerically collinear and the
        # raw normal equations produce huge coefficients that extrapolate
        # to negative densities (NaN in GGA) — the reference guards the
        # same way with its `invertible` sysolve check
        # (anderson_mixer.hpp:137-140, skip the correction when singular).
        m = len(self._x)
        dfs = [f - self._f[j] for j in range(m)]
        dxs = [x_in - self._x[j] for j in range(m)]
        a = np.array([[self._inner(dfs[i], dfs[j]) for j in range(m)] for i in range(m)])
        b = np.array([self._inner(dfs[i], f) for i in range(m)])
        g = np.zeros(m)
        if np.all(np.isfinite(a)) and np.all(np.isfinite(b)):
            try:
                w, v = np.linalg.eigh(0.5 * (a + a.conj().T))
            except np.linalg.LinAlgError:
                w = v = None
            if w is not None:
                keep = w > 1e-12 * max(float(w[-1]), 0.0)
                if np.any(keep):
                    g = np.real(
                        v[:, keep] @ ((v[:, keep].conj().T @ b) / w[keep])
                    )
        x_opt = x_in - sum(gi * dxi for gi, dxi in zip(g, dxs))
        f_opt = f - sum(gi * dfi for gi, dfi in zip(g, dfs))
        out = x_opt + self.beta * f_opt
        if not np.all(np.isfinite(out)):
            return x_in + self.beta * f  # plain damped step
        return out

    def _diff_blocks(self, x_in, f):
        """Successive-difference blocks DF[:,i] = f_{i+1}-f_i etc. including
        the current point as the newest history entry."""
        xs = self._x + [x_in]
        fs = self._f + [f]
        n = len(xs)
        dfs = np.stack([fs[i + 1] - fs[i] for i in range(n - 1)], axis=1)
        dxs = np.stack([xs[i + 1] - xs[i] for i in range(n - 1)], axis=1)
        return dfs, dxs

    def _mix_anderson_stable(self, x_in, f):
        # Solve the same least-squares problem through a metric-weighted QR
        # of DF (reference anderson_stable_mixer.hpp):
        #   x+ = x + beta (f - DF k) - DX k,   k = R^{-1} Q^H W^{1/2} f
        # The projection DF k equals the weighted-space Q Q^H f backmapped,
        # but is formed in UNWEIGHTED space: components with zero metric
        # weight (the G=0 charge row under the Hartree metric) must not be
        # divided back by W^{-1/2}.
        dfs, dxs = self._diff_blocks(x_in, f)
        sw = np.sqrt(self.weight)[:, None] if self.weight is not None else 1.0
        q, r = np.linalg.qr(sw * dfs, mode="reduced")
        # guard rank deficiency: drop near-dependent directions, then
        # re-factorize the kept columns (subsetting Q/R of the original QR
        # would not factor the kept block unless only trailing columns drop)
        diag = np.abs(np.diag(r))
        keep = diag > 1e-12 * max(diag.max(), 1e-300)
        if not np.all(keep):
            dfs, dxs = dfs[:, keep], dxs[:, keep]
            if dfs.shape[1] == 0:
                return x_in + self.beta * f
            q, r = np.linalg.qr(sw * dfs, mode="reduced")
        h = q.conj().T @ (np.ravel(sw) * f if self.weight is not None else f)
        try:
            k = np.linalg.solve(r, h)
        except np.linalg.LinAlgError:
            return x_in + self.beta * f
        return x_in + self.beta * (f - dfs @ k) - dxs @ k

    def _mix_broyden2(self, x_in, f):
        # Recursive rank-1 inverse-Jacobian update, G_1 = -beta I
        # (reference broyden2_mixer.hpp:63-80):
        #   alpha_i = [<df_i, f_n> - sum_{j>i} alpha_j <df_i, df_j>] / <df_i, df_i>
        #   x+ = x + beta f - sum_i alpha_i (beta df_i + dx_i)
        dfs, dxs = self._diff_blocks(x_in, f)
        m = dfs.shape[1]
        gram = np.array(
            [[self._inner(dfs[:, i], dfs[:, j]) for j in range(m)] for i in range(m)]
        )
        rhs = np.array([self._inner(dfs[:, i], f) for i in range(m)])
        alpha = np.zeros(m)
        for i in range(m - 1, -1, -1):
            num = rhs[i] - sum(alpha[j] * gram[i, j] for j in range(i + 1, m))
            alpha[i] = num / gram[i, i] if gram[i, i] > 1e-300 else 0.0
        return x_in + self.beta * f - dfs @ (self.beta * alpha) - dxs @ alpha

    def mix(self, x_in: np.ndarray, x_out: np.ndarray) -> np.ndarray:
        f = x_out - x_in
        if self._sx and not self._x:
            # materialize transferred secants against the FIRST actual
            # residual: the pair (x_in - dx_j, f - df_j) makes the
            # difference-to-current blocks of every scheme below exactly
            # (dx_j, df_j) — the donor's Jacobian model enters without any
            # absolute residual claim (see import_secants)
            self._x = [x_in - dx for dx in self._sx]
            self._f = [f - df for df in self._sf]
        self._sx = []
        self._sf = []
        if self.kind == "linear" or not self._x:
            nxt = x_in + self.beta * f
        elif self.kind == "anderson":
            nxt = self._mix_anderson(x_in, f)
        elif self.kind == "anderson_stable":
            nxt = self._mix_anderson_stable(x_in, f)
        elif self.kind == "broyden2":
            nxt = self._mix_broyden2(x_in, f)
        else:
            raise ValueError(f"unknown mixer type '{self.kind}'")
        self._x.append(x_in.copy())
        self._f.append(f.copy())
        if len(self._x) > self.max_history:
            self._x.pop(0)
            self._f.pop(0)
        return nxt

    def flush_history(self) -> None:
        """Drop the quasi-Newton history. Rung 0 of the recovery ladder
        (dft/recovery.py): a history poisoned by a diverging trajectory is
        the most common Anderson/Broyden divergence amplifier, and the next
        mix() degrades gracefully to a plain damped step."""
        self._x = []
        self._f = []
        self._sx = []
        self._sf = []

    def export_history(self) -> dict:
        """(x, f) history as stacked arrays for checkpointing; empty dict
        when there is no history yet. Restoring via import_history makes a
        resumed host-path SCF bit-reproducible."""
        if not self._x:
            return {}
        return {"mix_x": np.stack(self._x), "mix_f": np.stack(self._f)}

    def import_history(self, hist: dict) -> None:
        if "mix_x" not in hist:
            self._x = []
            self._f = []
            return
        self._x = [np.asarray(r) for r in hist["mix_x"]]
        self._f = [np.asarray(r) for r in hist["mix_f"]]

    def import_secants(self, dxs, dfs) -> None:
        """Seed the quasi-Newton model with secant pairs (dx_j, df_j) from
        ANOTHER SCF run at a nearby geometry (cross-job warm start,
        campaigns/handoff.py). Absolute (x, f) pairs must not be imported
        across problems: they assert "the residual at the donor's fixed
        point is zero", which is false by O(h) for the child, and the
        least-squares solve then parks the trajectory there — a stall
        lasting until the stale rows age out of max_history. Differences
        carry only the Jacobian action (and are invariant under the
        delta-density translation of the guess), so they stay valid. The
        pairs are held pending and anchored at the child's first actual
        (x_in, f) inside mix(); flush_history drops pending pairs too, so
        the recovery ladder also clears a poisoned transfer."""
        keep = max(self.max_history - 1, 0)
        self._sx = [np.asarray(r) for r in dxs][-keep:] if keep else []
        self._sf = [np.asarray(r) for r in dfs][-keep:] if keep else []


# ---------------------------------------------------------------------------
# Device-resident mixer (the jitted twin of Mixer for the fused SCF step).
#
# The host Mixer above keeps python-list history and runs numpy eigh per
# call; inside a compiled SCF iteration the history must be fixed-shape
# device state instead. DeviceMixerState holds a fixed max_history block of
# (x_in, f) pairs as (re, im) leaves — real leaves only, per the
# real-boundary contract of parallel/batched.py — plus a fill counter.
# Unfilled slots stay exactly zero, which makes their residual-difference
# directions zero vectors: the Gram matrix rows vanish and the same
# 1e-12 * w_max eigenvalue cut the host _mix_anderson applies drops them,
# so the masked fixed-shape solve is numerically identical to the host
# variable-length one (tested in tests/test_fused_scf.py).
# ---------------------------------------------------------------------------


class DeviceMixerState(NamedTuple):
    """Fixed-shape mixing history: [max_history, nx] real leaves."""

    hx_re: jnp.ndarray
    hx_im: jnp.ndarray
    hf_re: jnp.ndarray
    hf_im: jnp.ndarray
    count: jnp.ndarray  # int32 scalar, number of valid history rows


def device_mixer_init(nx: int, max_history: int,
                      dtype=jnp.float64) -> DeviceMixerState:
    # distinct buffers per leaf: the fused carry donates them, and donating
    # one buffer under several leaves is an XLA error
    def z():
        return jnp.zeros((max_history, nx), dtype=dtype)

    return DeviceMixerState(z(), z(), z(), z(), jnp.zeros((), jnp.int32))


def device_mixer_weights(mixer: Mixer):
    """The (weight, rms_weight, eha_weight) triple of a host Mixer as a
    dict of device arrays, so the fused step mixes in the exact metric the
    host path uses."""
    if mixer.weight is None or mixer._eha_w is None:
        raise ValueError("device mixer needs the G-space metric "
                         "(construct the host Mixer with glen2/omega)")
    return {
        "w": jnp.asarray(mixer.weight),
        "rms_w": jnp.asarray(mixer.rms_weight),
        "eha_w": jnp.asarray(np.where(np.isfinite(mixer._eha_w),
                                      mixer._eha_w, 0.0)),
    }


def device_mix(state: DeviceMixerState, x_in: jnp.ndarray, x_new: jnp.ndarray,
               weights: dict, beta: float, kind: str, max_history: int):
    """One mixer update inside jit. x_in/x_new are complex packed vectors
    (complex exists only inside the compiled program); returns
    (new_state, x_mixed, rms, eha_res) with rms/eha traced scalars.

    Semantics match the host sequence in run_scf exactly:
      rms     = Mixer.rms(x_in, x_new)        [before mixing]
      x_mixed = Mixer.mix(x_in, x_new)
      eha_res = Mixer.residual_hartree_energy(x_mixed, x_new)
    """
    if kind not in ("linear", "anderson"):
        raise ValueError(f"device mixer supports linear/anderson, got '{kind}'")
    w = weights["w"]
    rms_w = weights["rms_w"]
    eha_w = weights["eha_w"]
    f = x_new - x_in
    rms = jnp.sqrt(jnp.maximum(
        jnp.real(jnp.sum(rms_w * jnp.conj(f) * f)), 0.0))

    if kind == "linear":
        out = x_in + beta * f
    else:
        m = max_history
        valid = (jnp.arange(m, dtype=jnp.int32) < state.count)[:, None]
        hx = jnp.where(valid, jax.lax.complex(state.hx_re, state.hx_im), 0.0)
        hf = jnp.where(valid, jax.lax.complex(state.hf_re, state.hf_im), 0.0)
        dfs = jnp.where(valid, f[None, :] - hf, 0.0)
        dxs = jnp.where(valid, x_in[None, :] - hx, 0.0)
        a = jnp.real(jnp.einsum("ix,x,jx->ij", jnp.conj(dfs), w, dfs))
        b = jnp.real(jnp.einsum("ix,x,x->i", jnp.conj(dfs), w, f))
        ok = jnp.all(jnp.isfinite(a)) & jnp.all(jnp.isfinite(b))
        a = jnp.where(ok, a, jnp.eye(m, dtype=a.dtype))
        ew, v = jnp.linalg.eigh(0.5 * (a + a.T))
        # zero-padded history rows produce exactly-zero eigenvalues; the
        # host threshold (1e-12 * largest) removes them along with any
        # numerically collinear directions
        thresh = 1e-12 * jnp.maximum(ew[-1], 0.0)
        keep = ew > thresh
        ew_safe = jnp.where(keep, ew, 1.0)
        g = v @ (jnp.where(keep, 1.0 / ew_safe, 0.0) * (v.T @ b))
        g = jnp.where(ok & (state.count > 0), g, 0.0)
        x_opt = x_in - jnp.einsum("i,ix->x", g.astype(dxs.dtype), dxs)
        f_opt = f - jnp.einsum("i,ix->x", g.astype(dfs.dtype), dfs)
        out = x_opt + beta * f_opt
        out = jnp.where(jnp.all(jnp.isfinite(jnp.real(out))
                                & jnp.isfinite(jnp.imag(out))),
                        out, x_in + beta * f)

    # push (x_in, f) into the newest slot; roll the block once full
    def _push(h_re, h_im, val):
        full = state.count >= max_history
        h_re = jnp.where(full, jnp.roll(h_re, -1, axis=0), h_re)
        h_im = jnp.where(full, jnp.roll(h_im, -1, axis=0), h_im)
        slot = jnp.minimum(state.count, max_history - 1)
        return (h_re.at[slot].set(jnp.real(val)),
                h_im.at[slot].set(jnp.imag(val)))
    hx_re, hx_im = _push(state.hx_re, state.hx_im, x_in)
    hf_re, hf_im = _push(state.hf_re, state.hf_im, f)
    new_state = DeviceMixerState(
        hx_re, hx_im, hf_re, hf_im,
        jnp.minimum(state.count + 1, max_history).astype(jnp.int32))

    n = eha_w.shape[0]
    d = out[:n] - x_new[:n]
    eha = jnp.real(jnp.sum(eha_w * jnp.conj(d) * d))
    return new_state, out, rms, eha


def schedule_res_tol(itsol, res_tol: float, dens_metric: float, nel: float,
                     hartree_metric: bool) -> float:
    """Next iteration's band-solve residual bar from the density residual
    (reference dft_ground_state.cpp:252-259): tol = min(scale0 * metric,
    scale1 * tol_prev), clamped at min_tolerance. With the Hartree metric
    the density bar is an energy — scale it per electron as the reference
    does before feeding the solver."""
    m = dens_metric / max(1.0, nel) if hartree_metric else dens_metric
    return max(
        itsol.min_tolerance,
        min(itsol.tolerance_scale[0] * m,
            itsol.tolerance_scale[1] * res_tol),
    )
