"""Density mixers (reference: src/mixer/ — Linear, Anderson, Broyden2 over a
tuple of function spaces with configurable inner products, mixer.hpp:37-63).

Round-1 scope: the mixed vector is rho(G) on the fine set (complex), with
either the plain l2 inner product or the Hartree-weighted G-space metric
(4 pi / G^2, reference mixer_functions.cpp use_hartree) which preconditions
long-wavelength charge sloshing.
"""

from __future__ import annotations

import numpy as np


class Mixer:
    # broyden1 appears in legacy reference decks (verification/test21)
    KNOWN = ("linear", "anderson", "anderson_stable", "broyden1", "broyden2")

    def __init__(
        self,
        cfg,
        glen2: np.ndarray | None = None,
        num_components: int = 1,
        extra_len: int = 0,
    ):
        """num_components: G-sized components (charge first, then e.g.
        magnetization); extra_len: trailing flat entries mixed with plain l2
        (occupation matrices etc., reference mixer tuple of function spaces).
        """
        if cfg.type not in self.KNOWN:
            raise ValueError(
                f"unknown mixer type '{cfg.type}' (supported: {self.KNOWN})"
            )
        self.beta = cfg.beta
        self.max_history = cfg.max_history
        self.kind = cfg.type
        self.weight = None
        if cfg.use_hartree and glen2 is not None:
            # Hartree metric on the charge component; plain l2 on the others
            # (magnetization), matching the reference mixer_functions.cpp
            g2 = np.where(glen2 > 1e-12, glen2, np.inf)
            w = 4.0 * np.pi / g2
            self.weight = np.concatenate(
                [w]
                + [np.ones_like(w)] * (num_components - 1)
                + [np.ones(extra_len)]
            )
        self._x: list[np.ndarray] = []  # input history
        self._f: list[np.ndarray] = []  # residual history f = x_out - x_in

    def _inner(self, a: np.ndarray, b: np.ndarray) -> float:
        w = self.weight if self.weight is not None else 1.0
        return float(np.real(np.sum(w * np.conj(a) * b)))

    def rms(self, x_in: np.ndarray, x_out: np.ndarray) -> float:
        d = x_out - x_in
        return float(np.sqrt(max(self._inner(d, d), 0.0) / d.size))

    def mix(self, x_in: np.ndarray, x_out: np.ndarray) -> np.ndarray:
        f = x_out - x_in
        if self.kind == "linear" or not self._x:
            nxt = x_in + self.beta * f
        elif self.kind in ("anderson", "anderson_stable", "broyden1", "broyden2"):
            # Anderson acceleration (type-II): minimize ||f - sum g_j df_j||
            m = len(self._x)
            dfs = [f - self._f[j] for j in range(m)]
            dxs = [x_in - self._x[j] for j in range(m)]
            a = np.array([[self._inner(dfs[i], dfs[j]) for j in range(m)] for i in range(m)])
            b = np.array([self._inner(dfs[i], f) for i in range(m)])
            try:
                g = np.linalg.lstsq(a + 1e-12 * np.trace(a) / max(m, 1) * np.eye(m), b, rcond=None)[0]
            except np.linalg.LinAlgError:
                g = np.zeros(m)
            x_opt = x_in - sum(gi * dxi for gi, dxi in zip(g, dxs))
            f_opt = f - sum(gi * dfi for gi, dfi in zip(g, dfs))
            nxt = x_opt + self.beta * f_opt
        else:
            raise ValueError(f"unknown mixer type '{self.kind}'")
        self._x.append(x_in.copy())
        self._f.append(f.copy())
        if len(self._x) > self.max_history:
            self._x.pop(0)
            self._f.pop(0)
        return nxt
