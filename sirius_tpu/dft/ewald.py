"""Ewald energy of point ions in a neutralizing electron background.

Matches the reference formula exactly (src/dft/energy.cpp ewald_energy):
  E = (2 pi / Omega) [ sum_{G!=0} |S(G)|^2 e^{-G^2/(4 a)} / G^2 - N_el^2/(4 a) ]
      - sqrt(a/pi) sum_i z_i^2
      + (1/2) sum_{i != j, T} z_i z_j erfc(sqrt(a) |r_ij + T|) / |r_ij + T|
with S(G) = sum_i z_i e^{i G r_i} and N_el = sum_i z_i (neutral cell).

The splitting parameter follows the reference's adaptive choice
(simulation_context.cpp:130): start at lambda = 1 and increase/decrease by
x2 until the G-space tail at pw_cutoff is below 1e-16.

The Ewald energy depends only on the lattice and ion positions, so it is
computed ONCE on the host at context creation (SimulationContext.e_ewald)
and hoisted out of the SCF loop entirely: the fused device-resident
iteration (dft/fused.py) folds it into the total energy as a compile-time
constant rather than re-evaluating or transferring it per iteration.
"""

from __future__ import annotations

import numpy as np
from scipy.special import erfc


def ewald_lambda(pw_cutoff: float, omega: float) -> float:
    lam = 1.0
    gmax2 = pw_cutoff * pw_cutoff
    for _ in range(100):
        upper = np.exp(-gmax2 / (4.0 * lam))
        if upper < 1e-16:
            return lam
        lam *= 0.5
    return lam


def ewald_energy(
    lattice: np.ndarray,
    positions: np.ndarray,  # fractional
    charges: np.ndarray,
    gcart: np.ndarray,  # (ng, 3), G=0 first
    millers: np.ndarray,  # (ng, 3)
    pw_cutoff: float,
) -> float:
    lattice = np.asarray(lattice, dtype=np.float64)
    omega = float(abs(np.linalg.det(lattice)))
    lam = ewald_lambda(pw_cutoff, omega)
    z = np.asarray(charges, dtype=np.float64)
    nel = z.sum()

    # G-space sum (skip G=0)
    g2 = np.sum(gcart[1:] ** 2, axis=1)
    phase = np.exp(2j * np.pi * (millers[1:] @ positions.T))  # (ng-1, natom)
    s = phase @ z
    ewald_g = float(np.sum(np.abs(s) ** 2 * np.exp(-g2 / (4 * lam)) / g2))
    ewald_g -= nel * nel / (4.0 * lam)
    ewald_g *= 2.0 * np.pi / omega
    ewald_g -= np.sqrt(lam / np.pi) * np.sum(z * z)

    # real-space sum over neighbor shells within erfc cutoff
    rc = 10.0 / np.sqrt(lam)  # erfc(10) ~ 2e-45
    # translation range covering sphere rc
    inv = np.linalg.inv(lattice)
    nmax = np.ceil(rc * np.linalg.norm(inv, axis=0)).astype(int) + 1
    ts = np.array(
        np.meshgrid(*[np.arange(-n, n + 1) for n in nmax], indexing="ij")
    ).reshape(3, -1).T
    tcart = ts @ lattice
    pos_cart = positions @ lattice
    ewald_r = 0.0
    d = pos_cart[:, None, None, :] - pos_cart[None, :, None, :] + tcart[None, None, :, :]
    dist = np.linalg.norm(d, axis=-1)  # (na, na, nt)
    mask = (dist > 1e-10) & (dist < rc)
    zz = z[:, None, None] * z[None, :, None]
    ewald_r = 0.5 * float(np.sum(np.where(mask, zz * erfc(np.sqrt(lam) * dist) / np.where(mask, dist, 1.0), 0.0)))
    return ewald_g + ewald_r
