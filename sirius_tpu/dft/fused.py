"""Device-resident SCF iteration: density -> potential -> mixer fused into
one compiled XLA program.

The host loop in dft/scf.py historically round-tripped the full G-sphere
density, potential and mixer history through numpy every iteration. On TPU
that per-iteration host traffic (plus the numpy Anderson solve) dominates
wall time once the band solve itself is compiled. This module packages the
entire post-band-solve pipeline

  coarse |psi|^2 accumulation -> fine-G density (+ ultrasoft augmentation,
  + point-group symmetrization) -> mixer (linear / Anderson) -> Hartree +
  XC + local potential assembly -> D-operator + H-diagonal refresh

as one jitted step over a donated carry (FusedCarry), so the only thing
fetched to the host per iteration is a [NUM_SCALARS] vector of convergence
and energy scalars. Everything obeys the real-boundary contract of
parallel/batched.py: the carry and all step outputs are REAL leaves —
(re, im) pairs for complex quantities — and complex dtypes exist only
inside the compiled program.

The Ewald energy and all geometry tables are hoisted: built once on the
host at FusedScf construction and uploaded as a constant pytree of device
arrays (`self.tables`), passed (not closed over) so the executable does not
embed them.

Selection: run_scf uses this path when control.device_scf is "auto"/true
and the deck is in the supported regime (PP-PW, no Hubbard/PAW/mGGA, plain
or Anderson mixing, batched k-set band solve). control.device_scf = false
keeps the host path — bit-identical to the pre-fusion code — as the debug
fallback; tests/test_fused_scf.py pins the two paths to ~1e-8 Ha agreement.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.core.fftgrid import r_to_g
from sirius_tpu.dft.density import (
    build_dm_sym_tables,
    build_sym_pw_tables,
    symmetrize_density_matrix_device,
    symmetrize_pw_device,
)
from sirius_tpu.dft.mixer import (
    DeviceMixerState,
    device_mix,
    device_mixer_init,
    device_mixer_weights,
)
from sirius_tpu.dft.potential import (
    build_potential_device_tables,
    generate_potential_device,
)
from sirius_tpu.ops.augmentation import (
    build_aug_device_tables,
    d_operator_device,
    rho_aug_g_device,
)
from sirius_tpu.parallel.batched import compute_h_diag_device, split_cplx

# indices into the per-iteration scalar record (the ONLY device->host
# traffic of a fused iteration)
S_RMS = 0  # mixer rms (pre-mix)
S_EHA = 1  # Hartree energy of the (mixed - new) charge residual
S_VHA = 2  # int rho v_ha
S_VXC = 3  # int rho v_xc
S_VLOC = 4  # int rho v_loc
S_VEFF = 5  # int rho v_eff
S_EXC = 6  # int (rho + rho_core) eps_xc
S_BXC = 7  # int m b_xc
S_E1 = 8  # E_pot[rho_out] under the OLD potential
S_E2 = 9  # E_pot[rho_out] under the NEW potential
S_EVAL = 10  # sum_k w_k occ eps
S_NEL = 11  # electron count from rho_out (audit)
S_MAG = 12  # total moment from m_out (pre-mix)
S_V0 = 13  # Re veff(G=0)
S_ENT = 14  # smearing entropy sum
S_FINITE = 15  # 1.0 when the mixed vector and new potential are all-finite
# -- numerics ledger (obs/numerics.py): cheap per-iteration invariants
# appended to the SAME record, so they ride the one existing readback --
S_ORTHO = 16  # max |psi^H S psi - I| (S-orthonormality of the band block)
S_CHG = 17  # |Re x_mixed[0] - Re x_new[0]| * omega (mixer charge drift)
S_SYM = 18  # max |P_sym rho_new - rho_new| (symmetrization idempotency)
S_HERM = 19  # max |H_nl - H_nl^H| (subspace nonlocal-H hermiticity)
NUM_SCALARS = 20


class FusedCarry(NamedTuple):
    """Donated SCF carry: all-real leaves (the jit-boundary contract)."""

    x_re: jnp.ndarray  # [nx] packed mixed vector (rho fine-G [+ mag])
    x_im: jnp.ndarray
    hx_re: jnp.ndarray  # [M, nx] mixer input history
    hx_im: jnp.ndarray
    hf_re: jnp.ndarray  # [M, nx] mixer residual history
    hf_im: jnp.ndarray
    count: jnp.ndarray  # int32, valid history rows
    veff_re: jnp.ndarray  # [ng] effective potential (for the e1 term)
    veff_im: jnp.ndarray
    bz_re: jnp.ndarray  # [ng] collinear field (zeros when unpolarized)
    bz_im: jnp.ndarray


class FusedScf:
    """One SCF deck's fused device-resident iteration.

    Construction uploads every geometry/metric table once; step() is the
    compiled per-iteration program; finalize() is the single end-of-loop
    host fetch that reconstitutes what the final report needs.
    """

    def __init__(self, ctx, xc, mixer, polarized: bool, do_symmetrize: bool,
                 beta_dev=None, exec_cache=None):
        self.ctx = ctx
        self.xc = xc
        self.polarized = bool(polarized)
        self.do_symmetrize = bool(do_symmetrize)
        self.ns = 2 if polarized else 1
        self.ng = ctx.gvec.num_gvec
        self.omega = float(ctx.unit_cell.omega)
        self.dims = tuple(ctx.gvec.fft.dims)
        self.dims_coarse = tuple(ctx.fft_coarse.dims)
        self.kind = mixer.kind
        self.mix_beta = float(mixer.beta)
        self.max_history = int(mixer.max_history)
        self.nx = self.ns * self.ng
        nbeta = ctx.beta.num_beta_total
        # same gate as the host density/D path: with ctx.aug present the
        # density matrix is accumulated and D screened even if some species
        # carry no augmentation (their tables are simply absent)
        self.has_aug = ctx.aug is not None and nbeta > 0

        tables = {
            "mixw": device_mixer_weights(mixer),
            "pot": build_potential_device_tables(ctx),
            "fft_index_coarse": ctx.gvec_coarse.fft_index,
            "c2f": ctx.coarse_to_fine,
            "ekin": np.asarray(ctx.gkvec.kinetic(), dtype=np.float64),
            "gmask": np.asarray(ctx.gkvec.mask, dtype=np.float64),
            "dion": np.real(np.asarray(ctx.beta.dion))
            if nbeta
            else np.zeros((0, 0)),
            # bare augmentation overlap Q: the S metric of the ledger's
            # orthonormality invariant (same table make_hkset_params uses)
            "qmat": np.real(np.asarray(ctx.beta.qmat))
            if (nbeta and ctx.beta.qmat is not None)
            else np.zeros((nbeta, nbeta)),
        }
        if beta_dev is not None:
            tables["beta_re"], tables["beta_im"] = beta_dev
        elif nbeta:
            tables["beta_re"], tables["beta_im"] = split_cplx(
                np.asarray(ctx.beta.beta_gk)
            )
        else:
            nk = ctx.gkvec.num_kpoints
            z = np.zeros((nk, 0, ctx.gkvec.ngk_max))
            tables["beta_re"], tables["beta_im"] = z, z
        if self.has_aug:
            tables["aug"] = build_aug_device_tables(
                ctx.unit_cell, ctx.gvec, ctx.aug, ctx.beta
            )
        if self.do_symmetrize:
            tables["sym"] = build_sym_pw_tables(ctx)
            tables["dm_sym"] = build_dm_sym_tables(ctx)
        # one-time upload; step() takes these as an argument so they are
        # program inputs, not baked-in constants
        self.tables = jax.tree_util.tree_map(jnp.asarray, tables)
        self.kweights_dev = jnp.asarray(np.asarray(ctx.kweights))
        if exec_cache is not None:
            # serving: reuse a previously-jitted step whose trace signature
            # matches. The jitted callable is a bound method of the FIRST
            # instance in the bucket; every trace constant it bakes in is
            # part of the signature, and the tables it operates on are
            # program inputs, so reuse is exact — padded decks in one shape
            # bucket skip XLA compilation entirely.
            self._step = exec_cache.get(
                ("fused_step", *self._trace_signature()),
                lambda: jax.jit(self._step_impl, donate_argnums=(1,)),
            )
        else:
            self._step = jax.jit(self._step_impl, donate_argnums=(1,))

    def _trace_signature(self) -> tuple:
        """Everything _step_impl bakes into its trace (instance attrs used
        inside the jitted body) plus the shapes/dtypes of its table inputs
        and the per-call array ranks (nk/nb/ngk). Two FusedScf instances
        with equal signatures compile to identical programs."""
        leaves, treedef = jax.tree_util.tree_flatten(self.tables)
        tab = tuple((tuple(x.shape), str(x.dtype)) for x in leaves)
        return (
            self.ns, self.ng, self.nx, self.omega,
            self.dims, self.dims_coarse,
            self.kind, self.mix_beta, self.max_history,
            self.has_aug, self.do_symmetrize, self.polarized,
            tuple(self.xc.names),
            self.ctx.gkvec.num_kpoints, self.ctx.num_bands,
            self.ctx.gkvec.ngk_max,
            str(treedef), tab,
            tuple(self.kweights_dev.shape),
        )

    # -- host <-> device edges -------------------------------------------

    def init_carry(self, x_mix: np.ndarray, pot,
                   history: dict | None = None) -> FusedCarry:
        """Seed the carry from the host-side initial packed vector and the
        initial potential (generated on the host once, before the loop).
        `history` optionally restores a checkpointed mixer history
        ({'mix_x': [m, nx], 'mix_f': [m, nx]} complex, oldest first) so a
        resumed fused run continues the same Anderson trajectory."""
        x_re, x_im = split_cplx(np.asarray(x_mix))
        st = device_mixer_init(self.nx, self.max_history)
        if history and "mix_x" in history:
            hx = np.asarray(history["mix_x"])[-self.max_history:]
            hf = np.asarray(history["mix_f"])[-self.max_history:]
            m = hx.shape[0]
            hx_re = np.asarray(st.hx_re).copy()
            hx_im = np.asarray(st.hx_im).copy()
            hf_re = np.asarray(st.hf_re).copy()
            hf_im = np.asarray(st.hf_im).copy()
            hx_re[:m], hx_im[:m] = np.real(hx), np.imag(hx)
            hf_re[:m], hf_im[:m] = np.real(hf), np.imag(hf)
            st = DeviceMixerState(
                jnp.asarray(hx_re), jnp.asarray(hx_im),
                jnp.asarray(hf_re), jnp.asarray(hf_im),
                jnp.asarray(np.int32(m)),
            )
        v_re, v_im = split_cplx(np.asarray(pot.veff_g))
        if self.polarized and pot.bz_g is not None:
            b_re, b_im = split_cplx(np.asarray(pot.bz_g))
        else:
            # distinct buffers (donated leaves must not alias)
            b_re, b_im = np.zeros(self.ng), np.zeros(self.ng)
        return FusedCarry(
            jnp.asarray(x_re), jnp.asarray(x_im),
            st.hx_re, st.hx_im, st.hf_re, st.hf_im, st.count,
            jnp.asarray(v_re), jnp.asarray(v_im),
            jnp.asarray(b_re), jnp.asarray(b_im),
        )

    def fetch_state(self, carry: FusedCarry, with_history: bool = False):
        """Host copy of the packed mixed vector (and optionally the mixer
        history) from a carry — the rollback-snapshot / autosave fetch of
        dft/recovery.py. Called OUTSIDE the scf::fused_step profile span:
        it is an explicit, supervised host transfer, not per-iteration
        traffic."""
        x = np.asarray(carry.x_re) + 1j * np.asarray(carry.x_im)
        if not with_history:
            return x, None
        m = int(np.asarray(carry.count))
        hist = {}
        if m > 0:
            hist["mix_x"] = (np.asarray(carry.hx_re)[:m]
                             + 1j * np.asarray(carry.hx_im)[:m])
            hist["mix_f"] = (np.asarray(carry.hf_re)[:m]
                             + 1j * np.asarray(carry.hf_im)[:m])
        return x, hist

    def step(self, carry, acc, dm_re, dm_im, ev, occ_w, ent, pr, pi):
        """One fused iteration. acc: [ns, coarse box] occupation-weighted
        |psi(r)|^2 from density_kset; (dm_re, dm_im): [ns, nbeta, nbeta]
        from density_matrix_kset (empty for norm-conserving); ev: [nk, ns,
        nb] float64 eigenvalues; occ_w = occ * kweights; ent: entropy sum;
        (pr, pi): [nk, ns, nb, ngk] band block (already live on device for
        density_kset — feeding it here adds no transfer) for the numerics
        ledger. All device arrays. Returns (new_carry, out_dict)."""
        return self._step(self.tables, carry, acc, dm_re, dm_im, ev,
                          occ_w, ent, pr, pi)

    def finalize(self, carry, out) -> dict:
        """The single end-of-loop host fetch: mixed density, D matrices,
        density-matrix blocks and residual for the final report/forces."""
        ctx = self.ctx
        x = np.asarray(carry.x_re) + 1j * np.asarray(carry.x_im)
        rho_g = x[: self.ng]
        mag_g = x[self.ng :] if self.polarized else None
        d_by_spin = list(np.asarray(out["dion"], dtype=np.float64))
        rho_resid_g = (
            np.asarray(out["resid_re"]) + 1j * np.asarray(out["resid_im"])
        )
        dm_blocks_by_spin = []
        if self.has_aug:
            dm = np.asarray(out["dm_re"]) + 1j * np.asarray(out["dm_im"])
            for ispn in range(self.ns):
                dm_blocks_by_spin.append([
                    dm[ispn, off : off + nbf, off : off + nbf]
                    for _, off, nbf in ctx.beta.atom_blocks(ctx.unit_cell)
                ])
        return {
            "rho_g": rho_g,
            "mag_g": mag_g,
            "d_by_spin": d_by_spin,
            "rho_resid_g": rho_resid_g,
            "dm_blocks_by_spin": dm_blocks_by_spin,
        }

    # -- the compiled program --------------------------------------------

    def _step_impl(self, tables, carry, acc, dm_re, dm_im, ev, occ_w, ent,
                   pr, pi):
        ng, ns, omega = self.ng, self.ns, self.omega
        cdt = jnp.complex128

        # density_from_coarse_acc, traced: 1/Omega, coarse r -> coarse G,
        # scatter onto the fine sphere
        acc = acc.astype(jnp.float64)
        rho_c = r_to_g(
            (acc / omega).astype(cdt), tables["fft_index_coarse"],
            self.dims_coarse,
        )
        rho_spin = jnp.zeros((ns, ng), dtype=cdt).at[:, tables["c2f"]].set(
            rho_c
        )

        dm = jax.lax.complex(
            dm_re.astype(jnp.float64), dm_im.astype(jnp.float64)
        )
        if self.has_aug:
            if self.do_symmetrize:
                dm = symmetrize_density_matrix_device(dm, tables["dm_sym"])
            rho_spin = rho_spin + rho_aug_g_device(dm, tables["aug"], ng)

        rho_new = jnp.sum(rho_spin, axis=0)
        mag_new = rho_spin[0] - rho_spin[1] if self.polarized else None
        nel_got = jnp.real(rho_new[0]) * omega
        if self.do_symmetrize:
            rho_new = symmetrize_pw_device(rho_new, tables["sym"])
            if self.polarized:
                mag_new = symmetrize_pw_device(
                    mag_new, tables["sym"], axial_z=True
                )
        mag_moment = (
            jnp.real(mag_new[0]) * omega if self.polarized
            else jnp.zeros((), dtype=jnp.float64)
        )

        # mixing (host-sequence semantics: rms pre-mix, eha post-mix)
        x_new = (
            jnp.concatenate([rho_new, mag_new]) if self.polarized else rho_new
        )
        x_in = jax.lax.complex(carry.x_re, carry.x_im)
        state = DeviceMixerState(
            carry.hx_re, carry.hx_im, carry.hf_re, carry.hf_im, carry.count
        )
        state, x_mixed, rms, eha = device_mix(
            state, x_in, x_new, tables["mixw"], self.mix_beta, self.kind,
            self.max_history,
        )
        resid = rho_new - x_in[:ng]  # output - input density (scf-corr force)

        # Harris term e1 against the potential this iteration's bands saw
        veff_old = jax.lax.complex(carry.veff_re, carry.veff_im)
        e1 = jnp.real(jnp.sum(jnp.conj(rho_new) * veff_old)) * omega
        if self.polarized:
            bz_old = jax.lax.complex(carry.bz_re, carry.bz_im)
            e1 = e1 + jnp.real(jnp.sum(jnp.conj(mag_new) * bz_old)) * omega

        # potential from the MIXED density
        rho_mix = x_mixed[:ng]
        mag_mix = x_mixed[ng:] if self.polarized else None
        pot = generate_potential_device(
            self.xc, rho_mix, mag_mix, tables["pot"], self.dims,
            self.dims_coarse, omega,
            sym_tb=tables["sym"] if self.do_symmetrize else None,
        )
        veff_new = pot["veff_g"]
        bz_new = pot["bz_g"]
        e2 = jnp.real(jnp.sum(jnp.conj(rho_new) * veff_new)) * omega
        if self.polarized:
            e2 = e2 + jnp.real(jnp.sum(jnp.conj(mag_new) * bz_new)) * omega
        v0 = jnp.real(veff_new[0])

        # next iteration's D matrices and H diagonal
        if self.has_aug:
            ds = []
            for s in range(ns):
                if self.polarized:
                    vs = veff_new + (bz_new if s == 0 else -bz_new)
                else:
                    vs = veff_new
                ds.append(
                    d_operator_device(vs, tables["dion"], tables["aug"],
                                      omega)
                )
            dion_new = jnp.stack(ds)
        else:
            dion_new = jnp.broadcast_to(
                tables["dion"][None], (ns,) + tables["dion"].shape
            )
        h_diag = compute_h_diag_device(
            tables["ekin"], tables["gmask"], tables["beta_re"],
            tables["beta_im"], dion_new, v0,
        )

        # ---- numerics ledger: per-iteration invariants, same record ----
        # Note the choice of invariants: quantities whose exact value is
        # known (I, 0) so the scalar directly reads as accumulated rounding
        # + algorithmic drift. The Gram matrix itself and the density
        # matrix are hermitian BITWISE in IEEE arithmetic (conjugate-mirror
        # products round identically), so their asymmetry is useless; the
        # chained-GEMM subspace H_nl below is not mirror-exact and does
        # measure rounding. dion here is the BARE table (not dion_new):
        # host and device then score the identical quantity regardless of
        # where each path is in its D-refresh cycle.
        psi_c = jax.lax.complex(
            pr.astype(jnp.float64), pi.astype(jnp.float64)
        ) * tables["gmask"][:, None, None, :]
        beta_c = jax.lax.complex(
            tables["beta_re"].astype(jnp.float64),
            tables["beta_im"].astype(jnp.float64),
        )
        qmat64 = tables["qmat"].astype(jnp.float64)
        bp = jnp.einsum("kxg,ksbg->ksbx", jnp.conj(beta_c), psi_c)
        gram = jnp.einsum("ksbg,kscg->ksbc", jnp.conj(psi_c), psi_c)
        gram = gram + jnp.einsum(
            "ksbx,xy,kscy->ksbc", jnp.conj(bp), qmat64, bp
        )
        nb = psi_c.shape[2]
        s_ortho = jnp.max(jnp.abs(gram - jnp.eye(nb, dtype=gram.dtype)))
        s_chg = jnp.abs(
            jnp.real(x_mixed[0]) - jnp.real(x_new[0])
        ) * omega
        if self.do_symmetrize:
            s_sym = jnp.max(jnp.abs(
                symmetrize_pw_device(rho_new, tables["sym"]) - rho_new
            ))
        else:
            s_sym = jnp.zeros((), dtype=jnp.float64)
        dion64 = tables["dion"].astype(jnp.float64)
        h_nl = jnp.einsum("ksbx,xy,kscy->ksbc", jnp.conj(bp), dion64, bp)
        s_herm = jnp.max(jnp.abs(
            h_nl - jnp.conj(jnp.swapaxes(h_nl, -1, -2))
        ))

        eval_sum = jnp.sum(occ_w * ev)
        e = pot["energies"]
        # device-side health sentinel (dft/recovery.py): a NaN anywhere in
        # the mixed vector or the new potential collapses every scalar to
        # NaN anyway, but jnp.isfinite makes the check explicit and also
        # catches an Inf confined to a single G component that the energy
        # sums could mask by cancellation
        finite = (
            jnp.all(jnp.isfinite(jnp.real(x_mixed)))
            & jnp.all(jnp.isfinite(jnp.imag(x_mixed)))
            & jnp.all(jnp.isfinite(jnp.real(veff_new)))
            & jnp.all(jnp.isfinite(jnp.imag(veff_new)))
            & jnp.all(jnp.isfinite(ev))
        ).astype(jnp.float64)
        scalars = jnp.stack([
            rms, eha, e["vha"], e["vxc"], e["vloc"], e["veff"], e["exc"],
            e["bxc"], e1, e2, eval_sum, nel_got, mag_moment, v0,
            ent.astype(jnp.float64), finite,
            s_ortho, s_chg, s_sym, s_herm,
        ])

        if self.polarized:
            bz_re, bz_im = jnp.real(bz_new), jnp.imag(bz_new)
        else:
            bz_re = bz_im = jnp.zeros(ng, dtype=jnp.float64)
        new_carry = FusedCarry(
            jnp.real(x_mixed), jnp.imag(x_mixed),
            state.hx_re, state.hx_im, state.hf_re, state.hf_im, state.count,
            jnp.real(veff_new), jnp.imag(veff_new), bz_re, bz_im,
        )
        out = {
            "scalars": scalars,
            "veff_r_coarse": pot["veff_r_coarse"],
            "dion": dion_new,
            "h_diag": h_diag,
            "dm_re": jnp.real(dm),
            "dm_im": jnp.imag(dm),
            "resid_re": jnp.real(resid),
            "resid_im": jnp.imag(resid),
        }
        return new_carry, out
