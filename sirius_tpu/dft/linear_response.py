"""DFPT-style linear response on a converged ground state.

Reference: the `sirius_linear_solver` C-API entry (src/api/sirius_api.cpp:6101)
that Quantum ESPRESSO's phonon/DFPT code drives, backed by the block-CG
solver (src/multi_cg/multi_cg.hpp) and the Sternheimer operator
A_i = H - eps_i S + alpha_pv sum_occ S|psi><psi|S
(lr::Linear_response_operator).

This module is that call's consumer-facing equivalent: given the converged
(psi, eps, occ) of one k-point/spin and a perturbation applied to the
occupied states (dv_psi = dV . psi), it solves for the first-order orbital
response dpsi and assembles the density response drho. The solve runs
through solvers.multi_cg — fixed-shape masked CG, jit-able end to end.

Conventions: psi rows are bands ([nb, ngk], S-normalized as produced by the
band solver); the CG works on column blocks [ngk, nrhs] internally.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from sirius_tpu.solvers.multi_cg import multi_cg, sternheimer_operator


def solve_sternheimer_k(
    apply_h_s,
    params,
    psi_occ,  # [nocc, ngk] converged occupied states at this (k, spin)
    eps_occ,  # [nocc] their band energies
    dv_psi,  # [nocc, ngk] perturbation applied to each state, (dV psi_i)
    alpha_pv: float = 1.0,
    tol: float = 1e-10,
    maxiter: int = 200,
):
    """First-order orbital response dpsi [nocc, ngk] of one (k, spin).

    Solves (H - eps_i S + alpha_pv S P S) dpsi_i = -Pc dv_psi_i with
    P = sum_occ |psi><psi| and Pc = 1 - S P the conduction projector: the
    right-hand side is projected out of the occupied manifold exactly like
    the reference (QE convention), and the alpha_pv shift makes the
    operator nonsingular there. Returns (dpsi, niter, res_norms)."""
    psi_c = jnp.asarray(psi_occ).T  # [ngk, nocc] columns
    eps = jnp.asarray(eps_occ)

    def apply_cols(x_cols):
        hx, sx = apply_h_s(params, x_cols.T)
        return hx.T, sx.T

    apply_a = sternheimer_operator(apply_cols, psi_c, eps, alpha_pv)
    _, s_psi = apply_cols(psi_c)

    b = -jnp.asarray(dv_psi).T  # [ngk, nocc]
    # conduction projection of the rhs: b <- b - S psi (psi^H b)
    b = b - s_psi @ (jnp.conj(psi_c).T @ b)

    x0 = jnp.zeros_like(b)
    x, niter, res = multi_cg(apply_a, x0, b, tol=tol, maxiter=maxiter)
    return x.T, niter, res


def density_response_k(
    ctx,
    ik: int,
    psi_occ: np.ndarray,  # [nocc, ngk]
    dpsi: np.ndarray,  # [nocc, ngk]
    occ: np.ndarray,  # [nocc] occupations (incl. k-weight if desired)
) -> np.ndarray:
    """drho(r) on the coarse box from the orbital response of one k:
    drho = sum_i f_i (psi_i* dpsi_i + c.c.) / Omega."""
    from sirius_tpu.core.fftgrid import g_to_r

    dims = ctx.fft_coarse.dims
    fft_index = jnp.asarray(ctx.gkvec.fft_index[ik])
    psi_r = np.asarray(
        g_to_r(jnp.asarray(psi_occ), fft_index, dims)
    )
    dpsi_r = np.asarray(g_to_r(jnp.asarray(dpsi), fft_index, dims))
    acc = np.einsum(
        "b,bxyz->xyz", np.asarray(occ), 2.0 * np.real(np.conj(psi_r) * dpsi_r)
    )
    return acc / ctx.unit_cell.omega


def apply_local_perturbation(ctx, ik: int, dv_r: np.ndarray, psi: np.ndarray):
    """dv_psi_i = dV(r) psi_i(r) gathered back onto the G+k sphere;
    dv_r: real potential perturbation on the coarse box."""
    from sirius_tpu.core.fftgrid import g_to_r, r_to_g

    dims = ctx.fft_coarse.dims
    fft_index = jnp.asarray(ctx.gkvec.fft_index[ik])
    psi_r = g_to_r(jnp.asarray(psi), fft_index, dims)
    prod = psi_r * jnp.asarray(dv_r)
    out = r_to_g(prod, fft_index, dims)
    return np.asarray(out) * np.asarray(ctx.gkvec.mask[ik])
