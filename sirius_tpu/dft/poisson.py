"""Hartree potential in reciprocal space (reference: potential/poisson.cpp:151,
PP-PW branch; the muffin-tin pseudo-charge method arrives with the LAPW layer).

V_H(G) = 4 pi rho(G) / G^2,  V_H(0) = 0 (jellium convention; the divergent
G=0 pieces of Hartree/local/Ewald cancel in the total energy, tracked term
by term exactly like the reference).
E_H = Omega/2 sum_G |rho(G)|^2 4 pi / G^2.

Both functions here are pure jnp and are traced directly inside the fused
device-resident SCF step (dft/fused.py) as well as called from the host
potential path — keep them free of host-side coercions.
"""

from __future__ import annotations

import jax.numpy as jnp


def hartree_potential_g(rho_g: jnp.ndarray, glen2: jnp.ndarray) -> jnp.ndarray:
    """rho(G) -> V_H(G) on the same G set (G=0 first, set to zero)."""
    g2 = jnp.where(glen2 > 1e-12, glen2, 1.0)
    v = 4.0 * jnp.pi * rho_g / g2
    return jnp.where(glen2 > 1e-12, v, 0.0)


def hartree_energy(rho_g: jnp.ndarray, vha_g: jnp.ndarray, omega: float) -> jnp.ndarray:
    """E_H = (Omega/2) sum_G rho*(G) V_H(G) (real by construction)."""
    return 0.5 * omega * jnp.real(jnp.sum(jnp.conj(rho_g) * vha_g))
