"""Structural relaxation of atomic positions (reference: sirius.scf task
ground_state_relax driven by Force + the vcsqnm optimizer for variable-cell;
here fixed-cell BFGS over Cartesian positions using the analytic forces).

Each objective evaluation is a converged SCF; successive steps warm-start
from the previous step's wave functions and a delta-extrapolated density
(rho_prev - rho_atomic(old positions) + rho_atomic(new positions)). The
geometry-step plumbing (fixed-shape context rebuild, delta-density guess,
warm-start assembly) is shared with the MD driver via dft/geometry.py, and
a shared ExecutableCache keeps the fused SCF compiled once across steps."""

from __future__ import annotations

import numpy as np


def relax_atoms(
    cfg,
    base_dir: str = ".",
    max_steps: int = 30,
    force_tol: float = 1e-4,
    ctx=None,
    exec_cache=None,
    devices=None,
) -> dict:
    import sirius_tpu.context as cm
    from sirius_tpu.dft.geometry import (
        context_at_positions,
        delta_density_guess,
        warm_start_state,
    )
    from sirius_tpu.dft.scf import run_scf

    cfg.control.print_forces = True
    if ctx is None:
        ctx = cm.SimulationContext.create(cfg, base_dir)
    if exec_cache is None:
        from sirius_tpu.serve.cache import ExecutableCache

        exec_cache = ExecutableCache()
    uc0 = ctx.unit_cell
    lat = uc0.lattice
    pos = uc0.positions.copy()
    history = []
    res = None

    warm = {"state": None, "rho_at": None}

    def scf_at(positions):
        from sirius_tpu.dft.density import initial_density_g

        c = context_at_positions(cfg, base_dir, positions, uc0)
        rho_at = initial_density_g(c)
        state = warm["state"]
        if state is not None:
            # delta-density extrapolation across the geometry step
            # (QE-style): carry the bonding rearrangement, move the atomic
            # superposition with the nuclei
            state = warm_start_state(
                state,
                rho_g=delta_density_guess(
                    state["rho_g"], warm["rho_at"], rho_at
                ),
            )
        out = run_scf(
            cfg, ctx=c, initial_state=state, keep_state=True,
            exec_cache=exec_cache, devices=devices,
        )
        warm["state"] = out.get("_state")
        warm["rho_at"] = rho_at
        return out

    # simple BFGS on cartesian coordinates with analytic gradient
    x = (pos @ lat).ravel()
    n = x.size
    h_inv = np.eye(n) / 5.0  # initial inverse Hessian ~ optical phonon scale
    g_prev = None
    x_prev = None
    for step in range(max_steps):
        res = scf_at(np.linalg.solve(lat.T, x.reshape(-1, 3).T).T)
        f = np.asarray(res["forces"])
        g = -f.ravel()  # gradient of free energy
        fmax = float(np.abs(f).max())
        history.append({
            "step": step,
            "free": res["energy"]["free"],
            "fmax": fmax,
            "scf_iterations": int(res["num_scf_iterations"]),
        })
        if fmax < force_tol:
            break
        if g_prev is not None:
            s = x - x_prev
            y = g - g_prev
            sy = float(s @ y)
            if sy > 1e-12:
                hy = h_inv @ y
                h_inv = (
                    h_inv
                    + np.outer(s, s) * (sy + y @ hy) / sy**2
                    - (np.outer(hy, s) + np.outer(s, hy)) / sy
                )
        dx = -h_inv @ g
        # trust radius
        norm = np.linalg.norm(dx)
        if norm > 0.25:
            dx *= 0.25 / norm
        x_prev, g_prev = x.copy(), g.copy()
        x = x + dx
    return {
        "converged": history[-1]["fmax"] < force_tol if history else False,
        "num_steps": len(history),
        "history": history,
        "final_positions": np.mod(
            np.linalg.solve(lat.T, x.reshape(-1, 3).T).T, 1.0
        ).tolist(),
        "ground_state": res,
    }
