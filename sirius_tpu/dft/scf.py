"""SCF ground-state driver (reference: src/dft/dft_ground_state.cpp find
:178-427 and the sirius.scf mini-app output JSON).

Orchestration is host python; the hot pieces (per-k solver, density
accumulation, potential algebra) are jitted. The per-k eigensolve warm-starts
from the previous iteration's wave functions.
"""

from __future__ import annotations

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.config.schema import Config, load_config
from sirius_tpu.context import SimulationContext
from sirius_tpu.dft.density import (
    atomic_moments,
    generate_density_g,
    initial_density_g,
    initial_magnetization_g,
    rho_real_space,
    symmetrize_pw,
)
from sirius_tpu.dft.mixer import Mixer, schedule_res_tol
from sirius_tpu.dft.occupation import find_fermi
from sirius_tpu.dft.potential import generate_potential
from sirius_tpu.dft.recovery import ScfSupervisor
from sirius_tpu.dft.xc import XCFunctional
from sirius_tpu.ops.atomic import atomic_orbitals
from sirius_tpu.ops.augmentation import d_operator, rho_aug_g
from sirius_tpu.ops.hamiltonian import apply_h_s, make_hk_params
from sirius_tpu.solvers.davidson import davidson
from sirius_tpu.obs import costs as obs_costs
from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs import numerics as obs_numerics
from sirius_tpu.obs import spans as obs_spans
from sirius_tpu.obs import tracing as obs_tracing
from sirius_tpu.obs.log import get_logger
from sirius_tpu.obs.trace import CAPTURE as obs_trace
from sirius_tpu.utils import checksums as _cks
from sirius_tpu.utils import devfail
from sirius_tpu.utils import faults
from sirius_tpu.utils.profiler import counters, profile, timer_report

logger = get_logger("dft.scf")

_ITERATIONS = obs_metrics.REGISTRY.counter(
    "scf_iterations_total", "SCF iterations executed")
_ITER_SECONDS = obs_metrics.REGISTRY.histogram(
    "scf_iteration_seconds", "wall time per SCF iteration",
    buckets=(0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
             10.0, 30.0, 60.0))
_RMS = obs_metrics.REGISTRY.gauge(
    "scf_density_rms", "latest density residual RMS")
_ETOT = obs_metrics.REGISTRY.gauge(
    "scf_total_energy_ha", "latest total energy [Ha]")
_RUNS = obs_metrics.REGISTRY.counter(
    "scf_runs_total", "run_scf completions by outcome")
_AUTOSAVES = obs_metrics.REGISTRY.counter(
    "scf_autosaves_total", "mid-run checkpoint writes")
_FORECAST_ITERS = obs_metrics.REGISTRY.gauge(
    "scf_forecast_iterations",
    "forecasted total SCF iterations to convergence (obs/forecast.py)")
_FORECAST_WARNING = obs_metrics.REGISTRY.gauge(
    "scf_forecast_warning",
    "divergence early-warning score in [0, 1] (obs/forecast.py)")
_STRAGGLER = obs_metrics.REGISTRY.counter(
    "scf_straggler_preempts_total",
    "runs preempted at a snapshot boundary by the straggler watchdog")


def _h_o_diag(ctx: SimulationContext, ik: int, v0: float, dmat: np.ndarray):
    """Diagonals of H and S for the preconditioner at one k (serial debug
    path) — same formulas as the production k-set path, by construction."""
    from sirius_tpu.parallel.batched import compute_h_diag, compute_o_diag

    h = compute_h_diag(ctx, np.asarray(dmat)[None], v0)[ik, 0]
    o = compute_o_diag(ctx)[ik]
    return h, o


def _initial_subspace(ctx: SimulationContext) -> jnp.ndarray:
    """LCAO + random-fill initial trial vectors [nk, nspin, nbig, ngk].

    nbig = max(num_bands, num_atomic_orbitals): the FULL atomic-orbital set
    must enter the initial subspace even when it exceeds num_bands —
    truncating it drops whole orbital characters (e.g. 3 of the 5 Fe 3d
    orbitals with nb=10, nao=13) and the band solver then locks on higher
    eigenpairs it can reach instead (reference initialize_subspace.hpp:27
    always spans all atomic wfs and keeps the lowest nb Ritz vectors;
    run_scf performs that rotation at the first iteration)."""
    nk = ctx.gkvec.num_kpoints
    nb = ctx.num_bands
    ngk = ctx.gkvec.ngk_max
    ao = atomic_orbitals(ctx.unit_cell, ctx.gkvec, ctx.cfg.parameters.gk_cutoff + 1e-9)
    nao = ao.shape[1]
    nbig = max(nb, nao)
    rng = np.random.default_rng(42)
    psi = np.zeros((nk, ctx.num_spins, nbig, ngk), dtype=np.complex128)
    for ik in range(nk):
        base = np.zeros((nbig, ngk), dtype=np.complex128)
        n0 = min(nao, nbig)
        if n0:
            base[:n0] = ao[ik, :n0]
        if nbig > n0:
            r = rng.standard_normal((nbig - n0, ngk)) + 1j * rng.standard_normal((nbig - n0, ngk))
            # damp high-G components so random vectors are smooth-ish
            damp = 1.0 / (1.0 + ctx.gkvec.kinetic()[ik])
            base[n0:] = r * damp
        base *= ctx.gkvec.mask[ik]
        for ispn in range(ctx.num_spins):
            psi[ik, ispn] = base
    # host numpy, NOT a device array: complex must never be device-resident
    # outside jit (parallel/batched.py real-boundary contract)
    return psi


def _subspace_rotate_host(x, hx, sx, nb):
    """Host wrapper over the shared solvers.davidson.subspace_rotate
    (serial debug path only)."""
    from sirius_tpu.solvers.davidson import subspace_rotate

    return np.asarray(
        subspace_rotate(jnp.asarray(x), jnp.asarray(hx), jnp.asarray(sx), nb)
    )


def default_autosave_path(cfg, base_dir: str) -> str:
    """Default autosave location, job-scoped when control.autosave_tag is
    set so several jobs sharing a workdir (the serving engine) do not
    clobber each other's checkpoints."""
    tag = str(getattr(cfg.control, "autosave_tag", "") or "")
    name = f"sirius_autosave.{tag}.h5" if tag else "sirius_autosave.h5"
    return os.path.join(base_dir, name)


def run_scf(*args, **kwargs) -> dict:
    """Trace-context front door: a standalone SCF gets its own trace_id;
    one inherited from serve/campaigns (scheduler enters the job's
    trace_context) is kept, so every span/event of this run carries the
    end-to-end trace. See _run_scf_inner for the full contract."""
    with obs_tracing.ensure_trace():
        return _run_scf_inner(*args, **kwargs)


def _run_scf_inner(
    cfg: Config,
    base_dir: str = ".",
    restart_from: str | None = None,
    save_to: str | None = None,
    ctx: SimulationContext | None = None,
    initial_state: dict | None = None,
    keep_state: bool = False,
    serial_bands: bool = False,
    resume: str | None = None,
    exec_cache=None,
    devices=None,
    initial_guess: tuple | None = None,
) -> dict:
    """initial_state: optional in-memory warm start {rho_g, mag_g, psi}
    (e.g. the `_state` of a previous run_scf at nearby atomic positions,
    used by relax/vcrelax between geometry steps); its optional "scf"
    sub-dict {mix_x, mix_f, res_tol} re-seeds the quasi-Newton mixer
    history and band tolerance (see initial_guess below). initial_guess:
    the simple front door to the same machinery — a (rho_g, psi) pair
    (either may be None) validated against the context shapes, e.g. an
    extrapolated density and wave functions from an MD predictor; a
    third element, the "scf" hint dict of a previous run's `_state`,
    additionally imports that run's mixer (x, f) history — a multisecant
    model of the SCF Jacobian that stays accurate at a nearby geometry,
    so the first mix() of the warm run takes a quasi-Newton step instead
    of a plain damped one (cross-job handoff, campaigns/handoff.py).
    keep_state: attach that
    state to the result as `_state` (costs a host copy of all wave
    functions; only geometry drivers ask for it). serial_bands: use the
    per-(k, spin) debug path instead of the production one-program batched
    k-set solve (parallel/batched.py). resume: path to a mid-SCF autosave
    (control.autosave_every) — restarts the loop at the saved iteration
    with the full mixer/wave-function/tolerance state, bit-reproducibly on
    the host path; unlike restart_from (density-only warm start of a NEW
    run), resume continues the SAME run after preemption.

    exec_cache: optional serve.cache.ExecutableCache — FusedScf reuses a
    previously-jitted step program when the trace signature matches (the
    serving engine's compile amortization). devices: explicit device list
    to run on (a scheduler slice); defaults to jax.devices()."""
    t0 = time.time()
    from sirius_tpu.utils.profiler import reset_timers

    reset_timers()
    if os.environ.get("SIRIUS_TPU_FAULTS"):
        # child processes (tools/soak_scf.py) inherit their fault plan via
        # the environment; in-process plans (faults.install) are untouched
        faults.load_env()
    obs_metrics.set_enabled(bool(getattr(cfg.control, "telemetry", True)))
    obs_metrics.install_jax_listeners()
    if cfg.control.verbosity >= 1:
        # deck-driven verbosity keeps printing per-iteration lines even
        # when the CLI -v flag was not given
        from sirius_tpu.obs.log import setup as _log_setup

        _log_setup(cfg.control.verbosity)
    if getattr(cfg.control, "events_path", ""):
        ep = cfg.control.events_path
        obs_events.configure(
            ep if os.path.isabs(ep) else os.path.join(base_dir, ep))
    if getattr(cfg.control, "trace_capture", ""):
        tc = cfg.control.trace_capture
        obs_trace.request(
            tc if os.path.isabs(tc) else os.path.join(base_dir, tc),
            steps=int(getattr(cfg.control, "trace_capture_steps", 5)))
    p = cfg.parameters
    if ctx is None:
        ctx = SimulationContext.create(cfg, base_dir)
    xc = XCFunctional(p.xc_functionals)
    nk, ns, nb = ctx.gkvec.num_kpoints, ctx.num_spins, ctx.num_bands
    nel = ctx.unit_cell.num_valence_electrons - p.extra_charge
    mgga = xc.is_mgga
    if mgga:
        if serial_bands:
            raise NotImplementedError("mGGA: production batched path only")
        if any(t.paw is not None for t in ctx.unit_cell.atom_types):
            raise NotImplementedError("mGGA with PAW is not supported")
        if ctx.aug is not None:
            import warnings

            warnings.warn(
                "mGGA with ultrasoft augmentation: tau is computed from the "
                "smooth wave functions only (no augmentation tau), matching "
                "the common PW-code approximation"
            )

    if nb * ctx.max_occupancy * ctx.num_spins < nel - 1e-12:
        raise ValueError(
            f"num_bands={nb} cannot hold {nel} electrons "
            f"(max {nb * ctx.max_occupancy * ctx.num_spins})"
        )
    if ctx.num_mag_dims == 3:
        from sirius_tpu.dft.scf_nc import run_scf_nc

        if restart_from or initial_state is not None or keep_state:
            raise NotImplementedError(
                "non-collinear SCF does not support checkpoint/warm-start "
                "state passing yet"
            )
        if save_to:
            import warnings

            warnings.warn(
                "non-collinear SCF does not write checkpoints yet; "
                "save_to ignored"
            )
        return run_scf_nc(cfg, base_dir, ctx=ctx)
    polarized = ctx.num_mag_dims == 1
    # wave-function precision: fp32 runs the band solve in complex64
    # (reference precision_wf, dft_ground_state.cpp:216-304 fp32 SCF with
    # fp64 polish via settings.fp32_to_fp64_rms)
    if p.precision_wf not in ("fp32", "fp64"):
        raise ValueError(f"precision_wf must be fp32 or fp64, got '{p.precision_wf}'")
    wf_dtype = jnp.complex64 if p.precision_wf == "fp32" else jnp.complex128

    from sirius_tpu.ops.hubbard import (
        HubbardData,
        constraint_reference_matrix,
        constraint_update,
        hubbard_potential_and_energy,
        initial_occupancy,
        occupation_matrix,
        register_sym_ops,
        symmetrize_occupation,
        u_matrix_for_k,
    )

    hub = HubbardData.build(ctx)
    vhub = None  # per-k apply matrices [nk, ns, nhub, nhub] (or None)
    um_nl: list = []
    om_nl = None
    hub_lagrange = None
    hub_om_cons = None
    hub_cons_state = {"err": np.inf, "steps": 0}
    hub_cons_active = False
    e_hub = e_hub_one_el = 0.0
    if hub is not None:
        register_sym_ops(hub, ctx)
        n0 = initial_occupancy(ctx, hub, ns)
        hub_om_cons = constraint_reference_matrix(hub, ns)
        if hub_om_cons is not None:
            # constrained blocks start AT the target occupancy (reference
            # Occupation_matrix::init constrained_calculation branch)
            n0 = np.where(np.abs(hub_om_cons) > 0, hub_om_cons, n0)
        om_nl0 = [
            np.zeros((ns, 2 * e["il"] + 1, 2 * e["jl"] + 1), dtype=np.complex128)
            for e in hub.nonloc
        ]
        hub_cons_active = hub_om_cons is not None
        um_local, um_nl, e_hub, e_hub_one_el = hubbard_potential_and_energy(
            hub, n0, ctx.max_occupancy, om_nl=om_nl0,
            lagrange=None, om_cons=None,
        )
        vhub = np.stack([
            u_matrix_for_k(hub, um_local, um_nl, ctx.gkvec.kpoints[ik])
            for ik in range(nk)
        ])

    # --- PAW on-site machinery (dft/paw.py; None when no PAW species) ---
    from sirius_tpu.dft import paw as paw_mod

    paw = paw_mod.PawData.build(ctx)
    paw_dm = paw.initial_dm(ctx) if paw is not None else None

    rho_g = initial_density_g(ctx)
    mag_g = initial_magnetization_g(ctx) if polarized else None
    if restart_from:
        from sirius_tpu.io.checkpoint import load_state

        state = load_state(restart_from, ctx)
        rho_g = state["rho_g"]
        if polarized:
            mag_g = state.get("mag_g", mag_g)
        if paw is not None and state.get("paw_dm") is not None:
            paw_dm = np.asarray(state["paw_dm"])
    resume_scf = None
    _resume_psi = None
    if resume:
        from sirius_tpu.io.checkpoint import load_state

        state = load_state(resume, ctx)
        rho_g = state["rho_g"]
        if polarized and state.get("mag_g") is not None:
            mag_g = state["mag_g"]
        if paw is not None and state.get("paw_dm") is not None:
            paw_dm = np.asarray(state["paw_dm"])
        resume_scf = state.get("scf")
        _resume_psi = state.get("psi")
    psi = None
    guess_scf = None
    if initial_state is not None:
        rho_g = np.asarray(initial_state["rho_g"])
        if polarized and initial_state.get("mag_g") is not None:
            mag_g = np.asarray(initial_state["mag_g"])
        if paw is not None and initial_state.get("paw_dm") is not None:
            paw_dm = np.asarray(initial_state["paw_dm"])
        guess_scf = initial_state.get("scf")
        prev_psi = initial_state.get("psi")
        if prev_psi is not None and prev_psi.shape == (
            nk, ns, nb, ctx.gkvec.ngk_max,
        ):
            psi = np.asarray(prev_psi) * ctx.gkvec.mask[:, None, None, :]
    if initial_guess is not None:
        if len(initial_guess) == 3:
            guess_rho, guess_psi, guess_scf = initial_guess
        else:
            guess_rho, guess_psi = initial_guess
        if guess_rho is not None:
            guess_rho = np.asarray(guess_rho)
            if guess_rho.shape != rho_g.shape:
                raise ValueError(
                    f"initial_guess density shape {guess_rho.shape} does not "
                    f"match the context G set {rho_g.shape}"
                )
            rho_g = guess_rho.astype(np.complex128)
        if guess_psi is not None:
            guess_psi = np.asarray(guess_psi)
            want = (nk, ns, nb, ctx.gkvec.ngk_max)
            if guess_psi.shape != want:
                raise ValueError(
                    f"initial_guess wave-function shape {guess_psi.shape} "
                    f"does not match (nk, ns, nb, ngk_max) = {want}"
                )
            psi = guess_psi * ctx.gkvec.mask[:, None, None, :]
    if _resume_psi is not None and _resume_psi.shape == (
        nk, ns, nb, ctx.gkvec.ngk_max,
    ):
        # the autosaved wave functions warm-start the resumed band solve —
        # required for bit-reproducible host-path continuation
        psi = np.asarray(_resume_psi) * ctx.gkvec.mask[:, None, None, :]
    # first PAW on-site update (from the file-occupation guess or the
    # restored/warm-started dm)
    paw_res = paw_mod.compute_paw(paw, paw_dm, xc) if paw is not None else None
    e_paw_one_el = (
        paw_mod.one_elec_energy(paw, paw_dm, paw_res["dij_atoms"])
        if paw is not None
        else 0.0
    )
    # mGGA bootstrap: no wave functions yet -> tau = 0 (SCAN's alpha = 0
    # covenant region); replaced by the real tau after the first band solve
    tau_g = (
        np.zeros((ns, ctx.gvec.num_gvec), dtype=np.complex128) if mgga else None
    )
    pot = generate_potential(ctx, rho_g, xc, mag_g, tau_g=tau_g)
    psi_big = None
    if psi is None:
        # full atomic-orbital block (nbig >= nb); rotated down to the lowest
        # nb Ritz vectors at the first band solve, once the screened D of
        # the initial potential exists (reference initialize_subspace)
        psi_big = _initial_subspace(ctx)
    om_size = 0 if hub is None else ns * hub.num_hub_total * hub.num_hub_total
    nl_sizes = [] if hub is None else [
        ns * (2 * e["il"] + 1) * (2 * e["jl"] + 1) for e in hub.nonloc
    ]
    nl_size = sum(nl_sizes)
    # constrained-occupancy Lagrange multipliers join the mixing vector
    # (reference mixer_functions.cpp:275-347 mixes multipliers_constraints_
    # with the Hubbard matrix): the raw lambda += beta*(om - om_ref) map is
    # an unstable integrator on its own; Anderson/Broyden quasi-Newton
    # mixing is what finds the Lagrange saddle point.
    cons_size = om_size if (hub is not None and hub_om_cons is not None) else 0
    paw_size = 0 if paw is None else paw.dm_size()
    mixer = Mixer(
        cfg.mixer, ctx.gvec.glen2,
        num_components=2 if polarized else 1,
        extra_len=om_size + nl_size + cons_size + paw_size,
        omega=ctx.unit_cell.omega,
    )
    # constant device tables, uploaded once (not per iteration); the full-
    # precision projector stack feeds the density-matrix accumulation
    # independently of the wave-function working dtype
    # stored as a (re, im) real pair: complex arrays must never be device-
    # resident outside jit (real-boundary contract, parallel/batched.py)
    if ctx.beta.num_beta_total:
        from sirius_tpu.parallel.batched import split_cplx as _sc

        _bre, _bim = _sc(np.asarray(ctx.beta.beta_gk))
        beta_dev = (jnp.asarray(_bre), jnp.asarray(_bim))
    else:
        beta_dev = None
    hub_phi_stack = (
        None if hub is None else np.stack([hub.phi_s_gk[ik] for ik in range(nk)])
    )
    # per-(k, dtype) Hamiltonian parameter cache: only veff_r/dion change
    # between iterations, everything else is uploaded once via _replace
    _params_cache: dict = {}
    _kset_cache: dict = {}
    _gkc_cache: dict = {}

    def _gkc_dev(rdt):
        """Device-resident cartesian G+k components [nk, ngk, 3] for the
        mGGA tau operator, uploaded once per working precision."""
        key = str(rdt)
        if key not in _gkc_cache:
            _gkc_cache.clear()  # drop the stale-precision copy
            _gkc_cache[key] = jnp.asarray(ctx.gkvec.gkcart, dtype=rdt)
        return _gkc_cache[key]

    def kset_params(veff_stack, d_stack, v0, vhub_s, dtype):
        """Batched-path parameters with cached constant tables (only the
        potential-dependent leaves are re-uploaded per iteration)."""
        from sirius_tpu.ops.hamiltonian import real_dtype_of
        from sirius_tpu.parallel.batched import compute_h_diag, make_hkset_params

        rdt = real_dtype_of(dtype)
        if dtype not in _kset_cache:
            # a lower-precision entry is dead after the fp32->fp64 polish
            # switch: evict it so two full projector stacks never coexist
            for other in list(_kset_cache):
                if other != dtype:
                    del _kset_cache[other]
            _kset_cache[dtype] = make_hkset_params(
                ctx, veff_stack, d_stack, dtype=dtype, v0=v0,
                hub_phi=hub_phi_stack, vhub=vhub_s,
            )
            return _kset_cache[dtype]
        from sirius_tpu.parallel.batched import split_cplx

        h_diag = compute_h_diag(ctx, np.asarray(d_stack), v0)
        vh = (None, None) if vhub_s is None else split_cplx(vhub_s, rdt)
        # store the refreshed params back so the previous iteration's
        # potential-dependent device buffers are released
        _kset_cache[dtype] = _kset_cache[dtype]._replace(
            veff_r=jnp.asarray(veff_stack, dtype=rdt),
            dion=jnp.asarray(d_stack, dtype=rdt),
            h_diag=jnp.asarray(h_diag, dtype=rdt),
            vhub_re=None if vh[0] is None else jnp.asarray(vh[0]),
            vhub_im=None if vh[1] is None else jnp.asarray(vh[1]),
        )
        return _kset_cache[dtype]

    def hk_params(ik, veff_r, dmat, dtype, vhub_s=None):
        from sirius_tpu.ops.hamiltonian import real_dtype_of

        key = (ik, dtype)
        if key not in _params_cache:
            _params_cache[key] = make_hk_params(
                ctx, ik, veff_r, dmat, dtype=dtype,
                hub_phi=None if hub is None else hub.phi_s_gk[ik],
                vhub=vhub_s,
            )
            return _params_cache[key]
        rdt = real_dtype_of(dtype)
        return _params_cache[key]._replace(
            veff_r=jnp.asarray(veff_r, dtype=rdt),
            dion=jnp.asarray(dmat if dmat is not None else ctx.beta.dion, dtype=rdt),
            vhub=None if vhub_s is None else jnp.asarray(vhub_s, dtype=dtype),
        )
    do_symmetrize = (
        p.use_symmetry and ctx.symmetry is not None and ctx.symmetry.num_ops > 1
    )

    ng = ctx.gvec.num_gvec

    def pack(r, m, o, onl, pdm, lam=None):
        parts = [r]
        if polarized:
            parts.append(m)
        if hub is not None:
            parts.append(o.ravel())
            for blk in onl or []:
                parts.append(blk.ravel())
            if cons_size:
                parts.append(np.ravel(lam))
        if paw is not None:
            parts.append(pdm.astype(np.complex128))
        return np.concatenate(parts) if len(parts) > 1 else r

    def unpack(x):
        r = x[:ng]
        m = x[ng : 2 * ng] if polarized else None
        o = None
        onl = None
        pdm = None
        lam = None
        if paw is not None:
            pdm = np.real(x[len(x) - paw_size :])
        end = len(x) - paw_size
        if hub is not None:
            start = end - om_size - nl_size - cons_size
            o = x[start : start + om_size].reshape(
                ns, hub.num_hub_total, hub.num_hub_total
            )
            onl = []
            off = start + om_size
            for e, sz in zip(hub.nonloc, nl_sizes):
                onl.append(
                    x[off : off + sz].reshape(ns, 2 * e["il"] + 1, 2 * e["jl"] + 1)
                )
                off += sz
            if cons_size:
                lam = x[off : off + cons_size].reshape(
                    ns, hub.num_hub_total, hub.num_hub_total
                )
        return r, m, o, onl, pdm, lam

    om_mixed = n0 if hub is not None else None
    om_nl_mixed = om_nl0 if hub is not None else None
    if cons_size:
        hub_lagrange = np.zeros(
            (ns, hub.num_hub_total, hub.num_hub_total), dtype=np.complex128
        )
    x_mix = pack(rho_g, mag_g, om_mixed, om_nl_mixed, paw_dm, hub_lagrange)

    evals = np.zeros((nk, ns, nb))
    pr = pi = None  # batched-path device-resident (re, im) wave functions
    # production multi-device mesh: k-points over "k", bands over "b"
    # (GSPMD — same program, XLA inserts the collectives; None on 1 device)
    from sirius_tpu.parallel.mesh import place_kset_params, production_mesh

    scf_mesh, psi_spec = (None, None) if serial_bands else production_mesh(
        nk, nb, devices=devices)
    if scf_mesh is not None:
        from jax.sharding import NamedSharding

        _psi_sharding = NamedSharding(scf_mesh, psi_spec)

        def _place_psi(x):
            return jax.device_put(x, _psi_sharding)
    else:

        def _place_psi(x):
            return x

    # ---- G-sharded band solve (slab FFT over a "g" mesh): selected when
    # the replicated projector + wave-function footprint would not fit a
    # single device (cfg.control.gshard "auto"/True). Single-k no-U
    # regime — the Si-supercell flagship class. ----
    gsh = None
    g_flag = cfg.control.gshard
    _devs = list(devices) if devices is not None else jax.devices()
    ndev = len(_devs)
    gsh_want = False
    if (
        not serial_bands and g_flag not in (False, "false", "off")
        and nk == 1 and ns == 1 and hub is None and ndev > 1
        and ctx.beta.num_beta_total
    ):
        # replicated per-device footprint: projector table + psi workspace
        foot = (ctx.beta.num_beta_total + 4 * nb) * ctx.gkvec.ngk_max * 16
        dims_ok = (
            ctx.fft_coarse.dims[0] % ndev == 0
            and ctx.fft_coarse.dims[1] % ndev == 0
        )
        forced = g_flag in (True, "force")
        gsh_want = dims_ok and (
            forced
            or (g_flag == "auto" and foot > cfg.control.gshard_budget_bytes)
        )
        if forced and not dims_ok:
            import warnings

            warnings.warn(
                f"control.gshard forced but the coarse box "
                f"{ctx.fft_coarse.dims} is not divisible by {ndev} devices "
                "along x and y — falling back to the replicated band solve"
            )

    if mgga and gsh_want:
        # the G-sharded operator has no tau term and the gshard density
        # branch never updates tau_g — it would silently produce SCAN
        # energies from tau = 0
        raise NotImplementedError(
            "mGGA with the G-sharded band solve is not supported; set "
            "control.gshard = false"
        )

    def _setup_gshard(dtype):
        from jax.sharding import Mesh as _Mesh

        from sirius_tpu.ops.hamiltonian import real_dtype_of
        from sirius_tpu.parallel.dist_fft import (
            gshard_partition,
            make_apply_h_s_gshard,
            reorder_to_gshard,
        )

        g_mesh = _Mesh(np.array(_devs).reshape(ndev), ("g",))
        mill0 = np.asarray(ctx.gkvec.millers[0])
        g_order, g_lidx, _ = gshard_partition(mill0, ctx.fft_coarse.dims, ndev)
        prm0 = hk_params(0, np.zeros(ctx.fft_coarse.dims), None, dtype)
        g_fn, g_sharding = make_apply_h_s_gshard(
            g_mesh, ctx.fft_coarse.dims, g_lidx,
            reorder_to_gshard(np.asarray(prm0.ekin), g_order),
            reorder_to_gshard(np.asarray(prm0.mask), g_order),
            reorder_to_gshard(np.asarray(prm0.beta), g_order),
            np.asarray(prm0.dion), np.asarray(prm0.qmat),
            np.zeros(ctx.fft_coarse.dims),
        )
        g_mask = jnp.asarray(reorder_to_gshard(np.asarray(prm0.mask), g_order))
        return dict(fn=g_fn, order=g_order, sharding=g_sharding,
                    mask=g_mask, psi=None, dtype=dtype,
                    rdt=real_dtype_of(dtype), mesh=g_mesh)

    if gsh_want:
        gsh = _setup_gshard(wf_dtype)
        scf_mesh = None  # the "g" mesh replaces the (k, b) mesh
        if obs_metrics.enabled() and getattr(
                cfg.control, "collective_probe", True):
            # measure each named collective of the sharded apply once, in
            # isolation, at this deck's shapes — the per-iteration
            # compute/collective split of scf.band_solve scales these by
            # the analytic H-application row count
            try:
                from sirius_tpu.parallel.dist_fft import probe_collectives

                _pbatch = max(1, min(nb, 64))
                gsh["probe"] = {
                    "batch": _pbatch,
                    "per_call": probe_collectives(
                        gsh["mesh"], tuple(ctx.fft_coarse.dims), _pbatch,
                        nbeta=int(ctx.beta.num_beta_total),
                        ngk=int(gsh["order"].size), dtype=wf_dtype,
                        reps=2),
                }
            except Exception:
                gsh["probe"] = None
    # ---- chunked beta projectors (ops/beta_chunked.py): the dense
    # [nbeta_total, ngk] table is never materialized — each atom chunk is
    # regenerated inside the H application. Auto-dispatch mirrors gshard:
    # engage when the dense table would exceed beta_chunk_budget_bytes
    # (control.beta_chunked "auto"), or always when forced. Single-k
    # unpolarized no-U regime, like gshard. ----
    bchunk = None
    bc_flag = cfg.control.beta_chunked
    bc_foot = ctx.beta.num_beta_total * ctx.gkvec.ngk_max * 16
    # regime eligibility, captured separately from the budget decision: the
    # OOM degradation ladder (_recover "device_oom" below) engages the
    # chunked path mid-run after an HBM exhaustion, even when the budget
    # did not trip it at setup. The bchunk dispatch branch precedes
    # gamma_bands in the band solve, so a mid-run engagement shadows the
    # packed gamma path cleanly.
    _bchunk_ok = bool(
        not serial_bands and gsh is None
        and bc_flag not in (False, "false", "off")
        and nk == 1 and ns == 1 and hub is None and paw is None
        and not mgga and ctx.beta.num_beta_total
    )
    if _bchunk_ok:
        if bc_flag in (True, "force") or (
            bc_flag == "auto"
            and bc_foot > cfg.control.beta_chunk_budget_bytes
        ):
            bchunk = {"params": None, "dtype": None}
    # Gamma-point real-storage band solve (ops/gamma.py; reference
    # reduce_gvec, wave_functions.hpp:1589-1626): packed-real vectors make
    # the solver's GEMMs/eigh real. Hubbard needs the complex per-k U
    # apply and mGGA the complex tau operator — both keep the generic path.
    gamma_bands = (
        cfg.control.reduce_gvec
        and not serial_bands
        and gsh is None
        and bchunk is None
        and nk == 1
        and float(np.abs(np.asarray(ctx.gkvec.kpoints[0])).max()) < 1e-12
        and hub is None
        and not mgga
        # multi-device runs keep the band-sharded batched path — the packed
        # solve is single-device and would idle the rest of the mesh
        and ndev == 1
    )
    gm = None
    x_packed: list = [None] * ns
    gamma_cache: dict = {}  # rdtype -> constant-table GammaParams
    if gamma_bands:
        from sirius_tpu.ops.gamma import build_gamma_map

        gm = build_gamma_map(
            np.asarray(ctx.gkvec.millers[0]), np.asarray(ctx.gkvec.mask[0])
        )
    mu, occ, entropy_sum = 0.0, jnp.zeros((nk, ns, nb)), 0.0
    etot_history, rms_history, mag_history = [], [], []
    e_prev, converged, rms, scf_correction = None, False, 0.0, 0.0
    num_iter_done = 0
    itsol = cfg.iterative_solver
    # --- performance-attribution spans (obs/spans.py): per-stage wall
    # clocks recorded alongside (not replacing) the cumulative profiler
    # tree, each annotated with the analytic flops/bytes of its stage so
    # the timeline reports achieved GFLOP/s and roofline headroom ---
    _span_fence = bool(getattr(cfg.control, "span_fence", False))
    try:
        _stage_costs = obs_costs.scf_stage_costs(
            nk, ns, nb, int(ctx.gkvec.ngk_max),
            int(ctx.beta.num_beta_total), tuple(ctx.fft_coarse.dims), ng,
            itsol.num_steps, box_fine=tuple(ctx.gvec.fft.dims),
            mix_history=int(cfg.mixer.max_history), aug=ctx.aug is not None)
    except Exception:
        _stage_costs = {}

    def _stage_record(stage, dur_s, **attrs):
        c = _stage_costs.get(stage)
        obs_spans.record(stage, dur_s, flops=c.flops if c else 0.0,
                         bytes=c.bytes if c else 0.0, **attrs)

    def _hbm_attr():
        # per-iteration HBM high-water sample (device memory_stats peak;
        # host RSS fallback on CPU) — attached to scf.iteration spans
        if not obs_metrics.enabled():
            return {}
        hw = obs_tracing.hbm_high_water()
        return {"hbm_peak_bytes": max(hw.values())} if hw else {}

    def _fence(tree):
        # best-effort sync for truthful attribution (span_fence decks only)
        try:
            jax.block_until_ready(tree)
        except Exception:
            pass
    # adaptive band-solve tolerance, tightened each iteration with the
    # density residual (reference schedule dft_ground_state.cpp:252-259);
    # a static bar leaves a locked-band noise floor in the density that can
    # sit just above density_tol and stall tight decks at num_dft_iter
    res_tol = itsol.residual_tolerance
    it0 = 0
    warm_secants = None
    if guess_scf:
        # --- cross-run warm start of the MIXER, not just the density: the
        # successive differences of the donor's (x, f) history are secant
        # pairs of the SCF Jacobian, which a small geometry/volume
        # perturbation barely changes. Without them the warm density still
        # pays a full Anderson ramp-up (the model builds one pair per
        # iteration); with them the first mix() is already quasi-Newton.
        # Only DIFFERENCES transfer (Mixer.import_secants explains why
        # absolute pairs stall the child). The donor's final res_tol
        # replaces the loose start of the adaptive band-tolerance schedule
        # below — a warm density is past the regime the loose bar exists
        # for. A length mismatch (different G set / extras layout) drops
        # the hint silently: an optimization, never a correctness input. ---
        hx = np.asarray(guess_scf.get("mix_x", ()))
        hf = np.asarray(guess_scf.get("mix_f", ()))
        if (hx.ndim == 2 and hx.shape[0] >= 2 and hx.shape == hf.shape
                and hx.shape[1] == x_mix.size
                and np.all(np.isfinite(hx.view(np.float64)))
                and np.all(np.isfinite(hf.view(np.float64)))):
            warm_secants = (np.diff(hx.astype(np.complex128), axis=0),
                            np.diff(hf.astype(np.complex128), axis=0))
            mixer.import_secants(*warm_secants)
        hint_tol = guess_scf.get("res_tol")
        if hint_tol is not None and np.isfinite(hint_tol) and hint_tol > 0:
            res_tol = min(res_tol, float(hint_tol))
    if resume_scf is not None:
        # --- mid-SCF resume (control.autosave_every checkpoints): restore
        # the packed mixed vector, mixer history/backoff state, adaptive
        # tolerance, convergence histories and the iteration counter, then
        # rebuild everything derived (hub/PAW on-site state, potential).
        # With psi also restored above, the host path replays the exact
        # trajectory of the uninterrupted run. ---
        if mgga:
            raise NotImplementedError(
                "mid-SCF resume with mGGA (tau is not checkpointed)")
        x_mix = np.asarray(resume_scf["x_mix"])
        rho_g, mag_g, om_mixed, om_nl_mixed, paw_dm, lam_mixed = unpack(x_mix)
        if lam_mixed is not None:
            hub_lagrange = lam_mixed
        if hub is not None:
            um_local, um_nl, e_hub, _ = hubbard_potential_and_energy(
                hub, om_mixed, ctx.max_occupancy, om_nl=om_nl_mixed,
                lagrange=hub_lagrange if hub_cons_active else None,
                om_cons=hub_om_cons if hub_cons_active else None,
            )
            vhub = np.stack([
                u_matrix_for_k(hub, um_local, um_nl, ctx.gkvec.kpoints[ik])
                for ik in range(nk)
            ])
        if paw is not None:
            paw_res = paw_mod.compute_paw(paw, paw_dm, xc)
            e_paw_one_el = paw_mod.one_elec_energy(
                paw, paw_dm, paw_res["dij_atoms"])
        with profile("scf::potential"):
            pot = generate_potential(ctx, rho_g, xc, mag_g)
        mixer.import_history(resume_scf)
        mixer.beta = float(resume_scf.get("mix_beta", mixer.beta))
        mixer.kind = str(resume_scf.get("mix_kind", mixer.kind))
        res_tol = float(resume_scf.get("res_tol", res_tol))
        if "e_prev" in resume_scf:
            e_prev = float(resume_scf["e_prev"])
        etot_history = [float(v) for v in resume_scf.get("etot_history", [])]
        rms_history = [float(v) for v in resume_scf.get("rms_history", [])]
        mag_history = [float(v) for v in resume_scf.get("mag_history", [])]
        if "evals" in resume_scf:
            evals = np.asarray(resume_scf["evals"], dtype=np.float64)
        it0 = int(resume_scf.get("iteration", 0))
        num_iter_done = it0
        # honour an fp32 -> fp64 polish switch that fired before the save
        wf_dtype = (
            jnp.complex128
            if bool(resume_scf.get("wf_fp64", p.precision_wf == "fp64"))
            else jnp.complex64
        )

    # ---- fused device-resident iteration (dft/fused.py): density ->
    # mixer -> potential -> D/H-diag refresh as ONE compiled program with a
    # donated carry; per-iteration host traffic is a [NUM_SCALARS] vector.
    # control.device_scf = false keeps the host path below as the debug
    # fallback (tests/test_fused_scf.py pins the two to ~1e-8 Ha). ----
    fused = None
    fused_carry = fused_out = fused_np = None
    if (
        cfg.control.device_scf not in (False, "false", "off")
        and not serial_bands and gsh is None and not gamma_bands
        and bchunk is None and hub is None and paw is None and not mgga
        and mixer.kind in ("linear", "anderson")
        and not _cks.enabled()
    ):
        from sirius_tpu.dft.fused import (
            FusedScf,
            S_BXC, S_CHG, S_E1, S_E2, S_EHA, S_ENT, S_EVAL, S_EXC, S_FINITE,
            S_HERM, S_MAG, S_NEL, S_ORTHO, S_RMS, S_SYM, S_V0, S_VHA, S_VXC,
        )

        if scf_mesh is not None:
            # replicate the fused constants/state on the production mesh
            # ONCE: jit against mesh-sharded band-solve outputs would
            # otherwise reshard every uncommitted operand each iteration —
            # a hidden per-iteration transfer (caught by the
            # transfer-guard test in tests/test_fused_scf.py)
            from jax.sharding import NamedSharding, PartitionSpec

            _rep = NamedSharding(scf_mesh, PartitionSpec())

            def _repl(t):
                return jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, _rep), t
                )
        else:

            def _repl(t):
                return t

        if beta_dev is not None:
            beta_dev = _repl(beta_dev)

        def _fused_setup(x0, pot0, history=None, rebuild=True):
            # (re)build the fused program and/or its carry. The recovery
            # ladder calls this after a rollback: the donated carry of a
            # diverged step holds poisoned buffers, and a beta/kind change
            # needs a full rebuild because FusedScf bakes mixer.beta and
            # mixer.kind into the trace.
            nonlocal fused, fused_carry, fused_out, fused_np
            if rebuild or fused is None:
                fused = FusedScf(ctx, xc, mixer, polarized, do_symmetrize,
                                 beta_dev=beta_dev, exec_cache=exec_cache)
                fused.tables = _repl(fused.tables)
                fused.kweights_dev = _repl(fused.kweights_dev)
            fused_carry = _repl(fused.init_carry(x0, pot0, history=history))
            fused_out = fused_np = None

        _fused_setup(
            x_mix, pot,
            history=mixer.export_history() or None
            if resume_scf is not None else None,
        )
        # pre-wrapped device scalars: python floats fed to jit are implicit
        # host->device transfers, which the fused loop must not make
        fused_nel = _repl(jnp.asarray(float(nel), dtype=jnp.float64))
        fused_width = _repl(
            jnp.asarray(float(p.smearing_width), dtype=jnp.float64)
        )
        fused_occmax = _repl(jnp.asarray(
            float(ctx.max_occupancy), dtype=jnp.float64
        ))
        fused_dm0 = _repl(
            (jnp.zeros((ns, 0, 0)), jnp.zeros((ns, 0, 0)))
        )

    # ---- SCF supervision & recovery (dft/recovery.py): the sentinels
    # below (non-finite fields, energy blow-up, RMS divergence) roll the
    # loop back to the last finite snapshot and escalate a backoff ladder
    # instead of raising a fatal FloatingPointError. ----
    sup = ScfSupervisor(
        cfg.control, mixer.beta, mixer.kind,
        deck_label=f"nk={nk} ns={ns} nb={nb} ng={ng}",
        density_tol=float(p.density_tol),
    )
    _snap_every = max(1, int(getattr(cfg.control, "snapshot_every", 5)))
    _autosave_every = int(getattr(cfg.control, "autosave_every", 0))
    if sup.enabled:
        # rollback target before any iteration ran: the initial guess
        sup.snapshot(-1, {"x_mix": np.array(x_mix), "res_tol": res_tol})

    def _recover(sentinel, detail=""):
        """Roll back to the supervisor's snapshot and apply one ladder
        rung. Raises ScfAbortError (with the structured diagnostic) when
        the ladder or the recovery budget is exhausted."""
        nonlocal x_mix, rho_g, mag_g, om_mixed, om_nl_mixed, paw_dm
        nonlocal hub_lagrange, um_local, um_nl, e_hub, vhub
        nonlocal paw_res, e_paw_one_el, pot, psi, psi_big, pr, pi
        nonlocal x_packed, tau_g, fused, fused_carry, fused_out, fused_np
        nonlocal e_prev, res_tol, bchunk, evals
        if os.environ.get("SIRIUS_TPU_DUMP_DIVERGED"):
            np.savez(
                os.environ["SIRIUS_TPU_DUMP_DIVERGED"],
                rho_g=rho_g,
                mag_g=mag_g if mag_g is not None else np.zeros(1),
            )
        d = sup.recover(sentinel, it, detail=detail, state={
            "mixer_beta": mixer.beta, "mixer_kind": mixer.kind,
            "device_scf": fused is not None,
            # OOM-ladder applicability flags (dft/recovery.py _recover_oom)
            "beta_chunked": bchunk is not None,
            "beta_chunk_eligible": _bchunk_ok,
            "beta_chunk_can_halve": int(cfg.control.beta_chunk_size) > 16,
        })
        if cfg.control.verbosity >= 1:
            logger.warning(
                "recovery at it=%d: sentinel '%s' -> rung %d "
                "(rollback to it=%d)",
                it + 1, sentinel, d.rung, sup.snap["it"] + 1)
        snap = sup.snap
        x_mix = np.array(snap["x_mix"])
        if d.flush_history:
            mixer.flush_history()
        if d.beta is not None:
            mixer.beta = d.beta
        if d.kind is not None:
            mixer.kind = d.kind
        res_tol = float(snap.get("res_tol", itsol.residual_tolerance))
        e_prev = None
        rho_g, mag_g, om_mixed, om_nl_mixed, paw_dm, _lam = unpack(x_mix)
        if _lam is not None:
            hub_lagrange = _lam
        if hub is not None:
            um_local, um_nl, e_hub, _ = hubbard_potential_and_energy(
                hub, om_mixed, ctx.max_occupancy, om_nl=om_nl_mixed,
                lagrange=hub_lagrange if hub_cons_active else None,
                om_cons=hub_om_cons if hub_cons_active else None,
            )
            vhub = np.stack([
                u_matrix_for_k(hub, um_local, um_nl, ctx.gkvec.kpoints[ik])
                for ik in range(nk)
            ])
        if paw is not None:
            paw_res = paw_mod.compute_paw(paw, paw_dm, xc)
            e_paw_one_el = paw_mod.one_elec_energy(
                paw, paw_dm, paw_res["dij_atoms"])
        if mgga:
            # tau of the diverged wave functions is poisoned too; restart
            # from the tau = 0 bootstrap like the initial iteration
            tau_g = np.zeros((ns, ng), dtype=np.complex128)
        with profile("scf::potential"):
            pot = generate_potential(ctx, rho_g, xc, mag_g, tau_g=tau_g)
        # the diverged wave functions are part of the poisoned trajectory:
        # restart the band solve from a fresh LCAO subspace
        psi = None
        pr = pi = None
        x_packed = [None] * ns
        if gsh is not None:
            gsh["psi"] = None
        psi_big = _initial_subspace(ctx)
        # the band-solve branches that rebind evals leave a read-only view
        # of a device array behind; the in-place writers (chunked/gamma
        # paths) the ladder may switch to need a writable buffer
        evals = np.array(evals)
        if d.shrink_beta_budget:
            # OOM-ladder rung 0 (repeatable): quarter the dense-beta
            # engagement budget to below the current table's footprint and
            # halve the chunk size, so the next band solve allocates
            # strictly less HBM than the one that exhausted it
            cfg.control.beta_chunk_budget_bytes = min(
                float(cfg.control.beta_chunk_budget_bytes) / 4.0,
                bc_foot / 2.0)
            cfg.control.beta_chunk_size = max(
                16, int(cfg.control.beta_chunk_size) // 2)
        if (d.shrink_beta_budget or d.force_beta_chunked) and _bchunk_ok \
                and (d.force_beta_chunked or bchunk is not None
                     or bc_foot > cfg.control.beta_chunk_budget_bytes):
            # (re)engage the chunked projector path; params rebuild lazily
            # at the next band solve (dtype mismatch forces make_chunked_hk
            # at the new beta_chunk_size)
            bchunk = {"params": None, "dtype": None}
        if fused is not None:
            if d.disable_device or bchunk is not None:
                # rung 2: remaining iterations on the host path, which
                # re-validates every field per iteration (the chunked
                # projector path also runs under the host loop)
                fused = None
                fused_carry = fused_out = fused_np = None
            else:
                _fused_setup(
                    x_mix, pot,
                    rebuild=(d.beta is not None or d.kind is not None),
                )

    def _autosave(it):
        """Atomic mid-SCF checkpoint (io/checkpoint.py scf_state group):
        everything the resume path above needs to continue this run."""
        from sirius_tpu.io.checkpoint import save_state

        path = cfg.control.autosave_path or default_autosave_path(
            cfg, base_dir)
        if fused is not None and fused_carry is not None:
            x_now, hist = fused.fetch_state(fused_carry, with_history=True)
            ev_h = np.asarray(ev_dev, dtype=np.float64)
        else:
            x_now = np.array(x_mix)
            hist = mixer.export_history()
            ev_h = np.asarray(evals)
        if pr is not None:
            from sirius_tpu.parallel.batched import join_cplx as _jc

            psi_h = np.asarray(_jc(pr, pi), dtype=np.complex128)
        elif psi is not None:
            psi_h = np.asarray(psi, dtype=np.complex128)
        else:
            psi_h = None
        r_s, m_s, _, _, pdm_s, _ = unpack(x_now)
        scf_state = {
            "x_mix": x_now,
            "iteration": it + 1,
            "res_tol": res_tol,
            "e_prev": e_prev,
            "mix_beta": mixer.beta,
            "mix_kind": mixer.kind,
            "wf_fp64": wf_dtype == jnp.complex128,
            "evals": ev_h,
            "etot_history": np.asarray(etot_history),
            "rms_history": np.asarray(rms_history),
            "mag_history": np.asarray(mag_history),
        }
        if hist:
            scf_state.update(hist)
        save_state(
            path, ctx, r_s, m_s, psi=psi_h, band_energies=ev_h,
            paw_dm=pdm_s, scf_state=scf_state,
            rotate_keep=int(getattr(cfg.control, "autosave_keep", 0)),
        )
        _AUTOSAVES.inc()
        obs_events.emit("autosave", it=it + 1, path=path,
                        fused=fused is not None)
        # fault site: a preemption right after the autosave (soak test /
        # tests drive the resume path through this)
        faults.check("scf.autosave_kill", it)

    # ---- convergence forecasting + deadline feasibility (obs/forecast.py
    # via the supervisor): one scf_forecast event and two gauges per
    # iteration, plus a deadline_feasibility event whenever the
    # forecasted finish crosses control.deadline_ts in either direction.
    _fc_warnings = 0
    _fc_deadline_ok = None  # None until the first feasibility verdict
    _iter_wall: list[float] = []
    _numerics_probe = bool(getattr(cfg.control, "numerics_probe", False))
    _numerics_every = max(
        1, int(getattr(cfg.control, "numerics_probe_every", 10)))

    def _forecast_tick(it, dt, path):
        nonlocal _fc_warnings, _fc_deadline_ok
        if not (sup.enabled and sup.forecast_enabled):
            return
        _iter_wall.append(float(dt))
        # fault site: a deliberately wrong forecast — maximum warning with
        # no real divergence; drives the proactive-snapshot and deadline
        # paths and pins that a misfire alone never costs a recovery
        if faults.armed("scf.forecast_misfire", it):
            sup.inject_warning(1.0)
        snap = sup.forecast_snapshot()
        if snap is None:
            return
        warning = float(snap.get("warning") or 0.0)
        if warning >= sup.forecast_warning_threshold:
            _fc_warnings += 1
        total = snap.get("forecast_total")
        if total is not None:
            _FORECAST_ITERS.set(float(total))
        _FORECAST_WARNING.set(warning)
        obs_events.emit("scf_forecast", it=it + 1, path=path, **{
            k: snap.get(k) for k in (
                "decay_rate", "forecast_remaining", "forecast_total",
                "warning", "growth_streak")})
        deadline = float(getattr(cfg.control, "deadline_ts", 0.0) or 0.0)
        remaining = snap.get("forecast_remaining")
        if deadline > 0.0 and remaining is not None and _iter_wall:
            # median of the recent iteration walls: robust against the
            # compile-dominated first iteration
            tail = sorted(_iter_wall[-5:])
            per_it = tail[len(tail) // 2]
            eta = time.time() + per_it * float(remaining)
            ok = bool(eta <= deadline)
            if ok != _fc_deadline_ok:
                obs_events.emit(
                    "deadline_feasibility", it=it + 1, feasible=ok,
                    eta_ts=eta, deadline_ts=deadline,
                    forecast_remaining=remaining,
                    sec_per_iteration=per_it)
                _fc_deadline_ok = ok

    # ---- straggler watchdog (utils/devfail.py): per-iteration wall
    # against BOTH the run's own healthy-median baseline and the
    # obs/costs.py analytic model for scf.iteration. A slice degraded by
    # thermal throttling or a sick neighbor chip runs every iteration
    # slow; a sustained streak preempts the run at a snapshot boundary so
    # the serving layer can reschedule it on healthy hardware
    # (serve/scheduler.py treats StragglerPreempt as a preemption, never a
    # strike). control.straggler_detect "auto" keeps it OFF standalone —
    # the scheduler resolves it to on at job admission. ----
    _strag_on = getattr(cfg.control, "straggler_detect", "auto") in (
        True, "true", "on", "force")
    _strag_ratio = float(getattr(cfg.control, "straggler_ratio", 4.0))
    _strag_iters = max(1, int(getattr(cfg.control, "straggler_iters", 3)))
    _strag = {"healthy": [], "streak": 0, "fire": False, "delay": 0.0}
    _c_it = _stage_costs.get("scf.iteration")
    _strag_model_s = (
        _c_it.flops / (obs_costs.peak_gflops() * 1e9) if _c_it else 0.0)

    def _straggler_tick(it, dt, path):
        """Feed one iteration wall clock to the straggler detector."""
        if not _strag_on or _strag["fire"]:
            return
        if it - it0 < 2:
            return  # compile-dominated warm-up walls are not evidence
        healthy = _strag["healthy"]
        if len(healthy) >= 3:
            tail = sorted(healthy[-12:])
            base = max(tail[len(tail) // 2], _strag_model_s)
            if dt > _strag_ratio * base:
                _strag["streak"] += 1
                if _strag["streak"] >= _strag_iters:
                    _strag["fire"] = True
                    obs_events.emit(
                        "straggler", it=it + 1, path=path, dt=dt,
                        baseline_s=base, model_s=_strag_model_s,
                        ratio=dt / base, streak=_strag["streak"])
                return
        _strag["streak"] = 0
        healthy.append(float(dt))

    def _straggler_preempt(it):
        """After the detector fired: force a snapshot unless this
        iteration already autosaved, then hand the run back to the
        scheduler as a preemption (resume elsewhere from the autosave)."""
        if not _strag["fire"]:
            return
        if not (_autosave_every and (it + 1) % _autosave_every == 0):
            _autosave(it)
        _STRAGGLER.inc()
        raise devfail.StragglerPreempt(
            f"straggler watchdog preempted the run at iteration {it + 1}: "
            f"sustained slow iterations on this slice")

    obs_events.emit(
        "run_manifest", nk=nk, ns=ns, nb=nb, ng=ng,
        num_atoms=ctx.unit_cell.num_atoms, device_scf=fused is not None,
        it0=it0, num_dft_iter=p.num_dft_iter, resumed=resume is not None,
        xc=list(p.xc_functionals), precision_wf=p.precision_wf,
    )
    # everything since run_scf entry (context/tables/initial guess/fused
    # compile trigger) is one externally-timed setup span
    obs_spans.record("scf.setup", time.time() - t0, t0=t0,
                     fused=fused is not None)
    _it_t0 = time.time()
    for it in range(it0, p.num_dft_iter):
        obs_trace.tick()
        _it_t0 = time.time()
        # ---- injectable device faults at the jit-dispatch boundary
        # (utils/faults.py fire/armed; tools/chaos_serve.py device phases).
        # device.oom is classified (utils/devfail.py) and routed through
        # the OOM degradation ladder IN-RUN: the run rolls back to the
        # supervisor snapshot and continues on a smaller memory plan — no
        # job failure. device.lost is deliberately NOT caught here: a lost
        # chip takes the whole dispatch down, and only the serving layer
        # can rebuild a mesh from the surviving devices and resume from
        # the autosave. ----
        try:
            faults.fire("device.oom", it)
        except RuntimeError as _de:
            if devfail.classify(_de) != "oom":
                raise
            _recover("device_oom", detail=str(_de))
            continue
        faults.fire("device.lost", it)
        if _strag_on and faults.armed("device.straggler", it):
            # persistent slowdown from this iteration on — sized off the
            # run's own healthy walls so the detector's ratio bar is
            # crossed regardless of deck size
            _h = sorted(_strag["healthy"])
            _base = _h[len(_h) // 2] if _h else 0.1
            _strag["delay"] = max(0.45, (_strag_ratio + 2.0) * _base)
        if _strag["delay"]:
            time.sleep(_strag["delay"])
        # --- band solve per (k, spin) (warm start) ---
        if fused is None or fused_out is None:
            # host D/v0 from the host potential; once the fused step has
            # run, the refreshed D and v0 live on device (fused_out)
            _dm_t0 = time.perf_counter()
            d_by_spin = []
            for ispn in range(ns):
                if ctx.aug is not None:
                    vs_g = pot.veff_g + (pot.bz_g if ispn == 0 else -pot.bz_g) if polarized else pot.veff_g
                    d_by_spin.append(
                        d_operator(ctx.unit_cell, ctx.gvec, ctx.aug, vs_g, ctx.beta)
                    )
                else:
                    d_by_spin.append(ctx.beta.dion)
            if paw is not None:
                # add the on-site PAW Dij (from the mixed on-site density) to
                # the screened D before the band solve
                d_by_spin = paw_mod.add_dij_to_d(paw, paw_res["dij_atoms"], d_by_spin)
            v0 = float(np.real(pot.veff_g[0]))
            _stage_record("scf.d_matrix", time.perf_counter() - _dm_t0,
                          it=it + 1)
        _bs_t0 = time.perf_counter()
        with profile("scf::band_solve"):
            if gsh is not None:
                from sirius_tpu.ops.hamiltonian import real_dtype_of
                from sirius_tpu.parallel.dist_fft import (
                    reorder_from_gshard,
                    reorder_to_gshard,
                )

                if gsh["dtype"] != wf_dtype:
                    # fp32 -> fp64 polish: rebuild ekin/mask/beta tables at
                    # the new precision (the serial path gets this from the
                    # (ik, dtype)-keyed hk_params cache)
                    gsh = _setup_gshard(wf_dtype)

                if psi is None and psi_big is not None:
                    # one-off LCAO subspace init on the replicated path
                    params = hk_params(
                        0, pot.veff_r_coarse[0], d_by_spin[0], wf_dtype
                    )
                    xb = psi_big[0, 0] * np.asarray(ctx.gkvec.mask[0])
                    hx, sx = apply_h_s(params, jnp.asarray(xb, dtype=wf_dtype))
                    psi = np.zeros(
                        (1, 1, nb, ctx.gkvec.ngk_max), dtype=np.complex128
                    )
                    psi[0, 0] = _subspace_rotate_host(
                        xb, np.asarray(hx, dtype=np.complex128),
                        np.asarray(sx, dtype=np.complex128), nb,
                    )
                    counters["num_loc_op_applied"] += psi_big.shape[2]
                    psi_big = None
                x0 = gsh["psi"]
                if x0 is None:
                    x0 = jax.device_put(
                        jnp.asarray(reorder_to_gshard(
                            np.asarray(psi[0, 0]).astype(wf_dtype),
                            gsh["order"],
                        )),
                        gsh["sharding"],
                    )
                h_diag, o_diag = _h_o_diag(ctx, 0, v0, d_by_spin[0])
                hd = reorder_to_gshard(np.asarray(h_diag), gsh["order"])
                od = reorder_to_gshard(np.asarray(o_diag), gsh["order"])
                od[od == 0.0] = 1.0  # padding slots: finite preconditioner
                rdt = real_dtype_of(wf_dtype)
                veff_d = jax.device_put(
                    jnp.asarray(pot.veff_r_coarse[0]),
                    gsh["fn"].sharding_veff,
                )
                ev, x, rn = davidson(
                    gsh["fn"],
                    (veff_d, jnp.asarray(d_by_spin[0], dtype=gsh["rdt"])),
                    x0,
                    jnp.asarray(hd, dtype=rdt), jnp.asarray(od, dtype=rdt),
                    gsh["mask"],
                    num_steps=itsol.num_steps,
                    res_tol=res_tol,
                )
                gsh["psi"] = x
                evals[0, 0] = np.asarray(ev)
                # host round-trip for the density consumer; a device-side
                # gather + sharded density accumulation would avoid it
                # (known cost on this path — the band solve dominates)
                psi = jnp.asarray(
                    reorder_from_gshard(
                        np.asarray(x), gsh["order"], ctx.gkvec.ngk_max
                    )
                )[None, None]
            elif bchunk is not None:
                # chunk-generated projectors: the H/S application rebuilds
                # each atom chunk's beta block on the fly (lax.scan), so the
                # dense [nbeta, ngk] table never exists on device
                from sirius_tpu.ops.beta_chunked import (
                    apply_h_s_chunked,
                    make_chunked_hk,
                    pack_dmat_chunks,
                )
                from sirius_tpu.ops.hamiltonian import real_dtype_of

                rdt = real_dtype_of(wf_dtype)
                if bchunk["dtype"] != wf_dtype:
                    bchunk["params"] = make_chunked_hk(
                        ctx, 0, dtype=wf_dtype,
                        chunk=cfg.control.beta_chunk_size,
                    )
                    bchunk["dtype"] = wf_dtype
                prm = dict(
                    bchunk["params"],
                    veff_r=jnp.asarray(pot.veff_r_coarse[0], dtype=rdt),
                    dmat=jnp.asarray(
                        pack_dmat_chunks(
                            ctx, np.real(np.asarray(d_by_spin[0])),
                            cfg.control.beta_chunk_size,
                        ),
                        dtype=rdt,
                    ),
                )
                if psi is None and psi_big is not None:
                    # one-off LCAO subspace init through the chunked apply
                    xb = psi_big[0, 0] * np.asarray(ctx.gkvec.mask[0])
                    hx, sx = apply_h_s_chunked(
                        prm, jnp.asarray(xb, dtype=wf_dtype)
                    )
                    psi = np.zeros(
                        (1, 1, nb, ctx.gkvec.ngk_max), dtype=np.complex128
                    )
                    psi[0, 0] = _subspace_rotate_host(
                        xb, np.asarray(hx, dtype=np.complex128),
                        np.asarray(sx, dtype=np.complex128), nb,
                    )
                    counters["num_loc_op_applied"] += psi_big.shape[2]
                    psi_big = None
                h_diag, o_diag = _h_o_diag(ctx, 0, v0, d_by_spin[0])
                ev, x, rn = davidson(
                    apply_h_s_chunked, prm,
                    jnp.asarray(np.asarray(psi[0, 0]), dtype=wf_dtype),
                    jnp.asarray(h_diag, dtype=rdt),
                    jnp.asarray(o_diag, dtype=rdt),
                    jnp.asarray(ctx.gkvec.mask[0], dtype=rdt),
                    num_steps=itsol.num_steps,
                    res_tol=res_tol,
                )
                evals[0, 0] = np.asarray(ev)
                psi = np.asarray(x).astype(np.complex128)[None, None]
            elif gamma_bands:
                from sirius_tpu.ops.gamma import (
                    davidson_gamma,
                    make_gamma_params,
                    pack_diags,
                )
                from sirius_tpu.ops.gamma import pack as gpack
                from sirius_tpu.ops.gamma import unpack as gunpack
                from sirius_tpu.ops.hamiltonian import real_dtype_of

                rdt = real_dtype_of(wf_dtype)
                if x_packed[0] is not None and x_packed[0].dtype != np.dtype(rdt):
                    # fp32 -> fp64 polish: re-cast the packed block
                    x_packed = [jnp.asarray(x, dtype=rdt) for x in x_packed]
                if psi is not None and x_packed[0] is None:
                    # restart / warm start from full complex psi
                    x_packed = [
                        jnp.asarray(gpack(gm, np.asarray(psi[0, ispn])), dtype=rdt)
                        for ispn in range(ns)
                    ]
                psi_out = np.zeros(
                    (1, ns, nb, ctx.gkvec.ngk_max), dtype=np.complex128
                )
                if rdt not in gamma_cache:
                    # constant tables (packed beta, gather maps) uploaded
                    # once per precision; per-iteration leaves swapped below
                    gamma_cache[rdt] = make_gamma_params(
                        ctx, np.zeros(ctx.fft_coarse.dims), gm, rdtype=rdt
                    )
                for ispn in range(ns):
                    gp = gamma_cache[rdt]._replace(
                        veff_r=jnp.asarray(pot.veff_r_coarse[ispn], dtype=rdt),
                        dion=jnp.asarray(np.real(d_by_spin[ispn]), dtype=rdt),
                    )
                    if x_packed[ispn] is None:
                        # first iteration: rotate the packed LCAO block to
                        # the lowest nb Ritz vectors (initialize_subspace)
                        from sirius_tpu.solvers.davidson import (
                            subspace_rotate,
                        )
                        from sirius_tpu.ops.gamma import apply_h_s_gamma

                        xb = jnp.asarray(
                            gpack(gm, psi_big[0, ispn]), dtype=rdt
                        )
                        hx, sx = apply_h_s_gamma(gp, xb)
                        x_packed[ispn] = subspace_rotate(
                            xb, hx, sx, nb, mask=gp.mask_p
                        ).astype(rdt)
                        counters["num_loc_op_applied"] += psi_big.shape[2]
                    h_diag, o_diag = _h_o_diag(ctx, 0, v0, d_by_spin[ispn])
                    hd_p, od_p = pack_diags(
                        gm, np.asarray(h_diag), np.asarray(o_diag)
                    )
                    ev, xg, rn = davidson_gamma(
                        gp, x_packed[ispn],
                        jnp.asarray(hd_p, dtype=rdt),
                        jnp.asarray(od_p, dtype=rdt),
                        num_steps=itsol.num_steps,
                        res_tol=res_tol,
                    )
                    evals[0, ispn] = np.asarray(ev)
                    x_packed[ispn] = xg
                    psi_out[0, ispn] = gunpack(gm, np.asarray(xg))
                psi = psi_out
                psi_big = None
            elif serial_bands:
                if psi is None and psi_big is not None:
                    # first iteration from a fresh LCAO block: rotate the
                    # full atomic-orbital subspace down to nb Ritz vectors
                    # (reference initialize_subspace)
                    psi0 = np.zeros(
                        (nk, ns, nb, ctx.gkvec.ngk_max), dtype=np.complex128
                    )
                    for ik in range(nk):
                        for ispn in range(ns):
                            params = hk_params(
                                ik, pot.veff_r_coarse[ispn], d_by_spin[ispn],
                                wf_dtype,
                                vhub_s=None if vhub is None else vhub[ik, ispn],
                            )
                            xb = psi_big[ik, ispn] * np.asarray(ctx.gkvec.mask[ik])
                            hx, sx = apply_h_s(params, jnp.asarray(xb, dtype=wf_dtype))
                            psi0[ik, ispn] = _subspace_rotate_host(
                                xb,
                                np.asarray(hx, dtype=np.complex128),
                                np.asarray(sx, dtype=np.complex128),
                                nb,
                            )
                    counters["num_loc_op_applied"] += nk * ns * psi_big.shape[2]
                    psi = psi0
                    psi_big = None
                new_psi = []
                for ik in range(nk):
                    per_spin = []
                    for ispn in range(ns):
                        from sirius_tpu.ops.hamiltonian import real_dtype_of

                        params = hk_params(
                            ik, pot.veff_r_coarse[ispn], d_by_spin[ispn], wf_dtype,
                            vhub_s=None if vhub is None else vhub[ik, ispn],
                        )
                        h_diag, o_diag = _h_o_diag(ctx, ik, v0, d_by_spin[ispn])
                        rdt = real_dtype_of(wf_dtype)
                        ev, x, rn = davidson(
                            apply_h_s,
                            params,
                            psi[ik, ispn].astype(wf_dtype),
                            jnp.asarray(h_diag, dtype=rdt),
                            jnp.asarray(o_diag, dtype=rdt),
                            params.mask,
                            num_steps=itsol.num_steps,
                            res_tol=res_tol,
                        )
                        evals[ik, ispn] = np.asarray(ev)
                        per_spin.append(x)
                    new_psi.append(jnp.stack(per_spin))
                psi = jnp.stack(new_psi)
            else:
                # production path: the whole (k, spin) set as ONE program
                # (parallel/batched.py; shards over the ("k", "b") mesh).
                # Real-boundary: psi crosses the jit boundary as a (re, im)
                # pair — the TPU backend cannot transfer complex arrays.
                from sirius_tpu.ops.hamiltonian import real_dtype_of
                from sirius_tpu.parallel.batched import (
                    davidson_kset,
                    join_cplx,
                    split_cplx,
                )

                rdt = real_dtype_of(wf_dtype)
                if (
                    fused is not None and fused_out is not None
                    and wf_dtype in _kset_cache
                ):
                    # device-resident refresh: the fused step already
                    # produced veff_r/D/h_diag on device — swap them into
                    # the cached params without any host round-trip
                    _kset_cache[wf_dtype] = _kset_cache[wf_dtype]._replace(
                        veff_r=fused_out["veff_r_coarse"].astype(rdt),
                        dion=fused_out["dion"].astype(rdt),
                        h_diag=fused_out["h_diag"].astype(rdt),
                    )
                    ps = _kset_cache[wf_dtype]
                elif fused is not None and fused_out is not None:
                    # precision switch (fp32 -> fp64 polish): one-time host
                    # fetch to build the new-precision constant tables
                    ps = kset_params(
                        np.asarray(fused_out["veff_r_coarse"]),
                        np.asarray(fused_out["dion"]),
                        float(fused_np[S_V0]), vhub, wf_dtype,
                    )
                else:
                    ps = kset_params(
                        pot.veff_r_coarse[:ns], np.stack(d_by_spin), v0,
                        vhub, wf_dtype,
                    )
                ps = place_kset_params(ps, scf_mesh)
                if pr is None and psi is None and psi_big is not None:
                    # first iteration from a fresh LCAO block: rotate the
                    # full atomic-orbital subspace down to the lowest nb
                    # Ritz vectors (reference initialize_subspace.hpp:279)
                    from sirius_tpu.parallel.batched import (
                        initialize_subspace_kset,
                    )

                    pb_re, pb_im = split_cplx(psi_big, rdt)
                    if scf_mesh is not None:
                        # the LCAO block has nbig >= nb orbitals — shard it
                        # over "k" only (nbig need not divide the band axis)
                        from jax.sharding import (
                            NamedSharding as _NS,
                            PartitionSpec as _P,
                        )

                        _big = _NS(scf_mesh, _P("k", None, None, None))
                        pb_re = jax.device_put(jnp.asarray(pb_re), _big)
                        pb_im = jax.device_put(jnp.asarray(pb_im), _big)
                    pr, pi = initialize_subspace_kset(
                        ps, jnp.asarray(pb_re), jnp.asarray(pb_im), nb
                    )
                    pr, pi = _place_psi(pr), _place_psi(pi)
                    counters["num_loc_op_applied"] += nk * ns * psi_big.shape[2]
                    psi_big = None
                if pr is None or pr.dtype != np.dtype(rdt):
                    # initial entry or precision switch; psi may be stale
                    # (None) if the previous iterations kept the pair only
                    src = psi if psi is not None else join_cplx(pr, pi)
                    pr, pi = split_cplx(np.asarray(src), rdt)
                    pr, pi = _place_psi(jnp.asarray(pr)), _place_psi(jnp.asarray(pi))
                if mgga and pot.vtau_r_coarse is not None:
                    from sirius_tpu.ops.mgga import davidson_kset_mgga

                    ev, pr, pi, rn = davidson_kset_mgga(
                        ps, jnp.asarray(pot.vtau_r_coarse, dtype=rdt),
                        _gkc_dev(rdt), pr, pi,
                        num_steps=itsol.num_steps,
                        res_tol=res_tol,
                    )
                else:
                    ev, pr, pi, rn = davidson_kset(
                        ps, pr, pi,
                        num_steps=itsol.num_steps,
                        res_tol=res_tol,
                    )
                # canonicalize the pair onto the explicit psi sharding (a
                # no-op when GSPMD already placed it there): downstream
                # consumers must see the SAME placement whether psi came
                # from this solve or from a mid-SCF resume warm start,
                # or the executables (and their reduction orders) differ
                # and break bit-reproducible resume
                pr, pi = _place_psi(pr), _place_psi(pi)
                # psi stays device-resident as the (pr, pi) pair between
                # iterations; the complex host copy is materialized only for
                # consumers that need it (Hubbard occupations each
                # iteration, forces/stress/checkpoint after the loop)
                psi = join_cplx(pr, pi) if hub is not None else None
                if fused is not None:
                    # eigenvalues stay on device; the host copy is fetched
                    # once after the loop for the final report
                    ev_dev = ev.astype(jnp.float64)
                else:
                    evals = np.asarray(ev, dtype=np.float64)
            # H*psi application count (reference num_loc_op_applied counter)
            from sirius_tpu.solvers.davidson import num_applies

            counters["num_loc_op_applied"] += nk * ns * num_applies(
                itsol.num_steps, nb
            )
        if _span_fence:
            # the host paths already fenced via np.asarray(ev); only the
            # device-resident (fused) solve still has compute in flight
            if fused is not None:
                _fence((ev_dev, pr, pi))
            elif pr is not None:
                _fence((pr, pi))
        _bs_dt = time.perf_counter() - _bs_t0
        _stage_record("scf.band_solve", _bs_dt,
                      it=it + 1, num_steps=itsol.num_steps)
        if gsh is not None and gsh.get("probe"):
            # split the measured solve wall into collective vs compute:
            # fenced per-collective probe costs (probe_collectives, taken
            # once at setup) x the analytic H-application row count. A
            # host timer cannot see inside the jitted apply, so this is a
            # model (attrs say so) — cross-checked by bench_gshard_large
            # against the 1-device baseline.
            from sirius_tpu.solvers.davidson import num_applies as _napp

            _pb = gsh["probe"]
            _rows = nk * ns * _napp(itsol.num_steps, nb)
            _coll = sum(
                v for k, v in _pb["per_call"].items()
                if k != "collective.fft_local"
            ) / _pb["batch"] * _rows
            _coll = min(_coll, _bs_dt)
            _stage_record("scf.band_solve.collective", _coll, it=it + 1,
                          method="probe", ndev=ndev)
            _stage_record("scf.band_solve.compute", _bs_dt - _coll,
                          it=it + 1, method="probe", ndev=ndev)
        # --- band-solve supervision (dft/recovery.py): a stagnated or
        # blown-up solve is retried with a deeper subspace; the serial
        # debug path additionally falls back to dense diagonalization for
        # small |G+k| spheres (the reference's "robust" exact-solver
        # escape hatch). Host paths only — the fused loop's scalar record
        # already carries an all-finite eigenvalue sentinel, and checking
        # rn here would add per-iteration device->host traffic. On the
        # serial multi-k path rn covers the last (k, spin) solve, a proxy
        # that still catches whole-solve stagnation.
        if fused is None and sup.enabled:
            from sirius_tpu.solvers.davidson import residual_health

            rn_max, rn_ok = residual_health(
                rn, blowup=cfg.control.band_residual_blowup)
            if faults.armed("scf.band_stagnate", it):
                rn_ok = False
            if not rn_ok:
                rescued = False
                if (not serial_bands and not gamma_bands and gsh is None
                        and bchunk is None and not mgga):
                    # batched production path: one deeper retry, warm-
                    # started from the stagnated block (static num_steps
                    # means this compiles once and is then cached)
                    from sirius_tpu.parallel.batched import (
                        davidson_kset as _dk,
                        join_cplx as _jcx,
                    )

                    ev, pr, pi, rn = _dk(
                        ps, pr, pi, num_steps=2 * itsol.num_steps,
                        res_tol=res_tol,
                    )
                    evals = np.asarray(ev, dtype=np.float64)
                    if hub is not None:
                        psi = _jcx(pr, pi)
                    rescued = True
                elif serial_bands and int(ctx.gkvec.ngk_max) <= int(
                        cfg.control.exact_diag_max_ngk):
                    from sirius_tpu.solvers.eigen import (
                        build_h_s_matrices,
                        exact_diag,
                    )

                    try:
                        psi_r = np.asarray(psi, dtype=np.complex128).copy()
                        qmat = (
                            None if ctx.beta.qmat is None
                            else np.asarray(ctx.beta.qmat)
                        )
                        for ik in range(nk):
                            n_gk = int(ctx.gkvec.num_gk[ik])
                            gkd = {
                                "millers": np.asarray(
                                    ctx.gkvec.millers[ik][:n_gk]),
                                "ekin": np.asarray(
                                    ctx.gkvec.kinetic()[ik][:n_gk]),
                            }
                            bk = (
                                np.asarray(ctx.beta.beta_gk[ik])
                                if ctx.beta.num_beta_total else None
                            )
                            for ispn in range(ns):
                                vg = np.asarray(pot.veff_g)
                                if polarized and pot.bz_g is not None:
                                    vg = vg + np.asarray(
                                        pot.bz_g if ispn == 0 else -pot.bz_g
                                    )
                                h, s = build_h_s_matrices(
                                    gkd, vg, ctx.gvec.index_of_millers,
                                    beta_k=bk,
                                    dion=np.asarray(d_by_spin[ispn]),
                                    qmat=qmat,
                                )
                                ev_d, vec = exact_diag(h, s, nb)
                                evals[ik, ispn] = ev_d
                                psi_r[ik, ispn] = 0.0
                                psi_r[ik, ispn, :nb, :n_gk] = vec.T
                        psi = psi_r
                        rescued = True
                    except ValueError:
                        # fine G set lacks some G-G' differences
                        # (pw_cutoff < 2*gk_cutoff): keep the iterative
                        # result rather than build a truncated dense H
                        pass
                if rescued and cfg.control.verbosity >= 1:
                    logger.warning(
                        "band-solve rescue at it=%d (max rnorm %.2e)",
                        it + 1, rn_max)
        if _cks.enabled():
            _cks.checksum("evals", evals)

        if fused is not None:
            # --- fused device-resident remainder of the iteration: fermi
            # search, density, mixing, potential and the D/h_diag refresh
            # all run on device; ONE scalar vector comes back ---
            with profile("scf::fused_step"):
                # sub-stage clocks: honest per-stage splits need span_fence
                # (each _fence is a sync, not a transfer — the transfer
                # guard of test_fused_no_host_transfers stays satisfied);
                # unfenced, dispatch latency is recorded per stage and the
                # queued compute lands in scf.readback below
                _fu_t = time.perf_counter()
                mu, occ, entropy_sum = find_fermi(
                    ev_dev, fused.kweights_dev, fused_nel, fused_width,
                    kind=p.smearing, max_occupancy=fused_occmax,
                )
                occ_w = occ * fused.kweights_dev[:, None, None]
                if _span_fence:
                    _fence(occ_w)
                _stage_record("scf.occupations",
                              time.perf_counter() - _fu_t, it=it + 1)
                _fu_t = time.perf_counter()
                from sirius_tpu.parallel.batched import (
                    density_kset,
                    density_matrix_kset,
                )

                acc = density_kset(ps, pr, pi, occ_w)
                # fault site: NaN into the accumulated density (functional
                # device-side update; a no-op dict lookup when unarmed, so
                # the transfer-guard contract of this span is preserved)
                acc = faults.corrupt("scf.density", it, acc)
                if fused.has_aug and beta_dev is not None:
                    dm_re, dm_im = density_matrix_kset(
                        *beta_dev, pr, pi, occ_w
                    )
                else:
                    dm_re, dm_im = fused_dm0
                if _span_fence:
                    _fence((acc, dm_re, dm_im))
                _stage_record("scf.density",
                              time.perf_counter() - _fu_t, it=it + 1)
                _fu_t = time.perf_counter()
                fused_carry, fused_out = fused.step(
                    fused_carry, acc, dm_re, dm_im, ev_dev, occ_w,
                    entropy_sum, pr, pi,
                )
                if _span_fence:
                    _fence(fused_out)
                _stage_record("scf.fused_step",
                              time.perf_counter() - _fu_t, it=it + 1)
            # the ONLY per-iteration device->host fetch
            _rb_t0 = time.perf_counter()
            fused_np = np.asarray(fused_out["scalars"])
            _stage_record("scf.readback", time.perf_counter() - _rb_t0,
                          it=it + 1)
            if (not np.all(np.isfinite(fused_np))
                    or fused_np[S_FINITE] != 1.0):
                # non-finite fields on device: roll back and escalate
                # (dft/recovery.py) instead of losing the run
                _recover(
                    "device_nonfinite",
                    detail="non-finite scalars/fields from the "
                    "device-resident step",
                )
                continue
            rms = float(fused_np[S_RMS])
            eha_res = float(fused_np[S_EHA])
            dens_metric = eha_res if mixer.use_hartree else rms
            res_tol = schedule_res_tol(itsol, res_tol, dens_metric, nel,
                                       mixer.use_hartree)
            scf_correction = (
                float(fused_np[S_E2] - fused_np[S_E1])
                if p.use_scf_correction else 0.0
            )
            e_total = (
                float(fused_np[S_EVAL] - fused_np[S_VXC] - fused_np[S_BXC]
                      - 0.5 * fused_np[S_VHA] + fused_np[S_EXC])
                + ctx.e_ewald + scf_correction
            )
            if cfg.control.verification >= 1:
                nel_got = float(fused_np[S_NEL])
                if abs(nel_got - nel) > 1e-6 * max(1.0, nel):
                    import warnings

                    warnings.warn(
                        f"electron count from density {nel_got:.8f} != "
                        f"{nel:.8f}"
                    )
            etot_history.append(e_total + float(fused_np[S_ENT]))
            rms_history.append(rms)
            if polarized:
                mag_history.append(float(fused_np[S_MAG]))
            num_iter_done = it + 1
            _ITERATIONS.inc(path="fused")
            _it_dt = time.time() - _it_t0
            _ITER_SECONDS.observe(_it_dt)
            _RMS.set(rms)
            _ETOT.set(e_total)
            _stage_record("scf.iteration", _it_dt, t0=_it_t0, it=it + 1,
                          path="fused", **_hbm_attr())
            # numerics ledger: the invariants ride the existing [NUM_SCALARS]
            # readback (dft/fused.py) — naming them here costs no transfer
            ledger = obs_numerics.ledger_from_scalars(fused_np)
            obs_numerics.record_ledger(ledger, it + 1, "fused")
            obs_events.emit(
                "scf_iteration", it=it + 1, path="fused", rms=rms,
                e_total=e_total, dt=_it_dt,
                scalars=[float(v) for v in fused_np], ledger=ledger,
            )
            if cfg.control.verbosity >= 2:
                mg = f" mag={mag_history[-1]:+.4f}" if polarized else ""
                logger.info("it=%3d etot=%+.10f rms=%.3e%s",
                            it + 1, e_total, rms, mg)
            sentinel = sup.observe(it, rms, e_total)
            if sentinel is not None:
                _recover(sentinel)
                continue
            _forecast_tick(it, _it_dt, "fused")
            _straggler_tick(it, _it_dt, "fused")
            if sup.enabled and (it % _snap_every == 0
                                or sup.should_snapshot()):
                # rollback snapshot: fetch the mixed vector from the carry
                # OUTSIDE the fused profile span (an explicit supervised
                # transfer every snapshot_every iterations — plus whenever
                # the divergence early warning is raised, so a subsequent
                # rollback lands on the newest trusted iterate instead of
                # one up to snapshot_every iterations stale)
                x_snap, _ = fused.fetch_state(fused_carry)
                sup.snapshot(it, {
                    "x_mix": x_snap, "e_total": e_total,
                    "res_tol": res_tol,
                })
            de = abs(e_total - e_prev) if e_prev is not None else np.inf
            e_prev = e_total
            if (
                wf_dtype == jnp.complex64
                and cfg.settings.fp32_to_fp64_rms > 0
                and rms < cfg.settings.fp32_to_fp64_rms
            ):
                wf_dtype = jnp.complex128
                continue
            # autosave AFTER e_prev/precision bookkeeping: the saved state
            # must be exactly what the next iteration of an uninterrupted
            # run would start from
            if _autosave_every and (it + 1) % _autosave_every == 0:
                _autosave(it)
            if de < p.energy_tol and dens_metric < p.density_tol:
                converged = True
                break
            _straggler_preempt(it)
            continue

        # --- occupations ---
        # fault site: NaN into the band energies (detected with the other
        # non-finite fields after the density assembly below)
        evals = faults.corrupt("scf.evals", it, evals)
        _oc_t0 = time.perf_counter()
        mu, occ, entropy_sum = find_fermi(
            jnp.asarray(evals),
            jnp.asarray(ctx.kweights),
            nel,
            p.smearing_width,
            kind=p.smearing,
            max_occupancy=ctx.max_occupancy,
        )
        occ_np = np.asarray(occ)  # self-fencing host fetch
        _stage_record("scf.occupations", time.perf_counter() - _oc_t0,
                      it=it + 1)

        # --- Hubbard occupation matrix (mixed jointly with the density) ---
        om_new = None
        om_nl_new = None
        if hub is not None:
            om_new, occ_T = occupation_matrix(
                ctx, hub, psi, occ_np, ctx.max_occupancy
            )
            # Constrained-occupancy runs keep the RAW k-weighted om: the
            # stable dual-ascent drives the om to a target that is NOT
            # invariant under the crystal group (test30's eg off-diagonal
            # -0.351 cannot survive the symmetry average), so the om is
            # left unsymmetrized while a constraint is configured.
            if do_symmetrize and hub_om_cons is None:
                om_new, om_nl_new = symmetrize_occupation(
                    ctx, hub, om_new, occ_T
                )
            else:
                from sirius_tpu.ops.hubbard import nonlocal_from_occ_T

                om_nl_new = nonlocal_from_occ_T(hub, occ_T) if hub.nonloc else []
            # occupancy-constraint Lagrange multipliers (reference
            # calculate_constraints_and_error; RELEASES once converged)
            if hub_om_cons is not None:
                hub_lagrange, hub_cons_active = constraint_update(
                    hub, om_new, hub_lagrange, hub_om_cons, hub_cons_state
                )
            # one-electron term inside eval_sum: NEW occupancies against the
            # potential the band solve actually used (um_local/um_nl of the
            # previous mixing step; reference one_electron_energy_hubbard)
            e_hub_one_el = ctx.max_occupancy * (
                sum(
                    float(np.real(np.sum(om_new[ispn] * np.conj(um_local[ispn]))))
                    for ispn in range(ns)
                )
                + sum(
                    float(np.real(np.sum(o * np.conj(u))))
                    for o, u in zip(om_nl_new or [], um_nl)
                )
            )

        # --- density (per spin, then charge/magnetization assembly) ---
        _de_t0 = time.perf_counter()
        occ_w = jnp.asarray(occ_np * ctx.kweights[:, None, None])
        with profile("scf::density"):
            if (serial_bands or gamma_bands or gsh is not None
                    or bchunk is not None):
                rho_spin = generate_density_g(ctx, psi, occ_np)
            else:
                from sirius_tpu.dft.density import density_from_coarse_acc
                from sirius_tpu.parallel.batched import density_kset

                rho_spin = density_from_coarse_acc(
                    ctx, np.asarray(density_kset(ps, pr, pi, occ_w))
                )
                if mgga:
                    from sirius_tpu.ops.mgga import tau_kset

                    tau_acc = np.asarray(tau_kset(
                        ps.fft_index, _gkc_dev(rdt), pr, pi, occ_w,
                        tuple(ctx.fft_coarse.dims),
                    ))
                    # same 1/Omega + coarse->fine mapping as the density;
                    # tau transforms as a scalar field, so the reduced
                    # k-wedge sum needs the same point-group symmetrization
                    # as rho
                    tau_g = density_from_coarse_acc(ctx, tau_acc)
                    if do_symmetrize:
                        tau_g = np.stack(
                            [symmetrize_pw(ctx, t) for t in tau_g]
                        )
                    # NOTE: the potential is built from the MIXED density
                    # but the FRESH tau of the current wave functions (tau
                    # is psi-derived and not part of the mixing vector);
                    # near self-consistency the pair is consistent, and the
                    # SCAN smoke test covers the transient
        dm_blocks_by_spin = []
        if ctx.aug is not None:
            from sirius_tpu.dft.density import symmetrize_density_matrix
            from sirius_tpu.parallel.batched import density_matrix_kset, split_cplx

            if pr is not None:
                ppair = (pr, pi)  # batched path: already device-resident
            else:
                ppair = split_cplx(np.asarray(psi))
            dm_re, dm_im = density_matrix_kset(*beta_dev, *ppair, occ_w)
            from sirius_tpu.parallel.batched import join_cplx as _jc

            dm_by_spin = _jc(dm_re, dm_im)
            if do_symmetrize:
                dm_by_spin = symmetrize_density_matrix(ctx, dm_by_spin)
            for ispn in range(ns):
                dm_blocks = [
                    dm_by_spin[ispn, off : off + nbf, off : off + nbf]
                    for _, off, nbf in ctx.beta.atom_blocks(ctx.unit_cell)
                ]
                dm_blocks_by_spin.append(dm_blocks)
                rho_spin[ispn] += rho_aug_g(ctx.unit_cell, ctx.gvec, ctx.aug, dm_blocks)
        rho_new = rho_spin.sum(axis=0)
        mag_new = rho_spin[0] - rho_spin[1] if polarized else None
        if _cks.enabled():
            _cks.checksum("rho_new", rho_new)
        if cfg.control.verification >= 1:
            # electron-count audit (reference Density::check_num_electrons,
            # dft_ground_state.cpp:305-308)
            nel_got = float(np.real(rho_new[0]) * ctx.unit_cell.omega)
            if abs(nel_got - nel) > 1e-6 * max(1.0, nel):
                import warnings

                warnings.warn(
                    f"electron count from density {nel_got:.8f} != {nel:.8f}"
                )
        if do_symmetrize:
            rho_new = symmetrize_pw(ctx, rho_new)
            if polarized:
                mag_new = symmetrize_pw(ctx, mag_new, axial_z=True)
        paw_dm_new = (
            paw.dm_from_density_matrix(dm_by_spin) if paw is not None else None
        )
        # fault site: NaN into the freshly accumulated density (drives the
        # recovery-ladder tests without waiting for a real divergence)
        rho_new = faults.corrupt("scf.density", it, rho_new)
        x_new = pack(rho_new, mag_new, om_new, om_nl_new, paw_dm_new,
                     hub_lagrange)
        # the span extends past profile("scf::density") through augmentation,
        # symmetrization and packing — the full "new density" stage
        _stage_record("scf.density", time.perf_counter() - _de_t0, it=it + 1)
        rho_resid_g = rho_new - rho_g  # output - input density (scf-corr force)
        if not np.all(np.isfinite(evals)) or not np.isfinite(
            np.sum(np.abs(x_new))
        ):
            bad = [
                name
                for name, a in [
                    ("evals", evals),
                    ("rho_new", rho_new),
                    ("mag_new", mag_new if polarized else np.zeros(1)),
                    ("om_new", om_new if hub is not None else np.zeros(1)),
                    ("om_nl_new", np.concatenate([np.ravel(o) for o in om_nl_new]) if (hub is not None and om_nl_new) else np.zeros(1)),
                    ("paw_dm_new", paw_dm_new if paw_dm_new is not None else np.zeros(1)),
                    ("lagrange", hub_lagrange if hub_lagrange is not None else np.zeros(1)),
                    ("veff_in", pot.veff_r_coarse),
                    ("vhub_in", vhub if vhub is not None else np.zeros(1)),
                    ("rho_in", rho_g),
                ]
                if not np.all(np.isfinite(np.asarray(a)))
            ]
            _recover("nonfinite_fields", detail=f"non-finite {bad}")
            continue
        _mx_t0 = time.perf_counter()
        rms = mixer.rms(x_mix, x_new)
        x_mix = mixer.mix(x_mix, x_new)
        # density criterion in the reference's metric: with use_hartree the
        # bar is the Hartree ENERGY of (mixed - new), not the rms
        # (dft_ground_state.cpp:251,353) — quadratic in the residual, so
        # testing the Hartree-metric rms against the same density_tol is a
        # far stricter (square-root) bar and stalls decks at 100 iterations
        eha_res = mixer.residual_hartree_energy(x_mix, x_new)
        dens_metric = (
            eha_res if (mixer.use_hartree and eha_res is not None) else rms
        )
        res_tol = schedule_res_tol(itsol, res_tol, dens_metric, nel,
                                   mixer.use_hartree and eha_res is not None)
        rho_g, mag_g, om_mixed, om_nl_mixed, paw_dm, lam_mixed = unpack(x_mix)
        _stage_record("scf.mixing", time.perf_counter() - _mx_t0, it=it + 1)
        if lam_mixed is not None:
            hub_lagrange = lam_mixed  # quasi-Newton-mixed multipliers
        if hub is not None:
            um_local, um_nl, e_hub, _ = hubbard_potential_and_energy(
                hub, om_mixed, ctx.max_occupancy, om_nl=om_nl_mixed,
                lagrange=hub_lagrange if hub_cons_active else None,
                om_cons=hub_om_cons if hub_cons_active else None,
            )
            vhub = np.stack([
                u_matrix_for_k(hub, um_local, um_nl, ctx.gkvec.kpoints[ik])
                for ik in range(nk)
            ])
        if paw is not None:
            # PAW on-site update from the mixed dm: potentials, Dij (used by
            # the next band solve) and energies (reference generates the PAW
            # potential from the mixed density, potential.generate)
            paw_res = paw_mod.compute_paw(paw, paw_dm, xc)
            e_paw_one_el = paw_mod.one_elec_energy(
                paw, paw_dm, paw_res["dij_atoms"]
            )

        # first-order (Harris-like) correction: E_pot[rho_out] under the new
        # vs old potential (reference dft_ground_state.cpp:245,320-322)
        def _epot(r_out, m_out, p_):
            e = float(np.real(np.vdot(r_out, p_.veff_g))) * ctx.unit_cell.omega
            if polarized and p_.bz_g is not None and m_out is not None:
                e += float(np.real(np.vdot(m_out, p_.bz_g))) * ctx.unit_cell.omega
            return e

        e1 = _epot(rho_new, mag_new, pot)

        # --- potential + energies ---
        _pt_t0 = time.perf_counter()
        with profile("scf::potential"):
            pot = generate_potential(ctx, rho_g, xc, mag_g, tau_g=tau_g)
        _stage_record("scf.potential", time.perf_counter() - _pt_t0,
                      it=it + 1)
        # fault site: NaN into the generated effective potential
        pot.veff_r_coarse = faults.corrupt(
            "scf.potential", it, pot.veff_r_coarse)
        if not np.all(np.isfinite(np.asarray(pot.veff_r_coarse))):
            _recover(
                "potential_nonfinite",
                detail=f"potential non-finite from rho finite="
                f"{np.all(np.isfinite(rho_g))}, mag finite="
                f"{mag_g is None or np.all(np.isfinite(mag_g))}",
            )
            continue
        if _cks.enabled():
            _cks.checksum("veff", pot.veff_g)
        scf_correction = (
            _epot(rho_new, mag_new, pot) - e1 if p.use_scf_correction else 0.0
        )
        eval_sum = float(np.sum(ctx.kweights[:, None, None] * occ_np * evals))
        e = pot.energies
        e_total = (
            eval_sum - e["vxc"] - e["bxc"] - e.get("vtau_tau", 0.0)
            - 0.5 * e["vha"] + e["exc"] + ctx.e_ewald
            + scf_correction + (e_hub - e_hub_one_el if hub is not None else 0.0)
            + (paw_res["e_total"] - e_paw_one_el if paw is not None else 0.0)
        )
        # reference etot_history records the free energy (dft_ground_state
        # etot_hist; verified against verification/test23 and test01 outputs)
        etot_history.append(e_total + float(entropy_sum))
        rms_history.append(rms)
        if polarized:
            # per-iteration total moment (reference prints magnetisation
            # each SCF step); recorded from the OUTPUT density pre-mix
            mag_history.append(float(np.real(mag_new[0]) * ctx.unit_cell.omega))
        num_iter_done = it + 1
        _ITERATIONS.inc(path="host")
        _it_dt = time.time() - _it_t0
        _ITER_SECONDS.observe(_it_dt)
        _RMS.set(rms)
        _ETOT.set(e_total)
        _stage_record("scf.iteration", _it_dt, t0=_it_t0, it=it + 1,
                      path="host", **_hbm_attr())
        # numpy twin of the fused on-device numerics ledger (obs/numerics.py)
        # — same invariants from the same operands, so the fused values can
        # be validated against this path (tests/test_fused_scf.py)
        ledger = None
        if pr is not None:
            _sym_resid = (
                float(np.max(np.abs(symmetrize_pw(ctx, rho_new) - rho_new)))
                if do_symmetrize else 0.0
            )
            ledger = obs_numerics.ledger_host(
                np.asarray(pr) + 1j * np.asarray(pi),
                np.asarray(ctx.beta.beta_gk)
                if ctx.beta.num_beta_total else None,
                ctx.beta.qmat, ctx.beta.dion,
                np.asarray(ctx.gkvec.mask, dtype=np.float64),
                x_mix, x_new, ctx.unit_cell.omega, sym_resid=_sym_resid,
            )
            obs_numerics.record_ledger(ledger, it + 1, "host")
        obs_events.emit(
            "scf_iteration", it=it + 1, path="host", rms=rms,
            e_total=e_total, dt=_it_dt,
            # host-path equivalent of the fused [NUM_SCALARS] scalar record
            scalars={"eval_sum": eval_sum, "vha": e["vha"], "vxc": e["vxc"],
                     "exc": e["exc"], "bxc": e["bxc"],
                     "entropy": float(entropy_sum),
                     "scf_correction": scf_correction},
            ledger=ledger,
        )
        if cfg.control.verbosity >= 2:
            # reference per-iteration SCF line (dft_ground_state verbosity 2)
            mg = f" mag={mag_history[-1]:+.4f}" if polarized else ""
            logger.info("it=%3d etot=%+.10f rms=%.3e%s",
                        it + 1, e_total, rms, mg)

        sentinel = sup.observe(it, rms, e_total)
        if sentinel is not None:
            _recover(sentinel)
            continue
        _forecast_tick(it, _it_dt, "host")
        _straggler_tick(it, _it_dt, "host")
        # in-loop precision-headroom probes (obs/numerics.py): shadow
        # re-execution of the post-band stages at degraded precision on
        # the current iterate, every numerics_probe_every iterations
        if (_numerics_probe and pr is not None
                and (it + 1) % _numerics_every == 0):
            _pb_t0 = time.perf_counter()
            _stages = obs_numerics.probe_stages(
                ctx, xc, np.asarray(pr) + 1j * np.asarray(pi), occ_np,
                np.asarray(evals), rho_g, mag_g,
                mixer_beta=mixer.beta, smearing=p.smearing,
                smearing_width=float(p.smearing_width),
            )
            obs_numerics.emit_probe_events(_stages, it=it + 1)
            _stage_record("scf.numerics_probe",
                          time.perf_counter() - _pb_t0, it=it + 1)
        if sup.enabled:
            # host path: the snapshot is a cheap host copy — keep the last
            # finite post-mix state every iteration
            sup.snapshot(it, {
                "x_mix": np.array(x_mix), "e_total": e_total,
                "res_tol": res_tol,
            })
        de = abs(e_total - e_prev) if e_prev is not None else np.inf
        e_prev = e_total
        # fp32 -> fp64 polish switch (reference settings.fp32_to_fp64_rms);
        # when it fires, force at least one fp64 iteration before declaring
        # convergence so the final state is genuinely double precision
        if (
            wf_dtype == jnp.complex64
            and cfg.settings.fp32_to_fp64_rms > 0
            and rms < cfg.settings.fp32_to_fp64_rms
        ):
            wf_dtype = jnp.complex128
            if gsh is not None:
                gsh["psi"] = None  # rebuild the sharded block in fp64
            continue
        # autosave AFTER e_prev/precision bookkeeping: the saved state must
        # be exactly what the next iteration of an uninterrupted run would
        # start from (resume-equality is asserted bit-exact on this path)
        if _autosave_every and (it + 1) % _autosave_every == 0:
            _autosave(it)
        if de < p.energy_tol and dens_metric < p.density_tol:
            converged = True
            break
        _straggler_preempt(it)

    obs_trace.finish()
    # --- final report ---
    if fused is not None and fused_out is not None:
        # one-time exit fetch from the device-resident loop: mixed density,
        # D matrices and dm blocks for forces/stress, plus a host-side
        # potential regeneration so the report/checkpoint path below sees
        # the same PotentialResult fields it always has
        evals = np.asarray(ev_dev, dtype=np.float64)
        fin = fused.finalize(fused_carry, fused_out)
        rho_g = fin["rho_g"]
        mag_g = fin["mag_g"]
        d_by_spin = fin["d_by_spin"]
        rho_resid_g = fin["rho_resid_g"]
        dm_blocks_by_spin = fin["dm_blocks_by_spin"]
        with profile("scf::potential"):
            pot = generate_potential(ctx, rho_g, xc, mag_g)
    if psi is None and pr is not None:
        from sirius_tpu.parallel.batched import join_cplx

        psi = join_cplx(pr, pi)
    elif psi is None:
        # num_dft_iter == 0: no band solve ran, so the LCAO block was never
        # rotated; report its first nb rows for shape-valid output ONLY —
        # this truncation must not be persisted as a warm start
        psi = psi_big[:, :, :nb] if psi_big is not None else None
        keep_state = False
        save_to = None
    occ_np = np.asarray(occ)
    band_gap = _band_gap(evals, occ_np, ctx)
    rho_r = rho_real_space(ctx, rho_g)
    e = pot.energies
    eval_sum = float(np.sum(ctx.kweights[:, None, None] * occ_np * evals))
    e_total = (
        eval_sum - e["vxc"] - e["bxc"] - e.get("vtau_tau", 0.0)
            - 0.5 * e["vha"] + e["exc"] + ctx.e_ewald
        + scf_correction + (e_hub - e_hub_one_el if hub is not None else 0.0)
        + (paw_res["e_total"] - e_paw_one_el if paw is not None else 0.0)
    )
    result = {
        "converged": converged,
        "num_scf_iterations": num_iter_done,
        "gshard_devices": ndev if gsh is not None else 0,
        "efermi": float(mu),
        "band_gap": band_gap,
        "rho_min": float(rho_r.min()),
        "etot_history": etot_history,
        "rms_history": rms_history,
        "mag_history": mag_history,
        # supervision record (dft/recovery.py): empty ladder_history means
        # the run never needed a rollback
        "recovery": {
            "recoveries": sup.recoveries,
            "rung": sup.rung,
            "ladder_history": list(sup.history),
        },
        "scf_time": time.time() - t0,
        "energy": {
            "total": e_total,
            "free": e_total + float(entropy_sum),
            "eval_sum": eval_sum,
            "kin": eval_sum - e["veff"] - e["bxc"] - e.get("vtau_tau", 0.0),
            "veff": e["veff"],
            "vha": e["vha"],
            "vxc": e["vxc"],
            "vloc": e["vloc"],
            "exc": e["exc"],
            "bxc": e["bxc"],
            "ewald": ctx.e_ewald,
            "entropy_sum": float(entropy_sum),
            "scf_correction": scf_correction,
            "hubbard": e_hub if hub is not None else 0.0,
            "hubbard_one_el": e_hub_one_el if hub is not None else 0.0,
            "paw_total_energy": paw_res["e_total"] if paw is not None else 0.0,
            "paw_one_elec": e_paw_one_el if paw is not None else 0.0,
        },
        "band_energies": evals.tolist(),
        "band_occupancies": occ_np.tolist(),
        "counters": dict(counters),
        "timers": timer_report(),
    }
    # convergence-forecast summary (obs/forecast.py via the supervisor):
    # consumed by serve/scheduler.py (deadline triage) and campaigns
    _fc_snap = sup.forecast_snapshot()
    result["forecast"] = {
        "enabled": bool(sup.enabled and sup.forecast_enabled),
        "decay_rate": _fc_snap.get("decay_rate") if _fc_snap else None,
        "forecast_total": _fc_snap.get("forecast_total") if _fc_snap else None,
        "forecast_remaining": (
            _fc_snap.get("forecast_remaining") if _fc_snap else None),
        "warning": _fc_snap.get("warning") if _fc_snap else None,
        "warnings_total": _fc_warnings,
        "actual_iterations": num_iter_done,
    }
    # end-of-run precision-headroom probe on the final iterate (both
    # paths; the in-loop cadence above only covers the host path)
    if _numerics_probe and num_iter_done > 0 and psi is not None:
        _pb_t0 = time.perf_counter()
        _stages = obs_numerics.probe_stages(
            ctx, xc, np.asarray(psi), occ_np, np.asarray(evals),
            rho_g, mag_g, mixer_beta=mixer.beta, smearing=p.smearing,
            smearing_width=float(p.smearing_width),
        )
        obs_numerics.emit_probe_events(_stages, it=num_iter_done)
        _stage_record("scf.numerics_probe",
                      time.perf_counter() - _pb_t0, it=num_iter_done)
        result["numerics"] = _stages
    _RUNS.inc(outcome="converged" if converged else "unconverged")
    obs_events.emit(
        "scf_done", converged=converged, iterations=num_iter_done,
        e_total=e_total, recoveries=sup.recoveries, wall_s=result["scf_time"],
    )
    if hub is not None:
        result["_hubbard_v"] = vhub  # ndarray, consumed by the band-path task
    if keep_state:
        # in-memory state for warm starts across geometry steps; the "scf"
        # sub-dict (mixer history + final band tolerance) lets the NEXT run
        # warm-start the quasi-Newton model too, not just the density (fed
        # back through initial_state= or initial_guess=(rho, psi, scf))
        if fused is not None and fused_carry is not None:
            _, _hist = fused.fetch_state(fused_carry, with_history=True)
        else:
            _hist = mixer.export_history()
        result["_state"] = {
            "rho_g": np.asarray(rho_g),
            "mag_g": None if mag_g is None else np.asarray(mag_g),
            "psi": np.asarray(psi),
            "paw_dm": None if paw_dm is None else np.asarray(paw_dm),
            "scf": (dict(_hist, res_tol=float(res_tol)) if _hist else None),
        }
    if polarized:
        result["magnetisation"] = {
            "total": [0.0, 0.0, float(np.real(mag_g[0]) * ctx.unit_cell.omega)],
            "atoms": [
                [0.0, 0.0, float(mz)] for mz in atomic_moments(ctx, mag_g)
            ],
        }
    if cfg.control.print_forces and num_iter_done > 0:
        from sirius_tpu.dft.forces import total_forces

        fterms = total_forces(
            ctx, rho_g, pot.vxc_g, pot.veff_g, pot.bz_g, psi, occ_np, evals,
            d_by_spin, dm_blocks_by_spin, rho_resid_g=rho_resid_g,
        )
        if hub is not None:
            from sirius_tpu.dft.forces import forces_hubbard, symmetrize_forces

            if hub.nonloc or getattr(
                ctx.cfg.hubbard, "hubbard_subspace_method", "none"
            ) == "full_orthogonalization":
                # the inter-site +V occupancy derivative and the
                # full_orthogonalization O^{-1/2} derivative are not
                # implemented; adding the bare-phi local term on top of
                # orbitals that were actually O^{-1/2}-mixed would be
                # inconsistent — skip the Hubbard force entirely (the
                # reference computes forces only for the simple local
                # correction, hubbard_occupancies_derivatives.cpp)
                import warnings

                warnings.warn(
                    "Hubbard force term SKIPPED: +V / full_orthogonalization "
                    "occupancy derivatives are not implemented; reported "
                    "forces omit the Hubbard contribution"
                )
            else:
                fh = forces_hubbard(
                    ctx, hub, um_local, psi, occ_np, ctx.max_occupancy
                )
                fterms["hubbard"] = fh
                fterms["total"] = symmetrize_forces(ctx, fterms["total"] + fh)
        result["forces"] = fterms["total"].tolist()
    if cfg.control.print_stress and num_iter_done > 0:
        from sirius_tpu.dft.stress import StressCalculator

        if mgga:
            # StressCalculator evaluates the XC functional without tau and
            # the tau-operator stress term is not implemented: computing a
            # plausibly-sized wrong tensor silently is worse than refusing
            raise NotImplementedError("stress with mGGA is not implemented")
        calc = StressCalculator(ctx, xc)
        sterms = calc.compute(
            rho_g, mag_g, rho_r,
            rho_real_space(ctx, mag_g) if polarized else None,
            psi, occ_np, evals, d_by_spin,
            dm_blocks_by_spin=dm_blocks_by_spin if ctx.aug is not None else None,
            hub=hub,
        )
        result["stress"] = sterms["total"].tolist()
    if save_to:
        from sirius_tpu.io.checkpoint import save_state

        save_state(
            save_to, ctx, rho_g, mag_g, pot.veff_g, pot.bz_g,
            np.asarray(psi), evals, occ_np, paw_dm=paw_dm,
        )
    return result


def _band_gap(evals: np.ndarray, occ: np.ndarray, ctx: SimulationContext) -> float:
    tol = 1e-6 * ctx.max_occupancy
    occupied = evals[occ > ctx.max_occupancy - 1e-4]
    empty = evals[occ < tol]
    if len(occupied) == 0 or len(empty) == 0:
        return 0.0
    gap = float(empty.min() - occupied.max())
    # metallic if partial occupancies straddle
    partial = (occ > tol) & (occ < ctx.max_occupancy - 1e-4)
    if np.any(partial) and gap < 1e-8:
        return 0.0
    return max(gap, 0.0)


def run_scf_from_file(
    path: str, test_against: str | None = None, task: str = "ground_state_new"
) -> int:
    import os

    cfg = load_config(path)
    base_dir = os.path.dirname(os.path.abspath(path))
    state_file = os.path.join(base_dir, "sirius.h5")
    if cfg.parameters.electronic_structure_method == "full_potential_lapwlo":
        # FP-LAPW branch (reference dft_ground_state FP path); tasks other
        # than the ground state are PP-PW-only for now
        if task not in ("ground_state_new", "ground_state"):
            raise NotImplementedError(
                f"FP-LAPW task '{task}' not supported yet (ground state only)"
            )
        from sirius_tpu.lapw.scf_fp import run_scf_fp

        result = run_scf_fp(cfg, base_dir)
        out = {"ground_state": result, "task": task, "context": {}}
        with open("output.json", "w") as f:
            json.dump(out, f, indent=2, default=float)
        if test_against:
            with open(test_against) as f:
                refgs = json.load(f)["ground_state"]
            de = abs(refgs["energy"]["total"] - result["energy"]["total"])
            print(f"total energy difference: {de:.3e}")
            if de >= 1e-5:
                import sys as _sys

                print(
                    f"sirius-scf: test_against FAILED: |dE_total|={de:.3e} "
                    "(tol 1e-05)", file=_sys.stderr,
                )
                return 1
            return 0
        return 0
    ref = None
    if test_against:
        with open(test_against) as f:
            ref = json.load(f)["ground_state"]
        # a reference quantity we would silently not compute is a failed
        # comparison waiting to happen — switch the calculations on
        if "forces" in ref:
            cfg.control.print_forces = True
        if "stress" in ref:
            cfg.control.print_stress = True
    if task == "ground_state_relax":
        from sirius_tpu.dft.relax import relax_atoms

        rr = relax_atoms(cfg, base_dir)
        result = rr["ground_state"]
        result["relaxation"] = {k: rr[k] for k in ("converged", "num_steps", "history", "final_positions")}
    elif task == "ground_state_restart":
        # prefer a mid-SCF autosave (continues the interrupted run with the
        # full mixer/psi/tolerance state); fall back to the density-only
        # warm start from the converged state file
        from sirius_tpu.io.checkpoint import find_resumable

        auto = cfg.control.autosave_path or default_autosave_path(
            cfg, base_dir)
        resume_path = find_resumable(
            auto, keep=int(getattr(cfg.control, "autosave_keep", 0)))
        if resume_path is not None:
            result = run_scf(cfg, base_dir, resume=resume_path,
                             save_to=state_file)
        else:
            result = run_scf(cfg, base_dir, restart_from=state_file,
                             save_to=state_file)
    elif task == "ground_state_direct":
        from sirius_tpu.dft.direct_min import run_direct_min

        result = run_direct_min(cfg, base_dir)
    elif task == "k_point_path":
        from sirius_tpu.context import SimulationContext
        from sirius_tpu.dft.bands import band_path, sample_path
        from sirius_tpu.dft.xc import XCFunctional

        if XCFunctional(cfg.parameters.xc_functionals).is_mgga:
            # the saved state carries no tau and band_path applies the
            # tau-less operator; fail BEFORE the (long) SCF, not after
            raise NotImplementedError("k_point_path with mGGA")
        # vk defines the band path, NOT the SCF mesh (reference task
        # semantics: SCF on ngridk, then bands along vk)
        vk_path = list(cfg.parameters.vk)
        cfg.parameters.vk = []
        ctx = SimulationContext.create(cfg, base_dir)
        result = run_scf(cfg, base_dir, save_to=state_file, ctx=ctx)
        cfg.parameters.vk = vk_path  # restore: the echoed config must match
        from sirius_tpu.dft.potential import generate_potential
        from sirius_tpu.io.checkpoint import load_state
        from sirius_tpu.ops.augmentation import d_operator

        state = load_state(state_file, ctx)
        xc = XCFunctional(cfg.parameters.xc_functionals)
        pot = generate_potential(ctx, state["rho_g"], xc, state.get("mag_g"))
        # screened per-spin D (ultrasoft) — same operator the SCF solved with
        if ctx.aug is not None:
            d_full = np.stack([
                d_operator(
                    ctx.unit_cell, ctx.gvec, ctx.aug,
                    pot.veff_g + (0 if pot.bz_g is None else (pot.bz_g if ispn == 0 else -pot.bz_g)),
                    ctx.beta,
                )
                for ispn in range(ctx.num_spins)
            ])
        else:
            d_full = None
        vk = vk_path if vk_path else [[0, 0, 0], [0.5, 0, 0]]
        result["band_path"] = band_path(
            ctx, pot, sample_path(np.asarray(vk)), d_full=d_full,
            vhub=result.get("_hubbard_v"),
        )
    else:  # ground_state_new
        result = run_scf(cfg, base_dir, save_to=state_file)
    result.pop("_hubbard_v", None)  # ndarray, not JSON-serializable
    result.pop("_state", None)
    out = {
        "ground_state": result,
        "task": task,
        "config": cfg.to_dict(),
        "git_hash": "",
        "comm_world_size": 1,
    }
    summary = {"energy": result["energy"], "efermi": result["efermi"],
               "converged": result["converged"],
               "num_scf_iterations": result["num_scf_iterations"]}
    if "magnetisation" in result:
        summary["magnetisation"] = result["magnetisation"]
    print(json.dumps(summary, indent=2))
    with open("output.json", "w") as f:
        json.dump(out, f, indent=2)
    if ref is not None:
        ok = True
        fails = []
        de = abs(ref["energy"]["total"] - result["energy"]["total"])
        print(f"|dE_total| vs reference: {de:.3e}")
        if de >= 1e-5:
            ok = False
            fails.append(f"|dE_total|={de:.3e} (tol 1e-05)")
        for key, label, tol in (("forces", "|dF|_max", 1e-5), ("stress", "|dsigma|_max", 1e-5)):
            if key in ref:
                if key not in result:
                    print(f"{key}: present in reference but not computed -> FAIL")
                    ok = False
                    fails.append(f"{key} missing from result")
                    continue
                d = float(np.abs(np.asarray(ref[key]) - np.asarray(result[key])).max())
                print(f"{label} vs reference: {d:.3e}")
                if d >= tol:
                    ok = False
                    fails.append(f"{label}={d:.3e} (tol {tol:g})")
        print("TEST PASSED" if ok else "TEST FAILED")
        if not ok:
            # one-line machine-greppable diff summary on stderr: the serve
            # engine and CI use the exit code + this line as the probe
            import sys as _sys

            print(
                "sirius-scf: test_against FAILED: " + "; ".join(fails),
                file=_sys.stderr,
            )
            return 1
        return 0
    return 0
