"""Spectral gradient/divergence of muffin-tin (on-site) functions.

A function f(x) = sum_lm f_lm(|x|) R_lm(x-hat) has an exact spectral
cartesian gradient coupling l -> l+-1 channels with radial operators
(d/dr - l/r) and (d/dr + (l+1)/r) and Clebsch-Gordan(l, 1, l+-1)
coefficients — reference src/function3d/spheric_function.hpp:559-652
(gradient/divergence in complex harmonics, converted to real harmonics).

Real<->complex harmonic transforms are built NUMERICALLY from this
package's own ylm_real/ylm_complex evaluations on an exact quadrature, so
phase-convention mismatches are structurally impossible.
"""

from __future__ import annotations

from functools import lru_cache

import numpy as np

from sirius_tpu.core.sht import (
    _sphere_quadrature,
    lm_index,
    num_lm,
    ylm_complex,
    ylm_real,
)


@lru_cache(maxsize=8)
def _r2y_blocks(lmax: int):
    """Per-l matrices C with R_lm(x) = sum_m' Y_lm'(x) C[m', m]; i.e. the
    complex coefficients of a real expansion are fY = C @ fR per l block."""
    pts, w = _sphere_quadrature(2 * lmax + 2)
    Y = ylm_complex(lmax, pts)  # [npts, lmmax]
    R = ylm_real(lmax, pts)
    out = []
    for l in range(lmax + 1):
        idx = [lm_index(l, m) for m in range(-l, l + 1)]
        Yl = Y[:, idx]
        Rl = R[:, idx]
        # C = <Y|R> with the quadrature inner product (Y orthonormal)
        C = np.einsum("pi,p,pj->ij", np.conj(Yl), w, Rl)
        out.append((idx, C))
    return out


def _cg_lp1(l: int, m: int, mu: int) -> float:
    """<l m; 1 mu | l+1 m+mu> (closed form)."""
    if mu == 1:
        return np.sqrt((l + m + 1) * (l + m + 2) / ((2 * l + 1) * (2 * l + 2)))
    if mu == 0:
        return np.sqrt((l - m + 1) * (l + m + 1) / ((2 * l + 1) * (l + 1)))
    return np.sqrt((l - m + 1) * (l - m + 2) / ((2 * l + 1) * (2 * l + 2)))


def _cg_lm1(l: int, m: int, mu: int) -> float:
    """<l m; 1 mu | l-1 m+mu> (closed form, Edmonds table for j2=1)."""
    if mu == 1:
        return np.sqrt((l - m) * (l - m - 1) / (2 * l * (2 * l + 1)))
    if mu == 0:
        return -np.sqrt((l - m) * (l + m) / (l * (2 * l + 1)))
    return np.sqrt((l + m) * (l + m - 1) / (2 * l * (2 * l + 1)))


def _gradient_lm_complex(fy: np.ndarray, r: np.ndarray, lmax: int) -> np.ndarray:
    """Gradient of a complex-harmonic expansion fy [lmmax, nr] ->
    [3(x,y,z), lmmax, nr] (reference spheric_function.hpp:559)."""
    from scipy.interpolate import CubicSpline

    lmmax = num_lm(lmax)
    g = np.zeros((3, lmmax, len(r)), dtype=np.complex128)  # (mu=+1, mu=-1, z)
    # cubic-spline radial derivative (reference Spline::deriv): a 2nd-order
    # finite difference here loses ~1e-3 Ha on the steep AE-core density in
    # the on-site GGA XC (Fe, verification/test03)
    dfy = CubicSpline(r, fy, axis=-1)(r, 1)
    rinv = 1.0 / r
    for l in range(lmax + 1):
        d1 = np.sqrt((l + 1) / (2 * l + 3))
        d2 = np.sqrt(l / (2 * l - 1)) if l > 0 else 0.0
        for m in range(-l, l + 1):
            lm = lm_index(l, m)
            s = fy[lm]
            ds = dfy[lm]
            for mu in (-1, 0, 1):
                j = {1: 0, -1: 1, 0: 2}[mu]
                if l + 1 <= lmax and abs(m + mu) <= l + 1:
                    d = d1 * _cg_lp1(l, m, mu)
                    g[j, lm_index(l + 1, m + mu)] += (ds - s * rinv * l) * d
                if l - 1 >= 0 and abs(m + mu) <= l - 1:
                    d = d2 * _cg_lm1(l, m, mu)
                    g[j, lm_index(l - 1, m + mu)] -= (ds + s * rinv * (l + 1)) * d
    gp, gm, gz = g
    inv_sqrt2 = 1.0 / np.sqrt(2.0)
    return np.stack([
        (gm - gp) * inv_sqrt2,
        1j * (gm + gp) * inv_sqrt2,
        gz,
    ])


def _real_to_complex(fr: np.ndarray, lmax: int) -> np.ndarray:
    fy = np.zeros(fr.shape, dtype=np.complex128)
    for idx, C in _r2y_blocks(lmax):
        fy[idx] = np.einsum("ij,j...->i...", C, fr[idx])
    return fy


def _complex_to_real(fy: np.ndarray, lmax: int) -> np.ndarray:
    fr = np.zeros(fy.shape, dtype=np.complex128)
    for idx, C in _r2y_blocks(lmax):
        fr[idx] = np.einsum("ji,j...->i...", np.conj(C), fy[idx])
    return np.real(fr)


def gradient_lm_real(fr: np.ndarray, r: np.ndarray) -> np.ndarray:
    """Cartesian gradient of a real-harmonic expansion fr [lmmax, nr] ->
    [3, lmmax, nr] real-harmonic expansions (l channels above lmax are
    truncated, like the reference)."""
    lmax = int(np.sqrt(fr.shape[0])) - 1
    fy = _real_to_complex(fr, lmax)
    gy = _gradient_lm_complex(fy, r, lmax)
    return np.stack([_complex_to_real(gy[i], lmax) for i in range(3)])


def divergence_lm_real(w: np.ndarray, r: np.ndarray) -> np.ndarray:
    """Divergence of a cartesian vector of real-harmonic expansions
    w [3, lmmax, nr] -> [lmmax, nr] (reference divergence, sum of
    gradient components)."""
    lmax = int(np.sqrt(w.shape[1])) - 1
    out = np.zeros(w.shape[1:])
    for i in range(3):
        fy = _real_to_complex(w[i], lmax)
        gy = _gradient_lm_complex(fy, r, lmax)
        out += _complex_to_real(gy[i], lmax)
    return out
