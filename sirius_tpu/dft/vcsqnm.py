"""Variable-cell stabilized quasi-Newton (VC-SQNM) structure optimizer.

Reference: src/vcsqnm/sqnm.hpp (stabilized QN on the significant-subspace
Hessian, arXiv:2206.07339) and src/vcsqnm/periodic_optimizer.hpp (the
combined atomic + lattice coordinate transform). Host-side numpy — the
optimizer drives SCF runs; there is nothing to jit.

Conventions: positions/forces are CARTESIAN [nat, 3] row vectors; the
lattice matrix has ROWS a_i (the repo-wide convention — the reference's
Eigen column matrices are transposed here). Stress is the symmetric
Cartesian stress tensor; forces are -dE/dr (forces, not gradients).
"""

from __future__ import annotations

import numpy as np


class _HistoryList:
    """Sliding history with consecutive-difference lists (reference
    historylist.hpp): difflist[:, i] = v_{i+1} - v_i over kept entries."""

    def __init__(self, nhist_max: int):
        self.nhist_max = nhist_max
        self.entries: list[np.ndarray] = []

    def add(self, v: np.ndarray) -> int:
        self.entries.append(np.asarray(v, float).copy())
        if len(self.entries) > self.nhist_max + 1:
            self.entries.pop(0)
        return len(self.entries) - 1

    @property
    def difflist(self) -> np.ndarray:
        d = [
            self.entries[i + 1] - self.entries[i]
            for i in range(len(self.entries) - 1)
        ]
        return np.stack(d, axis=1) if d else np.zeros((0, 0))


class SQNM:
    """Stabilized quasi-Newton minimizer (reference sqnm.hpp:100-240)."""

    def __init__(self, ndim: int, nhist_max: int, alpha: float,
                 alpha0: float = 1e-2, eps_subsp: float = 1e-4):
        self.ndim = ndim
        self.nhist_max = min(nhist_max, ndim)
        self.alpha = alpha
        self.alpha0 = alpha0
        self.eps_subsp = eps_subsp
        self.xlist = _HistoryList(self.nhist_max)
        self.flist = _HistoryList(self.nhist_max)
        self.prev_f = 0.0
        self.prev_df = None
        self.dir = None
        self.h_eval_min = 1.0

    def step(self, x: np.ndarray, f_of_x: float, df_dx: np.ndarray) -> np.ndarray:
        """Displacement to ADD to x (df_dx is the gradient, = -force)."""
        x = np.asarray(x, float)
        df = np.asarray(df_dx, float)
        if np.linalg.norm(df) <= 1e-13:
            return np.zeros(self.ndim)
        nhist = self.xlist.add(x)
        self.flist.add(df)
        if nhist == 0:
            self.dir = -self.alpha * df
        else:
            gain = (f_of_x - self.prev_f) / (
                0.5 * float(self.dir @ self.prev_df)
            )
            if gain < 0.5:
                self.alpha = max(self.alpha * 0.65, self.alpha0)
            elif gain > 1.05:
                self.alpha *= 1.05

            dx = self.xlist.difflist  # [ndim, nhist]
            dg = self.flist.difflist
            norms = np.linalg.norm(dx, axis=0)
            dxn = dx / norms[None, :]
            S = dxn.T @ dxn
            s_eval, s_evec = np.linalg.eigh(S)
            keep = s_eval / s_eval[-1] > self.eps_subsp
            s_eval, s_evec = s_eval[keep], s_evec[:, keep]
            dr_sub = (dxn @ s_evec) / np.sqrt(s_eval)[None, :]
            df_sub = ((dg / norms[None, :]) @ s_evec) / np.sqrt(s_eval)[None, :]
            h = 0.5 * (df_sub.T @ dr_sub + dr_sub.T @ df_sub)
            h_eval, h_evec_s = np.linalg.eigh(h)
            h_evec = dr_sub @ h_evec_s  # eq. 15
            # residues (eq. 20) stabilize the eigenvalues (eq. 18)
            res = np.linalg.norm(
                df_sub @ h_evec_s - h_evec * h_eval[None, :], axis=0
            )
            h_eval = np.sqrt(h_eval**2 + res**2)
            self.h_eval_min = float(h_eval[0])
            # gradient split: steepest descent outside the subspace,
            # Newton inside (eqs. 16, 21)
            proj = h_evec.T @ df
            d = self.alpha * (df - h_evec @ proj)
            d += h_evec @ (proj / h_eval)
            self.dir = -d
        self.prev_f = float(f_of_x)
        self.prev_df = df
        return self.dir

    def lower_bound(self) -> float:
        if self.prev_df is None:
            return 0.0
        return self.prev_f - 0.5 * float(
            self.prev_df @ self.prev_df
        ) / max(self.h_eval_min, 1e-12)


class PeriodicOptimizer:
    """Fixed- or variable-cell relaxation driver (reference
    periodic_optimizer.hpp). For vc mode the lattice rides along as 9
    extra coordinates scaled by w*sqrt(nat)/|a_i| so atomic and cell
    degrees of freedom share one Hessian model."""

    def __init__(self, nat: int, lattice: np.ndarray | None = None,
                 initial_step_size: float = 1.0, nhist_max: int = 10,
                 lattice_weight: float = 2.0, alpha0: float = 1e-2,
                 eps_subsp: float = 1e-4):
        self.nat = nat
        self.vc = lattice is not None
        ndim = 3 * nat + (9 if self.vc else 0)
        self.opt = SQNM(ndim, nhist_max, initial_step_size, alpha0, eps_subsp)
        if self.vc:
            a0 = np.asarray(lattice, float)  # rows a_i
            self.a0 = a0
            self.a0_inv = np.linalg.inv(a0)
            t = np.diag(
                lattice_weight * np.sqrt(nat) / np.linalg.norm(a0, axis=1)
            )
            self.T = t
            self.T_inv = np.linalg.inv(t)

    def step_fixed(self, r: np.ndarray, energy: float, forces: np.ndarray):
        """r [nat,3] cartesian -> improved positions."""
        d = self.opt.step(r.ravel(), energy, -np.asarray(forces).ravel())
        return r + d.reshape(self.nat, 3)

    def step_vc(self, r: np.ndarray, energy: float, forces: np.ndarray,
                lattice: np.ndarray, stress: np.ndarray):
        """(positions [nat,3], lattice rows [3,3]) -> improved pair.

        q = r a^-1 a0 (fractional-consistent transformed coordinates),
        dq = -f a0^-1 a; lattice block scaled by T; lattice gradient
        da = -det(a) a^-1 stress (row convention transpose of the
        reference's calc_lattice_derivatices)."""
        a = np.asarray(lattice, float)
        f = np.asarray(forces, float)
        q = r @ np.linalg.inv(a) @ self.a0
        dq = -f @ self.a0_inv @ a
        a_t = self.T @ a
        da = -(np.linalg.det(a) * np.linalg.inv(a).T @ np.asarray(stress, float))
        da_t = self.T_inv @ da
        xall = np.concatenate([q.ravel(), a_t.ravel()])
        dall = np.concatenate([dq.ravel(), da_t.ravel()])
        step = self.opt.step(xall, energy, dall)
        xall = xall + step
        q = xall[: 3 * self.nat].reshape(self.nat, 3)
        a_t = xall[3 * self.nat :].reshape(3, 3)
        a_new = self.T_inv @ a_t
        r_new = q @ self.a0_inv @ a_new
        return r_new, a_new
