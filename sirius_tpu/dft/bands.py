"""Non-self-consistent band structure along a k-path (reference: sirius.scf
task k_point_path + apps/bands/bands.py plotting data).

The converged density/potential defines a fixed Hamiltonian; bands at each
path point are solved with the same blocked iterative solver on a fresh
|G+k| sphere."""

from __future__ import annotations

import numpy as np


def band_path(
    ctx,
    pot,
    kpoints: np.ndarray,  # (nk, 3) fractional path vertices (already sampled)
    num_bands: int | None = None,
    d_full=None,
    vhub: np.ndarray | None = None,  # converged Hubbard potential [ns, ...]
) -> dict:
    import dataclasses as _dc

    import jax.numpy as jnp

    from sirius_tpu.core.gvec import GkVec
    from sirius_tpu.ops.beta import BetaProjectors
    from sirius_tpu.ops.hamiltonian import HkParams, apply_h_s
    from sirius_tpu.solvers.davidson import davidson

    nb = num_bands or ctx.num_bands
    kpts = np.atleast_2d(np.asarray(kpoints, dtype=np.float64))
    gk = GkVec.build(ctx.gvec, kpts, ctx.cfg.parameters.gk_cutoff, ctx.fft_coarse)
    beta = BetaProjectors.build(ctx.unit_cell, gk, qmax=ctx.cfg.parameters.gk_cutoff + 1e-9)
    hub_path = None
    if vhub is not None and ctx.cfg.parameters.hubbard_correction:
        # rebuild the Hubbard orbital tables on the path k-points so NSCF
        # bands include the converged U potential
        from sirius_tpu.ops.hubbard import HubbardData

        # path projectors share the cell layout, so the SCF qmat applies
        ctx_path = _dc.replace(
            ctx, gkvec=gk, beta=_dc.replace(beta, qmat=ctx.beta.qmat)
        )
        hub_path = HubbardData.build(ctx_path)
    ns = ctx.num_spins
    dion = ctx.beta.dion if d_full is None else d_full
    qmat = ctx.beta.qmat if ctx.beta.qmat is not None else np.zeros_like(dion)
    rng = np.random.default_rng(7)
    evals = np.zeros((len(kpts), ns, nb))
    for ik in range(len(kpts)):
        ekin = gk.kinetic()[ik]
        for ispn in range(ns):
            veff_r = pot.veff_r_coarse[ispn]
            params = HkParams(
                veff_r=jnp.asarray(veff_r),
                ekin=jnp.asarray(ekin),
                mask=jnp.asarray(gk.mask[ik]),
                fft_index=jnp.asarray(gk.fft_index[ik]),
                beta=jnp.asarray(beta.beta_gk[ik], dtype=jnp.complex128),
                dion=jnp.asarray(dion if np.ndim(dion) == 2 else dion[ispn]),
                qmat=jnp.asarray(qmat),
                hub=None if hub_path is None else jnp.asarray(hub_path.phi_s_gk[ik]),
                vhub=None if hub_path is None else jnp.asarray(vhub[ispn]),
            )
            x0 = (
                rng.standard_normal((nb, gk.ngk_max))
                + 1j * rng.standard_normal((nb, gk.ngk_max))
            ) / (1.0 + ekin)[None, :]
            h_diag = np.where(gk.mask[ik] > 0, ekin + float(np.real(pot.veff_g[0])), 1e4)
            ev, x, rn = davidson(
                apply_h_s, params, jnp.asarray(x0 * gk.mask[ik]),
                jnp.asarray(h_diag), jnp.ones(gk.ngk_max), jnp.asarray(gk.mask[ik]),
                num_steps=40, res_tol=1e-8,
            )
            evals[ik, ispn] = np.asarray(ev)
    return {"kpoints": kpts.tolist(), "bands": evals.tolist()}


def sample_path(vertices: np.ndarray, points_per_segment: int = 20) -> np.ndarray:
    """Linear interpolation between path vertices."""
    vs = np.atleast_2d(np.asarray(vertices, dtype=np.float64))
    out = []
    for i in range(len(vs) - 1):
        for j in range(points_per_segment):
            out.append(vs[i] + (vs[i + 1] - vs[i]) * j / points_per_segment)
    out.append(vs[-1])
    return np.asarray(out)
