"""PAW on-site corrections: densities, potentials, Dij and energies.

Reference scheme (replicated exactly so reference decks match):
  - on-site ae/ps densities from the real packed density matrix with real
    Gaunt coefficients (src/density/density.cpp:506-573
    generate_paw_density; dm conversion density.cpp:1783-1810)
  - per-atom XC on a radial x angular product grid plus an on-site Hartree
    solve with free-atom boundary and NO nuclear term
    (src/potential/paw_potential.cpp:119-216 xc_mt_paw /
    calc_PAW_hartree_potential with poisson_vmt<true>,
    potential.hpp:296-385)
  - Dij radial integrals contracted with Gaunt coefficients
    (paw_potential.cpp:218-305 calc_PAW_local_Dij), added to the ultrasoft
    D matrix before the band solve
  - energies: PAW_total = on-site Hartree difference + XC difference
    (incl. core-XC), PAW_one_elec = sum dm_ij Dij (double counting),
    entering the total exactly as in src/dft/energy.cpp:152-156.

All per-atom work is vectorized numpy on the host (radial grids ~1e3
points, lm spaces ~25): it is O(MB) bookkeeping next to the jitted
plane-wave hot path, and runs once per SCF iteration.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from sirius_tpu.core.sht import gaunt_rlm, num_lm, ylm_real, _sphere_quadrature
from sirius_tpu.core.radial import spline_quadrature_weights

Y00 = 1.0 / np.sqrt(4.0 * np.pi)


def _cumulative_integral(r: np.ndarray, f: np.ndarray) -> np.ndarray:
    """Cumulative spline integral int_{r_0}^{r_i} f dr (matches the
    reference's Spline::integrate running sums; zero at the first knot)."""
    from scipy.interpolate import CubicSpline

    return CubicSpline(r, f).antiderivative()(r)


@dataclasses.dataclass
class PawTypeData:
    """Per-species PAW tables (all on the species' full radial mesh; the
    partial waves are zero beyond the augmentation cutoff index)."""

    r: np.ndarray  # [nr]
    rw: np.ndarray  # [nr] radial quadrature weights (plain dr metric)
    l_rf: np.ndarray  # [nbrf] l of each radial projector/partial wave
    ae_pair: np.ndarray  # [npack_rb, nr] (r phi_ae_i)(r phi_ae_j)
    ps_pair: np.ndarray  # [npack_rb, nr]
    q_pair: np.ndarray  # [npack_rb, lmax_rho+1, nr] Q_ij^l(r)
    ae_core: np.ndarray  # [nr]
    ps_core: np.ndarray  # [nr]
    core_energy: float
    occupations: np.ndarray  # [nbrf]
    # basis maps
    xi_rf: np.ndarray  # [nbf] radial-function index of basis function
    xi_lm: np.ndarray  # [nbf] lm index
    lmax: int
    lmmax_rho: int  # (2 lmax + 1)^2
    l_by_lm3: np.ndarray  # [lmmax_rho]
    gaunt: np.ndarray  # [nlm_b, nlm_b, lmmax_rho] real Gaunt
    # angular quadrature for the XC grid
    ang_pts_w: np.ndarray  # [npts]
    rlm: np.ndarray  # [npts, lmmax_rho]

    @property
    def nbf(self) -> int:
        return len(self.xi_rf)

    @property
    def npack_xi(self) -> int:
        return self.nbf * (self.nbf + 1) // 2

    @staticmethod
    def build(t) -> "PawTypeData":
        """t: crystal.atom_type.AtomType with pseudo_type == 'PAW'."""
        paw = t.paw
        r = t.r
        nr = len(r)
        nbrf = t.num_beta
        l_rf = np.asarray([b.l for b in t.beta])
        lmax = int(l_rf.max()) if nbrf else 0
        lmax_rho = 2 * lmax
        lmmax_rho = num_lm(lmax_rho)

        def padded(v):
            out = np.zeros(nr)
            v = np.asarray(v, dtype=np.float64)
            out[: len(v)] = v
            return out

        ae_wf = np.stack([padded(w["radial_function"]) for w in paw["ae_wfc"]])
        ps_wf = np.stack([padded(w["radial_function"]) for w in paw["ps_wfc"]])
        # the file stores full-mesh partial waves; the reference keeps only
        # the first header.cutoff_radius_index points (atom_type.cpp:682) —
        # the tails beyond r_cut are large and MUST be dropped
        icut = t.cutoff_radius_index if t.cutoff_radius_index else nr
        icut = min(int(icut), nr)
        ae_wf[:, icut:] = 0.0
        ps_wf[:, icut:] = 0.0

        npack_rb = nbrf * (nbrf + 1) // 2
        ae_pair = np.empty((npack_rb, nr))
        ps_pair = np.empty((npack_rb, nr))
        q_pair = np.zeros((npack_rb, lmax_rho + 1, nr))
        for j in range(nbrf):
            for i in range(j + 1):
                p = j * (j + 1) // 2 + i
                ae_pair[p] = ae_wf[i] * ae_wf[j]
                ps_pair[p] = ps_wf[i] * ps_wf[j]
        for ch in t.augmentation:
            i, j, l = ch.i, ch.j, ch.l
            if j < i:
                i, j = j, i
            p = j * (j + 1) // 2 + i
            if l <= lmax_rho:
                q_pair[p, l, : len(ch.qr)] = ch.qr

        # single source for the basis ordering convention
        from sirius_tpu.core.sht import lm_index

        idxrf, ls, ms = t.beta_lm_table()
        xi_rf = idxrf
        xi_lm = np.asarray([lm_index(l, m) for l, m in zip(ls, ms)])

        # quadrature order matches the reference's SHT Lebedev mesh
        # (sht.hpp: Lebedev_Laikov_npoint(2*lmax) with lmax = lmax_rho):
        # the on-site XC is DEFINED on that grid, so deck parity requires
        # the same resolution — a denser grid changes e_xc by ~2e-5 (Fe)
        pts, w = _sphere_quadrature(2 * lmax_rho)
        # some generators start the mesh at r = 0; the on-site densities
        # divide by r^2 and the Poisson solve by r^(l+1), so guard the origin
        r_safe = r.copy()
        if r_safe[0] <= 0.0:
            r_safe[0] = min(1e-8, 0.5 * r_safe[1])
        out = PawTypeData(
            r=r_safe,
            rw=spline_quadrature_weights(r),
            l_rf=l_rf,
            ae_pair=ae_pair,
            ps_pair=ps_pair,
            q_pair=q_pair,
            ae_core=padded(paw["ae_core_charge_density"]),
            ps_core=padded(t.rho_core) if t.rho_core is not None else np.zeros(nr),
            # parsed for completeness; the reference parses but never adds it
            # to the total energy (atom_type.hpp:1102 accessor is unused)
            core_energy=float(t.paw_core_energy),
            occupations=np.asarray(paw.get("occupations", np.zeros(nbrf))),
            xi_rf=np.asarray(xi_rf),
            xi_lm=np.asarray(xi_lm),
            lmax=lmax,
            lmmax_rho=lmmax_rho,
            l_by_lm3=np.asarray([l for l in range(lmax_rho + 1) for _ in range(2 * l + 1)]),
            gaunt=gaunt_rlm(lmax, lmax, lmax_rho),
            ang_pts_w=w,
            rlm=ylm_real(lmax_rho, pts),
        )
        out._pack_maps = out._build_pack_maps()
        return out

    def _build_pack_maps(self):
        n = self.nbf
        w_lm = np.zeros((self.npack_xi, self.lmmax_rho))
        pair_rb = np.empty(self.npack_xi, dtype=np.int64)
        for xi2 in range(n):
            for xi1 in range(xi2 + 1):
                p = xi2 * (xi2 + 1) // 2 + xi1
                diag = 1.0 if xi1 == xi2 else 2.0
                w_lm[p] = diag * self.gaunt[self.xi_lm[xi1], self.xi_lm[xi2]]
                i, j = sorted((self.xi_rf[xi1], self.xi_rf[xi2]))
                pair_rb[p] = j * (j + 1) // 2 + i
        return w_lm, pair_rb

    def pack_maps(self):
        """Cached xi-pair -> (Gaunt row with diag factor, radial-pair row)."""
        return self._pack_maps


@dataclasses.dataclass
class PawData:
    """Per-run PAW bookkeeping: which atoms are PAW, their type tables."""

    atoms: list[int]  # global atom indices
    types: list[PawTypeData]  # parallel to atoms
    offsets: list[int]  # beta-block offset of each PAW atom
    num_mag: int  # num_mag_dims (0 collinear-off, 1 collinear)

    @staticmethod
    def build(ctx) -> "PawData | None":
        uc = ctx.unit_cell
        paw_types = {}
        atoms, types, offsets = [], [], []
        blocks = {ia: (off, nbf) for ia, off, nbf in ctx.beta.atom_blocks(uc)}
        for ia in range(uc.num_atoms):
            it = uc.type_of_atom[ia]
            t = uc.atom_types[it]
            if t.pseudo_type != "PAW":
                continue
            if it not in paw_types:
                paw_types[it] = PawTypeData.build(t)
            atoms.append(ia)
            types.append(paw_types[it])
            offsets.append(blocks[ia][0])
        if not atoms:
            return None
        return PawData(
            atoms=atoms, types=types, offsets=offsets,
            num_mag=ctx.num_mag_dims,
        )

    def dm_size(self) -> int:
        return sum(t.npack_xi * (self.num_mag + 1) for t in self.types)

    def initial_dm(self, ctx) -> np.ndarray:
        """Packed real dm from the file occupations (reference
        density.cpp:470-505 init_density_matrix_for_paw_atom)."""
        out = []
        uc = ctx.unit_cell
        for ia, t in zip(self.atoms, self.types):
            dm = np.zeros((t.npack_xi, self.num_mag + 1))
            mz = uc.moments[ia, 2] if self.num_mag else 0.0
            nm = np.clip(mz, -1.0, 1.0)
            for xi in range(t.nbf):
                p = xi * (xi + 1) // 2 + xi
                l = t.l_rf[t.xi_rf[xi]]
                occ = t.occupations[t.xi_rf[xi]]
                if self.num_mag == 0:
                    dm[p, 0] = occ / (2 * l + 1)
                else:
                    up = 0.5 * (1 + nm) * occ / (2 * l + 1)
                    dn = 0.5 * (1 - nm) * occ / (2 * l + 1)
                    dm[p, 0] = up + dn
                    dm[p, 1] = up - dn
            out.append(dm.ravel())
        return np.concatenate(out)

    def dm_from_density_matrix(self, dm_by_spin: np.ndarray) -> np.ndarray:
        """Packed real per-atom dm from the full complex density matrix
        [ns, nbeta_tot, nbeta_tot] (reference density_matrix_aux)."""
        ns = dm_by_spin.shape[0]
        out = []
        for ia, t, off in zip(self.atoms, self.types, self.offsets):
            n = t.nbf
            blk = dm_by_spin[:, off : off + n, off : off + n]
            dm = np.zeros((t.npack_xi, self.num_mag + 1))
            for xi2 in range(n):
                for xi1 in range(xi2 + 1):
                    p = xi2 * (xi2 + 1) // 2 + xi1
                    if ns == 2:
                        dm[p, 0] = np.real(blk[0, xi2, xi1] + blk[1, xi2, xi1])
                        dm[p, 1] = np.real(blk[0, xi2, xi1] - blk[1, xi2, xi1])
                    else:
                        dm[p, 0] = np.real(blk[0, xi2, xi1])
            out.append(dm.ravel())
        return np.concatenate(out)

    def split_dm(self, flat: np.ndarray) -> list[np.ndarray]:
        out = []
        pos = 0
        for t in self.types:
            n = t.npack_xi * (self.num_mag + 1)
            out.append(flat[pos : pos + n].reshape(t.npack_xi, self.num_mag + 1))
            pos += n
        return out


def onsite_density(t: PawTypeData, dmp: np.ndarray):
    """(ae_dens, ps_dens) [nmag+1, lmmax_rho, nr] from the packed dm
    (reference generate_paw_density)."""
    w_lm, pair_rb = t.pack_maps()
    inv_r2 = 1.0 / t.r**2
    nmag1 = dmp.shape[1]
    ae = np.empty((nmag1, t.lmmax_rho, len(t.r)))
    ps = np.empty_like(ae)
    aep = t.ae_pair[pair_rb] * inv_r2  # [npack_xi, nr]
    psp = t.ps_pair[pair_rb] * inv_r2
    q3 = t.q_pair[pair_rb][:, t.l_by_lm3, :] * inv_r2  # [npack_xi, lmmax, nr]
    for im in range(nmag1):
        a = dmp[:, im : im + 1] * w_lm  # [npack_xi, lmmax]
        ae[im] = np.einsum("pm,pr->mr", a, aep, optimize=True)
        ps[im] = np.einsum("pm,pr->mr", a, psp, optimize=True) + np.einsum(
            "pm,pmr->mr", a, q3, optimize=True
        )
    return ae, ps


def poisson_onsite(t: PawTypeData, rho_lm: np.ndarray) -> np.ndarray:
    """Free-boundary radial Poisson per lm channel (reference
    poisson_vmt<true>, potential.hpp:357): no nuclear term."""
    r = t.r
    v = np.zeros_like(rho_lm)
    for lm in range(rho_lm.shape[0]):
        l = t.l_by_lm3[lm]
        g1 = _cumulative_integral(r, rho_lm[lm] * r ** (l + 2))
        g2 = _cumulative_integral(r, rho_lm[lm] * r ** (1 - l))
        v[lm] = (4.0 * np.pi / (2 * l + 1)) * (
            g1 / r ** (l + 1) + (g2[-1] - g2) * r**l
        )
    return v


def _inner_lm(t: PawTypeData, f_lm: np.ndarray, g_lm: np.ndarray) -> float:
    """sum_lm int f_lm g_lm r^2 dr."""
    return float(np.einsum("mr,mr,r->", f_lm, g_lm, t.rw * t.r**2, optimize=True))


def xc_onsite(t: PawTypeData, rho_lm: np.ndarray, core: np.ndarray, xc):
    """LDA XC on the radial x angular grid: returns (vxc_lm [nmag+1,
    lmmax, nr], exc_lm [lmmax, nr]) with the reference's conventions
    (vxc components = (v, bz), exc = energy per particle; core added to the
    scalar density, reference xc_mt_paw)."""
    if xc.is_gga:
        return xc_onsite_gga(t, rho_lm, core, xc)
    import jax.numpy as jnp

    nmag1 = rho_lm.shape[0]
    rho0 = rho_lm[0].copy()
    rho0[0] += core / Y00
    rho_pt = t.rlm @ rho0  # [npts, nr]
    if nmag1 == 2:
        m_pt = t.rlm @ rho_lm[1]
        up = 0.5 * (rho_pt + m_pt)
        dn = 0.5 * (rho_pt - m_pt)
    else:
        up = dn = 0.5 * rho_pt
    shape = rho_pt.shape
    out = xc.evaluate_polarized(
        jnp.asarray(np.maximum(up, 0.0).ravel()),
        jnp.asarray(np.maximum(dn, 0.0).ravel()),
    )
    e = np.asarray(out["e"]).reshape(shape)
    vu = np.asarray(out["v_up"]).reshape(shape)
    vd = np.asarray(out["v_dn"]).reshape(shape)
    eps = np.where(np.abs(rho_pt) > 1e-30, e / np.where(np.abs(rho_pt) > 1e-30, rho_pt, 1.0), 0.0)
    proj = (t.ang_pts_w[:, None] * t.rlm).T  # [lmmax, npts]
    vxc = np.empty((nmag1,) + rho_lm.shape[1:])
    vxc[0] = proj @ (0.5 * (vu + vd))
    if nmag1 == 2:
        vxc[1] = proj @ (0.5 * (vu - vd))
    exc_lm = proj @ eps
    return vxc, exc_lm


def xc_onsite_gga(t: PawTypeData, rho_lm: np.ndarray, core: np.ndarray, xc):
    """GGA XC on the radial x angular grid.

    Reference scheme (xc_mt.cpp): channel densities and their spectral
    cartesian gradients (dft/mt_gradient, reference
    spheric_function.hpp:559) are truncated at the SHT lmax and evaluated
    on the order-2*lmax mesh (t.rlm / t.ang_pts_w) — the on-site XC is
    DEFINED on that grid, so deck parity requires matching its resolution.
    The potential's -div(...) term is assembled spectrally and evaluated
    with the same quadrature."""
    import jax.numpy as jnp

    from sirius_tpu.dft.mt_gradient import divergence_lm_real, gradient_lm_real

    nmag1 = rho_lm.shape[0]
    rlm_g = t.rlm
    w_pts = t.ang_pts_w

    rho0 = rho_lm[0].copy()
    rho0[0] += core / Y00
    if nmag1 == 2:
        up_lm = 0.5 * (rho0 + rho_lm[1])
        dn_lm = 0.5 * (rho0 - rho_lm[1])
    else:
        up_lm = dn_lm = 0.5 * rho0
    gu = gradient_lm_real(up_lm, t.r)  # [3, lmmax_g, nr]
    gd = gu if nmag1 == 1 else gradient_lm_real(dn_lm, t.r)

    to_pt = lambda f_lm: rlm_g @ f_lm  # [npts, nr]
    up = np.maximum(to_pt(up_lm), 1e-20)
    dn = np.maximum(to_pt(dn_lm), 1e-20)
    gu_pt = np.stack([to_pt(gu[i]) for i in range(3)])
    gd_pt = gu_pt if nmag1 == 1 else np.stack([to_pt(gd[i]) for i in range(3)])
    suu = np.sum(gu_pt**2, axis=0)
    sud = np.sum(gu_pt * gd_pt, axis=0)
    sdd = np.sum(gd_pt**2, axis=0)

    shape = up.shape
    out = xc.evaluate_polarized(
        jnp.asarray(up.ravel()), jnp.asarray(dn.ravel()),
        jnp.asarray(suu.ravel()), jnp.asarray(sud.ravel()),
        jnp.asarray(sdd.ravel()),
    )
    e = np.asarray(out["e"]).reshape(shape)
    vu = np.asarray(out["v_up"]).reshape(shape)
    vd = np.asarray(out["v_dn"]).reshape(shape)
    vsuu = np.asarray(out["vsigma_uu"]).reshape(shape)
    vsud = np.asarray(out["vsigma_ud"]).reshape(shape)
    vsdd = np.asarray(out["vsigma_dd"]).reshape(shape)

    proj_g = (w_pts[:, None] * rlm_g).T  # [lmmax_g, npts]
    # W_s = 2 vsigma_ss grad n_s + vsigma_ud grad n_other; v_s -= div W_s
    wu_lm = np.stack([proj_g @ (2.0 * vsuu * gu_pt[i] + vsud * gd_pt[i]) for i in range(3)])
    wd_lm = np.stack([proj_g @ (2.0 * vsdd * gd_pt[i] + vsud * gu_pt[i]) for i in range(3)])
    div_u = to_pt(divergence_lm_real(wu_lm, t.r))
    div_d = to_pt(divergence_lm_real(wd_lm, t.r))
    vu = vu - div_u
    vd = vd - div_d

    rho_pt = up + dn
    eps = np.where(np.abs(rho_pt) > 1e-18, e / np.where(np.abs(rho_pt) > 1e-18, rho_pt, 1.0), 0.0)
    lmmax = rho_lm.shape[1]
    vxc = np.empty((nmag1, lmmax, len(t.r)))
    vxc[0] = (proj_g @ (0.5 * (vu + vd)))[:lmmax]
    if nmag1 == 2:
        vxc[1] = (proj_g @ (0.5 * (vu - vd)))[:lmmax]
    exc_lm = (proj_g @ eps)[:lmmax]
    return vxc, exc_lm


def compute_paw(paw: PawData, dm_flat: np.ndarray, xc):
    """One full PAW update from the (mixed) packed density matrix.

    Returns dict with:
      dij   [nbeta_tot, nbeta_tot] per magn component list (len nmag+1)
      e_hartree, e_xc, e_total (PAW_total_energy), core energies included
    """
    dms = paw.split_dm(dm_flat)
    nmag1 = paw.num_mag + 1
    e_ha = 0.0
    e_xc = 0.0
    dij_atoms = []
    for t, dmp in zip(paw.types, dms):
        ae, ps = onsite_density(t, dmp)
        # potentials per magn component: Hartree only in the scalar channel
        v_ae = np.zeros_like(ae)
        v_ps = np.zeros_like(ps)
        vxc_ae, exc_ae = xc_onsite(t, ae, t.ae_core, xc)
        vxc_ps, exc_ps = xc_onsite(t, ps, t.ps_core, xc)
        v_ae += vxc_ae
        v_ps += vxc_ps
        vha_ae = poisson_onsite(t, ae[0])
        vha_ps = poisson_onsite(t, ps[0])
        v_ae[0] += vha_ae
        v_ps[0] += vha_ps
        e_ha += 0.5 * _inner_lm(t, ae[0], vha_ae) - 0.5 * _inner_lm(
            t, ps[0], vha_ps
        )
        # XC energy difference: valence inner product + core contribution
        e_xc += _inner_lm(t, exc_ae, ae[0]) - _inner_lm(t, exc_ps, ps[0])
        e_xc += float(
            np.sum(
                (exc_ae[0] * t.ae_core - exc_ps[0] * t.ps_core)
                * t.r**2 * t.rw
            ) / Y00
        )
        # Dij: radial integrals x Gaunt (reference calc_PAW_local_Dij)
        q3 = t.q_pair[:, t.l_by_lm3, :]  # [npack_rb, lmmax, nr]
        dij = np.zeros((nmag1, t.nbf, t.nbf))
        # integrals[lm3, packrb, im] = int v_ae*ae_pair - v_ps*(ps_pair+q)
        for im in range(nmag1):
            ints = np.einsum(
                "mr,pr,r->mp", v_ae[im], t.ae_pair, t.rw, optimize=True
            ) - np.einsum(
                "mr,pr,r->mp", v_ps[im], t.ps_pair, t.rw, optimize=True
            ) - np.einsum(
                "mr,pmr,r->mp", v_ps[im], q3, t.rw, optimize=True
            )
            for xi2 in range(t.nbf):
                for xi1 in range(xi2 + 1):
                    i, j = sorted((t.xi_rf[xi1], t.xi_rf[xi2]))
                    prb = j * (j + 1) // 2 + i
                    val = float(
                        t.gaunt[t.xi_lm[xi1], t.xi_lm[xi2]] @ ints[:, prb]
                    )
                    dij[im, xi1, xi2] = val
                    dij[im, xi2, xi1] = val
        dij_atoms.append(dij)
    return {"dij_atoms": dij_atoms, "e_hartree": e_ha, "e_xc": e_xc,
            "e_total": e_ha + e_xc}


def one_elec_energy(paw: PawData, dm_flat: np.ndarray, dij_atoms) -> float:
    """sum_ij dm_ij Dij double-counting term (reference
    calc_PAW_one_elec_energy: packed dm against the full Dij matrix)."""
    e = 0.0
    for t, dmp, dij in zip(paw.types, paw.split_dm(dm_flat), dij_atoms):
        for im in range(dmp.shape[1]):
            for xi2 in range(t.nbf):
                for xi1 in range(t.nbf):
                    a, b = min(xi1, xi2), max(xi1, xi2)
                    e += dmp[b * (b + 1) // 2 + a, im] * dij[im, xi1, xi2]
    return e


def add_dij_to_d(paw: PawData, dij_atoms, d_by_spin: list[np.ndarray]) -> list[np.ndarray]:
    """Add the PAW Dij (magn components) to the per-spin screened D
    matrices: D_up/dn = D +/- Dij_bz (reference adds paw_dij to d_mtrx)."""
    ns = len(d_by_spin)
    out = [d.copy() for d in d_by_spin]
    for ia_idx, (t, off) in enumerate(zip(paw.types, paw.offsets)):
        dij = dij_atoms[ia_idx]
        n = t.nbf
        for ispn in range(ns):
            d = dij[0].copy()
            if paw.num_mag == 1:
                d = d + (dij[1] if ispn == 0 else -dij[1])
            out[ispn][off : off + n, off : off + n] += d
    return out
