"""Non-collinear SCF ground-state driver (num_mag_dims = 3).

Mirrors dft/scf.run_scf for spinor wave functions: one flattened-spinor
band set per k-point ([nb, 2*ngk]), 4-component density (rho, mx, my, mz),
vector B_xc from the locally-diagonal XC projection, and spin-block D/Q
operators. Reference call stack: dft_ground_state.cpp:178-427 with the
num_mag_dims()==3 branches of density.cpp, potential/xc.cpp and
hamiltonian/local_operator.cpp.

Spin-orbit coupling enters only through the (dmat, qmat) spin blocks and
the j-resolved projector transform (ops/so.py); the loop here is agnostic.
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from sirius_tpu.config.schema import Config
from sirius_tpu.context import SimulationContext
from sirius_tpu.dft.density import (
    initial_density_g,
    initial_magnetization_vec_g,
    rho_real_space,
    symmetrize_density_matrix_nc,
    symmetrize_pw,
)
from sirius_tpu.dft.mixer import Mixer, schedule_res_tol
from sirius_tpu.dft.occupation import find_fermi
from sirius_tpu.dft.potential_nc import (
    generate_potential_nc,
    symmetrize_vector_pw,
)
from sirius_tpu.dft.xc import XCFunctional
from sirius_tpu.ops.atomic import atomic_orbitals
from sirius_tpu.ops.augmentation import d_operator, rho_aug_g
from sirius_tpu.ops.spinor import spin_blocks_from_components
from sirius_tpu.parallel.batched import join_cplx, split_cplx
from sirius_tpu.parallel.batched_nc import (
    davidson_kset_nc,
    density_kset_nc,
    density_matrix_kset_nc,
    make_nc_set_params,
)
from sirius_tpu.utils.profiler import counters, profile, reset_timers, timer_report


def _initial_spinors(ctx: SimulationContext) -> np.ndarray:
    """LCAO spinors [nk, nb, 2*ngk]: orbital j fills bands 2j (up) and
    2j+1 (down); the rest are damped-random in both components."""
    nk = ctx.gkvec.num_kpoints
    nb = ctx.num_bands
    ngk = ctx.gkvec.ngk_max
    ao = atomic_orbitals(ctx.unit_cell, ctx.gkvec, ctx.cfg.parameters.gk_cutoff + 1e-9)
    rng = np.random.default_rng(42)
    psi = np.zeros((nk, nb, 2, ngk), dtype=np.complex128)
    nao = ao.shape[1]
    for ik in range(nk):
        j = 0
        for b in range(nb):
            if j < nao:
                psi[ik, b, b % 2] = ao[ik, j]
                if b % 2 == 1:
                    j += 1
            else:
                damp = 1.0 / (1.0 + ctx.gkvec.kinetic()[ik])
                psi[ik, b, :] = (
                    rng.standard_normal((2, ngk))
                    + 1j * rng.standard_normal((2, ngk))
                ) * damp
        psi[ik] *= ctx.gkvec.mask[ik][None, None, :]
    return psi.reshape(nk, nb, 2 * ngk)


def _dm_component_blocks(ctx, dm3):
    """Per-atom aux blocks for the 4 augmentation fields (rho, mz, mx, my)
    from the (uu, dd, ud) spin components (reference density_matrix_aux,
    density.cpp:1784-1811). Each returned matrix is Hermitian so the packed
    symmetric Q contraction in rho_aug_g is exact."""
    uu, dd, ud = dm3
    return {
        "rho": uu + dd,
        "mz": uu - dd,
        "mx": ud + ud.conj().T,
        "my": 1j * (ud - ud.conj().T),
    }


def run_scf_nc(
    cfg: Config,
    base_dir: str = ".",
    ctx: SimulationContext | None = None,
) -> dict:
    t0 = time.time()
    reset_timers()
    p = cfg.parameters
    if ctx is None:
        ctx = SimulationContext.create(cfg, base_dir)
    assert ctx.num_mag_dims == 3
    xc = XCFunctional(p.xc_functionals)
    if xc.is_mgga:
        # evaluate_polarized would silently default tau to zero and the
        # spinor apply has no tau operator
        raise NotImplementedError("mGGA with non-collinear magnetism")
    nk, nb = ctx.gkvec.num_kpoints, ctx.num_bands
    nel = ctx.unit_cell.num_valence_electrons - p.extra_charge
    if nb * ctx.max_occupancy < nel - 1e-12:
        raise ValueError(f"num_bands={nb} cannot hold {nel} electrons (spinor)")
    if cfg.hubbard.local:
        raise NotImplementedError("Hubbard+non-collinear is not implemented yet")
    wf_dtype = jnp.complex64 if p.precision_wf == "fp32" else jnp.complex128
    from sirius_tpu.ops.hamiltonian import real_dtype_of

    so = bool(getattr(p, "so_correction", False))
    so_data = None
    if so:
        from sirius_tpu.ops.so import SpinOrbitData

        so_data = SpinOrbitData.build(ctx)
        if so_data is None:
            raise ValueError(
                "so_correction requested but no species has j-resolved "
                "(relativistic) beta projectors"
            )

    rho_g = initial_density_g(ctx)
    mvec_g = initial_magnetization_vec_g(ctx)
    psi = _initial_spinors(ctx)

    pot = generate_potential_nc(ctx, rho_g, xc, mvec_g)
    mixer = Mixer(
        cfg.mixer, ctx.gvec.glen2, num_components=4, omega=ctx.unit_cell.omega
    )
    ng = ctx.gvec.num_gvec

    do_symmetrize = (
        p.use_symmetry and ctx.symmetry is not None and ctx.symmetry.num_ops > 1
    )
    if ctx.beta.num_beta_total:
        _bre, _bim = split_cplx(np.asarray(ctx.beta.beta_gk))
        beta_dev = (jnp.asarray(_bre), jnp.asarray(_bim))
    else:
        beta_dev = None

    def pack(r, m):
        return np.concatenate([r, m[0], m[1], m[2]])

    def unpack(x):
        return x[:ng], np.stack([x[ng : 2 * ng], x[2 * ng : 3 * ng], x[3 * ng :]])

    x_mix = pack(rho_g, mvec_g)
    evals = np.zeros((nk, nb))
    pr = pi = None
    ps = None  # device param tables, constants reused across iterations
    mu, occ, entropy_sum = 0.0, jnp.zeros((nk, 1, nb)), 0.0
    etot_history, rms_history = [], []
    e_prev, converged, rms, scf_correction = None, False, 0.0, 0.0
    num_iter_done = 0
    itsol = cfg.iterative_solver
    # adaptive band-solve tolerance (reference dft_ground_state.cpp:252-259);
    # see run_scf — a static bar stalls tight decks (test09: density_tol 1e-6
    # with a 1e-6 locked-band noise floor never meets the bar in 100 iters)
    res_tol = itsol.residual_tolerance

    for it in range(p.num_dft_iter):
        # --- spin-block D operator ---
        if ctx.aug is not None:
            d0 = d_operator(ctx.unit_cell, ctx.gvec, ctx.aug, pot.veff_g, ctx.beta)
            db = [
                d_operator(
                    ctx.unit_cell, ctx.gvec, ctx.aug, pot.bvec_g[i], ctx.beta,
                    include_dion=False,
                )
                for i in range(3)
            ]
        else:
            d0 = ctx.beta.dion
            db = [None, None, None]
        if so_data is not None:
            # SO: blocks built from the j-resolved f-coefficients
            # (Eq. 19 PhysRevB.71.115106; non_local_operator.cpp:110-200)
            dmat_blocks = so_data.d_blocks(np.asarray(d0), db)
            qmat_blocks = so_data.q_blocks()
        else:
            dmat_blocks = spin_blocks_from_components(d0, db[2], db[0], db[1])
            qmat_blocks = None
        v0 = float(np.real(pot.veff_g[0]))
        with profile("scf::band_solve"):
            ps = make_nc_set_params(
                ctx, pot.veff_boxes, dmat_blocks, qmat_blocks,
                dtype=wf_dtype, v0=v0, prev=ps,
            )
            rdt = real_dtype_of(wf_dtype)
            if pr is None or pr.dtype != np.dtype(rdt):
                src = psi if psi is not None else join_cplx(pr, pi)
                pr, pi = split_cplx(np.asarray(src), rdt)
            ev, pr, pi, rn = davidson_kset_nc(
                ps, pr, pi,
                num_steps=itsol.num_steps,
                res_tol=res_tol,
            )
            psi = None
            evals = np.asarray(ev, dtype=np.float64)
            from sirius_tpu.solvers.davidson import num_applies

            counters["num_loc_op_applied"] += nk * num_applies(itsol.num_steps, nb)

        # --- occupations (spinor bands: max occupancy 1) ---
        mu, occ, entropy_sum = find_fermi(
            jnp.asarray(evals[:, None, :]),
            jnp.asarray(ctx.kweights),
            nel,
            p.smearing_width,
            kind=p.smearing,
            max_occupancy=1.0,
        )
        occ_np = np.asarray(occ)[:, 0, :]

        # --- 4-component density ---
        occ_w = jnp.asarray(occ_np * ctx.kweights[:, None])
        with profile("scf::density"):
            from sirius_tpu.dft.density import density_from_coarse_acc

            rho4 = np.asarray(density_kset_nc(ps, pr, pi, occ_w))
            # rho4 order: (rho, mz, mx, my) on the coarse box
            fields = density_from_coarse_acc(ctx, rho4)
        rho_new = fields[0]
        mvec_new = np.stack([fields[2], fields[3], fields[1]])  # (mx, my, mz)

        if ctx.aug is not None:
            dm_re, dm_im = density_matrix_kset_nc(
                *beta_dev, pr, pi, occ_w
            )
            dm3 = np.asarray(dm_re) + 1j * np.asarray(dm_im)
            if so_data is not None:
                dm3 = so_data.rotate_dm(dm3)
            if do_symmetrize:
                dm3 = symmetrize_density_matrix_nc(ctx, dm3)
            comp = _dm_component_blocks(ctx, dm3)
            blocks = list(ctx.beta.atom_blocks(ctx.unit_cell))

            def aug(mat):
                bl = [mat[off : off + nbf, off : off + nbf] for _, off, nbf in blocks]
                return rho_aug_g(ctx.unit_cell, ctx.gvec, ctx.aug, bl)

            rho_new = rho_new + aug(comp["rho"])
            mvec_new = mvec_new + np.stack(
                [aug(comp["mx"]), aug(comp["my"]), aug(comp["mz"])]
            )
        if cfg.control.verification >= 1:
            nel_got = float(np.real(rho_new[0]) * ctx.unit_cell.omega)
            if abs(nel_got - nel) > 1e-6 * max(1.0, nel):
                import warnings

                warnings.warn(
                    f"electron count from density {nel_got:.8f} != {nel:.8f}"
                )
        if do_symmetrize:
            rho_new = symmetrize_pw(ctx, rho_new)
            mvec_new = symmetrize_vector_pw(ctx, mvec_new)

        if not np.all(np.isfinite(evals)) or not np.isfinite(
            np.sum(np.abs(rho_new))
        ):
            bad = [
                name
                for name, a in [
                    ("evals", evals),
                    ("rho_new", rho_new),
                    ("mvec_new", mvec_new),
                    ("veff_in", np.asarray(pot.veff_boxes)),
                    ("bvec_in", np.asarray(pot.bvec_g)),
                    ("rho_in", rho_g),
                    ("mvec_in", mvec_g),
                ]
                if not np.all(np.isfinite(np.asarray(a)))
            ]
            raise FloatingPointError(
                f"non-collinear SCF diverged at iteration {it + 1}: "
                f"non-finite {bad}"
            )
        x_new = pack(rho_new, mvec_new)
        rms = mixer.rms(x_mix, x_new)
        x_mix = mixer.mix(x_mix, x_new)
        # use_hartree density bar = Hartree energy of (mixed - new), the
        # reference's convergence metric (dft_ground_state.cpp:251,353)
        eha_res = mixer.residual_hartree_energy(x_mix, x_new)
        dens_metric = (
            eha_res if (mixer.use_hartree and eha_res is not None) else rms
        )
        res_tol = schedule_res_tol(itsol, res_tol, dens_metric, nel,
                                   mixer.use_hartree and eha_res is not None)
        rho_g, mvec_g = unpack(x_mix)

        def _epot(r_out, m_out, p_):
            e = float(np.real(np.vdot(r_out, p_.veff_g))) * ctx.unit_cell.omega
            e += sum(
                float(np.real(np.vdot(m_out[i], p_.bvec_g[i])))
                * ctx.unit_cell.omega
                for i in range(3)
            )
            return e

        e1 = _epot(rho_new, mvec_new, pot)
        with profile("scf::potential"):
            pot = generate_potential_nc(ctx, rho_g, xc, mvec_g)
        scf_correction = (
            _epot(rho_new, mvec_new, pot) - e1 if p.use_scf_correction else 0.0
        )
        eval_sum = float(np.sum(ctx.kweights[:, None] * occ_np * evals))
        e = pot.energies
        e_total = (
            eval_sum - e["vxc"] - e["bxc"] - 0.5 * e["vha"] + e["exc"]
            + ctx.e_ewald + scf_correction
        )
        etot_history.append(e_total + float(entropy_sum))
        rms_history.append(rms)
        num_iter_done = it + 1
        de = abs(e_total - e_prev) if e_prev is not None else np.inf
        e_prev = e_total
        if de < p.energy_tol and dens_metric < p.density_tol:
            converged = True
            break

    # --- final report ---
    if psi is None:
        psi = join_cplx(pr, pi)
    from sirius_tpu.dft.density import atomic_moments_vec

    rho_r = rho_real_space(ctx, rho_g)
    e = pot.energies
    eval_sum = float(np.sum(ctx.kweights[:, None] * np.asarray(occ)[:, 0, :] * evals))
    e_total = (
        eval_sum - e["vxc"] - e["bxc"] - 0.5 * e["vha"] + e["exc"]
        + ctx.e_ewald + scf_correction
    )
    mom_atoms = atomic_moments_vec(ctx, mvec_g)
    # total moment: cell integral of m (G=0 term)
    mom_total = [float(np.real(mvec_g[i][0]) * ctx.unit_cell.omega) for i in range(3)]
    result = {
        "converged": bool(converged),
        "num_scf_iterations": num_iter_done,
        "rho_min": float(rho_r.min()),
        "etot_history": etot_history,
        "rms_history": rms_history,
        "scf_time": time.time() - t0,
        "energy": {
            "total": e_total,
            "free": e_total + float(entropy_sum),
            "eval_sum": eval_sum,
            "kin": eval_sum - e["veff"] - e["bxc"],
            "veff": e["veff"],
            "vha": e["vha"],
            "vxc": e["vxc"],
            "vloc": e["vloc"],
            "exc": e["exc"],
            "bxc": e["bxc"],
            "ewald": ctx.e_ewald,
            "entropy_sum": float(entropy_sum),
            "scf_correction": scf_correction,
        },
        "efermi": float(mu),
        "band_gap": 0.0,
        "magnetisation": {
            "total": mom_total,
            "atoms": [list(map(float, m)) for m in mom_atoms],
        },
        "timers": timer_report(),
    }
    return result
