"""Stress tensor for the PP-PW method.

Reference: src/geometry/stress.cpp — sigma = kin + har + ewald + vloc +
nonloc + us + xc + core (stress.hpp:96-114), symmetrized.

Convention: sigma_ab = (1/Omega) dF/d eps_ab for r -> (1+eps) r at frozen
wave-function PW coefficients and occupations. Under that strain the
reciprocal vectors move as B -> B (1+eps)^{-1}, Miller indices / structure
phases e^{-2 pi i m.x} are invariant, the valence density coefficients
rescale as rho(G) -> rho(G) Omega0/Omega, and atom-attached form-factor
fields carry their 4pi/Omega prefactor.

Implementation: each term's frozen-coefficient energy functional is written
exactly for a strained lattice and differentiated by central differences in
the 6 independent strain components (O(h^2), h = 1e-5). The reference builds
closed-form d/dq radial tables instead (radial_integrals<true>,
beta_projectors_strain_deriv.hpp, sigma_us in stress.cpp) — same
derivative, different evaluation; the whole tensor is validated against
full-SCF strained-lattice finite differences in tests/test_stress.py.

Ultrasoft/PAW augmentation: at frozen density-matrix blocks the
augmentation charge rho_aug(eps, G) is rebuilt from strained Q(G) tables
inside the Hartree/local/XC functionals (the psi part of the density keeps
the pure Omega0/Omega coefficient scaling), which is exactly the
reference's sigma_us term distributed over those functionals. PAW on-site
energies are atom-attached and strain-invariant at frozen dm, so no extra
term appears.
"""

from __future__ import annotations

import numpy as np

from sirius_tpu.context import SimulationContext
from sirius_tpu.core.radial import RadialIntegralTable
from sirius_tpu.dft.ewald import ewald_energy
from sirius_tpu.dft.radial_tables import (
    rho_core_form_factor,
    structure_factors,
    vloc_ff,
)

_H = 1e-5


def _strained(lattice: np.ndarray, eps: np.ndarray) -> np.ndarray:
    return lattice @ (np.eye(3) + eps).T  # rows a_i -> (1+eps) a_i


def _ff_table(ff_fn, t, qmax: float):
    """Dense spline table of a form factor, evaluable at arbitrary q."""
    from scipy.interpolate import CubicSpline

    q = np.linspace(0.0, qmax, max(256, int(qmax * 24)))
    return CubicSpline(q, np.asarray(ff_fn(t, q)))


class StressCalculator:
    """Per-term sigma via central differences of exact strained functionals."""

    def __init__(self, ctx: SimulationContext, xc, h: float = _H):
        self.ctx = ctx
        self.xc = xc
        self.h = h
        uc = ctx.unit_cell
        self.sfact = structure_factors(uc, ctx.gvec)
        qmax_fine = ctx.cfg.parameters.pw_cutoff * 1.05
        qmax_gk = ctx.cfg.parameters.gk_cutoff * 1.05
        self.vloc_tab = [
            _ff_table(
                vloc_ff(ctx.cfg.settings.pseudo_grid_cutoff), t, qmax_fine
            )
            for t in uc.atom_types
        ]
        self.core_tab = [
            _ff_table(rho_core_form_factor, t, qmax_fine) if t.rho_core is not None else None
            for t in uc.atom_types
        ]
        from sirius_tpu.ops.beta import beta_radial_table

        self.beta_tab = [beta_radial_table(t, qmax_gk) for t in uc.atom_types]
        from sirius_tpu.core.radial import RadialIntegralTable

        self.ao_tab = [
            RadialIntegralTable.build(
                t.r, np.stack([w.chi for w in t.atomic_wfs]),
                np.array([w.l for w in t.atomic_wfs]), qmax_gk, m=1,
            ) if t.atomic_wfs else None
            for t in uc.atom_types
        ]
        if ctx.aug is not None:
            from sirius_tpu.ops.augmentation import aug_radial_tables

            self.aug_tabs = [
                aug_radial_tables(t, qmax_fine) if t.augmentation else None
                for t in uc.atom_types
            ]
        else:
            self.aug_tabs = None

    # --- strained geometric tables -------------------------------------
    def _recip(self, eps):
        return 2.0 * np.pi * np.linalg.inv(_strained(self.ctx.unit_cell.lattice, eps)).T

    def _gcart(self, eps):
        return self.ctx.gvec.millers @ self._recip(eps)

    def _gkcart(self, eps):
        b = self._recip(eps)
        mk = self.ctx.gkvec.millers + self.ctx.gkvec.kpoints[:, None, :]
        return (mk @ b) * self.ctx.gkvec.mask[..., None]

    def _omega(self, eps):
        return float(abs(np.linalg.det(_strained(self.ctx.unit_cell.lattice, eps))))

    # --- strained augmentation charge ----------------------------------
    def _rho_aug_eps(self, eps, dm_comp):
        """rho_aug(eps, G) at frozen per-atom dm blocks for one density
        component (charge: dm_up+dm_dn; magnetization: dm_up-dm_dn) — the
        production rho_aug_g assembly against strained Q(G) tables."""
        from sirius_tpu.ops.augmentation import q_pw_at, rho_aug_g

        ctx = self.ctx
        uc = ctx.unit_cell
        gc = self._gcart(eps)
        om = self._omega(eps)
        q_by_type = [
            None
            if at is None
            else q_pw_at(uc.atom_types[it], self.aug_tabs[it], gc, om)
            for it, at in enumerate(ctx.aug.per_type)
        ]
        return rho_aug_g(uc, ctx.gvec, ctx.aug, dm_comp, q_pw_by_type=q_by_type)

    def _density_eps(self, eps):
        """(rho(eps, G), mag(eps, G)): frozen psi-part coefficients scale
        with Omega0/Omega; the augmentation part is rebuilt from strained
        Q(G) at frozen dm. Memoized per strain point (three functionals
        consume the same densities)."""
        key = eps.tobytes()
        hit = self._density_eps_cache.get(key)
        if hit is not None:
            return hit
        scale = self.ctx.unit_cell.omega / self._omega(eps)
        rho = (self._rho_g_ref - self._rho_aug0) * scale + (
            self._rho_aug_eps(eps, self._dm_charge)
            if self._dm_charge is not None
            else 0.0
        )
        mag = None
        if self._mag_g_ref is not None:
            mag = (self._mag_g_ref - self._mag_aug0) * scale + (
                self._rho_aug_eps(eps, self._dm_mag)
                if self._dm_mag is not None
                else 0.0
            )
        self._density_eps_cache[key] = (rho, mag)
        return rho, mag

    # --- frozen-coefficient energy functionals -------------------------
    def e_hartree(self, eps):
        rho, _ = self._density_eps(eps)
        g2 = np.sum(self._gcart(eps) ** 2, axis=1)[1:]
        return 2.0 * np.pi * self._omega(eps) * float(
            np.sum(np.abs(rho[1:]) ** 2 / g2)
        )

    def e_vloc(self, eps):
        rho, _ = self._density_eps(eps)
        glen = np.sqrt(np.sum(self._gcart(eps) ** 2, axis=1))
        acc = 0.0
        for it in range(len(self.ctx.unit_cell.atom_types)):
            ff = self.vloc_tab[it](glen)
            acc += float(np.real(np.vdot(rho, ff * np.conj(self.sfact[it]))))
        return 4.0 * np.pi * acc

    def e_ewald(self, eps):
        uc = self.ctx.unit_cell
        z = np.asarray([uc.atom_types[t].zn for t in uc.type_of_atom])
        return ewald_energy(
            _strained(uc.lattice, eps), uc.positions, z,
            self._gcart(eps), self.ctx.gvec.millers, self.ctx.cfg.parameters.pw_cutoff,
        )

    def e_xc(self, eps):
        """E_xc[rho(eps) + rho_core(eps)]; valence density from
        _density_eps (psi-part scaling + strained augmentation), core
        rebuilt from its strained form factors."""
        import jax.numpy as jnp

        from sirius_tpu.core.fftgrid import g_to_r

        ctx = self.ctx
        om = self._omega(eps)
        glen = np.sqrt(np.sum(self._gcart(eps) ** 2, axis=1))
        core_g = np.zeros(ctx.gvec.num_gvec, dtype=np.complex128)
        for it in range(len(ctx.unit_cell.atom_types)):
            if self.core_tab[it] is not None:
                core_g += self.core_tab[it](glen) * np.conj(self.sfact[it])
        core_g *= 4.0 * np.pi / om
        fidx = jnp.asarray(ctx.gvec.fft_index)
        dims = ctx.gvec.fft.dims

        def to_r(f_g):
            return np.asarray(g_to_r(jnp.asarray(f_g), fidx, dims)).real

        rho_eps_g, mag_eps_g = self._density_eps(eps)
        core_r = to_r(core_g) if np.any(core_g) else 0.0
        rho_r = to_r(rho_eps_g)
        n = rho_r.size

        def sigma_of(total_g):
            """|grad f|^2 on the strained lattice (i G_s f(G))."""
            gc = self._gcart(eps)
            grads = [to_r(1j * gc[:, i] * total_g) for i in range(3)]
            return grads

        if mag_eps_g is None:
            rho = np.maximum(rho_r + core_r, 1e-25)
            if self.xc.is_gga:
                g = sigma_of(rho_eps_g + core_g)
                sig = g[0] ** 2 + g[1] ** 2 + g[2] ** 2
                e = np.asarray(
                    self.xc.evaluate(jnp.asarray(rho.ravel()), jnp.asarray(sig.ravel()))["e"]
                )
            else:
                e = np.asarray(self.xc.evaluate(jnp.asarray(rho.ravel()))["e"])
        else:
            mag_r = to_r(mag_eps_g)
            tot = np.maximum(rho_r + core_r, 1e-25)
            m = np.clip(mag_r, -tot, tot)
            if self.xc.is_gga:
                gu = sigma_of(0.5 * (rho_eps_g + core_g + mag_eps_g))
                gd = sigma_of(0.5 * (rho_eps_g + core_g - mag_eps_g))
                suu = sum(x * x for x in gu)
                sdd = sum(x * x for x in gd)
                sud = sum(a * b for a, b in zip(gu, gd))
                e = np.asarray(
                    self.xc.evaluate_polarized(
                        jnp.asarray(((tot + m) / 2).ravel()),
                        jnp.asarray(((tot - m) / 2).ravel()),
                        jnp.asarray(suu.ravel()), jnp.asarray(sud.ravel()),
                        jnp.asarray(sdd.ravel()),
                    )["e"]
                )
            else:
                e = np.asarray(
                    self.xc.evaluate_polarized(
                        jnp.asarray(((tot + m) / 2).ravel()), jnp.asarray(((tot - m) / 2).ravel())
                    )["e"]
                )
        return float(e.sum()) * om / n

    def _beta_k(self, ik, qlen, rlm, pref):
        """Strained beta-projector table for one k (shared by the nonloc
        and hubbard stress terms — ONE copy of the phase/prefactor
        convention pref * (-i)^l * R_lm * RI(q) * e^{-iG.r})."""
        from sirius_tpu.core.sht import lm_index

        ctx = self.ctx
        uc = ctx.unit_cell
        ngk = int(ctx.gkvec.num_gk[ik])
        beta_k = np.zeros((ctx.beta.num_beta_total, ngk), dtype=np.complex128)
        mk = ctx.gkvec.millers[ik, :ngk] + ctx.gkvec.kpoints[ik][None, :]
        for ia, off, nbf in ctx.beta.atom_blocks(uc):
            t = uc.atom_types[uc.type_of_atom[ia]]
            if not t.num_beta:
                continue
            ri = self.beta_tab[uc.type_of_atom[ia]](qlen[ik, :ngk])
            phase = np.exp(-2j * np.pi * (mk @ uc.positions[ia]))
            idxrf, ls, ms = t.beta_lm_table()
            for xi in range(nbf):
                l, m_, ir = int(ls[xi]), int(ms[xi]), int(idxrf[xi])
                beta_k[off + xi] = (
                    pref * (-1j) ** l * rlm[ik, :ngk, lm_index(l, m_)]
                    * ri[ir] * phase
                )
        return beta_k

    def e_nonloc(self, eps, psi, occ_w, evals, d_by_spin):
        """Non-local energy with strained projector tables; includes the
        -eps <psi|Q|psi> orthogonality term for ultrasoft."""
        from sirius_tpu.core.sht import ylm_real

        ctx = self.ctx
        uc = ctx.unit_cell
        if ctx.beta.num_beta_total == 0:
            return 0.0
        gk = self._gkcart(eps)
        qlen = np.linalg.norm(gk, axis=-1)
        lmax = max(t.lmax_beta for t in uc.atom_types if t.num_beta)
        rhat = np.where(
            qlen[..., None] > 1e-30, gk / np.maximum(qlen, 1e-30)[..., None], np.array([0.0, 0, 1.0])
        )
        rlm = ylm_real(lmax, rhat)
        pref = 4.0 * np.pi / np.sqrt(self._omega(eps))
        qmat = ctx.beta.qmat
        e = 0.0
        nk = ctx.gkvec.num_kpoints
        for ik in range(nk):
            ngk = int(ctx.gkvec.num_gk[ik])
            beta_k = self._beta_k(ik, qlen, rlm, pref)
            for ispn in range(psi.shape[1]):
                ps = np.asarray(psi[ik, ispn])[:, :ngk]
                bp = np.conj(beta_k) @ ps.T  # (nbeta, nb)
                f = occ_w[ik, ispn]
                d = np.einsum("xb,xy,yb->b", np.conj(bp), d_by_spin[ispn], bp).real
                e += float(np.sum(f * d))
                if qmat is not None:
                    o = np.einsum("xb,xy,yb->b", np.conj(bp), qmat, bp).real
                    e -= float(np.sum(f * evals[ik, ispn] * o))
        return e

    def _hub_om_eps(self, eps, psi, occ_w, hub):
        """(om_sym, om_nl) from STRAINED hubbard orbitals at frozen psi —
        the occupancy response the reference computes analytically in
        compute_occupancies_stress_derivatives
        (hubbard_occupancies_derivatives.cpp); here the same derivative is
        taken by central differences of the exact strained occupancy."""
        from sirius_tpu.core.sht import lm_index, ylm_real
        from sirius_tpu.ops.hubbard import (
            nonlocal_from_occ_T,
            symmetrize_occupation,
        )

        ctx = self.ctx
        uc = ctx.unit_cell
        gk = self._gkcart(eps)
        qlen = np.linalg.norm(gk, axis=-1)
        lmax_ao = max(
            (w.l for t in uc.atom_types for w in t.atomic_wfs), default=0
        )
        lmax_b = max(
            (t.lmax_beta for t in uc.atom_types if t.num_beta), default=0
        )
        rhat = np.where(
            qlen[..., None] > 1e-30,
            gk / np.maximum(qlen, 1e-30)[..., None], np.array([0.0, 0, 1.0]),
        )
        rlm = ylm_real(max(lmax_ao, lmax_b), rhat)
        pref = 4.0 * np.pi / np.sqrt(self._omega(eps))
        qmat = ctx.beta.qmat
        nk = ctx.gkvec.num_kpoints
        ns = psi.shape[1]
        nh = hub.num_hub_total
        ao_off = []
        off = 0
        for ia in range(uc.num_atoms):
            ao_off.append(off)
            off += uc.atom_types[uc.type_of_atom[ia]].num_atomic_wf_lm
        nao = off
        om = np.zeros((ns, nh, nh), dtype=np.complex128)
        occ_T = {t: np.zeros((ns, nh, nh), dtype=np.complex128) for t in hub.trans}
        for ik in range(nk):
            ngk = int(ctx.gkvec.num_gk[ik])
            mk = ctx.gkvec.millers[ik, :ngk] + ctx.gkvec.kpoints[ik][None, :]
            # strained atomic orbitals, whole cell
            phi = np.zeros((nao, ngk), dtype=np.complex128)
            for ia in range(uc.num_atoms):
                it = uc.type_of_atom[ia]
                t = uc.atom_types[it]
                if not t.atomic_wfs:
                    continue
                ri = self.ao_tab[it](qlen[ik, :ngk])
                phase = np.exp(-2j * np.pi * (mk @ uc.positions[ia]))
                xi = 0
                for iw, w in enumerate(t.atomic_wfs):
                    for m in range(-w.l, w.l + 1):
                        phi[ao_off[ia] + xi] = (
                            pref * (-1j) ** w.l
                            * rlm[ik, :ngk, lm_index(w.l, m)]
                            * ri[iw] * phase
                        )
                        xi += 1
            # strained beta for S phi (shared helper with e_nonloc)
            if qmat is not None and ctx.beta.num_beta_total:
                beta_k = self._beta_k(ik, qlen, rlm, pref)

                def s_apply(p):
                    bp = np.conj(beta_k) @ p.T
                    return p + (beta_k.T @ (qmat @ bp)).T
            else:
                s_apply = lambda p: p
            if hub.full_ortho:
                sphi = s_apply(phi)
                o = np.conj(phi) @ sphi.T
                s, u = np.linalg.eigh(0.5 * (o + o.conj().T))
                s = np.maximum(s, 1e-12)
                binv = (u * (1.0 / np.sqrt(s))[None, :]) @ u.conj().T
                phi = binv.T @ phi
            sphi = s_apply(phi)
            # block rows -> hubbard ordering
            phi_s = np.zeros((nh, ngk), dtype=np.complex128)
            for b in hub.blocks:
                t = uc.atom_types[uc.type_of_atom[b.ia]]
                src = ao_off[b.ia] + sum(
                    2 * t.atomic_wfs[i].l + 1 for i in range(b.iw)
                )
                phi_s[b.off : b.off + b.nm] = sphi[src : src + b.nm]
            k = ctx.gkvec.kpoints[ik]
            for ispn in range(ns):
                hp = np.conj(phi_s) @ np.asarray(psi[ik, ispn])[:, :ngk].T
                f = occ_w[ik, ispn] / ctx.max_occupancy
                o_k = np.einsum("mb,b,nb->mn", hp, f, np.conj(hp))
                om[ispn] += o_k
                for t_, acc in occ_T.items():
                    acc[ispn] += o_k * np.exp(
                        -2j * np.pi * float(np.dot(k, t_))
                    )
        if ctx.symmetry is not None and ctx.symmetry.num_ops > 1 and hub.sym_maps:
            om, om_nl = symmetrize_occupation(ctx, hub, om, occ_T)
        else:
            om_nl = nonlocal_from_occ_T(hub, occ_T) if hub.nonloc else []
        return om, om_nl

    def e_hubbard(self, eps, psi, occ_w, hub, um_local, um_nl):
        """Re_sum V_frozen . om(eps): its strain derivative is the
        reference's sigma_hub = sum V . dn/deps (stress.cpp:152-190)."""
        om, om_nl = self._hub_om_eps(eps, psi, occ_w, hub)
        e = sum(
            float(np.real(np.sum(om[ispn] * np.conj(um_local[ispn]))))
            for ispn in range(om.shape[0])
        )
        e += sum(
            float(np.real(np.sum(o * np.conj(u))))
            for o, u in zip(om_nl, um_nl)
        )
        return self.ctx.max_occupancy * e

    # --- assembly -------------------------------------------------------
    def compute(
        self, rho_g, mag_g, rho_r, mag_r, psi, occ, evals, d_by_spin,
        dm_blocks_by_spin=None, hub=None,
    ) -> dict:
        """dm_blocks_by_spin: per-spin list of per-atom density-matrix
        blocks (required for the augmentation stress of US/PAW species).
        hub: HubbardData — adds the sigma_hub term (reference
        calc_stress_hubbard)."""
        ctx = self.ctx
        self._rho_g_ref = rho_g
        self._mag_g_ref = mag_g
        self._dm_charge = self._dm_mag = None
        self._rho_aug0 = 0.0
        self._mag_aug0 = 0.0
        self._density_eps_cache = {}
        if ctx.aug is not None and dm_blocks_by_spin:
            ns_dm = len(dm_blocks_by_spin)
            natoms = len(dm_blocks_by_spin[0])
            self._dm_charge = [
                sum(dm_blocks_by_spin[s][ia] for s in range(ns_dm))
                for ia in range(natoms)
            ]
            self._rho_aug0 = self._rho_aug_eps(np.zeros((3, 3)), self._dm_charge)
            if mag_g is not None and ns_dm == 2:
                self._dm_mag = [
                    dm_blocks_by_spin[0][ia] - dm_blocks_by_spin[1][ia]
                    for ia in range(natoms)
                ]
                self._mag_aug0 = self._rho_aug_eps(np.zeros((3, 3)), self._dm_mag)
        occ_w = occ * ctx.gkvec.weights[:, None, None]
        terms = {
            "har": lambda e: self.e_hartree(e),
            "vloc": lambda e: self.e_vloc(e),
            "ewald": lambda e: self.e_ewald(e),
            "xc": lambda e: self.e_xc(e),
            "nonloc": lambda e: self.e_nonloc(e, psi, occ_w, evals, d_by_spin),
        }
        if hub is not None:
            from sirius_tpu.ops.hubbard import hubbard_potential_and_energy

            om0, om_nl0 = self._hub_om_eps(np.zeros((3, 3)), psi, occ_w, hub)
            um_local, um_nl, _, _ = hubbard_potential_and_energy(
                hub, om0, ctx.max_occupancy, om_nl=om_nl0,
            )
            terms["hubbard"] = lambda e: self.e_hubbard(
                e, psi, occ_w, hub, um_local, um_nl
            )
        out = {"kin": self.sigma_kinetic(psi, occ_w)}
        om = ctx.unit_cell.omega
        h = self.h
        for name, fn in terms.items():
            s = np.zeros((3, 3))
            for a in range(3):
                for b in range(a, 3):
                    eps = np.zeros((3, 3))
                    eps[a, b] += h
                    eps[b, a] += h
                    de = (fn(eps) - fn(-eps)) / (2 * h)
                    # symmetric-strain derivative gives sigma_ab + sigma_ba
                    s[a, b] = s[b, a] = de / 2.0
            out[name] = s / om
        total = sum(out.values())
        out["total"] = symmetrize_stress(ctx, total)
        return out

    def sigma_kinetic(self, psi, occ_w) -> np.ndarray:
        """CLOSED-FORM kinetic stress (reference stress.cpp sigma_kin):
        under r -> (1+eps) r at frozen coefficients, gk -> (1+eps)^{-T} gk,
        so d(1/2 |gk|^2)/d eps_ab = -gk_a gk_b and

          sigma_kin_ab = -(1/Omega) sum_{k,s,b,G} w f |psi(G)|^2 gk_a gk_b

        — exact, replacing 12 finite-difference evaluations of the most
        expensive strained functional (VERDICT r3 item 10)."""
        ctx = self.ctx
        s = np.zeros((3, 3))
        gk0 = np.asarray(ctx.gkvec.gkcart)
        for ik in range(ctx.gkvec.num_kpoints):
            dens = np.zeros(gk0.shape[1])
            for ispn in range(psi.shape[1]):
                dens += np.einsum(
                    "b,bg->g", occ_w[ik, ispn],
                    np.abs(np.asarray(psi[ik, ispn])) ** 2,
                )
            s -= np.einsum("g,ga,gb->ab", dens, gk0[ik], gk0[ik])
        return 0.5 * (s + s.T) / ctx.unit_cell.omega


def symmetrize_stress(ctx: SimulationContext, s: np.ndarray) -> np.ndarray:
    if ctx.symmetry is None or ctx.symmetry.num_ops <= 1:
        return 0.5 * (s + s.T)
    out = np.zeros((3, 3))
    for op in ctx.symmetry.ops:
        out += op.rot_cart @ s @ op.rot_cart.T
    out /= ctx.symmetry.num_ops
    return 0.5 * (out + out.T)
