"""SCF supervision & recovery: divergence sentinels and a backoff ladder.

The reference answers SCF divergence with restartable ground states and
"robust" direct-minimization solvers; long device-resident TPU loops add
preemption and silent NaN propagation on top (PAPERS.md: the TPU DFT and
quantum-chemistry papers both treat numerical-failure handling and
checkpoint/restart as prerequisites for multi-hour runs). Previously
run_scf raised a bare FloatingPointError at three sites (non-finite fused
scalars, non-finite eigen/mixed vectors, non-finite potential) and lost
the whole run.

ScfSupervisor turns those sites into a bounded retry loop:

  sentinel fires (non-finite field, energy blow-up, RMS growing for K
  consecutive iterations, or — earlier — the forecast early-warning score
  of obs/forecast.py staying high while the residual climbs an order of
  magnitude)
    -> roll back to the last finite (x_mix, energy) snapshot
    -> escalate one rung of the backoff ladder:
         rung 0: flush Anderson/Broyden history (a poisoned history is the
                 most common divergence amplifier)
         rung 1: flush + halve beta and fall back to linear mixing
         rung 2: disable the fused device path for the remaining
                 iterations (host path re-checks every field per iteration
                 and runs the band solve under supervision)
         rung 3+ (or recovery budget exhausted): abort with ScfAbortError
                 carrying a structured diagnostic (sentinel, iteration,
                 last-good energies, ladder history)

run_scf owns the actual state mutation (restoring x_mix, rebuilding the
potential and the fused program); the supervisor owns detection, the
snapshot payload, escalation bookkeeping, and the diagnostic dump.

Device OOM rides a SEPARATE ladder (``OOM_LADDER``): an HBM
RESOURCE_EXHAUSTED (classified by utils/devfail.py, injected by the
``device.oom`` fault site) means the memory plan is wrong, not the
physics — so instead of flushing mixer history the rungs shrink the
memory footprint, each journaled/metered like a divergence rung and
resumed from the last snapshot rather than restarting:

  rung 0: shrink beta_chunk_budget_bytes (and halve beta_chunk_size) so
          the chunked-projector path engages, or engages with smaller
          chunks; repeatable while the chunks can still halve
  rung 1: force the chunked beta path outright (when the deck is
          eligible: single k, ns=1, no Hubbard/PAW/mGGA)
  rung 2: disable device_scf — host fallback, smallest resident footprint
  rung 3+ (or recovery budget exhausted, or no applicable rung): abort —
          the serving layer then retries the job with the same rungs
          pre-applied via devfail.apply_oom_hint

Inapplicable rungs are skipped (a host-path run has no device_scf to
disable; a multi-k deck cannot chunk): the ladder escalates to the first
rung that actually changes the memory plan.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np

from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs.forecast import ConvergenceForecaster

_RECOVERIES = obs_metrics.REGISTRY.counter(
    "scf_recoveries_total", "recovery-ladder rungs taken, by action")
_ABORTS = obs_metrics.REGISTRY.counter(
    "scf_aborts_total", "runs lost past the recovery ladder")

# ladder rung -> human-readable action (diagnostic / log strings)
LADDER = (
    "flush_history",
    "halve_beta_linear",
    "disable_device_scf",
    "abort",
)

# the device-OOM degradation ladder (sentinel "device_oom"): memory-plan
# rungs, not numerics rungs — see the module docstring
OOM_LADDER = (
    "shrink_beta_budget",
    "force_beta_chunked",
    "disable_device_scf",
    "abort",
)


class ScfAbortError(FloatingPointError):
    """SCF diverged beyond the recovery ladder. Subclasses
    FloatingPointError so callers of the previous fatal behaviour keep
    catching it; .diagnostic holds the structured dump."""

    def __init__(self, message: str, diagnostic: dict):
        super().__init__(message)
        self.diagnostic = diagnostic


@dataclasses.dataclass
class RecoveryDirective:
    """What run_scf must do after a rollback, one ladder escalation."""

    rung: int
    flush_history: bool = False
    beta: float | None = None  # new mixer beta (None = keep)
    kind: str | None = None  # new mixer kind (None = keep)
    disable_device: bool = False
    # OOM-ladder rungs (sentinel "device_oom"): shrink the chunked-beta
    # engagement budget / halve the chunk size, or force the chunked path
    shrink_beta_budget: bool = False
    force_beta_chunked: bool = False


class ScfSupervisor:
    """Watches per-iteration scalars, keeps the last finite snapshot, and
    hands out ladder directives when a sentinel fires."""

    def __init__(self, control, mixer_beta: float, mixer_kind: str,
                 deck_label: str = "", density_tol: float | None = None):
        self.enabled = bool(getattr(control, "scf_supervision", True))
        self.max_recoveries = int(getattr(control, "max_recoveries", 3))
        self.rms_divergence_iters = int(
            getattr(control, "rms_divergence_iters", 8))
        self.energy_blowup_tol = float(
            getattr(control, "energy_blowup_tol", 1e4))
        self.diag_dump = str(getattr(control, "diag_dump", ""))
        # convergence analytics (obs/forecast.py): early-warning score +
        # iterations-to-converge forecast, fed the same observe() scalars
        self.forecast_enabled = bool(
            getattr(control, "forecast_enabled", True))
        self.forecast_warning_threshold = float(
            getattr(control, "forecast_warning_threshold", 0.5))
        self.forecast_backoff_ratio = float(
            getattr(control, "forecast_backoff_ratio", 10.0))
        # the forecast sentinel acts EARLIER than rms_divergence (half the
        # streak) but never instantly: a floor of 3 keeps one bad Anderson
        # step from costing a rollback
        self.forecast_backoff_iters = (
            int(getattr(control, "forecast_backoff_iters", 0))
            or max(3, self.rms_divergence_iters // 2))
        self.forecaster = ConvergenceForecaster(
            density_tol if density_tol is not None else 0.0)
        self._fc_streak = 0
        self._fc_start_rms: float | None = None
        self._fc_snap: dict | None = None
        self.deck_label = deck_label
        self.beta0 = float(mixer_beta)
        self.kind0 = str(mixer_kind)
        self.rung = 0
        self.oom_rung = 0  # separate pointer into OOM_LADDER
        self.recoveries = 0
        self.history: list[dict] = []  # one entry per recovery event
        # rollback payload: dict set by run_scf via snapshot()
        self._snap: dict | None = None
        self._rms_streak = 0
        self._streak_start_rms = None
        self._e_prev = None
        self._etot_tail: list[float] = []
        self._rms_tail: list[float] = []

    # -- snapshot ---------------------------------------------------------

    def snapshot(self, it: int, payload: dict) -> None:
        """Record the last-known-finite state. `payload` must contain
        everything run_scf needs to roll back (at minimum a host copy of
        the packed mixed vector under 'x_mix'); ownership transfers here —
        pass copies."""
        self._snap = {"it": it, **payload}

    @property
    def has_snapshot(self) -> bool:
        return self._snap is not None

    @property
    def snap(self) -> dict:
        if self._snap is None:
            raise RuntimeError("no snapshot recorded")
        return self._snap

    # -- sentinels --------------------------------------------------------

    def observe(self, it: int, rms: float, e_total: float) -> str | None:
        """Feed one finished iteration's scalars; returns the sentinel name
        if a soft-divergence condition fired, else None. (Hard non-finite
        sentinels are reported directly via recover().)"""
        self._etot_tail = (self._etot_tail + [float(e_total)])[-8:]
        self._rms_tail = (self._rms_tail + [float(rms)])[-8:]
        self._fc_snap = self.forecaster.update(it, rms, e_total)
        if not self.enabled:
            self._e_prev = e_total
            return None
        if self._e_prev is not None and np.isfinite(e_total) and np.isfinite(
                self._e_prev):
            if abs(e_total - self._e_prev) > self.energy_blowup_tol:
                self._e_prev = e_total
                return "energy_blowup"
        self._e_prev = e_total
        # RMS divergence: K consecutive growing iterations AND an order of
        # magnitude above where the streak started (plain non-monotone
        # Anderson steps must not trip it)
        if self._rms_tail[:-1] and rms > self._rms_tail[-2]:
            if self._rms_streak == 0:
                self._streak_start_rms = self._rms_tail[-2]
            self._rms_streak += 1
        else:
            self._rms_streak = 0
            self._streak_start_rms = None
        if (self._rms_streak >= self.rms_divergence_iters
                and self._streak_start_rms is not None
                and rms > 10.0 * max(self._streak_start_rms, 1e-300)):
            self._rms_streak = 0
            self._streak_start_rms = None
            return "rms_divergence"
        # forecast early warning (obs/forecast.py): backoff BEFORE the
        # non-finite/rms sentinels can trip. A separate streak from the
        # rms sentinel above: that one counts monotone growth, this one
        # counts sustained high warning scores — sharing state would
        # change the rms sentinel's firing pattern. The 10x-above-streak-
        # start guard keeps the mandatory early-run warnings (score 1.0
        # until the forecaster has min_history samples) from ever costing
        # a rollback on a healthy trajectory.
        if self.forecast_enabled:
            if self._fc_snap["warning"] >= self.forecast_warning_threshold:
                if self._fc_streak == 0:
                    self._fc_start_rms = float(rms)
                self._fc_streak += 1
            else:
                self._fc_streak = 0
                self._fc_start_rms = None
            if (self._fc_streak >= self.forecast_backoff_iters
                    and self._fc_start_rms is not None
                    and np.isfinite(rms)
                    and rms > self.forecast_backoff_ratio
                    * max(self._fc_start_rms, 1e-300)):
                self._fc_streak = 0
                self._fc_start_rms = None
                return "forecast_divergence"
        return None

    def should_snapshot(self) -> bool:
        """Proactive-snapshot trigger: True while the early-warning score
        is at or above the threshold (including the first iterations,
        where no contraction evidence exists yet). run_scf ORs this into
        its fused-path snapshot cadence so a rollback after an early fault
        lands on the newest trusted iterate instead of one up to
        snapshot_every iterations stale."""
        return (self.enabled and self.forecast_enabled
                and self._fc_snap is not None
                and self._fc_snap["warning"]
                >= self.forecast_warning_threshold)

    def forecast_snapshot(self) -> dict | None:
        """The forecaster's view after the last observe() (obs/forecast.py
        snapshot dict); None before the first iteration."""
        return self._fc_snap

    def inject_warning(self, score: float = 1.0) -> None:
        """Force the last forecast snapshot's early-warning score (fault
        site scf.forecast_misfire): exercises the proactive-snapshot and
        deadline-infeasibility consumers without a real divergence. The
        remaining-iterations forecast is dropped alongside — a run that
        warrants maximum warning has no credible convergence estimate."""
        if self._fc_snap is None:
            self._fc_snap = self.forecaster.snapshot()
        self._fc_snap = dict(
            self._fc_snap, warning=float(score),
            forecast_remaining=None, forecast_total=None,
        )

    def reset_trend(self) -> None:
        """Clear soft-sentinel trend state after a rollback (the restored
        iterate restarts the energy/rms trajectory — the poisoned tail
        must not contaminate the post-rollback decay fit either)."""
        self._rms_streak = 0
        self._streak_start_rms = None
        self._e_prev = None
        self._fc_streak = 0
        self._fc_start_rms = None
        self._fc_snap = None
        self.forecaster.reset()

    # -- recovery ---------------------------------------------------------

    def recover(self, sentinel: str, it: int, detail: str = "",
                state: dict | None = None) -> RecoveryDirective:
        """A sentinel fired at iteration `it`. Escalate one ladder rung and
        return the directive; raises ScfAbortError when the ladder (or the
        recovery budget, or the absence of any snapshot) is exhausted.

        The "device_oom" sentinel routes to the OOM degradation ladder
        (`state` must then carry the memory-plan flags — see
        _recover_oom); every other sentinel takes the divergence ladder.
        """
        if sentinel == "device_oom":
            return self._recover_oom(it, detail, state)
        if (not self.enabled or self._snap is None
                or self.recoveries >= self.max_recoveries
                or self.rung >= len(LADDER) - 1):
            raise self._abort(sentinel, it, detail, state)
        rung = self.rung
        action = LADDER[rung]
        self.rung += 1
        self.recoveries += 1
        self.history.append({
            "iteration": it,
            "sentinel": sentinel,
            "detail": detail,
            "rung": rung,
            "action": action,
            "rolled_back_to": self._snap["it"],
        })
        _RECOVERIES.inc(sentinel=sentinel, action=action)
        obs_events.emit("recovery", **self.history[-1])
        d = RecoveryDirective(rung=rung, flush_history=True)
        if rung >= 1:
            d.beta = 0.5 * self.beta0
            d.kind = "linear"
        if rung >= 2:
            d.disable_device = True
        self.reset_trend()
        return d

    def _recover_oom(self, it: int, detail: str,
                     state: dict | None) -> RecoveryDirective:
        """Device OOM at iteration `it`: escalate to the first OOM-ladder
        rung that actually changes the memory plan, given the run's
        current path flags in `state`:

          beta_chunk_eligible  the chunked projector path can engage
                               (single k, ns=1, no Hubbard/PAW/mGGA, not
                               explicitly disabled)
          beta_chunked         the chunked path is already active
          beta_chunk_can_halve beta_chunk_size is still above the floor
          device_scf           the fused device path is active

        Rung 0 is repeatable while the chunks can still halve (a fully
        host-side, already-chunked run has no rung 1/2 left to take)."""
        st = state or {}
        eligible = bool(st.get("beta_chunk_eligible"))
        active = bool(st.get("beta_chunked"))
        can_halve = bool(st.get("beta_chunk_can_halve", True))
        device = bool(st.get("device_scf"))
        can_shrink = (eligible and not active) or (active and can_halve)
        choice = None
        for r in range(self.oom_rung, len(OOM_LADDER) - 1):
            a = OOM_LADDER[r]
            if a == "shrink_beta_budget" and can_shrink:
                choice = r
                break
            if a == "force_beta_chunked" and eligible and not active:
                choice = r
                break
            if a == "disable_device_scf" and device:
                choice = r
                break
        if choice is None and can_shrink:
            choice = 0  # fully degraded path: keep halving the chunks
        if (choice is None or not self.enabled or self._snap is None
                or self.recoveries >= self.max_recoveries):
            raise self._abort("device_oom", it, detail, state)
        action = OOM_LADDER[choice]
        self.oom_rung = max(self.oom_rung, choice + 1)
        self.recoveries += 1
        self.history.append({
            "iteration": it,
            "sentinel": "device_oom",
            "detail": detail,
            "ladder": "oom",
            "rung": choice,
            "action": action,
            "rolled_back_to": self._snap["it"],
        })
        _RECOVERIES.inc(sentinel="device_oom", action=action)
        obs_events.emit("recovery", **self.history[-1])
        d = RecoveryDirective(rung=choice)
        if action == "shrink_beta_budget":
            d.shrink_beta_budget = True
        elif action == "force_beta_chunked":
            d.force_beta_chunked = True
        elif action == "disable_device_scf":
            d.disable_device = True
        self.reset_trend()
        return d

    def _abort(self, sentinel: str, it: int, detail: str,
               state: dict | None) -> ScfAbortError:
        diag = self.diagnostic(sentinel, it, detail, state)
        _ABORTS.inc(sentinel=sentinel)
        obs_events.emit("recovery", iteration=it, sentinel=sentinel,
                        detail=detail, rung=self.rung, action="abort",
                        rolled_back_to=diag["last_good_iteration"])
        if self.diag_dump:
            try:
                with open(self.diag_dump, "w") as f:
                    json.dump(diag, f, indent=2, default=str)
            except OSError:
                pass
        last_good = self._snap["it"] if self._snap is not None else None
        return ScfAbortError(
            f"SCF aborted at iteration {it}: sentinel '{sentinel}' fired "
            f"after {self.recoveries} recoveries "
            f"(last good iteration: {last_good})"
            + (f"; {detail}" if detail else ""),
            diag,
        )

    def diagnostic(self, sentinel: str, it: int, detail: str = "",
                   state: dict | None = None) -> dict:
        diag = {
            "sentinel": sentinel,
            "iteration": it,
            "deck": self.deck_label,
            "recoveries": self.recoveries,
            "rung": self.rung,
            "oom_rung": self.oom_rung,
            "ladder_history": list(self.history),
            "etot_tail": list(self._etot_tail),
            "rms_tail": list(self._rms_tail),
            "last_good_iteration": (
                self._snap["it"] if self._snap is not None else None),
            "last_good_energy": (
                self._snap.get("e_total") if self._snap is not None
                else None),
            "mixer_beta0": self.beta0,
            "mixer_kind0": self.kind0,
            "detail": detail,
            "forecast": self._fc_snap,
        }
        if state:
            diag.update(state)
        return diag
