"""Atomic forces for the PP-PW method.

Reference: src/geometry/force.cpp — total = vloc + ewald + core (NLCC) +
nonloc + us (augmentation) + usnl + scf_corr + hubbard contributions
(force.hpp:44-66), symmetrized over the space group.

All G-space sums are host-side numpy einsums over precomputed tables; the
k-dependent non-local part reuses the device beta tables with one extra
einsum per Cartesian direction (the reference generates separate gradient
beta projectors, beta_projectors_gradient.hpp — here the gradient is just
the analytic -i(G+k) factor).

Conventions: forces in Ha/bohr, Cartesian, one row per atom.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np
from scipy.special import erfc

from sirius_tpu.context import SimulationContext
from sirius_tpu.dft.ewald import ewald_lambda
from sirius_tpu.dft.radial_tables import rho_core_form_factor, vloc_ff


def _form_factor_force(
    ctx: SimulationContext, field_g: np.ndarray, ff_fn, skip=lambda t: False
) -> np.ndarray:
    """Shared shell-form-factor force kernel:
    F_a = Re sum_G 4 pi conj(field(G)) ff_a(|G|) iG e^{-i G r_a}."""
    uc = ctx.unit_cell
    out = np.zeros((uc.num_atoms, 3))
    qshell = np.sqrt(ctx.gvec.shell_g2)
    for it, t in enumerate(uc.atom_types):
        if skip(t):
            continue
        ff = np.asarray(ff_fn(t, qshell))[ctx.gvec.shell_idx]
        for ia in uc.atoms_of_type(it):
            phase = np.exp(-2j * np.pi * (ctx.gvec.millers @ uc.positions[ia]))
            w = 4.0 * np.pi * np.conj(field_g) * ff * phase
            out[ia] = np.real(1j * (w[:, None] * ctx.gvec.gcart).sum(axis=0))
    return out


def forces_vloc(ctx: SimulationContext, rho_g: np.ndarray) -> np.ndarray:
    """Local-potential force (reference force.cpp calc_forces_vloc)."""
    return _form_factor_force(ctx, rho_g, vloc_ff(ctx.cfg.settings.pseudo_grid_cutoff))


def forces_core(ctx: SimulationContext, vxc_g: np.ndarray) -> np.ndarray:
    """NLCC force: core density against V_xc (reference calc_forces_core)."""
    return _form_factor_force(
        ctx, vxc_g, rho_core_form_factor, skip=lambda t: t.rho_core is None
    )


def forces_scf_corr(ctx: SimulationContext, rho_resid_g: np.ndarray) -> np.ndarray:
    """First-order correction for incomplete SCF: the local-potential force
    of the density residual rho_out - rho_in (reference calc_forces_scf_corr);
    vanishes at convergence."""
    return _form_factor_force(
        ctx, rho_resid_g, vloc_ff(ctx.cfg.settings.pseudo_grid_cutoff)
    )


def forces_ewald(ctx: SimulationContext) -> np.ndarray:
    """Point-ion Ewald forces (reference calc_forces_ewald)."""
    uc = ctx.unit_cell
    gv = ctx.gvec
    omega = uc.omega
    z = np.asarray([uc.atom_types[t].zn for t in uc.type_of_atom])
    lam = ewald_lambda(ctx.cfg.parameters.pw_cutoff, omega)
    natom = uc.num_atoms
    out = np.zeros((natom, 3))
    # G-space: F_a = (4 pi / Omega) z_a sum_G!=0 G e^{-G^2/4lam}/G^2
    #                Im[e^{-i G r_a} S(G)]
    g2 = gv.glen2[1:]
    phases = np.exp(2j * np.pi * (gv.millers[1:] @ uc.positions.T))  # (ng, na)
    s = phases @ z
    w = np.exp(-g2 / (4 * lam)) / g2
    for ia in range(natom):
        # F_a = (4 pi/Omega) z_a sum_G w G Im[e^{iG r_a} conj(S)]
        t = np.imag(phases[:, ia] * np.conj(s)) * w
        out[ia] = (4.0 * np.pi / omega) * z[ia] * (t[:, None] * gv.gcart[1:]).sum(axis=0)
    # real-space
    rc = 10.0 / np.sqrt(lam)
    inv = np.linalg.inv(uc.lattice)
    nmax = np.ceil(rc * np.linalg.norm(inv, axis=0)).astype(int) + 1
    ts = np.array(
        np.meshgrid(*[np.arange(-n, n + 1) for n in nmax], indexing="ij")
    ).reshape(3, -1).T
    tcart = ts @ uc.lattice
    pos = uc.positions_cart()
    d = pos[:, None, None, :] - pos[None, :, None, :] - tcart[None, None, :, :]
    dist = np.linalg.norm(d, axis=-1)
    mask = (dist > 1e-10) & (dist < rc)
    a = np.sqrt(lam)
    with np.errstate(divide="ignore", invalid="ignore"):
        scal = np.where(
            mask,
            (erfc(a * dist) / dist + 2 * a / np.sqrt(np.pi) * np.exp(-lam * dist**2))
            / np.where(mask, dist**2, 1.0),
            0.0,
        )
    zz = z[:, None, None] * z[None, :, None]
    out += np.einsum("abt,abti->ai", zz * scal, d)
    return out


def forces_nonloc(
    ctx: SimulationContext,
    psi,  # [nk, ns, nb, ngk] jnp
    occ: np.ndarray,  # [nk, ns, nb]
    evals: np.ndarray,  # [nk, ns, nb]
    d_by_spin: list[np.ndarray],
) -> np.ndarray:
    """Beta-projector force: F_a,i = -2 Re sum_{k,s,b} w f
    conj(<d_i beta|psi>) (D - eps Q) <beta|psi> summed over a's projectors;
    d_i beta = -i (G+k)_i beta (reference non_local_functor.hpp)."""
    uc = ctx.unit_cell
    nbeta = ctx.beta.num_beta_total
    out = np.zeros((uc.num_atoms, 3))
    if nbeta == 0:
        return out
    qmat = ctx.beta.qmat
    for ik in range(ctx.gkvec.num_kpoints):
        beta = jnp.asarray(ctx.beta.beta_gk[ik])  # (nbeta, ngk)
        gk = jnp.asarray(ctx.gkvec.gkcart[ik])  # (ngk, 3)
        for ispn in range(psi.shape[1]):
            ps = psi[ik, ispn]  # (nb, ngk)
            bp = np.asarray(jnp.einsum("xg,bg->bx", jnp.conj(beta), ps))
            bpg = np.asarray(
                jnp.einsum("xg,gi,bg->bxi", jnp.conj(beta), gk, ps)
            )  # <beta| (G+k)_i |psi> -> conj(<d beta|psi>) = -i ...
            f = occ[ik, ispn] * ctx.gkvec.weights[ik]
            eps = evals[ik, ispn]
            dmat = d_by_spin[ispn]
            for b in range(ps.shape[0]):
                if abs(f[b]) < 1e-14:
                    continue
                eff = dmat - (eps[b] * qmat if qmat is not None else 0.0)
                # conj(<d_i beta|psi>) = conj(i <beta (G+k)_i | psi>)...
                # d_i beta = -i (G+k)_i beta => <d_i beta|psi> = i (G+k)_i-weighted
                dbp = 1j * bpg[b]  # (nbeta, 3)
                contrib = 2.0 * np.real(
                    np.einsum("xi,xy,y->xi", np.conj(dbp), eff, bp[b])
                )
                for ia, off, nbf in ctx.beta.atom_blocks(uc):
                    out[ia] -= f[b] * contrib[off : off + nbf].sum(axis=0)
    return out


def forces_us(
    ctx: SimulationContext,
    veff_g: np.ndarray,
    bz_g: np.ndarray | None,
    dm_blocks_by_spin: list,
) -> np.ndarray:
    """Augmentation force: the Q(G) charge moving with the atom against the
    effective potential (reference calc_forces_us):
    F_a = -Omega Re sum_G conj(V^s(G)) n^a Q(G) (-iG) e^{-i G r_a}."""
    uc = ctx.unit_cell
    out = np.zeros((uc.num_atoms, 3))
    if ctx.aug is None:
        return out
    ns = len(dm_blocks_by_spin)
    for ispn in range(ns):
        vs = veff_g if bz_g is None else (veff_g + bz_g if ispn == 0 else veff_g - bz_g)
        for it, at in enumerate(ctx.aug.per_type):
            if at is None:
                continue
            w2 = np.where(at.xi1 == at.xi2, 1.0, 2.0)
            for ia in uc.atoms_of_type(it):
                dmp = w2 * np.real(dm_blocks_by_spin[ispn][ia][at.xi1, at.xi2])
                phase = np.exp(-2j * np.pi * (ctx.gvec.millers @ uc.positions[ia]))
                qn = dmp @ at.q_pw  # (ng,)
                w = uc.omega * np.conj(vs) * qn * phase
                out[ia] += np.real(1j * (w[:, None] * ctx.gvec.gcart).sum(axis=0))
    return out


def symmetrize_forces(ctx: SimulationContext, f: np.ndarray) -> np.ndarray:
    """F'_{perm[a]} = R F_a averaged over ops (reference
    symmetrize_forces.hpp)."""
    if ctx.symmetry is None or ctx.symmetry.num_ops <= 1:
        return f
    out = np.zeros_like(f)
    for op in ctx.symmetry.ops:
        out[op.perm] += f @ op.rot_cart.T
    return out / ctx.symmetry.num_ops


def total_forces(
    ctx: SimulationContext,
    rho_g: np.ndarray,
    vxc_g: np.ndarray,
    veff_g: np.ndarray,
    bz_g,
    psi,
    occ,
    evals,
    d_by_spin,
    dm_blocks_by_spin,
    rho_resid_g: np.ndarray | None = None,
) -> dict:
    terms = {
        "vloc": forces_vloc(ctx, rho_g),
        "core": forces_core(ctx, vxc_g),
        "ewald": forces_ewald(ctx),
        "nonloc": forces_nonloc(ctx, psi, occ, evals, d_by_spin),
        "us": forces_us(ctx, veff_g, bz_g, dm_blocks_by_spin),
    }
    if rho_resid_g is not None:
        terms["scf_corr"] = forces_scf_corr(ctx, rho_resid_g)
    tot = sum(terms.values())
    terms["total"] = symmetrize_forces(ctx, tot)
    return terms


def forces_hubbard(ctx, hub, um_local, psi, occ: np.ndarray,
                   max_occupancy: float = 2.0) -> np.ndarray:
    """DFT+U force: F_a = -sum_{m1,m2,s} um(m1,m2) d n(m2,m1)/d R_a
    (reference hubbard_occupancies_derivatives.cpp, displacement branch;
    local blocks, "simple hubbard correction" scope — the same support
    boundary as the reference's force path, which raises for the
    non-collinear/ +V derivative combinations).

    n(m1,m2) = sum f <phi^S_m1|psi><psi|phi^S_m2> with
    phi^S = phi + beta q <beta|phi>. Derivatives use the -i(G+k) phase
    trick on phi (attaching to the orbital's atom) and on beta
    (attaching to each projector's atom for the ultrasoft S part)."""
    uc = ctx.unit_cell
    nat = uc.num_atoms
    out = np.zeros((nat, 3))
    if hub is None or um_local is None:
        return out
    nh = hub.num_hub_total
    nbeta = ctx.beta.num_beta_total
    qmat = ctx.beta.qmat
    own = np.zeros(nh, dtype=np.int64)
    for b in hub.blocks:
        own[b.off : b.off + b.nm] = b.ia
    beta_own = np.zeros(max(nbeta, 1), dtype=np.int64)
    if nbeta:
        for ia, off, nbf in ctx.beta.atom_blocks(uc):
            beta_own[off : off + nbf] = ia
    phis_all = hub.phi_s_gk
    phib_all = hub.phi_gk if hub.phi_gk is not None else hub.phi_s_gk
    for ik in range(ctx.gkvec.num_kpoints):
        phis = np.asarray(phis_all[ik])  # S phi [nh, ngk]
        phib = np.asarray(phib_all[ik])  # bare phi
        gk = np.asarray(ctx.gkvec.gkcart[ik])  # [ngk, 3]
        beta = (
            np.asarray(ctx.beta.beta_gk[ik]) if nbeta else None
        )
        for ispn in range(psi.shape[1]):
            ps = np.asarray(psi[ik, ispn])  # [nb, ngk]
            f = occ[ik, ispn] * ctx.kweights[ik] / max_occupancy
            um = um_local[ispn]  # um(m1, m2)
            hp = np.conj(phis) @ ps.T  # <phi^S_m|psi_b>  [nh, nb]
            # A[m] = sum_m2 um(m, m2) f_b <psi_b|phi^S_m2>: the partner
            # factor each derivative row contracts against
            A = um @ (np.conj(hp) * f[None, :])  # [nh, nb] (uses um(m1,m2))
            if nbeta and qmat is not None:
                beta_psi = np.conj(beta) @ ps.T  # [nbeta, nb]
                bphi = np.conj(beta) @ phib.T  # <beta_y|phi_m> [nbeta, nh]
            for x in range(3):
                # own-orbital phase derivative uses the BARE phi (the
                # S-augmented phi's phase mixes in the beta atoms' phases,
                # which the explicit beta chain below accounts for —
                # FD-verified attribution)
                dhp = (np.conj(phib) * (1j * gk[:, x])[None, :]) @ ps.T
                row = 2.0 * np.real(np.sum(dhp * A, axis=1))  # per m1
                np.add.at(out[:, x], own, -row * max_occupancy)
                if nbeta and qmat is not None:
                    dbeta_psi = (
                        np.conj(beta) * (1j * gk[:, x])[None, :]
                    ) @ ps.T  # <d beta|psi> [nbeta, nb]
                    dbphi = (
                        np.conj(beta) * (1j * gk[:, x])[None, :]
                    ) @ phib.T  # <d beta_y|phi_m> (beta displaced)
                    # beta-atom attribution: q_xy [conj<b_y|phi> <db_x|psi>
                    #   - conj<db_y|phi> <b_x|psi>]  (FD-verified signs)
                    t1 = np.einsum(
                        "xy,ym,xb->xmb", qmat, np.conj(bphi), dbeta_psi
                    )
                    t2 = np.einsum(
                        "xy,ym,xb->xmb", qmat, np.conj(dbphi), beta_psi
                    )
                    # attributions (qmat is block-diagonal per atom, so
                    # the x- and y-row atoms coincide): the <d beta|psi>
                    # piece (t1) and the <d beta_y|phi> piece (t2) both
                    # attach to the beta atom; translation invariance puts
                    # the -t2 partner on the ORBITAL's atom
                    per_beta = 2.0 * np.real(
                        np.einsum("xmb,mb->x", t1 + t2, A)
                    )
                    np.add.at(
                        out[:, x], beta_own, -per_beta * max_occupancy
                    )
                    per_m = 2.0 * np.real(
                        np.einsum("xmb,mb->m", t2, A)
                    )
                    np.add.at(out[:, x], own, per_m * max_occupancy)
    return out
