"""Direct total-energy minimization (ensemble-DFT flavor).

Reference: src/nlcglib/adaptor.hpp:198-246 (the nlcglib hook SIRIUS uses
for robust metallic convergence) and python_module/sirius/edft/ (the
Marzari-Vanderbilt free-energy minimization driver). Re-designed here as a
projected preconditioned gradient descent on the S-orthonormal Stiefel
manifold with smeared occupations refreshed from the subspace Hamiltonian:

  F[X, f] = E_KS[rho(X, f)] - T S[f],  X^H S X = I

  grad_X* F = w_k f_b (H[rho] X - S X (X^H H X))    (projected gradient;
  the potential-variation terms cancel by the Hellmann-Feynman argument,
  and df-terms vanish at f = f_smear(eps(X)) — the ensemble condition)

Each step: (1) density + potential from (X, f); (2) one H application;
(3) subspace rotation to the H eigenbasis, occupation refresh (mu, f, TS);
(4) Teter-preconditioned projected gradient step with backtracking line
search on F; (5) Loewdin S-re-orthonormalization. O(nb) extra memory, no
mixer — the robust path when Anderson mixing struggles (bad metals).

Scope: PP-PW collinear/unpolarized path (the same coverage as run_scf's
batched solver). Not a performance path yet — it exists for robustness
parity (VERDICT round-3 item 6) and is validated against run_scf energies
in tests/test_direct_min.py.
"""

from __future__ import annotations

import time

import jax.numpy as jnp
import numpy as np

from sirius_tpu.config.schema import Config
from sirius_tpu.context import SimulationContext
from sirius_tpu.dft.density import generate_density_g, initial_magnetization_g
from sirius_tpu.dft.occupation import find_fermi
from sirius_tpu.dft.potential import generate_potential
from sirius_tpu.dft.scf import _initial_subspace, _subspace_rotate_host
from sirius_tpu.dft.xc import XCFunctional
from sirius_tpu.ops.hamiltonian import apply_h_s, make_hk_params


def _s_orthonormalize(x, sx):
    """Loewdin in the S metric: X <- X (X^H S X)^{-1/2} (per (k, spin))."""
    o = x.conj() @ sx.T
    o = 0.5 * (o + o.conj().T)
    s, u = np.linalg.eigh(o)
    s = np.maximum(s, 1e-14)
    oinv = (u * (1.0 / np.sqrt(s))[None, :]) @ u.conj().T
    return oinv.T @ x


def run_direct_min(cfg: Config, base_dir: str = ".", ctx=None,
                   max_steps: int | None = None) -> dict:
    """Ground state via direct free-energy minimization. Returns the same
    result-dict shape as run_scf (subset)."""
    t0 = time.time()
    p = cfg.parameters
    if ctx is None:
        ctx = SimulationContext.create(cfg, base_dir)
    if ctx.num_mag_dims == 3:
        raise NotImplementedError("direct minimization: collinear/unpolarized only")
    xc = XCFunctional(p.xc_functionals)
    nk, ns, nb = ctx.gkvec.num_kpoints, ctx.num_spins, ctx.num_bands
    nel = ctx.unit_cell.num_valence_electrons - p.extra_charge
    polarized = ctx.num_mag_dims == 1
    max_steps = max_steps or max(p.num_dft_iter, 100)

    from sirius_tpu.dft.density import initial_density_g
    from sirius_tpu.ops.augmentation import d_operator

    rho_g = initial_density_g(ctx)
    mag_g = initial_magnetization_g(ctx) if polarized else None
    pot = generate_potential(ctx, rho_g, xc, mag_g)

    # --- S-orthonormal start: lowest-nb LCAO Ritz vectors ---
    psi_big = _initial_subspace(ctx)
    X = np.zeros((nk, ns, nb, ctx.gkvec.ngk_max), dtype=np.complex128)

    def params_for(ik, ispn, pot_):
        d = ctx.beta.dion
        if ctx.aug is not None:
            vs_g = (
                pot_.veff_g + (pot_.bz_g if ispn == 0 else -pot_.bz_g)
                if polarized
                else pot_.veff_g
            )
            d = d_operator(ctx.unit_cell, ctx.gvec, ctx.aug, vs_g, ctx.beta)
        return make_hk_params(ctx, ik, pot_.veff_r_coarse[ispn], d)

    for ik in range(nk):
        for ispn in range(ns):
            prm = params_for(ik, ispn, pot)
            xb = psi_big[ik, ispn] * np.asarray(ctx.gkvec.mask[ik])
            hx, sx = apply_h_s(prm, jnp.asarray(xb))
            X[ik, ispn] = _subspace_rotate_host(
                xb, np.asarray(hx), np.asarray(sx), nb
            )

    evals = np.zeros((nk, ns, nb))
    # initial occupancies from the LCAO Ritz values (NOT full filling: that
    # would build a first density with nb*max_occ electrons instead of nel)
    for ik in range(nk):
        for ispn in range(ns):
            prm = params_for(ik, ispn, pot)
            hx, _ = apply_h_s(prm, jnp.asarray(X[ik, ispn]))
            evals[ik, ispn] = np.real(
                np.diag(X[ik, ispn].conj() @ np.asarray(hx).T)
            )
    _mu0, occ0, _e0 = find_fermi(
        jnp.asarray(evals), jnp.asarray(ctx.kweights), nel,
        p.smearing_width, kind=p.smearing, max_occupancy=ctx.max_occupancy,
    )
    occ = np.asarray(occ0)
    mu, entropy_sum = 0.0, 0.0
    F_hist: list[float] = []
    alpha = float(getattr(cfg.iterative_solver, "min_alpha", 0.0) or 0.3)
    converged = False
    n_steps = 0
    _prev = None  # (G, <G,G>, P) for the Polak-Ribiere update

    from sirius_tpu.dft.density import symmetrize_pw

    do_symmetrize = (
        p.use_symmetry and ctx.symmetry is not None and ctx.symmetry.num_ops > 1
    )

    def free_energy_and_grad(X, occ, want_grad=True):
        """F, eval-by-term dict, per-(k,s) (HX, SX, Hsub) lists."""
        rho_spin = generate_density_g(ctx, jnp.asarray(X), occ)
        rho = rho_spin.sum(axis=0)
        mag = rho_spin[0] - rho_spin[1] if polarized else None
        if do_symmetrize:
            # the IBZ-weighted density must be symmetrized BEFORE the
            # functional evaluation — the KS energy is defined on the
            # symmetric manifold (same as run_scf's density step)
            rho = symmetrize_pw(ctx, rho)
            if polarized and mag is not None:
                mag = symmetrize_pw(ctx, mag, axial_z=True)
        pot_ = generate_potential(ctx, rho, xc, mag)
        e = pot_.energies
        eval_sum = 0.0
        HX = np.zeros_like(X)
        SX = np.zeros_like(X)
        eps = np.zeros((nk, ns, nb))
        for ik in range(nk):
            for ispn in range(ns):
                prm = params_for(ik, ispn, pot_)
                hx, sx = apply_h_s(prm, jnp.asarray(X[ik, ispn]))
                hx = np.asarray(hx)
                sx = np.asarray(sx)
                HX[ik, ispn] = hx
                SX[ik, ispn] = sx
                hsub = X[ik, ispn].conj() @ hx.T
                eps[ik, ispn] = np.real(np.diag(hsub))
                eval_sum += ctx.kweights[ik] * float(
                    np.sum(occ[ik, ispn] * eps[ik, ispn])
                )
        e_total = (
            eval_sum - e["vxc"] - e["bxc"] - 0.5 * e["vha"] + e["exc"]
            + ctx.e_ewald
        )
        return e_total, pot_, HX, SX, eps

    for step in range(max_steps):
        # (a) subspace rotation to the current H eigenbasis + occupations
        e_total, pot, HX, SX, eps_diag = free_energy_and_grad(X, occ)
        for ik in range(nk):
            for ispn in range(ns):
                hsub = X[ik, ispn].conj() @ HX[ik, ispn].T
                hsub = 0.5 * (hsub + hsub.conj().T)
                ev, u = np.linalg.eigh(hsub)
                evals[ik, ispn] = ev
                X[ik, ispn] = u.T @ X[ik, ispn]
                HX[ik, ispn] = u.T @ HX[ik, ispn]
                SX[ik, ispn] = u.T @ SX[ik, ispn]
        mu_j, occ_j, ent_j = find_fermi(
            jnp.asarray(evals), jnp.asarray(ctx.kweights), nel,
            p.smearing_width, kind=p.smearing,
            max_occupancy=ctx.max_occupancy,
        )
        mu, entropy_sum = float(mu_j), float(ent_j)
        occ = np.asarray(occ_j)
        F = e_total + entropy_sum
        F_hist.append(F)
        n_steps = step + 1

        # (b) projected preconditioned CG step with a parabolic line search
        G = np.zeros_like(X)
        res_occ = 0.0
        wsum = 0.0
        for ik in range(nk):
            ek = np.asarray(ctx.gkvec.kinetic()[ik])
            mask = np.asarray(ctx.gkvec.mask[ik])
            # Teter preconditioner on the kinetic profile
            t = ek / np.maximum(1.0, 1e-12 + np.abs(evals[ik]).max())
            pre = (27 + t * (18 + t * (12 + 8 * t))) / (
                27 + t * (18 + t * (12 + t * (8 + 16 * t)))
            )
            for ispn in range(ns):
                r = HX[ik, ispn] - evals[ik, ispn][:, None] * SX[ik, ispn]
                w = ctx.kweights[ik] * occ[ik, ispn]
                res_occ += float(np.sum(w * np.sum(np.abs(r) ** 2, axis=1)))
                wsum += float(np.sum(w))
                G[ik, ispn] = (
                    (r * pre[None, :])
                    * (w + 1e-4)[:, None]
                    * mask[None, :]
                )
        res_occ /= max(wsum, 1e-30)
        # converge on a SMALL energy step AND a small OCCUPIED-band
        # residual — the energy criterion alone can fire after
        # rotation-only steps while the minimization is still descending
        if (
            step >= 1
            and abs(F_hist[-1] - F_hist[-2]) < p.energy_tol
            and res_occ < 1e-9
        ):
            converged = True
            break

        # Polak-Ribiere CG direction (restart when non-descending)
        gdot = float(np.real(np.vdot(G, G)))
        if step == 0 or _prev is None:
            P = -G
        else:
            beta_pr = max(
                0.0, float(np.real(np.vdot(G, G - _prev[0]))) / max(_prev[1], 1e-30)
            )
            P = -G + beta_pr * _prev[2]
            if float(np.real(np.vdot(P, G))) > 0:
                P = -G  # not a descent direction: restart
        _prev = (G.copy(), gdot, P.copy())

        def retract(Xt):
            for ik in range(nk):
                for ispn in range(ns):
                    prm = params_for(ik, ispn, pot)
                    _, sx = apply_h_s(prm, jnp.asarray(Xt[ik, ispn]))
                    Xt[ik, ispn] = _s_orthonormalize(
                        Xt[ik, ispn], np.asarray(sx)
                    )
            return Xt

        # parabolic fit: F(0)=F, F'(0)=2Re<G,P>, F(a1) -> minimizer
        dF0 = 2.0 * float(np.real(np.vdot(G, P)))
        a1 = alpha
        X1 = retract(X + a1 * P)
        e1, *_ = free_energy_and_grad(X1, occ)
        F1 = e1 + entropy_sum
        denom = F1 - F - dF0 * a1
        improved = False
        if denom > 1e-300:
            a_star = float(np.clip(-0.5 * dF0 * a1 * a1 / denom, 0.05 * a1, 4.0 * a1))
            Xs = retract(X + a_star * P)
            es, *_ = free_energy_and_grad(Xs, occ)
            if es + entropy_sum < min(F, F1):
                X, alpha, improved = Xs, min(max(a_star, 1e-3), 2.0), True
        if not improved and F1 < F:
            X, alpha, improved = X1, min(a1 * 1.5, 2.0), True
        if not improved:
            alpha *= 0.3
            if alpha < 1e-7:
                # line search exhausted at the minimum: converged if the
                # free energy has stopped moving
                converged = (
                    step >= 1 and abs(F_hist[-1] - F_hist[-2]) < p.energy_tol
                )
                break

    band_gap = 0.0
    result = {
        "converged": converged,
        "num_scf_iterations": n_steps,
        "efermi": mu,
        "band_gap": band_gap,
        "etot_history": F_hist,
        "energy": {
            "total": F_hist[-1] - entropy_sum if F_hist else 0.0,
            "free": F_hist[-1] if F_hist else 0.0,
            "entropy_sum": entropy_sum,
        },
        "wall_s": time.time() - t0,
        "method": "direct_minimization",
    }
    return result
