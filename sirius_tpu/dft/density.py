"""Charge density: initial guess and generation from wave functions.

Reference: src/density/density.cpp (initial_density :137, generate :1105,
add_k_point_contribution_rg :700-760). The reference loops bands with
per-band FFTs and accumulates |psi(r)|^2 with OMP/CUDA kernels
(density_rg.cu); here the whole band block is one batched FFT and the
occupation-weighted reduction is a single einsum, jitted per k-point.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.context import SimulationContext
from sirius_tpu.core.fftgrid import g_to_r, r_to_g


def initial_density_g(ctx: SimulationContext) -> np.ndarray:
    """Superposition of free-atom densities, normalized to the electron
    count (reference density.cpp:137 initial_density_pseudo)."""
    rho_g = ctx.rho_atomic_g.copy()
    nel = ctx.unit_cell.num_valence_electrons
    n0 = rho_g[0].real * ctx.unit_cell.omega
    if abs(n0) < 1e-12:
        raise ValueError("free-atom density missing in species files")
    rho_g *= nel / n0
    return rho_g


@partial(jax.jit, static_argnames=("dims",))
def _accumulate_k(
    psi: jax.Array,  # [nspin, nb, ngk]
    occ_w: jax.Array,  # [nspin, nb] occupation * k-weight
    fft_index: jax.Array,
    dims: tuple[int, int, int],
) -> jax.Array:
    """sum_{s,b} occ_w[s,b] |psi_sb(r)|^2 on the coarse box (one batched FFT)."""
    n = dims[0] * dims[1] * dims[2]
    batch = psi.shape[:-1]
    box = jnp.zeros(batch + (n,), dtype=psi.dtype).at[..., fft_index].add(psi)
    fr = jnp.fft.ifftn(box.reshape(batch + dims), axes=(-3, -2, -1)) * n
    return jnp.einsum("sb,sbxyz->xyz", occ_w, jnp.abs(fr) ** 2)


def density_from_coarse_acc(ctx: SimulationContext, acc: np.ndarray) -> np.ndarray:
    """Finalize the per-spin density from the occupation-weighted |psi(r)|^2
    accumulation on the coarse box: divide by Omega, transform to coarse G,
    map to the fine G set. acc: [nspin, n1, n2, n3] real."""
    dims = ctx.fft_coarse.dims
    ns = acc.shape[0]
    out = np.zeros((ns, ctx.gvec.num_gvec), dtype=np.complex128)
    for ispn in range(ns):
        rho_r_coarse = np.asarray(acc[ispn]) / ctx.unit_cell.omega
        rho_g_coarse = np.asarray(
            r_to_g(jnp.asarray(rho_r_coarse, dtype=jnp.complex128),
                   jnp.asarray(ctx.gvec_coarse.fft_index), dims)
        )
        out[ispn, ctx.coarse_to_fine] = rho_g_coarse
    return out


def generate_density_g(
    ctx: SimulationContext,
    psi_all: jnp.ndarray,  # [nk, nspin, nb, ngk_max]
    occ: np.ndarray,  # [nk, nspin, nb]
) -> np.ndarray:
    """Per-spin valence density [nspin, ng_fine] from occupied wave
    functions (unsymmetrized; the SCF symmetrizes the assembled total).

    psi are S-normalized PW coefficients; |psi(r)|^2 accumulated on the
    coarse box, divided by Omega, transformed to coarse G, mapped to fine G.
    """
    dims = ctx.fft_coarse.dims
    nk = ctx.gkvec.num_kpoints
    ns = psi_all.shape[1]
    acc = np.zeros((ns,) + tuple(dims))
    for ispn in range(ns):
        a = jnp.zeros(dims)
        for ik in range(nk):
            ow = jnp.asarray(occ[ik, ispn : ispn + 1] * ctx.kweights[ik])
            a = a + _accumulate_k(
                psi_all[ik, ispn : ispn + 1], ow,
                jnp.asarray(ctx.gkvec.fft_index[ik]), dims,
            )
        acc[ispn] = np.asarray(a)
    return density_from_coarse_acc(ctx, acc)


def atomic_sphere_radii(uc, rmax: float = 2.0) -> np.ndarray:
    """Per-atom non-overlapping sphere radii: half the nearest-neighbor
    distance over periodic images (including an atom's own images, so
    single-atom cells are covered), capped at rmax (reference
    control.rmt_max flavor)."""
    pos = uc.positions_cart()
    ts = np.array(
        np.meshgrid(*[[-1, 0, 1]] * 3, indexing="ij")
    ).reshape(3, -1).T @ uc.lattice
    d = np.linalg.norm(
        pos[:, None, None, :] - pos[None, :, None, :] - ts[None, None, :, :],
        axis=-1,
    )
    d[d < 1e-8] = np.inf
    return np.minimum(0.5 * d.min(axis=(1, 2)), rmax)


def initial_magnetization_vec_g(ctx: SimulationContext) -> np.ndarray:
    """[3, ng] initial (mx, my, mz) from per-atom starting moment vectors.

    Two seeds, selected by settings.smooth_initial_mag exactly like the
    reference (density.cpp initial_density_pseudo):
      - smooth: per-atom Gaussian exp(-G^2/(4 alpha)), alpha = 4 — sharply
        peaked at the atom (~1.4 m e/a0^3 at r=0), which is what gives the
        first iteration a strong exchange splitting on localized shells;
      - default: compact normalized bump w(R, x) = (1 - (x/R)^2) e^{x/R} /
        (3.18866 R^3) inside an atomic sphere."""
    from sirius_tpu.core.radial import sbessel_integral

    uc = ctx.unit_cell
    gv = ctx.gvec
    out = np.zeros((3, gv.num_gvec), dtype=np.complex128)
    if not np.any(np.abs(uc.moments) > 1e-12):
        return out
    smooth = bool(ctx.cfg.settings.smooth_initial_mag)
    rad = atomic_sphere_radii(uc)
    qshell = np.sqrt(gv.shell_g2)
    for ia in range(uc.num_atoms):
        mvec = uc.moments[ia]
        if np.all(np.abs(mvec) < 1e-12):
            continue
        if smooth:
            alpha = 4.0
            ff = np.exp(-gv.shell_g2 / (4.0 * alpha))[gv.shell_idx]
        else:
            r = np.linspace(1e-8, rad[ia], 400)
            w = (1 - (r / rad[ia]) ** 2) * np.exp(r / rad[ia]) / (
                3.1886583903476735 * rad[ia] ** 3
            )
            ff = sbessel_integral(r, 4.0 * np.pi * w, 0, qshell, m=2)[gv.shell_idx]
        phase = np.exp(-2j * np.pi * (gv.millers @ uc.positions[ia]))
        for i in range(3):
            if abs(mvec[i]) > 1e-12:
                out[i] += (mvec[i] / uc.omega) * ff * phase
    return out


def initial_magnetization_g(ctx: SimulationContext) -> np.ndarray:
    """Initial z-magnetization (collinear): z-component of the vector seed."""
    return initial_magnetization_vec_g(ctx)[2]


def symmetrize_pw(
    ctx: SimulationContext, f_g: np.ndarray, axial_z: bool = False
) -> np.ndarray:
    """Symmetrize PW coefficients over the space group.

    f'(r) = (1/N) sum_S f(S^{-1} r) with S: x -> W x + t gives, for
    g' = (W^{-1})^T g = w_k g:
        f'(g') += f(g) e^{-2 pi i g'. t} / N
    (reference symmetrize_pw_function.hpp via Gvec_shells remap). The sphere
    is rotation-invariant so every image lands inside the set; rotation
    tables per op are cached on the context's gvec.

    axial_z: the field is the z-component of an axial vector (collinear
    magnetization / B_xc): each op's contribution carries its spin_sign
    (= det(R) R_zz, reference spin_rotation S(2,2)) — without it AFM
    sublattice-swap ops average the staggered field to zero."""
    sym = ctx.symmetry
    gv = ctx.gvec
    cache = getattr(ctx, "_sym_rot_cache", None)
    if cache is None:
        lut = {tuple(m): i for i, m in enumerate(gv.millers)}
        cache = []
        for op in sym.ops:
            gm = gv.millers @ op.w_k.T  # rows g' = w_k g
            idx = np.asarray([lut[tuple(m)] for m in gm], dtype=np.int64)
            phase = np.exp(-2j * np.pi * (gm @ op.t))
            cache.append((idx, phase, op.spin_sign))
        ctx._sym_rot_cache = cache
    out = np.zeros_like(f_g)
    for idx, phase, ssign in cache:
        np.add.at(out, idx, f_g * (phase * ssign if axial_z else phase))
    return out / sym.num_ops


def _beta_rotation_blocks(ctx: SimulationContext, op):
    """Per-atom-type block-diagonal Rlm rotation matrices for one symmetry
    op (shared by the collinear and non-collinear dm symmetrizers)."""
    from sirius_tpu.ops.hubbard import rlm_rotation_matrix

    uc = ctx.unit_cell
    dcache: dict = {}
    rot_by_type: dict = {}
    for ia, off, nbf in ctx.beta.atom_blocks(uc):
        it = uc.type_of_atom[ia]
        if it in rot_by_type:
            continue
        t = uc.atom_types[it]
        rmats = []
        for b in t.beta:
            if b.l not in dcache:
                dcache[b.l] = rlm_rotation_matrix(op.rot_cart, b.l)
            rmats.append(dcache[b.l])
        full = np.zeros((nbf, nbf))
        pos = 0
        for m in rmats:
            k = m.shape[0]
            full[pos : pos + k, pos : pos + k] = m
            pos += k
        rot_by_type[it] = full
    return rot_by_type


def symmetrize_density_matrix(ctx: SimulationContext, dm: np.ndarray) -> np.ndarray:
    """Symmetrize the beta-projector density matrix over the space group
    (reference src/symmetry/symmetrize_density_matrix.hpp): the IBZ k-sum
    only yields the full-BZ density matrix after averaging over operations,
    dm'[S a] += D(S) dm[a] D(S)^T per atom block, with D block-diagonal over
    the radial functions (real-harmonic Wigner blocks per l).

    dm: [ns, nbeta_tot, nbeta_tot] complex. Collinear spin channels swap
    under ops whose spin_sign is -1 (AFM sublattice swaps: the reference's
    spin_rotation maps up<->dn there); with spin_sign +1 they transform
    independently. Only the per-atom diagonal blocks are symmetrized and
    returned — inter-atom blocks come back zero (no consumer reads them;
    the reference stores the dm per atom and has no inter-atom blocks at
    all)."""
    sym = ctx.symmetry
    if sym is None or sym.num_ops <= 1:
        return dm
    uc = ctx.unit_cell
    ns = dm.shape[0]
    blocks = list(ctx.beta.atom_blocks(uc))
    off_by_atom = {ia: off for ia, off, _ in blocks}
    out = np.zeros_like(dm)
    for op in sym.ops:
        rot_by_type = _beta_rotation_blocks(ctx, op)
        flip = ns == 2 and op.spin_sign < 0
        for ia, off, nbf in blocks:
            r = rot_by_type[uc.type_of_atom[ia]]
            joff = off_by_atom[int(op.perm[ia])]
            for ispn in range(ns):
                src = (1 - ispn) if flip else ispn
                out[ispn, joff : joff + nbf, joff : joff + nbf] += (
                    r @ dm[src, off : off + nbf, off : off + nbf] @ r.T
                )
    return out / sym.num_ops


def symmetrize_density_matrix_nc(ctx: SimulationContext, dm3: np.ndarray) -> np.ndarray:
    """Non-collinear density-matrix symmetrization.

    dm3: [3, nbeta, nbeta] complex spin components (uu, dd, ud) — the du
    block is the Hermitian conjugate. Decompose per atom into the scalar
    d0 = uu + dd and the AXIAL vector (dx, dy, dz) = (ud + ud^H,
    i(ud - ud^H), uu - dd); the scalar transforms with the Wigner blocks
    alone, the vector additionally rotates with det(R) R (reference
    symmetrize_density_matrix.hpp spin_rotation branch)."""
    sym = ctx.symmetry
    if sym is None or sym.num_ops <= 1:
        return dm3
    uc = ctx.unit_cell
    blocks = list(ctx.beta.atom_blocks(uc))
    off_by_atom = {ia: off for ia, off, _ in blocks}
    out = np.zeros_like(dm3)
    for op in sym.ops:
        rot_by_type = _beta_rotation_blocks(ctx, op)
        srot = np.linalg.det(op.rot_cart) * op.rot_cart  # axial-vector rotation
        for ia, off, nbf in blocks:
            r = rot_by_type[uc.type_of_atom[ia]]
            joff = off_by_atom[int(op.perm[ia])]
            sl_i = slice(off, off + nbf)
            sl_j = slice(joff, joff + nbf)
            uu, dd, ud = dm3[0, sl_i, sl_i], dm3[1, sl_i, sl_i], dm3[2, sl_i, sl_i]
            d0 = uu + dd
            dvec = np.stack([ud + ud.conj().T, 1j * (ud - ud.conj().T), uu - dd])
            d0r = r @ d0 @ r.T
            dvr = np.einsum("ij,jab->iab", srot, [r @ c @ r.T for c in dvec])
            out[0, sl_j, sl_j] += 0.5 * (d0r + dvr[2])
            out[1, sl_j, sl_j] += 0.5 * (d0r - dvr[2])
            out[2, sl_j, sl_j] += 0.5 * (dvr[0] - 1j * dvr[1])
    return out / sym.num_ops


def rho_real_space(ctx: SimulationContext, rho_g: np.ndarray) -> np.ndarray:
    """rho(r) on the fine box."""
    return np.asarray(
        g_to_r(jnp.asarray(rho_g), jnp.asarray(ctx.gvec.fft_index), ctx.gvec.fft.dims)
    ).real


def atomic_moments(ctx: SimulationContext, mag_g: np.ndarray) -> np.ndarray:
    """Integral of m_z inside each atom's non-overlapping sphere (reference
    Density::get_magnetisation MT moments):
    int_{|r-ra|<R} e^{iG.r} dr = e^{iG.ra} (4 pi / G^3)(sin GR - GR cos GR).
    """
    gv = ctx.gvec
    uc = ctx.unit_cell
    glen = np.sqrt(gv.glen2)
    # reference per-atom moments use uniform control.rmt_max spheres
    # (simulation_context.cpp:977); stay non-overlapping within that cap
    radii = atomic_sphere_radii(uc, rmax=ctx.cfg.control.rmt_max)
    out = np.empty(uc.num_atoms)
    for ia in range(uc.num_atoms):
        radius = float(radii[ia])
        gr = glen * radius
        w = np.empty_like(gr)
        small = gr < 1e-8
        w[~small] = 4.0 * np.pi / np.maximum(glen[~small], 1e-30) ** 3 * (
            np.sin(gr[~small]) - gr[~small] * np.cos(gr[~small])
        )
        w[small] = 4.0 * np.pi * radius**3 / 3.0
        phase = np.exp(2j * np.pi * (gv.millers @ uc.positions[ia]))
        out[ia] = float(np.real(mag_g @ (w * phase)))
    return out


# ---------------------------------------------------------------------------
# Device-resident symmetrization (jit twins of symmetrize_pw /
# symmetrize_density_matrix for the fused SCF step). The host variants keep
# python loops over ops with np.add.at; on device the rotation tables become
# dense [nops, ...] arrays built once, and the op loop becomes one batched
# gather-scatter / einsum inside the compiled program.
# ---------------------------------------------------------------------------


def build_sym_pw_tables(ctx: SimulationContext):
    """Dense per-op PW rotation tables for symmetrize_pw_device:
    (idx [nops, ng] int32, phase_re/phase_im [nops, ng], ssign [nops]).
    Reuses (and fills) the same _sym_rot_cache the host path uses."""
    # prime the cache through the host function (identity op is cheap)
    if getattr(ctx, "_sym_rot_cache", None) is None:
        symmetrize_pw(ctx, np.zeros(ctx.gvec.num_gvec, dtype=np.complex128))
    idx = np.stack([c[0] for c in ctx._sym_rot_cache]).astype(np.int32)
    phase = np.stack([c[1] for c in ctx._sym_rot_cache])
    ssign = np.array([c[2] for c in ctx._sym_rot_cache], dtype=np.float64)
    return {
        "idx": idx,
        "phase_re": np.real(phase),
        "phase_im": np.imag(phase),
        "ssign": ssign,
    }


def symmetrize_pw_device(f_g: jnp.ndarray, tb: dict,
                         axial_z: bool = False) -> jnp.ndarray:
    """Jit-safe symmetrize_pw: f_g complex [ng] (inside the compiled
    program), tb from build_sym_pw_tables as device arrays."""
    nops = tb["idx"].shape[0]
    phase = jax.lax.complex(tb["phase_re"], tb["phase_im"])
    if axial_z:
        phase = phase * tb["ssign"][:, None]
    vals = f_g[None, :] * phase
    out = jnp.zeros_like(f_g).at[tb["idx"].reshape(-1)].add(vals.reshape(-1))
    return out / nops


def build_dm_sym_tables(ctx: SimulationContext):
    """Per-op dense beta-rotation matrices for the collinear density-matrix
    symmetrization: S_op[nops, nbeta, nbeta] with
    S[joff + i, off + j] = r[i, j] (joff the permuted atom's block), so
    dm' = (1/N) sum_op S dm S^T reproduces symmetrize_density_matrix's
    per-block r @ dm_block @ r.T scattered to the permuted block. flipneg
    marks ops with spin_sign < 0 (collinear channel swap); blockmask zeroes
    the inter-atom blocks the host variant never writes."""
    sym = ctx.symmetry
    uc = ctx.unit_cell
    nbeta = ctx.beta.num_beta_total
    blocks = list(ctx.beta.atom_blocks(uc))
    off_by_atom = {ia: off for ia, off, _ in blocks}
    ops = sym.ops if sym is not None and sym.num_ops > 1 else []
    s_ops = np.zeros((max(len(ops), 1), nbeta, nbeta))
    flipneg = np.zeros(max(len(ops), 1), dtype=bool)
    if not ops:
        s_ops[0] = np.eye(nbeta)
    for io, op in enumerate(ops):
        rot_by_type = _beta_rotation_blocks(ctx, op)
        flipneg[io] = op.spin_sign < 0
        for ia, off, nbf in blocks:
            r = rot_by_type[uc.type_of_atom[ia]]
            joff = off_by_atom[int(op.perm[ia])]
            s_ops[io, joff : joff + nbf, off : off + nbf] = r
    blockmask = np.zeros((nbeta, nbeta))
    for _, off, nbf in blocks:
        blockmask[off : off + nbf, off : off + nbf] = 1.0
    return {"s_ops": s_ops, "flipneg": flipneg, "blockmask": blockmask}


def symmetrize_density_matrix_device(dm: jnp.ndarray, tb: dict) -> jnp.ndarray:
    """Jit-safe symmetrize_density_matrix: dm complex [ns, nbeta, nbeta]
    inside the compiled program, tb from build_dm_sym_tables. For ns == 2
    the spin channels swap under flipneg ops exactly like the host."""
    ns = dm.shape[0]
    nops = tb["s_ops"].shape[0]
    if ns == 2:
        dms = jnp.where(tb["flipneg"][:, None, None, None],
                        dm[None, ::-1], dm[None])
    else:
        dms = jnp.broadcast_to(dm[None], (nops,) + dm.shape)
    out = jnp.einsum("oij,osjk,olk->sil", tb["s_ops"], dms, tb["s_ops"])
    return out * tb["blockmask"][None] / nops


def atomic_moments_vec(ctx: SimulationContext, mvec_g: np.ndarray) -> np.ndarray:
    """Per-atom (mx, my, mz) sphere integrals — vector form of
    atomic_moments for non-collinear runs. mvec_g: [3, ng]."""
    return np.stack(
        [atomic_moments(ctx, mvec_g[i]) for i in range(3)], axis=1
    )  # [natoms, 3]
