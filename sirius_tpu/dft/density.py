"""Charge density: initial guess and generation from wave functions.

Reference: src/density/density.cpp (initial_density :137, generate :1105,
add_k_point_contribution_rg :700-760). The reference loops bands with
per-band FFTs and accumulates |psi(r)|^2 with OMP/CUDA kernels
(density_rg.cu); here the whole band block is one batched FFT and the
occupation-weighted reduction is a single einsum, jitted per k-point.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.context import SimulationContext
from sirius_tpu.core.fftgrid import g_to_r, r_to_g


def initial_density_g(ctx: SimulationContext) -> np.ndarray:
    """Superposition of free-atom densities, normalized to the electron
    count (reference density.cpp:137 initial_density_pseudo)."""
    rho_g = ctx.rho_atomic_g.copy()
    nel = ctx.unit_cell.num_valence_electrons
    n0 = rho_g[0].real * ctx.unit_cell.omega
    if abs(n0) < 1e-12:
        raise ValueError("free-atom density missing in species files")
    rho_g *= nel / n0
    return rho_g


@partial(jax.jit, static_argnames=("dims",))
def _accumulate_k(
    psi: jax.Array,  # [nspin, nb, ngk]
    occ_w: jax.Array,  # [nspin, nb] occupation * k-weight
    fft_index: jax.Array,
    dims: tuple[int, int, int],
) -> jax.Array:
    """sum_{s,b} occ_w[s,b] |psi_sb(r)|^2 on the coarse box (one batched FFT)."""
    n = dims[0] * dims[1] * dims[2]
    batch = psi.shape[:-1]
    box = jnp.zeros(batch + (n,), dtype=psi.dtype).at[..., fft_index].add(psi)
    fr = jnp.fft.ifftn(box.reshape(batch + dims), axes=(-3, -2, -1)) * n
    return jnp.einsum("sb,sbxyz->xyz", occ_w, jnp.abs(fr) ** 2)


def generate_density_g(
    ctx: SimulationContext,
    psi_all: jnp.ndarray,  # [nk, nspin, nb, ngk_max]
    occ: np.ndarray,  # [nk, nspin, nb]
    symmetrize: bool = True,
) -> np.ndarray:
    """rho(G) on the fine set from occupied wave functions.

    psi are S-normalized PW coefficients; |psi(r)|^2 accumulated on the
    coarse box, divided by Omega, transformed to coarse G, mapped to fine G.
    Symmetrization over the full group happens on G coefficients.
    """
    dims = ctx.fft_coarse.dims
    nk = ctx.gkvec.num_kpoints
    acc = jnp.zeros(dims)
    for ik in range(nk):
        ow = jnp.asarray(occ[ik] * ctx.kweights[ik])
        acc = acc + _accumulate_k(
            psi_all[ik], ow, jnp.asarray(ctx.gkvec.fft_index[ik]), dims
        )
    rho_r_coarse = np.asarray(acc) / ctx.unit_cell.omega
    rho_g_coarse = np.asarray(
        r_to_g(jnp.asarray(rho_r_coarse, dtype=jnp.complex128),
               jnp.asarray(ctx.gvec_coarse.fft_index), dims)
    )
    rho_g = np.zeros(ctx.gvec.num_gvec, dtype=np.complex128)
    rho_g[ctx.coarse_to_fine] = rho_g_coarse
    if symmetrize and ctx.symmetry is not None and ctx.symmetry.num_ops > 1:
        rho_g = symmetrize_pw(ctx, rho_g)
    return rho_g


def symmetrize_pw(ctx: SimulationContext, f_g: np.ndarray) -> np.ndarray:
    """Symmetrize PW coefficients over the space group.

    f'(r) = (1/N) sum_S f(S^{-1} r) with S: x -> W x + t gives, for
    g' = (W^{-1})^T g = w_k g:
        f'(g') += f(g) e^{-2 pi i g'. t} / N
    (reference symmetrize_pw_function.hpp via Gvec_shells remap). The sphere
    is rotation-invariant so every image lands inside the set; rotation
    tables per op are cached on the context's gvec."""
    sym = ctx.symmetry
    gv = ctx.gvec
    cache = getattr(ctx, "_sym_rot_cache", None)
    if cache is None:
        lut = {tuple(m): i for i, m in enumerate(gv.millers)}
        cache = []
        for op in sym.ops:
            gm = gv.millers @ op.w_k.T  # rows g' = w_k g
            idx = np.asarray([lut[tuple(m)] for m in gm], dtype=np.int64)
            phase = np.exp(-2j * np.pi * (gm @ op.t))
            cache.append((idx, phase))
        ctx._sym_rot_cache = cache
    out = np.zeros_like(f_g)
    for idx, phase in cache:
        np.add.at(out, idx, f_g * phase)
    return out / sym.num_ops


def rho_real_space(ctx: SimulationContext, rho_g: np.ndarray) -> np.ndarray:
    """rho(r) on the fine box."""
    return np.asarray(
        g_to_r(jnp.asarray(rho_g), jnp.asarray(ctx.gvec.fft_index), ctx.gvec.fft.dims)
    ).real
