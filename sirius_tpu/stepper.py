"""Host-driven per-step SCF flow (the QE embedding contract, SURVEY §3.5).

The reference's C API lets the host own the SCF loop: it calls
sirius_find_eigen_states, reads band energies, sets occupancies (or asks
for them), calls sirius_generate_density, pulls rho with
sirius_get_pw_coeffs, MIXES ON THE HOST, pushes the mixed density (or
effective potential) back with sirius_set_pw_coeffs, regenerates the
potential, repeats (src/api/sirius_api.cpp: sirius_find_eigen_states,
sirius_generate_density, sirius_generate_effective_potential,
sirius_set/get_pw_coeffs, sirius_get_wave_functions).

GroundStateStepper is that flow's engine over the jax core: it exposes the
same primitives as separate calls on persistent state. run_scf remains the
single-shot driver; the stepper reuses the identical building blocks
(d_operator, batched davidson_kset, find_fermi, density accumulation,
generate_potential), so a host-driven loop converges to the same ground
state.

Scope: PP-PW norm-conserving/ultrasoft/PAW, unpolarized or collinear.
Hubbard and non-collinear flows stay in run_scf for now.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from sirius_tpu.config.schema import Config
from sirius_tpu.context import SimulationContext
from sirius_tpu.dft.density import (
    initial_density_g,
    initial_magnetization_g,
    symmetrize_density_matrix,
    symmetrize_pw,
)
from sirius_tpu.dft.occupation import find_fermi
from sirius_tpu.dft.potential import generate_potential
from sirius_tpu.dft.xc import XCFunctional
from sirius_tpu.ops.augmentation import d_operator, rho_aug_g


class GroundStateStepper:
    def __init__(self, cfg: Config, base_dir: str = ".", ctx=None):
        p = cfg.parameters
        if p.electronic_structure_method != "pseudopotential":
            raise NotImplementedError("stepper drives the PP-PW method only")
        self.cfg = cfg
        self.ctx = ctx if ctx is not None else SimulationContext.create(cfg, base_dir)
        if self.ctx.num_mag_dims == 3:
            raise NotImplementedError("stepper: collinear/unpolarized only")
        if cfg.hubbard.local:
            raise NotImplementedError("stepper: Hubbard not wired yet")
        self.xc = XCFunctional(p.xc_functionals)
        self.polarized = self.ctx.num_mag_dims == 1
        self.ns = self.ctx.num_spins
        self.nb = self.ctx.num_bands
        self.nk = self.ctx.gkvec.num_kpoints

        from sirius_tpu.dft import paw as paw_mod

        self._paw_mod = paw_mod
        self.paw = paw_mod.PawData.build(self.ctx)
        self.paw_dm = self.paw.initial_dm(self.ctx) if self.paw else None

        self.rho_g = initial_density_g(self.ctx)
        self.mag_g = initial_magnetization_g(self.ctx) if self.polarized else None
        self.pot = None
        self.evals = None
        self.occ = None
        self.efermi = 0.0
        self.entropy_sum = 0.0
        self.rho_out_g = None  # output (unmixed) density of the last
        self.mag_out_g = None  # generate_density call
        self._pr = self._pi = None  # device-resident wave functions
        self._psi_big = None
        self._kset_cache = {}
        self._paw_res = None
        self._e_paw_one_el = 0.0
        self.generate_effective_potential()

    # --- potential ---------------------------------------------------

    def generate_effective_potential(self):
        """Potential from the CURRENT input density (after the host pushed
        a mixed rho via set_pw_coeffs). Reference
        sirius_generate_effective_potential."""
        if self.paw is not None:
            self._paw_res = self._paw_mod.compute_paw(
                self.paw, self.paw_dm, self.xc
            )
            self._e_paw_one_el = self._paw_mod.one_elec_energy(
                self.paw, self.paw_dm, self._paw_res["dij_atoms"]
            )
        self.pot = generate_potential(self.ctx, self.rho_g, self.xc, self.mag_g)

    # --- band solve ---------------------------------------------------

    def _d_by_spin(self):
        ctx = self.ctx
        out = []
        for ispn in range(self.ns):
            if ctx.aug is not None:
                vs = self.pot.veff_g + (
                    (self.pot.bz_g if ispn == 0 else -self.pot.bz_g)
                    if self.polarized
                    else 0.0
                )
                out.append(d_operator(ctx.unit_cell, ctx.gvec, ctx.aug, vs, ctx.beta))
            else:
                out.append(ctx.beta.dion)
        if self.paw is not None:
            out = self._paw_mod.add_dij_to_d(
                self.paw, self._paw_res["dij_atoms"], out
            )
        return out

    def find_eigen_states(self, num_steps: int | None = None):
        """One band solve with the current potential (reference
        sirius_find_eigen_states). Warm-starts from the previous call."""
        from sirius_tpu.dft.scf import _initial_subspace
        from sirius_tpu.parallel.batched import (
            davidson_kset,
            initialize_subspace_kset,
            make_hkset_params,
            split_cplx,
        )

        ctx = self.ctx
        itsol = self.cfg.iterative_solver
        steps = itsol.num_steps if num_steps is None else num_steps
        v0 = float(np.real(self.pot.veff_g[0]))
        ps = make_hkset_params(
            ctx, self.pot.veff_r_coarse[: self.ns],
            np.stack(self._d_by_spin()), dtype=jnp.complex128, v0=v0,
        )
        self._ps = ps
        if self._pr is None:
            if self._psi_big is None:
                self._psi_big = _initial_subspace(ctx)
            pb_re, pb_im = split_cplx(self._psi_big, np.float64)
            self._pr, self._pi = initialize_subspace_kset(
                ps, jnp.asarray(pb_re), jnp.asarray(pb_im), self.nb
            )
            self._psi_big = None
        ev, self._pr, self._pi, rn = davidson_kset(
            ps, self._pr, self._pi,
            num_steps=steps, res_tol=itsol.residual_tolerance,
        )
        self.evals = np.asarray(ev, dtype=np.float64)
        return self.evals

    # --- occupations --------------------------------------------------

    def find_band_occupancies(self):
        p = self.cfg.parameters
        nel = self.ctx.unit_cell.num_valence_electrons - p.extra_charge
        mu, occ, ent = find_fermi(
            jnp.asarray(self.evals), jnp.asarray(self.ctx.kweights), nel,
            p.smearing_width, kind=p.smearing,
            max_occupancy=self.ctx.max_occupancy,
        )
        self.efermi = float(mu)
        self.occ = np.asarray(occ)
        self.entropy_sum = float(ent)
        return self.occ

    def get_band_energies(self, ik: int, ispn: int) -> np.ndarray:
        return np.asarray(self.evals[ik, ispn])

    def set_band_occupancies(self, ik: int, ispn: int, occ) -> None:
        if self.occ is None:
            self.occ = np.zeros((self.nk, self.ns, self.nb))
        self.occ[ik, ispn] = np.asarray(occ)

    def get_wave_functions(self, ik: int, ispn: int) -> np.ndarray:
        """[nb, ngk_max] PW coefficients (valid part padded with zeros)."""
        from sirius_tpu.parallel.batched import join_cplx

        # join only the requested slice — the full k-set array is the
        # largest object of the run
        return join_cplx(self._pr[ik, ispn], self._pi[ik, ispn])

    # --- density ------------------------------------------------------

    def generate_density(self):
        """Output density from the current (psi, occ) — NOT mixed into the
        input density; the host owns mixing (reference
        sirius_generate_density + host-side mixer)."""
        from sirius_tpu.dft.density import density_from_coarse_acc
        from sirius_tpu.parallel.batched import (
            density_kset,
            density_matrix_kset,
            join_cplx,
            split_cplx,
        )

        ctx = self.ctx
        occ_w = jnp.asarray(self.occ * ctx.kweights[:, None, None])
        rho_spin = density_from_coarse_acc(
            ctx, np.asarray(density_kset(self._ps, self._pr, self._pi, occ_w))
        )
        if ctx.aug is not None:
            if ctx.beta.num_beta_total:
                bre, bim = split_cplx(np.asarray(ctx.beta.beta_gk))
                dm_re, dm_im = density_matrix_kset(
                    jnp.asarray(bre), jnp.asarray(bim), self._pr, self._pi, occ_w
                )
                dm = join_cplx(dm_re, dm_im)
                if self._do_sym():
                    dm = symmetrize_density_matrix(ctx, dm)
                for ispn in range(self.ns):
                    blocks = [
                        dm[ispn, off : off + nbf, off : off + nbf]
                        for _, off, nbf in ctx.beta.atom_blocks(ctx.unit_cell)
                    ]
                    rho_spin[ispn] += rho_aug_g(
                        ctx.unit_cell, ctx.gvec, ctx.aug, blocks
                    )
                if self.paw is not None:
                    self.paw_dm = self.paw.dm_from_density_matrix(dm)
        rho_new = rho_spin.sum(axis=0)
        mag_new = rho_spin[0] - rho_spin[1] if self.polarized else None
        if self._do_sym():
            rho_new = symmetrize_pw(self.ctx, rho_new)
            if self.polarized:
                mag_new = symmetrize_pw(self.ctx, mag_new, axial_z=True)
        self.rho_out_g = rho_new
        self.mag_out_g = mag_new
        return rho_new

    def _do_sym(self) -> bool:
        return (
            self.cfg.parameters.use_symmetry
            and self.ctx.symmetry is not None
            and self.ctx.symmetry.num_ops > 1
        )

    # --- data exchange (reference sirius_set/get_pw_coeffs) -----------

    def get_pw_coeffs(self, label: str) -> np.ndarray:
        out = {
            "rho": self.rho_g,
            "rho_out": self.rho_out_g,
            "magz": self.mag_g,
            "magz_out": self.mag_out_g,
            "veff": None if self.pot is None else self.pot.veff_g,
            "vha": None if self.pot is None else self.pot.vha_g,
            "vxc": None if self.pot is None else self.pot.vxc_g,
        }.get(label)
        if out is None:
            raise KeyError(f"unknown/unset pw field '{label}'")
        return out

    def set_pw_coeffs(self, label: str, values: np.ndarray) -> None:
        v = np.asarray(values, dtype=np.complex128)
        if v.shape != (self.ctx.gvec.num_gvec,):
            raise ValueError(
                f"expected {self.ctx.gvec.num_gvec} PW coefficients, got {v.shape}"
            )
        if label == "rho":
            self.rho_g = v
        elif label == "magz":
            self.mag_g = v
        else:
            raise KeyError(f"set_pw_coeffs supports 'rho'/'magz', not '{label}'")

    # --- energy -------------------------------------------------------

    def total_energy(self) -> dict:
        """Energy terms from the current (evals, occ, pot) — the same
        assembly as run_scf's report (valid once the band solve used the
        potential generated from the current input density)."""
        e = self.pot.energies
        eval_sum = float(
            np.sum(self.ctx.kweights[:, None, None] * self.occ * self.evals)
        )
        e_total = (
            eval_sum - e["vxc"] - e["bxc"] - 0.5 * e["vha"] + e["exc"]
            + self.ctx.e_ewald
            + (
                self._paw_res["e_total"] - self._e_paw_one_el
                if self.paw is not None
                else 0.0
            )
        )
        return {
            "total": e_total,
            "free": e_total + self.entropy_sum,
            "eval_sum": eval_sum,
            "entropy_sum": self.entropy_sum,
            "kin": eval_sum - e["veff"] - e["bxc"],
            "scf_correction": 0.0,  # the host owns mixing in this flow
            **{k: e[k] for k in ("vha", "vxc", "exc", "bxc", "veff", "vloc")},
            "ewald": self.ctx.e_ewald,
        }

    # --- real-space grid exchange (reference sirius_set/get_rg_values) --

    def rg_dims(self) -> tuple:
        return tuple(self.ctx.gvec.fft.dims)

    def get_rg_values(self, label: str) -> np.ndarray:
        """Field values on the FULL fine real-space box [n1, n2, n3]."""
        from sirius_tpu.core.fftgrid import g_to_r
        import jax.numpy as jnp

        f_g = self.get_pw_coeffs(label)
        box = g_to_r(
            jnp.asarray(f_g), jnp.asarray(self.ctx.gvec.fft_index),
            self.ctx.gvec.fft.dims,
        )
        return np.real(np.asarray(box))

    def set_rg_values(self, label: str, values: np.ndarray) -> None:
        from sirius_tpu.core.fftgrid import r_to_g
        import jax.numpy as jnp

        v = np.asarray(values, dtype=np.float64)
        if v.shape != tuple(self.ctx.gvec.fft.dims):
            raise ValueError(
                f"expected box {self.ctx.gvec.fft.dims}, got {v.shape}"
            )
        f_g = np.asarray(
            r_to_g(
                jnp.asarray(v, dtype=jnp.complex128),
                jnp.asarray(self.ctx.gvec.fft_index), self.ctx.gvec.fft.dims,
            )
        )
        self.set_pw_coeffs(label, f_g)

    # --- checkpointing (reference sirius_save_state/load_state) ---------

    def save_state(self, path: str) -> None:
        from sirius_tpu.io.checkpoint import save_state as _save

        from sirius_tpu.parallel.batched import join_cplx

        psi = None if self._pr is None else join_cplx(self._pr, self._pi)
        _save(
            path, self.ctx,
            rho_g=self.rho_g, mag_g=self.mag_g,
            psi=psi, band_energies=self.evals,
            band_occupancies=self.occ, paw_dm=self.paw_dm,
        )

    def load_state(self, path: str) -> None:
        from sirius_tpu.io.checkpoint import load_state as _load

        st = _load(path, self.ctx)
        self.rho_g = np.asarray(st["rho_g"])
        if self.polarized and st.get("mag_g") is not None:
            self.mag_g = np.asarray(st["mag_g"])
        if st.get("psi") is not None:
            from sirius_tpu.parallel.batched import split_cplx

            pr, pi = split_cplx(np.asarray(st["psi"]), np.float64)
            self._pr, self._pi = jnp.asarray(pr), jnp.asarray(pi)
        if st.get("band_energies") is not None:
            self.evals = np.asarray(st["band_energies"])
        if st.get("band_occupancies") is not None:
            self.occ = np.asarray(st["band_occupancies"])
        if self.paw is not None and st.get("paw_dm") is not None:
            self.paw_dm = np.asarray(st["paw_dm"])

    # --- Sternheimer solve for a QE-driven DFPT loop (reference
    # sirius_linear_solver, backed by solvers/multi_cg) ------------------

    def linear_solver(self, vkq, psi, eigvals, dvpsi, alpha_pv: float = 0.0,
                      spin: int = 1, tol: float = 1e-8) -> np.ndarray:
        """Solve (H - eps_n S + alpha_pv P_occ) |dpsi_n> = -|dvpsi_n>.

        psi/dvpsi: [ngk, n] column vectors at this k (the host's layout);
        returns dpsi with the same shape. Single-k embedding: vkq must
        match one of the context's k-points."""
        from sirius_tpu.dft.linear_response import solve_sternheimer_k
        from sirius_tpu.ops.hamiltonian import apply_h_s, make_hk_params

        ctx = self.ctx
        kpts = np.asarray(ctx.gkvec.kpoints)
        ik = int(np.argmin(np.sum((kpts - np.asarray(vkq)) ** 2, axis=1)))
        ispn = max(0, int(spin) - 1)
        if self.pot is None:
            self.generate_effective_potential()
        d = self._d_by_spin()[ispn]
        prm = make_hk_params(ctx, ik, self.pot.veff_r_coarse[ispn], d)
        ngk_max = ctx.gkvec.ngk_max
        # host arrays are [n, ngk_host]; pad/crop to the context's ngk_max
        psi_rows = np.zeros((psi.shape[1], ngk_max), dtype=np.complex128)
        dv_rows = np.zeros_like(psi_rows)
        ncp = min(psi.shape[0], ngk_max)
        psi_rows[:, :ncp] = np.asarray(psi).T[:, :ncp]
        dv_rows[:, :ncp] = np.asarray(dvpsi).T[:, :ncp]
        dpsi, _niter, _res = solve_sternheimer_k(
            apply_h_s, prm, psi_rows, np.asarray(eigvals), dv_rows,
            alpha_pv=alpha_pv, tol=tol,
        )
        out = np.zeros((psi.shape[0], psi.shape[1]), dtype=np.complex128)
        out[:ncp, :] = np.asarray(dpsi).T[:ncp, :]
        return out
