"""sirius_tpu.campaigns: DAG job graphs over the serving engine.

A *campaign* is a DAG of SCF decks (CampaignSpec, spec.py) scheduled
through serve/ with dependency-aware admission, durable journaled edges
and cross-job warm-start handoff (handoff.py): a child node inherits its
parent's converged ``(rho, psi)`` through ``run_scf(initial_guess=)``,
with the delta-density transform for displaced geometries. Templates:
finite-displacement Γ phonons (phonon.py), Birch–Murnaghan EOS volume
sweeps (eos.py) and relax→SCF chains (chain.py). The ``sirius-campaign``
CLI (cli.py) runs a campaign end-to-end and writes a JSON result.
"""

from sirius_tpu.campaigns.spec import (  # noqa: F401
    CampaignNode, CampaignSpec, CampaignSpecError,
)
