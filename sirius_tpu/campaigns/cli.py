"""sirius-campaign: run a campaign DAG end-to-end on a local engine.

Examples::

    # Γ-point finite-displacement phonons of a deck (13 nodes for a
    # 2-atom cell: base + 12 displaced, all warm-started from base)
    sirius-campaign phonon si.json --displacement 0.01 --slices 4

    # Birch-Murnaghan EOS sweep, 7 volumes
    sirius-campaign eos si.json --scale0 0.94 --scale1 1.06 --points 7

    # relax then a final SCF at the relaxed geometry
    sirius-campaign chain si.json --force-tol 1e-4

    # an explicit spec (the JSON sirius-campaign writes next to its
    # journal), e.g. to resume after a crash: completed nodes are not
    # re-run, the rest replay from the journal with their edges intact
    sirius-campaign run --spec work/campaign.phonon.spec.json --resume

The campaign journal (``campaign.<id>.journal`` in the workdir by
default) makes the graph durable: re-running with ``--resume`` after a
SIGKILL picks up exactly the unfinished nodes.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--campaign-id", default=None,
                   help="campaign id (default: the template name)")
    p.add_argument("--slices", type=int, default=1,
                   help="device slices / concurrent nodes")
    p.add_argument("--workdir", default=".",
                   help="artifacts + journal + results live here")
    p.add_argument("--journal", default=None,
                   help="journal path (default: "
                        "<workdir>/campaign.<id>.journal)")
    p.add_argument("--events", default=None,
                   help="append JSONL observability events to this file "
                        "(default: <workdir>/campaign.<id>.events.jsonl)")
    p.add_argument("--timeout", type=float, default=3600.0,
                   help="overall wait bound in seconds")
    p.add_argument("--out", default=None,
                   help="result JSON path (default: "
                        "<workdir>/campaign.<id>.result.json)")
    p.add_argument("--resume", action="store_true",
                   help="re-attach to an existing journal instead of "
                        "submitting fresh nodes")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"])
    p.add_argument("-v", "--verbose", action="count", default=0)


def _load_deck(path: str) -> dict:
    with open(path) as f:
        return json.load(f)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="sirius-campaign",
        description="DAG job campaigns over the sirius_tpu serving engine",
    )
    sub = p.add_subparsers(dest="command", required=True)

    ph = sub.add_parser("phonon", help="finite-displacement Γ phonons")
    ph.add_argument("deck", help="base JSON deck (cli.py format)")
    ph.add_argument("--displacement", type=float, default=0.01,
                    help="Cartesian displacement in bohr")
    ph.add_argument("--atoms", default=None,
                    help="comma-separated atom indices to displace "
                         "(default: all)")
    _add_common(ph)

    eo = sub.add_parser("eos", help="Birch-Murnaghan EOS volume sweep")
    eo.add_argument("deck", help="base JSON deck (cli.py format)")
    eo.add_argument("--scale0", type=float, default=0.94)
    eo.add_argument("--scale1", type=float, default=1.06)
    eo.add_argument("--points", type=int, default=7)
    _add_common(eo)

    ch = sub.add_parser("chain", help="relax then SCF at the relaxed "
                                      "geometry")
    ch.add_argument("deck", help="base JSON deck (cli.py format)")
    ch.add_argument("--max-steps", type=int, default=10)
    ch.add_argument("--force-tol", type=float, default=1e-4)
    _add_common(ch)

    rn = sub.add_parser("run", help="run an explicit CampaignSpec JSON")
    rn.add_argument("--spec", required=True, help="CampaignSpec JSON file")
    _add_common(rn)
    return p


def _build_spec(args):
    from sirius_tpu.campaigns import chain, eos, phonon
    from sirius_tpu.campaigns.spec import CampaignSpec

    if args.command == "run":
        with open(args.spec) as f:
            return CampaignSpec.from_dict(json.load(f))
    deck = _load_deck(args.deck)
    cid = args.campaign_id or args.command
    if args.command == "phonon":
        atoms = ([int(t) for t in args.atoms.split(",")]
                 if args.atoms else None)
        return phonon.phonon_campaign(
            deck, displacement=args.displacement, atoms=atoms,
            campaign_id=cid)
    if args.command == "eos":
        return eos.eos_campaign(
            deck, scale0=args.scale0, scale1=args.scale1,
            num_points=args.points, campaign_id=cid)
    return chain.relax_scf_campaign(
        deck, max_steps=args.max_steps, force_tol=args.force_tol,
        campaign_id=cid)


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    from sirius_tpu import obs

    obs.setup_logging(args.verbose)

    if args.command != "run" and not os.path.isfile(args.deck):
        print(f"sirius-campaign: deck not found: {args.deck}",
              file=sys.stderr)
        return 2

    from sirius_tpu.campaigns.spec import CampaignSpecError

    try:
        spec = _build_spec(args)
    except (CampaignSpecError, ValueError, OSError, KeyError) as e:
        print(f"sirius-campaign: bad campaign spec: {e}", file=sys.stderr)
        return 2

    import jax

    if args.platform:
        jax.config.update(
            "jax_platforms",
            "axon" if args.platform == "tpu" else args.platform)

    from sirius_tpu.campaigns import runner
    from sirius_tpu.serve.engine import ServeEngine
    from sirius_tpu.serve.queue import JobStatus

    cid = spec.campaign_id
    workdir = args.workdir
    os.makedirs(workdir, exist_ok=True)
    spec_path = os.path.join(workdir, f"campaign.{cid}.spec.json")
    with open(spec_path, "w") as f:
        json.dump(spec.to_dict(), f, indent=2)
    journal = args.journal or os.path.join(workdir, f"campaign.{cid}.journal")
    events = args.events or os.path.join(
        workdir, f"campaign.{cid}.events.jsonl")

    eng = ServeEngine(
        num_slices=args.slices, workdir=workdir, verbose=args.verbose > 0,
        journal_path=journal, events_path=events)
    eng.start()
    t0 = time.time()
    try:
        if args.resume:
            handle = runner.resume_campaign(eng, spec, workdir=workdir)
            print(f"sirius-campaign: resumed {cid}: "
                  f"{len(handle.jobs)} node(s) replayed, "
                  f"{len(handle.prior_status)} already settled",
                  file=sys.stderr)
        else:
            handle = runner.submit_campaign(eng, spec, workdir=workdir)
        ok = handle.wait(timeout=args.timeout)
        res = handle.result()
        res["wall_s"] = time.time() - t0
        res["engine"] = eng.stats()
    finally:
        eng.shutdown(wait=True, mode="drain")
    out_path = args.out or os.path.join(
        workdir, f"campaign.{cid}.result.json")
    with open(out_path, "w") as f:
        json.dump(res, f, indent=2, default=float)
    summary = res.get("summary") or {}
    if summary.get("kind") == "phonon":
        freqs = ", ".join(
            f"{x:.1f}" for x in summary["frequencies_cm1"])
        print(f"phonon frequencies (cm^-1): {freqs}")
    elif summary.get("kind") == "eos":
        print(f"EOS fit: V0={summary['v0_bohr3']:.3f} bohr^3  "
              f"B0={summary['b0_gpa']:.2f} GPa  "
              f"B0'={summary['b0_prime']:.3f}")
    elif summary.get("kind") == "chain":
        print(f"chain: E_final={summary['final_energy_ha']:.10f} Ha in "
              f"{summary['final_scf_iterations']} warm iterations")
    print(json.dumps({k: v for k, v in res.items()
                      if k in ("campaign_id", "kind", "num_done",
                               "num_nodes", "wall_s")}, indent=2))
    print(f"sirius-campaign: result written to {out_path}",
          file=sys.stderr)
    if not ok:
        print("sirius-campaign: timed out waiting for nodes",
              file=sys.stderr)
        return 3
    all_done = all(
        handle.node_status(n.node_id) == JobStatus.DONE
        for n in spec.nodes)
    if not all_done or res.get("finalize_error"):
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
