"""CampaignSpec: a DAG of SCF decks with artifact handoff edges.

A campaign is a set of *nodes* — each a full JSON deck in the cli.py
format, usually derived from one base structure by a transform
(displacement, volume scale, relaxation) — plus *edges*: a node's
``parents`` must be terminal-DONE before it runs (serve/queue.py
dependency admission), and ``warm_from`` names the parent whose
converged ``(rho, psi)`` artifact seeds the child's SCF through
``run_scf(initial_guess=)`` (campaigns/handoff.py).

The spec is pure data (JSON round-trippable via ``to_dict``/
``from_dict``); submission and artifact plumbing live in
campaigns/runner.py, and the three stock templates — finite-displacement
phonons, EOS volume sweeps, relax→SCF chains — in campaigns/phonon.py,
eos.py and chain.py.
"""

from __future__ import annotations

import dataclasses
import re

_ID_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]*$")


class CampaignSpecError(ValueError):
    """The spec is not a well-formed DAG (cycle, unknown parent, ...)."""


@dataclasses.dataclass
class CampaignNode:
    """One job of the campaign DAG.

    ``warm_from`` must be one of ``parents`` (default: the first parent);
    ``displaced`` routes the handoff through the delta-density transform
    (dft/geometry.py::delta_density_guess) when the child's positions
    differ from the parent's; ``adopt_positions`` makes the child run at
    the positions recorded in the parent artifact (relax→SCF chains)."""

    node_id: str
    deck: dict
    parents: list[str] = dataclasses.field(default_factory=list)
    warm_from: str | None = None
    displaced: bool = True
    adopt_positions: bool = False
    meta: dict = dataclasses.field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "node_id": self.node_id,
            "deck": self.deck,
            "parents": list(self.parents),
            "warm_from": self.warm_from,
            "displaced": self.displaced,
            "adopt_positions": self.adopt_positions,
            "meta": self.meta,
        }


@dataclasses.dataclass
class CampaignSpec:
    """A named DAG of deck nodes; ``kind`` selects the finalizer that
    folds the per-node artifacts into campaign-level physics (phonon
    frequencies, an EOS fit, ...)."""

    campaign_id: str
    kind: str = "generic"
    nodes: list[CampaignNode] = dataclasses.field(default_factory=list)
    meta: dict = dataclasses.field(default_factory=dict)

    def node(self, node_id: str) -> CampaignNode:
        for n in self.nodes:
            if n.node_id == node_id:
                return n
        raise KeyError(f"campaign {self.campaign_id}: no node {node_id!r}")

    def job_id(self, node_id: str) -> str:
        """The serve job id of a node (campaign-scoped, journal-stable).
        Dot-separated, never "/": job ids become autosave-file tags."""
        return f"{self.campaign_id}.{node_id}"

    def validate(self) -> None:
        if not _ID_RE.match(self.campaign_id or ""):
            raise CampaignSpecError(
                f"bad campaign_id {self.campaign_id!r} (need "
                f"[A-Za-z0-9][A-Za-z0-9._-]*)")
        if not self.nodes:
            raise CampaignSpecError(
                f"campaign {self.campaign_id}: no nodes")
        ids = [n.node_id for n in self.nodes]
        seen: set[str] = set()
        for nid in ids:
            if not _ID_RE.match(nid or ""):
                raise CampaignSpecError(
                    f"campaign {self.campaign_id}: bad node_id {nid!r}")
            if nid in seen:
                raise CampaignSpecError(
                    f"campaign {self.campaign_id}: duplicate node {nid!r}")
            seen.add(nid)
        for n in self.nodes:
            if not isinstance(n.deck, dict):
                raise CampaignSpecError(
                    f"node {n.node_id}: deck must be a dict")
            for p in n.parents:
                if p not in seen:
                    raise CampaignSpecError(
                        f"node {n.node_id}: unknown parent {p!r}")
                if p == n.node_id:
                    raise CampaignSpecError(
                        f"node {n.node_id}: depends on itself")
            if n.warm_from is not None and n.warm_from not in n.parents:
                raise CampaignSpecError(
                    f"node {n.node_id}: warm_from {n.warm_from!r} is not "
                    f"one of its parents {n.parents}")
            if n.adopt_positions and not (n.warm_from or n.parents):
                raise CampaignSpecError(
                    f"node {n.node_id}: adopt_positions needs a parent")
        self.topo_order()  # raises CampaignSpecError on a cycle

    def topo_order(self) -> list[CampaignNode]:
        """Kahn topological order (stable within a rank by spec order)."""
        by_id = {n.node_id: n for n in self.nodes}
        indeg = {n.node_id: len(set(n.parents)) for n in self.nodes}
        children: dict[str, list[str]] = {n.node_id: [] for n in self.nodes}
        for n in self.nodes:
            for p in set(n.parents):
                children[p].append(n.node_id)
        ready = [n.node_id for n in self.nodes if indeg[n.node_id] == 0]
        out: list[CampaignNode] = []
        while ready:
            nid = ready.pop(0)
            out.append(by_id[nid])
            for c in children[nid]:
                indeg[c] -= 1
                if indeg[c] == 0:
                    ready.append(c)
        if len(out) != len(self.nodes):
            stuck = sorted(nid for nid, d in indeg.items() if d > 0)
            raise CampaignSpecError(
                f"campaign {self.campaign_id}: dependency cycle through "
                f"{stuck}")
        return out

    def to_dict(self) -> dict:
        return {
            "campaign_id": self.campaign_id,
            "kind": self.kind,
            "meta": self.meta,
            "nodes": [n.to_dict() for n in self.nodes],
        }

    @classmethod
    def from_dict(cls, d: dict) -> CampaignSpec:
        spec = cls(
            campaign_id=d["campaign_id"],
            kind=d.get("kind", "generic"),
            meta=dict(d.get("meta") or {}),
            nodes=[
                CampaignNode(
                    node_id=nd["node_id"],
                    deck=nd["deck"],
                    parents=list(nd.get("parents") or []),
                    warm_from=nd.get("warm_from"),
                    displaced=bool(nd.get("displaced", True)),
                    adopt_positions=bool(nd.get("adopt_positions", False)),
                    meta=dict(nd.get("meta") or {}),
                )
                for nd in d.get("nodes") or []
            ],
        )
        spec.validate()
        return spec
