"""Equation-of-state volume sweep as a campaign template.

Independent nodes at scaled lattice constants (no dependency edges: a
volume change changes the G sets, so there is nothing to warm-start
across — campaigns/handoff.py would detect the shape mismatch and
cold-start anyway). Finalization fits the third-order Birch–Murnaghan
E(V) form and reports V0, E0, B0 (GPa) and B0'. The same physics as the
``sirius-scf --task eos`` mini-app (apps_util.run_eos), but scheduled as
a DAG so the volume points run slice-parallel with journaled fault
tolerance.
"""

from __future__ import annotations

import json

import numpy as np

from sirius_tpu.campaigns.spec import (
    CampaignNode, CampaignSpec, CampaignSpecError,
)
from sirius_tpu.campaigns.phonon import deck_geometry

HA_BOHR3_TO_GPA = 29421.02648438959


def _with_scale(deck: dict, scale: float) -> dict:
    """The deck with every lattice vector scaled by ``scale`` (volume by
    scale^3); fractional positions are volume-invariant."""
    out = json.loads(json.dumps(deck))
    if isinstance(out.get("synthetic"), dict) or "synthetic" in out:
        syn = dict(out.get("synthetic") or {})
        syn["a"] = float(syn.get("a", 10.26)) * scale
        out["synthetic"] = syn
        return out
    uc = out.get("unit_cell")
    if isinstance(uc, dict) and uc.get("lattice_vectors"):
        uc = dict(uc)
        uc["lattice_vectors_scale"] = (
            float(uc.get("lattice_vectors_scale", 1.0)) * scale)
        out["unit_cell"] = uc
        return out
    raise CampaignSpecError(
        "eos_campaign: deck has neither a 'synthetic' section nor "
        "unit_cell lattice_vectors")


def eos_campaign(base_deck: dict, scale0: float = 0.94,
                 scale1: float = 1.06, num_points: int = 7,
                 campaign_id: str = "eos") -> CampaignSpec:
    """Volume sweep: ``num_points`` linear-in-length scales spanning
    [scale0, scale1] (volumes scale^3)."""
    if num_points < 4:
        raise CampaignSpecError(
            "eos_campaign: the Birch-Murnaghan fit has 4 parameters — "
            f"need >= 4 volume points, got {num_points}")
    if not (0 < scale0 < scale1):
        raise CampaignSpecError(
            f"eos_campaign: need 0 < scale0 < scale1, got "
            f"({scale0}, {scale1})")
    lattice, _ = deck_geometry(base_deck)
    v_base = float(abs(np.linalg.det(lattice)))
    scales = np.linspace(float(scale0), float(scale1), int(num_points))
    nodes = [
        CampaignNode(
            node_id=f"v{i}",
            deck=_with_scale(base_deck, float(s)),
            meta={"scale": float(s), "volume_bohr3": v_base * float(s) ** 3},
        )
        for i, s in enumerate(scales)
    ]
    return CampaignSpec(
        campaign_id=campaign_id, kind="eos", nodes=nodes,
        meta={"scales": scales.tolist(), "base_volume_bohr3": v_base},
    )


def birch_murnaghan(v, e0, v0, b0, b0p):
    """Third-order Birch-Murnaghan E(V) [Ha, bohr^3]."""
    v = np.asarray(v, dtype=np.float64)
    eta = (v0 / v) ** (2.0 / 3.0)
    return e0 + 9.0 * v0 * b0 / 16.0 * (
        (eta - 1.0) ** 3 * b0p + (eta - 1.0) ** 2 * (6.0 - 4.0 * eta))


def fit_birch_murnaghan(volumes, energies) -> dict:
    """Least-squares BM3 fit; initial guess from a parabola in V."""
    from scipy.optimize import curve_fit

    v = np.asarray(volumes, dtype=np.float64)
    e = np.asarray(energies, dtype=np.float64)
    c2, c1, c0 = np.polyfit(v, e, 2)
    if c2 <= 0:
        raise ValueError(
            "EOS fit: energies are not convex in volume — the sweep does "
            "not bracket a minimum")
    v0 = -c1 / (2.0 * c2)
    p0 = [c0 + c1 * v0 + c2 * v0 ** 2, v0, 2.0 * c2 * v0, 4.0]
    popt, pcov = curve_fit(birch_murnaghan, v, e, p0=p0, maxfev=20000)
    e0, v0, b0, b0p = (float(x) for x in popt)
    resid = e - birch_murnaghan(v, *popt)
    return {
        "e0_ha": e0,
        "v0_bohr3": v0,
        "b0_ha_bohr3": b0,
        "b0_gpa": b0 * HA_BOHR3_TO_GPA,
        "b0_prime": b0p,
        "fit_rms_ha": float(np.sqrt(np.mean(resid ** 2))),
    }


def finalize(spec: CampaignSpec, artifacts: dict) -> dict:
    """Fold the volume-node artifacts into the BM fit."""
    vols, es, points = [], [], []
    for n in spec.nodes:
        art = artifacts.get(n.node_id)
        if art is None:
            continue
        v = float(n.meta["volume_bohr3"])
        e = float(art["energy_total"])
        vols.append(v)
        es.append(e)
        points.append({"node": n.node_id, "scale": n.meta["scale"],
                       "volume_bohr3": v, "energy_ha": e})
    if len(vols) < 4:
        raise ValueError(
            f"EOS finalize: only {len(vols)} of {len(spec.nodes)} volume "
            "points completed — not enough for the 4-parameter fit")
    fit = fit_birch_murnaghan(vols, es)
    return {"kind": "eos", "num_points": len(vols), "points": points, **fit}
