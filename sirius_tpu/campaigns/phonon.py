"""Finite-displacement Γ-point phonons as a campaign template.

The textbook frozen-phonon recipe (reference SIRIUS drives it through
its Python workflow layer; here it is a first-class campaign): one base
SCF at the equilibrium geometry, then ``6·N_moved`` displaced decks —
atom ``a`` moved by ``±h`` bohr along each Cartesian axis — every one a
child of the base node, warm-started from its converged density through
the delta-density handoff. All nodes share one compiled-executable
bucket (same lattice, cutoffs and ``ngk_pad_quantum``), so the marginal
cost of a displaced node is a warm SCF with zero compiles.

Finalization builds the force-constant matrix by central differences,

    C[3a+i, 3b+j] = -(F_bj(+h_ai) - F_bj(-h_ai)) / (2h),

symmetrizes it, enforces the acoustic sum rule (the self-term absorbs
minus the sum over partners, so uniform translations cost nothing), and
diagonalizes the mass-weighted dynamical matrix D = C/sqrt(m_a m_b).
Frequencies are reported in cm^-1 and THz; imaginary modes come out as
negative numbers (sign(λ)·sqrt(|λ|)).
"""

from __future__ import annotations

import json

import numpy as np

from sirius_tpu.campaigns.spec import (
    CampaignNode, CampaignSpec, CampaignSpecError,
)
from sirius_tpu.md.integrator import AMU_TO_AU

HA_TO_CM1 = 219474.6313702  # 1 Ha (= 1 a.u. angular frequency) in cm^-1
CM1_TO_THZ = 0.0299792458

_AXES = "xyz"


def deck_geometry(deck: dict):
    """(lattice[3,3] bohr, fractional positions[N,3]) of a deck.

    Mirrors serve/scheduler.py::build_job_context for ``synthetic``
    decks and config/schema.py for ``unit_cell`` decks; campaigns must
    derive displaced nodes from the same geometry the scheduler will
    build."""
    syn = deck.get("synthetic")
    if isinstance(syn, dict) or "synthetic" in deck:
        syn = syn or {}
        a = float(syn.get("a", 10.26))
        lattice = a / 2 * np.array([[0.0, 1, 1], [1, 0, 1], [1, 1, 0]])
        positions = np.asarray(
            syn.get("positions", [[0.0, 0, 0], [0.25, 0.25, 0.25]]),
            dtype=np.float64)
        n = int(syn.get("supercell", 1))
        if n > 1:
            shifts = np.array(
                [[i, j, k] for i in range(n)
                 for j in range(n) for k in range(n)], dtype=np.float64)
            positions = (
                (positions[None, :, :] + shifts[:, None, :]) / n
            ).reshape(-1, 3)
            lattice = lattice * n
        return lattice, positions
    uc = deck.get("unit_cell")
    if isinstance(uc, dict) and uc.get("lattice_vectors"):
        scale = float(uc.get("lattice_vectors_scale", 1.0))
        lattice = np.asarray(uc["lattice_vectors"], dtype=np.float64) * scale
        pos = []
        for sites in (uc.get("atoms") or {}).values():
            pos.extend([list(map(float, s[:3])) for s in sites])
        return lattice, np.asarray(pos, dtype=np.float64)
    raise CampaignSpecError(
        "deck has neither a 'synthetic' section nor unit_cell "
        "lattice_vectors: cannot derive displaced geometries")


def with_positions(deck: dict, positions) -> dict:
    """A deep-copied deck at the given fractional positions."""
    out = json.loads(json.dumps(deck))
    pos = np.asarray(positions, dtype=np.float64).tolist()
    if isinstance(out.get("synthetic"), dict) or "synthetic" in out:
        syn = dict(out.get("synthetic") or {})
        syn["positions"] = pos
        out["synthetic"] = syn
        return out
    uc = dict(out["unit_cell"])
    atoms = uc.get("atoms") or {}
    i = 0
    new_atoms = {}
    for label, sites in atoms.items():
        n = len(sites)
        new_atoms[label] = pos[i:i + n]
        i += n
    uc["atoms"] = new_atoms
    out["unit_cell"] = uc
    return out


def _with_forces(deck: dict) -> dict:
    out = json.loads(json.dumps(deck))
    ctl = dict(out.get("control") or {})
    ctl["print_forces"] = True
    out["control"] = ctl
    return out


def node_id_for(atom: int, axis: int, sign: int) -> str:
    return f"d{atom}{_AXES[axis]}{'p' if sign > 0 else 'm'}"


def phonon_campaign(base_deck: dict, displacement: float = 0.01,
                    atoms: list[int] | None = None,
                    campaign_id: str = "phonon") -> CampaignSpec:
    """CampaignSpec for Γ-point finite-displacement phonons.

    ``displacement`` is the Cartesian step in bohr; ``atoms`` restricts
    which atoms are displaced (default: all — restrict only when
    symmetry or cost arguments apply, e.g. chaos/bench runs)."""
    lattice, positions = deck_geometry(base_deck)
    natoms = len(positions)
    moved = list(range(natoms)) if atoms is None else sorted(set(atoms))
    for a in moved:
        if not 0 <= a < natoms:
            raise CampaignSpecError(
                f"phonon_campaign: atom index {a} out of range "
                f"(0..{natoms - 1})")
    h = float(displacement)
    if h <= 0:
        raise CampaignSpecError("phonon_campaign: displacement must be > 0")
    inv_lat = np.linalg.inv(lattice)
    base = _with_forces(base_deck)
    nodes = [CampaignNode(node_id="base", deck=base)]
    from sirius_tpu.campaigns.handoff import uniform_translation

    seen: list[tuple[str, np.ndarray]] = []  # displaced (node_id, pos)
    for a in moved:
        for i in range(3):
            dfrac = h * inv_lat[i]  # cart h*e_i in fractional coords
            for s in (+1, -1):
                pos = positions.copy()
                pos[a] = pos[a] + s * dfrac
                # a displaced geometry that is an earlier node rigidly
                # translated (2-atom cell: moving atom 1 by +h IS moving
                # atom 0 by -h plus a uniform shift) warm-starts from THAT
                # node: the handoff detects the translation and hands the
                # child the exactly phase-twisted converged fields, so it
                # converges in O(1) iterations instead of re-grinding the
                # displacement response
                src = next(
                    (nid for nid, p in seen
                     if uniform_translation(p, pos) is not None), "base")
                nodes.append(CampaignNode(
                    node_id=node_id_for(a, i, s),
                    deck=with_positions(base, pos),
                    parents=[src] if src != "base" else ["base"],
                    warm_from=src,
                    displaced=True,
                    meta={"atom": a, "axis": i, "sign": s,
                          **({"translation_of": src}
                             if src != "base" else {})},
                ))
                seen.append((node_id_for(a, i, s), pos))
    return CampaignSpec(
        campaign_id=campaign_id, kind="phonon", nodes=nodes,
        meta={"displacement": h, "natoms": natoms, "atoms": moved},
    )


def finalize(spec: CampaignSpec, artifacts: dict) -> dict:
    """Fold the node artifacts into Γ frequencies.

    ``artifacts`` maps node_id -> the dict campaigns/handoff.py
    ``load_artifact`` returns (so finalization works equally from live
    results and from a journal-replayed campaign's on-disk state)."""
    h = float(spec.meta["displacement"])
    moved = list(spec.meta["atoms"])
    base = artifacts.get("base")
    if base is None:
        raise ValueError("phonon finalize: base node artifact missing")
    natoms = len(np.asarray(base["positions"]))
    masses = np.asarray(base["masses_amu"], dtype=np.float64) * AMU_TO_AU
    if set(moved) != set(range(natoms)):
        raise ValueError(
            "phonon finalize: the dynamical matrix needs every atom "
            f"displaced (moved {moved}, natoms {natoms})")
    n3 = 3 * natoms
    C = np.zeros((n3, n3))
    for a in moved:
        for i in range(3):
            pair = []
            for s in (+1, -1):
                nid = node_id_for(a, i, s)
                art = artifacts.get(nid)
                if art is None or art.get("forces") is None:
                    raise ValueError(
                        f"phonon finalize: node {nid} has no forces "
                        "(control.print_forces off, or the node never ran)")
                pair.append(np.asarray(art["forces"], dtype=np.float64))
            fp, fm = pair
            C[3 * a + i, :] = -(fp - fm).reshape(-1) / (2.0 * h)
    asr_violation = float(np.max(np.abs(
        C.reshape(n3, natoms, 3).sum(axis=1))))
    C = 0.5 * (C + C.T)
    # acoustic sum rule: uniform translation must be a zero mode
    for a in range(natoms):
        for i in range(3):
            row = C[3 * a + i].reshape(natoms, 3)
            C[3 * a + i, 3 * a:3 * a + 3] -= row.sum(axis=0)
    herm_err = float(np.max(np.abs(C - C.T)))
    sqrt_m = np.sqrt(np.repeat(masses, 3))
    D = C / np.outer(sqrt_m, sqrt_m)
    D = 0.5 * (D + D.T)
    evals = np.linalg.eigvalsh(D)
    omega_au = np.sign(evals) * np.sqrt(np.abs(evals))
    freq_cm1 = omega_au * HA_TO_CM1
    acoustic = int(np.sum(np.abs(freq_cm1) < 5.0))
    return {
        "kind": "phonon",
        "displacement_bohr": h,
        "natoms": natoms,
        "masses_amu": (np.asarray(base["masses_amu"])).tolist(),
        "frequencies_cm1": freq_cm1.tolist(),
        "frequencies_thz": (freq_cm1 * CM1_TO_THZ).tolist(),
        "num_acoustic_near_zero": acoustic,
        "asr_violation_ha_bohr2": asr_violation,
        "symmetrization_error": herm_err,
        "base_energy_ha": float(base["energy_total"]),
    }
