"""Submit a CampaignSpec to a ServeEngine and fold the results.

``submit_campaign`` walks the spec in topological order and submits one
serve job per node, carrying the DAG metadata the queue needs
(``parents`` for dependency admission) and the handoff plumbing the
scheduler needs (``handoff_in``/``handoff_out`` artifact paths). With a
journaled engine every edge is durable: a SIGKILL mid-campaign replays
the un-finished nodes with their dependencies intact
(``resume_campaign`` re-attaches a handle to the replayed graph), and
completed nodes are *not* re-run — their artifacts on disk are what
``finalize`` reads.

Observability: ``campaign_submit`` / ``campaign_node_done`` /
``campaign_done`` events carry the campaign id; metrics stay at
bounded cardinality (``campaign_nodes_total{outcome}``,
``campaign_wall_seconds{kind}``) because a per-campaign label is
unbounded under real traffic — per-campaign progress lives in the
event stream and ``CampaignHandle.status()``.
"""

from __future__ import annotations

import time

from sirius_tpu.campaigns import chain as chain_mod
from sirius_tpu.campaigns import eos as eos_mod
from sirius_tpu.campaigns import handoff as handoff_mod
from sirius_tpu.campaigns import phonon as phonon_mod
from sirius_tpu.campaigns.spec import CampaignSpec
from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs import spans as obs_spans
from sirius_tpu.obs import tracing as obs_tracing
from sirius_tpu.serve.queue import Job, JobStatus

_NODES = obs_metrics.REGISTRY.counter(
    "campaign_nodes_total", "campaign node outcomes by template kind")
_WALL = obs_metrics.REGISTRY.histogram(
    "campaign_wall_seconds", "submit-to-finalize campaign wall time")


def _generic_finalize(spec: CampaignSpec, artifacts: dict) -> dict:
    return {
        "kind": spec.kind,
        "energies_ha": {
            nid: float(art["energy_total"])
            for nid, art in artifacts.items() if art is not None
        },
    }


FINALIZERS = {
    "phonon": phonon_mod.finalize,
    "eos": eos_mod.finalize,
    "chain": chain_mod.finalize,
    "generic": _generic_finalize,
}


class CampaignHandle:
    """A submitted (or replayed) campaign: wait, inspect, finalize."""

    def __init__(self, engine, spec: CampaignSpec, workdir: str,
                 jobs: dict[str, Job], prior_status: dict[str, str]):
        self.engine = engine
        self.spec = spec
        self.workdir = workdir
        #: node_id -> live Job (replay: only the nodes that re-entered
        #: the queue; nodes terminal in a previous process are absent)
        self.jobs = jobs
        #: node_id -> terminal status settled in a previous process
        self.prior_status = prior_status
        self.submitted_at = time.time()

    def node_status(self, node_id: str) -> str | None:
        job = self.jobs.get(node_id)
        if job is not None:
            return job.status
        return self.prior_status.get(node_id)

    def wait(self, timeout: float | None = None) -> bool:
        """Block until every live node is terminal. False on timeout."""
        deadline = None if timeout is None else time.time() + timeout
        for job in self.jobs.values():
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return False
            if not job.wait(remaining):
                return False
        return True

    def status(self) -> dict:
        nodes = {n.node_id: self.node_status(n.node_id)
                 for n in self.spec.nodes}
        done = sum(s == JobStatus.DONE for s in nodes.values())
        terminal = sum(
            s in (JobStatus.DONE, JobStatus.FAILED, JobStatus.ABORTED,
                  JobStatus.SKIPPED_UPSTREAM)
            for s in nodes.values())
        return {
            "campaign_id": self.spec.campaign_id,
            "kind": self.spec.kind,
            "nodes": nodes,
            "num_nodes": len(nodes),
            "num_done": done,
            "num_terminal": terminal,
        }

    def artifacts(self) -> dict:
        """node_id -> on-disk artifact dict (None when absent)."""
        return {
            n.node_id: handoff_mod.load_artifact(handoff_mod.artifact_path(
                self.workdir, self.spec.campaign_id, n.node_id))
            for n in self.spec.nodes
        }

    def finalize(self) -> dict:
        """Fold the artifacts through the template finalizer. Reads from
        disk, so it works identically after a journal replay."""
        finalizer = FINALIZERS.get(self.spec.kind, _generic_finalize)
        with obs_spans.span("campaign.finalize", template=self.spec.kind):
            summary = finalizer(self.spec, self.artifacts())
        wall = time.time() - self.submitted_at
        _WALL.observe(wall, kind=self.spec.kind)
        st = self.status()
        obs_events.emit(
            "campaign_done", campaign_id=self.spec.campaign_id,
            campaign_kind=self.spec.kind, num_done=st["num_done"],
            num_nodes=st["num_nodes"], wall_s=wall)
        return summary

    def result(self) -> dict:
        """Status + finalizer output (finalizer errors are reported, not
        raised: a partially-failed campaign still has a result)."""
        out = self.status()
        out["scf_iterations"] = {
            nid: job.result.get("num_scf_iterations")
            for nid, job in self.jobs.items()
            if job.status == JobStatus.DONE and isinstance(job.result, dict)
        }
        # per-node convergence-forecast record (obs/forecast.py via
        # run_scf): forecast accuracy across a DAG is a campaign-level
        # health signal — a template whose nodes systematically run past
        # their forecasts is mis-budgeted
        out["forecast"] = {
            nid: job.result.get("forecast")
            for nid, job in self.jobs.items()
            if job.status == JobStatus.DONE and isinstance(job.result, dict)
        }
        try:
            out["summary"] = self.finalize()
        except (ValueError, KeyError) as e:
            out["summary"] = None
            out["finalize_error"] = str(e)
        return out


def _node_outcome_hook(job: Job) -> None:
    _NODES.inc(outcome=job.status)
    obs_events.emit(
        "campaign_node_done", campaign_id=job.campaign_id,
        node=job.node_id, job_id=job.id, status=job.status,
        attempts=job.attempts)


def submit_campaign(engine, spec: CampaignSpec,
                    workdir: str | None = None,
                    priority: int = 0) -> CampaignHandle:
    """Validate and submit every node of ``spec`` (topological order, so
    a parent is always journaled before its children)."""
    spec.validate()
    workdir = workdir or engine.workdir
    cid = spec.campaign_id
    # one trace for the whole DAG: every node job, every retry, every SCF
    # span of the campaign carries this id (inherit an ambient trace when
    # the caller already opened one)
    trace_id = obs_tracing.current_trace_id() or obs_tracing.new_trace_id()
    obs_events.emit(
        "campaign_submit", campaign_id=cid, campaign_kind=spec.kind,
        num_nodes=len(spec.nodes), trace_id=trace_id,
        nodes=[n.node_id for n in spec.nodes],
        # the DAG shape, for the critical-path analyzer (obs/timeline.py)
        edges={n.node_id: list(n.parents) for n in spec.nodes})
    jobs: dict[str, Job] = {}
    for node in spec.topo_order():
        handoff_in = None
        src = node.warm_from or (node.parents[0] if node.parents else None)
        if src is not None:
            handoff_in = {
                "path": handoff_mod.artifact_path(workdir, cid, src),
                "displaced": node.displaced,
                "adopt_positions": node.adopt_positions,
            }
        job = engine.submit(
            node.deck, job_id=spec.job_id(node.node_id),
            priority=priority, base_dir=workdir,
            parents=[spec.job_id(p) for p in node.parents],
            campaign_id=cid, node_id=node.node_id,
            handoff_in=handoff_in,
            handoff_out=handoff_mod.artifact_path(
                workdir, cid, node.node_id),
            trace_id=trace_id,
        )
        job.add_terminal_hook(_node_outcome_hook)
        jobs[node.node_id] = job
    return CampaignHandle(engine, spec, workdir, jobs, {})


def resume_campaign(engine, spec: CampaignSpec,
                    workdir: str | None = None) -> CampaignHandle:
    """Re-attach to a journal-replayed campaign: nodes the previous
    process finished stay finished (their terminal status comes from the
    journal, their results from the handoff artifacts on disk); only the
    replayed jobs are waited on."""
    spec.validate()
    workdir = workdir or engine.workdir
    jobs: dict[str, Job] = {}
    prior: dict[str, str] = {}
    for node in spec.nodes:
        jid = spec.job_id(node.node_id)
        job = engine.queue.jobs.get(jid)
        if job is not None:
            job.add_terminal_hook(_node_outcome_hook)
            jobs[node.node_id] = job
        else:
            status = engine.queue.external_parent_status.get(jid)
            if status is not None:
                prior[node.node_id] = status
    obs_events.emit(
        "campaign_resume", campaign_id=spec.campaign_id,
        campaign_kind=spec.kind,
        replayed=sorted(jobs), settled=sorted(prior))
    return CampaignHandle(engine, spec, workdir, jobs, prior)
