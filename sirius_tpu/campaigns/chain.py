"""Relax→SCF chain as a campaign template.

Node ``relax`` runs fixed-cell BFGS (dft/relax.py, dispatched by the
slice scheduler through the deck's top-level ``task: "relax"`` key) and
records its *final* geometry and converged state in its handoff
artifact. Node ``scf`` then runs at that relaxed geometry
(``adopt_positions``) — typically with tighter tolerances or extra
outputs — warm-started from the relaxed density/wave functions, so the
production-quality SCF costs a handful of iterations instead of a full
cold solve.
"""

from __future__ import annotations

import json

from sirius_tpu.campaigns.spec import CampaignNode, CampaignSpec


def relax_scf_campaign(base_deck: dict, max_steps: int = 10,
                       force_tol: float = 1e-4,
                       final_overrides: dict | None = None,
                       campaign_id: str = "chain") -> CampaignSpec:
    """Two-node chain: relax the structure, then one final SCF at the
    relaxed positions. ``final_overrides`` is merged section-by-section
    into the final deck (e.g. {"parameters": {"energy_tol": 1e-12}})."""
    relax_deck = json.loads(json.dumps(base_deck))
    relax_deck["task"] = "relax"
    relax_deck["relax"] = {
        "max_steps": int(max_steps), "force_tol": float(force_tol)}
    final_deck = json.loads(json.dumps(base_deck))
    final_deck.pop("task", None)
    for section, over in (final_overrides or {}).items():
        if isinstance(over, dict):
            merged = dict(final_deck.get(section) or {})
            merged.update(over)
            final_deck[section] = merged
        else:
            final_deck[section] = over
    return CampaignSpec(
        campaign_id=campaign_id, kind="chain",
        nodes=[
            CampaignNode(node_id="relax", deck=relax_deck),
            CampaignNode(
                node_id="scf", deck=final_deck, parents=["relax"],
                warm_from="relax", displaced=True, adopt_positions=True),
        ],
        meta={"max_steps": int(max_steps), "force_tol": float(force_tol)},
    )


def finalize(spec: CampaignSpec, artifacts: dict) -> dict:
    relax = artifacts.get("relax")
    scf = artifacts.get("scf")
    if relax is None or scf is None:
        raise ValueError("chain finalize: relax and scf artifacts required")
    out = {
        "kind": "chain",
        "relaxed_positions": [
            [float(x) for x in row] for row in relax["positions"]],
        "relax_energy_ha": float(relax["energy_total"]),
        "final_energy_ha": float(scf["energy_total"]),
        "final_scf_iterations": int(scf["num_scf_iterations"]),
    }
    summary = relax.get("summary") or {}
    if isinstance(summary.get("relax"), dict):
        out["relax"] = summary["relax"]
    return out
