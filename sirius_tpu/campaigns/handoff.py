"""Cross-job warm-start handoff: the durable artifact a campaign parent
leaves for its children.

After a campaign node converges, the slice scheduler writes one ``.npz``
per node (atomic tmp+rename, like io/checkpoint.py) holding the
converged density and wave functions, the superposition-of-atoms density
at the parent's positions, the positions/forces/energy, and a small JSON
summary. A child node loads the artifact and turns it into a
``run_scf(initial_guess=(rho, psi))`` pair:

- same positions -> the parent density/psi verbatim;
- displaced positions -> the QE-style delta-density transform
  (dft/geometry.py::delta_density_guess): keep the parent's bonding
  delta ``rho - rho_atomic(old)``, move the free-atom part to the new
  positions via the child context's own ``rho_atomic_g``.

Degradation is always graceful: a missing artifact, a shape mismatch
(e.g. EOS nodes at different volumes have different G sets), or
corruption (non-finite values — the ``campaign.handoff_corrupt`` fault
site injects exactly this) downgrade to a cold start, never a failed
job. run_scf raises ValueError on shape-mismatched guesses and the
scheduler classifies ValueError as a permanent bad-deck failure, so
every shape is validated here *before* it reaches run_scf.

The artifact intentionally carries the node's scalar results (energy,
forces, iteration count) too: campaign finalizers (phonon dynamical
matrix, EOS fit) read them from disk, so a campaign that was SIGKILLed
and replayed can still finalize even though the completed nodes'
in-memory ``job.result`` died with the first process.
"""

from __future__ import annotations

import json
import os

import numpy as np

from sirius_tpu.obs import tracing as obs_tracing
from sirius_tpu.obs.log import get_logger
from sirius_tpu.utils import faults

logger = get_logger("campaigns")

ARTIFACT_VERSION = 1


class HandoffError(RuntimeError):
    """The handoff artifact exists but is unusable (corrupt/non-finite).
    Callers treat this as a cold start, not a job failure."""


def artifact_path(workdir: str, campaign_id: str, node_id: str) -> str:
    """Canonical artifact path for a node (journal-stable: replayed jobs
    recompute the same path from the same ids)."""
    return os.path.join(
        str(workdir), f"handoff.{campaign_id}.{node_id}.npz")


def save_artifact(path: str, ctx, result: dict, state: dict | None = None,
                  positions=None) -> str:
    """Write a node's handoff artifact atomically; returns ``path``.

    ``state`` is the run_scf ``_state`` dict (rho_g/mag_g/psi); without
    it the artifact still carries the scalars the finalizers need.
    ``positions`` overrides the context positions (fractional) — the
    relax template records its *final* geometry, not its starting one."""
    pos = np.asarray(
        positions if positions is not None else ctx.unit_cell.positions,
        dtype=np.float64)
    from sirius_tpu.md.integrator import AMU_TO_AU, masses_au

    summary = {
        "energy_total": float(result["energy"]["total"]),
        "num_scf_iterations": int(result.get("num_scf_iterations") or 0),
        "converged": bool(result.get("converged", False)),
    }
    if isinstance(result.get("relax"), dict):
        summary["relax"] = {
            k: v for k, v in result["relax"].items() if k != "history"}
    arrs: dict = {
        "version": np.int64(ARTIFACT_VERSION),
        "positions": pos,
        "masses_amu": masses_au(ctx.unit_cell) / AMU_TO_AU,
        "energy_total": np.float64(summary["energy_total"]),
        "num_scf_iterations": np.int64(summary["num_scf_iterations"]),
        "summary_json": np.str_(json.dumps(summary, default=float)),
    }
    # the campaign's trace rides in the artifact so a child job loaded in
    # a FRESH process (resume after SIGKILL) can continue the parent's
    # end-to-end trace (obs/tracing.py)
    tid = obs_tracing.current_trace_id()
    if tid is not None:
        arrs["trace_id"] = np.str_(tid)
    forces = result.get("forces")
    if isinstance(forces, dict):
        forces = forces.get("total")
    if forces is not None:
        arrs["forces"] = np.asarray(forces, dtype=np.float64)
    if state is not None and state.get("rho_g") is not None:
        arrs["rho_g"] = np.asarray(state["rho_g"], dtype=np.complex128)
        # the free-atom superposition at the PARENT's positions,
        # normalized exactly like the child's cold start will be — the
        # "old" term of delta_density_guess
        from sirius_tpu.dft.density import initial_density_g

        arrs["rho_atomic_g"] = np.asarray(
            initial_density_g(ctx), dtype=np.complex128)
        if state.get("psi") is not None:
            arrs["psi"] = np.asarray(state["psi"], dtype=np.complex128)
        if state.get("mag_g") is not None:
            arrs["mag_g"] = np.asarray(state["mag_g"], dtype=np.complex128)
        scf = state.get("scf")
        if isinstance(scf, dict) and scf.get("mix_x") is not None:
            # the parent's quasi-Newton mixer history: a multisecant model
            # of the SCF Jacobian the children import so their first mix()
            # is already Anderson, not a plain damped step — this, not the
            # density alone, is where most of the warm-start iteration
            # savings come from
            arrs["mix_x"] = np.asarray(scf["mix_x"], dtype=np.complex128)
            arrs["mix_f"] = np.asarray(scf["mix_f"], dtype=np.complex128)
            if scf.get("res_tol") is not None:
                arrs["res_tol"] = np.float64(scf["res_tol"])
    tmp = f"{path}.tmp{os.getpid()}"
    try:
        with open(tmp, "wb") as fh:
            np.savez(fh, **arrs)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            try:
                os.remove(tmp)
            except OSError:
                pass
    return path


def artifact_trace_id(path: str) -> str | None:
    """Just the trace_id stored in an artifact (None when absent) —
    cheap: npz members load lazily, the arrays stay on disk."""
    if not path or not os.path.exists(path):
        return None
    try:
        with np.load(path, allow_pickle=False) as data:
            if "trace_id" in data.files:
                return str(data["trace_id"])
    except Exception:
        return None
    return None


def load_artifact(path: str) -> dict | None:
    """The artifact as a plain dict (None when the file is absent)."""
    if not os.path.exists(path):
        return None
    out: dict = {}
    with np.load(path, allow_pickle=False) as data:
        for k in data.files:
            out[k] = data[k]
    if "summary_json" in out:
        try:
            out["summary"] = json.loads(str(out.pop("summary_json")))
        except ValueError:
            out["summary"] = {}
    return out


def uniform_translation(pos_old, pos_new, atol: float = 1e-10):
    """The single fractional vector t with pos_new = pos_old + t for EVERY
    atom (mod lattice), or None. A uniform translation is an exact
    symmetry of the Hamiltonian, so a parent artifact at pos_old is an
    exact converged solution at pos_new after a G-space phase twist —
    the strongest warm start a campaign edge can carry (finite-
    displacement templates exploit it: in a 2-atom cell, displacing atom
    1 by +h is the rigid translation of displacing atom 0 by -h)."""
    pos_old = np.asarray(pos_old, dtype=np.float64)
    pos_new = np.asarray(pos_new, dtype=np.float64)
    if pos_old.shape != pos_new.shape or pos_old.ndim != 2:
        return None
    d = pos_new - pos_old
    rel = d - d[0]
    rel -= np.round(rel)  # compare mod 1: fractional coords may wrap
    if np.max(np.abs(rel)) > atol:
        return None
    return d[0].copy()


def load_guess(path: str, ctx, displaced: bool = True):
    """``(rho, psi, scf_hint)`` for run_scf(initial_guess=) from a parent
    artifact; ``scf_hint`` is the parent's mixer-history/band-tolerance
    dict (None when the artifact predates it or the history is unusable).

    Returns None for a cold start (artifact absent, densities not kept,
    or every field shape-incompatible with the child context). Raises
    HandoffError when the artifact is damaged (unreadable / non-finite
    after the ``campaign.handoff_corrupt`` fault) — the caller logs it
    and cold-starts. Every shape is validated against ``ctx`` here so a
    mismatch degrades instead of tripping run_scf's ValueError, which
    the scheduler would misread as a permanently bad deck."""
    try:
        art = load_artifact(path)
    except (OSError, ValueError) as e:
        raise HandoffError(f"unreadable handoff artifact {path}: {e}") from e
    if art is None or art.get("rho_g") is None:
        return None
    rho = np.asarray(art["rho_g"], dtype=np.complex128)
    rho = faults.corrupt("campaign.handoff_corrupt", 0, rho)
    expected = ctx.rho_atomic_g.shape
    if rho.shape != expected:
        logger.info(
            "handoff %s: density shape %s does not match the child G set "
            "%s — cold start", path, rho.shape, expected)
        return None
    pos_old = np.asarray(art.get("positions"))
    pos_new = np.asarray(ctx.unit_cell.positions)
    moved = displaced and not np.allclose(pos_old, pos_new, atol=1e-12)
    trans = uniform_translation(pos_old, pos_new) if moved else None
    if moved and trans is not None:
        # exact symmetry: rho'(r) = rho(r - t) -> rho'_G = rho_G e^{-2pi i
        # G.t} (same convention as the structure factors, dft/density.py);
        # the child starts AT the parent's converged fixed point
        rho = rho * np.exp(-2j * np.pi
                           * (np.asarray(ctx.gvec.millers) @ trans))
    elif moved:
        from sirius_tpu.dft.density import initial_density_g
        from sirius_tpu.dft.geometry import delta_density_guess

        rho_at_old = art.get("rho_atomic_g")
        if rho_at_old is not None and rho_at_old.shape == expected:
            rho = delta_density_guess(
                rho, rho_at_old, initial_density_g(ctx))
    if not np.all(np.isfinite(rho.view(np.float64))):
        raise HandoffError(
            f"handoff artifact {path}: non-finite density (corrupt)")
    psi = art.get("psi")
    if psi is not None:
        want = (ctx.gkvec.num_kpoints, ctx.num_spins, ctx.num_bands,
                ctx.gkvec.ngk_max)
        if psi.shape != want:
            psi = None
        elif not np.all(np.isfinite(psi.view(np.float64))):
            raise HandoffError(
                f"handoff artifact {path}: non-finite psi (corrupt)")
        elif trans is not None:
            # Bloch coefficients at G+k pick up e^{-2pi i (G+k).t}
            mk = (np.asarray(ctx.gkvec.millers)
                  + np.asarray(ctx.gkvec.kpoints)[:, None, :])
            psi = psi * np.exp(-2j * np.pi * (mk @ trans))[:, None, None, :]
    scf_hint = None
    hx, hf = art.get("mix_x"), art.get("mix_f")
    if trans is not None:
        # the translated guess is already the (phase-twisted) fixed point;
        # the parent's untwisted mixer history would point the model at
        # the untranslated problem, so it stays home
        hx = hf = None
    if (hx is not None and hf is not None and hx.ndim == 2
            and hx.shape == hf.shape
            and np.all(np.isfinite(hx.view(np.float64)))
            and np.all(np.isfinite(hf.view(np.float64)))):
        # run_scf itself re-validates the packed length against its own
        # mix vector and drops the hint on mismatch, so a usable density
        # with an unusable history still warm-starts. No geometry
        # translation is needed: run_scf turns the rows into successive
        # DIFFERENCES (secant pairs, Mixer.import_secants), and constant
        # shifts cancel in differences.
        scf_hint = {"mix_x": hx, "mix_f": hf}
        if art.get("res_tol") is not None:
            scf_hint["res_tol"] = float(art["res_tol"])
    return (rho, psi, scf_hint)


def adopt_positions(deck: dict, path: str) -> dict:
    """The deck with its positions replaced by the parent artifact's
    (relax→SCF chains run the child at the relaxed geometry). Supports
    the ``synthetic`` section and ``unit_cell.atoms`` decks; raises
    OSError when the artifact is missing — running the chain's final SCF
    at the *unrelaxed* geometry would be a silently wrong answer, and
    OSError is a retryable failure class in the scheduler."""
    art = load_artifact(path)
    if art is None:
        raise OSError(f"handoff artifact not found: {path}")
    pos = np.asarray(art["positions"], dtype=np.float64)
    deck = json.loads(json.dumps(deck))  # deep copy, JSON-pure
    if isinstance(deck.get("synthetic"), dict) or "synthetic" in deck:
        syn = dict(deck.get("synthetic") or {})
        syn["positions"] = pos.tolist()
        deck["synthetic"] = syn
        return deck
    uc = deck.get("unit_cell")
    if isinstance(uc, dict) and isinstance(uc.get("atoms"), dict):
        atoms = uc["atoms"]
        i = 0
        out: dict = {}
        for label, sites in atoms.items():
            n = len(sites)
            out[label] = pos[i:i + n].tolist()
            i += n
        if i != len(pos):
            raise HandoffError(
                f"adopt_positions: deck has {i} atoms, artifact has "
                f"{len(pos)}")
        uc = dict(uc)
        uc["atoms"] = out
        deck["unit_cell"] = uc
        return deck
    raise HandoffError(
        "adopt_positions: deck has neither a 'synthetic' section nor "
        "unit_cell.atoms")
