"""Lightweight hierarchical profiler + work counters.

Replaces the reference's vendored rt_graph timers (src/core/rt_graph.hpp,
PROFILE macros in core/profiler.hpp:37-61) and the self-reported work
counters (evp_work_count / num_loc_op_applied, davidson.hpp:834,
sirius.scf.cpp:232-234). Device-side profiling composes with
jax.profiler traces; this registry covers the host-orchestrated spans and
produces the timers.json-style summary the reference emits at finalize.
"""

from __future__ import annotations

import contextlib
import time
from collections import defaultdict

_STACK: list[str] = []
_TIMINGS: dict[str, list[float]] = defaultdict(list)
counters: dict[str, float] = defaultdict(float)


@contextlib.contextmanager
def profile(name: str):
    """Nested scoped timer: with profile("scf::band_solve"): ..."""
    _STACK.append(name)
    full = "/".join(_STACK)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _TIMINGS[full].append(time.perf_counter() - t0)
        _STACK.pop()


def add_time(name: str, dt: float) -> None:
    """Record an externally-measured span (same registry as profile())."""
    _TIMINGS[name].append(dt)


def reset_timers() -> None:
    _TIMINGS.clear()
    counters.clear()


def timer_report() -> dict:
    """{name: {count, total, avg, min, max}} sorted by total time."""
    out = {}
    for name, ts in sorted(_TIMINGS.items(), key=lambda kv: -sum(kv[1])):
        out[name] = {
            "count": len(ts),
            "total": sum(ts),
            "avg": sum(ts) / len(ts),
            "min": min(ts),
            "max": max(ts),
        }
    return out
