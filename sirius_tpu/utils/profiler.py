"""Lightweight hierarchical profiler + work counters.

Replaces the reference's vendored rt_graph timers (src/core/rt_graph.hpp,
PROFILE macros in core/profiler.hpp:37-61) and the self-reported work
counters (evp_work_count / num_loc_op_applied, davidson.hpp:834,
sirius.scf.cpp:232-234). Device-side profiling composes with
jax.profiler traces; this registry covers the host-orchestrated spans and
produces the timers.json-style summary the reference emits at finalize.

Concurrency: the serving engine (sirius_tpu/serve/) runs several SCF jobs
on worker threads at once. Span stacks, timings, and counters are all
thread-local so concurrent jobs cannot interleave each other's span trees
or double-count work; ``collect()`` merges a snapshot across every thread
that has recorded anything. The per-thread views keep the historical
single-threaded semantics: ``reset_timers()`` / ``timer_report()`` /
``dict(counters)`` inside a job see only that job's numbers.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import defaultdict
from collections.abc import MutableMapping

_tls = threading.local()

# Registry of every thread's (timings, counters) dicts so collect() can
# produce a merged snapshot. Guarded by _registry_lock; entries are keyed
# by thread ident and carry the thread name for attribution.
_registry_lock = threading.Lock()
_registry: dict[int, dict] = {}


def _local() -> dict:
    """This thread's profiler state, registering it on first touch."""
    state = getattr(_tls, "state", None)
    if state is None:
        t = threading.current_thread()
        state = {
            "name": t.name,
            "stack": [],
            "timings": defaultdict(list),
            "counters": defaultdict(float),
        }
        _tls.state = state
        with _registry_lock:
            _registry[t.ident] = state
    return state


class _ThreadLocalCounters(MutableMapping):
    """Mapping facade over the calling thread's counter dict.

    Modules do ``from ...profiler import counters`` and then
    ``counters["x"] += 1`` / ``dict(counters)``; both must keep working
    while resolving to per-thread storage at access time.
    """

    def _d(self) -> dict:
        return _local()["counters"]

    def __getitem__(self, key):
        return self._d()[key]

    def __setitem__(self, key, value):
        self._d()[key] = value

    def __delitem__(self, key):
        del self._d()[key]

    def __iter__(self):
        return iter(dict(self._d()))

    def __len__(self):
        return len(self._d())

    def __repr__(self):
        return repr(dict(self._d()))

    def clear(self):
        self._d().clear()


counters = _ThreadLocalCounters()


@contextlib.contextmanager
def profile(name: str):
    """Nested scoped timer: with profile("scf::band_solve"): ..."""
    state = _local()
    stack = state["stack"]
    stack.append(name)
    full = "/".join(stack)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        state["timings"][full].append(time.perf_counter() - t0)
        stack.pop()


def add_time(name: str, dt: float) -> None:
    """Record an externally-measured span (same registry as profile())."""
    _local()["timings"][name].append(dt)


def reset_timers() -> None:
    """Clear the calling thread's timings and counters (per-job reset)."""
    state = _local()
    state["timings"].clear()
    state["counters"].clear()


def _report(timings: dict[str, list[float]]) -> dict:
    out = {}
    for name, ts in sorted(timings.items(), key=lambda kv: -sum(kv[1])):
        out[name] = {
            "count": len(ts),
            "total": sum(ts),
            "avg": sum(ts) / len(ts),
            "min": min(ts),
            "max": max(ts),
        }
    return out


def timer_report() -> dict:
    """{name: {count, total, avg, min, max}} for the calling thread,
    sorted by total time."""
    return _report(_local()["timings"])


def collect() -> dict:
    """Merged cross-thread snapshot.

    Returns ``{"counters": summed, "timers": merged_report,
    "threads": {name: report}}``. Counter values are summed across
    threads; timing samples for the same span name are concatenated
    before the report statistics are computed.
    """
    with _registry_lock:
        states = [
            {
                "name": s["name"],
                "timings": {k: list(v) for k, v in s["timings"].items()},
                "counters": dict(s["counters"]),
            }
            for s in _registry.values()
        ]
    merged_counters: dict[str, float] = defaultdict(float)
    merged_timings: dict[str, list[float]] = defaultdict(list)
    per_thread: dict[str, dict] = {}
    for s in states:
        for k, v in s["counters"].items():
            merged_counters[k] += v
        for k, v in s["timings"].items():
            merged_timings[k].extend(v)
        if s["timings"] or s["counters"]:
            per_thread[s["name"]] = _report(s["timings"])
    return {
        "counters": dict(merged_counters),
        "timers": _report(merged_timings),
        "threads": per_thread,
    }
