"""Lightweight hierarchical profiler + work counters.

Replaces the reference's vendored rt_graph timers (src/core/rt_graph.hpp,
PROFILE macros in core/profiler.hpp:37-61) and the self-reported work
counters (evp_work_count / num_loc_op_applied, davidson.hpp:834,
sirius.scf.cpp:232-234). Device-side profiling composes with
jax.profiler traces; this registry covers the host-orchestrated spans and
produces the timers.json-style summary the reference emits at finalize.

Concurrency: the serving engine (sirius_tpu/serve/) runs several SCF jobs
on worker threads at once. Span stacks, timings, and counters are all
thread-local so concurrent jobs cannot interleave each other's span trees
or double-count work; ``collect()`` merges a snapshot across every thread
that has recorded anything. The per-thread views keep the historical
single-threaded semantics: ``reset_timers()`` / ``timer_report()`` /
``dict(counters)`` inside a job see only that job's numbers.

Registry lifetime: entries are keyed by a per-state uid (not
``thread.ident``, which the OS reuses after a thread dies — a recycled
ident would clobber a live worker's state) and hold only a weakref to
their thread. When a thread dies its numbers are folded into a single
``_retired`` aggregate and the entry is dropped, so a long-lived serve
process does not accumulate one registry entry per finished worker while
``collect()`` totals still include every thread that ever recorded.

Spans double as the backend for the obs metrics registry: each completed
``profile()`` span also lands in the ``span_seconds`` histogram
(labelled by span path), so the /metrics endpoint exposes the same
timer tree Prometheus-side.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
import weakref
from collections import defaultdict
from collections.abc import MutableMapping

from sirius_tpu.obs import metrics as _obs_metrics

_tls = threading.local()

# Registry of every thread's (timings, counters) dicts so collect() can
# produce a merged snapshot. Guarded by _registry_lock; entries are keyed
# by a unique state uid and carry a weakref to their owning thread so
# dead workers can be pruned into the _retired aggregate.
_registry_lock = threading.Lock()
_registry: dict[int, dict] = {}
_uid = itertools.count()
_retired = {
    "timings": defaultdict(list),
    "counters": defaultdict(float),
    "threads": 0,
}


def _prune_dead_locked() -> None:
    """Fold states of dead threads into _retired (lock must be held)."""
    dead = []
    for uid, state in _registry.items():
        t = state["thread"]()
        if t is None or not t.is_alive():
            dead.append(uid)
    for uid in dead:
        state = _registry.pop(uid)
        for k, v in state["timings"].items():
            _retired["timings"][k].extend(v)
        for k, v in state["counters"].items():
            _retired["counters"][k] += v
        _retired["threads"] += 1


def _local() -> dict:
    """This thread's profiler state, registering it on first touch."""
    state = getattr(_tls, "state", None)
    if state is None:
        t = threading.current_thread()
        state = {
            "name": t.name,
            "thread": weakref.ref(t),
            "stack": [],
            "timings": defaultdict(list),
            "counters": defaultdict(float),
        }
        _tls.state = state
        with _registry_lock:
            _prune_dead_locked()
            _registry[next(_uid)] = state
    return state


class _ThreadLocalCounters(MutableMapping):
    """Mapping facade over the calling thread's counter dict.

    Modules do ``from ...profiler import counters`` and then
    ``counters["x"] += 1`` / ``dict(counters)``; both must keep working
    while resolving to per-thread storage at access time.
    """

    def _d(self) -> dict:
        return _local()["counters"]

    def __getitem__(self, key):
        return self._d()[key]

    def __setitem__(self, key, value):
        self._d()[key] = value

    def __delitem__(self, key):
        del self._d()[key]

    def __iter__(self):
        return iter(dict(self._d()))

    def __len__(self):
        return len(self._d())

    def __repr__(self):
        return repr(dict(self._d()))

    def clear(self):
        self._d().clear()


counters = _ThreadLocalCounters()


@contextlib.contextmanager
def profile(name: str):
    """Nested scoped timer: with profile("scf::band_solve"): ..."""
    state = _local()
    stack = state["stack"]
    stack.append(name)
    full = "/".join(stack)
    t0 = time.perf_counter()
    try:
        yield
    finally:
        dt = time.perf_counter() - t0
        state["timings"][full].append(dt)
        stack.pop()
        _obs_metrics.REGISTRY.histogram(
            "span_seconds", "host-orchestrated profiler spans").observe(
                dt, span=full)


def add_time(name: str, dt: float) -> None:
    """Record an externally-measured span (same registry as profile())."""
    _local()["timings"][name].append(dt)
    _obs_metrics.REGISTRY.histogram(
        "span_seconds", "host-orchestrated profiler spans").observe(
            dt, span=name)


def reset_timers() -> None:
    """Clear the calling thread's timings and counters (per-job reset)."""
    state = _local()
    state["timings"].clear()
    state["counters"].clear()


def registry_size() -> int:
    """Live (non-retired) registry entries — one per thread that has
    recorded and not yet been pruned."""
    with _registry_lock:
        return len(_registry)


def prune_dead_threads() -> int:
    """Explicitly fold dead threads into the retired aggregate.
    Returns the number of live entries remaining."""
    with _registry_lock:
        _prune_dead_locked()
        return len(_registry)


def _report(timings: dict[str, list[float]]) -> dict:
    out = {}
    for name, ts in sorted(timings.items(), key=lambda kv: -sum(kv[1])):
        out[name] = {
            "count": len(ts),
            "total": sum(ts),
            "avg": sum(ts) / len(ts),
            "min": min(ts),
            "max": max(ts),
        }
    return out


def timer_report() -> dict:
    """{name: {count, total, avg, min, max}} for the calling thread,
    sorted by total time."""
    return _report(_local()["timings"])


def collect() -> dict:
    """Merged cross-thread snapshot.

    Returns ``{"counters": summed, "timers": merged_report,
    "threads": {name: report}}``. Counter values are summed across
    threads (including threads that have since died — their totals live
    on in the retired aggregate); timing samples for the same span name
    are concatenated before the report statistics are computed. Dead
    threads no longer appear individually under ``"threads"``; their
    merged numbers show up as ``"_retired"`` when non-empty.
    """
    with _registry_lock:
        _prune_dead_locked()
        states = [
            {
                "name": s["name"],
                "timings": {k: list(v) for k, v in s["timings"].items()},
                "counters": dict(s["counters"]),
            }
            for s in _registry.values()
        ]
        retired = {
            "timings": {k: list(v) for k, v in _retired["timings"].items()},
            "counters": dict(_retired["counters"]),
        }
    merged_counters: dict[str, float] = defaultdict(float)
    merged_timings: dict[str, list[float]] = defaultdict(list)
    per_thread: dict[str, dict] = {}
    for s in states:
        for k, v in s["counters"].items():
            merged_counters[k] += v
        for k, v in s["timings"].items():
            merged_timings[k].extend(v)
        if s["timings"] or s["counters"]:
            per_thread[s["name"]] = _report(s["timings"])
    for k, v in retired["counters"].items():
        merged_counters[k] += v
    for k, v in retired["timings"].items():
        merged_timings[k].extend(v)
    if retired["timings"] or retired["counters"]:
        per_thread["_retired"] = _report(retired["timings"])
    return {
        "counters": dict(merged_counters),
        "timers": _report(merged_timings),
        "threads": per_thread,
    }
