from sirius_tpu.utils.profiler import profile, timer_report, reset_timers, counters
