"""Deterministic fault injection for the SCF supervision/recovery machinery
(dft/recovery.py).

Every recovery branch — mixer-history flush, beta backoff, host fallback,
checkpoint-interrupted-save, resume-after-kill — must be drivable from a
test without waiting for a real divergence or a real preemption. A fault
plan arms named sites at specific iterations; the instrumented code calls
the hooks below, which are no-ops when nothing is armed (the common case:
one dict lookup against an empty plan).

Sites currently wired:
  scf.density        corrupt the freshly accumulated density (host or fused)
  scf.potential      corrupt the generated effective potential
  scf.evals          corrupt the band-solve eigenvalues
  scf.band_stagnate  force the band-solve health check to report stagnation
  scf.autosave_kill  die (SimulatedKill or hard exit) right after an autosave
  md.autosave_kill   die right after an MD trajectory checkpoint (md/driver)
  checkpoint.before_rename  die inside save_state between the temp-file
                            write and the atomic rename

Plans are process-local (``install``/``clear``) or inherited by child
processes through the ``SIRIUS_TPU_FAULTS`` environment variable, e.g.::

    SIRIUS_TPU_FAULTS="scf.density@3:nan,scf.autosave_kill@5:exit"

Each armed entry fires ``count`` times (default once) and then disarms, so
an injected NaN does not re-poison the state the supervisor just rolled
back.
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

ACTIONS = ("nan", "raise", "exit", "flag")


class SimulatedKill(Exception):
    """In-process stand-in for SIGKILL/preemption (raised by 'raise' faults)."""


@dataclasses.dataclass
class FaultSpec:
    site: str
    iteration: int = 0  # SCF iteration (0-based) at which the fault arms
    action: str = "nan"
    count: int = 1  # how many times the fault fires before disarming

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown fault action '{self.action}' (known: {ACTIONS})"
            )


_plan: list[FaultSpec] = []
_log: list[tuple[str, int, str]] = []  # (site, iteration, action) fired


def install(specs) -> None:
    """Arm a fault plan for this process (list of FaultSpec or of
    (site, iteration[, action[, count]]) tuples)."""
    global _plan
    out = []
    for s in specs:
        if isinstance(s, FaultSpec):
            out.append(s)
        else:
            out.append(FaultSpec(*s))
    _plan = out
    _log.clear()


def clear() -> None:
    global _plan
    _plan = []
    _log.clear()


def fired() -> list[tuple[str, int, str]]:
    """(site, iteration, action) records of every fault that fired."""
    return list(_log)


def load_env(env: str | None = None) -> None:
    """Parse SIRIUS_TPU_FAULTS ('site@iter:action[,...]') into the plan."""
    env = env if env is not None else os.environ.get("SIRIUS_TPU_FAULTS", "")
    specs = []
    for tok in filter(None, (t.strip() for t in env.split(","))):
        site, _, rest = tok.partition("@")
        itspec, _, action = rest.partition(":")
        specs.append(FaultSpec(site, int(itspec or 0), action or "nan"))
    install(specs)


def _match(site: str, iteration: int) -> FaultSpec | None:
    for s in _plan:
        if s.site == site and s.iteration == iteration and s.count > 0:
            return s
    return None


def _consume(spec: FaultSpec, iteration: int) -> str:
    spec.count -= 1
    _log.append((spec.site, iteration, spec.action))
    return spec.action


def armed(site: str, iteration: int = 0) -> bool:
    """True (and consumes one shot) when a 'flag' fault is armed here.
    Used for sites that alter control flow rather than data, e.g.
    scf.band_stagnate forcing the band-health check to fail."""
    spec = _match(site, iteration)
    if spec is None:
        return False
    _consume(spec, iteration)
    return True


def check(site: str, iteration: int = 0) -> None:
    """Fire a kill-style fault: 'raise' -> SimulatedKill, 'exit' -> hard
    process exit with no cleanup (the closest in-process analog of
    SIGKILL/preemption)."""
    spec = _match(site, iteration)
    if spec is None:
        return
    action = _consume(spec, iteration)
    if action == "raise":
        raise SimulatedKill(f"fault '{site}' at iteration {iteration}")
    if action == "exit":
        os._exit(137)
    # nan/flag actions are meaningless here; treat as armed-and-ignored


def corrupt(site: str, iteration: int, arr):
    """Return `arr` with a NaN injected in its first element when a 'nan'
    fault is armed at (site, iteration); otherwise `arr` unchanged. Works
    for numpy arrays and jax arrays (functional .at update)."""
    spec = _match(site, iteration)
    if spec is None:
        return arr
    action = _consume(spec, iteration)
    if action != "nan":
        if action == "raise":
            raise SimulatedKill(f"fault '{site}' at iteration {iteration}")
        if action == "exit":
            os._exit(137)
        return arr
    if isinstance(arr, np.ndarray):
        out = arr.copy()
        out.reshape(-1)[0] = np.nan
        return out
    # jax array: functional update (stays on device; NaN propagates through
    # the fused program exactly like a real numerical blow-up would)
    flat = arr.reshape(-1)
    flat = flat.at[0].set(np.nan)
    return flat.reshape(arr.shape)
