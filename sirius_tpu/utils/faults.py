"""Deterministic fault injection for the SCF supervision/recovery machinery
(dft/recovery.py).

Every recovery branch — mixer-history flush, beta backoff, host fallback,
checkpoint-interrupted-save, resume-after-kill — must be drivable from a
test without waiting for a real divergence or a real preemption. A fault
plan arms named sites at specific iterations; the instrumented code calls
the hooks below, which are no-ops when nothing is armed (the common case:
one dict lookup against an empty plan).

Sites currently wired:
  scf.density        corrupt the freshly accumulated density (host or fused)
  scf.potential      corrupt the generated effective potential
  scf.evals          corrupt the band-solve eigenvalues
  scf.band_stagnate  force the band-solve health check to report stagnation
  scf.forecast_misfire
                     force the convergence forecaster's early-warning
                     score to maximum at one iteration (a deliberately
                     wrong forecast): drives the proactive-snapshot and
                     deadline-infeasibility paths deterministically, and
                     pins that a misfire alone never costs a recovery
  scf.autosave_kill  die (SimulatedKill or hard exit) right after an autosave
  md.autosave_kill   die right after an MD trajectory checkpoint (md/driver)
  checkpoint.before_rename  die inside save_state between the temp-file
                            write and the atomic rename
  serve.worker_crash kill a serve slice-worker thread mid-job (WorkerCrash
                     escapes the scheduler's catch-all; the supervisor
                     watchdog must respawn the slice) — ``iteration`` is
                     the job attempt index (0-based)
  serve.job_hang     make a serve job attempt hang on its worker instead
                     of running, until the watchdog abandons it —
                     ``iteration`` is the job attempt index
  serve.journal_torn tear the next job-journal append mid-line (partial
                     write, no newline, no fsync — the on-disk state a
                     crash inside write() leaves) — ``iteration`` is the
                     journal's append sequence number
  campaign.node_fail fail a campaign node's attempt in the scheduler
                     before its SCF starts (``raise`` preempts and
                     retries; exhausting retries exercises the
                     SKIPPED_UPSTREAM cascade to its children) —
                     ``iteration`` is the job attempt index (0-based)
  campaign.handoff_corrupt
                     corrupt the parent-handoff density as the child
                     loads it; the child must detect the damage and
                     fall back to a cold start instead of failing
                     (``iteration`` 0, fires once per armed count)
  fleet.lease_lost   make a fleet lease renewal report the lease lost
                     (the deterministic stand-in for an expiry takeover
                     after this engine stalled): the engine abandons the
                     job to its new owner, discarding any late result —
                     ``iteration`` is the FleetDir renew sequence number
  fleet.store_corrupt
                     tear the next result-store sidecar write (present
                     but unparseable record-valid marker — the state a
                     crash between the npz and sidecar renames leaves);
                     readers must treat it as a miss and recompute —
                     ``iteration`` is the store's put sequence number
  device.oom         synthesize an HBM RESOURCE_EXHAUSTED backend error
                     at the SCF iteration's jit-dispatch boundary
                     (``fire``); run_scf routes it through the
                     supervisor's OOM degradation ladder
                     (utils/devfail.py classifies it as "oom")
  device.lost        synthesize a device-loss backend error at the same
                     boundary; it escapes run_scf to the serving layer,
                     which degrades the slice, shrinks its mesh to the
                     surviving devices, and resumes from autosave
                     (classified "device_lost")
  device.straggler   flag site: from the armed iteration on, run_scf's
                     iterations are artificially slowed so the straggler
                     watchdog (per-iteration wall vs the obs/costs.py
                     model and the run's healthy baseline) preempts the
                     run at a snapshot boundary

Plans are process-local (``install``/``clear``) or inherited by child
processes through the ``SIRIUS_TPU_FAULTS`` environment variable. The env
grammar is ``site@iter:action*count`` per comma-separated entry — ``@iter``
defaults to 0, ``:action`` to ``nan``, ``*count`` to 1 — e.g.::

    SIRIUS_TPU_FAULTS="scf.density@3:nan,scf.autosave_kill@5:exit"
    SIRIUS_TPU_FAULTS="serve.job_hang@0:flag*2"   # hang attempts 1 and 2

Each armed entry fires ``count`` times (default once) and then disarms, so
an injected NaN does not re-poison the state the supervisor just rolled
back. ``count`` must be >= 0 (0 arms a spec that never fires; negative
counts are rejected at validation).
"""

from __future__ import annotations

import dataclasses
import os
import threading

import numpy as np

ACTIONS = ("nan", "raise", "exit", "flag")

# The canonical fault-site registry: one entry per instrumented site in
# the tree (the docstring above documents each). sirius-lint's
# unknown-fault-site rule parses this tuple by AST, and
# tools/chaos_serve.py validates its phase specs against it, so a typo'd
# site in code or a chaos plan fails fast instead of silently never
# firing. Add the site here in the same change that wires the hook.
KNOWN_SITES = (
    "scf.density",
    "scf.potential",
    "scf.evals",
    "scf.band_stagnate",
    "scf.forecast_misfire",
    "scf.autosave_kill",
    "md.autosave_kill",
    "checkpoint.before_rename",
    "serve.worker_crash",
    "serve.job_hang",
    "serve.journal_torn",
    "campaign.node_fail",
    "campaign.handoff_corrupt",
    "fleet.lease_lost",
    "fleet.store_corrupt",
    "device.oom",
    "device.lost",
    "device.straggler",
)

# realistic backend-error text per device fault site: the exact status
# strings a real HBM exhaustion / lost chip produces, so
# utils/devfail.py's classifier and everything downstream see what
# production would (fire() raises these as RuntimeError — jaxlib's
# XlaRuntimeError subclasses RuntimeError, and the classifier matches on
# the status markers, not the concrete type)
_DEVICE_ERRORS = {
    "device.oom": (
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "17179869184 bytes. [tf-allocator-allocation-error]"),
    "device.lost": (
        "INTERNAL: Device or resource lost: the TPU system has halted; "
        "restart required"),
}


class SimulatedKill(Exception):
    """In-process stand-in for SIGKILL/preemption (raised by 'raise' faults)."""


class WorkerCrash(BaseException):
    """Kills a serving worker thread (serve.worker_crash site).

    Deliberately a BaseException: the slice scheduler's catch-all
    ``except Exception`` must NOT swallow it — the point of the site is a
    worker thread dying with a job still assigned, which only the
    supervisor watchdog can recover from."""


@dataclasses.dataclass
class FaultSpec:
    site: str
    iteration: int = 0  # SCF iteration (0-based) at which the fault arms
    action: str = "nan"
    count: int = 1  # how many times the fault fires before disarming

    def __post_init__(self):
        if self.action not in ACTIONS:
            raise ValueError(
                f"unknown fault action '{self.action}' (known: {ACTIONS})"
            )
        if self.count < 0:
            raise ValueError(
                f"fault count must be >= 0, got {self.count} "
                f"(site '{self.site}')"
            )


_plan: list[FaultSpec] = []
_log: list[tuple[str, int, str]] = []  # (site, iteration, action) fired
# serve slice-workers probe sites concurrently: match-and-consume must be
# atomic or a count-1 spec can fire on two threads at once
_mu = threading.Lock()


def install(specs) -> None:
    """Arm a fault plan for this process (list of FaultSpec or of
    (site, iteration[, action[, count]]) tuples)."""
    global _plan
    out = []
    for s in specs:
        if isinstance(s, FaultSpec):
            out.append(s)
        else:
            out.append(FaultSpec(*s))
    _plan = out
    _log.clear()


def clear() -> None:
    global _plan
    _plan = []
    _log.clear()


def fired() -> list[tuple[str, int, str]]:
    """(site, iteration, action) records of every fault that fired."""
    return list(_log)


def load_env(env: str | None = None) -> None:
    """Parse SIRIUS_TPU_FAULTS ('site@iter:action*count[,...]') into the
    plan. ``@iter`` defaults to 0, ``:action`` to 'nan', ``*count`` to 1."""
    env = env if env is not None else os.environ.get("SIRIUS_TPU_FAULTS", "")
    specs = []
    for tok in filter(None, (t.strip() for t in env.split(","))):
        # action first, then iteration: 'site:action' (no @iter) is legal
        head, _, action = tok.partition(":")
        site, _, itspec = head.partition("@")
        action, _, countspec = action.partition("*")
        specs.append(FaultSpec(site, int(itspec or 0), action or "nan",
                               int(countspec or 1)))
    install(specs)


def _match(site: str, iteration: int) -> FaultSpec | None:
    for s in _plan:
        if s.site == site and s.iteration == iteration and s.count > 0:
            return s
    return None


def _consume(spec: FaultSpec, iteration: int) -> str:
    spec.count -= 1
    _log.append((spec.site, iteration, spec.action))
    return spec.action


def _take(site: str, iteration: int) -> str | None:
    """Atomically match-and-consume one shot; None when nothing armed."""
    with _mu:
        spec = _match(site, iteration)
        if spec is None:
            return None
        return _consume(spec, iteration)


def armed(site: str, iteration: int = 0) -> bool:
    """True (and consumes one shot) when a 'flag' fault is armed here.
    Used for sites that alter control flow rather than data, e.g.
    scf.band_stagnate forcing the band-health check to fail."""
    return _take(site, iteration) is not None


def check(site: str, iteration: int = 0) -> None:
    """Fire a kill-style fault: 'raise' -> SimulatedKill, 'exit' -> hard
    process exit with no cleanup (the closest in-process analog of
    SIGKILL/preemption)."""
    action = _take(site, iteration)
    if action is None:
        return
    if action == "raise":
        raise SimulatedKill(f"fault '{site}' at iteration {iteration}")
    if action == "exit":
        os._exit(137)
    # nan/flag actions are meaningless here; treat as armed-and-ignored


def fire(site: str, iteration: int = 0) -> None:
    """Fire a device-fault site: raise the synthesized backend error
    armed at (site, iteration) — the realistic RESOURCE_EXHAUSTED /
    device-loss status text a real failure produces (``_DEVICE_ERRORS``),
    as a RuntimeError at the caller's jit-dispatch boundary. 'exit'
    hard-exits like a chip taking the process down; no-op when unarmed."""
    action = _take(site, iteration)
    if action is None:
        return
    if action == "exit":
        os._exit(137)
    msg = _DEVICE_ERRORS.get(site, f"INTERNAL: injected fault '{site}'")
    raise RuntimeError(f"{msg} (iteration {iteration})")


def corrupt(site: str, iteration: int, arr):
    """Return `arr` with a NaN injected in its first element when a 'nan'
    fault is armed at (site, iteration); otherwise `arr` unchanged. Works
    for numpy arrays and jax arrays (functional .at update)."""
    action = _take(site, iteration)
    if action is None:
        return arr
    if action != "nan":
        if action == "raise":
            raise SimulatedKill(f"fault '{site}' at iteration {iteration}")
        if action == "exit":
            os._exit(137)
        return arr
    if isinstance(arr, np.ndarray):
        out = arr.copy()
        out.reshape(-1)[0] = np.nan
        return out
    # jax array: functional update (stays on device; NaN propagates through
    # the fused program exactly like a real numerical blow-up would)
    flat = arr.reshape(-1)
    flat = flat.at[0].set(np.nan)
    return flat.reshape(arr.shape)
