"""Device-failure taxonomy: classify backend runtime errors so the
serving layer can choose a recovery policy per failure class.

A device-level failure surfaces in JAX as ``XlaRuntimeError`` (a
``RuntimeError`` subclass raised from jaxlib) whose *message* carries an
absl status code plus backend detail — the exception type alone says
nothing about what happened. ``classify`` maps that message (walking the
``__cause__``/``__context__`` chain, so wrapped dispatch errors still
classify) onto three classes with distinct recovery semantics:

  ``oom``          HBM ``RESOURCE_EXHAUSTED``: the *program* does not fit.
                   Retrying identically re-fails identically; the only
                   useful retry changes the memory plan. In-run, the
                   ScfSupervisor's OOM degradation ladder (dft/recovery.py)
                   shrinks the projector budget / forces the chunked beta
                   path / falls back to the host path, resuming from the
                   last snapshot. At the job level the scheduler retries
                   with ``apply_oom_hint`` pre-degrading the controls.
  ``device_lost``  the chip is gone (preemption, halt, reset): nothing
                   in-process can recover it. The serve layer marks the
                   slice degraded, rebuilds its mesh from the surviving
                   devices, and resumes the job from its autosave on the
                   shrunk mesh — preemption semantics, never a poison
                   strike (the deck did nothing wrong).
  ``transient``    everything else the backend tags retryable
                   (UNAVAILABLE / DEADLINE_EXCEEDED / CANCELLED / ABORTED
                   or an otherwise-unrecognized ``XlaRuntimeError``):
                   plain backoff-retry on the same mesh.

A ``RuntimeError`` with *no* device markers returns ``None`` — an honest
bug must keep failing the job permanently, not burn retries.

Fault injection: ``utils/faults.py`` sites ``device.oom`` /
``device.lost`` synthesize errors with the realistic backend message
text (``faults.fire``), so everything downstream — this classifier, the
ladder, the mesh-shrink path — is exercised by the exact strings a real
TPU failure produces. ``device.straggler`` is a flag site consumed by
run_scf's straggler detector (see StragglerPreempt below).
"""

from __future__ import annotations

from sirius_tpu.utils.faults import SimulatedKill

CLASSES = ("oom", "device_lost", "transient")

# substring markers, matched case-insensitively against the full
# exception text. Sources: PJRT/absl status payloads observed from real
# HBM exhaustion, TPU preemption/halt, and collective timeouts.
_OOM_MARKERS = (
    "resource_exhausted",
    "out of memory",
    "hbm space",
    "allocation failure",
    "failed to allocate",
)
_LOST_MARKERS = (
    "device_lost",
    "device lost",
    "device or resource lost",
    "system has halted",
    "chip has been disabled",
    "device is in an error state",
    "hardware failure",
    "slice health check failed",
)
_TRANSIENT_MARKERS = (
    "unavailable",
    "deadline_exceeded",
    "deadline exceeded",
    "cancelled",
    "aborted",
    "connection reset",
)
# exception type names that mark an error as backend-originated even
# when the message carries no status code (then: transient)
_BACKEND_TYPE_NAMES = ("XlaRuntimeError", "PjRtError")


class StragglerPreempt(SimulatedKill):
    """run_scf detected a straggling device (per-iteration wall far above
    the obs/costs.py model and the run's own healthy baseline) and
    preempted itself at a snapshot boundary. Subclasses SimulatedKill so
    any handler treating injected preemptions as retryable keeps working;
    the scheduler catches it first to degrade the slice and retry the job
    under the ``straggler`` failure class (no poison strike)."""


def _chain(exc: BaseException):
    seen = set()
    while exc is not None and id(exc) not in seen:
        seen.add(id(exc))
        yield exc
        exc = exc.__cause__ or exc.__context__


def classify(exc: BaseException | None) -> str | None:
    """Failure class of a (possibly wrapped) backend error, or None when
    the exception is not a device failure at all."""
    if exc is None:
        return None
    backend = False
    text = []
    for e in _chain(exc):
        if type(e).__name__ in _BACKEND_TYPE_NAMES:
            backend = True
        if isinstance(e, RuntimeError) or backend:
            text.append(str(e))
    blob = " | ".join(text).lower()
    if not blob:
        return None
    if any(m in blob for m in _OOM_MARKERS):
        return "oom"
    if any(m in blob for m in _LOST_MARKERS):
        return "device_lost"
    if any(m in blob for m in _TRANSIENT_MARKERS):
        return "transient"
    # an XlaRuntimeError we cannot parse is still a backend error: retry
    # beats permanently failing a job on e.g. a new status string
    return "transient" if backend else None


def apply_oom_hint(control, level: int) -> list[str]:
    """Pre-degrade a job's controls before a retry that previously died
    of HBM OOM — the job-granularity mirror of the in-run degradation
    ladder (dft/recovery.py OOM_LADDER), applied by serve/scheduler.py.

    level 1: quarter the chunked-beta engagement budget and halve the
             chunk size (smaller peak projector footprint);
    level 2: additionally force the chunked beta path;
    level 3: additionally disable device_scf (host fallback).

    Returns the list of rung names applied (for the retry detail/event).
    """
    applied = []
    lvl = int(level)
    if lvl >= 1:
        control.beta_chunk_budget_bytes = float(
            control.beta_chunk_budget_bytes) / 4.0
        control.beta_chunk_size = max(
            16, int(control.beta_chunk_size) // 2)
        applied.append("shrink_beta_budget")
    if lvl >= 2 and control.beta_chunked not in (False, "false", "off"):
        control.beta_chunked = True
        applied.append("force_beta_chunked")
    if lvl >= 3:
        control.device_scf = False
        applied.append("disable_device_scf")
    return applied
