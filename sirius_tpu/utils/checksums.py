"""Env-gated per-stage scalar checksums (SURVEY §5; reference
env::print_checksum() + print_checksum() calls through the SCF chain,
src/core/env/env.hpp): a cheap tripwire for cross-mesh nondeterminism.

Enable with SIRIUS_TPU_PRINT_CHECKSUM=1. Each call prints one line
`[checksum] <tag>: <value>` and records the value so a test (or a
debugging session) can compare the single-device and mesh-sharded
trajectories stage by stage.
"""

from __future__ import annotations

import os

import numpy as np

_records: dict[str, list] = {}


def enabled() -> bool:
    return os.environ.get("SIRIUS_TPU_PRINT_CHECKSUM", "") == "1"


def checksum(tag: str, arr) -> None:
    """Record + print the plain sum of `arr` under `tag` (no-op unless
    SIRIUS_TPU_PRINT_CHECKSUM=1)."""
    if not enabled():
        return
    a = np.asarray(arr)
    v = complex(np.sum(a)) if np.iscomplexobj(a) else float(np.sum(a))
    _records.setdefault(tag, []).append(v)
    print(f"[checksum] {tag}: {v!r}", flush=True)


def records() -> dict[str, list]:
    return _records


def reset() -> None:
    _records.clear()
