"""sirius-scf command-line mini-app (reference: apps/mini_app/sirius.scf.cpp).

Round-1 stub: argument surface is in place; SCF driving lands with the dft
layer. Exits with a clear message rather than ModuleNotFoundError.
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="sirius-scf",
        description="TPU-native Kohn-Sham DFT SCF mini-app (sirius_tpu)",
    )
    p.add_argument("input", nargs="?", default="sirius.json", help="JSON input file")
    p.add_argument("--test_against", help="reference output JSON to compare against")
    args = p.parse_args(argv)
    try:
        from sirius_tpu.dft.scf import run_scf_from_file
    except ModuleNotFoundError as e:
        if e.name in ("sirius_tpu.dft.scf", "sirius_tpu.dft"):
            print("sirius-scf: SCF driver not built yet in this revision", file=sys.stderr)
            return 2
        raise
    return run_scf_from_file(args.input, test_against=args.test_against)


if __name__ == "__main__":
    raise SystemExit(main())
