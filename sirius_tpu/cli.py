"""sirius-scf command-line mini-app (reference: apps/mini_app/sirius.scf.cpp).

Round-1 stub: argument surface is in place; SCF driving lands with the dft
layer. Exits with a clear message rather than ModuleNotFoundError.
"""

from __future__ import annotations

import argparse
import sys


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="sirius-scf",
        description="TPU-native Kohn-Sham DFT SCF mini-app (sirius_tpu)",
    )
    p.add_argument("input", nargs="?", default="sirius.json", help="JSON input file")
    p.add_argument("--test_against", help="reference output JSON to compare against")
    p.add_argument(
        "--task",
        default="ground_state_new",
        choices=["ground_state_new", "ground_state_restart", "ground_state_relax", "ground_state_direct", "k_point_path", "eos", "molecular_dynamics"],
        help="calculation task (reference sirius.scf task semantics)",
    )
    p.add_argument("--volume_scale0", type=float, default=0.95,
                   help="eos task: first volume scale")
    p.add_argument("--volume_scale1", type=float, default=1.05,
                   help="eos task: last volume scale")
    p.add_argument("--num_steps", type=int, default=7,
                   help="eos task: number of volume points")
    p.add_argument(
        "--platform",
        default=None,
        choices=["cpu", "tpu", "axon"],
        help="JAX platform; 'cpu' runs the f64 verification path. Note: the "
        "JAX_PLATFORMS env var is unreliable when a sitecustomize pre-imports "
        "jax, so this flag sets jax.config explicitly. Default: cpu when the "
        "deck requests processing_unit=cpu, else the jax default.",
    )
    p.add_argument("-v", "--verbose", action="count", default=0,
                   help="raise log level (-v info, -vv debug)")
    args = p.parse_args(argv)

    from sirius_tpu.obs.log import setup as _log_setup

    _log_setup(args.verbose)

    import json
    import os

    # fail fast on a bad input path, before any (slow) jax backend init
    if not os.path.isfile(args.input):
        print(f"sirius-scf: input file not found: {args.input}", file=sys.stderr)
        return 2

    import jax

    platform = args.platform
    if platform is None:
        try:
            with open(args.input) as f:
                if json.load(f).get("control", {}).get("processing_unit") == "cpu":
                    platform = "cpu"
        except (OSError, json.JSONDecodeError):
            pass
    if platform:
        jax.config.update("jax_platforms", "axon" if platform == "tpu" else platform)
    try:
        from sirius_tpu.dft.scf import run_scf_from_file
    except ModuleNotFoundError as e:
        if e.name in ("sirius_tpu.dft.scf", "sirius_tpu.dft"):
            print("sirius-scf: SCF driver not built yet in this revision", file=sys.stderr)
            return 2
        raise
    if args.task == "molecular_dynamics":
        from sirius_tpu.md.driver import run_md_from_file

        if args.test_against:
            print(
                "sirius-scf: --test_against is not supported by the "
                "molecular_dynamics task", file=sys.stderr,
            )
            return 2
        return run_md_from_file(args.input)
    if args.task == "eos":
        from sirius_tpu.apps_util import run_eos

        if args.test_against:
            print(
                "sirius-scf: --test_against is not supported by the eos "
                "task (no reference eos artifacts in-tree)", file=sys.stderr,
            )
            return 2
        cfg_dict = json.load(open(args.input))
        out = run_eos(
            cfg_dict, os.path.dirname(os.path.abspath(args.input)) or ".",
            args.volume_scale0, args.volume_scale1, num_steps=args.num_steps,
        )
        for v, e in zip(out["volume"], out["energy"]):
            print(f"volume: {v}, energy: {e}")
        return 0
    return run_scf_from_file(args.input, test_against=args.test_against, task=args.task)


if __name__ == "__main__":
    raise SystemExit(main())
