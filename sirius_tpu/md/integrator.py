"""MD integrator: velocity-Verlet with NVE / Langevin / Bussi-CSVR
ensembles, in Hartree atomic units throughout.

Conventions (all atomic units unless suffixed):
  positions   cartesian bohr
  velocities  bohr / a.u. time
  forces      Ha / bohr
  masses      electron masses (amu * 1822.888...)

The thermostats are formulated as half-step velocity maps applied around
the two velocity-Verlet kicks (the standard middle-point splitting):

  Langevin  exact Ornstein-Uhlenbeck update over dt/2
            v <- c v + sqrt((1 - c^2) kT / m) xi,   c = exp(-dt/(2 tau))
  CSVR      Bussi-Donadio-Parrinello stochastic velocity rescaling
            (canonical sampling through a single global rescale; J. Chem.
            Phys. 126, 014101 (2007)) over dt/2

Both accumulate the energy they inject/remove so a conserved quantity
exists for every ensemble:

  NVE       E_kin + E_pot
  NVT       E_kin + E_pot - sum(thermostat work)   (Bussi's "effective
            energy"; flat for a correct integration, drifts when dt is
            too large — exactly the diagnostic MD needs)

Thermostat noise is counter-based: every random draw is generated from
`SeedSequence([seed, step, salt])`, so a restarted trajectory replays the
identical noise stream from just (seed, step) — no RNG state to
checkpoint.
"""

from __future__ import annotations

import dataclasses

import numpy as np

# CODATA-2018 conversion factors
FS_TO_AU = 41.341374575751  # 1 fs in atomic time units
AMU_TO_AU = 1822.888486209  # 1 amu in electron masses
KB_HA = 3.166811563e-6  # Boltzmann constant [Ha/K]
HA_TO_EV = 27.211386245988
BOHR_TO_ANG = 0.529177210903

ENSEMBLES = ("nve", "nvt_langevin", "nvt_csvr")


def masses_au(unit_cell) -> np.ndarray:
    """Per-atom masses [electron masses] from the cell's species
    (crystal/atom_type.py mass_amu: species-file header mass or the
    standard atomic weight of the element)."""
    return np.array(
        [unit_cell.atom_types[t].mass_amu * AMU_TO_AU
         for t in unit_cell.type_of_atom],
        dtype=np.float64,
    )


def _rng(seed: int, step: int, salt: int = 0) -> np.random.Generator:
    """Counter-based generator: deterministic in (seed, step, salt) so a
    resumed trajectory replays the same noise without serializing RNG
    state."""
    return np.random.default_rng(
        np.random.SeedSequence([
            int(seed) & 0xFFFFFFFF, int(step) & 0xFFFFFFFF,
            int(salt) & 0xFFFFFFFF,
        ])
    )


def num_dof(natoms: int, remove_com: bool) -> int:
    """Translational degrees of freedom entering temperature estimates."""
    n = 3 * natoms - (3 if (remove_com and natoms > 1) else 0)
    return max(n, 1)


def kinetic_energy(velocities: np.ndarray, masses: np.ndarray) -> float:
    return float(0.5 * np.sum(masses[:, None] * velocities**2))


def temperature_k(velocities: np.ndarray, masses: np.ndarray,
                  remove_com: bool = True) -> float:
    ndof = num_dof(len(masses), remove_com)
    return 2.0 * kinetic_energy(velocities, masses) / (ndof * KB_HA)


def remove_com_velocity(velocities: np.ndarray, masses: np.ndarray) -> np.ndarray:
    """Zero the center-of-mass momentum (mass-weighted)."""
    p = (masses[:, None] * velocities).sum(axis=0)
    return velocities - p / masses.sum()


def maxwell_boltzmann_velocities(
    masses: np.ndarray,
    temperature: float,
    seed: int = 42,
    remove_com: bool = True,
) -> np.ndarray:
    """Maxwell-Boltzmann velocities at `temperature` [K], COM-projected
    and rescaled to the exact target (the conventional deterministic
    init; temperature <= 0 returns zeros)."""
    n = len(masses)
    if temperature <= 0.0 or n == 0:
        return np.zeros((n, 3))
    rng = _rng(seed, -1)
    v = rng.standard_normal((n, 3)) * np.sqrt(
        KB_HA * temperature / masses[:, None]
    )
    if remove_com and n > 1:
        v = remove_com_velocity(v, masses)
    t_now = temperature_k(v, masses, remove_com)
    if t_now > 0:
        v *= np.sqrt(temperature / t_now)
    return v


@dataclasses.dataclass
class Thermostat:
    """Half-step velocity map for the configured ensemble.

    apply() returns (new_velocities, injected_energy); the injected energy
    (KE_after - KE_before) feeds the conserved-quantity tracker. `salt`
    disambiguates the two half-steps of one MD step so they draw
    independent noise.
    """

    ensemble: str  # nve | nvt_langevin | nvt_csvr
    temperature: float  # target [K]
    tau_fs: float  # relaxation time [fs]
    seed: int = 42
    remove_com: bool = True

    def __post_init__(self):
        if self.ensemble not in ENSEMBLES:
            raise ValueError(
                f"unknown ensemble '{self.ensemble}' (known: {ENSEMBLES})"
            )
        if self.ensemble != "nve" and self.temperature <= 0.0:
            raise ValueError(
                f"{self.ensemble}: temperature_k must be positive, got "
                f"{self.temperature}"
            )
        if self.ensemble != "nve" and self.tau_fs <= 0.0:
            raise ValueError(
                f"{self.ensemble}: thermostat_tau_fs must be positive, got "
                f"{self.tau_fs}"
            )

    def apply(
        self,
        velocities: np.ndarray,
        masses: np.ndarray,
        dt_half: float,
        step: int,
        salt: int,
    ) -> tuple[np.ndarray, float]:
        if self.ensemble == "nve":
            return velocities, 0.0
        ke0 = kinetic_energy(velocities, masses)
        tau = self.tau_fs * FS_TO_AU
        rng = _rng(self.seed, step, salt)
        if self.ensemble == "nvt_langevin":
            # exact OU propagation over dt_half: damping + matched noise
            c = np.exp(-dt_half / tau)
            sigma = np.sqrt(
                (1.0 - c * c) * KB_HA * self.temperature / masses[:, None]
            )
            v = c * velocities + sigma * rng.standard_normal(velocities.shape)
            if self.remove_com and len(masses) > 1:
                # keep the total momentum zero: the noise otherwise pumps
                # the COM mode while ndof counts 3N - 3
                v = remove_com_velocity(v, masses)
        else:  # nvt_csvr (Bussi stochastic velocity rescaling)
            ndof = num_dof(len(masses), self.remove_com)
            ke_bar = 0.5 * ndof * KB_HA * self.temperature
            if ke0 <= 0.0:
                # cold start: seed the kinetic energy from the target MB
                # distribution instead of dividing by zero
                v = maxwell_boltzmann_velocities(
                    masses, self.temperature, seed=self.seed + step + salt,
                    remove_com=self.remove_com,
                )
                return v, kinetic_energy(v, masses) - ke0
            c = np.exp(-dt_half / tau)
            r1 = rng.standard_normal()
            # sum of (ndof - 1) squared normals ~ chi^2(ndof - 1)
            s = (
                2.0 * rng.standard_gamma(0.5 * (ndof - 1))
                if ndof > 1 else 0.0
            )
            alpha2 = (
                c
                + (1.0 - c) * (ke_bar / (ndof * ke0)) * (r1 * r1 + s)
                + 2.0 * r1 * np.sqrt(c * (1.0 - c) * ke_bar / (ndof * ke0))
            )
            v = velocities * np.sqrt(max(alpha2, 0.0))
        return v, kinetic_energy(v, masses) - ke0


class ConservedTracker:
    """Per-step conserved-quantity bookkeeping.

    record() accumulates thermostat work and stores the ensemble's
    conserved quantity E_kin + E_pot - W_thermostat; drift() reports the
    max deviation from the first recorded value (Ha, and Ha/atom)."""

    def __init__(self, natoms: int):
        self.natoms = max(int(natoms), 1)
        self.w_thermostat = 0.0  # accumulated injected energy
        self.history: list[float] = []

    def add_work(self, de: float) -> None:
        self.w_thermostat += float(de)

    def record(self, e_kin: float, e_pot: float) -> float:
        e_cons = float(e_kin) + float(e_pot) - self.w_thermostat
        self.history.append(e_cons)
        return e_cons

    def drift(self) -> dict:
        if not self.history:
            return {"max_abs": 0.0, "max_abs_per_atom": 0.0}
        h = np.asarray(self.history)
        d = float(np.abs(h - h[0]).max())
        return {"max_abs": d, "max_abs_per_atom": d / self.natoms}

    def export(self) -> dict:
        return {
            "w_thermostat": self.w_thermostat,
            "econs_history": np.asarray(self.history, dtype=np.float64),
        }

    def restore(self, state: dict) -> None:
        self.w_thermostat = float(state.get("w_thermostat", 0.0))
        self.history = [float(v) for v in state.get("econs_history", [])]


def velocity_verlet_step(
    positions: np.ndarray,
    velocities: np.ndarray,
    f_current: np.ndarray,
    masses: np.ndarray,
    dt: float,
    thermostat: Thermostat,
    step: int,
    force_fn,
    tracker: ConservedTracker | None = None,
):
    """One full velocity-Verlet step with the thermostat applied as
    half-steps around the kicks (the middle/OBABO splitting):

      v <- T(dt/2); v += (dt/2) f(t)/m; r += dt v;
      f(t+dt) = force_fn(r)        # the caller's SCF+forces evaluation
      v += (dt/2) f(t+dt)/m; v <- T(dt/2)

    `force_fn(r_cart)` returns (f, e_pot, extra); returns (positions,
    velocities, f_new, e_pot, extra)."""
    v, de = thermostat.apply(velocities, masses, 0.5 * dt, step, salt=0)
    if tracker is not None:
        tracker.add_work(de)
    v = v + 0.5 * dt * f_current / masses[:, None]
    r = positions + dt * v
    f_new, e_pot, extra = force_fn(r)
    v = v + 0.5 * dt * f_new / masses[:, None]
    v, de = thermostat.apply(v, masses, 0.5 * dt, step, salt=1)
    if tracker is not None:
        tracker.add_work(de)
    return r, v, f_new, e_pot, extra
