"""Predictor state across MD/geometry steps: ASPC density extrapolation
and subspace-aligned wave-function extrapolation.

Each Born-Oppenheimer step's SCF needs an initial (rho, psi). Restarting
from the superposition of atomic densities every step ("cold start")
costs the full SCF iteration count at every geometry; extrapolating the
converged states of the previous steps starts the SCF inside the
convergence basin and cuts the iterations per step severalfold — the
standard MD-embedding technique (CP2K's ASPC extrapolation; QE's
pot/wfc extrapolation).

Two coefficient families over the last m converged values x(t), x(t-h),
... (newest first):

- `aspc_coefficients(m)` — Kolafa's always-stable predictor-corrector
  (J. Comput. Chem. 25, 335 (2004)):

      B_j = (-1)^(j+1) j C(2m, m-j) / C(2m-2, m-1),   j = 1..m

  ({2,-1}, {5/2,-2,1/2}, {14/5,-14/5,6/5,-1/5}, ...). The predictor is
  exact on linear trajectories only: the higher-order Taylor terms are
  deliberately damped, which is what keeps the predictor-corrector loop
  stable at every order when the SCF "corrector" is not iterated to full
  self-consistency. The matching corrector mixing is
  `aspc_omega(m) = m/(2m-1)`.

- `poly_coefficients(m)` — pure Lagrange/forward-difference
  extrapolation, c_j = (-1)^(j+1) C(m, j) ({2,-1}, {3,-3,1}, ...): exact
  on polynomial trajectories up to degree m-1 (a 3-point predictor
  reproduces a quadratic trajectory exactly), at the price of amplifying
  noise. For tightly converged BOMD (this driver converges every step)
  both work; `md.extrapolation_kind` selects.

Wave functions additionally carry a gauge freedom: the SCF returns an
arbitrary unitary mix within degenerate/occupied subspaces, so raw
psi(t) - psi(t-h) differences are dominated by gauge noise. The subspace
extrapolator first aligns each new psi to the running gauge by the polar
decomposition of the band-overlap matrix (the orthogonal Procrustes
rotation), then extrapolates the aligned coefficients.
"""

from __future__ import annotations

from math import comb

import numpy as np

KINDS = ("aspc", "poly", "off")


def aspc_coefficients(m: int) -> np.ndarray:
    """Kolafa ASPC predictor coefficients over the last m values (newest
    first). m=1 degenerates to reusing the last value."""
    if m < 1:
        raise ValueError(f"aspc_coefficients: need m >= 1, got {m}")
    if m == 1:
        return np.array([1.0])
    den = comb(2 * m - 2, m - 1)
    return np.array(
        [(-1) ** (j + 1) * j * comb(2 * m, m - j) / den for j in range(1, m + 1)]
    )


def aspc_omega(m: int) -> float:
    """Corrector mixing weight paired with aspc_coefficients(m):
    x(t+h) = omega x_scf + (1 - omega) x_pred (Kolafa's
    omega = (k+2)/(2k+3) with k = m - 2)."""
    if m < 2:
        return 1.0
    return m / (2.0 * m - 1.0)


def poly_coefficients(m: int) -> np.ndarray:
    """Polynomial (forward-difference) extrapolation coefficients: exact
    for trajectories polynomial in time up to degree m-1."""
    if m < 1:
        raise ValueError(f"poly_coefficients: need m >= 1, got {m}")
    return np.array([(-1) ** (j + 1) * comb(m, j) for j in range(1, m + 1)],
                    dtype=np.float64)


def _coefficients(kind: str, m: int) -> np.ndarray:
    return aspc_coefficients(m) if kind == "aspc" else poly_coefficients(m)


class AspcExtrapolator:
    """Field extrapolator over a bounded history of converged values.

    order: maximum history depth (number of previous steps used; 1 =
    reuse the last value). kind: 'aspc' | 'poly' | 'off'. The corrector
    mixing (ASPC omega) is applied in push() so the stored history is the
    actual predictor-corrector trajectory; with use_corrector=False the
    raw SCF output is stored (pure predictor, right for tightly converged
    BOMD where the SCF result is the ground truth)."""

    def __init__(self, order: int, kind: str = "aspc",
                 use_corrector: bool = False):
        if kind not in KINDS:
            raise ValueError(f"unknown extrapolation kind '{kind}' "
                             f"(known: {KINDS})")
        self.order = max(int(order), 0)
        self.kind = kind
        self.use_corrector = bool(use_corrector) and kind == "aspc"
        self.history: list[np.ndarray] = []  # newest first

    def predict(self):
        """Predicted next value, or None (cold start) when disabled or
        no history exists yet."""
        if self.kind == "off" or self.order < 1 or not self.history:
            return None
        m = min(len(self.history), self.order)
        c = _coefficients(self.kind, m)
        out = c[0] * self.history[0]
        for j in range(1, m):
            out = out + c[j] * self.history[j]
        return out

    def push(self, x_scf: np.ndarray) -> None:
        """Record a converged value (newest first, bounded history)."""
        if self.kind == "off" or self.order < 1:
            return
        x = np.asarray(x_scf)
        if self.use_corrector and self.history:
            pred = self.predict()
            w = aspc_omega(min(len(self.history) + 1, self.order))
            x = w * x + (1.0 - w) * pred
        self.history.insert(0, x)
        del self.history[self.order:]

    def export(self) -> np.ndarray | None:
        """Checkpointable stack [m, ...] (newest first), None when empty."""
        if not self.history:
            return None
        return np.stack(self.history)

    def restore(self, stack) -> None:
        if stack is None:
            self.history = []
            return
        a = np.asarray(stack)
        self.history = [a[i] for i in range(min(a.shape[0], self.order))]


def align_subspace(psi_new: np.ndarray, psi_ref: np.ndarray) -> np.ndarray:
    """Rotate the bands of psi_new ([nb, ngk], G-vector rows masked) into
    the gauge of psi_ref: R = U V^H from the SVD of the band-overlap
    C = psi_ref psi_new^H — the unitary minimizing
    ||R psi_new - psi_ref||_F (orthogonal Procrustes)."""
    c = psi_ref @ psi_new.conj().T
    u, _, vh = np.linalg.svd(c)
    return (u @ vh) @ psi_new


class SubspaceExtrapolator(AspcExtrapolator):
    """Wave-function extrapolator: every pushed psi [nk, ns, nb, ngk] is
    first gauge-aligned per (k, spin) block against the newest history
    member, so the whole history shares one smooth gauge chain and the
    linear combination is meaningful."""

    def push(self, psi: np.ndarray) -> None:
        if self.kind == "off" or self.order < 1:
            return
        psi = np.asarray(psi)
        if self.history:
            ref = self.history[0]
            aligned = np.empty_like(psi)
            nk, ns = psi.shape[:2]
            for ik in range(nk):
                for ispn in range(ns):
                    aligned[ik, ispn] = align_subspace(
                        psi[ik, ispn], ref[ik, ispn]
                    )
            psi = aligned
        super().push(psi)
