"""Born-Oppenheimer MD driver: converged SCF + analytic forces per step,
compile-once across the trajectory.

Each velocity-Verlet step evaluates forces by running the full SCF at the
new positions. Three pieces make the stepping cheap:

- the SimulationContext at every step is rebuilt at the displaced
  positions with identical array shapes (dft/geometry.py
  context_at_positions), so the fused SCF iteration and every module-jit
  helper hit their compiled executables — zero XLA recompiles after the
  first step (tracked via serve/cache.py's jax.monitoring listener);
- a shared ExecutableCache carries the fused-step program across run_scf
  calls (the serving engine's compile amortization, reused here);
- the SCF warm-starts from ASPC-extrapolated density and subspace-aligned
  extrapolated wave functions (md/extrapolate.py), which cuts the
  iterations per step severalfold against the superposition-of-atoms cold
  start.

Restart: every md.autosave_every steps the driver checkpoints a /md group
(io/checkpoint.py) holding step counter, positions, velocities, forces,
thermostat work and the extrapolation histories. Thermostat noise is
counter-based in (seed, step), so a resumed trajectory replays the exact
noise sequence of the uninterrupted run — resume equality is a test, not
a hope (tests/test_md_driver.py).
"""

from __future__ import annotations

import json
import os
import sys
import time
import warnings

import numpy as np

from sirius_tpu.md.extrapolate import AspcExtrapolator, SubspaceExtrapolator
from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs import spans as obs_spans
from sirius_tpu.obs import tracing as obs_tracing
from sirius_tpu.obs.log import get_logger, job_context

logger = get_logger("md")

_STEPS = obs_metrics.REGISTRY.counter(
    "md_steps_total", "MD steps integrated")
_STEP_SECONDS = obs_metrics.REGISTRY.histogram(
    "md_step_seconds", "wall time per MD step")
_SCF_PER_STEP = obs_metrics.REGISTRY.histogram(
    "md_scf_iterations_per_step", "SCF iterations each MD step needed",
    buckets=(1, 2, 3, 5, 8, 12, 20, 40, 80))
_DRIFT = obs_metrics.REGISTRY.gauge(
    "md_conserved_drift_ha", "conserved-quantity drift from step 0")
_XERR = obs_metrics.REGISTRY.gauge(
    "md_extrapolation_rel_error", "relative ASPC density prediction error")
from sirius_tpu.md.integrator import (
    BOHR_TO_ANG,
    FS_TO_AU,
    HA_TO_EV,
    ConservedTracker,
    Thermostat,
    kinetic_energy,
    masses_au,
    maxwell_boltzmann_velocities,
    temperature_k,
    velocity_verlet_step,
)

# 1 Ha/bohr^3 in GPa (for the optional per-step pressure report)
HA_BOHR3_TO_GPA = 29421.02648438959


def default_md_autosave_path(cfg, base_dir: str) -> str:
    """MD restart checkpoint location: control.autosave_path when set,
    else <base_dir>/sirius_md_autosave[.tag].h5 (job-scoped like the SCF
    autosave so shared workdirs do not clobber)."""
    explicit = str(getattr(cfg.control, "autosave_path", "") or "")
    if explicit:
        return explicit
    tag = str(getattr(cfg.control, "autosave_tag", "") or "")
    name = f"sirius_md_autosave.{tag}.h5" if tag else "sirius_md_autosave.h5"
    return os.path.join(base_dir, name)


def _orthonormalize(psi: np.ndarray) -> np.ndarray:
    """Per-(k, spin) QR re-orthonormalization of an extrapolated psi: the
    linear combination of orthonormal history members is only approximately
    orthonormal, and the band solver expects a proper frame. Masked G rows
    are zero in every history member, so they stay zero."""
    out = np.empty_like(psi)
    nk, ns = psi.shape[:2]
    for ik in range(nk):
        for ispn in range(ns):
            q, _ = np.linalg.qr(psi[ik, ispn].T)
            out[ik, ispn] = q.T
    return out


def _write_xyz_frame(fh, ctx, r_cart, velocities, forces, step, e_pot_ha):
    """Append one extended-XYZ frame (ase-compatible): positions [Å],
    velocities [Å/fs], forces [eV/Å], energy [eV]."""
    uc = ctx.unit_cell
    lat = (uc.lattice * BOHR_TO_ANG).reshape(-1)
    syms = [uc.atom_types[t].symbol for t in uc.type_of_atom]
    fh.write(f"{uc.num_atoms}\n")
    fh.write(
        'Lattice="' + " ".join(f"{x:.10f}" for x in lat) + '" '
        "Properties=species:S:1:pos:R:3:vel:R:3:forces:R:3 "
        f"energy={e_pot_ha * HA_TO_EV:.10f} step={step} pbc=\"T T T\"\n"
    )
    pos = r_cart * BOHR_TO_ANG
    vel = velocities * BOHR_TO_ANG * FS_TO_AU  # bohr/a.u. -> Å/fs
    frc = forces * (HA_TO_EV / BOHR_TO_ANG)
    for i, s in enumerate(syms):
        fh.write(
            f"{s:2s} "
            + " ".join(f"{x: .10f}" for x in pos[i])
            + " " + " ".join(f"{x: .10f}" for x in vel[i])
            + " " + " ".join(f"{x: .10f}" for x in frc[i])
            + "\n"
        )
    fh.flush()


def run_md(*args, **kwargs) -> dict:
    """Trace-context front door (see _run_md_impl): one trace for the
    whole trajectory — every md_step and inner SCF span shares it, so a
    timeline export reconstructs the full MD run, and an ambient trace
    (serve/campaigns) is continued rather than forked."""
    with obs_tracing.ensure_trace():
        return _run_md_impl(*args, **kwargs)


def _run_md_impl(
    cfg,
    base_dir: str = ".",
    ctx=None,
    exec_cache=None,
    resume: str | None = None,
) -> dict:
    """Run cfg.md.num_steps of Born-Oppenheimer MD; returns the per-step
    records, conserved-quantity drift, SCF cost and recompile statistics.

    resume: path to a /md checkpoint (default_md_autosave_path) — continues
    the trajectory from the saved step, replaying the uninterrupted run.
    exec_cache: shared serve.cache.ExecutableCache (created when None)."""
    from sirius_tpu.dft.geometry import context_at_positions, warm_start_state
    from sirius_tpu.dft.scf import run_scf
    from sirius_tpu.io.checkpoint import load_state, save_state
    from sirius_tpu.serve.cache import (
        ExecutableCache,
        backend_compiles_total,
        install_compile_listener,
    )
    from sirius_tpu.utils import faults

    md = cfg.md
    if md.num_steps < 1:
        raise ValueError(f"md.num_steps must be >= 1, got {md.num_steps}")
    if md.dt_fs <= 0.0:
        raise ValueError(f"md.dt_fs must be positive, got {md.dt_fs}")
    # forces every step are the point of BOMD; stress only when asked
    cfg.control.print_forces = True
    if md.compute_stress:
        cfg.control.print_stress = True
    # the MD driver owns checkpointing; a mid-SCF autosave inside each step
    # would clobber the trajectory file with single-step state
    cfg.control.autosave_every = 0

    install_compile_listener()
    if exec_cache is None:
        exec_cache = ExecutableCache()
    if ctx is None:
        # honours the species-file-free "synthetic" deck section the same
        # way sirius-serve does; plain decks fall through to
        # SimulationContext.create
        from sirius_tpu.serve.scheduler import build_job_context

        ctx = build_job_context(cfg, base_dir)
    uc0 = ctx.unit_cell
    natoms = uc0.num_atoms
    if natoms < 1:
        raise ValueError("MD needs at least one atom")
    lattice = np.asarray(uc0.lattice, dtype=np.float64)
    lat_inv = np.linalg.inv(lattice)
    masses = masses_au(uc0)
    dt = md.dt_fs * FS_TO_AU

    thermostat = Thermostat(
        ensemble=md.ensemble,
        temperature=md.temperature_k,
        tau_fs=md.thermostat_tau_fs,
        seed=md.seed,
        remove_com=md.remove_com,
    )
    tracker = ConservedTracker(natoms)
    rho_x = AspcExtrapolator(md.extrapolation_order, md.extrapolation_kind)
    psi_x = SubspaceExtrapolator(
        md.extrapolation_order if md.extrapolate_psi else 0,
        md.extrapolation_kind,
    )

    autosave_path = default_md_autosave_path(cfg, base_dir)
    compiles_start = backend_compiles_total()
    scf_iters: list[int] = []
    carry = {"state": None}  # previous step's converged _state (mag/PAW ride)

    def evaluate(r_cart, step_index):
        """SCF + forces at cartesian positions; the force_fn of the
        integrator. Warm-starts from the extrapolators, falls back to a
        cold superposition-of-atoms start when the warm SCF fails."""
        frac = r_cart @ lat_inv
        ctx_step = context_at_positions(cfg, base_dir, frac, uc0)
        if md.extrapolation_kind == "off":
            # true A/B baseline: superposition-of-atoms cold start every
            # step, no carry-over at all (tools/bench_md.py measures the
            # extrapolation payoff against exactly this)
            init = None
        else:
            with obs_spans.span("md.extrapolate", step=step_index):
                rho_pred = rho_x.predict()
                psi_pred = psi_x.predict()
                if psi_pred is not None:
                    psi_pred = _orthonormalize(psi_pred)
                init = warm_start_state(
                    carry["state"], rho_g=rho_pred, psi=psi_pred
                )
        with obs_spans.span("md.scf", step=step_index, warm=init is not None):
            res = run_scf(
                cfg, base_dir, ctx=ctx_step, initial_state=init,
                keep_state=True, exec_cache=exec_cache,
            )
        if not res.get("converged", False) and init is not None:
            # MD-level recovery ladder rung: the extrapolated guess can be
            # poisoned after an SCF-level recovery event; one cold retry
            warnings.warn(
                f"MD step {step_index}: warm-started SCF did not converge; "
                "retrying from the atomic superposition"
            )
            with obs_spans.span("md.scf", step=step_index, warm=False):
                res = run_scf(
                    cfg, base_dir, ctx=ctx_step, keep_state=True,
                    exec_cache=exec_cache,
                )
        if not res.get("converged", False):
            warnings.warn(
                f"MD step {step_index}: SCF unconverged after cold retry; "
                "continuing with the last iterate's forces"
            )
        state = res["_state"]
        carry["state"] = state
        xerr = None
        if init is not None and init.get("rho_g") is not None:
            # how good was the predictor? relative L2 distance between the
            # extrapolated density and the converged one
            rho_conv = np.asarray(state["rho_g"])
            dnorm = np.linalg.norm(rho_conv)
            if dnorm > 0:
                xerr = float(
                    np.linalg.norm(np.asarray(init["rho_g"]) - rho_conv)
                    / dnorm)
                _XERR.set(xerr)
        rho_x.push(state["rho_g"])
        psi_x.push(state["psi"])
        f = np.asarray(res["forces"], dtype=np.float64)
        e_pot = float(res["energy"]["free"])
        extra = {
            "scf_iterations": int(res["num_scf_iterations"]),
            "converged": bool(res.get("converged", False)),
            "recovery": res.get("recovery"),
            "extrapolation_error": xerr,
        }
        if md.compute_stress and "stress" in res:
            s = np.asarray(res["stress"], dtype=np.float64)
            extra["stress"] = s
            extra["pressure_gpa"] = float(-np.trace(s) / 3.0 * HA_BOHR3_TO_GPA)
        scf_iters.append(extra["scf_iterations"])
        return f, e_pot, extra

    step0 = 0
    if resume:
        saved = load_state(resume, ctx)
        mdres = saved.get("md")
        if mdres is None:
            raise ValueError(
                f"checkpoint '{resume}' has no /md group (not an MD "
                "restart file, or the G set changed since it was written)"
            )
        step0 = int(mdres["step"])
        r_cart = np.asarray(mdres["positions_cart"], dtype=np.float64)
        velocities = np.asarray(mdres["velocities"], dtype=np.float64)
        f_cur = np.asarray(mdres["forces"], dtype=np.float64)
        e_pot = float(mdres["e_pot"])
        tracker.restore(mdres)
        rho_x.restore(mdres.get("rho_history"))
        psi_x.restore(mdres.get("psi_history"))
        carry["state"] = {
            "rho_g": np.asarray(saved["rho_g"]),
            "mag_g": saved.get("mag_g"),
            "psi": np.asarray(saved["psi"]) if "psi" in saved else None,
            "paw_dm": saved.get("paw_dm"),
        }
    else:
        r_cart = np.asarray(uc0.positions, dtype=np.float64) @ lattice
        velocities = maxwell_boltzmann_velocities(
            masses, md.temperature_k, seed=md.seed, remove_com=md.remove_com
        )
        f_cur, e_pot, _ = evaluate(r_cart, step_index=0)

    records: list[dict] = []
    traj_fh = None
    if md.trajectory_path:
        tpath = md.trajectory_path
        if not os.path.isabs(tpath):
            tpath = os.path.join(base_dir, tpath)
        traj_fh = open(tpath, "a" if resume else "w")
        if not resume:
            _write_xyz_frame(
                traj_fh, ctx, r_cart, velocities, f_cur, 0, e_pot
            )
    compiles_after_first = None
    t_start = time.time()

    def checkpoint(step_done):
        md_state = {
            "step": step_done,
            "positions_cart": r_cart,
            "velocities": velocities,
            "forces": f_cur,
            "e_pot": e_pot,
            "seed": md.seed,
            "dt_fs": md.dt_fs,
            "ensemble": md.ensemble,
        }
        md_state.update(tracker.export())
        rh, ph = rho_x.export(), psi_x.export()
        if rh is not None:
            md_state["rho_history"] = rh
        if ph is not None:
            md_state["psi_history"] = ph
        state = carry["state"] or {}
        save_state(
            autosave_path, ctx,
            rho_g=np.asarray(state.get("rho_g")),
            mag_g=state.get("mag_g"),
            psi=state.get("psi"),
            paw_dm=state.get("paw_dm"),
            md_state=md_state,
        )
        obs_events.emit("checkpoint", step=step_done, path=autosave_path,
                        scope="md")
        # simulate preemption right after the durable checkpoint: the
        # resumed trajectory must replay the uninterrupted one
        faults.check("md.autosave_kill", step_done)

    try:
        if not resume:
            tracker.record(kinetic_energy(velocities, masses), e_pot)
        for step in range(step0, md.num_steps):
            n0 = backend_compiles_total()
            t_step0 = time.time()
            with job_context(step=step + 1):
                # md.integrate parents the md.extrapolate / md.scf spans
                # fired from the evaluate() force callback
                with obs_spans.span("md.integrate", step=step + 1):
                    r_cart, velocities, f_cur, e_pot, extra = (
                        velocity_verlet_step(
                            r_cart, velocities, f_cur, masses, dt, thermostat,
                            step, lambda r: evaluate(r, step_index=step + 1),
                            tracker,
                        ))
            e_kin = kinetic_energy(velocities, masses)
            e_cons = tracker.record(e_kin, e_pot)
            rec = {
                "step": step + 1,
                "time_fs": (step + 1) * md.dt_fs,
                "e_pot": e_pot,
                "e_kin": e_kin,
                "e_cons": e_cons,
                "temperature_k": temperature_k(
                    velocities, masses, md.remove_com
                ),
                "scf_iterations": extra["scf_iterations"],
                "converged": extra["converged"],
                "backend_compiles": backend_compiles_total() - n0,
            }
            if "pressure_gpa" in extra:
                rec["pressure_gpa"] = extra["pressure_gpa"]
            records.append(rec)
            _STEPS.inc()
            _STEP_SECONDS.observe(time.time() - t_step0)
            _SCF_PER_STEP.observe(rec["scf_iterations"])
            drift_now = tracker.drift()
            _DRIFT.set(drift_now["max_abs"])
            obs_events.emit(
                "md_step", **rec, drift=drift_now["max_abs"],
                dt=time.time() - t_step0,
                extrapolation_error=extra.get("extrapolation_error"),
            )
            if step == step0:
                compiles_after_first = backend_compiles_total()
            if traj_fh is not None:
                _write_xyz_frame(
                    traj_fh, ctx, r_cart, velocities, f_cur, step + 1, e_pot
                )
            if md.autosave_every > 0 and (step + 1) % md.autosave_every == 0:
                checkpoint(step + 1)
    finally:
        if traj_fh is not None:
            traj_fh.close()

    elapsed = time.time() - t_start
    steps_run = md.num_steps - step0
    return {
        "records": records,
        "num_steps": md.num_steps,
        "steps_run": steps_run,
        "dt_fs": md.dt_fs,
        "ensemble": md.ensemble,
        "positions_cart": r_cart.tolist(),
        "positions_frac": (r_cart @ lat_inv).tolist(),
        "velocities": velocities.tolist(),
        "forces": f_cur.tolist(),
        "drift": tracker.drift(),
        "scf_iterations": scf_iters,
        "mean_scf_iterations": (
            float(np.mean(scf_iters)) if scf_iters else 0.0
        ),
        "backend_compiles_total": backend_compiles_total() - compiles_start,
        "backend_compiles_after_first_step": (
            backend_compiles_total() - compiles_after_first
            if compiles_after_first is not None
            else 0
        ),
        "steps_per_minute": (
            60.0 * steps_run / elapsed if elapsed > 0 else 0.0
        ),
        "elapsed_s": elapsed,
        "exec_cache": exec_cache.stats(),
        "autosave_path": autosave_path,
    }


def run_md_from_file(path: str, resume: str | None = None) -> int:
    """CLI entry body: load the deck, run the trajectory, write
    md_output.json next to the working directory and print a per-step
    summary line (the sirius-scf output.json convention)."""
    from sirius_tpu.config import load_config

    cfg = load_config(path)
    base_dir = os.path.dirname(os.path.abspath(path))
    if resume == "auto":
        from sirius_tpu.io.checkpoint import find_resumable

        resume = find_resumable(default_md_autosave_path(cfg, base_dir))
        if resume:
            logger.warning("resuming MD from %s", resume)
    result = run_md(cfg, base_dir, resume=resume)
    for rec in result["records"]:
        print(
            f"step {rec['step']:5d}  t={rec['time_fs']:9.3f} fs  "
            f"E_pot={rec['e_pot']:.10f} Ha  T={rec['temperature_k']:8.2f} K  "
            f"E_cons={rec['e_cons']:.10f} Ha  "
            f"scf_iters={rec['scf_iterations']}"
        )
    d = result["drift"]
    print(
        f"conserved-quantity drift: {d['max_abs']:.3e} Ha "
        f"({d['max_abs_per_atom']:.3e} Ha/atom); "
        f"mean SCF iterations/step: {result['mean_scf_iterations']:.2f}; "
        f"backend compiles after first step: "
        f"{result['backend_compiles_after_first_step']}"
    )
    with open("md_output.json", "w") as f:
        json.dump(result, f, indent=2, default=float)
    return 0


def main(argv: list[str] | None = None) -> int:
    """sirius-md mini-app (pyproject [project.scripts])."""
    import argparse

    p = argparse.ArgumentParser(
        prog="sirius-md",
        description="Born-Oppenheimer molecular dynamics on the "
        "TPU-native SCF engine (sirius_tpu.md)",
    )
    p.add_argument("input", nargs="?", default="sirius.json",
                   help="JSON input file with an 'md' section")
    p.add_argument(
        "--resume", default=None, metavar="PATH|auto",
        help="resume from an /md checkpoint; 'auto' probes the default "
        "autosave path",
    )
    p.add_argument(
        "--platform", default=None, choices=["cpu", "tpu", "axon"],
        help="JAX platform (same semantics as sirius-scf)",
    )
    p.add_argument("-v", "--verbose", action="count", default=0,
                   help="raise log level (-v info, -vv debug)")
    args = p.parse_args(argv)
    if not os.path.isfile(args.input):
        print(f"sirius-md: input file not found: {args.input}",
              file=sys.stderr)
        return 2
    from sirius_tpu.obs.log import setup as _log_setup

    _log_setup(args.verbose)
    import jax

    platform = args.platform
    if platform is None:
        try:
            with open(args.input) as f:
                if (json.load(f).get("control", {})
                        .get("processing_unit") == "cpu"):
                    platform = "cpu"
        except (OSError, json.JSONDecodeError):
            pass
    if platform:
        jax.config.update(
            "jax_platforms", "axon" if platform == "tpu" else platform
        )
    return run_md_from_file(args.input, resume=args.resume)


if __name__ == "__main__":
    raise SystemExit(main())
