"""Born-Oppenheimer molecular dynamics on the TPU-native SCF engine.

- integrator.py: velocity-Verlet NVE plus Langevin and Bussi/CSVR NVT
  thermostats, mass handling, conserved-quantity tracking
- extrapolate.py: ASPC density extrapolation and subspace-aligned
  wave-function extrapolation across steps
- driver.py: the step loop (run_scf -> total_forces -> integrate) with
  compile-once executable reuse, trajectory writing and /md restart
"""

from sirius_tpu.md.driver import run_md, run_md_from_file  # noqa: F401
from sirius_tpu.md.extrapolate import (  # noqa: F401
    AspcExtrapolator,
    SubspaceExtrapolator,
    aspc_coefficients,
    poly_coefficients,
)
from sirius_tpu.md.integrator import (  # noqa: F401
    ConservedTracker,
    Thermostat,
    masses_au,
    maxwell_boltzmann_velocities,
)
