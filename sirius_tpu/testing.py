"""Self-contained synthetic systems for benchmarks, compile checks and the
multi-chip dry run — no species files needed: an analytic erf-Coulomb local
potential plus Gaussian beta projectors with a small augmentation channel,
shaped like a real ultrasoft silicon run."""

from __future__ import annotations

import numpy as np

from sirius_tpu.config.schema import Config
from sirius_tpu.context import SimulationContext
from sirius_tpu.crystal.atom_type import (
    AtomType,
    AtomicWf,
    AugmentationChannel,
    BetaProjector,
)


def synthetic_silicon_type(zn: float = 4.0, ultrasoft: bool = True) -> AtomType:
    from scipy.special import erf

    r = np.geomspace(1e-6, 12.0, 700)
    vloc = -zn * erf(r) / r
    # two beta channels (l=0, l=1), smooth nodeless shapes (r*beta(r))
    rb0 = r * np.exp(-(r**2)) * 2.0
    rb1 = r * r * np.exp(-(r**2)) * 1.5
    betas = [BetaProjector(l=0, rbeta=rb0, nr=len(r)), BetaProjector(l=1, rbeta=rb1, nr=len(r))]
    d_ion = np.array([[0.8, 0.0], [0.0, 0.4]])
    aug = []
    if ultrasoft:
        # one l=0 augmentation channel per radial pair (r^2-weighted Gaussians)
        q00 = 0.05 * r**2 * np.exp(-2.0 * r**2)
        q11 = 0.03 * r**2 * np.exp(-2.0 * r**2)
        aug = [
            AugmentationChannel(i=0, j=0, l=0, qr=q00),
            AugmentationChannel(i=1, j=1, l=0, qr=q11),
        ]
    wfs = [
        AtomicWf(l=0, occupation=2.0, chi=r * np.exp(-0.8 * r), label="3S"),
        AtomicWf(l=1, occupation=2.0, chi=r * r * np.exp(-0.8 * r), label="3P"),
    ]
    rho = 4.0 * np.pi * r**2 * (zn * 0.4**3 / np.pi) * np.exp(-0.8 * r) * 0.5
    return AtomType(
        label="Si", symbol="Si", zn=zn, pseudo_type="US" if ultrasoft else "NC",
        r=r, vloc=vloc, beta=betas, d_ion=d_ion, augmentation=aug,
        atomic_wfs=wfs, rho_total=rho, rho_core=None, core_correction=False,
    )


def synthetic_silicon_context(
    gk_cutoff: float = 6.0,
    pw_cutoff: float = 20.0,
    ngridk=(2, 2, 2),
    num_bands: int | None = None,
    ultrasoft: bool = True,
    use_symmetry: bool = True,
    positions: np.ndarray | None = None,
    extra_params: dict | None = None,
    moments: np.ndarray | None = None,
    supercell: int = 1,
) -> SimulationContext:
    """Diamond-Si-like 2-atom cell with the synthetic species.

    supercell=n replicates the cell n x n x n (2 n^3 atoms) — the
    Si-supercell-class bench tier (BASELINE.md flagship regime)."""
    import sirius_tpu.crystal.unit_cell as ucm

    params = {
        "gk_cutoff": gk_cutoff,
        "pw_cutoff": pw_cutoff,
        "ngridk": list(ngridk),
        "use_symmetry": use_symmetry,
        "num_bands": num_bands if num_bands else -1,
        "xc_functionals": ["XC_LDA_X", "XC_LDA_C_PZ"],
        "smearing_width": 0.025,
    }
    if extra_params:
        params.update(extra_params)
    cfg = Config.from_dict({"parameters": params})
    a = 10.26
    lattice = a / 2 * np.array([[0.0, 1, 1], [1, 0, 1], [1, 1, 0]])
    t = synthetic_silicon_type(ultrasoft=ultrasoft)
    if positions is None:
        positions = np.array([[0.0, 0, 0], [0.25, 0.25, 0.25]])
    positions = np.asarray(positions, dtype=np.float64)
    if supercell > 1 and moments is not None:
        raise ValueError("supercell>1 with explicit moments: tile them "
                         "yourself (per-atom moments must cover all images)")
    if supercell > 1:
        n = supercell
        shifts = np.array(
            [[i, j, k] for i in range(n) for j in range(n) for k in range(n)],
            dtype=np.float64,
        )
        positions = (
            (positions[None, :, :] + shifts[:, None, :]) / n
        ).reshape(-1, 3)
        lattice = lattice * n
    uc = ucm.UnitCell(
        lattice=lattice,
        atom_types=[t],
        type_of_atom=np.zeros(len(positions), dtype=np.int32),
        positions=positions,
        moments=(
            np.zeros((len(positions), 3))
            if moments is None else np.asarray(moments, float)
        ),
    )
    # SimulationContext.create reads species from files; build the parts
    # directly instead (same code path below the unit-cell level).
    import sirius_tpu.context as cm

    orig = ucm.UnitCell.from_config
    try:
        ucm.UnitCell.from_config = staticmethod(lambda c, b=".": uc)
        ctx = cm.SimulationContext.create(cfg, ".")
    finally:
        ucm.UnitCell.from_config = orig
    return ctx


# --------------------------------------------------------------------------
# Runtime lock-order monitor (sirius-lint's dynamic counterpart)
#
# The static lock rules in sirius_tpu.analysis.lockrules prove the absence
# of ordering cycles over the *declared* call graph; this shim checks the
# orders that actually happen at runtime, including paths the static model
# cannot resolve (dynamic dispatch, callbacks crossing threads).  Within a
# monitoring window every threading.Lock/RLock *created* in a matching
# source file is wrapped; each acquisition while other monitored locks are
# held records a directed edge (held -> acquired).  Seeing both A->B and
# B->A — or any longer cycle — is a latent deadlock even if this particular
# run never interleaved badly.

import sys as _sys
import threading as _threading


class _MonitoredLock:
    """Wraps a real Lock/RLock; delegates Condition's private protocol."""

    def __init__(self, inner, name, monitor, reentrant):
        self._sl_inner = inner
        self._sl_name = name
        self._sl_mon = monitor
        self._sl_reentrant = reentrant

    def acquire(self, blocking=True, timeout=-1):
        ok = self._sl_inner.acquire(blocking, timeout)
        if ok:
            self._sl_mon._note_acquire(self)
        return ok

    def release(self):
        self._sl_mon._note_release(self)
        self._sl_inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()

    def locked(self):
        return self._sl_inner.locked()

    # Condition(lock) probes for these via hasattr and, finding them here,
    # uses them for wait()'s release/reacquire — keep the held-stack honest.
    def _release_save(self):
        self._sl_mon._note_release(self, all_recursion=True)
        inner = self._sl_inner
        if hasattr(inner, "_release_save"):
            return inner._release_save()
        inner.release()
        return None

    def _acquire_restore(self, state):
        inner = self._sl_inner
        if hasattr(inner, "_acquire_restore"):
            inner._acquire_restore(state)
        else:
            inner.acquire()
        self._sl_mon._note_acquire(self)

    def _is_owned(self):
        inner = self._sl_inner
        if hasattr(inner, "_is_owned"):
            return inner._is_owned()
        # plain Lock: non-blocking probe on the raw lock (not monitored)
        if inner.acquire(False):
            inner.release()
            return False
        return True

    def __repr__(self):
        return f"<MonitoredLock {self._sl_name}>"


class LockOrderMonitor:
    """Patch threading.Lock/RLock in a window and record acquisition order.

    Usage::

        with LockOrderMonitor(scope="sirius_tpu/serve") as mon:
            ...exercise the code...
        mon.assert_clean()

    Only locks whose creation site's filename contains ``scope`` are
    wrapped; everything else gets the real lock, so third-party code in
    the window is unaffected.  Edges and violations survive ``__exit__``
    (wrapped locks keep reporting), so a module-scoped pytest fixture can
    assert once at teardown.
    """

    def __init__(self, scope: str = "sirius_tpu/serve"):
        self.scope = scope
        self.edges: dict[tuple[str, str], tuple[str, str]] = {}
        self.violations: list[str] = []
        self._tls = _threading.local()
        self._state = _threading.Lock()  # guards edges/violations
        self._orig_lock = None
        self._orig_rlock = None

    # -- patch window ------------------------------------------------------

    def _creation_site(self):
        f = _sys._getframe(2)
        while f is not None:
            fn = f.f_code.co_filename.replace("\\", "/")
            if __file__.replace("\\", "/") != fn and "threading" not in fn:
                return fn, f.f_lineno
            f = f.f_back
        return "<unknown>", 0

    def _factory(self, orig, reentrant):
        def make(*a, **kw):
            inner = orig(*a, **kw)
            fn, line = self._creation_site()
            if self.scope not in fn:
                return inner
            name = f"{fn.rsplit('/sirius_tpu/', 1)[-1]}:{line}"
            return _MonitoredLock(inner, name, self, reentrant)
        return make

    def __enter__(self):
        self._orig_lock = _threading.Lock
        self._orig_rlock = _threading.RLock
        _threading.Lock = self._factory(self._orig_lock, reentrant=False)
        _threading.RLock = self._factory(self._orig_rlock, reentrant=True)
        return self

    def __exit__(self, *exc):
        _threading.Lock = self._orig_lock
        _threading.RLock = self._orig_rlock
        return False

    # -- recording ---------------------------------------------------------

    def _held(self):
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _note_acquire(self, lock):
        stack = self._held()
        tname = _threading.current_thread().name
        new = lock._sl_name
        with self._state:
            for held in stack:
                if held is lock:
                    continue  # RLock reentry: not an ordering edge
                a, b = held._sl_name, new
                if a == b:
                    continue
                self.edges.setdefault((a, b), (tname, ""))
                if (b, a) in self.edges:
                    other = self.edges[(b, a)][0]
                    self.violations.append(
                        f"lock-order inversion: {a} -> {b} (thread {tname})"
                        f" vs {b} -> {a} (thread {other})"
                    )
        stack.append(lock)

    def _note_release(self, lock, all_recursion=False):
        stack = self._held()
        if all_recursion:
            self._tls.stack = [h for h in stack if h is not lock]
            return
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] is lock:
                del stack[i]
                return

    # -- verdict -----------------------------------------------------------

    def _cycles(self):
        graph: dict[str, set[str]] = {}
        for a, b in self.edges:
            graph.setdefault(a, set()).add(b)
        cycles, done = [], set()
        def dfs(node, path, on_path):
            if node in on_path:
                cycles.append(path[path.index(node):])
                return
            if node in done:
                return
            on_path.add(node)
            for nxt in graph.get(node, ()):
                dfs(nxt, path + [nxt], on_path)
            on_path.discard(node)
            done.add(node)
        for start in list(graph):
            dfs(start, [start], set())
        return cycles

    def assert_clean(self):
        problems = list(self.violations)
        for cyc in self._cycles():
            problems.append("lock-order cycle: " + " -> ".join(cyc))
        if problems:
            raise AssertionError(
                "LockOrderMonitor found %d problem(s):\n  %s"
                % (len(problems), "\n  ".join(sorted(set(problems))))
            )
