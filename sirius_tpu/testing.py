"""Self-contained synthetic systems for benchmarks, compile checks and the
multi-chip dry run — no species files needed: an analytic erf-Coulomb local
potential plus Gaussian beta projectors with a small augmentation channel,
shaped like a real ultrasoft silicon run."""

from __future__ import annotations

import numpy as np

from sirius_tpu.config.schema import Config
from sirius_tpu.context import SimulationContext
from sirius_tpu.crystal.atom_type import (
    AtomType,
    AtomicWf,
    AugmentationChannel,
    BetaProjector,
)


def synthetic_silicon_type(zn: float = 4.0, ultrasoft: bool = True) -> AtomType:
    from scipy.special import erf

    r = np.geomspace(1e-6, 12.0, 700)
    vloc = -zn * erf(r) / r
    # two beta channels (l=0, l=1), smooth nodeless shapes (r*beta(r))
    rb0 = r * np.exp(-(r**2)) * 2.0
    rb1 = r * r * np.exp(-(r**2)) * 1.5
    betas = [BetaProjector(l=0, rbeta=rb0, nr=len(r)), BetaProjector(l=1, rbeta=rb1, nr=len(r))]
    d_ion = np.array([[0.8, 0.0], [0.0, 0.4]])
    aug = []
    if ultrasoft:
        # one l=0 augmentation channel per radial pair (r^2-weighted Gaussians)
        q00 = 0.05 * r**2 * np.exp(-2.0 * r**2)
        q11 = 0.03 * r**2 * np.exp(-2.0 * r**2)
        aug = [
            AugmentationChannel(i=0, j=0, l=0, qr=q00),
            AugmentationChannel(i=1, j=1, l=0, qr=q11),
        ]
    wfs = [
        AtomicWf(l=0, occupation=2.0, chi=r * np.exp(-0.8 * r), label="3S"),
        AtomicWf(l=1, occupation=2.0, chi=r * r * np.exp(-0.8 * r), label="3P"),
    ]
    rho = 4.0 * np.pi * r**2 * (zn * 0.4**3 / np.pi) * np.exp(-0.8 * r) * 0.5
    return AtomType(
        label="Si", symbol="Si", zn=zn, pseudo_type="US" if ultrasoft else "NC",
        r=r, vloc=vloc, beta=betas, d_ion=d_ion, augmentation=aug,
        atomic_wfs=wfs, rho_total=rho, rho_core=None, core_correction=False,
    )


def synthetic_silicon_context(
    gk_cutoff: float = 6.0,
    pw_cutoff: float = 20.0,
    ngridk=(2, 2, 2),
    num_bands: int | None = None,
    ultrasoft: bool = True,
    use_symmetry: bool = True,
    positions: np.ndarray | None = None,
    extra_params: dict | None = None,
    moments: np.ndarray | None = None,
    supercell: int = 1,
) -> SimulationContext:
    """Diamond-Si-like 2-atom cell with the synthetic species.

    supercell=n replicates the cell n x n x n (2 n^3 atoms) — the
    Si-supercell-class bench tier (BASELINE.md flagship regime)."""
    import sirius_tpu.crystal.unit_cell as ucm

    params = {
        "gk_cutoff": gk_cutoff,
        "pw_cutoff": pw_cutoff,
        "ngridk": list(ngridk),
        "use_symmetry": use_symmetry,
        "num_bands": num_bands if num_bands else -1,
        "xc_functionals": ["XC_LDA_X", "XC_LDA_C_PZ"],
        "smearing_width": 0.025,
    }
    if extra_params:
        params.update(extra_params)
    cfg = Config.from_dict({"parameters": params})
    a = 10.26
    lattice = a / 2 * np.array([[0.0, 1, 1], [1, 0, 1], [1, 1, 0]])
    t = synthetic_silicon_type(ultrasoft=ultrasoft)
    if positions is None:
        positions = np.array([[0.0, 0, 0], [0.25, 0.25, 0.25]])
    positions = np.asarray(positions, dtype=np.float64)
    if supercell > 1 and moments is not None:
        raise ValueError("supercell>1 with explicit moments: tile them "
                         "yourself (per-atom moments must cover all images)")
    if supercell > 1:
        n = supercell
        shifts = np.array(
            [[i, j, k] for i in range(n) for j in range(n) for k in range(n)],
            dtype=np.float64,
        )
        positions = (
            (positions[None, :, :] + shifts[:, None, :]) / n
        ).reshape(-1, 3)
        lattice = lattice * n
    uc = ucm.UnitCell(
        lattice=lattice,
        atom_types=[t],
        type_of_atom=np.zeros(len(positions), dtype=np.int32),
        positions=positions,
        moments=(
            np.zeros((len(positions), 3))
            if moments is None else np.asarray(moments, float)
        ),
    )
    # SimulationContext.create reads species from files; build the parts
    # directly instead (same code path below the unit-cell level).
    import sirius_tpu.context as cm

    orig = ucm.UnitCell.from_config
    try:
        ucm.UnitCell.from_config = staticmethod(lambda c, b=".": uc)
        ctx = cm.SimulationContext.create(cfg, ".")
    finally:
        ucm.UnitCell.from_config = orig
    return ctx
