"""Embedded observability HTTP endpoint for ServeEngine.

Serves, on a daemon ThreadingHTTPServer:

- ``GET /metrics``        — Prometheus text exposition of the registry
  (device-memory gauges refreshed on scrape, so a scrape is the poll)
- ``GET /healthz``        — JSON liveness/engine summary; 200 while the
  engine accepts work, 503 after shutdown
- ``GET /debug/trace?steps=N[&dir=...]`` — arm a jax.profiler capture of
  the next N SCF iterations on any slice (obs/trace.py); 202 when armed,
  409 when a capture is already pending
- ``GET /debug/trace/status`` — capture state
- ``GET /debug/timeline[?trace_id=...&campaign=...]`` — Chrome-trace
  JSON built live from the configured event sink (obs/timeline.py);
  save the body and load it in ui.perfetto.dev. 409 when no event sink
  is configured.

Bound to 127.0.0.1 by default; ``port=0`` picks an ephemeral port
(tests, CI) exposed as ``server.port``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from sirius_tpu.obs import metrics as _metrics
from sirius_tpu.obs.log import get_logger
from sirius_tpu.obs.trace import CAPTURE

logger = get_logger("obs.http")


class _Handler(BaseHTTPRequestHandler):
    server_version = "sirius-obs/1"

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    def _send_json(self, code: int, obj) -> None:
        self._send(code, json.dumps(obj, indent=1) + "\n", "application/json")

    def do_GET(self):  # noqa: N802 (BaseHTTPRequestHandler API)
        url = urlparse(self.path)
        route = url.path.rstrip("/") or "/"
        try:
            if route == "/metrics":
                _metrics.update_device_memory_gauges()
                self._send(200, _metrics.REGISTRY.render_prometheus(),
                           "text/plain; version=0.0.4; charset=utf-8")
            elif route == "/healthz":
                health = self.server.health_fn()
                self._send_json(200 if health.get("ok", False) else 503,
                                health)
            elif route == "/debug/trace":
                q = parse_qs(url.query)
                steps = int(q.get("steps", ["5"])[0])
                tdir = q.get("dir", [self.server.default_trace_dir])[0]
                armed = CAPTURE.request(tdir, steps, force=True)
                self._send_json(202 if armed else 409,
                                {"armed": armed, **CAPTURE.status()})
            elif route == "/debug/trace/status":
                self._send_json(200, CAPTURE.status())
            elif route == "/debug/timeline":
                from sirius_tpu.obs import events as _events
                from sirius_tpu.obs import timeline as _timeline
                ev_path = _events.path()
                if not ev_path:
                    self._send_json(
                        409, {"error": "no event sink configured; start "
                                       "the engine with an events path"})
                else:
                    q = parse_qs(url.query)
                    doc = _timeline.build_chrome_trace(
                        _events.read_events(ev_path),
                        trace_id=q.get("trace_id", [None])[0],
                        campaign_id=q.get("campaign", [None])[0])
                    self._send(200, json.dumps(doc), "application/json")
            else:
                self._send_json(404, {"error": f"no route {route}"})
        except Exception as exc:
            logger.warning("obs http %s failed: %s", route, exc)
            try:
                self._send_json(500, {"error": str(exc)})
            except Exception:
                pass

    def log_message(self, format, *args):  # silence per-request stderr spam
        logger.debug("http %s", format % args)


class ObsHttpServer:
    """Lifecycle wrapper: start() binds and spins a daemon thread,
    stop() shuts the socket down. health_fn is polled per /healthz."""

    def __init__(self, port: int = 0, *, host: str = "127.0.0.1",
                 health_fn=None, default_trace_dir: str = "trace_capture"):
        self._host = host
        self._requested_port = port
        self._health_fn = health_fn or (lambda: {"ok": True})
        self._default_trace_dir = default_trace_dir
        self._httpd: ThreadingHTTPServer | None = None
        self._thread: threading.Thread | None = None

    @property
    def port(self) -> int | None:
        return self._httpd.server_address[1] if self._httpd else None

    @property
    def url(self) -> str | None:
        return f"http://{self._host}:{self.port}" if self._httpd else None

    def start(self) -> "ObsHttpServer":
        if self._httpd is not None:
            return self
        httpd = ThreadingHTTPServer((self._host, self._requested_port),
                                    _Handler)
        httpd.daemon_threads = True
        httpd.health_fn = self._health_fn
        httpd.default_trace_dir = self._default_trace_dir
        self._httpd = httpd
        self._thread = threading.Thread(
            target=httpd.serve_forever, name="obs-http", daemon=True)
        self._thread.start()
        logger.info("obs endpoint listening on %s", self.url)
        return self

    def stop(self) -> None:
        if self._httpd is None:
            return
        self._httpd.shutdown()
        self._httpd.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        self._httpd = None
        self._thread = None
