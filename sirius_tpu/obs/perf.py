"""Perf-gated bench time series (`sirius-bench` / tools/bench_regress.py).

Runs a pinned tier of synthetic decks under the span timeline
(obs/spans.py) with ``control.span_fence`` on, reduces every SCF stage to
a median + dispersion over repeats, and maintains a schema-versioned
``PERF_BASELINE.json`` *time series* — one entry per recorded run, newest
last. ``--compare`` re-measures and exits nonzero when any stage median
regresses beyond the tolerance recorded WITH the baseline (noise-aware:
each stage's tolerance is derived from its own observed dispersion, with
a generous floor so CPU jitter cannot page anyone).

Two comparison modes:

- absolute (default): stage medians in seconds — right when baseline and
  candidate run on the same machine class (the perf lab flow);
- ``--normalize``: stage *shares* of the iteration median — machine-
  independent, the mode the CI gate uses (a stage suddenly eating 2x its
  historical fraction of the iteration is a regression on any host).

Baseline schema::

    {"schema": 1,
     "series": [{"created": ..., "host": ..., "platform": ...,
                 "tiers": {"small": {"stages": {"scf.band_solve":
                     {"median_s": ..., "mad_s": ..., "p10_s": ..,
                      "p90_s": .., "n": .., "tol_ratio": ..,
                      "gflops": .., "roofline_gflops": ..,
                      "mfu": ..}, ...},
                     "iteration_median_s": .., "attributed_fraction": ..,
                     "repeats": .., "iterations": ..}}}]}
"""

from __future__ import annotations

import argparse
import json
import os
import platform as _platform
import statistics
import sys
import tempfile
import time

SCHEMA = 1

# stage tolerances never go below this ratio (CPU wall clocks are noisy;
# a 35% swing on a warm cache is routine)
MIN_TOL_RATIO = 1.5
# ignore regressions on stages faster than this (scheduler jitter floor)
ABS_FLOOR_S = 2e-3
# tolerance = max(MIN_TOL_RATIO, 1 + K * MAD/median): a stage that is
# noisy in the baseline gets proportionally more slack in the gate
TOL_MAD_K = 6.0

# pinned tiers: deck shape + iteration/repeat counts. The small tier is
# the CI deck (seconds on one CPU core); large is the perf-lab deck.
TIERS = {
    "small": {
        "gk_cutoff": 3.0, "pw_cutoff": 7.0, "num_bands": 8,
        "ngridk": [1, 1, 1], "num_dft_iter": 4, "repeats": 3,
    },
    "large": {
        "gk_cutoff": 4.0, "pw_cutoff": 9.0, "num_bands": 16,
        "ngridk": [1, 1, 1], "num_dft_iter": 3, "repeats": 2,
    },
}

# stages the gate watches (scf.setup and serve.* are not per-iteration
# and scf.readback is pure sync noise without a device)
GATED_PREFIX = "scf."
UNGATED = {"scf.setup", "scf.readback"}


def tier_deck(spec: dict) -> dict:
    """Synthetic ultrasoft-Si deck for one tier (species-file free)."""
    return {
        "parameters": {
            "gk_cutoff": spec["gk_cutoff"],
            "pw_cutoff": spec["pw_cutoff"],
            "ngridk": list(spec["ngridk"]),
            "num_bands": spec["num_bands"],
            "use_symmetry": False,
            "xc_functionals": ["XC_LDA_X", "XC_LDA_C_PZ"],
            "smearing_width": 0.025,
            "num_dft_iter": spec["num_dft_iter"],
            # never converge early: every repeat must run the full pinned
            # iteration count or medians are not comparable
            "density_tol": 1e-14,
            "energy_tol": 1e-16,
        },
        "control": {
            "ngk_pad_quantum": 16,
            "telemetry": True,
            "span_fence": True,
            "verbosity": 0,
        },
        "synthetic": {"ultrasoft": True},
    }


def _median(xs):
    return statistics.median(xs)


def _mad(xs, med):
    return statistics.median([abs(x - med) for x in xs])


def _pct(xs, q):
    s = sorted(xs)
    i = min(len(s) - 1, max(0, int(round(q * (len(s) - 1)))))
    return s[i]


def run_tier(name: str, spec: dict, repeats: int | None = None,
             base_dir: str | None = None) -> dict:
    """Measure one tier: warmup run (compiles), then `repeats` measured
    runs under a span capture; reduce to per-stage statistics."""
    from sirius_tpu.config.schema import load_config
    from sirius_tpu.dft.scf import run_scf
    from sirius_tpu.obs import metrics as obs_metrics
    from sirius_tpu.obs import spans as obs_spans
    from sirius_tpu.obs.costs import detect_platform, peak_gflops
    from sirius_tpu.serve.scheduler import build_job_context

    nrep = int(repeats or spec["repeats"])
    own_tmp = base_dir is None
    tmp = tempfile.mkdtemp(prefix=f"sirius_bench_{name}_") if own_tmp \
        else base_dir
    cfg = load_config(tier_deck(spec))
    ctx = build_job_context(cfg, tmp)
    obs_metrics.set_enabled(True)
    # warmup: pays every XLA compile so the measured repeats see only
    # steady-state execution
    run_scf(cfg, base_dir=tmp, ctx=ctx)
    caps = []
    for _ in range(nrep):
        with obs_spans.capture() as cap:
            run_scf(cfg, base_dir=tmp, ctx=ctx)
        caps.append(cap)

    stages: dict[str, dict] = {}
    names = set()
    for cap in caps:
        names |= {n for n in cap.names() if n.startswith(GATED_PREFIX)}
    iter_durs = [d for cap in caps for d in cap.durations("scf.iteration")]
    iter_med = _median(iter_durs) if iter_durs else 0.0
    for sname in sorted(names):
        durs = [d for cap in caps for d in cap.durations(sname)]
        if not durs:
            continue
        med = _median(durs)
        mad = _mad(durs, med)
        ent = {
            "median_s": med,
            "mad_s": mad,
            "p10_s": _pct(durs, 0.10),
            "p90_s": _pct(durs, 0.90),
            "n": len(durs),
            "tol_ratio": max(MIN_TOL_RATIO,
                             1.0 + TOL_MAD_K * (mad / med if med > 0 else 0.0)),
        }
        if iter_med > 0 and sname != "scf.iteration":
            ent["share"] = med / iter_med
        # roofline annotations ride on the records (obs/costs.py)
        recs = [r for cap in caps for r in cap.by_name(sname)
                if "gflops" in r]
        if recs:
            ent["gflops"] = _median([r["gflops"] for r in recs])
            ent["roofline_gflops"] = recs[-1]["roofline_gflops"]
            ent["mfu"] = _median([r["mfu"] for r in recs])
        stages[sname] = ent

    # attribution check: per-iteration stage spans must explain the
    # iteration wall time (acceptance bar: >= 0.90 with fencing on)
    per_iter = [n for n in names
                if n not in UNGATED and n != "scf.iteration"]
    attributed = sum(stages[n]["median_s"] for n in per_iter
                     if n in stages)
    return {
        "deck": {k: spec[k] for k in
                 ("gk_cutoff", "pw_cutoff", "num_bands", "num_dft_iter")},
        "repeats": nrep,
        "iterations": len(iter_durs),
        "iteration_median_s": iter_med,
        "attributed_fraction": (attributed / iter_med) if iter_med else 0.0,
        "peak_gflops": peak_gflops(detect_platform()),
        "stages": stages,
    }


def measure(tiers: list[str], repeats: int | None = None) -> dict:
    entry = {
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host": _platform.node(),
        "platform": None,
        "cpu_count": os.cpu_count(),
        "tiers": {},
    }
    from sirius_tpu.obs.costs import detect_platform

    entry["platform"] = detect_platform()
    for t in tiers:
        if t not in TIERS:
            raise SystemExit(f"unknown tier '{t}' (have {sorted(TIERS)})")
        entry["tiers"][t] = run_tier(t, TIERS[t], repeats=repeats)
    return entry


def load_baseline(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA:
        raise SystemExit(
            f"{path}: schema {doc.get('schema')!r} != supported {SCHEMA}")
    if not doc.get("series"):
        raise SystemExit(f"{path}: empty series")
    return doc


def compare(base_entry: dict, cur_entry: dict, normalize: bool = False,
            min_ratio: float | None = None) -> list[dict]:
    """Regressions of `cur_entry` vs `base_entry` (the newest series
    element). A stage present in the baseline but missing from the
    candidate is itself a regression — silently losing attribution is how
    perf gates rot."""
    regressions = []
    for tname, base_tier in base_entry["tiers"].items():
        cur_tier = cur_entry["tiers"].get(tname)
        if cur_tier is None:
            continue  # not re-measured this run (e.g. CI runs small only)
        base_iter = base_tier.get("iteration_median_s") or 0.0
        cur_iter = cur_tier.get("iteration_median_s") or 0.0
        for sname, b in base_tier["stages"].items():
            if sname in UNGATED:
                continue
            c = cur_tier["stages"].get(sname)
            if c is None:
                regressions.append({
                    "tier": tname, "stage": sname, "kind": "missing",
                    "detail": "stage present in baseline, absent now",
                })
                continue
            tol = float(b.get("tol_ratio", MIN_TOL_RATIO))
            if min_ratio is not None:
                tol = max(tol, float(min_ratio))
            if normalize and sname != "scf.iteration":
                if base_iter <= 0 or cur_iter <= 0:
                    continue
                bv = b["median_s"] / base_iter
                cv = c["median_s"] / cur_iter
                unit = "share"
            else:
                bv, cv = b["median_s"], c["median_s"]
                unit = "s"
            if bv <= 0:
                continue
            ratio = cv / bv
            if ratio > tol and (normalize
                                or (cv - bv) > ABS_FLOOR_S):
                regressions.append({
                    "tier": tname, "stage": sname, "kind": "slower",
                    "baseline": bv, "current": cv, "unit": unit,
                    "ratio": ratio, "tol_ratio": tol,
                })
    return regressions


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sirius-bench",
        description="span-attributed SCF bench + perf regression gate")
    ap.add_argument("--tiers", default="small",
                    help="comma list of tiers to run (small,large)")
    ap.add_argument("--repeats", type=int, default=None,
                    help="override the tier's pinned repeat count")
    ap.add_argument("--compare", metavar="BASELINE",
                    help="compare against the newest entry of this "
                    "PERF_BASELINE.json; exit 1 on regression")
    ap.add_argument("--update", metavar="BASELINE",
                    help="append this run to the baseline series "
                    "(creates the file if missing)")
    ap.add_argument("--normalize", action="store_true",
                    help="gate on stage shares of the iteration median "
                    "(machine-independent; the CI mode)")
    ap.add_argument("--min-ratio", type=float, default=None,
                    help="floor every stage tolerance at this ratio "
                    "(e.g. 2.0 for noisy CI hosts)")
    ap.add_argument("--out", metavar="PATH",
                    help="also write this run's entry as JSON")
    args = ap.parse_args(argv)

    tiers = [t.strip() for t in args.tiers.split(",") if t.strip()]
    entry = measure(tiers, repeats=args.repeats)

    for tname, tier in entry["tiers"].items():
        print(f"[{tname}] iteration median "
              f"{tier['iteration_median_s'] * 1e3:.2f} ms, "
              f"attributed {tier['attributed_fraction'] * 100:.1f}%")
        for sname, s in sorted(tier["stages"].items()):
            extra = ""
            if "gflops" in s:
                extra = (f"  {s['gflops']:.2f} GFLOP/s"
                         f" (roof {s['roofline_gflops']:.0f},"
                         f" mfu {s['mfu'] * 100:.2f}%)")
            print(f"  {sname:<18} {s['median_s'] * 1e3:9.3f} ms"
                  f" ±{s['mad_s'] * 1e3:.3f}{extra}")

    if args.out:
        with open(args.out, "w") as f:
            json.dump({"schema": SCHEMA, "series": [entry]}, f, indent=1)
        print(f"wrote {args.out}")

    rc = 0
    if args.compare:
        doc = load_baseline(args.compare)
        regs = compare(doc["series"][-1], entry,
                       normalize=args.normalize, min_ratio=args.min_ratio)
        if regs:
            rc = 1
            print(f"PERF REGRESSION vs {args.compare} "
                  f"({doc['series'][-1]['created']}):", file=sys.stderr)
            for r in regs:
                if r["kind"] == "missing":
                    print(f"  {r['tier']}/{r['stage']}: {r['detail']}",
                          file=sys.stderr)
                else:
                    print(f"  {r['tier']}/{r['stage']}: "
                          f"{r['baseline']:.4g} -> {r['current']:.4g} "
                          f"{r['unit']} ({r['ratio']:.2f}x > "
                          f"{r['tol_ratio']:.2f}x allowed)",
                          file=sys.stderr)
        else:
            print(f"perf gate OK vs {args.compare}")

    if args.update:
        if os.path.exists(args.update):
            doc = load_baseline(args.update)
        else:
            doc = {"schema": SCHEMA, "series": []}
        doc["series"].append(entry)
        with open(args.update, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"appended to {args.update} "
              f"({len(doc['series'])} entries)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
