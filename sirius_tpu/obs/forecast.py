"""Convergence analytics for SCF trajectories (ISSUE 14, pillar 3).

Pure-host, numpy-only estimators fed by the per-iteration scalar record
that the SCF loop already reads back (no extra device work, no extra
transfers):

``fit_decay``
    log-linear least-squares fit of the residual tail -> geometric decay
    rate per iteration (rate < 1 means contraction).
``ConvergenceForecaster``
    incremental wrapper: feed it ``(it, rms, e_total)`` each iteration and
    read the decay rate, an iterations-to-converge forecast against the
    deck's ``density_tol`` and a divergence early-warning score in [0, 1].
``replay`` / ``converged_iteration``
    run the same estimator over *recorded* ``scf_iteration`` event streams
    (obs/events.py JSONL) — this is how forecast accuracy and warning lead
    time are scored against checked-in runs in tests/test_numerics.py.

Consumers: dft/recovery.py (proactive snapshot + backoff BEFORE the
non-finite sentinel trips), dft/scf.py (``scf_forecast`` events, the
``scf_forecast_iterations`` gauge and deadline-feasibility events) and
serve/scheduler.py (deadline triage per job).
"""

from __future__ import annotations

import math

import numpy as np

__all__ = [
    "ConvergenceForecaster",
    "converged_iteration",
    "fit_decay",
    "replay",
]


def fit_decay(values) -> float:
    """Geometric per-iteration decay rate of a residual tail.

    Least-squares slope of log10(values) against the sample index,
    returned as ``10**slope``: 0.5 means the residual halves every
    iteration, 1.0 is a stall, >1 is growth.  Non-finite and non-positive
    entries are dropped (they carry no decay information); with fewer than
    two usable points the rate is undefined and NaN is returned.
    """
    v = np.asarray(list(values), dtype=np.float64)
    idx = np.arange(v.size, dtype=np.float64)
    ok = np.isfinite(v) & (v > 0.0)
    if int(ok.sum()) < 2:
        return float("nan")
    x, y = idx[ok], np.log10(v[ok])
    xm, ym = x.mean(), y.mean()
    den = float(np.sum((x - xm) ** 2))
    if den == 0.0:
        return float("nan")
    slope = float(np.sum((x - xm) * (y - ym))) / den
    return float(10.0 ** slope)


class ConvergenceForecaster:
    """Incremental decay-rate / iterations-to-converge / early-warning
    estimator over a single SCF trajectory.

    The fit window is deliberately short (``window`` trailing iterations):
    SCF convergence is piecewise-geometric — mixer history build-up,
    tolerance scheduling and recovery rollbacks all change the contraction
    factor mid-run — so a global fit would average incompatible regimes.
    """

    def __init__(self, density_tol: float, window: int = 8,
                 min_history: int = 3):
        self.tol = float(density_tol)
        self.window = max(2, int(window))
        self.min_history = max(1, int(min_history))
        self._its: list[int] = []
        self._rms: list[float] = []
        self._etot: list[float] = []
        # consecutive iterations with rms strictly above the previous one
        self._growth_streak = 0

    # ---- feeding -------------------------------------------------------

    def update(self, it: int, rms: float, e_total: float | None = None):
        """Record one iteration; returns the post-update snapshot dict
        (same shape as :meth:`snapshot`)."""
        rms = float(rms)
        prev = self._rms[-1] if self._rms else None
        if (prev is not None and math.isfinite(rms) and math.isfinite(prev)
                and rms > prev):
            self._growth_streak += 1
        else:
            self._growth_streak = 0
        self._its.append(int(it))
        self._rms.append(rms)
        self._etot.append(float(e_total) if e_total is not None else math.nan)
        return self.snapshot()

    def reset(self) -> None:
        """Drop all history (recovery rollback: the poisoned trajectory
        must not contaminate the post-rollback fit)."""
        self._its.clear()
        self._rms.clear()
        self._etot.clear()
        self._growth_streak = 0

    # ---- estimators ----------------------------------------------------

    def _tail(self) -> list[float]:
        return self._rms[-self.window:]

    def decay_rate(self) -> float:
        """Fitted geometric decay rate over the trailing window (NaN until
        two usable samples exist)."""
        return fit_decay(self._tail())

    def forecast_remaining(self) -> int | None:
        """Iterations still needed to reach ``density_tol``, extrapolating
        the fitted decay; None when no contraction is measurable (stalled,
        growing, or not enough history)."""
        if not self._rms:
            return None
        last = self._rms[-1]
        if math.isfinite(last) and last <= self.tol:
            return 0
        rate = self.decay_rate()
        if (self.tol <= 0.0
                or not math.isfinite(rate) or rate <= 0.0 or rate >= 1.0
                or not math.isfinite(last) or last <= 0.0):
            return None
        n = math.log(self.tol / last) / math.log(rate)
        return max(1, int(math.ceil(n)))

    def forecast_total(self) -> int | None:
        """Forecast of the final 1-based iteration count (current
        iteration + remaining); None when remaining is unforecastable."""
        rem = self.forecast_remaining()
        if rem is None or not self._its:
            return None
        return self._its[-1] + rem

    def warning_score(self) -> float:
        """Divergence early-warning score in [0, 1].

        1.0 before ``min_history`` samples exist — a trajectory with no
        contraction evidence yet has not earned trust, which is exactly
        what makes the score a useful proactive-snapshot trigger in the
        first iterations where fault-injection tests strike.  After that:
        >= 0.6 when the fitted rate says stall-or-growth, pushed towards
        1.0 by a sustained growth streak scaled by how many decades the
        residual climbed above its recent minimum.  A clean geometric
        contraction scores ~0.
        """
        if not self._rms:
            return 1.0
        last = self._rms[-1]
        if not math.isfinite(last):
            return 1.0
        if len(self._rms) < self.min_history:
            return 1.0
        rate = self.decay_rate()
        score = 0.0
        if not math.isfinite(rate) or rate >= 1.0:
            score = 0.6
        elif rate > 0.9:
            # near-stall: small positive score, never enough to fire alone
            score = (rate - 0.9) * 4.0
        if self._growth_streak >= 2:
            tail = [r for r in self._tail()
                    if math.isfinite(r) and r > 0.0]
            rmin = min(tail) if tail else last
            decades = math.log10(max(last / max(rmin, 1e-300), 1.0))
            score = max(score, min(1.0, 0.5 + 0.25 * decades))
        return float(min(1.0, score))

    def snapshot(self) -> dict:
        """One dict per iteration for events/tests: everything the scf
        loop emits in its ``scf_forecast`` event."""
        rate = self.decay_rate()
        rem = self.forecast_remaining()
        return {
            "it": self._its[-1] if self._its else None,
            "rms": self._rms[-1] if self._rms else None,
            "decay_rate": None if not math.isfinite(rate) else rate,
            "forecast_remaining": rem,
            "forecast_total": self.forecast_total(),
            "warning": self.warning_score(),
            "growth_streak": self._growth_streak,
            "n_history": len(self._rms),
        }


# ---- replay over recorded event streams --------------------------------


def replay(records, density_tol: float, window: int = 8,
           min_history: int = 3) -> list[dict]:
    """Run the forecaster over recorded ``scf_iteration`` events.

    ``records`` is an iterable of dicts with at least ``it`` and ``rms``
    (obs.events.read_events(path, kind="scf_iteration") output).  Returns
    one :meth:`ConvergenceForecaster.snapshot` dict per record — the
    forecaster's view *after* seeing that iteration.
    """
    fc = ConvergenceForecaster(density_tol, window=window,
                               min_history=min_history)
    return [fc.update(int(r["it"]), float(r["rms"]), r.get("e_total"))
            for r in records]


def converged_iteration(records, tol: float) -> int | None:
    """First recorded iteration whose rms is at or below ``tol`` (the
    ground truth the forecast is scored against); None if never reached."""
    for r in records:
        rms = float(r["rms"])
        if math.isfinite(rms) and rms <= float(tol):
            return int(r["it"])
    return None
