"""Timeline export: the span/event JSONL merged into one Chrome-trace /
Perfetto JSON, plus the campaign critical-path analyzer.

The span timeline (obs/spans.py) and event sink (obs/events.py) already
record everything a distributed trace needs — identity (trace_id from
obs/tracing.py), physical placement (pid, thread), lineage (span_id /
parent_id), wall-clock intervals — but as JSONL, which no timeline UI
reads. This module folds them into the Chrome trace-event format
(https://ui.perfetto.dev loads it directly):

- one *process* per OS pid seen in the records (serve engine restarts
  across a SIGKILL show up as two processes sharing one trace_id —
  exactly the story the trace should tell);
- one *thread track* per worker thread (spans become "X" complete
  events, non-span events become "i" instants on the same track);
- one synthetic *campaign process* per campaign, with a track per DAG
  node spanning its RUNNING->terminal interval, and "s"/"f" flow arrows
  along the handoff edges;
- "C" counter tracks for the per-iteration HBM high-water samples that
  dft/scf.py attaches to scf.iteration spans, and for the numerics
  observatory: the SCF residual and on-device ledger invariants
  (scf_iteration events), the decay-rate/forecast/early-warning series
  (scf_forecast events) and the per-stage precision-headroom probe
  impacts (numerics_probe events) each render as counter series;
- optionally, the jax.profiler device traces (``*.trace.json.gz``
  written by obs/trace.py captures) merged in with offset pids — one
  track per device, stitched under the same timeline (best-effort: the
  profiler's own format already IS Chrome JSON).

The critical-path analyzer reads the campaign DAG shape from the
``campaign_submit`` event (runner.py ships ``edges``), node intervals
from ``job_transition`` events, and SCF effort from ``scf_done``; it
reports the longest path, per-node slack (classic CPM es/ef/ls/lf), and
a warm-start savings estimate per handoff edge.

CLI (``sirius-trace``):

    sirius-trace export --events run/events.jsonl --out timeline.json
    sirius-trace validate timeline.json
    sirius-trace critical-path --events run/events.jsonl
"""

from __future__ import annotations

import argparse
import glob
import gzip
import json
import os
import sys
import time

from sirius_tpu.obs import events as _events
from sirius_tpu.obs import spans as _spans

_US = 1_000_000  # chrome trace timestamps are microseconds


# ---------------------------------------------------------------------------
# chrome-trace building


def _tid_for(tid_map: dict, pid: int, thread: str) -> int:
    key = (pid, str(thread))
    if key not in tid_map:
        tid_map[key] = len([k for k in tid_map if k[0] == pid]) + 1
    return tid_map[key]


def build_chrome_trace(records: list[dict], trace_id: str | None = None,
                       campaign_id: str | None = None) -> dict:
    """Fold event-sink records into a Chrome trace-event document.

    trace_id: keep only records of that trace (None = all).
    campaign_id: restrict the synthetic campaign tracks (None = all
    campaigns present).
    """
    if trace_id is not None:
        records = [r for r in records if r.get("trace_id") == trace_id]
    ev: list[dict] = []
    tid_map: dict = {}
    pids_seen: set[int] = set()

    for r in records:
        kind = r.get("kind")
        pid = int(r.get("pid") or 0)
        thread = r.get("thread") or "main"
        if kind == "span":
            tid = _tid_for(tid_map, pid, thread)
            pids_seen.add(pid)
            args = {k: v for k, v in r.items()
                    if k not in ("kind", "name", "t0", "dur_s", "ts",
                                 "pid", "thread")}
            ev.append({
                "name": r.get("name", "span"), "ph": "X", "cat": "span",
                "ts": int(float(r["t0"]) * _US),
                "dur": max(1, int(float(r["dur_s"]) * _US)),
                "pid": pid, "tid": tid, "args": args,
            })
            if r.get("hbm_peak_bytes") is not None:
                ev.append({
                    "name": "hbm_peak_bytes", "ph": "C",
                    "ts": int((float(r["t0"]) + float(r["dur_s"])) * _US),
                    "pid": pid, "tid": tid,
                    "args": {"bytes": float(r["hbm_peak_bytes"])},
                })
        elif "ts" in r:
            tid = _tid_for(tid_map, pid, thread)
            pids_seen.add(pid)
            args = {k: v for k, v in r.items()
                    if k not in ("kind", "ts", "pid", "thread")}
            ev.append({
                "name": kind or "event", "ph": "i", "cat": "event",
                "ts": int(float(r["ts"]) * _US), "s": "t",
                "pid": pid, "tid": tid, "args": args,
            })
            # numerics observatory counter tracks (obs/numerics.py +
            # obs/forecast.py): residual, ledger invariants, forecast and
            # probe headroom render as Perfetto counter series next to
            # the hbm_peak_bytes track above
            cts = int(float(r["ts"]) * _US)
            if kind == "scf_iteration":
                if isinstance(r.get("rms"), (int, float)):
                    ev.append({"name": "scf_residual", "ph": "C",
                               "ts": cts, "pid": pid, "tid": tid,
                               "args": {"rms": float(r["rms"])}})
                led = r.get("ledger")
                if isinstance(led, dict) and led:
                    ev.append({
                        "name": "numerics_ledger", "ph": "C", "ts": cts,
                        "pid": pid, "tid": tid,
                        "args": {k: float(v) for k, v in led.items()
                                 if isinstance(v, (int, float))}})
            elif kind == "scf_forecast":
                fc = {k: float(r[k]) for k in
                      ("decay_rate", "forecast_remaining", "warning")
                      if isinstance(r.get(k), (int, float))}
                if fc:
                    ev.append({"name": "scf_forecast", "ph": "C",
                               "ts": cts, "pid": pid, "tid": tid,
                               "args": fc})
            elif kind == "numerics_probe":
                if isinstance(r.get("energy_impact_ha"), (int, float)):
                    series = f"{r.get('stage')}:{r.get('prec')}"
                    ev.append({
                        "name": "numerics_headroom", "ph": "C", "ts": cts,
                        "pid": pid, "tid": tid,
                        "args": {series: float(r["energy_impact_ha"])}})

    ev.extend(_campaign_tracks(records, campaign_id))

    # metadata: name the processes and thread tracks
    meta: list[dict] = []
    for pid in sorted(pids_seen):
        meta.append({"name": "process_name", "ph": "M", "pid": pid,
                     "args": {"name": f"sirius pid {pid}"}})
    for (pid, thread), tid in sorted(tid_map.items(), key=lambda x: x[1]):
        meta.append({"name": "thread_name", "ph": "M", "pid": pid,
                     "tid": tid, "args": {"name": thread}})
    return {"traceEvents": meta + ev, "displayTimeUnit": "ms"}


def _campaign_tracks(records: list[dict],
                     campaign_id: str | None = None) -> list[dict]:
    """Synthetic per-campaign process: one track per DAG node spanning its
    RUNNING->terminal interval, with flow arrows along handoff edges."""
    submits = [r for r in records if r.get("kind") == "campaign_submit"
               and (campaign_id is None
                    or r.get("campaign_id") == campaign_id)]
    out: list[dict] = []
    for ci, sub in enumerate(submits):
        cid = sub.get("campaign_id")
        edges = sub.get("edges") or {}
        nodes = sub.get("nodes") or sorted(edges)
        pid = 90000 + ci  # out of the way of real OS pids
        out.append({"name": "process_name", "ph": "M", "pid": pid,
                    "args": {"name": f"campaign {cid}"}})
        iv = _node_intervals(records, cid)
        tids = {n: i + 1 for i, n in enumerate(nodes)}
        for n, t in tids.items():
            out.append({"name": "thread_name", "ph": "M", "pid": pid,
                        "tid": t, "args": {"name": f"node {n}"}})
            span = iv.get(n)
            if span is None:
                continue
            out.append({
                "name": f"{cid}.{n}", "ph": "X", "cat": "campaign_node",
                "ts": int(span["start"] * _US),
                "dur": max(1, int((span["end"] - span["start"]) * _US)),
                "pid": pid, "tid": t,
                "args": {"status": span["status"], "campaign_id": cid,
                         "node_id": n},
            })
        flow = 0
        for child, parents in edges.items():
            for parent in parents or []:
                if parent not in iv or child not in iv:
                    continue
                flow += 1
                fid = f"{cid}:{parent}->{child}"
                out.append({"name": "handoff", "ph": "s", "cat": "handoff",
                            "id": fid, "ts": int(iv[parent]["end"] * _US),
                            "pid": pid, "tid": tids.get(parent, 0)})
                out.append({"name": "handoff", "ph": "f", "cat": "handoff",
                            "bp": "e", "id": fid,
                            "ts": int(iv[child]["start"] * _US),
                            "pid": pid, "tid": tids.get(child, 0)})
    return out


_TERMINAL = ("done", "failed", "aborted", "skipped_upstream")


def _node_intervals(records: list[dict], cid: str) -> dict:
    """{node_id: {queued, start, end, status}} from the job_transition
    events of one campaign. ``queued`` is the submit-time transition,
    ``start`` the first COMPILING/RUNNING transition (what the timeline
    track draws; falls back to ``queued`` for nodes that never ran),
    ``end`` the terminal transition. The critical-path analyzer needs
    both anchors: the scheduler does real per-node setup (deck parsing,
    context build) between queue pop and the COMPILING transition, so
    charging a node only start->end would leak that work out of the
    wall reconciliation, while charging queued->end would charge a
    child its parent's whole runtime."""
    raw: dict[str, dict] = {}
    for r in records:
        if r.get("kind") != "job_transition" or r.get("campaign_id") != cid:
            continue
        jid = str(r.get("job_id") or "")
        node = jid[len(cid) + 1:] if jid.startswith(f"{cid}.") else jid
        ts = float(r["ts"])
        status = r.get("status")
        e = raw.setdefault(node, {"queued": ts, "start": None, "end": ts,
                                  "status": status})
        if status in ("compiling", "running") and e["start"] is None:
            e["start"] = ts
        if e["status"] not in _TERMINAL:
            e["end"] = ts
            e["status"] = status
    for e in raw.values():
        if e["start"] is None:
            e["start"] = e["queued"]
    return raw


# ---------------------------------------------------------------------------
# jax.profiler merge (best-effort: the profiler writes Chrome JSON itself)


def merge_jax_profiler_trace(doc: dict, trace_dir: str,
                             pid_offset: int = 100000) -> int:
    """Merge ``*.trace.json[.gz]`` files under ``trace_dir`` (written by
    jax.profiler / obs.trace captures) into ``doc`` with offset pids so
    device tracks sit next to the host tracks. Returns the number of
    events merged; silently returns 0 when nothing usable is found."""
    merged = 0
    pats = ("**/*.trace.json.gz", "**/*.trace.json")
    files = []
    for p in pats:
        files.extend(glob.glob(os.path.join(trace_dir, p), recursive=True))
    for i, f in enumerate(sorted(files)):
        try:
            opener = gzip.open if f.endswith(".gz") else open
            with opener(f, "rt", encoding="utf-8") as fh:
                sub = json.load(fh)
            sub_ev = sub.get("traceEvents") or []
        except Exception:
            continue
        for e in sub_ev:
            if not isinstance(e, dict) or "ph" not in e:
                continue
            e = dict(e)
            e["pid"] = int(e.get("pid") or 0) + pid_offset + i * 1000
            doc.setdefault("traceEvents", []).append(e)
            merged += 1
    return merged


# ---------------------------------------------------------------------------
# validation (the CI trace-smoke gate)

_KNOWN_PH = {"B", "E", "X", "i", "I", "C", "M", "s", "t", "f", "b", "n",
             "e", "P", "N", "O", "D"}


def validate_chrome_trace(doc: dict) -> list[str]:
    """Structural validation against the Chrome trace-event format.
    Returns a list of problems — empty means loadable."""
    problems = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    ev = doc.get("traceEvents")
    if not isinstance(ev, list):
        return ["traceEvents missing or not a list"]
    if not ev:
        problems.append("traceEvents is empty")
    for i, e in enumerate(ev):
        where = f"traceEvents[{i}]"
        if not isinstance(e, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = e.get("ph")
        if ph not in _KNOWN_PH:
            problems.append(f"{where}: unknown ph {ph!r}")
            continue
        if ph != "M":
            ts = e.get("ts")
            if not isinstance(ts, (int, float)):
                problems.append(f"{where}: ph={ph} without numeric ts")
        if ph == "X":
            dur = e.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: X event without dur >= 0")
            if not e.get("name"):
                problems.append(f"{where}: X event without name")
        if ph == "M" and e.get("name") in ("process_name", "thread_name"):
            if not isinstance(e.get("args", {}).get("name"), str):
                problems.append(f"{where}: metadata without args.name")
        for key in ("pid", "tid"):
            if key in e and not isinstance(e[key], int):
                problems.append(f"{where}: {key} not an int")
    return problems


# ---------------------------------------------------------------------------
# campaign critical path


def campaign_critical_path(records: list[dict],
                           campaign_id: str | None = None) -> dict:
    """Longest path through a campaign DAG with per-node slack and a
    warm-start savings estimate.

    Classic CPM over node *durations* (RUNNING->terminal wall): earliest
    start/finish forward, latest start/finish backward, slack = ls - es.
    ``critical_path_s`` is the duration sum along the longest chain —
    on a serial chain it reconciles with the measured campaign wall
    (acceptance: within 5%)."""
    submits = [r for r in records if r.get("kind") == "campaign_submit"]
    if campaign_id is not None:
        submits = [r for r in submits
                   if r.get("campaign_id") == campaign_id]
    if not submits:
        raise ValueError(
            f"no campaign_submit event"
            + (f" for campaign {campaign_id!r}" if campaign_id else "")
            + " in the record stream")
    sub = submits[-1]
    cid = sub["campaign_id"]
    edges: dict = sub.get("edges") or {}
    nodes = list(sub.get("nodes") or sorted(edges))
    iv = _node_intervals(records, cid)
    present = [n for n in nodes if n in iv]
    parents = {n: [p for p in (edges.get(n) or []) if p in iv]
               for n in present}
    order, seen = [], set()

    def _visit(n, stack=()):
        if n in seen:
            return
        if n in stack:
            raise ValueError(f"cycle through {n}")
        for p in parents.get(n, []):
            _visit(p, stack + (n,))
        seen.add(n)
        order.append(n)

    for n in present:
        _visit(n)
    # effective node duration: ready -> terminal, where ready = submitted
    # AND every parent terminal. This charges the node the scheduler's
    # pre-COMPILING setup (queue pop, deck parse, context build) without
    # charging it the parents' runtime — the anchor the wall
    # reconciliation needs.
    dur = {}
    for n in order:
        ready = max((iv[p]["end"] for p in parents[n]),
                    default=iv[n]["queued"])
        ready = max(ready, iv[n]["queued"])
        dur[n] = max(0.0, iv[n]["end"] - ready)
    es, ef = {}, {}
    for n in order:
        es[n] = max((ef[p] for p in parents[n]), default=0.0)
        ef[n] = es[n] + dur[n]
    cp_total = max(ef.values(), default=0.0)
    children: dict = {n: [] for n in dur}
    for n in dur:
        for p in parents[n]:
            children[p].append(n)
    lf, ls = {}, {}
    for n in reversed(order):
        lf[n] = min((ls[c] for c in children[n]), default=cp_total)
        ls[n] = lf[n] - dur[n]
    slack = {n: max(0.0, ls[n] - es[n]) for n in dur}

    # walk the zero-slack chain from the last-finishing critical node
    path = []
    cur = max((n for n in dur if abs(ef[n] - cp_total) < 1e-9),
              key=lambda n: ef[n], default=None)
    while cur is not None:
        path.append(cur)
        cur = max((p for p in parents[cur]
                   if abs(ef[p] - es[path[-1]]) < 1e-9),
                  key=lambda p: ef[p], default=None)
    path.reverse()

    # measured wall: the finalize summary when present, else the span of
    # the node intervals
    walls = [r.get("wall_s") for r in records
             if r.get("kind") == "campaign_done"
             and r.get("campaign_id") == cid]
    if walls and walls[-1]:
        measured = float(walls[-1])
    elif dur:
        measured = (max(iv[n]["end"] for n in dur)
                    - min(iv[n]["queued"] for n in dur))
    else:
        measured = 0.0

    # per-node SCF effort + warm-start savings estimate: cold nodes set
    # the baseline iteration count; a warm node's shortfall against it is
    # the handoff's saving
    modes = {}
    for r in records:
        if r.get("kind") == "campaign_handoff" and r.get(
                "campaign_id") == cid:
            modes[str(r.get("node_id"))] = r.get("mode")
    iters = {}
    for r in records:
        if r.get("kind") != "scf_done":
            continue
        jid = str(r.get("job_id") or "")
        if jid.startswith(f"{cid}."):
            iters[jid[len(cid) + 1:]] = int(r.get("iterations") or 0)
    cold = [v for n, v in iters.items() if modes.get(n) != "warm"]
    baseline = (sorted(cold)[len(cold) // 2] if cold else None)
    savings = {}
    for n, m in modes.items():
        if m == "warm" and baseline is not None and n in iters:
            savings[n] = max(0, baseline - iters[n])

    return {
        "campaign_id": cid,
        "nodes": {
            n: {
                "dur_s": round(dur[n], 3),
                "es": round(es[n], 3), "ef": round(ef[n], 3),
                "slack_s": round(slack[n], 3),
                "critical": n in path,
                "status": iv[n]["status"],
                "scf_iterations": iters.get(n),
                "handoff_mode": modes.get(n),
            } for n in dur
        },
        "critical_path": path,
        "critical_path_s": round(cp_total, 3),
        "measured_wall_s": round(measured, 3),
        "cp_over_wall": round(cp_total / measured, 3) if measured else None,
        "warm_savings_iterations": savings,
        "warm_baseline_iterations": baseline,
        "trace_id": sub.get("trace_id"),
    }


# ---------------------------------------------------------------------------
# CLI


def export_timeline(events_path: str, out_path: str | None = None,
                    trace_id: str | None = None,
                    campaign_id: str | None = None,
                    jax_trace_dir: str | None = None) -> dict:
    """events JSONL -> Chrome trace document (written to out_path when
    given). The export itself is a ``trace.export`` span."""
    t0 = time.perf_counter()
    records = _events.read_events(events_path)
    doc = build_chrome_trace(records, trace_id=trace_id,
                             campaign_id=campaign_id)
    merged = 0
    if jax_trace_dir:
        merged = merge_jax_profiler_trace(doc, jax_trace_dir)
    if out_path:
        with open(out_path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
    _spans.record("trace.export", time.perf_counter() - t0,
                  events=len(records),
                  trace_events=len(doc["traceEvents"]),
                  device_events=merged)
    return doc


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="sirius-trace",
        description="export/validate Perfetto timelines and analyze "
                    "campaign critical paths from the obs event log")
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("export", help="events JSONL -> Chrome trace JSON")
    p.add_argument("--events", required=True, help="events JSONL path")
    p.add_argument("--out", default="timeline.json")
    p.add_argument("--trace-id", default=None,
                   help="keep only this trace's records")
    p.add_argument("--campaign", default=None,
                   help="campaign id for the synthetic node tracks")
    p.add_argument("--jax-trace-dir", default=None,
                   help="merge jax.profiler *.trace.json(.gz) from here")

    p = sub.add_parser("validate",
                       help="check a file against the trace-event format")
    p.add_argument("file")

    p = sub.add_parser("critical-path",
                       help="campaign CPM report from the event log")
    p.add_argument("--events", required=True)
    p.add_argument("--campaign", default=None)
    p.add_argument("--json", action="store_true",
                   help="machine-readable output")

    args = ap.parse_args(argv)
    if args.cmd == "export":
        doc = export_timeline(args.events, out_path=args.out,
                              trace_id=args.trace_id,
                              campaign_id=args.campaign,
                              jax_trace_dir=args.jax_trace_dir)
        problems = validate_chrome_trace(doc)
        print(f"wrote {args.out}: {len(doc['traceEvents'])} events"
              + (f", {len(problems)} problems" if problems else ""))
        for pr in problems:
            print(f"  problem: {pr}", file=sys.stderr)
        return 1 if problems else 0
    if args.cmd == "validate":
        with open(args.file, encoding="utf-8") as fh:
            doc = json.load(fh)
        problems = validate_chrome_trace(doc)
        for pr in problems:
            print(f"problem: {pr}", file=sys.stderr)
        print(f"{args.file}: "
              + ("OK" if not problems else f"{len(problems)} problems"))
        return 1 if problems else 0
    if args.cmd == "critical-path":
        records = _events.read_events(args.events)
        rep = campaign_critical_path(records, campaign_id=args.campaign)
        if args.json:
            print(json.dumps(rep, indent=1))
            return 0
        print(f"campaign {rep['campaign_id']}  trace {rep['trace_id']}")
        print(f"critical path ({rep['critical_path_s']} s, wall "
              f"{rep['measured_wall_s']} s, ratio {rep['cp_over_wall']}):")
        print("  " + " -> ".join(rep["critical_path"]))
        print(f"{'node':<16}{'dur_s':>8}{'slack_s':>9}{'crit':>6}"
              f"{'iters':>7}  handoff")
        for n, d in sorted(rep["nodes"].items()):
            print(f"{n:<16}{d['dur_s']:>8.2f}{d['slack_s']:>9.2f}"
                  f"{'*' if d['critical'] else '':>6}"
                  f"{d['scf_iterations'] or '-':>7}  "
                  f"{d['handoff_mode'] or '-'}")
        if rep["warm_savings_iterations"]:
            tot = sum(rep["warm_savings_iterations"].values())
            print(f"warm-start savings: ~{tot} SCF iterations vs cold "
                  f"baseline {rep['warm_baseline_iterations']}")
        return 0
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
