"""Nestable wall-clock span timeline — the performance-attribution layer.

`utils/profiler.py` keeps the reference-style cumulative timer tree
(timers.json report); this module is the *event* view of the same
instants: every span is one record with identity (span_id), lineage
(parent_id via a contextvar, so nesting survives generators and
callbacks), monotonic start/duration, and optional analytic cost
annotations (GFLOP/s, roofline ceiling, MFU from obs/costs.py when the
producer attaches a flops/bytes estimate).

Three consumers, all fed on span close:

- the JSONL event sink (obs/events.py): one ``kind="span"`` record per
  completed span, carrying job_id/step from the logging context;
- the metrics registry: a ``perf_span_seconds`` histogram labelled by
  span name (the Prometheus-side view of the timeline);
- in-process `capture()` collectors: tools/bench_regress.py runs an SCF
  under `with capture() as cap:` and reads per-stage durations straight
  from `cap` without parsing the event log.

Device-bound spans and fencing: XLA dispatch is asynchronous, so a bare
host timer around `davidson_kset(...)` measures dispatch, not compute —
the wall time lands in whichever span first blocks (usually the scalar
readback). Durations still *sum* to the true wall time, but per-stage
attribution is skewed. Passing ``fence=`` (a jax pytree, or assigning
``sp.fence = out`` inside the block) makes ``__exit__`` call
``jax.block_until_ready`` on it first, charging the compute to the span
that launched it. run_scf wires this behind ``control.span_fence``
(default off: production never pays the sync; bench_regress turns it on
for truthful attribution).

When telemetry is disabled (``control.telemetry = false`` ->
obs.metrics.set_enabled(False)) every span is a no-op: ``__enter__``
returns after one flag test — no contextvar writes, no clock reads, no
records anywhere.
"""

from __future__ import annotations

import contextlib
import contextvars
import functools
import itertools
import threading
import time

from sirius_tpu.obs import events as _events
from sirius_tpu.obs import metrics as _metrics
from sirius_tpu.obs import tracing as _tracing

# the innermost live span of this logical context (contextvar, not a
# thread-local stack: lineage must survive contextvars-aware frameworks
# and stays isolated per serve worker thread)
_parent: contextvars.ContextVar = contextvars.ContextVar(
    "sirius_tpu_span_parent", default=None)
_next_id = itertools.count(1)

_collectors_lock = threading.Lock()
_collectors: list["SpanCapture"] = []


class SpanCapture:
    """In-process sink of finished span records (plain dicts)."""

    def __init__(self):
        self.records: list[dict] = []
        self._lock = threading.Lock()

    def add(self, rec: dict) -> None:
        with self._lock:
            self.records.append(rec)

    def by_name(self, name: str) -> list[dict]:
        with self._lock:
            return [r for r in self.records if r["name"] == name]

    def durations(self, name: str) -> list[float]:
        return [r["dur_s"] for r in self.by_name(name)]

    def names(self) -> set[str]:
        with self._lock:
            return {r["name"] for r in self.records}


@contextlib.contextmanager
def capture():
    """Collect every span finished anywhere in the process while the
    context is open (process-global, like the event sink — the producers
    span serve worker threads)."""
    cap = SpanCapture()
    with _collectors_lock:
        _collectors.append(cap)
    try:
        yield cap
    finally:
        with _collectors_lock:
            _collectors.remove(cap)


def _finish(rec: dict) -> None:
    _metrics.REGISTRY.histogram(
        "perf_span_seconds", "span-timeline durations by span name").observe(
            rec["dur_s"], span=rec["name"])
    _events.emit("span", **rec)
    with _collectors_lock:
        caps = list(_collectors)
    for cap in caps:
        cap.add(rec)


class span:
    """Context manager: ``with span("scf.density", flops=f) as sp: ...``

    ``fence``: jax pytree (or callable returning one) blocked on before
    the clock stops; assignable inside the block (``sp.fence = out``).
    ``flops``/``bytes``: analytic cost estimate for this span's work —
    when given, the record is annotated with achieved GFLOP/s, the
    roofline ceiling, and MFU against the shared peak table
    (obs/costs.py). Extra keyword arguments become record fields.
    """

    __slots__ = ("name", "attrs", "fence", "flops", "bytes", "span_id",
                 "parent_id", "depth", "dur_s", "_t0", "_t0_wall",
                 "_token")

    def __init__(self, name: str, fence=None, flops: float = 0.0,
                 bytes: float = 0.0, **attrs):
        self.name = name
        self.fence = fence
        self.flops = flops
        self.bytes = bytes
        self.attrs = attrs
        self.dur_s = None

    def __enter__(self):
        if not _metrics.enabled():
            return self
        parent = _parent.get()
        self.span_id = next(_next_id)
        self.parent_id = parent.span_id if parent is not None else None
        self.depth = (parent.depth + 1) if parent is not None else 0
        self._token = _parent.set(self)
        self._t0_wall = time.time()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if not hasattr(self, "_token"):
            return False  # telemetry was off at __enter__: stay a no-op
        if self.fence is not None:
            try:
                import jax

                jax.block_until_ready(
                    self.fence() if callable(self.fence) else self.fence)
            except Exception:
                pass  # fencing is best-effort observability, never fatal
        self.dur_s = time.perf_counter() - self._t0
        _parent.reset(self._token)
        del self._token
        rec = {
            "name": self.name,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "depth": self.depth,
            "t0": self._t0_wall,
            "dur_s": self.dur_s,
            **_tracing.context_fields(),
        }
        if exc_type is not None:
            rec["error"] = exc_type.__name__
        if self.attrs:
            rec.update(self.attrs)
        if self.flops:
            from sirius_tpu.obs import costs as _costs

            rec.update(_costs.annotate_span(self.dur_s, self.flops,
                                            self.bytes))
        _finish(rec)
        return False


def record(name: str, dur_s: float, t0: float | None = None,
           flops: float = 0.0, bytes: float = 0.0, **attrs) -> None:
    """Record an externally-timed span (e.g. serve queue wait measured as
    a timestamp delta, or a setup phase bracketed by plain perf_counter
    reads). Lineage comes from the current contextvar like a live span."""
    if not _metrics.enabled():
        return
    parent = _parent.get()
    rec = {
        "name": name,
        "span_id": next(_next_id),
        "parent_id": parent.span_id if parent is not None else None,
        "depth": (parent.depth + 1) if parent is not None else 0,
        "t0": float(t0) if t0 is not None else time.time() - float(dur_s),
        "dur_s": float(dur_s),
        **_tracing.context_fields(),
    }
    if attrs:
        rec.update(attrs)
    if flops:
        from sirius_tpu.obs import costs as _costs

        rec.update(_costs.annotate_span(float(dur_s), flops, bytes))
    _finish(rec)


def spanned(name: str | None = None, **span_kw):
    """Decorator form: ``@spanned("md.extrapolate")`` (defaults to the
    function's qualified name)."""

    def deco(fn):
        label = name or fn.__qualname__

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with span(label, **span_kw):
                return fn(*args, **kwargs)

        return wrapper

    return deco


def current() -> "span | None":
    """The innermost live span of this context (None at top level)."""
    return _parent.get()
