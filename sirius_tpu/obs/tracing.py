"""Trace-context propagation — the distributed layer of the obs stack.

A *trace* is one logical unit of user-visible work: a serve job from
submit to terminal state, a whole campaign DAG, an MD trajectory. The
span timeline (obs/spans.py) gives lineage *within* one context via
parent_id; this module gives identity *across* contexts — worker
threads, process restarts (journal replay), and DAG handoff between
jobs — by carrying a 16-hex ``trace_id`` in a contextvar that every
span record, event, and metric exemplar stamps on itself.

Propagation paths (who carries the id across which boundary):

- serve: ``ServeEngine.submit`` assigns a trace_id to the Job *before*
  write-ahead journaling, so SIGKILL + journal replay reconstructs the
  same trace; ``scheduler._run_job`` enters ``trace_context(job.trace_id)``
  around every attempt, so all SCF spans from any worker thread / retry
  land on the job's trace.
- campaigns: ``runner.submit_campaign`` mints one trace_id for the whole
  DAG and passes it to every node's submit; the handoff artifact
  (campaigns/handoff.py) stores it too, so a child job warm-started in a
  *fresh process* (resume after SIGKILL) still continues the campaign's
  trace.
- drivers: ``run_scf`` / ``run_md`` call ``ensure_trace()`` — standalone
  runs get a fresh trace, runs under serve/campaigns keep the inherited
  one.

This module is deliberately stdlib-only at import time (obs/__init__.py
imports events/metrics before spans; tracing must be importable by all
of them without cycles). jax is imported lazily inside
``hbm_high_water`` only.
"""

from __future__ import annotations

import contextlib
import contextvars
import os
import threading
import uuid

_trace_var: contextvars.ContextVar = contextvars.ContextVar(
    "sirius_tpu_trace_id", default=None)


def new_trace_id() -> str:
    """A fresh 16-hex trace id (random, process-unique, journal-safe)."""
    return uuid.uuid4().hex[:16]


def current_trace_id() -> str | None:
    """The trace id of this logical context (None outside any trace)."""
    return _trace_var.get()


@contextlib.contextmanager
def trace_context(trace_id: str | None = None):
    """Enter a trace: ``with trace_context(job.trace_id):``. With
    ``trace_id=None`` a fresh id is minted. Yields the active id; restores
    the previous context on exit (nesting re-enters the same or a child
    trace — span lineage, not trace ids, expresses nesting)."""
    tid = trace_id or new_trace_id()
    token = _trace_var.set(tid)
    try:
        yield tid
    finally:
        _trace_var.reset(token)


@contextlib.contextmanager
def ensure_trace():
    """Keep the inherited trace if one is active, else mint one. The
    driver-entry idiom: run_scf/run_md wrap their body in this so
    standalone runs are traced without serve knowing, and serve-run SCFs
    join their job's trace instead of forking a new one."""
    tid = _trace_var.get()
    if tid is not None:
        yield tid
        return
    with trace_context() as tid:
        yield tid


def context_fields() -> dict:
    """The stamp applied to span records and events: trace_id (when a
    trace is active) plus the physical coordinates (pid, thread) that
    the timeline exporter turns into Perfetto tracks."""
    out = {"pid": os.getpid(), "thread": threading.current_thread().name}
    tid = _trace_var.get()
    if tid is not None:
        out["trace_id"] = tid
    return out


def hbm_high_water() -> dict:
    """Per-device peak memory since process start, in bytes:
    ``{"tpu:0": 123456, ...}``. CPU backends report no memory_stats; then
    falls back to the process RSS high-water (``host_rss``) so the
    GSHARD bench has *a* memory axis on every platform. Best-effort:
    returns {} when nothing is measurable."""
    out: dict = {}
    try:
        import jax

        for dev in jax.local_devices():
            stats = None
            try:
                stats = dev.memory_stats()
            except Exception:
                stats = None
            if not stats:
                continue
            peak = stats.get("peak_bytes_in_use",
                             stats.get("bytes_in_use"))
            if peak is not None:
                out[f"{dev.platform}:{dev.id}"] = int(peak)
    except Exception:
        pass
    if not out:
        try:
            import resource

            import sys

            rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
            # linux reports KiB, macOS bytes; normalize to bytes
            scale = 1 if sys.platform == "darwin" else 1024
            out["host_rss"] = int(rss) * scale
        except Exception:
            pass
    return out
