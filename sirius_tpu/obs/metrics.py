"""Metrics registry: labelled counters, gauges and histograms, plus the
jax.monitoring backend listeners.

The reference reports work through rt_graph timer trees printed at
finalize (core/rt_graph.hpp) and self-reported counters
(davidson.hpp:834); a serving engine needs the same numbers *while the
process runs*. This module is the shared registry every layer publishes
into: dft/scf.py (iteration counts, residuals), dft/recovery.py (ladder
rungs), serve/* (queue depth, job latency, cache hits, XLA compiles),
md/driver.py (step counters, drift). Exporters render it as Prometheus
text (obs/http.py) or embed ``REGISTRY.snapshot()`` into bench JSON.

Everything is thread-safe and cheap on the hot path: one dict lookup plus
a float add under a lock per update. ``sirius_tpu.obs.disable()`` turns
every update into a no-op for overhead-critical benchmarking.

The XLA listener generalizes the serve/cache.py compile counter: one
jax.monitoring registration feeds backend-compile counts (kept per-thread
for the cache-hit assertions in tests/test_serve.py) AND trace/lowering
duration histograms, so compile-time regressions are visible in the same
scrape as the throughput numbers.
"""

from __future__ import annotations

import bisect
import threading
import time

from sirius_tpu.obs import tracing as _tracing

# ---------------------------------------------------------------------------
# registry

# default histogram buckets: latencies from sub-ms jit dispatches to
# multi-minute cold SCF jobs
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
    60.0, 120.0, 300.0, 600.0,
)

_enabled = True


def set_enabled(flag: bool) -> None:
    """Process-wide kill switch (control.telemetry = false)."""
    global _enabled
    _enabled = bool(flag)


def enabled() -> bool:
    return _enabled


def _labelkey(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


# ---------------------------------------------------------------------------
# cardinality guard
#
# Label values must come from small closed sets (stage names, failure
# classes, device ids) — NEVER from per-request identity (job_id,
# trace_id, campaign node). Those ride on events and exemplars instead.
# As a backstop against a producer regressing this rule, each family caps
# its labelset count; updates past the cap collapse into a single
# {overflow="true"} child and are tallied in ``cardinality_clips()`` so
# tests (and a dashboard) can alert on the leak without the registry
# eating unbounded memory first.

_MAX_LABELSETS_DEFAULT = 128
_max_labelsets = _MAX_LABELSETS_DEFAULT
_OVERFLOW_KEY = (("overflow", "true"),)
_clips_lock = threading.Lock()
_clips: dict[str, int] = {}


def set_max_labelsets(n: int) -> int:
    """Set the per-family labelset cap; returns the previous cap."""
    global _max_labelsets
    prev = _max_labelsets
    _max_labelsets = int(n)
    return prev


def max_labelsets() -> int:
    return _max_labelsets


def cardinality_clips() -> dict[str, int]:
    """{family name: updates routed to the overflow child}."""
    with _clips_lock:
        return dict(_clips)


def _note_clip(name: str) -> None:
    with _clips_lock:
        _clips[name] = _clips.get(name, 0) + 1


class _Family:
    """One named metric family; children are keyed by their label set."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._children: dict[tuple, object] = {}
        self._exemplars: dict[tuple, dict] = {}

    def _child_keyed(self, labels: dict):
        key = _labelkey(labels)
        with self._lock:
            c = self._children.get(key)
            if c is None:
                if (key != _OVERFLOW_KEY
                        and len(self._children) >= _max_labelsets):
                    _note_clip(self.name)
                    key = _OVERFLOW_KEY
                    c = self._children.get(key)
                if c is None:
                    c = self._new_child()
                    self._children[key] = c
            return key, c

    def _child(self, labels: dict):
        return self._child_keyed(labels)[1]

    def _note_exemplar(self, key: tuple, value: float) -> None:
        """Attach the current trace to this sample (last-write-wins) —
        the OpenMetrics exemplar idea: per-identity correlation lives
        here, not in label values. Caller holds self._lock."""
        tid = _tracing.current_trace_id()
        if tid is not None:
            self._exemplars[key] = {
                "trace_id": tid, "value": float(value), "ts": time.time()}

    def exemplar(self, **labels) -> dict | None:
        with self._lock:
            ex = self._exemplars.get(_labelkey(labels))
            return dict(ex) if ex else None

    def labelsets(self) -> list[tuple]:
        with self._lock:
            return list(self._children)


class Counter(_Family):
    """Monotonically increasing value per label set."""

    kind = "counter"

    def _new_child(self):
        return [0.0]

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not _enabled:
            return
        key, c = self._child_keyed(labels)
        with self._lock:
            c[0] += amount
            self._note_exemplar(key, c[0])

    def value(self, **labels) -> float:
        return self._child(labels)[0]


class Gauge(_Family):
    """Last-written value per label set."""

    kind = "gauge"

    def _new_child(self):
        return [0.0]

    def set(self, value: float, **labels) -> None:
        if not _enabled:
            return
        c = self._child(labels)
        with self._lock:
            c[0] = float(value)

    def inc(self, amount: float = 1.0, **labels) -> None:
        if not _enabled:
            return
        c = self._child(labels)
        with self._lock:
            c[0] += amount

    def max(self, value: float, **labels) -> None:
        """High-water-mark update (queue depth peaks)."""
        if not _enabled:
            return
        c = self._child(labels)
        with self._lock:
            if value > c[0]:
                c[0] = float(value)

    def value(self, **labels) -> float:
        return self._child(labels)[0]


class Histogram(_Family):
    """Cumulative-bucket histogram (Prometheus semantics) per label set."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "", buckets=DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def _new_child(self):
        # [per-bucket counts..., +Inf count], sum, count
        return {"counts": [0] * (len(self.buckets) + 1),
                "sum": 0.0, "n": 0}

    def observe(self, value: float, **labels) -> None:
        if not _enabled:
            return
        key, c = self._child_keyed(labels)
        i = bisect.bisect_left(self.buckets, float(value))
        with self._lock:
            c["counts"][i] += 1
            c["sum"] += float(value)
            c["n"] += 1
            self._note_exemplar(key, value)

    def child_stats(self, **labels) -> dict:
        c = self._child(labels)
        with self._lock:
            return {"sum": c["sum"], "count": c["n"],
                    "buckets": dict(zip(
                        [*self.buckets, float("inf")], c["counts"]))}


class MetricsRegistry:
    """Named families; idempotent creation so producers never coordinate."""

    def __init__(self):
        self._lock = threading.Lock()
        self._families: dict[str, _Family] = {}

    def _get(self, cls, name: str, help: str, **kw):
        with self._lock:
            fam = self._families.get(name)
            if fam is None:
                fam = cls(name, help, **kw)
                self._families[name] = fam
            elif not isinstance(fam, cls):
                raise TypeError(
                    f"metric '{name}' already registered as {fam.kind}")
            return fam

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets=DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def families(self) -> list[_Family]:
        with self._lock:
            return list(self._families.values())

    def reset(self) -> None:
        """Drop every family (tests only)."""
        with self._lock:
            self._families.clear()
        with _clips_lock:
            _clips.clear()

    # -- exporters --------------------------------------------------------

    def snapshot(self) -> dict:
        """JSON-friendly dump: {name: {type, help, samples: [...]}}.
        Histogram samples carry sum/count/cumulative buckets."""
        out = {}
        for fam in self.families():
            samples = []
            for key in fam.labelsets():
                labels = dict(key)
                if fam.kind == "histogram":
                    sample = {"labels": labels, **fam.child_stats(**labels)}
                else:
                    sample = {"labels": labels, "value": fam.value(**labels)}
                ex = fam.exemplar(**labels)
                if ex is not None:
                    sample["exemplar"] = ex
                samples.append(sample)
            out[fam.name] = {"type": fam.kind, "help": fam.help,
                             "samples": samples}
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition format (text/plain; version=0.0.4)."""

        def fmt_labels(labels: dict, extra: dict | None = None) -> str:
            items = {**labels, **(extra or {})}
            if not items:
                return ""
            body = ",".join(
                f'{k}="{_escape(str(v))}"' for k, v in sorted(items.items()))
            return "{" + body + "}"

        def _escape(s: str) -> str:
            return s.replace("\\", "\\\\").replace('"', '\\"').replace(
                "\n", "\\n")

        def fmt_val(v: float) -> str:
            if v == float("inf"):
                return "+Inf"
            f = float(v)
            return repr(int(f)) if f == int(f) else repr(f)

        lines = []
        for fam in sorted(self.families(), key=lambda f: f.name):
            if fam.help:
                lines.append(f"# HELP {fam.name} {fam.help}")
            lines.append(f"# TYPE {fam.name} {fam.kind}")
            for key in fam.labelsets():
                labels = dict(key)
                if fam.kind == "histogram":
                    st = fam.child_stats(**labels)
                    acc = 0
                    for le, n in st["buckets"].items():
                        acc += n
                        lines.append(
                            f"{fam.name}_bucket"
                            f"{fmt_labels(labels, {'le': fmt_val(le)})}"
                            f" {acc}")
                    lines.append(
                        f"{fam.name}_sum{fmt_labels(labels)}"
                        f" {repr(st['sum'])}")
                    lines.append(
                        f"{fam.name}_count{fmt_labels(labels)}"
                        f" {st['count']}")
                else:
                    lines.append(
                        f"{fam.name}{fmt_labels(labels)}"
                        f" {fmt_val(fam.value(**labels))}")
        return "\n".join(lines) + "\n"


REGISTRY = MetricsRegistry()

# The authoritative metric-name registry: every
# ``REGISTRY.counter/gauge/histogram("name", ...)`` literal in
# production code must name one of these (enforced by sirius-lint's
# unknown-metric-name rule, which parses this tuple by AST) so dashboard
# queries and the CI /metrics smoke can rely on the namespace being
# closed. Tests register throwaway names on private registries and are
# exempt.
KNOWN_METRIC_NAMES = (
    # counters
    "campaign_node_scf_iterations_total",
    "campaign_nodes_total",
    "fleet_lease_ops_total",
    "fleet_memo_total",
    "fleet_watcher_attaches_total",
    "jax_backend_compiles_total",
    "md_steps_total",
    "scf_aborts_total",
    "scf_autosaves_total",
    "scf_iterations_total",
    "scf_recoveries_total",
    "scf_runs_total",
    "scf_straggler_preempts_total",
    "serve_cache_exec_total",
    "serve_cache_jobs_total",
    "serve_job_failures_total",
    "serve_job_retries_total",
    "serve_job_transitions_total",
    "serve_journal_records_total",
    "serve_journal_replays_total",
    "serve_quarantines_total",
    "serve_queue_rejected_total",
    "serve_slice_degraded_total",
    "serve_watchdog_fires_total",
    "serve_worker_restarts_total",
    # gauges
    "jax_device_memory_bytes",
    "md_conserved_drift_ha",
    "md_extrapolation_rel_error",
    "numerics_probe_energy_impact_ha",
    "numerics_probe_rel_err",
    "scf_density_rms",
    "scf_forecast_iterations",
    "scf_forecast_warning",
    "scf_numerics_ledger",
    "scf_total_energy_ha",
    "serve_queue_depth",
    "serve_queue_depth_high_water",
    "serve_tenant_queue_depth",
    # histograms
    "campaign_wall_seconds",
    "jax_backend_compile_seconds",
    "jax_lowering_seconds",
    "jax_trace_seconds",
    "md_scf_iterations_per_step",
    "md_step_seconds",
    "perf_span_seconds",
    "scf_iteration_seconds",
    "serve_backoff_seconds",
    "serve_job_latency_seconds",
    "serve_job_run_seconds",
    "serve_job_state_seconds",
    "span_seconds",
)


# ---------------------------------------------------------------------------
# jax.monitoring backend listeners (generalized from serve/cache.py)

# every XLA backend compile / jaxpr trace / MLIR lowering fires one of
# these duration events on the calling thread (jax/_src/dispatch.py)
BACKEND_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
JAXPR_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"
LOWERING_EVENT = "/jax/core/compile/jaxpr_to_mlir_module_duration"

_compile_lock = threading.Lock()
_compiles_total = 0
_compiles_tls = threading.local()
_listener_installed = False


def _on_duration_event(event: str, *args, **kwargs) -> None:
    global _compiles_total
    # the duration is the first positional arg in every jax version that
    # ships these events; be tolerant of signature drift
    dt = float(args[0]) if args else 0.0
    if event == BACKEND_COMPILE_EVENT:
        with _compile_lock:
            _compiles_total += 1
        _compiles_tls.count = getattr(_compiles_tls, "count", 0) + 1
        _compiles_tls.seconds = getattr(_compiles_tls, "seconds", 0.0) + dt
        REGISTRY.counter(
            "jax_backend_compiles_total",
            "XLA backend compilations").inc()
        REGISTRY.histogram(
            "jax_backend_compile_seconds",
            "XLA backend compile durations").observe(dt)
    elif event == JAXPR_TRACE_EVENT:
        REGISTRY.histogram(
            "jax_trace_seconds", "jaxpr trace durations").observe(dt)
    elif event == LOWERING_EVENT:
        REGISTRY.histogram(
            "jax_lowering_seconds",
            "jaxpr-to-MLIR lowering durations").observe(dt)


def install_jax_listeners() -> bool:
    """Register the XLA compile/trace/lowering listener (idempotent).
    Returns False when this jax build has no monitoring hooks."""
    global _listener_installed
    if _listener_installed:
        return True
    try:
        from jax import monitoring
        monitoring.register_event_duration_secs_listener(_on_duration_event)
    except (ImportError, AttributeError):
        return False
    _listener_installed = True
    return True


def backend_compiles_total() -> int:
    """Process-wide XLA backend compile count (monotone across engine
    lifetimes: the listener registration is global and permanent)."""
    with _compile_lock:
        return _compiles_total


def backend_compiles_this_thread() -> int:
    return getattr(_compiles_tls, "count", 0)


def backend_compile_seconds_this_thread() -> float:
    """Cumulative XLA backend-compile seconds on the calling thread —
    deltas across a run give its serve.compile span (scheduler.py)."""
    return getattr(_compiles_tls, "seconds", 0.0)


def update_device_memory_gauges() -> None:
    """Refresh per-device memory gauges from device.memory_stats().
    Backends without memory introspection (CPU) report 0 so the series
    still exists for dashboards that alert on its absence."""
    try:
        import jax
        devices = jax.devices()
    except Exception:
        return
    g = REGISTRY.gauge(
        "jax_device_memory_bytes",
        "device memory from device.memory_stats() (0 = not reported)")
    for d in devices:
        try:
            stats = d.memory_stats()
        except Exception:
            stats = None
        dev = f"{d.platform}:{d.id}"
        if not stats:
            g.set(0.0, device=dev, kind="bytes_in_use")
            continue
        for kind in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit"):
            if kind in stats:
                g.set(float(stats[kind]), device=dev, kind=kind)
