"""Structured logging with job/step context.

Serve slice workers, the MD step loop, and nested SCF runs all used to
write raw ``print(...)`` lines that interleave arbitrarily under
concurrency. Here every module grabs a child of the ``sirius_tpu``
logger and the current job id / step ride along in contextvars, so a
line like::

    [serve] retrying si-3 after SimulatedKill (attempt 2)

renders as::

    12:03:44 sirius_tpu.serve [job=si-3] retrying after SimulatedKill (attempt 2)

no matter which slice thread emitted it. Quiet by default (NullHandler);
``setup(verbosity)`` — called from the CLIs' ``-v`` flag or from
``control.verbosity`` — attaches one stderr handler idempotently.
Plain ``threading.Thread`` workers start with an *empty* contextvars
context, so long-lived pools (serve slice workers) must set the context
explicitly per job — scheduler._run_job wraps each job in
``job_context(job.id)`` for exactly this reason.
"""

from __future__ import annotations

import contextlib
import contextvars
import logging
import sys

_job_id_var: contextvars.ContextVar[str | None] = contextvars.ContextVar(
    "sirius_job_id", default=None)
_step_var: contextvars.ContextVar[int | None] = contextvars.ContextVar(
    "sirius_step", default=None)

ROOT = "sirius_tpu"

_setup_done = False
_setup_level = logging.WARNING


def current_job_id() -> str | None:
    return _job_id_var.get()


def current_step() -> int | None:
    return _step_var.get()


@contextlib.contextmanager
def job_context(job_id: str | None = None, step: int | None = None):
    """Attach job_id/step to every log record and obs event emitted
    inside the block (threads inherit a copy at start time)."""
    tok_j = _job_id_var.set(job_id) if job_id is not None else None
    tok_s = _step_var.set(step) if step is not None else None
    try:
        yield
    finally:
        if tok_j is not None:
            _job_id_var.reset(tok_j)
        if tok_s is not None:
            _step_var.reset(tok_s)


class _ContextFilter(logging.Filter):
    def filter(self, record: logging.LogRecord) -> bool:
        job = _job_id_var.get()
        step = _step_var.get()
        parts = []
        if job is not None:
            parts.append(f"job={job}")
        if step is not None:
            parts.append(f"step={step}")
        record.obs_ctx = f"[{' '.join(parts)}] " if parts else ""
        return True


def get_logger(name: str = "") -> logging.Logger:
    """Child of the sirius_tpu hierarchy; e.g. get_logger('serve')."""
    logger = logging.getLogger(f"{ROOT}.{name}" if name else ROOT)
    return logger


def setup(verbosity: int = 0, *, stream=None, force: bool = False) -> None:
    """Attach the stderr handler once. verbosity 0 → WARNING,
    1 → INFO, 2+ → DEBUG. Re-calling only ever lowers the threshold
    (a serve engine at -v must not silence a -vv CLI)."""
    global _setup_done, _setup_level
    level = (logging.WARNING if verbosity <= 0
             else logging.INFO if verbosity == 1 else logging.DEBUG)
    root = logging.getLogger(ROOT)
    if _setup_done and not force:
        if level < _setup_level:
            _setup_level = level
            root.setLevel(level)
        return
    handler = logging.StreamHandler(stream or sys.stderr)
    handler.setFormatter(logging.Formatter(
        "%(asctime)s %(name)s %(obs_ctx)s%(message)s", datefmt="%H:%M:%S"))
    handler.addFilter(_ContextFilter())
    if force:
        for h in list(root.handlers):
            root.removeHandler(h)
    root.addHandler(handler)
    root.setLevel(level)
    root.propagate = False
    _setup_done = True
    _setup_level = level


# importing sirius_tpu must never print; callers opt in via setup()
logging.getLogger(ROOT).addHandler(logging.NullHandler())
