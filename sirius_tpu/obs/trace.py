"""On-demand jax.profiler trace capture around SCF iterations.

A trace of the *whole* run is useless for long serve processes and
thousand-step MD — you want "the next N SCF iterations, starting now".
This singleton arms a capture (from ``control.trace_capture`` at run_scf
entry, or live from the serve ``/debug/trace?steps=N`` endpoint); the
SCF loop calls ``tick()`` at the top of every iteration and ``finish()``
when it leaves the loop. tick() starts jax.profiler.trace on the first
iteration after arming and stops it after N ticks, writing a
TensorBoard-readable directory (plugins/profile/<ts>/ with .xplane.pb).

The SCF loop has several ``continue`` paths (recovery rollback, band
rescue), which is why bracketing start/stop around the loop body would
leak an open trace; counting at the loop head plus an unconditional
finish() after the loop is robust to all of them. A completed-dirs set
keeps ``control.trace_capture`` from re-arming on every MD step's
run_scf call — one trace per requested directory unless force=True
(the serve endpoint forces, with a fresh subdirectory per request).
"""

from __future__ import annotations

import threading
import time

from sirius_tpu.obs import events
from sirius_tpu.obs.log import get_logger

logger = get_logger("obs.trace")


class TraceCapture:
    def __init__(self):
        self._lock = threading.Lock()
        self._armed_dir: str | None = None
        self._remaining = 0
        self._active = False
        self._done_dirs: set[str] = set()

    def request(self, trace_dir: str, steps: int = 5, *,
                force: bool = False) -> bool:
        """Arm a capture of the next ``steps`` SCF iterations into
        ``trace_dir``. Returns False when already captured (and not
        forced) or a capture is in flight."""
        trace_dir = str(trace_dir)
        with self._lock:
            if self._active or self._armed_dir is not None:
                return False
            if trace_dir in self._done_dirs and not force:
                return False
            self._armed_dir = trace_dir
            self._remaining = max(1, int(steps))
        logger.info("trace capture armed: %d iterations -> %s",
                    self._remaining, trace_dir)
        return True

    def tick(self) -> None:
        """Call at the top of each SCF iteration."""
        with self._lock:
            if self._armed_dir is not None and not self._active:
                target = self._armed_dir
                start = True
            elif self._active:
                self._remaining -= 1
                if self._remaining <= 0:
                    return self._stop_locked()
                return
            else:
                return
        if start:
            self._start(target)

    def finish(self) -> None:
        """Call after the SCF loop exits (converged, aborted, or
        exhausted) — closes a capture shorter than requested."""
        with self._lock:
            if self._active:
                self._stop_locked()
            self._armed_dir = None

    def status(self) -> dict:
        with self._lock:
            return {"active": self._active,
                    "armed_dir": self._armed_dir,
                    "remaining": self._remaining,
                    "completed": sorted(self._done_dirs)}

    # -- internals (lock handling: _start runs unlocked because
    #    jax.profiler.start_trace can itself compile) ------------------

    def _start(self, trace_dir: str) -> None:
        import os
        try:
            os.makedirs(trace_dir, exist_ok=True)
            import jax
            jax.profiler.start_trace(trace_dir)
        except Exception as exc:  # profiler unavailable on some builds
            logger.warning("trace capture failed to start: %s", exc)
            with self._lock:
                self._armed_dir = None
                self._remaining = 0
            return
        with self._lock:
            self._active = True
        events.emit("trace_capture", phase="start", trace_dir=trace_dir,
                    steps=self._remaining)

    def _stop_locked(self) -> None:
        # called with self._lock held
        trace_dir = self._armed_dir
        self._active = False
        self._armed_dir = None
        self._remaining = 0
        if trace_dir is not None:
            self._done_dirs.add(trace_dir)
        def _stop():
            try:
                import jax
                jax.profiler.stop_trace()
            except Exception as exc:
                logger.warning("trace capture failed to stop: %s", exc)
                return
            logger.info("trace capture written: %s", trace_dir)
            events.emit("trace_capture", phase="stop", trace_dir=trace_dir,
                        ts_stop=time.time())
        # release before touching the profiler: stop_trace flushes to disk
        self._lock.release()
        try:
            _stop()
        finally:
            self._lock.acquire()


CAPTURE = TraceCapture()
