"""Structured JSONL event sink.

One line per event, append-only, flushed per write so a preempted run
leaves a readable log. Schema: every record carries ``ts`` (unix
seconds), ``kind``, and whatever fields the producer passed; ``job_id``
and ``step`` are injected from the logging context (obs/log.py) when not
given explicitly, so serve-worker SCF iterations attribute to their job
without the DFT layer knowing it runs under serve.

Event kinds emitted across the tree:

- ``run_manifest``   — once per run_scf/run_md: deck label, task, shapes
- ``scf_iteration``  — per SCF iteration: the [NUM_SCALARS] device scalar
  record (dft/fused.py) or the host-path equivalents, plus rms/e_total
  and the named numerics ledger invariants (``ledger``)
- ``scf_done``       — terminal SCF record: converged, iterations, energy
- ``scf_forecast``   — per SCF iteration when forecast_enabled: decay
  rate, iterations-to-converge forecast, early-warning score
  (obs/forecast.py via dft/recovery.py)
- ``deadline_feasibility`` — the forecasted finish crossing a
  control.deadline_ts boundary in either direction (dft/scf.py; serve
  jobs carry it per job via serve/scheduler.py)
- ``numerics_probe`` — one record per (stage, precision) shadow probe:
  energy_impact_ha, rel_err, clears (obs/numerics.py)
- ``recovery``       — each ladder rung taken (dft/recovery.py)
- ``autosave`` / ``checkpoint`` — checkpoint writes with path + iteration
- ``md_step``        — per MD step: energies, drift, scf_iterations,
  extrapolation error
- ``job_transition`` — serve job lifecycle (queued→…→done|failed|aborted)
- ``backoff``        — serve retry backoff: delay_s, attempt, failure_class
- ``watchdog_fire``  — slice watchdog detection (kind=crash|hang)
- ``worker_restart`` — slice worker respawned (reason, generation)
- ``slice_degraded`` — a serve slice marked degraded after a device-level
  failure: reason=device_lost|straggler|oom, surviving device count,
  cooldown (serve/supervisor.py)
- ``straggler``      — run_scf's straggler detector fired: iteration,
  wall vs healthy-median baseline and obs/costs.py model seconds
  (dft/scf.py; the run preempts at the next snapshot boundary)
- ``quarantine``     — job permanently failed as poison (strikes)
- ``journal_replay`` / ``journal_replay_job`` — jobs re-submitted from the
  durable job journal after a restart (serve/journal.py)
- ``drain`` / ``abort`` — engine shutdown handing queued jobs back
- ``trace_capture``  — profiler trace start/stop with the output dir
- ``campaign_submit`` / ``campaign_resume`` — a campaign DAG entering
  the engine: campaign_id, kind, num_nodes (campaigns/runner.py)
- ``campaign_handoff`` — a node consuming its parent's warm-start
  artifact: mode=warm|missing|corrupt_fallback, displaced
- ``campaign_node_done`` — terminal node outcome: node_id, status,
  warm_start, scf iterations
- ``campaign_done``  — finalize summary: kind, num_done, wall seconds
- ``memo_hit`` / ``memo_store`` — content-addressed dedup: a job
  answered from the fleet result store (with the donor's trace id), or
  a fresh answer persisted under its canonical hash (serve/engine.py)
- ``watcher_attach`` — a duplicate submission attached to the one
  in-flight job for its canonical hash instead of recomputing
- ``fleet_submit``   — a job durably enqueued in a shared fleet
  directory (fleet/federation.py)
- ``fleet_claim``    — an engine won a job's lease (``reclaimed`` marks
  takeover of an expired lease after its owner died)
- ``fleet_lease_lost`` — a renewal found the lease gone or re-owned;
  the engine abandons the job to its new owner

Unconfigured, ``emit`` is one attribute test — safe on every hot path.
Configuration is process-wide (module-level) because producers span
threads; tests configure per-tmpdir and ``close()`` in teardown.
"""

from __future__ import annotations

import json
import threading
import time

from sirius_tpu.obs import log as _log
from sirius_tpu.obs import tracing as _tracing

_lock = threading.Lock()
_fh = None
_path: str | None = None

# The authoritative kind registry: every ``emit(kind, ...)`` literal in
# the tree must name one of these (enforced by sirius-lint's
# unknown-event-kind rule, which parses this tuple by AST), so consumers
# — the trace exporter, the replayer, dashboards — can rely on the set
# being closed. Keep the docstring above in sync when adding one.
KNOWN_EVENT_KINDS = (
    "abort",
    "autosave",
    "backoff",
    "campaign_done",
    "campaign_handoff",
    "campaign_node_done",
    "campaign_resume",
    "campaign_submit",
    "checkpoint",
    "deadline_feasibility",
    "drain",
    "fleet_claim",
    "fleet_lease_lost",
    "fleet_submit",
    "job_transition",
    "journal_replay",
    "journal_replay_job",
    "md_step",
    "memo_hit",
    "memo_store",
    "numerics_probe",
    "quarantine",
    "recovery",
    "run_manifest",
    "scf_done",
    "scf_forecast",
    "scf_iteration",
    "slice_degraded",
    "span",
    "straggler",
    "trace_capture",
    "watcher_attach",
    "watchdog_fire",
    "worker_restart",
)


def configure(path: str) -> str:
    """Open (append) the JSONL sink at ``path``. Returns the path.
    Reconfiguring to the same path is a no-op; to a new path closes the
    old sink first."""
    global _fh, _path
    with _lock:
        if _fh is not None and _path == str(path):
            return _path
        if _fh is not None:
            _fh.close()
        _fh = open(path, "a", encoding="utf-8")
        _path = str(path)
        return _path


def configured() -> bool:
    return _fh is not None


def path() -> str | None:
    return _path


def close() -> None:
    global _fh, _path
    with _lock:
        if _fh is not None:
            _fh.close()
        _fh = None
        _path = None


def emit(kind: str, **fields) -> None:
    """Append one event. No-op unless configure() was called."""
    if _fh is None:
        return
    rec = {"ts": time.time(), "kind": kind}
    if "job_id" not in fields:
        job = _log.current_job_id()
        if job is not None:
            rec["job_id"] = job
    if "step" not in fields:
        step = _log.current_step()
        if step is not None:
            rec["step"] = step
    if "trace_id" not in fields:
        tid = _tracing.current_trace_id()
        if tid is not None:
            rec["trace_id"] = tid
    rec.update(fields)
    line = json.dumps(rec, default=_coerce) + "\n"
    with _lock:
        if _fh is None:
            return
        _fh.write(line)
        _fh.flush()


def _coerce(obj):
    # numpy / jax scalars and arrays show up in producer payloads
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if fn is not None:
            try:
                return fn()
            except Exception:
                pass
    return str(obj)


def read_events(path: str, kind: str | None = None) -> list[dict]:
    """Parse a JSONL event log back (tools/bench_md.py, tests)."""
    out = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if kind is None or rec.get("kind") == kind:
                out.append(rec)
    return out
