"""Analytic cost model: FLOPs / bytes-moved per SCF stage from deck
shapes, the shared accelerator peak table, and roofline annotations.

This is the single source of truth for "how much work is that stage":

- `peak_gflops()` / `peak_gbps()`: the accelerator peak table (moved
  here from bench.py's private copy) with env overrides
  (``BENCH_PEAK_GFLOPS`` kept for compatibility, plus
  ``SIRIUS_TPU_PEAK_GFLOPS`` / ``SIRIUS_TPU_PEAK_GBPS``) for unlisted
  hardware;
- per-kernel FLOP formulas (`fft_flops`, `hpsi_flops`,
  `beta_gemm_flops`, ...) — the self-reported work counters of the
  reference (wave_functions.hpp:1790-1833) generalized to every hot
  stage; complex MACs count 8 flops, complex FFTs 5 N log2 N;
- `scf_stage_costs()`: one `StageCost` (flops + bytes) per span name of
  an SCF iteration, which bench_regress and the span layer use to
  annotate measured durations with achieved GFLOP/s, the roofline
  ceiling min(peak, intensity * bandwidth), and MFU;
- `xla_cost_analysis()`: the cross-check against what XLA itself counts
  via ``jitted.lower(...).compile().cost_analysis()`` — returns None
  (degrade, never raise) on backends that provide nothing.

The byte counts are a minimal-traffic model (each operand read once,
each result written once, complex128 = 16 B) — good enough to place a
stage on the roofline, not a cache simulation.
"""

from __future__ import annotations

import dataclasses
import math
import os

# nominal fp32 peak GFLOPS per accelerator class (BASELINE.md anchors):
# TPU v5p-class 229.5e3 (half the 459e3 bf16 MXU peak), P100 9.3e3, CPU
# ~76.8/core (24 f32 FLOP/cycle @ 3.2 GHz)
PEAK_GFLOPS = {
    "tpu": 229.5e3,
    "gpu": 9.3e3,
    "cuda": 9.3e3,
}
CPU_CORE_GFLOPS = 76.8

# nominal memory bandwidth GB/s per class: TPU v5p HBM 2765, P100 HBM
# 732, CPU ~6.4/core (shared DDR; deliberately coarse)
PEAK_GBPS = {
    "tpu": 2765.0,
    "gpu": 732.0,
    "cuda": 732.0,
}
CPU_CORE_GBPS = 6.4

# Span names that deliberately have NO analytic flop model: wall-clock
# orchestration spans (queue wait, whole-iteration envelopes, MD step
# framing) where "achieved GFLOP/s" would be meaningless. sirius-lint's
# uncosted-span rule requires every scf.*/md.*/serve.*/campaign.* span
# wired into obs/spans.py to have a scf_stage_costs() key or entry here,
# so a new span is an explicit decision, not silent 0-FLOP noise in the
# attribution report.
UNCOSTED_SPANS = (
    "scf.setup",
    "md.integrate",
    "md.extrapolate",
    "md.scf",
    "serve.run",
    "serve.compile",
    "serve.queue_wait",
    "campaign.finalize",
    # model-based compute/collective split of the G-sharded band solve
    # (probe-timed collectives x analytic apply counts, dft/scf.py)
    "scf.band_solve.compute",
    "scf.band_solve.collective",
    # fenced collective probes at deck shapes (parallel/dist_fft.py)
    "collective.all_to_all_x2y",
    "collective.all_to_all_y2x",
    "collective.fft_local",
    "collective.psum_beta",
    # timeline export work itself (obs/timeline.py)
    "trace.export",
    # precision-headroom shadow probes (obs/numerics.py): duplicate stage
    # evaluations at reduced precision — attribution would double-count
    # the real stages' FLOPs
    "scf.numerics_probe",
)


def detect_platform() -> str:
    """Backend platform string without forcing a jax init ("cpu" when
    jax is unavailable or uninitialized-and-unneeded)."""
    try:
        import jax

        return jax.devices()[0].platform
    except Exception:
        return "cpu"


def peak_gflops(platform: str | None = None,
                override: float | None = None) -> float:
    """Shared accelerator peak table (fp32 GFLOPS). Resolution order:
    explicit ``override`` (config) > ``BENCH_PEAK_GFLOPS`` /
    ``SIRIUS_TPU_PEAK_GFLOPS`` env > class table > per-core CPU model."""
    if override:
        return float(override)
    env = (os.environ.get("BENCH_PEAK_GFLOPS")
           or os.environ.get("SIRIUS_TPU_PEAK_GFLOPS"))
    if env:
        return float(env)
    if platform is None:
        platform = detect_platform()
    return PEAK_GFLOPS.get(platform, CPU_CORE_GFLOPS * (os.cpu_count() or 1))


def peak_gbps(platform: str | None = None,
              override: float | None = None) -> float:
    """Nominal memory bandwidth (GB/s) for the roofline ceiling."""
    if override:
        return float(override)
    env = os.environ.get("SIRIUS_TPU_PEAK_GBPS")
    if env:
        return float(env)
    if platform is None:
        platform = detect_platform()
    return PEAK_GBPS.get(platform, CPU_CORE_GBPS * (os.cpu_count() or 1))


@dataclasses.dataclass(frozen=True)
class StageCost:
    """Analytic work of one stage: flops + bytes moved."""

    flops: float
    bytes: float = 0.0

    @property
    def intensity(self) -> float:
        """Arithmetic intensity flops/byte (inf for byte-free models)."""
        return self.flops / self.bytes if self.bytes > 0 else float("inf")

    def gflops(self, dur_s: float) -> float:
        return self.flops / dur_s / 1e9 if dur_s > 0 else 0.0

    def roofline_gflops(self, platform: str | None = None,
                        peak: float | None = None,
                        bw_gbps: float | None = None) -> float:
        """min(compute peak, intensity * bandwidth) — the ceiling this
        stage could reach on the given hardware."""
        pk = peak if peak is not None else peak_gflops(platform)
        bw = bw_gbps if bw_gbps is not None else peak_gbps(platform)
        if self.bytes <= 0:
            return pk
        return min(pk, self.intensity * bw)

    def mfu(self, dur_s: float, platform: str | None = None,
            peak: float | None = None) -> float:
        pk = peak if peak is not None else peak_gflops(platform)
        return self.gflops(dur_s) / pk if pk > 0 else 0.0


def annotate_span(dur_s: float, flops: float, bytes: float = 0.0,
                  platform: str | None = None,
                  peak: float | None = None) -> dict:
    """Roofline annotation fields for a measured span duration."""
    c = StageCost(flops=float(flops), bytes=float(bytes))
    roof = c.roofline_gflops(platform=platform, peak=peak)
    return {
        "flops": c.flops,
        "bytes": c.bytes,
        "gflops": c.gflops(dur_s),
        "roofline_gflops": roof,
        "mfu": c.mfu(dur_s, platform=platform, peak=peak),
    }


# ---------------------------------------------------------------------------
# per-kernel FLOP formulas (exact closed forms — tests hand-count these)


def _nbox(box) -> int:
    return int(box[0]) * int(box[1]) * int(box[2])


def fft_flops(box, batch: int = 1) -> float:
    """One complex FFT on `box` costs 5 N log2 N real flops (the
    standard split-radix count the reference also reports)."""
    n = _nbox(box)
    return float(batch) * 5.0 * n * math.log2(max(n, 2))


def fft_bytes(box, batch: int = 1, itemsize: int = 16) -> float:
    """Minimal traffic of one complex FFT: read + write the box once."""
    return float(batch) * 2.0 * itemsize * _nbox(box)


def beta_gemm_flops(nb: int, nbeta: int, ngk: int) -> float:
    """One beta-projection GEMM <beta|psi>: [nb, ngk] x [ngk, nbeta]
    complex, 8 flops per complex MAC."""
    return 8.0 * nb * nbeta * ngk


def beta_gemm_bytes(nb: int, nbeta: int, ngk: int,
                    itemsize: int = 16) -> float:
    return float(itemsize) * (nb * ngk + nbeta * ngk + nb * nbeta)


def hpsi_flops(nb: int, ngk: int, nbeta: int, box) -> float:
    """Flops of ONE H*psi + S*psi application on [nb, ngk] (the counter
    the reference self-reports as GFLOPS): per band two complex FFTs on
    the coarse box, the pointwise V multiply, the kinetic diagonal, and
    the beta-projector einsums (project, D/Q apply, expand for both H
    and S; 8 flops/cmac). Identical to the historical bench.py model."""
    n = _nbox(box)
    fft = 2 * 5.0 * n * math.log2(max(n, 2))
    local = 7.0 * n + 8.0 * ngk
    nl = 8.0 * (3.0 * nbeta * ngk + 2.0 * nbeta * nbeta)
    return nb * (fft + local + nl)


def hpsi_bytes(nb: int, ngk: int, nbeta: int, box,
               itemsize: int = 16) -> float:
    """Minimal traffic of one H*psi + S*psi: per band two FFT round
    trips + veff read + psi read/write, plus one read of the projector
    table and the projection coefficients."""
    n = _nbox(box)
    per_band = 2 * 2.0 * itemsize * n + 8.0 * n + 2.0 * itemsize * ngk
    return nb * per_band + itemsize * (nbeta * ngk + 2.0 * nb * nbeta)


def davidson_applies(num_steps: int, nb: int,
                     refresh_every: int | None = None) -> int:
    """H-applications in band rows of one davidson() call (delegates to
    solvers/davidson.num_applies so the counts can never drift)."""
    from sirius_tpu.solvers.davidson import REFRESH_EVERY, num_applies

    return num_applies(num_steps, nb,
                       refresh_every=refresh_every or REFRESH_EVERY)


def davidson_cost(nb: int, ngk: int, nbeta: int, box,
                  num_steps: int) -> StageCost:
    """One davidson() solve: the H/S applications plus the per-step
    dense subspace algebra (3nb x 3nb Gram products, the Rayleigh-Ritz
    eigensolve, and the rotation GEMMs back to the band block)."""
    rows = davidson_applies(num_steps, nb)
    apply_f = hpsi_flops(1, ngk, nbeta, box) * rows
    apply_b = hpsi_bytes(1, ngk, nbeta, box) * rows
    m = 3 * nb  # [X, K R, P] subspace
    gram = 2.0 * 8.0 * m * m * ngk  # hsub + ssub
    eig = 30.0 * m ** 3  # eigh(3nb) + the basis transforms around it
    rot = 6.0 * 8.0 * nb * m * ngk  # xn/hxn/sxn + pn/hpn/spn
    sub_f = num_steps * (gram + eig + rot)
    sub_b = num_steps * 16.0 * (3.0 * m * ngk + 2.0 * m * m)
    return StageCost(flops=apply_f + sub_f, bytes=apply_b + sub_b)


def scf_stage_costs(nk: int, ns: int, nb: int, ngk: int, nbeta: int,
                    box, ng: int, num_steps: int,
                    box_fine=None, mix_history: int = 8,
                    aug: bool = True) -> dict[str, StageCost]:
    """Per-iteration StageCost keyed by the span names run_scf emits.

    Shapes come straight from the SimulationContext: `box` is the coarse
    FFT grid (wave functions), `box_fine` the fine grid (density and
    potential; defaults to the coarse box when not given), `ng` the fine
    G set, `ngk` the padded |G+k| sphere."""
    bf = box_fine if box_fine is not None else box
    nf = _nbox(bf)
    c: dict[str, StageCost] = {}
    dav = davidson_cost(nb, ngk, nbeta, box, num_steps)
    c["scf.band_solve"] = StageCost(flops=nk * ns * dav.flops,
                                    bytes=nk * ns * dav.bytes)
    # screened D: augmentation Q * veff integrals on the fine G set
    dmat = (8.0 * ns * nbeta * nbeta * ng) if aug and nbeta else 2.0 * ng
    c["scf.d_matrix"] = StageCost(flops=dmat, bytes=16.0 * ns * ng)
    # fermi search: ~60 bisection sweeps over every band energy
    c["scf.occupations"] = StageCost(flops=60.0 * 4.0 * nk * ns * nb,
                                     bytes=8.0 * nk * ns * nb)
    # density: one inverse FFT + |psi|^2 accumulate per occupied band,
    # the coarse->fine map, plus the augmentation density matrix GEMM
    dens = nk * ns * nb * (fft_flops(box) + 2.0 * _nbox(box))
    dens_b = nk * ns * nb * fft_bytes(box)
    if aug and nbeta:
        dens += nk * ns * beta_gemm_flops(nb, nbeta, ngk) + \
            8.0 * ns * nbeta * nbeta * ng
        dens_b += 16.0 * (nbeta * ngk + ns * nbeta * nbeta)
    c["scf.density"] = StageCost(flops=dens, bytes=dens_b)
    # quasi-Newton mixing: history GEMMs over the packed vector
    nx = ng * (2 if ns == 2 else 1)
    c["scf.mixing"] = StageCost(flops=8.0 * nx * (2.0 * mix_history + 4.0),
                                bytes=16.0 * nx * (mix_history + 2.0))
    # potential: Hartree (pointwise on G), XC on the fine real grid
    # (~2 FFT round trips + the functional evaluation)
    potf = 10.0 * ng + 4.0 * fft_flops(bf) + 80.0 * ns * nf
    c["scf.potential"] = StageCost(flops=potf,
                                   bytes=4.0 * fft_bytes(bf) + 16.0 * ng)
    # fused device step = density assembly + mix + potential + D refresh
    c["scf.fused_step"] = StageCost(
        flops=c["scf.mixing"].flops + c["scf.potential"].flops
        + c["scf.d_matrix"].flops,
        bytes=c["scf.mixing"].bytes + c["scf.potential"].bytes
        + c["scf.d_matrix"].bytes,
    )
    # one [NUM_SCALARS] float64 vector per iteration (dft/fused.py; the
    # numerics-ledger invariants ride in the same record)
    c["scf.readback"] = StageCost(flops=0.0, bytes=8.0 * 20)
    c["scf.iteration"] = StageCost(
        flops=sum(v.flops for k, v in c.items()
                  if k not in ("scf.fused_step", "scf.readback")),
        bytes=sum(v.bytes for k, v in c.items()
                  if k not in ("scf.fused_step", "scf.readback")),
    )
    return c


# ---------------------------------------------------------------------------
# XLA cross-check


def xla_cost_analysis(jitted, *args, **kwargs) -> dict | None:
    """FLOP/byte counts from XLA's own cost model for a jitted callable
    at the given example arguments, or None when the backend provides
    nothing (older jax, some plugin backends) — callers must treat None
    as "skip the cross-check", never as a failure."""
    try:
        compiled = jitted.lower(*args, **kwargs).compile()
        ca = compiled.cost_analysis()
    except Exception:
        return None
    # historical jax versions returned [dict] per device program
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not isinstance(ca, dict) or not ca:
        return None
    return dict(ca)


def xla_flops(jitted, *args, **kwargs) -> float | None:
    """Just the flop count of the cross-check, or None when absent."""
    ca = xla_cost_analysis(jitted, *args, **kwargs)
    if ca is None:
        return None
    v = ca.get("flops")
    try:
        v = float(v)
    except (TypeError, ValueError):
        return None
    return v if v > 0 else None
