"""Numerics observatory: per-stage precision-headroom probes and the
on-device numerics ledger (`sirius-numerics` CLI, ISSUE 14).

The mixed-precision SCF ladder needs a measurement, not a guess, of which
SCF stages tolerate reduced precision. This module answers it two ways:

**Shadow probes** (`probe_stages`) re-evaluate individual SCF stages at a
converged-enough iterate with inputs degraded to fp32/bf16 and score the
result against the fp64 reference in the one currency that matters: the
first-order total-energy impact in Hartree. Stages are keyed by the same
span names as ``obs/costs.py::scf_stage_costs()`` so headroom tables join
against cost tables. Two probe modes, stated per stage below: the band
solve re-runs the REAL kernel in complex64 (true reduced arithmetic);
every other stage round-trips its inputs through the target precision and
re-runs in fp64 (input-representation sensitivity — a lower bound on the
true-arithmetic error, and the part that is independent of any particular
kernel rewrite).

**Ledger helpers**: the fused step appends four cheap invariants
(S-orthonormality, mixer charge drift, symmetrization idempotency,
subspace-H hermiticity) to its per-iteration scalar record (dft/fused.py
S_ORTHO..S_HERM — same single readback). ``ledger_from_scalars`` names
them for events/metrics and ``ledger_host`` is the numpy twin the host
debug path emits, pinned to the device values to <=1e-12 by
tests/test_fused_scf.py.

The headroom table is gated by a checked-in ``NUMERICS_BASELINE.json``
(same time-series idiom as obs/perf.py): ``sirius-numerics report
--compare NUMERICS_BASELINE.json`` exits nonzero when a stage's
clears-the-bound verdict flips or its error grows by more than a decade.
"""

from __future__ import annotations

import argparse
import json
import os
import platform as _platform
import sys
import tempfile
import time

import numpy as np

from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics

SCHEMA = 1
# energy-impact bar a stage must clear to be a mixed-precision candidate
BOUND_HA = 1e-8
# errors below this are indistinguishable accumulation noise: two runs of
# the same binary differ at this level, so the gate treats them as equal
NOISE_FLOOR = 1e-14
# compare gate: error growth beyond this many decades (log10) is a
# regression even when the clears verdict did not flip
TOL_DECADES = 1.0

# probed stages, keyed like obs/costs.py::scf_stage_costs(); scf.d_matrix
# is skipped on decks without augmentation
PROBE_STAGES = (
    "scf.density",
    "scf.mixing",
    "scf.potential",
    "scf.occupations",
    "scf.band_solve",
    "scf.d_matrix",
)

PRECISIONS = ("fp32", "bf16")

# the four on-device ledger invariants, in scalar-record order
# (dft/fused.py S_ORTHO, S_CHG, S_SYM, S_HERM)
LEDGER_KEYS = ("ortho", "charge", "sym", "herm")

_PROBE_IMPACT = obs_metrics.REGISTRY.gauge(
    "numerics_probe_energy_impact_ha",
    "shadow-probe first-order energy impact of reduced precision (Ha)")
_PROBE_REL = obs_metrics.REGISTRY.gauge(
    "numerics_probe_rel_err",
    "shadow-probe relative output error of reduced precision")
_LEDGER = obs_metrics.REGISTRY.gauge(
    "scf_numerics_ledger",
    "per-iteration on-device numerical invariants, by invariant")


# ---- ledger ------------------------------------------------------------


def ledger_from_scalars(scalars) -> dict:
    """Name the ledger slice of a fused per-iteration scalar record."""
    from sirius_tpu.dft.fused import S_CHG, S_HERM, S_ORTHO, S_SYM

    s = np.asarray(scalars, dtype=np.float64)
    return {
        "ortho": float(s[S_ORTHO]),
        "charge": float(s[S_CHG]),
        "sym": float(s[S_SYM]),
        "herm": float(s[S_HERM]),
    }


def ledger_host(psi, beta_gk, qmat, dion, gmask, x_mixed, x_new,
                omega: float, sym_resid: float = 0.0) -> dict:
    """numpy twin of the fused step's ledger block (dft/fused.py).

    Must compute the IDENTICAL quantities: psi masked by gmask, the
    S-metric Gram with the bare augmentation qmat, the mixer G=0 charge
    drift against the packed vectors, and the chained-GEMM subspace
    nonlocal H against the BARE dion (not the screened per-iteration D,
    whose refresh timing differs between the host and fused paths).
    """
    psi = np.asarray(psi, dtype=np.complex128) * np.asarray(
        gmask, dtype=np.float64)[:, None, None, :]
    nk, ns, nb, _ = psi.shape
    if beta_gk is not None and np.asarray(beta_gk).shape[1]:
        beta = np.asarray(beta_gk, dtype=np.complex128)
        bp = np.einsum("kxg,ksbg->ksbx", np.conj(beta), psi)
    else:
        bp = np.zeros((nk, ns, nb, 0), dtype=np.complex128)
    qm = np.asarray(qmat, dtype=np.float64) if qmat is not None \
        else np.zeros((bp.shape[-1], bp.shape[-1]))
    gram = np.einsum("ksbg,kscg->ksbc", np.conj(psi), psi)
    gram = gram + np.einsum("ksbx,xy,kscy->ksbc", np.conj(bp), qm, bp)
    s_ortho = float(np.max(np.abs(gram - np.eye(nb))))
    s_chg = float(abs(np.real(x_mixed[0]) - np.real(x_new[0])) * omega)
    dn = np.real(np.asarray(dion, dtype=np.float64)) if dion is not None \
        else qm * 0.0
    h_nl = np.einsum("ksbx,xy,kscy->ksbc", np.conj(bp), dn, bp)
    s_herm = float(np.max(np.abs(
        h_nl - np.conj(np.swapaxes(h_nl, -1, -2)))))
    return {"ortho": s_ortho, "charge": s_chg, "sym": float(sym_resid),
            "herm": s_herm}


def record_ledger(ledger: dict, it: int, path: str) -> None:
    """Push one iteration's ledger to /metrics (per-invariant gauge)."""
    for k, v in ledger.items():
        _LEDGER.set(v, invariant=k, path=path)


# ---- precision degradation ---------------------------------------------


def _rt(a, prec: str):
    """Round-trip an array through the target precision back to fp64
    (complex arrays component-wise: there is no complex bf16 anywhere)."""
    if a is None:
        return None
    a = np.asarray(a)
    if prec == "fp32":
        def r(x):
            return x.astype(np.float32).astype(np.float64)
    elif prec == "bf16":
        import jax.numpy as jnp

        def r(x):
            return np.asarray(
                jnp.asarray(x).astype(jnp.bfloat16)).astype(np.float64)
    else:
        raise ValueError(f"unknown precision '{prec}'")
    if np.iscomplexobj(a):
        return r(np.real(a)) + 1j * r(np.imag(a))
    return r(np.asarray(a, dtype=np.float64))


def _rel(delta, ref) -> float:
    nref = float(np.linalg.norm(np.ravel(ref)))
    return float(np.linalg.norm(np.ravel(delta))) / max(nref, 1e-300)


# ---- the probe harness -------------------------------------------------


def probe_stages(ctx, xc, psi, occ, evals, rho_g, mag_g=None,
                 bound_ha: float = BOUND_HA, mixer_beta: float = 0.7,
                 smearing: str = "gaussian",
                 smearing_width: float = 0.025) -> dict:
    """Shadow-evaluate each SCF stage at the given iterate in fp32/bf16
    against fp64 and score the first-order total-energy impact.

    Arguments are the host-side iterate run_scf exposes via
    ``keep_state=True``: psi [nk, ns, nb, ngk] complex, occ [nk, ns, nb],
    evals [nk, ns, nb], rho_g/mag_g fine-sphere densities. Returns
    {stage: {"fp32": {"energy_impact_ha", "rel_err"}, "bf16": {...},
    "clears_fp32": bool, "clears_bf16": bool}}.
    """
    import jax.numpy as jnp

    from sirius_tpu.dft.density import generate_density_g
    from sirius_tpu.dft.occupation import find_fermi
    from sirius_tpu.dft.potential import generate_potential
    from sirius_tpu.ops.augmentation import d_operator
    from sirius_tpu.ops.hamiltonian import apply_h_s, make_hk_params

    psi = np.asarray(psi, dtype=np.complex128)
    occ = np.asarray(occ, dtype=np.float64)
    evals = np.asarray(evals, dtype=np.float64)
    rho_g = np.asarray(rho_g)
    nk, ns, nb, _ = psi.shape
    omega = float(ctx.unit_cell.omega)
    kw = np.asarray(ctx.kweights, dtype=np.float64)
    occ_w = occ * kw[:, None, None]
    nel = float(ctx.unit_cell.num_valence_electrons)
    width = float(smearing_width)

    # fp64 references, computed once
    pot = generate_potential(ctx, rho_g, xc, mag_g)
    veff_g = np.asarray(pot.veff_g)

    def _epot(e) -> float:
        # the potential-derived part of the total-energy expression
        return float(-0.5 * e["vha"] + e["exc"] - e["vxc"] - e["bxc"])

    def _drho_impact(drho) -> float:
        # first-order energy change of a density perturbation: int drho veff
        return abs(float(np.real(np.sum(np.conj(drho) * veff_g))) * omega)

    rho_out = np.asarray(generate_density_g(ctx, psi, occ)).sum(axis=0)

    def _eval_sum(ev, oc) -> float:
        return float(np.sum(kw[:, None, None] * oc * ev))

    def _band_energy_ref() -> float:
        return _eval_sum(evals, occ)

    has_aug = ctx.aug is not None and ctx.beta.num_beta_total > 0
    if has_aug:
        d64 = np.asarray(
            d_operator(ctx.unit_cell, ctx.gvec, ctx.aug, veff_g, ctx.beta))
        beta = np.asarray(ctx.beta.beta_gk, dtype=np.complex128)
        bp = np.einsum("kxg,ksbg->ksbx", np.conj(beta), psi)
        # first-order nonlocal-energy weight: dE = sum dD_xy M_xy
        dm_w = np.real(np.einsum("ksb,ksbx,ksby->xy", occ_w,
                                 np.conj(bp), bp))
    else:
        d64 = dm_w = None

    # hpsi fp64 reference (the true-arithmetic band-solve probe baseline);
    # veff_r_coarse is [ns, n1, n2, n3] — HkParams wants one spin's box
    veff_box = np.asarray(pot.veff_r_coarse)
    e_hpsi64 = 0.0
    for ik in range(nk):
        for s in range(ns):
            params = make_hk_params(ctx, ik, veff_box[s],
                                    dtype=jnp.complex128)
            hpsi, _ = apply_h_s(params, jnp.asarray(psi[ik, s]))
            hpsi = np.asarray(hpsi)
            e_hpsi64 += float(np.sum(
                occ_w[ik, s] * np.real(np.einsum(
                    "bg,bg->b", np.conj(psi[ik, s]), hpsi))))

    def _probe(prec: str) -> dict:
        out = {}
        # scf.density: |psi|^2 accumulation from a degraded band block
        rho_p = np.asarray(
            generate_density_g(ctx, _rt(psi, prec), occ)).sum(axis=0)
        out["scf.density"] = {
            "energy_impact_ha": _drho_impact(rho_p - rho_out),
            "rel_err": _rel(rho_p - rho_out, rho_out),
        }
        # scf.mixing: linear mixer apply on degraded vectors
        mix64 = (1.0 - mixer_beta) * rho_g + mixer_beta * rho_out
        mix_p = ((1.0 - mixer_beta) * _rt(rho_g, prec)
                 + mixer_beta * _rt(rho_out, prec))
        out["scf.mixing"] = {
            "energy_impact_ha": _drho_impact(mix_p - mix64),
            "rel_err": _rel(mix_p - mix64, mix64),
        }
        # scf.potential: Hartree+XC+local assembly from a degraded density
        pot_p = generate_potential(ctx, _rt(rho_g, prec), xc,
                                   _rt(mag_g, prec))
        out["scf.potential"] = {
            "energy_impact_ha": abs(_epot(pot_p.energies)
                                    - _epot(pot.energies)),
            "rel_err": _rel(np.asarray(pot_p.veff_g) - veff_g, veff_g),
        }
        # scf.occupations: fermi search over degraded eigenvalues
        _, occ_p, _ = find_fermi(
            jnp.asarray(_rt(evals, prec)), jnp.asarray(kw), nel, width,
            kind=smearing, max_occupancy=ctx.max_occupancy)
        occ_p = np.asarray(occ_p)
        out["scf.occupations"] = {
            "energy_impact_ha": abs(_eval_sum(evals, occ_p)
                                    - _band_energy_ref()),
            "rel_err": _rel(occ_p - occ, occ),
        }
        # scf.band_solve: H|psi>. fp32 runs the REAL kernel in complex64;
        # bf16 has no complex dtype, so inputs are degraded and applied
        # in fp64
        e_hpsi_p = 0.0
        if prec == "fp32":
            veff_p = veff_box
            psi_in = psi.astype(np.complex64)
        else:
            veff_p = _rt(veff_box, prec)
            psi_in = _rt(psi, prec)
        for ik in range(nk):
            for s in range(ns):
                params = make_hk_params(
                    ctx, ik, veff_p[s],
                    dtype=jnp.complex64 if prec == "fp32"
                    else jnp.complex128)
                hpsi, _ = apply_h_s(params, jnp.asarray(psi_in[ik, s]))
                hpsi = np.asarray(hpsi, dtype=np.complex128)
                e_hpsi_p += float(np.sum(
                    occ_w[ik, s] * np.real(np.einsum(
                        "bg,bg->b",
                        np.conj(psi_in[ik, s]).astype(np.complex128),
                        hpsi))))
        out["scf.band_solve"] = {
            "energy_impact_ha": abs(e_hpsi_p - e_hpsi64),
            "rel_err": abs(e_hpsi_p - e_hpsi64) / max(abs(e_hpsi64),
                                                      1e-300),
        }
        # scf.d_matrix: D-operator screening from a degraded potential
        if has_aug:
            d_p = np.asarray(d_operator(
                ctx.unit_cell, ctx.gvec, ctx.aug, _rt(veff_g, prec),
                ctx.beta))
            out["scf.d_matrix"] = {
                "energy_impact_ha": abs(float(np.sum(
                    (np.real(d_p) - np.real(d64)) * dm_w))),
                "rel_err": _rel(d_p - d64, d64),
            }
        return out

    by_prec = {prec: _probe(prec) for prec in PRECISIONS}
    stages: dict[str, dict] = {}
    for sname in PROBE_STAGES:
        if sname not in by_prec["fp32"]:
            continue
        ent = {prec: by_prec[prec][sname] for prec in PRECISIONS}
        for prec in PRECISIONS:
            ent[f"clears_{prec}"] = bool(
                ent[prec]["energy_impact_ha"] <= bound_ha)
        stages[sname] = ent
    return stages


def emit_probe_events(stages: dict, it: int | None = None,
                      tier: str | None = None) -> None:
    """One ``numerics_probe`` event + gauge set per (stage, precision)."""
    for sname, ent in stages.items():
        for prec in PRECISIONS:
            p = ent[prec]
            obs_events.emit(
                "numerics_probe", stage=sname, prec=prec,
                energy_impact_ha=p["energy_impact_ha"],
                rel_err=p["rel_err"], clears=ent[f"clears_{prec}"],
                **({"it": it} if it is not None else {}),
                **({"tier": tier} if tier is not None else {}),
            )
            _PROBE_IMPACT.set(p["energy_impact_ha"], stage=sname,
                              prec=prec)
            _PROBE_REL.set(p["rel_err"], stage=sname, prec=prec)


# ---- tiers / baseline / CLI (obs/perf.py idiom) ------------------------


def run_tier(name: str, spec: dict, bound_ha: float = BOUND_HA,
             base_dir: str | None = None) -> dict:
    """Run one pinned tier deck to its iteration budget, then probe every
    stage at the final iterate."""
    from sirius_tpu.config.schema import load_config
    from sirius_tpu.dft.scf import run_scf
    from sirius_tpu.dft.xc import XCFunctional
    from sirius_tpu.obs.perf import tier_deck
    from sirius_tpu.serve.scheduler import build_job_context

    tmp = base_dir or tempfile.mkdtemp(prefix=f"sirius_numerics_{name}_")
    cfg = load_config(tier_deck(spec))
    cfg.control.numerics_probe = False  # the harness probes explicitly
    ctx = build_job_context(cfg, tmp)
    obs_metrics.set_enabled(True)
    res = run_scf(cfg, base_dir=tmp, ctx=ctx, keep_state=True)
    st = res["_state"]
    xc = XCFunctional(cfg.parameters.xc_functionals)
    stages = probe_stages(
        ctx, xc, st["psi"],
        np.asarray(res["band_occupancies"]),
        np.asarray(res["band_energies"]),
        st["rho_g"], st.get("mag_g"),
        bound_ha=bound_ha,
        mixer_beta=float(cfg.mixer.beta),
        smearing=cfg.parameters.smearing,
        smearing_width=float(cfg.parameters.smearing_width),
    )
    emit_probe_events(stages, tier=name)
    return {
        "deck": {k: spec[k] for k in
                 ("gk_cutoff", "pw_cutoff", "num_bands", "num_dft_iter")},
        "iterations": res["num_scf_iterations"],
        "stages": stages,
    }


def measure(tiers: list[str], bound_ha: float = BOUND_HA) -> dict:
    from sirius_tpu.obs.costs import detect_platform
    from sirius_tpu.obs.perf import TIERS

    entry = {
        "created": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "host": _platform.node(),
        "platform": detect_platform(),
        "bound_ha": bound_ha,
        "tiers": {},
    }
    for t in tiers:
        if t not in TIERS:
            raise SystemExit(f"unknown tier '{t}' (have {sorted(TIERS)})")
        entry["tiers"][t] = run_tier(t, TIERS[t], bound_ha=bound_ha)
    return entry


def load_baseline(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if doc.get("schema") != SCHEMA:
        raise SystemExit(
            f"{path}: schema {doc.get('schema')!r} != supported {SCHEMA}")
    if not doc.get("series"):
        raise SystemExit(f"{path}: empty series")
    return doc


def compare_entries(base_entry: dict, cur_entry: dict,
                    tol_decades: float = TOL_DECADES) -> list[dict]:
    """Noise-aware headroom regressions of `cur_entry` vs `base_entry`.

    A regression is: a stage/precision present in the baseline but absent
    now; a clears-the-bound verdict flipping pass -> fail; or the energy
    impact growing by more than `tol_decades` decades above the baseline
    (both sides floored at NOISE_FLOOR, so noise-level errors compare
    equal no matter how their last digits moved).
    """
    regs = []
    for tname, bt in base_entry["tiers"].items():
        ct = cur_entry["tiers"].get(tname)
        if ct is None:
            continue  # not re-measured this run
        for sname, b in bt["stages"].items():
            c = ct["stages"].get(sname)
            if c is None:
                regs.append({
                    "tier": tname, "stage": sname, "prec": "*",
                    "kind": "missing",
                    "detail": "stage present in baseline, absent now",
                })
                continue
            for prec in PRECISIONS:
                if prec not in b:
                    continue
                if prec not in c:
                    regs.append({
                        "tier": tname, "stage": sname, "prec": prec,
                        "kind": "missing",
                        "detail": "precision present in baseline, "
                        "absent now",
                    })
                    continue
                bkey, ckey = f"clears_{prec}", f"clears_{prec}"
                if b.get(bkey) and not c.get(ckey):
                    regs.append({
                        "tier": tname, "stage": sname, "prec": prec,
                        "kind": "clears_flip",
                        "baseline": b[prec]["energy_impact_ha"],
                        "current": c[prec]["energy_impact_ha"],
                    })
                    continue
                bv = max(float(b[prec]["energy_impact_ha"]), NOISE_FLOOR)
                cv = max(float(c[prec]["energy_impact_ha"]), NOISE_FLOOR)
                if np.log10(cv) - np.log10(bv) > tol_decades:
                    regs.append({
                        "tier": tname, "stage": sname, "prec": prec,
                        "kind": "error_growth",
                        "baseline": bv, "current": cv,
                        "decades": float(np.log10(cv) - np.log10(bv)),
                    })
    return regs


def _print_report(entry: dict) -> None:
    for tname, tier in entry["tiers"].items():
        print(f"[{tname}] headroom vs {entry['bound_ha']:.0e} Ha bound "
              f"({tier['iterations']} iterations)")
        print(f"  {'stage':<18} {'fp32 impact':>12} {'bf16 impact':>12}"
              f"   clears fp32/bf16")
        for sname, s in sorted(tier["stages"].items()):
            c32 = "yes" if s["clears_fp32"] else "NO"
            c16 = "yes" if s["clears_bf16"] else "NO"
            print(f"  {sname:<18} "
                  f"{s['fp32']['energy_impact_ha']:>12.3e} "
                  f"{s['bf16']['energy_impact_ha']:>12.3e}"
                  f"   {c32:>3} / {c16}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="sirius-numerics",
        description="per-stage precision-headroom probes + baseline gate")
    sub = ap.add_subparsers(dest="cmd", required=True)
    rp = sub.add_parser(
        "report", help="probe tiers, print the headroom table, "
        "optionally gate against / update a baseline")
    rp.add_argument("--tiers", default="small",
                    help="comma list of tiers to probe (small,large)")
    rp.add_argument("--bound", type=float, default=BOUND_HA,
                    help="energy-impact bound in Ha (default 1e-8)")
    rp.add_argument("--compare", metavar="BASELINE",
                    help="compare against the newest entry of this "
                    "NUMERICS_BASELINE.json; exit 1 on regression")
    rp.add_argument("--update", metavar="BASELINE",
                    help="append this run to the baseline series "
                    "(creates the file if missing)")
    rp.add_argument("--tol-decades", type=float, default=TOL_DECADES,
                    help="allowed error growth in decades before the "
                    "gate trips (default 1.0)")
    rp.add_argument("--out", metavar="PATH",
                    help="also write this run's entry as JSON")
    args = ap.parse_args(argv)

    tiers = [t.strip() for t in args.tiers.split(",") if t.strip()]
    entry = measure(tiers, bound_ha=args.bound)
    _print_report(entry)

    if args.out:
        with open(args.out, "w") as f:
            json.dump({"schema": SCHEMA, "series": [entry]}, f, indent=1)
        print(f"wrote {args.out}")

    rc = 0
    if args.compare:
        doc = load_baseline(args.compare)
        regs = compare_entries(doc["series"][-1], entry,
                               tol_decades=args.tol_decades)
        if regs:
            rc = 1
            print(f"NUMERICS REGRESSION vs {args.compare} "
                  f"({doc['series'][-1]['created']}):", file=sys.stderr)
            for r in regs:
                if r["kind"] == "missing":
                    print(f"  {r['tier']}/{r['stage']}[{r['prec']}]: "
                          f"{r['detail']}", file=sys.stderr)
                elif r["kind"] == "clears_flip":
                    print(f"  {r['tier']}/{r['stage']}[{r['prec']}]: "
                          f"cleared the bound in baseline "
                          f"({r['baseline']:.3e} Ha), now fails "
                          f"({r['current']:.3e} Ha)", file=sys.stderr)
                else:
                    print(f"  {r['tier']}/{r['stage']}[{r['prec']}]: "
                          f"error grew {r['decades']:.2f} decades "
                          f"({r['baseline']:.3e} -> {r['current']:.3e} "
                          f"Ha)", file=sys.stderr)
        else:
            print(f"numerics gate OK vs {args.compare}")

    if args.update:
        if os.path.exists(args.update):
            doc = load_baseline(args.update)
        else:
            doc = {"schema": SCHEMA, "series": []}
        doc["series"].append(entry)
        with open(args.update, "w") as f:
            json.dump(doc, f, indent=1)
        print(f"appended to {args.update} ({len(doc['series'])} entries)")
    return rc


if __name__ == "__main__":
    sys.exit(main())
