"""sirius_tpu.obs — unified telemetry: metrics registry, JSONL events,
structured logging, on-demand jax.profiler capture, and the serve
/metrics HTTP endpoint.

Quick tour::

    from sirius_tpu import obs

    obs.REGISTRY.counter("scf_iterations_total").inc(job_id="si-0")
    obs.emit("scf_iteration", it=3, rms=1e-5)     # no-op unless configured
    obs.configure_events("run/events.jsonl")
    with obs.job_context("si-0", step=3):
        obs.get_logger("dft").info("converged")

``disable()`` (or ``control.telemetry = false``) turns metric updates
into no-ops for overhead-critical benchmarking; the event sink is
already a no-op unless a path was configured.
"""

from sirius_tpu.obs.events import (
    close as close_events,
    configure as configure_events,
    configured as events_configured,
    emit,
    read_events,
)
from sirius_tpu.obs.log import get_logger, job_context, setup as setup_logging
from sirius_tpu.obs.metrics import (
    REGISTRY,
    backend_compiles_this_thread,
    backend_compiles_total,
    install_jax_listeners,
    set_enabled,
    update_device_memory_gauges,
)
from sirius_tpu.obs.trace import CAPTURE
from sirius_tpu.obs.tracing import (
    current_trace_id,
    ensure_trace,
    hbm_high_water,
    new_trace_id,
    trace_context,
)

# spans/costs AFTER events/metrics: spans.py imports those submodules, so
# it must come once their attributes exist on the partial package
from sirius_tpu.obs.costs import (
    StageCost,
    annotate_span,
    peak_gbps,
    peak_gflops,
    xla_cost_analysis,
)
from sirius_tpu.obs.spans import (
    capture as capture_spans,
    current as current_span,
    record as record_span,
    span,
    spanned,
)

__all__ = [
    "REGISTRY",
    "CAPTURE",
    "span",
    "spanned",
    "capture_spans",
    "record_span",
    "current_span",
    "StageCost",
    "annotate_span",
    "peak_gflops",
    "peak_gbps",
    "xla_cost_analysis",
    "trace_context",
    "ensure_trace",
    "current_trace_id",
    "new_trace_id",
    "hbm_high_water",
    "emit",
    "configure_events",
    "events_configured",
    "close_events",
    "read_events",
    "get_logger",
    "job_context",
    "setup_logging",
    "install_jax_listeners",
    "backend_compiles_total",
    "backend_compiles_this_thread",
    "update_device_memory_gauges",
    "enable",
    "disable",
]


def enable() -> None:
    set_enabled(True)


def disable() -> None:
    set_enabled(False)
