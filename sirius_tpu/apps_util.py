"""Small mini-app utilities: EOS task + unit-cell tools.

Reference counterparts: the `eos` task of apps/mini_app/sirius.scf.cpp:412
(scan volume scales, record E(V)) and apps/utils/unit_cell_tools.cpp
(supercell construction from a 3x3 integer transformation)."""

from __future__ import annotations

import copy
import json

import numpy as np


def birch_murnaghan_fit(volume, energy):
    """3rd-order Birch-Murnaghan E(V) fit -> {v0, e0, b0 (Ha/bohr^3),
    b0_GPa, bp}. Least squares on the standard form."""
    v = np.asarray(volume, float)
    e = np.asarray(energy, float)
    if len(v) < 5:  # under-determined for the 4-parameter form
        return None
    # initial guesses from a parabola in v^{-2/3}
    x = v ** (-2.0 / 3.0)
    c = np.polyfit(x, e, 2)
    v0 = (-c[1] / (2 * c[0])) ** (-3.0 / 2.0) if c[0] > 0 else v[np.argmin(e)]
    p0 = [float(np.min(e)), float(v0), 0.01, 4.0]

    def bm(vv, e0, v0_, b0, bp):
        eta = (v0_ / vv) ** (2.0 / 3.0)
        return e0 + 9.0 * v0_ * b0 / 16.0 * (
            (eta - 1.0) ** 3 * bp + (eta - 1.0) ** 2 * (6.0 - 4.0 * eta)
        )

    try:
        from scipy.optimize import curve_fit

        popt, _ = curve_fit(bm, v, e, p0=p0, maxfev=20000)
        e0, v0_, b0, bp = (float(t) for t in popt)
    except Exception:  # no scipy / fit failure: E(V) data still useful
        return None
    return {
        "e0": e0, "v0": v0_, "b0_Ha_bohr3": b0,
        "b0_GPa": b0 * 29421.02648438959, "bp": bp,
    }


def run_eos(cfg_dict: dict, base_dir: str, volume_scale0: float,
            volume_scale1: float, num_steps: int = 7,
            output: str = "output_eos.json") -> dict:
    """Reference eos task: for s in cbrt(linspace(scale0, scale1)), scale
    the lattice, converge the ground state, record (volume, free energy).
    Writes output_eos.json and returns the dict (with a Birch-Murnaghan
    fit appended — the reference leaves fitting to the user)."""
    from sirius_tpu.config.schema import load_config
    from sirius_tpu.dft.scf import run_scf

    units = cfg_dict["unit_cell"].get("atom_coordinate_units", "lattice")
    if units not in ("lattice", ""):
        raise NotImplementedError(
            "eos scales lattice vectors, which only preserves the structure "
            f"with fractional atom coordinates (got '{units}')"
        )
    s0 = volume_scale0 ** (1.0 / 3.0)
    s1 = volume_scale1 ** (1.0 / 3.0)
    volume, energy, results = [], [], []
    base_lat = np.asarray(cfg_dict["unit_cell"]["lattice_vectors"], float)
    scale = float(cfg_dict["unit_cell"].get("lattice_vectors_scale", 1.0) or 1.0)
    for i in range(num_steps):
        s = s0 + i * (s1 - s0) / max(num_steps - 1, 1)
        d = copy.deepcopy(cfg_dict)
        d["unit_cell"]["lattice_vectors"] = (base_lat * s).tolist()
        cfg = load_config(d)
        res = run_scf(cfg, base_dir=base_dir)
        omega = abs(np.linalg.det(base_lat * scale * s))
        volume.append(omega)
        energy.append(res["energy"]["free"])
        results.append({
            "scale": s, "converged": res["converged"],
            "energy": res["energy"],
        })
    out = {"volume": volume, "energy": energy, "result": results}
    fit = birch_murnaghan_fit(volume, energy)
    if fit is not None:
        out["birch_murnaghan"] = fit
    with open(output, "w") as f:
        json.dump(out, f, indent=1)
    return out


def make_supercell(cfg_dict: dict, transform) -> dict:
    """New input dict with lattice T @ a and atoms replicated into the
    supercell (reference unit_cell_tools.cpp create_supercell). transform:
    3x3 integer matrix (row-vectors convention, |det| = volume multiple)."""
    T = np.asarray(transform, float).reshape(3, 3)
    det = int(round(abs(np.linalg.det(T))))
    if det < 1:
        raise ValueError(f"singular supercell transform (det {det})")
    uc = cfg_dict["unit_cell"]
    units = uc.get("atom_coordinate_units", "lattice")
    if units not in ("lattice", ""):
        raise NotImplementedError(
            f"supercell construction needs fractional atom coordinates "
            f"(atom_coordinate_units='{units}' is Cartesian)"
        )
    a = np.asarray(uc["lattice_vectors"], float)
    a_sc = T @ a
    t_inv = np.linalg.inv(T)
    # lattice translations of the primitive cell that fall inside the
    # supercell: scan a bounding block of integer shifts
    lim = int(np.ceil(np.abs(T).sum(axis=0).max())) + 1
    shifts = []
    rng = range(-lim, lim + 1)
    for i in rng:
        for j in rng:
            for kk in rng:
                f = np.array([i, j, kk], float) @ t_inv
                if np.all(f > -1e-9) and np.all(f < 1.0 - 1e-9):
                    shifts.append([i, j, kk])
    if len(shifts) != det:
        raise RuntimeError(
            f"found {len(shifts)} interior translations, expected {det}"
        )
    out = copy.deepcopy(cfg_dict)
    new_atoms = {}
    for label, plist in uc["atoms"].items():
        rows = []
        for p in plist:
            pos = np.asarray(p[:3], float)
            extra = list(p[3:])
            for sft in shifts:
                f_sc = (pos + np.asarray(sft, float)) @ t_inv
                f_sc = np.mod(f_sc, 1.0)
                rows.append([float(x) for x in f_sc] + extra)
        new_atoms[label] = rows
    out["unit_cell"]["lattice_vectors"] = a_sc.tolist()
    out["unit_cell"]["atoms"] = new_atoms
    return out


def unit_cell_tools_main(argv=None) -> int:
    """CLI: sirius-unit-cell-tools --input sirius.json --supercell
    "n1 n2 n3 n4 n5 n6 n7 n8 n9" [-o out.json]."""
    import argparse

    p = argparse.ArgumentParser(prog="sirius-unit-cell-tools")
    p.add_argument("--input", default="sirius.json")
    p.add_argument("--supercell", required=True,
                   help="9 integers of the 3x3 transformation (row major)")
    p.add_argument("-o", "--output", default="sirius_supercell.json")
    args = p.parse_args(argv)
    cfg = json.load(open(args.input))
    T = [int(x) for x in args.supercell.split()]
    if len(T) != 9:
        p.error(f"--supercell needs 9 integers (3x3, row major); got {len(T)}")
    out = make_supercell(cfg, T)
    with open(args.output, "w") as f:
        json.dump(out, f, indent=1)
    nat = sum(len(v) for v in out["unit_cell"]["atoms"].values())
    print(f"supercell with {nat} atoms -> {args.output}")
    return 0
