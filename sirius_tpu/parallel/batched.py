"""K-set-batched band solve: the whole (k, spin) loop as ONE jitted/vmapped
computation, shardable over the ("k", "b") mesh.

The reference loops local k-points serially per MPI rank
(diagonalize.hpp:58); on TPU the padded fixed-shape per-k arrays (GkVec)
make the entire k-set one vmapped davidson call — a single XLA program that
shards over the mesh with zero hand-written collectives (density reduction
over "k" is a psum XLA inserts from the einsum).

REAL-BOUNDARY CONTRACT: the TPU backend in this environment cannot move
complex arrays across any host<->device or jit boundary (transfers and
executable I/O with complex dtypes fail with UNIMPLEMENTED and wedge the
process; measured empirically — see bench.py). Every jitted entry point
here therefore takes and returns REAL arrays only; complex leaves of the
parameter pytree are stored as (re, im) pairs and the complex working
arrays exist only inside the compiled programs.

This is the PRODUCTION band-solve path: dft/scf.run_scf drives it each SCF
iteration with the per-spin screened D matrices and Hubbard potentials
batched in (a serial per-(k, spin) fallback remains for debugging).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.ops.hamiltonian import HkParams, apply_h_s
from sirius_tpu.solvers.davidson import davidson


class HkSetParams(NamedTuple):
    """Batched-over-(k, spin) Hamiltonian data, real leaves only.

    Per-k leaves carry a leading nk axis; spin-dependent leaves (potential,
    screened D, Hubbard V) carry an ns axis. ns == num_spins of the run
    (1 for unpolarized, 2 collinear). Complex tables are split into re/im
    real arrays (see module docstring)."""

    veff_r: jax.Array  # [ns, n1,n2,n3] effective potential per spin channel
    ekin: jax.Array  # [nk, ngk]
    mask: jax.Array  # [nk, ngk]
    fft_index: jax.Array  # [nk, ngk]
    beta_re: jax.Array  # [nk, nbeta, ngk]
    beta_im: jax.Array  # [nk, nbeta, ngk]
    dion: jax.Array  # [ns, nbeta, nbeta] screened D per spin
    qmat: jax.Array  # [nbeta, nbeta] shared
    h_diag: jax.Array  # [nk, ns, ngk]
    o_diag: jax.Array  # [nk, ngk] (S is spin-independent)
    hub_re: jax.Array = None  # [nk, nhub, ngk] S-weighted Hubbard orbitals
    hub_im: jax.Array = None
    vhub_re: jax.Array = None  # [nk, ns, nhub, nhub] (per-k: +V phases)
    vhub_im: jax.Array = None


def _cplx(re, im):
    """Complex from a re/im pair — ONLY call inside a jitted program."""
    return jax.lax.complex(re, im)


def split_cplx(a, rdtype=None):
    """Host-side split of a numpy complex array into a (re, im) real pair."""
    a = np.asarray(a)
    re = np.ascontiguousarray(np.real(a))
    im = np.ascontiguousarray(np.imag(a))
    if rdtype is not None:
        re = re.astype(rdtype)
        im = im.astype(rdtype)
    return re, im


def join_cplx(re, im):
    """Host-side join of a (re, im) device/real pair into numpy complex."""
    return np.asarray(re).astype(np.complex128) + 1j * np.asarray(im)


def compute_h_diag(ctx, dion, v0: float = 0.0):
    """h_diag [nk, ns, ngk]: H preconditioner diagonal for the whole k-set
    (reference get_h_o_diag_pw); changes every SCF iteration with the
    screened D. dion: [ns, nbeta, nbeta]."""
    nbeta = ctx.beta.num_beta_total
    nk = ctx.gkvec.num_kpoints
    ns = dion.shape[0]
    ekin = ctx.gkvec.kinetic()
    h_diag = np.empty((nk, ns, ctx.gkvec.ngk_max))
    for ik in range(nk):
        b = ctx.beta.beta_gk[ik]
        for ispn in range(ns):
            h = ekin[ik] + v0
            if nbeta:
                h = h + np.real(
                    np.einsum("xg,xy,yg->g", np.conj(b), dion[ispn], b)
                )
            h_diag[ik, ispn] = np.where(ctx.gkvec.mask[ik] > 0, h, 1e4)
    return h_diag


def compute_h_diag_device(ekin, mask, beta_re, beta_im, dion, v0):
    """Traced twin of compute_h_diag for the fused device-resident SCF
    step: all inputs are arrays already on device (ekin/mask [nk, ngk],
    beta pair [nk, nbeta, ngk], dion [ns, nbeta, nbeta], v0 traced scalar).
    Returns [nk, ns, ngk]. Call only inside a compiled program."""
    h = ekin[:, None, :] + v0
    if beta_re.shape[1]:
        b = _cplx(beta_re, beta_im)
        h = h + jnp.real(
            jnp.einsum("kxg,sxy,kyg->ksg", jnp.conj(b), dion, b)
        )
    else:
        h = jnp.broadcast_to(h, (h.shape[0], dion.shape[0], h.shape[2]))
    return jnp.where(mask[:, None, :] > 0, h, 1e4)


def compute_o_diag(ctx):
    """o_diag [nk, ngk]: S preconditioner diagonal; potential-independent
    (only the constant augmentation Q enters), computed once per run."""
    nbeta = ctx.beta.num_beta_total
    nk = ctx.gkvec.num_kpoints
    qmat = ctx.beta.qmat if ctx.beta.qmat is not None else np.zeros((nbeta, nbeta))
    o_diag = np.empty((nk, ctx.gkvec.ngk_max))
    for ik in range(nk):
        o = np.ones(ctx.gkvec.ngk_max)
        if nbeta:
            b = ctx.beta.beta_gk[ik]
            o = o + np.real(np.einsum("xg,xy,yg->g", np.conj(b), qmat, b))
        o_diag[ik] = np.where(ctx.gkvec.mask[ik] > 0, o, 1.0)
    return o_diag


def hkset_slice_r(params: HkSetParams, ik: int = 0, ispn: int = 0):
    """Single-(k, spin) real-leaf view of a batched HkSetParams, as a dict
    suitable for jit closure constants or real-boundary jit args. Rebuild
    the complex HkParams INSIDE the jitted program with hk_complex()."""
    return dict(
        veff_r=params.veff_r[ispn],
        ekin=params.ekin[ik],
        mask=params.mask[ik],
        fft_index=params.fft_index[ik],
        beta_re=params.beta_re[ik],
        beta_im=params.beta_im[ik],
        dion=params.dion[ispn],
        qmat=params.qmat,
        hub_re=None if params.hub_re is None else params.hub_re[ik],
        hub_im=None if params.hub_im is None else params.hub_im[ik],
        vhub_re=None if params.vhub_re is None else params.vhub_re[ik, ispn],
        vhub_im=None if params.vhub_im is None else params.vhub_im[ik, ispn],
    )


def hk_complex(p: dict) -> HkParams:
    """Assemble the complex per-k HkParams from real leaves; call only
    inside jit (complex must never cross the program boundary)."""
    return HkParams(
        veff_r=p["veff_r"],
        ekin=p["ekin"],
        mask=p["mask"],
        fft_index=p["fft_index"],
        beta=_cplx(p["beta_re"], p["beta_im"]),
        dion=p["dion"],
        qmat=p["qmat"],
        hub=None if p["hub_re"] is None else _cplx(p["hub_re"], p["hub_im"]),
        vhub=None if p["vhub_re"] is None else _cplx(p["vhub_re"], p["vhub_im"]),
    )


def make_hkset_params(
    ctx,
    veff_r_coarse,
    d_full=None,
    dtype=jnp.complex128,
    v0: float = 0.0,
    hub_phi=None,
    vhub=None,
) -> HkSetParams:
    """veff_r_coarse: [n1,n2,n3] or [ns, n1,n2,n3]; d_full: [nbeta,nbeta] or
    [ns,nbeta,nbeta] screened D (defaults to the bare dion); v0: average
    effective potential veff(G=0), included in the preconditioner diagonal
    exactly like the serial path (_h_o_diag). All leaves are REAL arrays."""
    from sirius_tpu.ops.hamiltonian import real_dtype_of

    nbeta = ctx.beta.num_beta_total
    nk = ctx.gkvec.num_kpoints
    veff = np.asarray(veff_r_coarse)
    if veff.ndim == 3:
        veff = veff[None]
    ns = veff.shape[0]
    dion = ctx.beta.dion if d_full is None else np.asarray(d_full)
    if dion.ndim == 2:
        dion = np.broadcast_to(dion, (ns,) + dion.shape)
    qmat = ctx.beta.qmat if ctx.beta.qmat is not None else np.zeros((nbeta, nbeta))

    rdtype = real_dtype_of(dtype)
    ekin = ctx.gkvec.kinetic()
    h_diag = compute_h_diag(ctx, dion, v0)
    o_diag = compute_o_diag(ctx)
    beta = (
        np.asarray(ctx.beta.beta_gk)
        if nbeta
        else np.zeros((nk, 0, ctx.gkvec.ngk_max), dtype=np.complex128)
    )
    beta_re, beta_im = split_cplx(beta, rdtype)
    hub_pair = (None, None) if hub_phi is None else split_cplx(hub_phi, rdtype)
    vhub_pair = (None, None) if vhub is None else split_cplx(vhub, rdtype)
    asr = lambda a: jnp.asarray(a, dtype=rdtype)
    return HkSetParams(
        veff_r=asr(veff),
        ekin=asr(ekin),
        mask=asr(ctx.gkvec.mask),
        fft_index=jnp.asarray(ctx.gkvec.fft_index),
        beta_re=jnp.asarray(beta_re),
        beta_im=jnp.asarray(beta_im),
        dion=asr(dion),
        qmat=asr(qmat),
        h_diag=asr(h_diag),
        o_diag=asr(o_diag),
        hub_re=None if hub_pair[0] is None else jnp.asarray(hub_pair[0]),
        hub_im=None if hub_pair[1] is None else jnp.asarray(hub_pair[1]),
        vhub_re=None if vhub_pair[0] is None else jnp.asarray(vhub_pair[0]),
        vhub_im=None if vhub_pair[1] is None else jnp.asarray(vhub_pair[1]),
    )


@partial(jax.jit, static_argnames=("nb",))
def initialize_subspace_kset(params: HkSetParams, psi_re, psi_im, nb: int):
    """LCAO subspace initialization for the whole (k, spin) set: one H/S
    application to the full atomic-orbital block (+ random tail), one
    generalized Rayleigh-Ritz, keep the lowest nb Ritz vectors (reference
    initialize_subspace.hpp:27 per-k, :279 kset driver). The input block is
    [nk, ns, nbig, ngk] with nbig >= nb; truncating atomic orbitals to nb
    BEFORE the rotation loses orbital characters and mis-seeds the band
    solver (Fe 3d, test03).

    Returns (psi_re, psi_im) [nk, ns, nb, ngk]."""
    from sirius_tpu.solvers.davidson import subspace_rotate

    psi = _cplx(psi_re, psi_im)
    has_hub = params.hub_re is not None

    def one_k(ekin, mask, fft_index, beta_re, beta_im, hub_re_k, hub_im_k,
              vhub_re_k, vhub_im_k, psi_k):
        def one_spin(veff_s, dion_s, vhub_re_s, vhub_im_s, x0):
            pk = HkParams(
                veff_r=veff_s,
                ekin=ekin,
                mask=mask,
                fft_index=fft_index,
                beta=_cplx(beta_re, beta_im),
                dion=dion_s,
                qmat=params.qmat,
                hub=None if hub_re_k is None else _cplx(hub_re_k, hub_im_k),
                vhub=None if vhub_re_s is None else _cplx(vhub_re_s, vhub_im_s),
            )
            x = x0 * mask
            hx, sx = apply_h_s(pk, x)
            return subspace_rotate(x, hx, sx, nb, mask=mask)

        return jax.vmap(
            one_spin,
            in_axes=(0, 0, None if not has_hub else 0,
                     None if not has_hub else 0, 0),
        )(params.veff_r, params.dion, vhub_re_k, vhub_im_k, psi_k)

    hub_ax = 0 if has_hub else None
    x = jax.vmap(
        one_k,
        in_axes=(0, 0, 0, 0, 0, hub_ax, hub_ax, hub_ax, hub_ax, 0),
    )(
        params.ekin, params.mask, params.fft_index, params.beta_re,
        params.beta_im, params.hub_re, params.hub_im,
        params.vhub_re, params.vhub_im, psi,
    )
    return jnp.real(x), jnp.imag(x)


@partial(jax.jit, static_argnames=("num_steps",))
def davidson_kset(
    params: HkSetParams, psi_re, psi_im, num_steps: int = 20, res_tol: float = 1e-6
):
    """Solve bands at every (k, spin) in one vmapped call.

    psi_re/psi_im: [nk, ns, nb, ngk] real pair ->
    (evals [nk, ns, nb], psi_re', psi_im', rnorm [nk, ns, nb])."""
    psi = _cplx(psi_re, psi_im)
    has_hub = params.hub_re is not None

    def one_k(ekin, mask, fft_index, beta_re, beta_im, h_diag_k, o_diag,
              hub_re_k, hub_im_k, vhub_re_k, vhub_im_k, psi_k):
        def one_spin(veff_s, dion_s, vhub_re_s, vhub_im_s, h_diag_s, x0):
            pk = HkParams(
                veff_r=veff_s,
                ekin=ekin,
                mask=mask,
                fft_index=fft_index,
                beta=_cplx(beta_re, beta_im),
                dion=dion_s,
                qmat=params.qmat,
                hub=None if hub_re_k is None else _cplx(hub_re_k, hub_im_k),
                vhub=None if vhub_re_s is None else _cplx(vhub_re_s, vhub_im_s),
            )
            return davidson(
                apply_h_s, pk, x0, h_diag_s, o_diag, mask,
                num_steps=num_steps, res_tol=res_tol,
            )

        return jax.vmap(
            one_spin,
            in_axes=(0, 0, None if not has_hub else 0,
                     None if not has_hub else 0, 0, 0),
        )(params.veff_r, params.dion, vhub_re_k, vhub_im_k,
          h_diag_k, psi_k)

    hub_ax = 0 if has_hub else None
    ev, x, rn = jax.vmap(
        one_k,
        in_axes=(0, 0, 0, 0, 0, 0, 0, hub_ax, hub_ax, hub_ax, hub_ax, 0),
    )(
        params.ekin, params.mask, params.fft_index, params.beta_re,
        params.beta_im, params.h_diag, params.o_diag,
        params.hub_re, params.hub_im, params.vhub_re, params.vhub_im, psi,
    )
    return ev, jnp.real(x), jnp.imag(x), rn


@jax.jit
def density_kset(params: HkSetParams, psi_re, psi_im, occ_w):
    """Coarse-box density sum_{k,b} occ_w |psi(r)|^2 per spin — contracts
    over the whole k-set in one program (psum over "k" under sharding).

    occ_w: [nk, ns, nb] occupation x k-weight. Returns [ns, n1, n2, n3]
    (real)."""
    psi = _cplx(psi_re, psi_im)
    dims = params.veff_r.shape[-3:]
    n = dims[0] * dims[1] * dims[2]

    def one_k(fft_index, psi_k, ow):
        batch = psi_k.shape[:-1]
        box = jnp.zeros(batch + (n,), dtype=psi_k.dtype).at[..., fft_index].add(psi_k)
        fr = jnp.fft.ifftn(box.reshape(batch + dims), axes=(-3, -2, -1)) * n
        return jnp.einsum("sb,sbxyz->sxyz", ow, jnp.abs(fr) ** 2)

    return jnp.sum(jax.vmap(one_k)(params.fft_index, psi, occ_w), axis=0)


@jax.jit
def density_matrix_kset(beta_re, beta_im, psi_re, psi_im, occ_w):
    """Non-local density matrix n^sigma_{xi xi'} = sum_{k,b} occ_w
    conj(<beta_xi|psi>) <beta_xi'|psi>, contracted over the whole k-set
    (reference add_k_point_contribution_dm_pwpp, density.cpp:847-901).

    beta_re/beta_im: [nk, nbeta, ngk] projector tables (pass the
    full-precision f64 pair so the accumulation precision is independent of
    the wave-function working dtype). Returns a (re, im) pair of
    [ns, nbeta, nbeta]."""
    rdt = jnp.promote_types(beta_re.dtype, psi_re.dtype)
    beta = _cplx(beta_re.astype(rdt), beta_im.astype(rdt))
    psi = _cplx(psi_re.astype(rdt), psi_im.astype(rdt))

    def one_k(beta_k, psi_k, ow):
        bp = jnp.einsum("xg,sbg->sbx", jnp.conj(beta_k), psi_k)
        return jnp.einsum("sb,sbx,sby->sxy", ow, jnp.conj(bp), bp)

    dm = jnp.sum(jax.vmap(one_k)(beta, psi, occ_w), axis=0)
    return jnp.real(dm), jnp.imag(dm)
