"""K-set-batched band solve: the whole k-point loop as ONE jitted/vmapped
computation, shardable over the ("k", "b") mesh.

The reference loops local k-points serially per MPI rank
(diagonalize.hpp:58); on TPU the padded fixed-shape per-k arrays (GkVec)
make the entire k-set one vmapped davidson call — a single XLA program that
shards over the mesh with zero hand-written collectives (density reduction
over "k" is a psum XLA inserts from the einsum).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.ops.hamiltonian import HkParams, apply_h_s
from sirius_tpu.solvers.davidson import davidson


class HkSetParams(NamedTuple):
    """Batched-over-k Hamiltonian data (leading axis nk on per-k leaves)."""

    veff_r: jax.Array  # [n1,n2,n3] shared
    ekin: jax.Array  # [nk, ngk]
    mask: jax.Array  # [nk, ngk]
    fft_index: jax.Array  # [nk, ngk]
    beta: jax.Array  # [nk, nbeta, ngk]
    dion: jax.Array  # [nbeta, nbeta] shared
    qmat: jax.Array  # [nbeta, nbeta] shared
    h_diag: jax.Array  # [nk, ngk]
    o_diag: jax.Array  # [nk, ngk]


def make_hkset_params(
    ctx, veff_r_coarse, d_full=None, dtype=jnp.complex128, v0: float = 0.0
) -> HkSetParams:
    """v0: average effective potential veff(G=0), included in the
    preconditioner diagonal exactly like the serial path (_h_o_diag)."""
    nbeta = ctx.beta.num_beta_total
    nk = ctx.gkvec.num_kpoints
    dion = ctx.beta.dion if d_full is None else d_full
    qmat = ctx.beta.qmat if ctx.beta.qmat is not None else np.zeros((nbeta, nbeta))
    from sirius_tpu.ops.hamiltonian import real_dtype_of

    rdtype = real_dtype_of(dtype)
    ekin = ctx.gkvec.kinetic()
    h_diag = np.empty((nk, ctx.gkvec.ngk_max))
    o_diag = np.empty_like(h_diag)
    for ik in range(nk):
        b = ctx.beta.beta_gk[ik]
        h = ekin[ik] + v0
        o = np.ones_like(h)
        if nbeta:
            h = h + np.real(np.einsum("xg,xy,yg->g", np.conj(b), dion, b))
            o = o + np.real(np.einsum("xg,xy,yg->g", np.conj(b), qmat, b))
        h_diag[ik] = np.where(ctx.gkvec.mask[ik] > 0, h, 1e4)
        o_diag[ik] = np.where(ctx.gkvec.mask[ik] > 0, o, 1.0)
    beta = (
        ctx.beta.beta_gk
        if nbeta
        else np.zeros((nk, 0, ctx.gkvec.ngk_max), dtype=np.complex128)
    )
    return HkSetParams(
        veff_r=jnp.asarray(veff_r_coarse, dtype=rdtype),
        ekin=jnp.asarray(ekin, dtype=rdtype),
        mask=jnp.asarray(ctx.gkvec.mask, dtype=rdtype),
        fft_index=jnp.asarray(ctx.gkvec.fft_index),
        beta=jnp.asarray(beta, dtype=dtype),
        dion=jnp.asarray(dion, dtype=rdtype),
        qmat=jnp.asarray(qmat, dtype=rdtype),
        h_diag=jnp.asarray(h_diag, dtype=rdtype),
        o_diag=jnp.asarray(o_diag, dtype=rdtype),
    )


def _davidson_one_k(params_k: HkParams, h_diag, o_diag, x0, num_steps, res_tol):
    return davidson(
        apply_h_s, params_k, x0, h_diag, o_diag, params_k.mask,
        num_steps=num_steps, res_tol=res_tol,
    )


@partial(jax.jit, static_argnames=("num_steps",))
def davidson_kset(params: HkSetParams, psi, num_steps: int = 20, res_tol: float = 1e-6):
    """Solve bands at every (k, spin) in one vmapped call.

    psi: [nk, ns, nb, ngk] -> (evals [nk, ns, nb], psi', rnorm [nk, ns, nb]).
    """

    def one_k(ekin, mask, fft_index, beta, h_diag, o_diag, psi_k):
        pk = HkParams(
            veff_r=params.veff_r,
            ekin=ekin,
            mask=mask,
            fft_index=fft_index,
            beta=beta,
            dion=params.dion,
            qmat=params.qmat,
        )

        def one_spin(x0):
            return _davidson_one_k(pk, h_diag, o_diag, x0, num_steps, res_tol)

        return jax.vmap(one_spin)(psi_k)

    return jax.vmap(one_k)(
        params.ekin, params.mask, params.fft_index, params.beta,
        params.h_diag, params.o_diag, psi,
    )


@jax.jit
def density_kset(params: HkSetParams, psi, occ_w):
    """Coarse-box density sum_{k,s,b} occ_w |psi(r)|^2 — contracts over the
    whole k-set in one program (psum over "k" under sharding).

    occ_w: [nk, ns, nb] occupation x k-weight."""
    dims = params.veff_r.shape
    n = dims[0] * dims[1] * dims[2]

    def one_k(fft_index, psi_k, ow):
        batch = psi_k.shape[:-1]
        box = jnp.zeros(batch + (n,), dtype=psi_k.dtype).at[..., fft_index].add(psi_k)
        fr = jnp.fft.ifftn(box.reshape(batch + dims), axes=(-3, -2, -1)) * n
        return jnp.einsum("sb,sbxyz->xyz", ow, jnp.abs(fr) ** 2)

    return jnp.sum(jax.vmap(one_k)(params.fft_index, psi, occ_w), axis=0)
