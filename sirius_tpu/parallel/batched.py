"""K-set-batched band solve: the whole (k, spin) loop as ONE jitted/vmapped
computation, shardable over the ("k", "b") mesh.

The reference loops local k-points serially per MPI rank
(diagonalize.hpp:58); on TPU the padded fixed-shape per-k arrays (GkVec)
make the entire k-set one vmapped davidson call — a single XLA program that
shards over the mesh with zero hand-written collectives (density reduction
over "k" is a psum XLA inserts from the einsum).

This is the PRODUCTION band-solve path: dft/scf.run_scf drives it each SCF
iteration with the per-spin screened D matrices and Hubbard potentials
batched in (a serial per-(k, spin) fallback remains for debugging).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.ops.hamiltonian import HkParams, apply_h_s
from sirius_tpu.solvers.davidson import davidson


class HkSetParams(NamedTuple):
    """Batched-over-(k, spin) Hamiltonian data.

    Per-k leaves carry a leading nk axis; spin-dependent leaves (potential,
    screened D, Hubbard V) carry an ns axis. ns == num_spins of the run
    (1 for unpolarized, 2 collinear)."""

    veff_r: jax.Array  # [ns, n1,n2,n3] effective potential per spin channel
    ekin: jax.Array  # [nk, ngk]
    mask: jax.Array  # [nk, ngk]
    fft_index: jax.Array  # [nk, ngk]
    beta: jax.Array  # [nk, nbeta, ngk]
    dion: jax.Array  # [ns, nbeta, nbeta] screened D per spin
    qmat: jax.Array  # [nbeta, nbeta] shared
    h_diag: jax.Array  # [nk, ns, ngk]
    o_diag: jax.Array  # [nk, ngk] (S is spin-independent)
    hub: jax.Array = None  # [nk, nhub, ngk] S-weighted Hubbard orbitals
    vhub: jax.Array = None  # [ns, nhub, nhub]


def compute_h_diag(ctx, dion, v0: float = 0.0):
    """h_diag [nk, ns, ngk]: H preconditioner diagonal for the whole k-set
    (reference get_h_o_diag_pw); changes every SCF iteration with the
    screened D. dion: [ns, nbeta, nbeta]."""
    nbeta = ctx.beta.num_beta_total
    nk = ctx.gkvec.num_kpoints
    ns = dion.shape[0]
    ekin = ctx.gkvec.kinetic()
    h_diag = np.empty((nk, ns, ctx.gkvec.ngk_max))
    for ik in range(nk):
        b = ctx.beta.beta_gk[ik]
        for ispn in range(ns):
            h = ekin[ik] + v0
            if nbeta:
                h = h + np.real(
                    np.einsum("xg,xy,yg->g", np.conj(b), dion[ispn], b)
                )
            h_diag[ik, ispn] = np.where(ctx.gkvec.mask[ik] > 0, h, 1e4)
    return h_diag


def compute_o_diag(ctx):
    """o_diag [nk, ngk]: S preconditioner diagonal; potential-independent
    (only the constant augmentation Q enters), computed once per run."""
    nbeta = ctx.beta.num_beta_total
    nk = ctx.gkvec.num_kpoints
    qmat = ctx.beta.qmat if ctx.beta.qmat is not None else np.zeros((nbeta, nbeta))
    o_diag = np.empty((nk, ctx.gkvec.ngk_max))
    for ik in range(nk):
        o = np.ones(ctx.gkvec.ngk_max)
        if nbeta:
            b = ctx.beta.beta_gk[ik]
            o = o + np.real(np.einsum("xg,xy,yg->g", np.conj(b), qmat, b))
        o_diag[ik] = np.where(ctx.gkvec.mask[ik] > 0, o, 1.0)
    return o_diag


def hkset_slice(params: HkSetParams, ik: int = 0, ispn: int = 0) -> HkParams:
    """Single-(k, spin) HkParams view of a batched HkSetParams (used by the
    bench/probe/entry micro-workloads; Hubbard leaves carried along)."""
    return HkParams(
        veff_r=params.veff_r[ispn],
        ekin=params.ekin[ik],
        mask=params.mask[ik],
        fft_index=params.fft_index[ik],
        beta=params.beta[ik],
        dion=params.dion[ispn],
        qmat=params.qmat,
        hub=None if params.hub is None else params.hub[ik],
        vhub=None if params.vhub is None else params.vhub[ispn],
    )


def make_hkset_params(
    ctx,
    veff_r_coarse,
    d_full=None,
    dtype=jnp.complex128,
    v0: float = 0.0,
    hub_phi=None,
    vhub=None,
) -> HkSetParams:
    """veff_r_coarse: [n1,n2,n3] or [ns, n1,n2,n3]; d_full: [nbeta,nbeta] or
    [ns,nbeta,nbeta] screened D (defaults to the bare dion); v0: average
    effective potential veff(G=0), included in the preconditioner diagonal
    exactly like the serial path (_h_o_diag)."""
    from sirius_tpu.ops.hamiltonian import real_dtype_of

    nbeta = ctx.beta.num_beta_total
    nk = ctx.gkvec.num_kpoints
    veff = np.asarray(veff_r_coarse)
    if veff.ndim == 3:
        veff = veff[None]
    ns = veff.shape[0]
    dion = ctx.beta.dion if d_full is None else np.asarray(d_full)
    if dion.ndim == 2:
        dion = np.broadcast_to(dion, (ns,) + dion.shape)
    qmat = ctx.beta.qmat if ctx.beta.qmat is not None else np.zeros((nbeta, nbeta))

    rdtype = real_dtype_of(dtype)
    ekin = ctx.gkvec.kinetic()
    h_diag = compute_h_diag(ctx, dion, v0)
    o_diag = compute_o_diag(ctx)
    beta = (
        ctx.beta.beta_gk
        if nbeta
        else np.zeros((nk, 0, ctx.gkvec.ngk_max), dtype=np.complex128)
    )
    return HkSetParams(
        veff_r=jnp.asarray(veff, dtype=rdtype),
        ekin=jnp.asarray(ekin, dtype=rdtype),
        mask=jnp.asarray(ctx.gkvec.mask, dtype=rdtype),
        fft_index=jnp.asarray(ctx.gkvec.fft_index),
        beta=jnp.asarray(beta, dtype=dtype),
        dion=jnp.asarray(dion, dtype=rdtype),
        qmat=jnp.asarray(qmat, dtype=rdtype),
        h_diag=jnp.asarray(h_diag, dtype=rdtype),
        o_diag=jnp.asarray(o_diag, dtype=rdtype),
        hub=None if hub_phi is None else jnp.asarray(hub_phi, dtype=dtype),
        vhub=None if vhub is None else jnp.asarray(vhub, dtype=dtype),
    )


def _davidson_one_k(params_k: HkParams, h_diag, o_diag, x0, num_steps, res_tol):
    return davidson(
        apply_h_s, params_k, x0, h_diag, o_diag, params_k.mask,
        num_steps=num_steps, res_tol=res_tol,
    )


@partial(jax.jit, static_argnames=("num_steps",))
def davidson_kset(params: HkSetParams, psi, num_steps: int = 20, res_tol: float = 1e-6):
    """Solve bands at every (k, spin) in one vmapped call.

    psi: [nk, ns, nb, ngk] -> (evals [nk, ns, nb], psi', rnorm [nk, ns, nb]).
    """

    def one_k(ekin, mask, fft_index, beta, h_diag_k, o_diag, hub_k, psi_k):
        def one_spin(veff_s, dion_s, vhub_s, h_diag_s, x0):
            pk = HkParams(
                veff_r=veff_s,
                ekin=ekin,
                mask=mask,
                fft_index=fft_index,
                beta=beta,
                dion=dion_s,
                qmat=params.qmat,
                hub=hub_k,
                vhub=vhub_s,
            )
            return _davidson_one_k(pk, h_diag_s, o_diag, x0, num_steps, res_tol)

        return jax.vmap(one_spin)(
            params.veff_r, params.dion, params.vhub, h_diag_k, psi_k
        )

    return jax.vmap(
        one_k,
        in_axes=(0, 0, 0, 0, 0, 0, None if params.hub is None else 0, 0),
    )(
        params.ekin, params.mask, params.fft_index, params.beta,
        params.h_diag, params.o_diag, params.hub, psi,
    )


@jax.jit
def density_kset(params: HkSetParams, psi, occ_w):
    """Coarse-box density sum_{k,b} occ_w |psi(r)|^2 per spin — contracts
    over the whole k-set in one program (psum over "k" under sharding).

    occ_w: [nk, ns, nb] occupation x k-weight. Returns [ns, n1, n2, n3]."""
    dims = params.veff_r.shape[-3:]
    n = dims[0] * dims[1] * dims[2]

    def one_k(fft_index, psi_k, ow):
        batch = psi_k.shape[:-1]
        box = jnp.zeros(batch + (n,), dtype=psi_k.dtype).at[..., fft_index].add(psi_k)
        fr = jnp.fft.ifftn(box.reshape(batch + dims), axes=(-3, -2, -1)) * n
        return jnp.einsum("sb,sbxyz->sxyz", ow, jnp.abs(fr) ** 2)

    return jnp.sum(jax.vmap(one_k)(params.fft_index, psi, occ_w), axis=0)


@jax.jit
def density_matrix_kset(beta, psi, occ_w):
    """Non-local density matrix n^sigma_{xi xi'} = sum_{k,b} occ_w
    conj(<beta_xi|psi>) <beta_xi'|psi>, contracted over the whole k-set
    (reference add_k_point_contribution_dm_pwpp, density.cpp:847-901).

    beta: [nk, nbeta, ngk] projector tables (pass the full-precision c128
    stack so the accumulation precision is independent of the wave-function
    working dtype). Returns [ns, nbeta, nbeta]."""

    def one_k(beta_k, psi_k, ow):
        bp = jnp.einsum("xg,sbg->sbx", jnp.conj(beta_k), psi_k)
        return jnp.einsum("sb,sbx,sby->sxy", ow, jnp.conj(bp), bp)

    return jnp.sum(jax.vmap(one_k)(beta, psi, occ_w), axis=0)
