"""K-set-batched spinor band solve and 4-component density accumulation
(non-collinear magnetism), real-boundary contract like parallel/batched.py.

The whole k-set solves in ONE vmapped program; spinors are flattened into
the G axis ([nb, 2*ngk]) so the fixed-shape Davidson is reused unchanged.
Density accumulation produces the reference's 4 real fields
(rho, mz, mx, my) from the spinor components in a single contraction
(reference density.cpp:636-700 add_k_point_contribution_rg_noncollinear:
up = |psi_u|^2, dn = |psi_d|^2, mx = 2 Re psi_u psi_d*, my = -2 Im).
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.ops.spinor import NcHkParams, apply_h_s_nc, nc_h_o_diag
from sirius_tpu.solvers.davidson import davidson


class NcSetParams(NamedTuple):
    """Batched-over-k spinor Hamiltonian data, real leaves only.

    Complex tables are (re, im) pairs (see parallel/batched.py)."""

    veff_uu: jax.Array  # [n1,n2,n3]
    veff_dd: jax.Array
    bx: jax.Array
    by: jax.Array
    ekin: jax.Array  # [nk, ngk]
    mask: jax.Array  # [nk, ngk]
    fft_index: jax.Array  # [nk, ngk]
    beta_re: jax.Array  # [nk, nbeta, ngk]
    beta_im: jax.Array
    dmat_re: jax.Array  # [4, nbeta, nbeta]
    dmat_im: jax.Array
    qmat_re: jax.Array  # [4, nbeta, nbeta]
    qmat_im: jax.Array
    h_diag: jax.Array  # [nk, 2*ngk]
    o_diag: jax.Array  # [nk, 2*ngk]


def _cplx(re, im):
    return jax.lax.complex(re, im)


def make_nc_set_params(
    ctx, veff_boxes, dmat_blocks, qmat_blocks=None, dtype=jnp.complex128,
    v0: float = 0.0, prev: NcSetParams | None = None,
) -> NcSetParams:
    """veff_boxes: (v_uu, v_dd, bx, by) coarse real boxes; dmat_blocks:
    [4, nbeta, nbeta] complex (uu, dd, ud, du); qmat_blocks defaults to the
    spin-diagonal augmentation Q.

    prev: pass the previous iteration's params to reuse the constant device
    tables (projectors, kinetic, masks, Q) — only the potential-dependent
    leaves are re-uploaded (like the collinear _kset_cache in dft/scf.py)."""
    from sirius_tpu.ops.hamiltonian import real_dtype_of
    from sirius_tpu.parallel.batched import split_cplx

    nbeta = ctx.beta.num_beta_total
    nk = ctx.gkvec.num_kpoints
    rdtype = real_dtype_of(dtype)
    v_uu, v_dd, bx, by = [np.asarray(v) for v in veff_boxes]
    h_diag, o_diag = nc_h_o_diag(ctx, np.real(dmat_blocks), v0)
    dr, di = split_cplx(dmat_blocks, rdtype)
    asr = lambda a: jnp.asarray(a, dtype=rdtype)
    if prev is not None and prev.veff_uu.dtype == np.dtype(rdtype):
        return prev._replace(
            veff_uu=asr(v_uu), veff_dd=asr(v_dd), bx=asr(bx), by=asr(by),
            dmat_re=jnp.asarray(dr), dmat_im=jnp.asarray(di),
            h_diag=asr(h_diag),
        )
    if qmat_blocks is None:
        q = ctx.beta.qmat if ctx.beta.qmat is not None else np.zeros((nbeta, nbeta))
        z = np.zeros_like(q)
        qmat_blocks = np.stack([q, q, z, z]).astype(np.complex128)
    beta = (
        np.asarray(ctx.beta.beta_gk)
        if nbeta
        else np.zeros((nk, 0, ctx.gkvec.ngk_max), dtype=np.complex128)
    )
    br, bi = split_cplx(beta, rdtype)
    qr, qi = split_cplx(qmat_blocks, rdtype)
    return NcSetParams(
        veff_uu=asr(v_uu), veff_dd=asr(v_dd), bx=asr(bx), by=asr(by),
        ekin=asr(ctx.gkvec.kinetic()),
        mask=asr(ctx.gkvec.mask),
        fft_index=jnp.asarray(ctx.gkvec.fft_index),
        beta_re=jnp.asarray(br), beta_im=jnp.asarray(bi),
        dmat_re=jnp.asarray(dr), dmat_im=jnp.asarray(di),
        qmat_re=jnp.asarray(qr), qmat_im=jnp.asarray(qi),
        h_diag=asr(h_diag), o_diag=asr(o_diag),
    )


@partial(jax.jit, static_argnames=("num_steps",))
def davidson_kset_nc(
    params: NcSetParams, psi_re, psi_im, num_steps: int = 20, res_tol: float = 1e-6
):
    """psi_re/psi_im: [nk, nb, 2*ngk] flattened spinors ->
    (evals [nk, nb], psi_re', psi_im', rnorm [nk, nb])."""
    psi = _cplx(psi_re, psi_im)
    dmat = _cplx(params.dmat_re, params.dmat_im)
    qmat = _cplx(params.qmat_re, params.qmat_im)

    def one_k(ekin, mask, fft_index, beta_re, beta_im, h_diag, o_diag, x0):
        pk = NcHkParams(
            veff_uu=params.veff_uu, veff_dd=params.veff_dd,
            bx=params.bx, by=params.by,
            ekin=ekin, mask=mask, fft_index=fft_index,
            beta=_cplx(beta_re, beta_im), dmat=dmat, qmat=qmat,
        )
        mask2 = jnp.tile(mask, 2)
        return davidson(
            apply_h_s_nc, pk, x0, h_diag, o_diag, mask2,
            num_steps=num_steps, res_tol=res_tol,
        )

    ev, x, rn = jax.vmap(one_k)(
        params.ekin, params.mask, params.fft_index,
        params.beta_re, params.beta_im, params.h_diag, params.o_diag, psi,
    )
    return ev, jnp.real(x), jnp.imag(x), rn


@jax.jit
def density_kset_nc(params: NcSetParams, psi_re, psi_im, occ_w):
    """4-component coarse-box density (rho, mz, mx, my).

    psi: [nk, nb, 2*ngk] flattened spinors; occ_w: [nk, nb] occupation x
    k-weight. Returns [4, n1, n2, n3] real."""
    psi = _cplx(psi_re, psi_im)
    dims = params.veff_uu.shape
    n = dims[0] * dims[1] * dims[2]

    def one_k(fft_index, psi_k, ow):
        nb = psi_k.shape[0]
        ngk = fft_index.shape[0]
        p = psi_k.reshape(nb, 2, ngk)
        box = jnp.zeros((nb, 2, n), dtype=p.dtype).at[..., fft_index].add(p)
        fr = jnp.fft.ifftn(box.reshape((nb, 2) + dims), axes=(-3, -2, -1)) * n
        up = jnp.einsum("b,bxyz->xyz", ow, jnp.abs(fr[:, 0]) ** 2)
        dn = jnp.einsum("b,bxyz->xyz", ow, jnp.abs(fr[:, 1]) ** 2)
        z2 = jnp.einsum("b,bxyz->xyz", ow, fr[:, 0] * jnp.conj(fr[:, 1]))
        return jnp.stack([
            up + dn, up - dn, 2.0 * jnp.real(z2), -2.0 * jnp.imag(z2)
        ])

    return jnp.sum(jax.vmap(one_k)(params.fft_index, psi, occ_w), axis=0)


@jax.jit
def density_matrix_kset_nc(beta_re, beta_im, psi_re, psi_im, occ_w):
    """Spin-resolved non-local density matrix, 3 components (uu, dd, ud):
    n^{ss'}_{xy} = sum_{k,b} occ_w <beta_x|psi_s> conj(<beta_y|psi_s'>)
    (reference density.cpp:901-1025 add_k_point_contribution_dm_pwpp_
    noncollinear; the du block is the Hermitian conjugate and not stored).

    psi: [nk, nb, 2*ngk]; returns (re, im) of [3, nbeta, nbeta]."""
    rdt = jnp.promote_types(beta_re.dtype, psi_re.dtype)
    beta = _cplx(beta_re.astype(rdt), beta_im.astype(rdt))
    psi = _cplx(psi_re.astype(rdt), psi_im.astype(rdt))

    def one_k(beta_k, psi_k, ow):
        nb = psi_k.shape[0]
        ngk = beta_k.shape[-1]
        p = psi_k.reshape(nb, 2, ngk)
        bp = jnp.einsum("xg,bsg->bsx", jnp.conj(beta_k), p)
        uu = jnp.einsum("b,bx,by->xy", ow, bp[:, 0], jnp.conj(bp[:, 0]))
        dd = jnp.einsum("b,bx,by->xy", ow, bp[:, 1], jnp.conj(bp[:, 1]))
        ud = jnp.einsum("b,bx,by->xy", ow, bp[:, 0], jnp.conj(bp[:, 1]))
        return jnp.stack([uu, dd, ud])

    dm = jnp.sum(jax.vmap(one_k)(beta, psi, occ_w), axis=0)
    return jnp.real(dm), jnp.imag(dm)
