"""Device meshes and sharding for distributed SCF.

The reference's 3-level MPI product grid world = comm_k x (npr x npc)
(simulation_context.cpp:1300-1349) maps to one jax.sharding.Mesh with axes

  "k" — k-point parallelism (embarrassingly parallel band solves; only the
        density reduction and Fermi sync cross it -> psum over "k");
  "b" — band parallelism (batched FFTs are per-band independent; subspace
        Gram matrices contract over bands -> XLA inserts all-gathers);

G-vector sharding (the reference's z-column/SpFFT slab axis) composes with
these via sharded FFT boxes and is introduced when single-replica boxes stop
fitting; at the sizes of the verification suite k x b sharding saturates the
chips first.

Everything uses GSPMD through jit + NamedSharding: the solver code is the
same single-device code; collectives are inserted by XLA (SURVEY.md §2.8).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_k: int | None = None, num_b: int | None = None) -> Mesh:
    """Mesh over all available devices, factored as ("k", "b").

    By default puts as many devices on "k" as divide the device count."""
    devs = np.array(jax.devices())
    n = len(devs)
    if num_k is None:
        num_k = n
        num_b = 1
    if num_b is None:
        num_b = n // num_k
    assert num_k * num_b == n, f"{num_k}*{num_b} != {n} devices"
    return Mesh(devs.reshape(num_k, num_b), ("k", "b"))


def shard_kset(mesh: Mesh, psi):
    """Shard a [nk, ns, nb, ngk] wave-function array: k-points over "k",
    bands over "b"."""
    return jax.device_put(psi, NamedSharding(mesh, P("k", None, "b", None)))


def kset_spec() -> P:
    return P("k", None, "b", None)


def production_mesh(nk: int, nb: int, devices=None):
    """Mesh for the production SCF on however many devices are present.

    Chooses (num_k, num_b) with num_k | nk, num_b | nb and
    num_k * num_b <= ndev maximizing the used device count (k first on
    ties — band solves are embarrassingly parallel over k). The mesh may
    be PARTIAL (a subset of devices): real parallelism on fewer devices
    beats a full-device mesh with replicated axes. Returns
    (mesh, psi_spec) or (None, None) when no parallel factorization
    exists — callers keep the exact single-device path then.

    Multi-process (multi-host) runs require every process's devices in
    the mesh, so partial meshes are limited to single-process sessions;
    multi-host falls back to the full-device gcd factorization.

    devices: explicit device list to build the mesh from (a serving-engine
    slice); defaults to jax.devices()."""
    import math

    devices = list(devices) if devices is not None else jax.devices()
    ndev = len(devices)
    if ndev <= 1:
        return None, None
    nk = max(nk, 1)
    nb = max(nb, 1)
    multi_host = jax.process_count() > 1
    if multi_host:
        num_k = math.gcd(nk, ndev)
        # (multi-host ignores `devices`: every process's devices must be in
        # the mesh, so slice scheduling is a single-process feature)
        # full-device mesh (multi-host requires every device present); the
        # band axis is sized ndev//num_k and only USED when nb divides it —
        # otherwise the "b" axis replicates (spec None below) by design
        mesh = make_mesh(num_k=num_k, num_b=ndev // num_k)
        band_ax = "b" if (ndev // num_k > 1 and nb % (ndev // num_k) == 0) else None
        if num_k == 1 and band_ax is None:
            return None, None
        return mesh, P("k", None, band_ax, None)
    best = (1, 1)
    for dk in range(1, min(nk, ndev) + 1):
        if nk % dk:
            continue
        db = math.gcd(nb, ndev // dk)
        if dk * db > best[0] * best[1] or (
            dk * db == best[0] * best[1] and dk > best[0]
        ):
            best = (dk, db)
    num_k, num_b = best
    if num_k * num_b == 1:
        return None, None
    devs = np.array(devices[: num_k * num_b])
    mesh = Mesh(devs.reshape(num_k, num_b), ("k", "b"))
    band_ax = "b" if num_b > 1 else None
    return mesh, P("k", None, band_ax, None)


def place_kset_params(params, mesh: Mesh):
    """device_put every leaf of an HkSetParams with its natural sharding:
    leading-nk leaves split over "k", spin/shared tables replicated. A
    device_put onto an identical sharding is a no-op, so calling this per
    SCF iteration only moves the refreshed potential-dependent leaves."""
    if mesh is None:
        return params
    k1 = NamedSharding(mesh, P("k", None))
    k2 = NamedSharding(mesh, P("k", None, None))
    rep = NamedSharding(mesh, P())

    def put(x, s):
        return None if x is None else jax.device_put(x, s)

    return params._replace(
        veff_r=put(params.veff_r, rep),
        ekin=put(params.ekin, k1),
        mask=put(params.mask, k1),
        fft_index=put(params.fft_index, k1),
        beta_re=put(params.beta_re, k2),
        beta_im=put(params.beta_im, k2),
        dion=put(params.dion, rep),
        qmat=put(params.qmat, rep),
        h_diag=put(params.h_diag, k2),
        o_diag=put(params.o_diag, k1),
        hub_re=put(params.hub_re, k2),
        hub_im=put(params.hub_im, k2),
        vhub_re=put(params.vhub_re, rep),
        vhub_im=put(params.vhub_im, rep),
    )
