"""Device meshes and sharding for distributed SCF.

The reference's 3-level MPI product grid world = comm_k x (npr x npc)
(simulation_context.cpp:1300-1349) maps to one jax.sharding.Mesh with axes

  "k" — k-point parallelism (embarrassingly parallel band solves; only the
        density reduction and Fermi sync cross it -> psum over "k");
  "b" — band parallelism (batched FFTs are per-band independent; subspace
        Gram matrices contract over bands -> XLA inserts all-gathers);

G-vector sharding (the reference's z-column/SpFFT slab axis) composes with
these via sharded FFT boxes and is introduced when single-replica boxes stop
fitting; at the sizes of the verification suite k x b sharding saturates the
chips first.

Everything uses GSPMD through jit + NamedSharding: the solver code is the
same single-device code; collectives are inserted by XLA (SURVEY.md §2.8).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(num_k: int | None = None, num_b: int | None = None) -> Mesh:
    """Mesh over all available devices, factored as ("k", "b").

    By default puts as many devices on "k" as divide the device count."""
    devs = np.array(jax.devices())
    n = len(devs)
    if num_k is None:
        num_k = n
        num_b = 1
    if num_b is None:
        num_b = n // num_k
    assert num_k * num_b == n, f"{num_k}*{num_b} != {n} devices"
    return Mesh(devs.reshape(num_k, num_b), ("k", "b"))


def shard_kset(mesh: Mesh, psi):
    """Shard a [nk, ns, nb, ngk] wave-function array: k-points over "k",
    bands over "b"."""
    return jax.device_put(psi, NamedSharding(mesh, P("k", None, "b", None)))


def kset_spec() -> P:
    return P("k", None, "b", None)
