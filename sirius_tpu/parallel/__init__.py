from sirius_tpu.parallel.mesh import make_mesh, shard_kset
from sirius_tpu.parallel.batched import davidson_kset, HkSetParams, make_hkset_params
