"""Distributed 3-D FFT over a "g" mesh axis (slab decomposition).

Reference mechanism: SpFFT slab FFTs over z-columns of the box with MPI
transposes (src/core/fft/gvec.hpp:805 Gvec_fft, fft.hpp:29-95), used when
a replicated FFT box per band stops fitting (Si-511 class: ~1e6 G x ~2e3
bands). TPU-native equivalent: shard the box's FIRST axis over the "g"
mesh axis, do local FFTs over the two unsharded axes, one
lax.all_to_all re-slab, then the FFT along the remaining axis —
exactly the slab algorithm, with the MPI alltoall replaced by the ICI
collective.

Layouts (P = mesh size along "g"):
  x-slabs:  [n1/P, n2, n3]  per shard (sharded axis 0)
  y-slabs:  [n1, n2/P, n3]  per shard (sharded axis 1)

fft3d(box sharded x-slabs) -> full FFT, sharded y-slabs; ifft3d inverts.
n1 and n2 must be divisible by P (good_fft_size can always pad to a
multiple — the driver chooses box dims with the mesh in mind).

All entry points are shard_map'ed pure functions: call them inside jit
with arrays already device-put to the matching NamedSharding (see
tests/test_dist_fft.py for the canonical wiring).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# jax < 0.5 ships shard_map under jax.experimental only
_shard_map = getattr(jax, "shard_map", None)
if _shard_map is None:
    from jax.experimental.shard_map import shard_map as _shard_map


def x_slab_spec() -> P:
    """Spec of a [..., n1, n2, n3] box sharded into x-slabs over "g"."""
    return P(None, "g", None, None)


def y_slab_spec() -> P:
    return P(None, None, "g", None)


def _fft_local_yz(slab):
    return jnp.fft.fftn(slab, axes=(-2, -1))


def _reslab_x_to_y(slab, axis_name: str):
    """[n1/P, n2, n3] x-slab -> [n1, n2/P, n3] y-slab via one all_to_all.

    Split the y axis into P blocks, exchange so every shard receives its
    y-block from all x-slabs, and concatenate along x."""
    # slab: [..., n1p, n2, n3] -> split axis -2 into P chunks, all_to_all
    # over the chunk axis, then merge the received x-chunks along axis -3
    # (named_scope tags the HLO so device profiles and xprof group the
    # exchange under a stable name the timeline exporter knows)
    with jax.named_scope("collective.all_to_all_x2y"):
        return jax.lax.all_to_all(
            slab, axis_name, split_axis=slab.ndim - 2,
            concat_axis=slab.ndim - 3, tiled=True,
        )


def _reslab_y_to_x(slab, axis_name: str):
    with jax.named_scope("collective.all_to_all_y2x"):
        return jax.lax.all_to_all(
            slab, axis_name, split_axis=slab.ndim - 3,
            concat_axis=slab.ndim - 2, tiled=True,
        )


def fft3d_shard(slab, axis_name: str = "g"):
    """Forward 3-D FFT of an x-slab-sharded box; result is y-slab sharded.

    slab: [..., n1/P, n2, n3] local block (call inside shard_map)."""
    slab = _fft_local_yz(slab)
    slab = _reslab_x_to_y(slab, axis_name)  # [..., n1, n2/P, n3]
    return jnp.fft.fft(slab, axis=-3)


def ifft3d_shard(slab, axis_name: str = "g"):
    """Inverse of fft3d_shard: y-slab-sharded spectrum -> x-slab box."""
    slab = jnp.fft.ifft(slab, axis=-3)
    slab = _reslab_y_to_x(slab, axis_name)  # [..., n1/P, n2, n3]
    return jnp.fft.ifftn(slab, axes=(-2, -1))


def make_dist_fft(mesh: Mesh, dims: tuple[int, int, int], batch: int):
    """jitted (fft, ifft) pair over `mesh`'s "g" axis for boxes
    [batch, n1, n2, n3]; inputs/outputs carry the slab NamedShardings."""
    npg = mesh.shape["g"]
    n1, n2, _ = dims
    if n1 % npg or n2 % npg:
        raise ValueError(
            f"box dims {dims} not divisible by mesh axis g={npg}; pick "
            "good_fft_size multiples of the mesh size"
        )
    xs = NamedSharding(mesh, x_slab_spec())
    ys = NamedSharding(mesh, y_slab_spec())

    fwd = jax.jit(
        _shard_map(
            partial(fft3d_shard, axis_name="g"),
            mesh=mesh, in_specs=x_slab_spec(), out_specs=y_slab_spec(),
        ),
        in_shardings=xs, out_shardings=ys,
    )
    inv = jax.jit(
        _shard_map(
            partial(ifft3d_shard, axis_name="g"),
            mesh=mesh, in_specs=y_slab_spec(), out_specs=x_slab_spec(),
        ),
        in_shardings=ys, out_shardings=xs,
    )
    return fwd, inv


def make_apply_veff_dist(mesh: Mesh, dims: tuple[int, int, int]):
    """Distributed local-operator core V.psi: spectral boxes in, spectral
    boxes out, every stage slab-sharded over "g" (the reference's per-band
    SpFFT loop body, local_operator.cpp:320-370, as two distributed
    transforms around a sharded pointwise multiply).

    Returns a jitted fn(psi_spec [nb, n1, n2, n3] y-slab-sharded spectrum,
    veff_r [n1, n2, n3] x-slab-sharded real potential) -> y-slab spectrum
    of V.psi. With the module's conventions (f(r) = N ifftn(F)) the N
    factors cancel: F' = fft3d(ifft3d(F) * V)."""
    npg = mesh.shape["g"]
    n1, n2, _ = dims
    if n1 % npg or n2 % npg:
        raise ValueError(f"box dims {dims} not divisible by g={npg}")
    ys = NamedSharding(mesh, y_slab_spec())
    vxs = NamedSharding(mesh, P("g", None, None))

    def _core(psi_spec, veff):
        r = ifft3d_shard(psi_spec, "g")  # [nb, n1/P, n2, n3] x-slab real
        r = r * veff[None]
        return fft3d_shard(r, "g")

    return jax.jit(
        _shard_map(
            _core, mesh=mesh,
            in_specs=(y_slab_spec(), P("g", None, None)),
            out_specs=y_slab_spec(),
        ),
        in_shardings=(ys, vxs), out_shardings=ys,
    )


# ---------------------------------------------------------------------------
# G-sharded Hamiltonian application: the slab path packaged as a davidson-
# compatible operator (equivalence-tested through a full band solve; the
# SCF driver selects it for the single-k Si-supercell-class regime — not
# yet auto-dispatched from run_scf). The G sphere is
# partitioned by the x index of each G's box slot, so every shard scatters
# its own coefficients into its own x-slab locally; the local operator runs
# as (ifft yz) -> all_to_all -> (ifft x) -> x V -> (fft x) -> all_to_all ->
# (fft yz); the beta-projector contractions reduce over "g" with one psum.
# ---------------------------------------------------------------------------


def gshard_partition(millers, dims, nparts: int):
    """Partition a G set by box x-slab.

    Returns (order [ngk_pad_total], local_index [nparts, ngk_loc],
    counts [nparts]): `order` maps the new (shard-major, padded) G layout
    back to the original G index (-1 = padding); local_index holds each
    shard's flattened LOCAL box indices (slab layout [n1/P, n2, n3]),
    with padding pointing at slot 0 alongside zero coefficients."""
    import numpy as np

    n1, n2, n3 = dims
    if nparts <= 0 or n1 % nparts:
        raise ValueError(f"n1={n1} not divisible into {nparts} x-slabs")
    i0 = np.mod(np.asarray(millers)[:, 0], n1)
    i1 = np.mod(np.asarray(millers)[:, 1], n2)
    i2 = np.mod(np.asarray(millers)[:, 2], n3)
    n1p = n1 // nparts
    part = i0 // n1p
    counts = np.bincount(part, minlength=nparts)
    ngk_loc = int(counts.max())
    order = np.full((nparts, ngk_loc), -1, dtype=np.int64)
    lidx = np.zeros((nparts, ngk_loc), dtype=np.int64)
    for p in range(nparts):
        sel = np.nonzero(part == p)[0]
        order[p, : len(sel)] = sel
        lidx[p, : len(sel)] = (
            (i0[sel] - p * n1p) * n2 + i1[sel]
        ) * n3 + i2[sel]
    return order, lidx, counts


def reorder_to_gshard(arr, order):
    """Gather the last axis of `arr` into the (shard-major, padded) layout;
    padding slots get zeros."""
    import numpy as np

    flat = order.reshape(-1)
    safe = np.maximum(flat, 0)
    out = np.asarray(arr)[..., safe]
    out = np.where(flat >= 0, out, 0.0)
    return out


def reorder_from_gshard(arr, order, ngk: int):
    """Inverse of reorder_to_gshard (padding dropped)."""
    import numpy as np

    flat = order.reshape(-1)
    out = np.zeros(arr.shape[:-1] + (ngk,), dtype=np.asarray(arr).dtype)
    ok = flat >= 0
    out[..., flat[ok]] = np.asarray(arr)[..., ok]
    return out


_GSHARD_INNER_CACHE: dict = {}


def _gshard_inner(mesh: Mesh, n1p: int, n2: int, n3: int):
    """Jitted shard_map operator body, cached per (mesh, slab geometry) —
    a STABLE callable so repeated factory calls (new potential each SCF
    iteration) hit the same compiled program instead of retracing a fresh
    closure (the no-closure rule of ops/hamiltonian.py)."""
    key = (id(mesh), n1p, n2, n3)
    hit = _GSHARD_INNER_CACHE.get(key)
    if hit is not None:
        return hit
    nloc = n1p * n2 * n3
    gspec = P(None, "g")
    gspec1 = P("g")

    def _apply(psi_loc, ekin_loc, mask_loc, beta_loc, lidx_loc, dion_r,
               qmat_r, veff_loc):
        # psi_loc: [nb, ngk_loc] this shard's coefficients
        nb = psi_loc.shape[0]
        psi_loc = psi_loc * mask_loc
        box = jnp.zeros((nb, nloc), dtype=psi_loc.dtype)
        box = box.at[:, lidx_loc].add(psi_loc)
        box = box.reshape(nb, n1p, n2, n3)
        # spectrum x-slab -> real y-slab
        fr = jnp.fft.ifftn(box, axes=(-2, -1))
        fr = _reslab_x_to_y(fr, "g")  # [nb, n1, n2/P, n3]
        fr = jnp.fft.ifft(fr, axis=-3)
        fr = fr * veff_loc[None]  # veff_loc: [n1, n2/P, n3] y-slab
        # real y-slab -> spectrum x-slab
        fr = jnp.fft.fft(fr, axis=-3)
        fr = _reslab_y_to_x(fr, "g")
        fr = jnp.fft.fftn(fr, axes=(-2, -1))
        vpsi = fr.reshape(nb, nloc)[:, lidx_loc] * mask_loc
        hpsi = jnp.where(mask_loc > 0, ekin_loc, 0.0) * psi_loc + vpsi
        spsi = psi_loc
        if beta_loc.shape[0]:
            with jax.named_scope("collective.psum_beta"):
                bp = jax.lax.psum(
                    jnp.einsum("xg,bg->bx", jnp.conj(beta_loc), psi_loc),
                    "g",
                )
            hpsi = hpsi + jnp.einsum("bx,xy,yg->bg", bp, dion_r, beta_loc)
            spsi = spsi + jnp.einsum("bx,xy,yg->bg", bp, qmat_r, beta_loc)
        return hpsi * mask_loc, spsi * mask_loc

    inner = jax.jit(
        _shard_map(
            _apply, mesh=mesh,
            in_specs=(gspec, gspec1, gspec1, P(None, "g"), gspec1, P(), P(),
                      P(None, "g", None)),
            out_specs=(gspec, gspec),
        )
    )
    _GSHARD_INNER_CACHE[key] = inner
    return inner


def make_apply_h_s_gshard(mesh: Mesh, dims, lidx, ekin_g, mask_g,
                          beta_g, dion, qmat, veff_r):
    """G-sharded (H psi, S psi) over the mesh's "g" axis.

    All *_g tables are in the shard-major gshard layout (callers apply
    reorder_to_gshard with the `order` from gshard_partition) and are
    device_put by this factory; psi arguments use the same layout:
    [nb, nparts*ngk_loc] with NamedSharding P(None, "g").

    Covers the kinetic + local + beta-projector (D/Q) terms of
    ops.hamiltonian.apply_h_s — equality asserted through a full davidson
    solve in tests/test_gshard_apply.py. Hubbard U is NOT applied on this
    path; +U runs use the replicated operator (the flagship G-sharded
    regime is plain Si-supercell class)."""
    import numpy as np

    npg = mesh.shape["g"]
    n1, n2, n3 = dims
    if n1 % npg or n2 % npg:
        raise ValueError(f"box dims {dims} not divisible by g={npg}")
    n1p = n1 // npg
    nloc = n1p * n2 * n3

    gspec = P(None, "g")     # [nb, ngk] arrays
    gspec1 = P("g")          # 1-D per-G tables
    gshard = NamedSharding(mesh, gspec)
    gshard1 = NamedSharding(mesh, gspec1)
    rep = NamedSharding(mesh, P())

    ekin_d = jax.device_put(jnp.asarray(ekin_g), gshard1)
    mask_d = jax.device_put(jnp.asarray(mask_g), gshard1)
    beta_d = jax.device_put(jnp.asarray(beta_g), NamedSharding(mesh, P(None, "g")))
    lidx_d = jax.device_put(jnp.asarray(lidx.reshape(-1)), gshard1)
    dion_d = jax.device_put(jnp.asarray(dion), rep)
    qmat_d = jax.device_put(jnp.asarray(qmat), rep)
    # real potential in the Y-slab layout the multiply needs; it is passed
    # per CALL (params slot) so SCF iterations with a new potential reuse
    # the same compiled program instead of retracing a fresh closure
    veff_sharding = NamedSharding(mesh, P(None, "g", None))
    veff_d = jax.device_put(jnp.asarray(np.asarray(veff_r)), veff_sharding)

    inner = _gshard_inner(mesh, n1p, n2, n3)

    def apply_h_s_gshard(params, psi):
        """davidson-compatible apply. params:
          None              -> factory veff + factory dion
          veff              -> new potential, factory dion
          (veff, dion)      -> per-SCF-iteration potential AND screened D
        (all leaves same shape/sharding as the factory ones, so iterations
        reuse the compiled program without retracing)."""
        d = dion_d
        if isinstance(params, tuple):
            v, d = params
        else:
            v = veff_d if params is None else params
        return inner(psi, ekin_d, mask_d, beta_d, lidx_d, d, qmat_d, v)

    apply_h_s_gshard.sharding_veff = veff_sharding
    apply_h_s_gshard.veff0 = veff_d
    return apply_h_s_gshard, gshard


# ---------------------------------------------------------------------------
# collective attribution probes
#
# A host timer cannot see inside one jitted apply — the exchanges, local
# FFTs, and the beta psum all fuse into one program. These probes compile
# each piece SEPARATELY at the deck's real shapes, warm it, then time it
# fenced, giving a measured per-call cost for every named collective. The
# SCF layer multiplies these by analytic apply counts to split the
# measured scf.band_solve wall into compute vs collective sub-spans, and
# bench_gshard_large writes them per-ndev into GSHARD_LARGE.json.
# ---------------------------------------------------------------------------


def probe_collectives(mesh: Mesh, dims: tuple[int, int, int], batch: int,
                      nbeta: int = 0, ngk: int | None = None,
                      dtype=jnp.complex128, reps: int = 3) -> dict:
    """Time each named collective of the G-sharded apply in isolation.

    batch: the band-block size the solver actually applies (nb rows per
    H.psi). ngk: padded G-count for the beta-psum probe (defaults to the
    box volume / 8, roughly the cutoff-sphere fill of a production deck).
    Returns {span_name: seconds per call (median of reps)}; each probe
    also records a ``collective.*`` span so the timeline shows them.
    """
    import time as _time

    import numpy as np

    from sirius_tpu.obs import spans as _spans

    npg = mesh.shape["g"]
    n1, n2, n3 = dims
    if n1 % npg or n2 % npg:
        raise ValueError(f"box dims {dims} not divisible by g={npg}")
    xs = NamedSharding(mesh, x_slab_spec())
    ys = NamedSharding(mesh, y_slab_spec())

    box = jax.device_put(
        jnp.ones((batch, n1, n2, n3), dtype=dtype), xs)
    box_y = jax.device_put(
        jnp.ones((batch, n1, n2, n3), dtype=dtype), ys)

    def _fft_local_apply(slab):
        # the four local-FFT stages of one apply, exchanges elided
        fr = jnp.fft.ifftn(slab, axes=(-2, -1))
        fr = jnp.fft.ifft(fr, axis=-3)
        fr = jnp.fft.fft(fr, axis=-3)
        return jnp.fft.fftn(fr, axes=(-2, -1))

    probes: dict[str, tuple] = {
        "collective.all_to_all_x2y": (
            jax.jit(_shard_map(
                partial(_reslab_x_to_y, axis_name="g"), mesh=mesh,
                in_specs=x_slab_spec(), out_specs=y_slab_spec()),
                in_shardings=xs, out_shardings=ys),
            (box,)),
        "collective.all_to_all_y2x": (
            jax.jit(_shard_map(
                partial(_reslab_y_to_x, axis_name="g"), mesh=mesh,
                in_specs=y_slab_spec(), out_specs=x_slab_spec()),
                in_shardings=ys, out_shardings=xs),
            (box_y,)),
        "collective.fft_local": (
            jax.jit(_shard_map(
                _fft_local_apply, mesh=mesh,
                in_specs=x_slab_spec(), out_specs=x_slab_spec()),
                in_shardings=xs, out_shardings=xs),
            (box,)),
    }

    if nbeta > 0:
        if ngk is None:
            ngk = max(npg, (n1 * n2 * n3) // 8 // npg * npg)
        gsh = NamedSharding(mesh, P(None, "g"))
        psi = jax.device_put(jnp.ones((batch, ngk), dtype=dtype), gsh)
        beta = jax.device_put(jnp.ones((nbeta, ngk), dtype=dtype), gsh)

        def _beta_psum(b, p):
            with jax.named_scope("collective.psum_beta"):
                return jax.lax.psum(
                    jnp.einsum("xg,bg->bx", jnp.conj(b), p), "g")

        probes["collective.psum_beta"] = (
            jax.jit(_shard_map(
                _beta_psum, mesh=mesh,
                in_specs=(P(None, "g"), P(None, "g")), out_specs=P())),
            (beta, psi))

    out = {}
    for name, (fn, arglist) in probes.items():
        jax.block_until_ready(fn(*arglist))  # compile + warm
        times = []
        for _ in range(max(1, reps)):
            t0 = _time.perf_counter()
            jax.block_until_ready(fn(*arglist))
            times.append(_time.perf_counter() - t0)
        med = float(np.median(times))
        _spans.record(name, med, ndev=npg, batch=batch,
                      dims=list(dims), reps=len(times))
        out[name] = med
    return out
