"""Distributed 3-D FFT over a "g" mesh axis (slab decomposition).

Reference mechanism: SpFFT slab FFTs over z-columns of the box with MPI
transposes (src/core/fft/gvec.hpp:805 Gvec_fft, fft.hpp:29-95), used when
a replicated FFT box per band stops fitting (Si-511 class: ~1e6 G x ~2e3
bands). TPU-native equivalent: shard the box's FIRST axis over the "g"
mesh axis, do local FFTs over the two unsharded axes, one
lax.all_to_all re-slab, then the FFT along the remaining axis —
exactly the slab algorithm, with the MPI alltoall replaced by the ICI
collective.

Layouts (P = mesh size along "g"):
  x-slabs:  [n1/P, n2, n3]  per shard (sharded axis 0)
  y-slabs:  [n1, n2/P, n3]  per shard (sharded axis 1)

fft3d(box sharded x-slabs) -> full FFT, sharded y-slabs; ifft3d inverts.
n1 and n2 must be divisible by P (good_fft_size can always pad to a
multiple — the driver chooses box dims with the mesh in mind).

All entry points are shard_map'ed pure functions: call them inside jit
with arrays already device-put to the matching NamedSharding (see
tests/test_dist_fft.py for the canonical wiring).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def x_slab_spec() -> P:
    """Spec of a [..., n1, n2, n3] box sharded into x-slabs over "g"."""
    return P(None, "g", None, None)


def y_slab_spec() -> P:
    return P(None, None, "g", None)


def _fft_local_yz(slab):
    return jnp.fft.fftn(slab, axes=(-2, -1))


def _reslab_x_to_y(slab, axis_name: str):
    """[n1/P, n2, n3] x-slab -> [n1, n2/P, n3] y-slab via one all_to_all.

    Split the y axis into P blocks, exchange so every shard receives its
    y-block from all x-slabs, and concatenate along x."""
    # slab: [..., n1p, n2, n3] -> split axis -2 into P chunks, all_to_all
    # over the chunk axis, then merge the received x-chunks along axis -3
    return jax.lax.all_to_all(
        slab, axis_name, split_axis=slab.ndim - 2, concat_axis=slab.ndim - 3,
        tiled=True,
    )


def _reslab_y_to_x(slab, axis_name: str):
    return jax.lax.all_to_all(
        slab, axis_name, split_axis=slab.ndim - 3, concat_axis=slab.ndim - 2,
        tiled=True,
    )


def fft3d_shard(slab, axis_name: str = "g"):
    """Forward 3-D FFT of an x-slab-sharded box; result is y-slab sharded.

    slab: [..., n1/P, n2, n3] local block (call inside shard_map)."""
    slab = _fft_local_yz(slab)
    slab = _reslab_x_to_y(slab, axis_name)  # [..., n1, n2/P, n3]
    return jnp.fft.fft(slab, axis=-3)


def ifft3d_shard(slab, axis_name: str = "g"):
    """Inverse of fft3d_shard: y-slab-sharded spectrum -> x-slab box."""
    slab = jnp.fft.ifft(slab, axis=-3)
    slab = _reslab_y_to_x(slab, axis_name)  # [..., n1/P, n2, n3]
    return jnp.fft.ifftn(slab, axes=(-2, -1))


def make_dist_fft(mesh: Mesh, dims: tuple[int, int, int], batch: int):
    """jitted (fft, ifft) pair over `mesh`'s "g" axis for boxes
    [batch, n1, n2, n3]; inputs/outputs carry the slab NamedShardings."""
    npg = mesh.shape["g"]
    n1, n2, _ = dims
    if n1 % npg or n2 % npg:
        raise ValueError(
            f"box dims {dims} not divisible by mesh axis g={npg}; pick "
            "good_fft_size multiples of the mesh size"
        )
    xs = NamedSharding(mesh, x_slab_spec())
    ys = NamedSharding(mesh, y_slab_spec())

    fwd = jax.jit(
        jax.shard_map(
            partial(fft3d_shard, axis_name="g"),
            mesh=mesh, in_specs=x_slab_spec(), out_specs=y_slab_spec(),
        ),
        in_shardings=xs, out_shardings=ys,
    )
    inv = jax.jit(
        jax.shard_map(
            partial(ifft3d_shard, axis_name="g"),
            mesh=mesh, in_specs=y_slab_spec(), out_specs=x_slab_spec(),
        ),
        in_shardings=ys, out_shardings=xs,
    )
    return fwd, inv


def make_apply_veff_dist(mesh: Mesh, dims: tuple[int, int, int]):
    """Distributed local-operator core V.psi: spectral boxes in, spectral
    boxes out, every stage slab-sharded over "g" (the reference's per-band
    SpFFT loop body, local_operator.cpp:320-370, as two distributed
    transforms around a sharded pointwise multiply).

    Returns a jitted fn(psi_spec [nb, n1, n2, n3] y-slab-sharded spectrum,
    veff_r [n1, n2, n3] x-slab-sharded real potential) -> y-slab spectrum
    of V.psi. With the module's conventions (f(r) = N ifftn(F)) the N
    factors cancel: F' = fft3d(ifft3d(F) * V)."""
    npg = mesh.shape["g"]
    n1, n2, _ = dims
    if n1 % npg or n2 % npg:
        raise ValueError(f"box dims {dims} not divisible by g={npg}")
    ys = NamedSharding(mesh, y_slab_spec())
    vxs = NamedSharding(mesh, P("g", None, None))

    def _core(psi_spec, veff):
        r = ifft3d_shard(psi_spec, "g")  # [nb, n1/P, n2, n3] x-slab real
        r = r * veff[None]
        return fft3d_shard(r, "g")

    return jax.jit(
        jax.shard_map(
            _core, mesh=mesh,
            in_specs=(y_slab_spec(), P("g", None, None)),
            out_specs=y_slab_spec(),
        ),
        in_shardings=(ys, vxs), out_shardings=ys,
    )
