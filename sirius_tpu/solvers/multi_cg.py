"""Blocked conjugate-gradient solver with per-column convergence locking.

Reference: src/multi_cg/multi_cg.hpp:40-180 (sirius::cg::multi_cg) — the
backend of the reference's sirius_linear_solver C-API call
(src/api/sirius_api.cpp:6101) used by Quantum ESPRESSO's DFPT/phonon code.

TPU-first redesign: the reference moves converged columns to the front of
the block (repack) to shrink the GEMMs — a dynamic shape. Under jit we
keep the block FIXED and mask converged columns out of the updates
instead: every iteration is the same static program, the while_loop exits
when the mask empties. The per-column quantities (rho, alpha) ride along
as [nrhs] vectors.

The Sternheimer operator for linear response,
  A_i = H - eps_i S + alpha_pv sum_occ S |psi><psi| S,
is provided as a closure factory; its projector term regularizes the
near-singular occupied subspace exactly like the reference's
Linear_response_operator (alpha_pv from QE).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax


def multi_cg(apply_a, x0, b, apply_p=None, tol: float = 1e-3,
             maxiter: int = 100):
    """Solve A x_i = b_i for a block of right-hand sides.

    apply_a(X): [m, nrhs] -> [m, nrhs] (each column through its own
    operator — closures may index per-column shifts); apply_p optional
    preconditioner. Returns (X, niter, res_norms [nrhs]).

    Masked-fixed-shape analog of the reference multi_cg (repack -> mask)."""
    if apply_p is None:
        def apply_p(r):
            return r

    nrhs = b.shape[1]

    def dots(a_, b_):
        return jnp.sum(jnp.conj(a_) * b_, axis=0)

    r0 = b - apply_a(x0)

    def cond(state):
        it, _, _, _, _, _, active = state
        return jnp.logical_and(it < maxiter, jnp.any(active))

    def body(state):
        it, x, r, u, rho_old, first, active = state
        c = apply_p(r)
        rho = dots(c, r)
        active = jnp.logical_and(active, jnp.abs(rho) > tol * tol)
        beta = jnp.where(
            first | ~active,
            jnp.zeros_like(rho),
            rho / jnp.where(jnp.abs(rho_old) > 0, rho_old, 1.0),
        )
        u = c + beta[None, :] * u
        ac = apply_a(u)
        sigma = dots(u, ac)
        alpha = jnp.where(
            active,
            rho / jnp.where(jnp.abs(sigma) > 0, sigma, 1.0),
            jnp.zeros_like(rho),
        )
        x = x + alpha[None, :] * u
        r = r - alpha[None, :] * ac
        return (it + 1, x, r, u, rho, jnp.zeros((), bool), active)

    state = (
        jnp.zeros((), jnp.int32), x0, r0, jnp.zeros_like(b),
        jnp.zeros(nrhs, b.dtype),
        jnp.ones((), bool), jnp.ones(nrhs, bool),
    )
    it, x, r, _, _, _, _ = lax.while_loop(cond, body, state)
    return x, it, jnp.sqrt(jnp.abs(dots(r, r)))


def sternheimer_operator(apply_h_s, psi_occ, eps, alpha_pv: float):
    """A(X)[:, i] = (H - eps_i S) X[:, i] + alpha_pv S Psi (Psi^H S X).

    apply_h_s(X) -> (HX, SX) columnwise; psi_occ [m, nocc] unperturbed
    occupied states; eps [nrhs] band energies of the columns being solved
    (reference lr::Linear_response_operator, multi_cg.hpp:320-420)."""
    _, s_psi = apply_h_s(psi_occ)

    def apply_a(x):
        hx, sx = apply_h_s(x)
        proj = s_psi @ (jnp.conj(s_psi).T @ x)
        return hx - eps[None, :] * sx + alpha_pv * proj

    return apply_a
