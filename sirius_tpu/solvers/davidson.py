"""Blocked iterative eigensolver for (H, S), fixed-shape and jit-able.

The reference uses a growing-subspace block Davidson with locking and
restarts (src/hamiltonian/davidson.hpp:107-856). Growing subspaces mean
dynamic shapes — poison for XLA — so the TPU design is a locked-block
LOBPCG-style iteration with a constant 3*nb subspace [X, K R, P]:

  1. R = H X - eval S X, soft-locked by convergence mask
  2. K R: Teter-style diagonal preconditioner (reference residuals_aux.cu
     apply_preconditioner: p = h_diag - e*o_diag; p <- (1+p+sqrt(1+(p-1)^2))/2)
  3. Rayleigh-Ritz on V = [X, KR, P] with a rank-revealing (eigh-based)
     overlap regularization instead of Cholesky — ill-conditioned subspace
     directions are projected out, not crashed on
  4. X' = V C_low, P' = V C_low minus the X-block contribution

Every step is dense batched linear algebra (MXU) + ONE H/S application to
the new preconditioned-residual block: H X and H P are carried through the
scan and updated by the same linear combinations as X and P (the reference
likewise applies H only to the newly-added subspace block per iteration,
davidson.hpp:751-801). In single precision the carried blocks drift and the
Rayleigh-Ritz step amplifies the inconsistency (variational feedback), so
every `refresh_every` steps the carried H X / H P are recomputed with a true
application (chunked scan, still ~3x fewer H applies than re-applying to the
full 3nb subspace each step). The iteration count is static (config
iterative_solver.num_steps).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

# refresh cadence of the carried H X / H P blocks; scf.py's H-application
# counter derives from this, keep them in sync via this constant
REFRESH_EVERY = 5


def num_applies(num_steps: int, nb: int, refresh_every: int = REFRESH_EVERY) -> int:
    """H-applications (in band rows) of one davidson() call: nb at the first
    boundary (P still zero), 2nb at later chunk boundaries, nb per step for
    the new block, nb on exit."""
    nchunks = -(-num_steps // refresh_every)
    return nb * (num_steps + 2 * nchunks)


def residual_health(rnorm, blowup: float = 1e2) -> tuple[float, bool]:
    """(max residual norm, healthy?) of a band solve's exit residuals —
    the band-solve sentinel of the SCF supervisor (dft/recovery.py). A
    non-finite or blown-up residual means the solver stagnated or the
    subspace collapsed; the supervisor then retries with a deeper subspace
    or falls back to dense diagonalization."""
    import numpy as np

    r = np.asarray(rnorm, dtype=np.float64)
    if r.size == 0:
        return 0.0, True
    rmax = float(np.max(r)) if np.all(np.isfinite(r)) else float("inf")
    return rmax, np.isfinite(rmax) and rmax <= blowup


def _rayleigh_ritz(hsub: jax.Array, ssub: jax.Array, nev: int, big: float = 1e6):
    """Lowest-nev gen-EVP of a possibly rank-deficient subspace pair."""
    s, u = jnp.linalg.eigh(ssub)
    smax = jnp.max(jnp.abs(s))
    # rank cutoff must scale with the working precision: eigh noise sits at
    # ~eps*smax (1e-7 for c64), so a fixed 1e-13 would rsqrt-amplify noise
    # directions in single precision. The floor also bounds the rsqrt
    # amplification to ~3e5: directions barely above eps*smax get blended
    # with ~1e7 coefficients whose cancellation error feeds back through
    # the carried H X blocks and can blow the iteration up (observed with
    # exactly-degenerate Kramers pairs in the SO spinor solve)
    eps = jnp.finfo(ssub.real.dtype).eps
    good = s > jnp.maximum(50.0 * eps, 1e-11) * smax
    t = u * jnp.where(good, jax.lax.rsqrt(jnp.where(good, s, 1.0)), 0.0)[None, :]
    at = t.conj().T @ hsub @ t
    at = at + jnp.diag(jnp.where(good, 0.0, big).astype(at.dtype))
    e, y = jnp.linalg.eigh(at)
    c = t @ y
    return e[:nev], c[:, :nev]


def subspace_rotate(x, hx, sx, nb: int, mask=None):
    """Lowest-nb Ritz vectors of the trial block x given carried H x / S x:
    shared by the LCAO initialize-subspace paths (serial host and batched
    device); pure jnp, callable inside or outside jit."""
    hsub = x.conj() @ hx.T
    ssub = x.conj() @ sx.T
    hsub = 0.5 * (hsub + hsub.conj().T)
    ssub = 0.5 * (ssub + ssub.conj().T)
    _, c = _rayleigh_ritz(hsub, ssub, nb)
    xn = c.T @ x
    if mask is not None:
        xn = xn * mask
    nrm = jnp.real(jnp.sum(xn.conj() * (c.T @ sx), axis=1))
    return xn / jnp.sqrt(jnp.maximum(nrm, 1e-30))[:, None]


def _precondition(r: jax.Array, h_diag: jax.Array, o_diag: jax.Array, eval_: jax.Array):
    """Reference apply_preconditioner (residuals_aux.cu): smooth Teter-like."""
    p = h_diag[None, :] - eval_[:, None] * o_diag[None, :]
    p = 0.5 * (1.0 + p + jnp.sqrt(1.0 + (p - 1.0) ** 2))
    return r / p


@partial(jax.jit, static_argnames=("apply_fn", "num_steps", "refresh_every"))
def davidson(
    apply_fn,  # (params, psi [nb, ng]) -> (h psi, s psi); a STABLE module-
    # level function — closures would retrace the jit per call site
    params,  # pytree of per-k Hamiltonian data (ops.hamiltonian.HkParams)
    x0: jax.Array,  # [nb, ng] initial guess
    h_diag: jax.Array,  # [ng] H diagonal (preconditioner)
    o_diag: jax.Array,  # [ng] S diagonal
    mask: jax.Array,  # [ng] valid-G mask
    num_steps: int = 20,
    res_tol: float = 1e-6,
    refresh_every: int = REFRESH_EVERY,
):
    """Returns (eval [nb], X [nb, ng], res_norms [nb])."""
    nb = x0.shape[0]

    def apply_h_s(psi):
        return apply_fn(params, psi)

    def ortho(x):
        g = (x * mask) @ (x * mask).conj().T
        s, u = jnp.linalg.eigh(g)
        good = s > 50.0 * jnp.finfo(g.real.dtype).eps * jnp.max(jnp.abs(s))
        t = u * jnp.where(good, jax.lax.rsqrt(jnp.where(good, s, 1.0)), 0.0)[None, :]
        return t.conj().T @ x

    x = ortho(x0 * mask)

    def step(carry, _):
        x, hx, sx, p, hp, sp = carry
        # Ritz values of current block (H X, S X carried, no re-application).
        # Guard the quotient: a rank-deficient Rayleigh-Ritz (heavy Kramers
        # degeneracy + locking) can hand back a ~zero Ritz vector, and a
        # 0/0 here NaN-poisons the whole scan (observed: Au SO spinor solve)
        den = jnp.real(jnp.sum(x.conj() * sx, axis=1))
        evals = jnp.real(jnp.sum(x.conj() * hx, axis=1)) / jnp.where(
            jnp.abs(den) > 1e-30, den, 1.0
        )
        r = (hx - evals[:, None] * sx) * mask
        rnorm = jnp.sqrt(jnp.real(jnp.sum(jnp.abs(r) ** 2, axis=1)))
        conv = rnorm < res_tol
        w = jnp.where(conv[:, None], 0.0, _precondition(r, h_diag, o_diag, evals)) * mask
        # project out X and normalize rows: keeps the 3nb overlap matrix
        # well-conditioned so the rank-revealing cutoff doesn't stall
        # convergence near the solution
        w = w - (w @ x.conj().T) @ x
        w = w / jnp.maximum(jnp.linalg.norm(w, axis=1, keepdims=True), 1e-30)
        # the ONLY H/S application of the step: the new block.  The
        # named_scope blocks tag the emitted HLO so trace capture
        # (obs/trace.py) and XLA profiles attribute time to the same four
        # stage names obs/costs.py models — host spans cannot cut inside
        # this jit.
        with jax.named_scope("davidson_hpsi"):
            hw, sw = apply_h_s(w)
        v = jnp.concatenate([x, w, p], axis=0)  # (3nb, ng)
        hv = jnp.concatenate([hx, hw, hp], axis=0)
        sv = jnp.concatenate([sx, sw, sp], axis=0)
        with jax.named_scope("davidson_inner"):
            hsub = v.conj() @ hv.T
            ssub = v.conj() @ sv.T
            hsub = 0.5 * (hsub + hsub.conj().T)
            ssub = 0.5 * (ssub + ssub.conj().T)
        with jax.named_scope("davidson_rr"):
            e, c = _rayleigh_ritz(hsub, ssub, nb)
        with jax.named_scope("davidson_rotate"):
            # X' = V C and the carried H X' = (H V) C, S X' = (S V) C exactly
            xn = (c.T @ v) * mask
            hxn = (c.T @ hv) * mask
            sxn = (c.T @ sv) * mask
            # new search direction: the non-X part of the update
            # (row-normalized, with the same scale applied to the carried
            # H P / S P)
            cp = c.at[:nb, :].set(0.0)
            pn = (cp.T @ v) * mask
            pscale = 1.0 / jnp.maximum(
                jnp.linalg.norm(pn, axis=1, keepdims=True), 1e-30)
        return (xn, hxn, sxn, pn * pscale, (cp.T @ hv) * mask * pscale,
                (cp.T @ sv) * mask * pscale), rnorm

    z = jnp.zeros_like(x)
    p, hp, sp = z, z, z
    done = 0
    while done < num_steps:
        steps = min(refresh_every, num_steps - done)
        if done == 0:
            # P is exactly zero before the first chunk: only X needs applying
            with jax.named_scope("davidson_hpsi"):
                hx, sx = apply_h_s(x)
        else:
            # chunk-boundary refresh: true H/S application to [X; P]
            with jax.named_scope("davidson_hpsi"):
                hxp, sxp = apply_h_s(jnp.concatenate([x, p], axis=0))
            hx, sx = hxp[:nb], sxp[:nb]
            hp, sp = hxp[nb:], sxp[nb:]
        (x, hx, sx, p, hp, sp), rhist = jax.lax.scan(
            step, (x, hx, sx, p, hp, sp), None, length=steps
        )
        done += steps
    # fresh application for the exit values: the carried H X accumulates
    # linear-combination rounding (matters in c64)
    with jax.named_scope("davidson_hpsi"):
        hx, sx = apply_h_s(x)
    den = jnp.real(jnp.sum(x.conj() * sx, axis=1))
    evals = jnp.real(jnp.sum(x.conj() * hx, axis=1)) / jnp.where(
        jnp.abs(den) > 1e-30, den, 1.0
    )
    rnorm = jnp.sqrt(jnp.real(jnp.sum(jnp.abs(hx - evals[:, None] * sx) ** 2, axis=1)))
    # normalize to <x|S|x> = 1 (den floored: a zero Ritz vector must come
    # back as a zero row, not NaN/Inf)
    x = x / jnp.sqrt(jnp.maximum(den, 1e-30))[:, None]
    return evals, x, rnorm
