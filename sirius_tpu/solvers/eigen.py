"""Dense eigensolvers: generalized Hermitian EVP and exact plane-wave
diagonalization for verification (reference: Eigensolver_lapack
eigenproblem.hpp:39 and diagonalize_pp_exact / pseudopotential_hmatrix.hpp).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np


def eigh_gen(a: jax.Array, b: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Solve A z = e B z for Hermitian A, HPD B via Cholesky reduction
    (the reference's LAPACK hegvx path). Returns (e, z) with z B-orthonormal."""
    l = jnp.linalg.cholesky(b)
    linv = jax.scipy.linalg.solve_triangular(l, jnp.eye(l.shape[-1], dtype=l.dtype), lower=True)
    astd = linv @ a @ linv.conj().T
    e, y = jnp.linalg.eigh(astd)
    z = linv.conj().T @ y
    return e, z


def build_h_s_matrices(
    gkvec_ik: dict,
    veff_g_fine: np.ndarray,
    fine_index_of_miller,
    beta_k: np.ndarray | None = None,
    dion: np.ndarray | None = None,
    qmat: np.ndarray | None = None,
) -> tuple[np.ndarray, np.ndarray]:
    """Dense H, S in the |G+k| basis for one k-point (verification path).

    H_GG' = (|G+k|^2/2) delta + V_eff(G-G') + sum beta D beta^H
    S_GG' = delta + sum beta Q beta^H
    V_eff(G-G') is looked up in the fine G set via Miller differences.
    """
    mill = gkvec_ik["millers"]  # (ngk, 3) valid part only
    ekin = gkvec_ik["ekin"]
    ngk = len(mill)
    dm = mill[:, None, :] - mill[None, :, :]
    idx = fine_index_of_miller(dm.reshape(-1, 3)).reshape(ngk, ngk)
    if np.any(idx < 0):
        raise ValueError("fine G set does not contain all G-G' differences")
    h = veff_g_fine[idx].astype(np.complex128)
    h[np.arange(ngk), np.arange(ngk)] += ekin
    s = np.eye(ngk, dtype=np.complex128)
    if beta_k is not None and beta_k.shape[0]:
        b = beta_k[:, :ngk]  # (nbeta, ngk)
        h += b.conj().T @ dion @ b
        if qmat is not None:
            s += b.conj().T @ qmat @ b
    return h, s


def exact_diag(h: np.ndarray, s: np.ndarray | None, nev: int) -> tuple[np.ndarray, np.ndarray]:
    """Lowest nev eigenpairs of (H, S) via scipy (host-side verification)."""
    import scipy.linalg

    if s is None:
        e, v = scipy.linalg.eigh(h)
    else:
        e, v = scipy.linalg.eigh(h, s)
    return e[:nev], v[:, :nev]
