from sirius_tpu.solvers.eigen import eigh_gen, exact_diag
from sirius_tpu.solvers.davidson import davidson
