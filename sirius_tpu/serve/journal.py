"""Durable job journal: an append-only JSONL write-ahead log for the
serving engine.

Every accepted submission and every terminal transition is appended as
one JSON line and fsync'd before the engine acts on it, so the set of
jobs the engine owes an answer for survives ``kill -9``. On restart,
``replay()`` folds the log into the jobs that were submitted but never
reached a terminal state — exactly the ones a fresh ``ServeEngine``
must re-run (resuming from their job-scoped autosaves, which is why a
replayed job costs only the iterations since its last autosave, not a
full SCF).

Record kinds::

    {"kind": "submit",   "job_id", "deck", "base_dir", "priority",
     "deadline", "max_retries", "wall_time_budget", "tenant",
     "canon_hash", "ts",
     # campaign DAG edges (present only on campaign nodes): the journal
     # IS the durable copy of the graph — a SIGKILL mid-campaign replays
     # the edges, not just the jobs
     "campaign_id", "node_id", "parents", "handoff_in", "handoff_out"}
    {"kind": "terminal", "job_id", "status", "error", "permanent", "ts"}

Crash-safety contract:

- **Atomic appends.** A record is one ``write()`` of one newline-
  terminated line, flushed and ``os.fsync``'d before ``append`` returns.
  A crash leaves at most one torn (partial, newline-less) line at the
  tail — never an interleaved or half-overwritten record.
- **Torn-tail-tolerant replay.** ``replay`` skips unparseable lines
  (counting them) instead of failing: a torn ``submit`` means the engine
  never acknowledged the job; a torn ``terminal`` means the job re-runs —
  at-least-once semantics, which SCF resume makes cheap and idempotent.
- **Tail repair on reopen.** Opening a journal whose last line is torn
  first writes a lone ``\\n`` so the next append cannot glue onto the
  torn fragment and corrupt itself.

The ``serve.journal_torn`` fault site (utils/faults.py) tears a chosen
append mid-line — the ``iteration`` of the spec is the journal's append
sequence number — so tests and tools/chaos_serve.py can exercise the
replay contract without an actual crash inside ``write()``.
"""

from __future__ import annotations

import json
import os
import threading

from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs.log import get_logger
from sirius_tpu.utils import faults

logger = get_logger("serve")

_RECORDS = obs_metrics.REGISTRY.counter(
    "serve_journal_records_total", "journal appends by record kind")

TERMINAL_STATUSES = ("done", "failed", "aborted", "skipped_upstream")


class JobJournal:
    """Append-only fsync'd JSONL journal (one engine process at a time)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()
        self._appends = 0
        parent = os.path.dirname(os.path.abspath(self.path))
        os.makedirs(parent, exist_ok=True)
        self._repair_tail()
        self._fh = open(self.path, "a", encoding="utf-8")

    def _repair_tail(self) -> None:
        """Isolate a torn last line so future appends stay parseable."""
        try:
            with open(self.path, "rb") as fh:
                fh.seek(0, os.SEEK_END)
                if fh.tell() == 0:
                    return
                fh.seek(-1, os.SEEK_END)
                torn = fh.read(1) != b"\n"
            if torn:
                with open(self.path, "ab") as fh:
                    fh.write(b"\n")
                    fh.flush()
                    os.fsync(fh.fileno())
        except FileNotFoundError:
            return

    def append(self, rec: dict) -> None:
        line = json.dumps(rec, default=float)
        with self._lock:
            if self._fh is None:
                # a late terminal hook (watcher promotion settling, a
                # fleet lease released after shutdown) must not crash on
                # the closed handle; dropping the record is safe — an
                # unrecorded terminal means the job replays, and
                # at-least-once is the journal's contract
                logger.warning("journal closed; dropping %s record for %s",
                               rec.get("kind"), rec.get("job_id"))
                return
            seq = self._appends
            self._appends += 1
            if faults.armed("serve.journal_torn", seq):
                # the on-disk state a crash inside write() leaves: a
                # partial line, no newline, nothing durably synced
                self._fh.write(line[: max(1, len(line) // 2)])
                self._fh.flush()
                return
            self._fh.write(line + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())
        _RECORDS.inc(kind=rec.get("kind", "unknown"))

    def record_submit(self, job) -> None:
        rec = {
            "kind": "submit",
            "job_id": job.id,
            "deck": job.deck,
            "base_dir": job.base_dir,
            "priority": job.priority,
            "deadline": job.deadline,
            "max_retries": job.max_retries,
            "wall_time_budget": job.wall_time_budget,
            "trace_id": getattr(job, "trace_id", None),
            "tenant": getattr(job, "tenant", None),
            "canon_hash": getattr(job, "canon_hash", None),
            "ts": job.submitted_at,
        }
        if getattr(job, "campaign_id", None) or getattr(job, "parents", None):
            rec.update(
                campaign_id=job.campaign_id,
                node_id=job.node_id,
                parents=list(job.parents),
                handoff_in=job.handoff_in,
                handoff_out=job.handoff_out,
            )
        self.append(rec)

    def record_terminal(self, job) -> None:
        self.append({
            "kind": "terminal",
            "job_id": job.id,
            "status": job.status,
            "error": job.error,
            "permanent": job.permanent,
            "ts": job.finished_at,
        })

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._fh.close()
                self._fh = None


def replay(path: str) -> tuple[list[dict], dict]:
    """Fold a journal into its non-terminal submissions.

    Returns ``(pending, stats)``: ``pending`` is the submit records (in
    original submit order, duplicates collapsed to the newest) that have
    no terminal record after them; ``stats`` counts what was seen and
    maps each terminally-settled job to its final status in
    ``stats["terminal_status"]`` (how a replayed campaign child resolves
    parents that finished in a previous process). Never raises on a
    torn/garbled line — those are counted in ``stats["torn_lines"]`` and
    skipped.
    """
    pending: dict[str, dict] = {}
    stats = {"submits": 0, "terminals": 0, "torn_lines": 0,
             "terminal_status": {}}
    if not os.path.exists(path):
        return [], stats
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                stats["torn_lines"] += 1
                continue
            kind = rec.get("kind")
            job_id = rec.get("job_id")
            if not job_id:
                stats["torn_lines"] += 1
                continue
            if kind == "submit":
                stats["submits"] += 1
                pending[job_id] = rec
                # a resubmitted id supersedes its earlier terminal record
                stats["terminal_status"].pop(job_id, None)
            elif kind == "terminal":
                stats["terminals"] += 1
                pending.pop(job_id, None)
                stats["terminal_status"][job_id] = rec.get("status")
    out = list(pending.values())
    if out:
        obs_events.emit(
            "journal_replay", path=str(path),
            pending=[r["job_id"] for r in out],
            **{k: v for k, v in stats.items() if k != "terminal_status"})
    return out, stats
