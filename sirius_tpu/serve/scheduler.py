"""Device-slice scheduler: concurrent SCF jobs over a partitioned mesh.

The global device list is split into ``num_slices`` contiguous slices;
one worker thread drains the queue per slice (thread-per-slice — XLA
execution releases the GIL, so slices genuinely overlap on CPU tests and
would on real accelerators). Each job runs through the normal run_scf
machinery — ScfSupervisor ladder, control.autosave_every checkpoints —
with a job-scoped autosave path, so a failed or preempted job is retried
and *resumed* from its newest valid autosave rather than restarted.

Failure classification:
  transient  -> requeue (up to job.max_retries), resuming from autosave:
               SimulatedKill (injected preemption), ScfAbortError
               (supervisor ladder exhausted — a rollback snapshot may
               still converge from the autosave), CheckpointError (bad
               autosave: the resume path is cleared first), OSError.
  permanent  -> failed, never retried: UpfParseError and other
               ValueError/NotImplementedError/KeyError deck problems —
               re-running bad input cannot succeed.
"""

from __future__ import annotations

import logging
import os
import threading

import numpy as np

from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs import spans as obs_spans
from sirius_tpu.obs.log import get_logger, job_context
from sirius_tpu.serve import cache as cache_mod
from sirius_tpu.serve.queue import Job, JobQueue, JobStatus
from sirius_tpu.utils.profiler import counters

logger = get_logger("serve")

_RUN_SECONDS = obs_metrics.REGISTRY.histogram(
    "serve_job_run_seconds", "per-attempt SCF wall time by bucket warmth")
_RETRIES = obs_metrics.REGISTRY.counter(
    "serve_job_retries_total", "transient-failure retries")
_FAILURES = obs_metrics.REGISTRY.counter(
    "serve_job_failures_total", "terminal job failures")

# SimulationContext building for synthetic decks monkeypatches
# UnitCell.from_config (testing.py idiom); serialize every context build
# so concurrent workers never see each other's patch
_CTX_LOCK = threading.Lock()


def build_job_context(cfg, base_dir: str = "."):
    """SimulationContext for a deck Config.

    A ``synthetic`` extra section ({"ultrasoft": bool, "positions": [...],
    "supercell": n, "a": lattice const}) builds the in-memory Si-like test
    species instead of reading species files — the species-file-free deck
    form used by tests and tools/loadgen.py. Everything else (cutoffs,
    k-mesh, control knobs incl. ngk_pad_quantum) comes from the normal
    config sections.
    """
    from sirius_tpu.context import SimulationContext

    syn = cfg.extra.get("synthetic") if isinstance(cfg.extra, dict) else None
    if not syn:
        with _CTX_LOCK:
            return SimulationContext.create(cfg, base_dir)

    import sirius_tpu.crystal.unit_cell as ucm
    from sirius_tpu.testing import synthetic_silicon_type

    a = float(syn.get("a", 10.26))
    lattice = a / 2 * np.array([[0.0, 1, 1], [1, 0, 1], [1, 1, 0]])
    t = synthetic_silicon_type(ultrasoft=bool(syn.get("ultrasoft", True)))
    positions = np.asarray(
        syn.get("positions", [[0.0, 0, 0], [0.25, 0.25, 0.25]]),
        dtype=np.float64,
    )
    n = int(syn.get("supercell", 1))
    if n > 1:
        shifts = np.array(
            [[i, j, k]
             for i in range(n) for j in range(n) for k in range(n)],
            dtype=np.float64,
        )
        positions = (
            (positions[None, :, :] + shifts[:, None, :]) / n
        ).reshape(-1, 3)
        lattice = lattice * n
    uc = ucm.UnitCell(
        lattice=lattice,
        atom_types=[t],
        type_of_atom=np.zeros(len(positions), dtype=np.int32),
        positions=positions,
        moments=np.zeros((len(positions), 3)),
    )
    with _CTX_LOCK:
        orig = ucm.UnitCell.from_config
        try:
            ucm.UnitCell.from_config = staticmethod(lambda c, b=".": uc)
            return SimulationContext.create(cfg, base_dir)
        finally:
            ucm.UnitCell.from_config = orig


class SliceScheduler:
    """Partition ``devices`` into ``num_slices`` and drain ``queue``."""

    def __init__(self, queue: JobQueue, exec_cache, num_slices: int = 1,
                 devices=None, autosave_every: int = 3,
                 autosave_keep: int = 2, verbose: bool = False):
        import jax

        self.queue = queue
        self.cache = exec_cache
        devices = list(devices) if devices is not None else jax.devices()
        num_slices = max(1, min(int(num_slices), len(devices)))
        per = len(devices) // num_slices
        self.slices = [
            devices[i * per:(i + 1) * per] for i in range(num_slices)
        ]
        # leftover devices join the last slice rather than idling
        self.slices[-1].extend(devices[num_slices * per:])
        self.autosave_every = int(autosave_every)
        self.autosave_keep = int(autosave_keep)
        self.verbose = verbose
        self._threads: list[threading.Thread] = []

    def start(self) -> None:
        for i, devs in enumerate(self.slices):
            t = threading.Thread(
                target=self._worker, args=(i, devs),
                name=f"serve-slice-{i}", daemon=True,
            )
            t.start()
            self._threads.append(t)

    def join(self, timeout: float | None = None) -> None:
        for t in self._threads:
            t.join(timeout)

    def _worker(self, idx: int, devs) -> None:
        while True:
            job = self.queue.pop(timeout=0.5)
            if job is None:
                if self.queue._closed:
                    return
                continue
            self._run_job(job, idx, devs)

    def _run_job(self, job: Job, slice_idx: int, devs) -> None:
        job.attempts += 1
        # every log line and obs event inside the attempt carries job.id
        with job_context(job.id):
            self._run_job_inner(job, slice_idx, devs)

    def _run_job_inner(self, job: Job, slice_idx: int, devs) -> None:
        import time as _time

        import jax

        from sirius_tpu.config.schema import load_config
        from sirius_tpu.dft.recovery import ScfAbortError
        from sirius_tpu.dft.scf import run_scf
        from sirius_tpu.io.checkpoint import CheckpointError
        from sirius_tpu.io.upf import UpfParseError
        from sirius_tpu.utils.faults import SimulatedKill

        cfg = None
        try:
            cfg = load_config(dict(job.deck))
            # serve defaults: job-scoped autosaves with rotation so every
            # job is resumable and none clobbers a neighbour's checkpoint
            if not cfg.control.autosave_tag and not cfg.control.autosave_path:
                cfg.control.autosave_tag = job.id
            if not cfg.control.autosave_every:
                cfg.control.autosave_every = self.autosave_every
            if not cfg.control.autosave_keep:
                cfg.control.autosave_keep = self.autosave_keep
            ctx = build_job_context(cfg, job.base_dir)
            key = cache_mod.bucket_key(cfg, ctx)
            warm = self.cache.note_job(key)
            job._transition(
                JobStatus.RUNNING if warm else JobStatus.COMPILING,
                f"slice {slice_idx}, bucket {'warm' if warm else 'cold'}",
            )
            if job.started_at is None:
                job.started_at = job.events[-1][0]
            if job.submitted_at is not None:
                # externally-timed span: submit -> this worker popping it
                obs_spans.record(
                    "serve.queue_wait",
                    max(0.0, _time.time() - job.submitted_at),
                    t0=job.submitted_at, slice=slice_idx,
                    bucket="warm" if warm else "cold")
            compiles0 = cache_mod.backend_compiles_this_thread()
            csec0 = obs_metrics.backend_compile_seconds_this_thread()
            t_run0 = _time.time()
            with obs_spans.span("serve.run", slice=slice_idx,
                                bucket="warm" if warm else "cold"):
                with jax.default_device(devs[0]):
                    result = run_scf(
                        cfg, base_dir=job.base_dir, ctx=ctx,
                        exec_cache=self.cache, devices=devs,
                        resume=job.resume_path,
                    )
            _RUN_SECONDS.observe(_time.time() - t_run0,
                                 bucket="warm" if warm else "cold",
                                 slice=slice_idx)
            compiled = cache_mod.backend_compiles_this_thread() - compiles0
            # compile time attributed via the jax.monitoring listener's
            # per-thread accumulator: run_scf happened on THIS thread, so
            # the delta is exactly this job's XLA backend-compile seconds
            csec = obs_metrics.backend_compile_seconds_this_thread() - csec0
            if compiled or csec:
                obs_spans.record("serve.compile", csec, slice=slice_idx,
                                 compiled_executables=compiled)
            counters["serve.backend_compiles"] += compiled
            result["serve"] = {
                "job_id": job.id,
                "slice": slice_idx,
                "attempts": job.attempts,
                "bucket_warm": warm,
                "compiled_executables": compiled,
            }
            job.result = result
            job._transition(
                JobStatus.DONE,
                f"E={result['energy']['total']:.10f} "
                f"compiled={compiled}",
            )
        except SimulatedKill as e:
            self._retry(job, cfg, f"preempted: {e}")
        except CheckpointError as e:
            # the autosave we tried to resume from is unusable: retry from
            # scratch rather than looping on the same bad file
            job.resume_path = None
            self._retry(job, cfg, f"bad checkpoint: {e}", resume=False)
        except UpfParseError as e:
            self._fail(job, f"UPF parse error: {e}", permanent=True)
        except (ValueError, NotImplementedError, KeyError) as e:
            self._fail(job, f"bad deck: {type(e).__name__}: {e}",
                       permanent=True)
        except ScfAbortError as e:
            self._retry(job, cfg, f"scf aborted: {e}")
        except OSError as e:
            self._retry(job, cfg, f"io error: {e}")
        except Exception as e:  # a serving worker must outlive any job
            self._fail(job, f"unexpected {type(e).__name__}: {e}",
                       permanent=True)

    def _retry(self, job: Job, cfg, detail: str, resume: bool = True) -> None:
        from sirius_tpu.dft.scf import default_autosave_path
        from sirius_tpu.io.checkpoint import find_resumable

        counters["serve.retries"] += 1
        _RETRIES.inc(job_id=job.id)
        if job.attempts > job.max_retries:
            self._fail(job, f"{detail} (retries exhausted)")
            return
        if resume and cfg is not None:
            auto = cfg.control.autosave_path or default_autosave_path(
                cfg, job.base_dir)
            job.resume_path = find_resumable(
                auto, keep=int(cfg.control.autosave_keep))
        logger.log(
            logging.INFO if self.verbose else logging.DEBUG,
            "retrying %s: %s (resume=%s)", job.id, detail, job.resume_path)
        self.queue.requeue(job, detail)

    def _fail(self, job: Job, detail: str, permanent: bool = False) -> None:
        job.error = detail
        job.permanent = permanent
        counters["serve.failures"] += 1
        _FAILURES.inc(permanent=str(permanent).lower())
        logger.info("job %s failed: %s", job.id, detail)
        job._transition(JobStatus.FAILED, detail)

    def cleanup_autosaves(self, jobs) -> None:
        """Remove job-scoped autosave generations of terminal jobs."""
        for job in jobs:
            tag = job.id
            base = os.path.join(job.base_dir, f"sirius_autosave.{tag}.h5")
            for p in [base] + [f"{base}.{i}" for i in range(1, 10)]:
                if os.path.exists(p):
                    try:
                        os.remove(p)
                    except OSError:
                        pass
