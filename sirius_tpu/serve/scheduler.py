"""Device-slice scheduler: concurrent SCF jobs over a partitioned mesh.

The global device list is split into ``num_slices`` contiguous slices;
one worker thread drains the queue per slice (thread-per-slice — XLA
execution releases the GIL, so slices genuinely overlap on CPU tests and
would on real accelerators). Each job runs through the normal run_scf
machinery — ScfSupervisor ladder, control.autosave_every checkpoints —
with a job-scoped autosave path, so a failed or preempted job is retried
and *resumed* from its newest valid autosave rather than restarted.

Failure classification:
  transient  -> requeue (up to job.max_retries) with exponential backoff
               (``job.not_before``, jittered, never past the deadline),
               resuming from autosave: SimulatedKill (injected
               preemption, class ``preempted``), ScfAbortError
               (supervisor ladder exhausted — a rollback snapshot may
               still converge from the autosave, class ``scf_abort``),
               CheckpointError (bad autosave: the resume path is cleared
               first, class ``bad_checkpoint``), OSError (class ``io``),
               plus watchdog hand-backs (class ``crash``/``hang``).
  device     -> backend errors classified by the utils/devfail.py
               taxonomy instead of falling into the permanent catch-all:
               ``oom`` retries with a degradation hint (the next attempt
               runs on a smaller memory plan — apply_oom_hint),
               ``device_lost`` shrinks the slice to its surviving
               devices and resumes from autosave on the smaller mesh,
               ``straggler`` (StragglerPreempt from run_scf's watchdog)
               parks the slice behind a cooldown so the retry lands on
               healthy hardware, ``transient`` plain-retries. All are
               preemption semantics: device evidence is against the
               hardware, never a poison strike against the deck.
  permanent  -> failed, never retried: UpfParseError and other
               ValueError/NotImplementedError/KeyError deck problems —
               re-running bad input cannot succeed — unclassifiable
               unexpected exceptions, and poison quarantine
               (serve/supervisor.py).

Workers are supervised (serve/supervisor.py): they heartbeat every poll
cycle, register the job they run, and are respawned by the watchdog when
they die or hang. Each attempt captures ``job._epoch`` at pickup; a
worker whose job was taken away by the watchdog discards its outcome
instead of clobbering the job's new life.

Campaign nodes (sirius_tpu.campaigns) ride the same path with three
extra steps: a ``handoff_in`` artifact is loaded into
``run_scf(initial_guess=)`` (degrading to a cold start on damage or
shape mismatch — campaigns/handoff.py), a top-level ``task: "relax"``
deck key dispatches dft/relax.py instead of a single SCF, and on DONE a
``handoff_out`` artifact is written *before* the terminal transition so
the journal's DONE record always implies a durable artifact for the
children. The ``campaign.node_fail`` fault site preempts a node attempt
before its SCF to drive the SKIPPED_UPSTREAM cascade in tests.
"""

from __future__ import annotations

import logging
import os
import random
import threading
import time

import numpy as np

from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs import spans as obs_spans
from sirius_tpu.obs import tracing as obs_tracing
from sirius_tpu.obs.log import get_logger, job_context
from sirius_tpu.serve import cache as cache_mod
from sirius_tpu.serve.queue import Job, JobQueue, JobStatus
from sirius_tpu.serve.supervisor import SliceSupervisor
from sirius_tpu.utils import devfail
from sirius_tpu.utils import faults
from sirius_tpu.utils.profiler import counters

logger = get_logger("serve")

_RUN_SECONDS = obs_metrics.REGISTRY.histogram(
    "serve_job_run_seconds", "per-attempt SCF wall time by bucket warmth")
_RETRIES = obs_metrics.REGISTRY.counter(
    "serve_job_retries_total", "transient-failure retries by failure class")
_FAILURES = obs_metrics.REGISTRY.counter(
    "serve_job_failures_total", "terminal job failures")
_BACKOFF = obs_metrics.REGISTRY.histogram(
    "serve_backoff_seconds", "retry backoff delays by failure class")
_NODE_ITERS = obs_metrics.REGISTRY.counter(
    "campaign_node_scf_iterations_total",
    "SCF iterations spent on campaign nodes, by warm/cold handoff")
# same family run_scf updates mid-run (dft/scf.py); serve re-publishes the
# terminal forecast per slice so dashboards see it after the job finishes
_FORECAST_ITERS = obs_metrics.REGISTRY.gauge(
    "scf_forecast_iterations",
    "forecasted total SCF iterations to convergence (obs/forecast.py)")

# SimulationContext building for synthetic decks monkeypatches
# UnitCell.from_config (testing.py idiom); serialize every context build
# so concurrent workers never see each other's patch
_CTX_LOCK = threading.Lock()


def build_job_context(cfg, base_dir: str = "."):
    """SimulationContext for a deck Config.

    A ``synthetic`` extra section ({"ultrasoft": bool, "positions": [...],
    "supercell": n, "a": lattice const}) builds the in-memory Si-like test
    species instead of reading species files — the species-file-free deck
    form used by tests and tools/loadgen.py. Everything else (cutoffs,
    k-mesh, control knobs incl. ngk_pad_quantum) comes from the normal
    config sections.
    """
    from sirius_tpu.context import SimulationContext

    syn = cfg.extra.get("synthetic") if isinstance(cfg.extra, dict) else None
    if not syn:
        with _CTX_LOCK:
            return SimulationContext.create(cfg, base_dir)

    import sirius_tpu.crystal.unit_cell as ucm
    from sirius_tpu.testing import synthetic_silicon_type

    a = float(syn.get("a", 10.26))
    lattice = a / 2 * np.array([[0.0, 1, 1], [1, 0, 1], [1, 1, 0]])
    t = synthetic_silicon_type(ultrasoft=bool(syn.get("ultrasoft", True)))
    positions = np.asarray(
        syn.get("positions", [[0.0, 0, 0], [0.25, 0.25, 0.25]]),
        dtype=np.float64,
    )
    n = int(syn.get("supercell", 1))
    if n > 1:
        shifts = np.array(
            [[i, j, k]
             for i in range(n) for j in range(n) for k in range(n)],
            dtype=np.float64,
        )
        positions = (
            (positions[None, :, :] + shifts[:, None, :]) / n
        ).reshape(-1, 3)
        lattice = lattice * n
    uc = ucm.UnitCell(
        lattice=lattice,
        atom_types=[t],
        type_of_atom=np.zeros(len(positions), dtype=np.int32),
        positions=positions,
        moments=np.zeros((len(positions), 3)),
    )
    with _CTX_LOCK:
        orig = ucm.UnitCell.from_config
        try:
            ucm.UnitCell.from_config = staticmethod(lambda c, b=".": uc)
            return SimulationContext.create(cfg, base_dir)
        finally:
            ucm.UnitCell.from_config = orig


class SliceScheduler:
    """Partition ``devices`` into ``num_slices`` and drain ``queue``."""

    def __init__(self, queue: JobQueue, exec_cache, num_slices: int = 1,
                 devices=None, autosave_every: int = 3,
                 autosave_keep: int = 2, verbose: bool = False,
                 poison_threshold: int = 2,
                 job_wall_time_budget: float | None = None,
                 watchdog_interval: float = 0.25,
                 backoff_base: float = 0.5, backoff_max: float = 30.0,
                 backoff_jitter: float = 0.1,
                 straggler_cooldown: float = 5.0):
        import jax

        self.queue = queue
        self.cache = exec_cache
        devices = list(devices) if devices is not None else jax.devices()
        num_slices = max(1, min(int(num_slices), len(devices)))
        per = len(devices) // num_slices
        self.slices = [
            devices[i * per:(i + 1) * per] for i in range(num_slices)
        ]
        # leftover devices join the last slice rather than idling
        self.slices[-1].extend(devices[num_slices * per:])
        self.autosave_every = int(autosave_every)
        self.autosave_keep = int(autosave_keep)
        self.verbose = verbose
        self.backoff_base = float(backoff_base)
        self.backoff_max = float(backoff_max)
        self.backoff_jitter = float(backoff_jitter)
        self.straggler_cooldown = float(straggler_cooldown)
        self.supervisor = SliceSupervisor(
            self, poison_threshold=poison_threshold,
            job_wall_time_budget=job_wall_time_budget,
            interval=watchdog_interval,
        )

    def start(self) -> None:
        self.supervisor.start()

    def join(self, timeout: float | None = None) -> None:
        self.supervisor.join(timeout)

    def stop_supervision(self) -> None:
        self.supervisor.stop()

    def _worker(self, idx: int, devs) -> None:
        sup = self.supervisor
        while True:
            sup.beat(idx)
            if not sup.slice_available(idx):
                # degradation cooldown (straggler): leave queued work to
                # the healthy slices until the deadline passes
                if self.queue.closed and len(self.queue) == 0:
                    return
                time.sleep(0.05)
                continue
            job = self.queue.pop(timeout=0.5)
            if job is None:
                if self.queue.closed and len(self.queue) == 0:
                    return
                continue
            epoch = job._epoch
            sup.note_job(idx, job, epoch)
            # a WorkerCrash (or any other BaseException) propagates past
            # note_idle: the thread dies with the job still registered,
            # which is exactly what the watchdog recovers from
            self._run_job(job, idx, devs, epoch)
            sup.note_idle(idx, job)

    def _run_job(self, job: Job, slice_idx: int, devs, epoch: int) -> None:
        job.attempts += 1
        if job.trace_id is None and job.handoff_in:
            # a job joining a DAG without engine.submit's assignment:
            # continue the trace stored in the parent's handoff artifact
            from sirius_tpu.campaigns import handoff as handoff_mod

            job.trace_id = handoff_mod.artifact_trace_id(
                job.handoff_in.get("path"))
        if job.trace_id is None:
            # direct queue users bypass engine.submit; give the job a
            # trace here so every attempt still has end-to-end identity
            job.trace_id = obs_tracing.new_trace_id()
        # every log line and obs event inside the attempt carries job.id,
        # and every span/event/exemplar the job's trace_id — across
        # worker threads, retries, and (via the journal) process restarts
        with obs_tracing.trace_context(job.trace_id), job_context(job.id):
            if faults.armed("serve.worker_crash", job.attempts - 1):
                raise faults.WorkerCrash(
                    f"fault serve.worker_crash (job {job.id} "
                    f"attempt {job.attempts})")
            if faults.armed("serve.job_hang", job.attempts - 1):
                self._hang(job, slice_idx, epoch)
                return
            self._run_job_inner(job, slice_idx, devs, epoch)

    def _hang(self, job: Job, slice_idx: int, epoch: int) -> None:
        """Simulate a wedged worker (serve.job_hang): park until the
        watchdog abandons the job (epoch bump) — never transition it."""
        job._transition(JobStatus.RUNNING, f"slice {slice_idx} (hung)")
        t0 = time.time()
        while job._epoch == epoch and time.time() - t0 < 120.0:
            time.sleep(0.02)
        logger.info("hung attempt of job %s unparked (%s)", job.id,
                    "abandoned" if job._epoch != epoch else "timed out")

    def _stale(self, job: Job, epoch: int) -> bool:
        """True when the watchdog took this job away mid-attempt: the
        outcome of the attempt must be discarded, not applied."""
        if job._epoch != epoch:
            logger.warning("discarding stale attempt outcome for job %s "
                           "(abandoned by the watchdog)", job.id)
            return True
        return False

    def _load_handoff(self, job: Job, ctx):
        """Load the parent artifact named by ``job.handoff_in`` into an
        ``initial_guess`` for run_scf.

        Degrades rather than fails: a missing/partial artifact or one
        whose shapes don't match this node's context gives a cold start
        (mode ``"missing"``/``"cold"``); a corrupt one (non-finite
        payload, campaign.handoff_corrupt fault site) is dropped with
        mode ``"corrupt_fallback"``. Only a usable (rho, psi) pair
        reaches run_scf, so the ValueError shape guard there — a
        permanent-failure class — can never fire on handoff data."""
        from sirius_tpu.campaigns import handoff as handoff_mod

        path = job.handoff_in.get("path")
        displaced = bool(job.handoff_in.get("displaced", True))
        guess = None
        try:
            guess = handoff_mod.load_guess(path, ctx, displaced=displaced)
            mode = "warm" if guess is not None else "missing"
        except handoff_mod.HandoffError as e:
            logger.warning("job %s: corrupt handoff artifact %s (%s); "
                           "falling back to a cold start", job.id, path, e)
            mode = "corrupt_fallback"
        obs_events.emit("campaign_handoff", job_id=job.id,
                        campaign_id=job.campaign_id, node_id=job.node_id,
                        mode=mode, displaced=displaced)
        return guess, mode

    def _run_job_inner(self, job: Job, slice_idx: int, devs,
                       epoch: int) -> None:
        import time as _time

        import jax

        from sirius_tpu.config.schema import load_config
        from sirius_tpu.dft.recovery import ScfAbortError
        from sirius_tpu.dft.scf import run_scf
        from sirius_tpu.io.checkpoint import CheckpointError
        from sirius_tpu.io.upf import UpfParseError
        from sirius_tpu.utils.devfail import StragglerPreempt
        from sirius_tpu.utils.faults import SimulatedKill

        cfg = None
        try:
            if job.campaign_id:
                # test/chaos hook: preempt a campaign node attempt before
                # any SCF work (retries, then SKIPPED_UPSTREAM cascade)
                faults.check("campaign.node_fail", job.attempts - 1)
            deck = dict(job.deck)
            task = deck.get("task") or "scf"
            if job.handoff_in and job.handoff_in.get("adopt_positions"):
                from sirius_tpu.campaigns import handoff as handoff_mod

                # run at the geometry the parent settled on (relax->SCF
                # chains); a missing artifact raises OSError = retryable
                deck = handoff_mod.adopt_positions(
                    deck, job.handoff_in["path"])
            cfg = load_config(deck)
            job._cfg = cfg  # watchdog retries refresh the resume path
            # serve defaults: job-scoped autosaves with rotation so every
            # job is resumable and none clobbers a neighbour's checkpoint
            if not cfg.control.autosave_tag and not cfg.control.autosave_path:
                cfg.control.autosave_tag = job.id
            if not cfg.control.autosave_every:
                cfg.control.autosave_every = self.autosave_every
            if not cfg.control.autosave_keep:
                cfg.control.autosave_keep = self.autosave_keep
            if job.deadline is not None and not cfg.control.deadline_ts:
                # forecast-driven deadline triage: run_scf emits
                # deadline_feasibility events against this bound as its
                # iterations-to-converge forecast evolves (obs/forecast.py)
                cfg.control.deadline_ts = float(job.deadline)
            if cfg.control.straggler_detect == "auto":
                # straggler watchdog on by default under serve only: a
                # slow slice preempts the run at a snapshot boundary and
                # the retry resumes on healthy hardware (dft/scf.py)
                cfg.control.straggler_detect = True
            if job.oom_degrade:
                # a previous attempt died of HBM exhaustion below the
                # in-run ladder's reach: start this one pre-degraded
                applied = devfail.apply_oom_hint(
                    cfg.control, job.oom_degrade)
                logger.warning(
                    "job %s retrying at OOM degradation level %d: %s",
                    job.id, job.oom_degrade, ",".join(applied))
            ctx = build_job_context(cfg, job.base_dir)
            key = cache_mod.bucket_key(cfg, ctx)
            warm = self.cache.note_job(key)
            job._transition(
                JobStatus.RUNNING if warm else JobStatus.COMPILING,
                f"slice {slice_idx}, bucket {'warm' if warm else 'cold'}",
            )
            if job.started_at is None:
                job.started_at = job.events[-1][0]
            if job.submitted_at is not None:
                # externally-timed span: submit -> this worker popping it
                obs_spans.record(
                    "serve.queue_wait",
                    max(0.0, _time.time() - job.submitted_at),
                    t0=job.submitted_at, slice=slice_idx,
                    bucket="warm" if warm else "cold")
            guess = None
            handoff_mode = None
            if job.handoff_in:
                guess, handoff_mode = self._load_handoff(job, ctx)
            keep_state = bool(job.handoff_out)
            compiles0 = cache_mod.backend_compiles_this_thread()
            csec0 = obs_metrics.backend_compile_seconds_this_thread()
            t_run0 = _time.time()
            final_positions = None
            with obs_spans.span("serve.run", slice=slice_idx,
                                bucket="warm" if warm else "cold"):
                with jax.default_device(devs[0]):
                    if task == "relax":
                        from sirius_tpu.dft.relax import relax_atoms

                        relax_args = (
                            deck.get("relax")
                            if isinstance(deck.get("relax"), dict) else {})
                        rr = relax_atoms(
                            cfg, base_dir=job.base_dir,
                            max_steps=int(relax_args.get("max_steps", 30)),
                            force_tol=float(
                                relax_args.get("force_tol", 1e-4)),
                            ctx=ctx, exec_cache=self.cache, devices=devs,
                        )
                        gs = rr["ground_state"]
                        final_positions = rr["final_positions"]
                        result = {
                            "task": "relax",
                            "converged": rr["converged"],
                            "energy": gs["energy"],
                            "num_scf_iterations": sum(
                                h["scf_iterations"] for h in rr["history"]),
                            "forces": gs.get("forces"),
                            "_state": gs.get("_state"),
                            "relax": {
                                k: rr[k] for k in (
                                    "converged", "num_steps", "history",
                                    "final_positions")
                            },
                        }
                    else:
                        result = run_scf(
                            cfg, base_dir=job.base_dir, ctx=ctx,
                            exec_cache=self.cache, devices=devs,
                            resume=job.resume_path,
                            initial_guess=guess, keep_state=keep_state,
                        )
            _RUN_SECONDS.observe(_time.time() - t_run0,
                                 bucket="warm" if warm else "cold",
                                 slice=slice_idx)
            compiled = cache_mod.backend_compiles_this_thread() - compiles0
            # compile time attributed via the jax.monitoring listener's
            # per-thread accumulator: run_scf happened on THIS thread, so
            # the delta is exactly this job's XLA backend-compile seconds
            csec = obs_metrics.backend_compile_seconds_this_thread() - csec0
            if compiled or csec:
                obs_spans.record("serve.compile", csec, slice=slice_idx,
                                 compiled_executables=compiled)
            counters["serve.backend_compiles"] += compiled
            state = result.pop("_state", None)
            result["serve"] = {
                "job_id": job.id,
                "slice": slice_idx,
                "attempts": job.attempts,
                "bucket_warm": warm,
                "compiled_executables": compiled,
                "warm_start": guess is not None,
                "handoff": handoff_mode,
                "forecast": result.get("forecast"),
            }
            _fc = result.get("forecast") or {}
            if _fc.get("forecast_total") is not None:
                _FORECAST_ITERS.set(float(_fc["forecast_total"]),
                                    slice=str(slice_idx))
            if self._stale(job, epoch):
                return
            if job.handoff_out:
                from sirius_tpu.campaigns import handoff as handoff_mod

                # artifact before the terminal transition: a journaled
                # DONE record must imply a durable artifact, or a replay
                # could skip a node whose children have nothing to load
                handoff_mod.save_artifact(
                    job.handoff_out, ctx, result, state,
                    positions=final_positions)
            if job.campaign_id:
                _NODE_ITERS.inc(
                    int(result.get("num_scf_iterations") or 0),
                    warm="true" if guess is not None else "false")
            job.result = result
            job._transition(
                JobStatus.DONE,
                f"E={result['energy']['total']:.10f} "
                f"compiled={compiled}",
            )
        except StragglerPreempt as e:
            # before SimulatedKill: StragglerPreempt subclasses it. The
            # slice, not the deck, is slow — park it behind a cooldown so
            # the retry lands on healthy hardware; never a strike.
            if self._stale(job, epoch):
                return
            self.supervisor.degrade_slice(
                slice_idx, "straggler", cooldown=self.straggler_cooldown)
            self._retry(job, cfg, f"straggler preempt: {e}", "straggler")
        except SimulatedKill as e:
            if self._stale(job, epoch):
                return
            self._retry(job, cfg, f"preempted: {e}", "preempted")
        except CheckpointError as e:
            if self._stale(job, epoch):
                return
            # the autosave we tried to resume from is unusable: retry from
            # scratch rather than looping on the same bad file
            job.resume_path = None
            self._retry(job, cfg, f"bad checkpoint: {e}", "bad_checkpoint",
                        resume=False)
        except UpfParseError as e:
            if self._stale(job, epoch):
                return
            self._fail(job, f"UPF parse error: {e}", permanent=True)
        except (ValueError, NotImplementedError, KeyError) as e:
            if self._stale(job, epoch):
                return
            self._fail(job, f"bad deck: {type(e).__name__}: {e}",
                       permanent=True)
        except ScfAbortError as e:
            if self._stale(job, epoch):
                return
            if e.diagnostic.get("sentinel") == "device_oom":
                # the in-run OOM ladder ran out of rungs: retry under the
                # ``oom`` class with the same rungs pre-applied, so the
                # next attempt starts on the smaller memory plan instead
                # of re-climbing the ladder from scratch
                job.oom_degrade = min(job.oom_degrade + 1, 3)
                self._retry(job, cfg, f"scf aborted on device OOM: {e}",
                            "oom")
            else:
                self._retry(job, cfg, f"scf aborted: {e}", "scf_abort")
        except OSError as e:
            if self._stale(job, epoch):
                return
            self._retry(job, cfg, f"io error: {e}", "io")
        except Exception as e:  # a serving worker must outlive any job
            if self._stale(job, epoch):
                return
            cls = devfail.classify(e)
            if cls == "oom":
                # HBM exhaustion that unwound past run_scf's in-run ladder
                # (e.g. from inside a compiled program): retry with a
                # degradation hint so the next attempt starts on a
                # smaller memory plan (devfail.apply_oom_hint above)
                job.oom_degrade = min(job.oom_degrade + 1, 3)
                self._retry(job, cfg, f"device OOM: {e}", "oom")
            elif cls == "device_lost":
                # hardware evidence against the slice, not the job:
                # shrink the slice to its surviving devices and resume
                # from autosave on the smaller mesh — preemption
                # semantics, never a poison strike
                self.supervisor.degrade_slice(
                    slice_idx, "device_lost", drop_devices=1)
                self._retry(job, cfg, f"device lost: {e}", "device_lost")
            elif cls == "transient":
                self._retry(job, cfg, f"transient backend error: {e}",
                            "transient")
            else:
                self._fail(job, f"unexpected {type(e).__name__}: {e}",
                           permanent=True)

    def _backoff_delay(self, job: Job) -> float:
        """Exponential backoff with jitter, clamped so the retry can never
        be pushed past the job's deadline (a late answer is a wrong
        answer — better to retry sooner than to abort unrun)."""
        delay = min(self.backoff_max,
                    self.backoff_base * (2.0 ** max(0, job.attempts - 1)))
        delay *= 1.0 + self.backoff_jitter * random.random()
        if job.deadline is not None:
            delay = max(0.0, min(delay, job.deadline - time.time()))
        return delay

    def _retry(self, job: Job, cfg, detail: str, failure_class: str,
               resume: bool = True) -> None:
        from sirius_tpu.dft.scf import default_autosave_path
        from sirius_tpu.io.checkpoint import find_resumable

        if job.terminal:
            return  # quarantined/drained while the attempt unwound
        counters["serve.retries"] += 1
        # labeled by failure class, NOT job id: one series per job is
        # unbounded cardinality under real traffic
        _RETRIES.inc(failure_class=failure_class)
        if job.attempts > job.max_retries:
            self._fail(job, f"{detail} (retries exhausted)")
            return
        if resume and cfg is not None:
            auto = cfg.control.autosave_path or default_autosave_path(
                cfg, job.base_dir)
            job.resume_path = find_resumable(
                auto, keep=int(cfg.control.autosave_keep))
        delay = self._backoff_delay(job)
        job.not_before = time.time() + delay
        _BACKOFF.observe(delay, failure_class=failure_class)
        obs_events.emit("backoff", job_id=job.id, delay_s=delay,
                        attempt=job.attempts, failure_class=failure_class,
                        not_before=job.not_before)
        logger.log(
            logging.INFO if self.verbose else logging.DEBUG,
            "retrying %s in %.2fs: %s (resume=%s)", job.id, delay, detail,
            job.resume_path)
        self.queue.requeue(job, f"{detail} (backoff {delay:.2f}s)")

    def _watchdog_retry(self, job: Job, detail: str,
                        failure_class: str) -> None:
        """Supervisor entry point: hand a crashed/hung worker's job back
        to the queue with backoff, resuming from its newest autosave."""
        self._retry(job, job._cfg, detail, failure_class)

    def _fail(self, job: Job, detail: str, permanent: bool = False,
              quarantined: bool = False) -> None:
        job.error = detail
        job.permanent = permanent
        job.quarantined = quarantined
        counters["serve.failures"] += 1
        _FAILURES.inc(permanent=str(permanent).lower())
        logger.info("job %s failed: %s", job.id, detail)
        job._transition(JobStatus.FAILED, detail)

    def cleanup_autosaves(self, jobs) -> None:
        """Remove job-scoped autosave generations of terminal jobs.

        Rotation depth follows the engine's ``autosave_keep`` (probing a
        little past it, like io.checkpoint.find_resumable, in case keep
        was lowered between runs) so raised keep values don't leak files.
        Jobs drained into the journal keep their autosaves — they are the
        restart's resume points."""
        for job in jobs:
            if job.leave_in_journal or not job.terminal:
                continue
            tag = job.id
            base = os.path.join(job.base_dir, f"sirius_autosave.{tag}.h5")
            paths = [base] + [
                f"{base}.{i}" for i in range(1, max(self.autosave_keep, 1) + 1)
            ]
            i = max(self.autosave_keep, 1) + 1
            while os.path.exists(f"{base}.{i}") and i < 100:
                paths.append(f"{base}.{i}")
                i += 1
            for p in paths:
                if os.path.exists(p):
                    try:
                        os.remove(p)
                    except OSError:
                        pass
