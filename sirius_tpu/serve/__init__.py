"""Multi-job SCF serving: queue + executable cache + device-slice scheduler.

The serving layer amortizes XLA compilation across independent SCF jobs
(the throughput lever of TPU practice — Lewis et al. arXiv:2112.09017,
Pederson et al. arXiv:2202.01255): decks whose padded shapes match share
jitted FusedScf/Davidson executables, and the global device mesh is
partitioned into slices that each run one job at a time.

Entry points: ServeEngine (library), `sirius-serve` (CLI, serve.engine),
tools/loadgen.py (throughput/latency benchmark).
"""

from sirius_tpu.serve.cache import ExecutableCache
from sirius_tpu.serve.queue import Job, JobQueue, JobStatus
from sirius_tpu.serve.scheduler import SliceScheduler

__all__ = [
    "ExecutableCache",
    "Job",
    "JobQueue",
    "JobStatus",
    "SliceScheduler",
]
