"""Multi-job SCF serving: queue + executable cache + device-slice scheduler.

The serving layer amortizes XLA compilation across independent SCF jobs
(the throughput lever of TPU practice — Lewis et al. arXiv:2112.09017,
Pederson et al. arXiv:2202.01255): decks whose padded shapes match share
jitted FusedScf/Davidson executables, and the global device mesh is
partitioned into slices that each run one job at a time.

The serving layer is fault-tolerant (ISSUE 8): a durable JSONL job
journal (serve/journal.py) makes submissions and outcomes survive
``kill -9`` with replay-and-resume on restart; slice workers run under a
supervisor watchdog (serve/supervisor.py) that respawns dead or hung
workers and quarantines poison jobs; retries back off exponentially
(deadline-aware) and admission is bounded (QueueFullError).

Fleet serving (ISSUE 19, sirius_tpu.fleet): content-addressed physics
memoization (exact resubmissions answered from a durable result store,
concurrent duplicates attached as watchers to the one in-flight job),
per-tenant fair-share scheduling (weighted deficit round robin +
per-tenant quotas on the queue), and multi-process federation over a
shared lease-based queue directory (a SIGKILL'd engine's leases expire
and survivors resume its jobs from their autosaves).

Entry points: ServeEngine (library), `sirius-serve` (CLI, serve.engine),
tools/loadgen.py (throughput/latency benchmark), tools/chaos_serve.py
(kill/restart/hang chaos gauntlet -> CHAOS_BENCH.json).
"""

from sirius_tpu.serve.cache import ExecutableCache
from sirius_tpu.serve.journal import JobJournal
from sirius_tpu.serve.queue import Job, JobQueue, JobStatus, QueueFullError
from sirius_tpu.serve.scheduler import SliceScheduler
from sirius_tpu.serve.supervisor import SliceSupervisor

__all__ = [
    "ExecutableCache",
    "Job",
    "JobJournal",
    "JobQueue",
    "JobStatus",
    "QueueFullError",
    "SliceScheduler",
    "SliceSupervisor",
]
