"""Slice supervision: heartbeats, a watchdog, worker respawn, and poison
quarantine for the serving scheduler.

The scheduler's thread-per-slice workers are supervised rather than
trusted: each worker heartbeats every queue-poll cycle and registers the
job it is about to run; a watchdog thread checks the fleet every
``interval`` seconds and recovers from the two ways a slice dies in
production:

- **Worker death** (a crash escaping the job sandbox — driven in tests
  by the ``serve.worker_crash`` fault): the thread is gone but its job
  never reached a terminal state. The watchdog strikes the job, hands it
  back to the queue (or quarantines it), and respawns a replacement
  worker on the same device slice.
- **Worker hang** (a job stuck inside run_scf past its wall-time budget
  — driven by ``serve.job_hang``): Python threads cannot be killed, so
  the watchdog *abandons* the job instead: it bumps ``job._epoch`` (the
  hung worker notices and discards any late result), strikes the job,
  and spawns a replacement worker so the slice keeps serving. The hung
  thread unwinds on its own or stays parked; either way it can no longer
  touch the job.

**Poison quarantine**: a job that kills or stalls its workers
``poison_threshold`` times is permanently failed (``job.quarantined``)
instead of being retried into a fourth dead slice — the serving-layer
analog of a poison-pill message queue. Strikes are tracked separately
from ``job.attempts`` so an honest preemption retry is never conflated
with evidence of a hostile deck.

**Slice degradation** (utils/devfail.py device-fault taxonomy): a
device-level failure is hardware evidence against the *slice*, not the
job, so it never strikes. ``degrade_slice`` marks the slice degraded —
on ``device_lost`` it additionally rebuilds the slice's device list in
place from the surviving devices (the worker thread holds a reference to
that list object, so the next job dispatches on the shrunk mesh), and on
``straggler`` it parks the slice behind a cooldown so the retried job
lands on healthy hardware first. ``slice_available`` gates the worker's
queue poll on that cooldown (bypassed for single-slice fleets, where
waiting would just idle the only capacity).

Everything the supervisor does is observable: ``serve_watchdog_fires_total``
(kind=crash|hang), ``serve_worker_restarts_total`` (reason),
``serve_quarantines_total``, ``serve_slice_degraded_total`` (reason),
plus ``watchdog_fire`` / ``worker_restart`` / ``quarantine`` /
``slice_degraded`` JSONL events.
"""

from __future__ import annotations

import threading
import time

from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs.log import get_logger
from sirius_tpu.utils import devfail

logger = get_logger("serve")

_WATCHDOG_FIRES = obs_metrics.REGISTRY.counter(
    "serve_watchdog_fires_total", "watchdog detections by kind")
_RESTARTS = obs_metrics.REGISTRY.counter(
    "serve_worker_restarts_total", "slice workers respawned by reason")
_QUARANTINES = obs_metrics.REGISTRY.counter(
    "serve_quarantines_total", "jobs quarantined as poison")
_DEGRADED = obs_metrics.REGISTRY.counter(
    "serve_slice_degraded_total",
    "slices marked degraded after a device-level failure, by reason")


class WorkerState:
    """Mutable supervision record for one slice worker."""

    def __init__(self, idx: int):
        self.idx = idx
        self.thread: threading.Thread | None = None
        self.heartbeat = time.time()
        self.generation = 0  # how many threads have served this slice
        self.job = None  # Job currently assigned (None while idle)
        self.job_epoch = 0
        self.job_started = 0.0


class SliceSupervisor:
    """Watchdog over the scheduler's slice workers.

    ``scheduler`` must provide ``queue``, ``slices``, ``_worker(idx,
    devs)``, and the recovery entry points ``_watchdog_retry(job,
    detail, failure_class)`` / ``_fail(job, detail, permanent,
    quarantined)``.
    """

    def __init__(self, scheduler, *, poison_threshold: int = 2,
                 job_wall_time_budget: float | None = None,
                 interval: float = 0.25,
                 heartbeat_timeout: float = 30.0):
        self.scheduler = scheduler
        self.poison_threshold = max(1, int(poison_threshold))
        self.job_wall_time_budget = job_wall_time_budget
        self.interval = float(interval)
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.workers = [
            WorkerState(i) for i in range(len(scheduler.slices))
        ]
        # per-slice degradation cooldown deadlines (unix seconds): a slice
        # past its deadline serves normally; slice_available() gates the
        # worker queue poll on it
        self.degraded_until = [0.0] * len(self.workers)
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._watchdog: threading.Thread | None = None

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        with self._lock:
            for state in self.workers:
                self._spawn_locked(state, reason="start")
        self._watchdog = threading.Thread(
            target=self._watch, name="serve-watchdog", daemon=True)
        self._watchdog.start()

    def stop(self) -> None:
        self._stop.set()
        if self._watchdog is not None:
            self._watchdog.join(timeout=5.0)

    def join(self, timeout: float | None = None) -> None:
        for state in self.workers:
            t = state.thread
            if t is not None and t.is_alive():
                t.join(timeout)

    def _spawn_locked(self, state: WorkerState, reason: str) -> None:
        state.generation += 1
        state.heartbeat = time.time()
        name = f"serve-slice-{state.idx}"
        if state.generation > 1:
            name += f"-g{state.generation}"
            _RESTARTS.inc(reason=reason)
            obs_events.emit("worker_restart", slice=state.idx,
                            generation=state.generation, reason=reason)
            logger.warning("respawning slice %d worker (%s, generation %d)",
                           state.idx, reason, state.generation)
        t = threading.Thread(
            target=self.scheduler._worker,
            args=(state.idx, self.scheduler.slices[state.idx]),
            name=name, daemon=True,
        )
        state.thread = t
        t.start()

    # -- worker-side notifications ----------------------------------------

    def beat(self, idx: int) -> None:
        self.workers[idx].heartbeat = time.time()

    def note_job(self, idx: int, job, epoch: int) -> None:
        state = self.workers[idx]
        with self._lock:
            state.job = job
            state.job_epoch = epoch
            state.job_started = time.time()
        state.heartbeat = state.job_started

    def note_idle(self, idx: int, job) -> None:
        state = self.workers[idx]
        with self._lock:
            if state.job is job:
                state.job = None
        state.heartbeat = time.time()

    # -- device-fault degradation (utils/devfail.py taxonomy) --------------

    def degrade_slice(self, idx: int, reason: str, *, drop_devices: int = 0,
                      cooldown: float = 0.0) -> None:
        """Mark slice ``idx`` degraded after a device-level failure.

        ``drop_devices`` > 0 (device loss) shrinks the slice's device
        list IN PLACE to the survivors — the worker thread holds a
        reference to that list object, so its next job dispatches on the
        shrunk mesh without a respawn (mesh-shape-agnostic checkpoints
        make the resume transparent). ``cooldown`` (stragglers) parks the
        slice so the preempted job's retry lands on healthy hardware
        first. Never strikes the job: hardware evidence is against the
        slice, not the deck."""
        with self._lock:
            devs = self.scheduler.slices[idx]
            if drop_devices > 0:
                survivors = devs[:-drop_devices] or devs[:1]
                devs[:] = survivors
            if cooldown > 0.0:
                self.degraded_until[idx] = max(
                    self.degraded_until[idx], time.time() + cooldown)
        _DEGRADED.inc(reason=reason)
        obs_events.emit("slice_degraded", slice=idx, reason=reason,
                        devices_left=len(devs), cooldown_s=cooldown)
        logger.error("slice %d degraded (%s): %d device(s) left, "
                     "cooldown %.1fs", idx, reason, len(devs), cooldown)

    def slice_available(self, idx: int) -> bool:
        """False while the slice sits out a degradation cooldown (always
        True for single-slice fleets — parking the only slice would just
        idle the queue)."""
        if len(self.workers) <= 1:
            return True
        return time.time() >= self.degraded_until[idx]

    # -- watchdog ----------------------------------------------------------

    def _queue_active(self) -> bool:
        q = self.scheduler.queue
        return not (q.closed and len(q) == 0)

    def _watch(self) -> None:
        while not self._stop.wait(self.interval):
            for state in self.workers:
                try:
                    self._check_worker(state)
                except Exception as e:
                    # the watchdog thread must survive anything a check
                    # raises — but a device-class failure surfacing HERE
                    # (outside any job dispatch) is hardware news that
                    # must never drown in a generic traceback line
                    cls = devfail.classify(e)
                    if cls in ("oom", "device_lost"):
                        logger.critical(
                            "device-class failure (%s) in watchdog check "
                            "for slice %d: %s", cls, state.idx, e)
                    else:
                        logger.exception(
                            "watchdog check failed for slice %d", state.idx)

    def _check_worker(self, state: WorkerState) -> None:
        thread = state.thread
        if thread is not None and not thread.is_alive():
            with self._lock:
                job, epoch = state.job, state.job_epoch
                state.job = None
            if job is not None and not job.terminal and job._epoch == epoch:
                _WATCHDOG_FIRES.inc(kind="crash")
                obs_events.emit("watchdog_fire", reason="crash",
                                slice=state.idx, job_id=job.id)
                logger.error("slice %d worker died running job %s",
                             state.idx, job.id)
                self._strike(job, f"worker crash on slice {state.idx}",
                             failure_class="crash")
            if self._queue_active() and not self._stop.is_set():
                with self._lock:
                    self._spawn_locked(state, reason="crash")
            return
        with self._lock:
            job, epoch, started = (
                state.job, state.job_epoch, state.job_started)
        if job is None or job.terminal:
            return
        budget = job.wall_time_budget or self.job_wall_time_budget
        if not budget:
            return
        elapsed = time.time() - started
        if elapsed <= budget:
            return
        # hung: abandon the job (the worker thread cannot be killed),
        # strike it, and replace the worker so the slice keeps serving
        _WATCHDOG_FIRES.inc(kind="hang")
        obs_events.emit("watchdog_fire", reason="hang", slice=state.idx,
                        job_id=job.id, elapsed_s=elapsed, budget_s=budget)
        logger.error("slice %d worker hung on job %s (%.1fs > budget %.1fs)",
                     state.idx, job.id, elapsed, budget)
        with self._lock:
            if state.job is not job or job._epoch != epoch:
                return  # finished or already handled in the window
            job._epoch += 1  # the hung worker's result is now stale
            state.job = None
        self._strike(job, f"hung {elapsed:.1f}s (budget {budget:.1f}s) "
                          f"on slice {state.idx}", failure_class="hang")
        if self._queue_active() and not self._stop.is_set():
            with self._lock:
                self._spawn_locked(state, reason="hang")

    def _strike(self, job, detail: str, failure_class: str) -> None:
        job.poison_strikes += 1
        if job.poison_strikes >= self.poison_threshold:
            _QUARANTINES.inc()
            obs_events.emit("quarantine", job_id=job.id,
                            strikes=job.poison_strikes, detail=detail)
            logger.error("quarantining job %s after %d strikes: %s",
                         job.id, job.poison_strikes, detail)
            self.scheduler._fail(
                job,
                f"quarantined after {job.poison_strikes} worker-fatal "
                f"strikes: {detail}",
                permanent=True, quarantined=True,
            )
        else:
            self.scheduler._watchdog_retry(job, detail, failure_class)
