"""ServeEngine: queue + executable cache + slice scheduler, and the
`sirius-serve` CLI.

Library use::

    eng = ServeEngine(num_slices=4)
    eng.start()
    job = eng.submit(deck_dict, priority=1)
    job.wait()
    eng.shutdown()
    print(eng.stats())

CLI use: ``sirius-serve deck1.json deck2.json ... [--slices N]`` runs the
decks to completion and prints a JSON stats report (the same shape
tools/loadgen.py writes to SERVE_BENCH.json).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from sirius_tpu.serve.cache import ExecutableCache
from sirius_tpu.serve.queue import Job, JobQueue, JobStatus
from sirius_tpu.serve.scheduler import SliceScheduler


def _percentile(xs, q: float) -> float:
    """Nearest-rank percentile of a non-empty list."""
    s = sorted(xs)
    i = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[i]


class ServeEngine:
    def __init__(self, num_slices: int = 1, devices=None,
                 cache_capacity: int = 32, autosave_every: int = 3,
                 autosave_keep: int = 2, workdir: str = ".",
                 verbose: bool = False):
        self.queue = JobQueue()
        self.cache = ExecutableCache(capacity=cache_capacity)
        self.workdir = workdir
        self.scheduler = SliceScheduler(
            self.queue, self.cache, num_slices=num_slices, devices=devices,
            autosave_every=autosave_every, autosave_keep=autosave_keep,
            verbose=verbose,
        )
        self._t0: float | None = None
        self._submitted: list[Job] = []

    @property
    def num_slices(self) -> int:
        return len(self.scheduler.slices)

    def start(self) -> None:
        self._t0 = time.time()
        self.scheduler.start()

    def submit(self, deck: dict, job_id: str | None = None,
               priority: int = 0, deadline: float | None = None,
               base_dir: str | None = None, max_retries: int = 2) -> Job:
        job = Job(
            deck, job_id=job_id, base_dir=base_dir or self.workdir,
            priority=priority, deadline=deadline, max_retries=max_retries,
        )
        self._submitted.append(job)
        return self.queue.submit(job)

    def wait_all(self, timeout: float | None = None) -> bool:
        """Block until every submitted job is terminal. False on timeout."""
        deadline = None if timeout is None else time.time() + timeout
        for job in self._submitted:
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return False
            if not job.wait(remaining):
                return False
        return True

    def shutdown(self, wait: bool = True, cleanup: bool = True) -> None:
        self.queue.close()
        if wait:
            self.scheduler.join(timeout=60.0)
        if cleanup:
            self.scheduler.cleanup_autosaves(self._submitted)

    def stats(self) -> dict:
        done = [j for j in self._submitted if j.status == JobStatus.DONE]
        lat = [j.latency for j in done if j.latency is not None]
        wall = (time.time() - self._t0) if self._t0 else 0.0
        return {
            "num_jobs": len(self._submitted),
            "num_done": len(done),
            "num_failed": sum(
                j.status == JobStatus.FAILED for j in self._submitted),
            "num_aborted": sum(
                j.status == JobStatus.ABORTED for j in self._submitted),
            "num_slices": self.num_slices,
            "wall_s": wall,
            "jobs_per_min": (len(done) / wall * 60.0) if wall > 0 else 0.0,
            "p50_latency_s": _percentile(lat, 50) if lat else None,
            "p95_latency_s": _percentile(lat, 95) if lat else None,
            "cache": self.cache.stats(),
            "retries_total": sum(j.attempts - 1 for j in self._submitted),
        }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="sirius-serve",
        description="multi-job SCF serving engine (sirius_tpu.serve)",
    )
    p.add_argument("decks", nargs="+", help="JSON deck files (cli.py format)")
    p.add_argument("--slices", type=int, default=1,
                   help="device slices / concurrent jobs")
    p.add_argument("--repeat", type=int, default=1,
                   help="submit each deck N times (cache warm-up study)")
    p.add_argument("--priority", type=int, default=0)
    p.add_argument("--deadline", type=float, default=None,
                   help="per-job deadline in seconds from submission")
    p.add_argument("--timeout", type=float, default=3600.0,
                   help="overall wait bound in seconds")
    p.add_argument("--stats_out", default=None,
                   help="also write the stats JSON to this path")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"])
    args = p.parse_args(argv)

    import os

    for d in args.decks:
        if not os.path.isfile(d):
            print(f"sirius-serve: deck not found: {d}", file=sys.stderr)
            return 2

    import jax

    if args.platform:
        jax.config.update(
            "jax_platforms",
            "axon" if args.platform == "tpu" else args.platform,
        )

    eng = ServeEngine(num_slices=args.slices, verbose=True)
    eng.start()
    for rep in range(args.repeat):
        for path in args.decks:
            with open(path) as f:
                deck = json.load(f)
            name = os.path.splitext(os.path.basename(path))[0]
            eng.submit(
                deck, job_id=f"{name}-{rep}", priority=args.priority,
                deadline=(time.time() + args.deadline
                          if args.deadline else None),
                base_dir=os.path.dirname(os.path.abspath(path)) or ".",
            )
    ok = eng.wait_all(timeout=args.timeout)
    eng.shutdown(wait=True)
    stats = eng.stats()
    stats["jobs"] = [j.to_dict() for j in eng._submitted]
    print(json.dumps(stats, indent=2, default=float))
    if args.stats_out:
        with open(args.stats_out, "w") as f:
            json.dump(stats, f, indent=2, default=float)
    if not ok:
        print("sirius-serve: timed out waiting for jobs", file=sys.stderr)
        return 3
    return 1 if stats["num_failed"] or stats["num_aborted"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
