"""ServeEngine: queue + executable cache + slice scheduler, and the
`sirius-serve` CLI.

Library use::

    eng = ServeEngine(num_slices=4)
    eng.start()
    job = eng.submit(deck_dict, priority=1)
    job.wait()
    eng.shutdown()
    print(eng.stats())

CLI use: ``sirius-serve deck1.json deck2.json ... [--slices N]`` runs the
decks to completion and prints a JSON stats report (the same shape
tools/loadgen.py writes to SERVE_BENCH.json).

Observability: ``metrics_port`` starts the obs HTTP endpoint
(``/metrics`` Prometheus text, ``/healthz`` JSON, ``/debug/trace`` to arm
a jax.profiler capture — obs/http.py) for the engine's lifetime, and
``events_path`` opens the JSONL event sink so every job transition and
SCF iteration is logged. ``metrics_snapshot()`` is the pull-style
equivalent for batch runs: the full registry plus engine stats as one
JSON-friendly dict (what loadgen embeds into SERVE_BENCH.json).
"""

from __future__ import annotations

import argparse
import json
import sys
import time

from sirius_tpu import obs
from sirius_tpu.serve.cache import ExecutableCache
from sirius_tpu.serve.queue import Job, JobQueue, JobStatus
from sirius_tpu.serve.scheduler import SliceScheduler


def _percentile(xs, q: float) -> float:
    """Nearest-rank percentile of a non-empty list."""
    s = sorted(xs)
    i = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[i]


class ServeEngine:
    def __init__(self, num_slices: int = 1, devices=None,
                 cache_capacity: int = 32, autosave_every: int = 3,
                 autosave_keep: int = 2, workdir: str = ".",
                 verbose: bool = False, metrics_port: int | None = None,
                 events_path: str | None = None):
        self.queue = JobQueue()
        self.cache = ExecutableCache(capacity=cache_capacity)
        self.workdir = workdir
        self.scheduler = SliceScheduler(
            self.queue, self.cache, num_slices=num_slices, devices=devices,
            autosave_every=autosave_every, autosave_keep=autosave_keep,
            verbose=verbose,
        )
        self._t0: float | None = None
        self._submitted: list[Job] = []
        self._shutdown = False
        self._obs_server = None
        if events_path:
            obs.configure_events(events_path)
        if metrics_port is not None:
            import os

            from sirius_tpu.obs.http import ObsHttpServer
            self._obs_server = ObsHttpServer(
                port=metrics_port, health_fn=self._health,
                default_trace_dir=os.path.join(workdir, "trace_capture"),
            )

    @property
    def num_slices(self) -> int:
        return len(self.scheduler.slices)

    def start(self) -> None:
        self._t0 = time.time()
        if self._obs_server is not None:
            self._obs_server.start()
        self.scheduler.start()

    @property
    def metrics_url(self) -> str | None:
        """Base URL of the obs endpoint (None when metrics_port unset)."""
        return self._obs_server.url if self._obs_server else None

    def _health(self) -> dict:
        terminal = (JobStatus.DONE, JobStatus.FAILED, JobStatus.ABORTED)
        return {
            "ok": not self._shutdown,
            "num_slices": self.num_slices,
            "queue_depth": len(self.queue),
            "jobs_submitted": len(self._submitted),
            "jobs_in_flight": sum(
                j.status not in terminal for j in self._submitted),
            "uptime_s": (time.time() - self._t0) if self._t0 else 0.0,
        }

    def submit(self, deck: dict, job_id: str | None = None,
               priority: int = 0, deadline: float | None = None,
               base_dir: str | None = None, max_retries: int = 2) -> Job:
        job = Job(
            deck, job_id=job_id, base_dir=base_dir or self.workdir,
            priority=priority, deadline=deadline, max_retries=max_retries,
        )
        self._submitted.append(job)
        return self.queue.submit(job)

    def wait_all(self, timeout: float | None = None) -> bool:
        """Block until every submitted job is terminal. False on timeout."""
        deadline = None if timeout is None else time.time() + timeout
        for job in self._submitted:
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return False
            if not job.wait(remaining):
                return False
        return True

    def shutdown(self, wait: bool = True, cleanup: bool = True) -> None:
        self._shutdown = True
        self.queue.close()
        if wait:
            self.scheduler.join(timeout=60.0)
        if cleanup:
            self.scheduler.cleanup_autosaves(self._submitted)
        if self._obs_server is not None:
            self._obs_server.stop()

    def stats(self) -> dict:
        done = [j for j in self._submitted if j.status == JobStatus.DONE]
        lat = [j.latency for j in done if j.latency is not None]
        wall = (time.time() - self._t0) if self._t0 else 0.0
        return {
            "num_jobs": len(self._submitted),
            "num_done": len(done),
            "num_failed": sum(
                j.status == JobStatus.FAILED for j in self._submitted),
            "num_aborted": sum(
                j.status == JobStatus.ABORTED for j in self._submitted),
            "num_slices": self.num_slices,
            "wall_s": wall,
            "jobs_per_min": (len(done) / wall * 60.0) if wall > 0 else 0.0,
            "p50_latency_s": _percentile(lat, 50) if lat else None,
            "p95_latency_s": _percentile(lat, 95) if lat else None,
            "cache": self.cache.stats(),
            "retries_total": sum(j.attempts - 1 for j in self._submitted),
        }

    def metrics_snapshot(self) -> dict:
        """Full observability snapshot for batch runs: engine stats,
        compile counts, queue high-water, and the metrics registry
        (histograms with cumulative buckets) as JSON-friendly data."""
        obs.update_device_memory_gauges()
        return {
            "stats": self.stats(),
            "backend_compiles_total": obs.backend_compiles_total(),
            "queue_depth_high_water": self.queue.high_water,
            "registry": obs.REGISTRY.snapshot(),
        }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="sirius-serve",
        description="multi-job SCF serving engine (sirius_tpu.serve)",
    )
    p.add_argument("decks", nargs="+", help="JSON deck files (cli.py format)")
    p.add_argument("--slices", type=int, default=1,
                   help="device slices / concurrent jobs")
    p.add_argument("--repeat", type=int, default=1,
                   help="submit each deck N times (cache warm-up study)")
    p.add_argument("--priority", type=int, default=0)
    p.add_argument("--deadline", type=float, default=None,
                   help="per-job deadline in seconds from submission")
    p.add_argument("--timeout", type=float, default=3600.0,
                   help="overall wait bound in seconds")
    p.add_argument("--stats_out", default=None,
                   help="also write the stats JSON to this path")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"])
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve /metrics + /healthz on this port "
                        "(0 = ephemeral; off when omitted)")
    p.add_argument("--events", default=None,
                   help="append JSONL observability events to this file")
    p.add_argument("-v", "--verbose", action="count", default=0,
                   help="raise log level (-v info, -vv debug)")
    args = p.parse_args(argv)

    obs.setup_logging(args.verbose)

    import os

    for d in args.decks:
        if not os.path.isfile(d):
            print(f"sirius-serve: deck not found: {d}", file=sys.stderr)
            return 2

    import jax

    if args.platform:
        jax.config.update(
            "jax_platforms",
            "axon" if args.platform == "tpu" else args.platform,
        )

    eng = ServeEngine(num_slices=args.slices, verbose=True,
                      metrics_port=args.metrics_port,
                      events_path=args.events)
    eng.start()
    if eng.metrics_url:
        print(f"sirius-serve: metrics at {eng.metrics_url}/metrics",
              file=sys.stderr)
    for rep in range(args.repeat):
        for path in args.decks:
            with open(path) as f:
                deck = json.load(f)
            name = os.path.splitext(os.path.basename(path))[0]
            eng.submit(
                deck, job_id=f"{name}-{rep}", priority=args.priority,
                deadline=(time.time() + args.deadline
                          if args.deadline else None),
                base_dir=os.path.dirname(os.path.abspath(path)) or ".",
            )
    ok = eng.wait_all(timeout=args.timeout)
    stats_obs = eng.metrics_snapshot()
    eng.shutdown(wait=True)
    stats = eng.stats()
    stats["obs"] = {k: v for k, v in stats_obs.items() if k != "stats"}
    stats["jobs"] = [j.to_dict() for j in eng._submitted]
    print(json.dumps(stats, indent=2, default=float))
    if args.stats_out:
        with open(args.stats_out, "w") as f:
            json.dump(stats, f, indent=2, default=float)
    if not ok:
        print("sirius-serve: timed out waiting for jobs", file=sys.stderr)
        return 3
    return 1 if stats["num_failed"] or stats["num_aborted"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
