"""ServeEngine: queue + executable cache + slice scheduler + durable job
journal, and the `sirius-serve` CLI.

Library use::

    eng = ServeEngine(num_slices=4, journal_path="jobs.journal")
    eng.start()
    job = eng.submit(deck_dict, priority=1)
    job.wait()
    eng.shutdown(mode="drain")
    print(eng.stats())

CLI use: ``sirius-serve deck1.json deck2.json ... [--slices N]`` runs the
decks to completion and prints a JSON stats report (the same shape
tools/loadgen.py writes to SERVE_BENCH.json).

Fault tolerance (ISSUE 8): with ``journal_path`` set, every accepted
submission and terminal transition is fsync'd to an append-only JSONL
write-ahead journal (serve/journal.py) *before* the engine acts on it. A
new engine pointed at the same journal replays the jobs that never
reached a terminal state, re-submitting them with ``resume_path`` aimed
at their job-scoped autosaves — a ``kill -9`` mid-campaign costs only
the SCF iterations since each job's last autosave. ``shutdown`` knows
``drain`` (stop admissions, finish in-flight, leave queued jobs in the
journal for the next process) from ``abort`` (queued jobs are terminally
aborted and journaled as such); the CLI maps SIGTERM to a drain and
exits 0. Slice workers are supervised with heartbeats, a watchdog, and
poison quarantine (serve/supervisor.py).

Fleet serving (ISSUE 19, sirius_tpu.fleet): ``store_dir`` (or
``fleet_dir``, which implies a shared ``<fleet_dir>/store``) arms
content-addressed dedup — an exact resubmission is answered from the
durable result store instantly with ``provenance: memo`` and the donor
run's trace id, and a duplicate of a job currently in flight attaches
to it as a *watcher*, so no canonical hash is ever computed twice
concurrently. ``fleet_dir`` additionally federates this engine with any
number of peer processes over one shared queue directory: a pull thread
leases pending jobs (fsync'd atomic claim + heartbeat renewal), and a
peer's SIGKILL expires its leases so this engine reclaims and resumes
its jobs from their shared autosaves, continuing the original trace
ids. ``fair_share``/``tenants`` switch the queue to per-tenant weighted
deficit-round-robin popping with per-tenant quotas (serve/queue.py).

Observability: ``metrics_port`` starts the obs HTTP endpoint
(``/metrics`` Prometheus text, ``/healthz`` JSON, ``/debug/trace`` to arm
a jax.profiler capture — obs/http.py) for the engine's lifetime, and
``events_path`` opens the JSONL event sink so every job transition and
SCF iteration is logged. ``metrics_snapshot()`` is the pull-style
equivalent for batch runs: the full registry plus engine stats as one
JSON-friendly dict (what loadgen embeds into SERVE_BENCH.json).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

from sirius_tpu import obs
from sirius_tpu.fleet.canon import deck_hash
from sirius_tpu.fleet.federation import FleetMember
from sirius_tpu.fleet.store import ResultStore
from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs import tracing as obs_tracing
from sirius_tpu.serve import journal as journal_mod
from sirius_tpu.serve.cache import ExecutableCache
from sirius_tpu.serve.queue import Job, JobQueue, JobStatus
from sirius_tpu.serve.scheduler import SliceScheduler

_REPLAYS = obs_metrics.REGISTRY.counter(
    "serve_journal_replays_total", "jobs replayed from the journal")
_MEMO = obs_metrics.REGISTRY.counter(
    "fleet_memo_total",
    "content-addressed dedup outcomes (outcome=hit|miss|store)")
_WATCHERS = obs_metrics.REGISTRY.counter(
    "fleet_watcher_attaches_total",
    "duplicate submissions attached as watchers to an in-flight job")


def _percentile(xs, q: float) -> float:
    """Nearest-rank percentile of a non-empty list."""
    s = sorted(xs)
    i = min(len(s) - 1, max(0, int(round(q / 100.0 * (len(s) - 1)))))
    return s[i]


class ServeEngine:
    def __init__(self, num_slices: int = 1, devices=None,
                 cache_capacity: int = 32, autosave_every: int = 3,
                 autosave_keep: int = 2, workdir: str = ".",
                 verbose: bool = False, metrics_port: int | None = None,
                 events_path: str | None = None,
                 journal_path: str | None = None, queue_maxsize: int = 0,
                 poison_threshold: int = 2,
                 job_wall_time_budget: float | None = None,
                 watchdog_interval: float = 0.25,
                 backoff_base: float = 0.5, backoff_max: float = 30.0,
                 store_dir: str | None = None, dedup: bool | None = None,
                 fleet_dir: str | None = None, fleet_poll: float = 0.25,
                 lease_ttl: float = 6.0, engine_id: str | None = None,
                 fair_share: bool = False,
                 tenants: dict[str, dict] | None = None):
        self.queue = JobQueue(maxsize=queue_maxsize, fair_share=fair_share,
                              tenants=tenants)
        self.cache = ExecutableCache(capacity=cache_capacity)
        self.workdir = workdir
        os.makedirs(workdir, exist_ok=True)
        self.autosave_keep = int(autosave_keep)
        self.scheduler = SliceScheduler(
            self.queue, self.cache, num_slices=num_slices, devices=devices,
            autosave_every=autosave_every, autosave_keep=autosave_keep,
            verbose=verbose, poison_threshold=poison_threshold,
            job_wall_time_budget=job_wall_time_budget,
            watchdog_interval=watchdog_interval,
            backoff_base=backoff_base, backoff_max=backoff_max,
        )
        self._t0: float | None = None
        self._submitted: list[Job] = []
        self._shutdown = False
        self._obs_server = None
        # wait_all blocks on this condition; every job's terminal hook
        # notifies it, so completion latency is not quantized by polling
        self._done_cv = threading.Condition()
        if events_path:
            obs.configure_events(events_path)
        # content-addressed memo layer (sirius_tpu.fleet): a fleet dir
        # implies a fleet-wide shared store unless one is given
        if fleet_dir and store_dir is None:
            store_dir = os.path.join(fleet_dir, "store")
        self.store: ResultStore | None = (
            ResultStore(store_dir) if store_dir else None)
        self.dedup = (self.store is not None if dedup is None
                      else bool(dedup) and self.store is not None)
        # canonical hash -> the one Job computing it right now; duplicate
        # submissions attach to it as watchers instead of recomputing
        self._inflight: dict[str, Job] = {}
        self._inflight_lock = threading.Lock()
        self.dedup_lookups = 0
        self.memo_hits = 0
        self.watcher_attaches = 0
        self.fleet: FleetMember | None = None
        if fleet_dir:
            self.fleet = FleetMember(self, fleet_dir, poll=fleet_poll,
                                     lease_ttl=lease_ttl, owner=engine_id)
        self.journal: journal_mod.JobJournal | None = None
        self.replayed: list[Job] = []
        if journal_path:
            pending, jstats = journal_mod.replay(journal_path)
            self.journal = journal_mod.JobJournal(journal_path)
            self._journal_stats = jstats
            # campaign children replayed below may depend on parents that
            # settled in a previous process and so never re-enter the
            # queue: resolve those edges from the journal's terminal map
            self.queue.external_parent_status.update(
                jstats.get("terminal_status") or {})
            for rec in pending:
                self.replayed.append(self._replay_job(rec))
        if metrics_port is not None:
            from sirius_tpu.obs.http import ObsHttpServer
            self._obs_server = ObsHttpServer(
                port=metrics_port, health_fn=self._health,
                default_trace_dir=os.path.join(workdir, "trace_capture"),
            )

    def _notify_terminal(self, job: Job) -> None:
        """Job terminal hook: wake wait_all promptly."""
        with self._done_cv:
            self._done_cv.notify_all()

    # -- content-addressed dedup (sirius_tpu.fleet) ------------------------

    @staticmethod
    def _memo_result(rec: dict) -> dict:
        """A job result served from the store: the donor's physics plus
        a provenance trail back to the run that computed it."""
        res = {k: rec[k]
               for k in ("energy", "converged", "num_scf_iterations",
                         "forces", "stress", "task")
               if rec.get(k) is not None}
        res["provenance"] = "memo"
        res["donor_trace_id"] = rec.get("trace_id")
        res["donor_job_id"] = rec.get("job_id")
        return res

    def _try_dedup(self, job: Job) -> bool:
        """Answer ``job`` without computing: from the store (memo hit)
        or by attaching it as a watcher to the in-flight job for the
        same canonical hash. Returns False — after registering ``job``
        as the new in-flight leader — when a fresh compute is needed."""
        canon = job.canon_hash
        with self._inflight_lock:  # counters shared with FleetMember thread
            self.dedup_lookups += 1
        rec = self.store.get(canon) if self.store is not None else None
        if rec is not None:
            with self._inflight_lock:
                self.memo_hits += 1
            _MEMO.inc(outcome="hit")
            job.result = self._memo_result(rec)
            job.submitted_at = job.submitted_at or time.time()
            obs_events.emit("memo_hit", job_id=job.id, canon_hash=canon,
                            donor_trace_id=rec.get("trace_id"),
                            trace_id=job.trace_id)
            job._transition(
                JobStatus.DONE,
                f"memo hit {canon[:12]} (donor {rec.get('job_id')})")
            return True
        with self._inflight_lock:
            leader = self._inflight.get(canon)
            if leader is None or leader.terminal:
                self._inflight[canon] = job
                leader = None
        if leader is None:
            _MEMO.inc(outcome="miss")
            job.add_terminal_hook(self._store_result)
            job.add_terminal_hook(self._inflight_forget)
            return False
        with self._inflight_lock:
            self.watcher_attaches += 1
        _WATCHERS.inc()
        job.submitted_at = job.submitted_at or time.time()
        obs_events.emit("watcher_attach", job_id=job.id, leader=leader.id,
                        canon_hash=canon, trace_id=job.trace_id)
        # fires immediately if the leader settled in the check window
        # (add_terminal_hook's after-terminal contract), so the watcher
        # can never miss the answer
        leader.add_terminal_hook(self._make_watcher_settle(job))
        return True

    def _make_watcher_settle(self, watcher: Job):
        def settle(leader: Job) -> None:
            self._settle_watcher(watcher, leader)
        return settle

    def _settle_watcher(self, watcher: Job, leader: Job) -> None:
        """The leader for ``watcher``'s hash settled: copy its answer,
        or — if the leader died without one — promote the watcher to
        compute (or chain it onto an already-promoted sibling)."""
        if watcher.terminal:
            return
        if leader.status == JobStatus.DONE and leader.result:
            res = {k: v for k, v in leader.result.items() if k != "serve"}
            res.update(provenance="watcher",
                       donor_trace_id=leader.trace_id,
                       donor_job_id=leader.id)
            watcher.result = res
            watcher._transition(
                JobStatus.DONE, f"watcher served by {leader.id}")
            return
        with self._inflight_lock:
            cur = self._inflight.get(watcher.canon_hash)
            if cur is leader or cur is None or cur.terminal:
                self._inflight[watcher.canon_hash] = watcher
                cur = None
        if cur is not None:
            # a sibling watcher was promoted first: wait on it instead
            cur.add_terminal_hook(self._make_watcher_settle(watcher))
            return
        watcher.add_terminal_hook(self._store_result)
        watcher.add_terminal_hook(self._inflight_forget)
        if self.journal is not None:
            # the watcher is real work the engine owes now — make it
            # durable before queueing, like any fresh submission
            watcher.submitted_at = watcher.submitted_at or time.time()
            self.journal.record_submit(watcher)
            watcher.add_terminal_hook(self._journal_terminal)
        # the watcher already holds _notify_terminal from submit();
        # re-order it to fire last so the store/journal writes land
        # before any waiter resumes (see submit())
        if self._notify_terminal in watcher._terminal_hooks:
            watcher._terminal_hooks.remove(self._notify_terminal)
            watcher._terminal_hooks.append(self._notify_terminal)
        self.queue.requeue(
            watcher, f"promoted: leader {leader.id} {leader.status}")

    def _store_result(self, job: Job) -> None:
        """Job terminal hook: persist a freshly computed answer under
        its content address (never re-store memo/watcher copies)."""
        if (self.store is None or job.canon_hash is None
                or job.status != JobStatus.DONE or not job.result
                or job.result.get("provenance") in ("memo", "watcher")):
            return
        if self.store.put(job.canon_hash, job.result,
                          trace_id=job.trace_id, job_id=job.id):
            _MEMO.inc(outcome="store")
            obs_events.emit("memo_store", job_id=job.id,
                            canon_hash=job.canon_hash,
                            trace_id=job.trace_id)

    def _inflight_forget(self, job: Job) -> None:
        """Job terminal hook: stop routing duplicates to a settled
        leader (later exact submissions hit the store instead)."""
        if job.canon_hash is None:
            return
        with self._inflight_lock:
            if self._inflight.get(job.canon_hash) is job:
                del self._inflight[job.canon_hash]

    # -- fleet federation (sirius_tpu.fleet.federation) --------------------

    def _adopt_fleet_job(self, rec: dict) -> Job | None:
        """Admit a fleet job whose lease we just won into the local
        queue, resuming from its shared-work-dir autosave with its
        ORIGINAL trace id; store hits settle instantly as memo answers.
        Returns None when the engine can no longer take work (the
        member releases the lease). Fleet jobs are deliberately not
        written to the local journal — the fleet dir is their durable
        record."""
        if self._shutdown or self.queue.closed:
            return None
        job = Job(
            rec.get("deck") or {}, job_id=rec["job_id"],
            base_dir=self.fleet.dir.work_dir,
            priority=int(rec.get("priority") or 0),
            deadline=rec.get("deadline"),
            max_retries=int(rec.get("max_retries") or 2),
            wall_time_budget=rec.get("wall_time_budget"),
            trace_id=rec.get("trace_id"),
            tenant=rec.get("tenant") or "default",
            canon_hash=(rec.get("canon_hash") if self.dedup else None),
        )
        job.submitted_at = rec.get("ts") or time.time()
        self._submitted.append(job)
        # _notify_terminal last (see submit()): the store write must
        # land before any waiter resumes
        if job.canon_hash and self._try_dedup(job):
            job.add_terminal_hook(self._notify_terminal)
            return job
        job.add_terminal_hook(self._notify_terminal)
        job.resume_path = self._find_replay_autosave(job)
        self.queue.requeue(job, "fleet claim")
        return job

    def _abandon_fleet_job(self, job: Job) -> None:
        """Our lease on ``job`` was lost: some survivor owns it now.
        Bump the epoch so a still-running worker's late result is
        discarded, and keep the autosaves (``leave_in_journal``) for
        the new owner to resume from."""
        job._epoch += 1
        job.leave_in_journal = True
        job._transition(JobStatus.ABORTED, "fleet lease lost")

    # -- journal -----------------------------------------------------------

    def _journal_terminal(self, job: Job) -> None:
        """Job terminal hook: make the outcome durable. Drained jobs are
        deliberately left non-terminal so a restart re-runs them."""
        if self.journal is None or job.leave_in_journal:
            return
        self.journal.record_terminal(job)

    def _replay_job(self, rec: dict) -> Job:
        """Re-submit one non-terminal journal record, resuming from the
        newest valid generation of its job-scoped autosave."""
        job = Job(
            rec.get("deck") or {}, job_id=rec["job_id"],
            base_dir=rec.get("base_dir") or self.workdir,
            priority=int(rec.get("priority") or 0),
            deadline=rec.get("deadline"),
            max_retries=int(rec.get("max_retries") or 2),
            wall_time_budget=rec.get("wall_time_budget"),
            parents=rec.get("parents"),
            campaign_id=rec.get("campaign_id"),
            node_id=rec.get("node_id"),
            handoff_in=rec.get("handoff_in"),
            handoff_out=rec.get("handoff_out"),
            trace_id=rec.get("trace_id"),
            tenant=rec.get("tenant") or "default",
            canon_hash=(rec.get("canon_hash") if self.dedup else None),
        )
        job.resume_path = self._find_replay_autosave(job)
        job.add_terminal_hook(self._journal_terminal)
        job.submitted_at = rec.get("ts") or time.time()
        self._submitted.append(job)
        _REPLAYS.inc()
        obs_events.emit("journal_replay_job", job_id=job.id,
                        resume=job.resume_path)
        # replayed duplicates dedup like fresh ones: a store hit (or an
        # already-replayed leader for the same hash) settles this job
        # without a recompute, and the terminal record converges the
        # journal. _notify_terminal last (see submit()).
        if job.canon_hash and self._try_dedup(job):
            job.add_terminal_hook(self._notify_terminal)
            return job
        job.add_terminal_hook(self._notify_terminal)
        # requeue, not submit: the journal already admitted this work, so
        # it is exempt from the admission bound and not re-journaled
        self.queue.requeue(job, "journal replay")
        return job

    def _find_replay_autosave(self, job: Job) -> str | None:
        from sirius_tpu.io.checkpoint import CheckpointError, find_resumable

        ctl = {}
        if isinstance(job.deck, dict):
            ctl = job.deck.get("control") or {}
        # mirror the scheduler's serve defaults: explicit autosave_path
        # wins, then the (tag or job-id)-scoped rotation in base_dir
        base = ctl.get("autosave_path") or os.path.join(
            job.base_dir,
            f"sirius_autosave.{ctl.get('autosave_tag') or job.id}.h5")
        try:
            return find_resumable(base, keep=self.autosave_keep)
        except (CheckpointError, OSError):
            # only the two ways probing an autosave legitimately fails:
            # damaged/mismatched file or filesystem trouble — a cold
            # replay is the right degradation for both. Anything else
            # (incl. a device-class error) must surface, not be eaten.
            return None

    @property
    def num_slices(self) -> int:
        return len(self.scheduler.slices)

    def start(self) -> None:
        self._t0 = time.time()
        if self._obs_server is not None:
            self._obs_server.start()
        self.scheduler.start()
        if self.fleet is not None:
            self.fleet.start()

    @property
    def metrics_url(self) -> str | None:
        """Base URL of the obs endpoint (None when metrics_port unset)."""
        return self._obs_server.url if self._obs_server else None

    def _health(self) -> dict:
        return {
            "ok": not self._shutdown,
            "num_slices": self.num_slices,
            "queue_depth": len(self.queue),
            "jobs_submitted": len(self._submitted),
            "jobs_in_flight": sum(
                not j.terminal for j in self._submitted),
            "journal": self.journal.path if self.journal else None,
            "jobs_replayed": len(self.replayed),
            "dedup_memo_hits": self.memo_hits,
            "dedup_watcher_attaches": self.watcher_attaches,
            "fleet_owner": self.fleet.owner if self.fleet else None,
            "fleet_claimed": (self.fleet.claimed_ids()
                              if self.fleet else []),
            "uptime_s": (time.time() - self._t0) if self._t0 else 0.0,
        }

    def submit(self, deck: dict, job_id: str | None = None,
               priority: int = 0, deadline: float | None = None,
               base_dir: str | None = None, max_retries: int = 2,
               wall_time_budget: float | None = None,
               block: bool = False, timeout: float | None = None,
               parents: list[str] | None = None,
               campaign_id: str | None = None,
               node_id: str | None = None,
               handoff_in: dict | None = None,
               handoff_out: str | None = None,
               trace_id: str | None = None,
               tenant: str = "default") -> Job:
        """Admit a job. Raises QueueFullError when the queue is bounded
        and full (immediately, or after ``timeout`` with ``block=True``)
        or when ``tenant`` is over its queue quota.
        With a journal, the submission is durable before it is queued.
        With a result store (``store_dir``/``fleet_dir``), an exact
        resubmission — same canonical deck hash — is answered from the
        store instantly (``provenance: memo``), and a duplicate of a job
        currently in flight attaches to it as a watcher instead of
        recomputing; neither consumes queue capacity.
        ``parents``/``campaign_id``/``handoff_*`` attach the job to a
        campaign DAG (sirius_tpu.campaigns): it runs only after every
        parent is DONE, is skipped terminally when one fails, and routes
        the parent's converged state in as run_scf(initial_guess=)."""
        job = Job(
            deck, job_id=job_id, base_dir=base_dir or self.workdir,
            priority=priority, deadline=deadline, max_retries=max_retries,
            wall_time_budget=wall_time_budget,
            parents=parents, campaign_id=campaign_id, node_id=node_id,
            handoff_in=handoff_in, handoff_out=handoff_out,
            # trace identity BEFORE journaling: explicit id (campaigns) >
            # the caller's ambient trace > a fresh one — so replay after
            # SIGKILL continues the same end-to-end trace
            trace_id=(trace_id or obs_tracing.current_trace_id()
                      or obs_tracing.new_trace_id()),
            tenant=tenant,
            canon_hash=(deck_hash(deck) if self.dedup else None),
        )
        # _notify_terminal (which wakes wait_all) must be the LAST hook:
        # hooks fire in registration order, and a waiter resuming before
        # _store_result / _journal_terminal ran could resubmit the same
        # deck and miss the memo that is still being written
        if job.canon_hash and self._try_dedup(job):
            # answered from the store or attached to the in-flight
            # leader: no queue admission, no journal record — the engine
            # owes nothing a crash could lose
            job.add_terminal_hook(self._notify_terminal)
            self._submitted.append(job)
            return job
        if self.journal is not None:
            job.add_terminal_hook(self._journal_terminal)
            # write-ahead: journal first so a crash between journaling and
            # queueing re-runs the job (at-least-once) instead of losing it
            job.submitted_at = time.time()
            self.journal.record_submit(job)
        job.add_terminal_hook(self._notify_terminal)
        try:
            self.queue.submit(job, block=block, timeout=timeout)
        except Exception as e:
            # keep the journal consistent: the rejection is terminal (the
            # _on_terminal hook writes the terminal record)
            job.error = f"rejected: {e}"
            job._transition(JobStatus.ABORTED, job.error)
            raise
        self._submitted.append(job)
        return job

    def wait_all(self, timeout: float | None = None) -> bool:
        """Block until every submitted job is terminal. False on timeout.

        Condition-based, not polled: each job's terminal hook notifies
        ``_done_cv``, so a waiter wakes within the transition itself —
        campaign completion latency is not quantized by a poll interval.
        The pending set is re-evaluated on every wakeup, which also
        covers jobs submitted after the wait began."""
        deadline = None if timeout is None else time.time() + timeout
        with self._done_cv:
            while True:
                # status is set before the hook fires, so any job whose
                # notify we could have missed is already terminal here
                if all(j.terminal for j in self._submitted):
                    return True
                remaining = (None if deadline is None
                             else deadline - time.time())
                if remaining is not None and remaining <= 0:
                    return False
                self._done_cv.wait(remaining)

    def shutdown(self, wait: bool = True, cleanup: bool = True,
                 mode: str = "drain") -> None:
        """Stop the engine.

        ``mode="drain"``: stop admissions, let in-flight jobs finish, and
        hand queued-but-unstarted jobs back to the journal (terminal
        ABORTED in-process so ``wait_all`` returns, but left non-terminal
        on disk with their autosaves intact — the next engine on this
        journal re-runs them). ``mode="abort"``: queued jobs are
        terminally aborted, in the journal too."""
        if mode not in ("drain", "abort"):
            raise ValueError(f"shutdown mode must be drain|abort, not {mode!r}")
        self._shutdown = True
        if self.fleet is not None:
            # stop claiming and renewing first: our queued fleet jobs'
            # leases are released below, in-flight ones either finish
            # (terminal record written, fenced) or expire for survivors
            self.fleet.stop()
        self.queue.close()
        # "drain" keeps work durable for whoever resumes it — the local
        # journal or, for fleet jobs, the shared fleet dir
        leave = mode == "drain" and (self.journal is not None
                                     or self.fleet is not None)
        drained = self.queue.abort_pending(
            "drained for restart" if mode == "drain" else "abort shutdown",
            leave_in_journal=leave,
        )
        if drained:
            obs_events.emit("drain" if mode == "drain" else "abort",
                            jobs=[j.id for j in drained])
        if wait:
            self.scheduler.join(timeout=60.0)
        self.scheduler.stop_supervision()
        # deterministic close: nothing a dead/raced worker left behind may
        # stay QUEUED forever (wait_all would block on it)
        self.queue.abort_pending(
            "queue closed before worker pickup", leave_in_journal=leave)
        if cleanup:
            self.scheduler.cleanup_autosaves(self._submitted)
        if self.journal is not None:
            self.journal.close()
        if self._obs_server is not None:
            self._obs_server.stop()

    def stats(self) -> dict:
        done = [j for j in self._submitted if j.status == JobStatus.DONE]
        lat = [j.latency for j in done if j.latency is not None]
        wall = (time.time() - self._t0) if self._t0 else 0.0
        by_tenant: dict[str, list[Job]] = {}
        for j in self._submitted:
            by_tenant.setdefault(j.tenant, []).append(j)

        def _tenant_row(js: list[Job]) -> dict:
            tl = [j.latency for j in js
                  if j.status == JobStatus.DONE and j.latency is not None]
            return {
                "num_jobs": len(js),
                "num_done": sum(j.status == JobStatus.DONE for j in js),
                "p50_latency_s": _percentile(tl, 50) if tl else None,
                "p95_latency_s": _percentile(tl, 95) if tl else None,
            }

        return {
            "num_jobs": len(self._submitted),
            "num_done": len(done),
            "num_failed": sum(
                j.status == JobStatus.FAILED for j in self._submitted),
            "num_aborted": sum(
                j.status == JobStatus.ABORTED for j in self._submitted),
            "num_skipped_upstream": sum(
                j.status == JobStatus.SKIPPED_UPSTREAM
                for j in self._submitted),
            "num_quarantined": sum(
                j.quarantined for j in self._submitted),
            "num_replayed": len(self.replayed),
            "num_drained": sum(
                j.leave_in_journal for j in self._submitted),
            "num_slices": self.num_slices,
            "wall_s": wall,
            "jobs_per_min": (len(done) / wall * 60.0) if wall > 0 else 0.0,
            "p50_latency_s": _percentile(lat, 50) if lat else None,
            "p95_latency_s": _percentile(lat, 95) if lat else None,
            "cache": self.cache.stats(),
            "retries_total": sum(j.attempts - 1 for j in self._submitted),
            "tenants": {t: _tenant_row(js)
                        for t, js in sorted(by_tenant.items())},
            "fair_share": self.queue.fair_share,
            "dedup": {
                "enabled": self.dedup,
                "lookups": self.dedup_lookups,
                "memo_hits": self.memo_hits,
                "watcher_attaches": self.watcher_attaches,
                "hit_rate": ((self.memo_hits + self.watcher_attaches)
                             / self.dedup_lookups
                             if self.dedup_lookups else 0.0),
                "store": self.store.stats() if self.store else None,
            },
            "fleet": ({"owner": self.fleet.owner,
                       "claimed": self.fleet.claimed_ids()}
                      if self.fleet else None),
        }

    def metrics_snapshot(self) -> dict:
        """Full observability snapshot for batch runs: engine stats,
        compile counts, queue high-water, and the metrics registry
        (histograms with cumulative buckets) as JSON-friendly data."""
        obs.update_device_memory_gauges()
        return {
            "stats": self.stats(),
            "backend_compiles_total": obs.backend_compiles_total(),
            "queue_depth_high_water": self.queue.high_water,
            "registry": obs.REGISTRY.snapshot(),
        }


def main(argv: list[str] | None = None) -> int:
    p = argparse.ArgumentParser(
        prog="sirius-serve",
        description="multi-job SCF serving engine (sirius_tpu.serve)",
    )
    p.add_argument("decks", nargs="*",
                   help="JSON deck files (cli.py format); optional when "
                        "--fleet-dir supplies the work")
    p.add_argument("--slices", type=int, default=1,
                   help="device slices / concurrent jobs")
    p.add_argument("--fleet-dir", default=None,
                   help="shared fleet queue directory: lease jobs other "
                        "processes submitted, and serve until drained "
                        "(sirius_tpu.fleet.federation)")
    p.add_argument("--engine-id", default=None,
                   help="stable lease-owner id in the fleet dir "
                        "(default: host-pid-random)")
    p.add_argument("--lease-ttl", type=float, default=6.0,
                   help="fleet lease expiry in seconds; a SIGKILL'd "
                        "engine's jobs are reclaimed after this long")
    p.add_argument("--store-dir", default=None,
                   help="content-addressed result store for dedup "
                        "(defaults to <fleet-dir>/store in fleet mode)")
    p.add_argument("--no-dedup", action="store_true",
                   help="disable content-addressed dedup even with a "
                        "store configured")
    p.add_argument("--fair-share", action="store_true",
                   help="weighted deficit-round-robin popping across "
                        "tenants instead of global priority order")
    p.add_argument("--tenant", default="default",
                   help="tenant id for decks submitted by this CLI")
    p.add_argument("--repeat", type=int, default=1,
                   help="submit each deck N times (cache warm-up study)")
    p.add_argument("--priority", type=int, default=0)
    p.add_argument("--deadline", type=float, default=None,
                   help="per-job deadline in seconds from submission")
    p.add_argument("--timeout", type=float, default=3600.0,
                   help="overall wait bound in seconds")
    p.add_argument("--stats_out", default=None,
                   help="also write the stats JSON to this path")
    p.add_argument("--platform", default=None, choices=["cpu", "tpu", "axon"])
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve /metrics + /healthz on this port "
                        "(0 = ephemeral; off when omitted)")
    p.add_argument("--events", default=None,
                   help="append JSONL observability events to this file")
    p.add_argument("--journal", default=None,
                   help="durable job journal (JSONL WAL); a restart with "
                        "the same path resumes unfinished jobs")
    p.add_argument("--queue-max", type=int, default=0,
                   help="bound the queue (0 = unbounded); full queues "
                        "reject submissions")
    p.add_argument("--budget", type=float, default=None,
                   help="per-attempt wall-time budget in seconds enforced "
                        "by the slice watchdog")
    p.add_argument("--poison-threshold", type=int, default=2,
                   help="worker-fatal strikes before a job is quarantined")
    p.add_argument("-v", "--verbose", action="count", default=0,
                   help="raise log level (-v info, -vv debug)")
    args = p.parse_args(argv)

    obs.setup_logging(args.verbose)

    if not args.decks and not args.fleet_dir:
        print("sirius-serve: nothing to do (no decks and no --fleet-dir)",
              file=sys.stderr)
        return 2
    for d in args.decks:
        if not os.path.isfile(d):
            print(f"sirius-serve: deck not found: {d}", file=sys.stderr)
            return 2

    import jax

    if args.platform:
        jax.config.update(
            "jax_platforms",
            "axon" if args.platform == "tpu" else args.platform,
        )

    import signal
    import threading

    eng = ServeEngine(num_slices=args.slices, verbose=True,
                      metrics_port=args.metrics_port,
                      events_path=args.events,
                      journal_path=args.journal,
                      queue_maxsize=args.queue_max,
                      job_wall_time_budget=args.budget,
                      poison_threshold=args.poison_threshold,
                      store_dir=args.store_dir,
                      dedup=False if args.no_dedup else None,
                      fleet_dir=args.fleet_dir,
                      engine_id=args.engine_id,
                      lease_ttl=args.lease_ttl,
                      fair_share=args.fair_share)
    drain = threading.Event()

    def _on_sigterm(signum, frame):
        # graceful drain: stop accepting, finish in-flight, leave the
        # rest (journaled) for the next process, exit 0
        print("sirius-serve: SIGTERM — draining", file=sys.stderr)
        drain.set()
        eng.queue.close()

    try:
        signal.signal(signal.SIGTERM, _on_sigterm)
    except ValueError:
        pass  # not the main thread (embedded use)
    eng.start()
    if eng.metrics_url:
        print(f"sirius-serve: metrics at {eng.metrics_url}/metrics",
              file=sys.stderr)
    if eng.replayed:
        print(f"sirius-serve: replayed {len(eng.replayed)} unfinished "
              f"job(s) from {args.journal}", file=sys.stderr)
    for rep in range(args.repeat):
        for path in args.decks:
            with open(path) as f:
                deck = json.load(f)
            name = os.path.splitext(os.path.basename(path))[0]
            eng.submit(
                deck, job_id=f"{name}-{rep}", priority=args.priority,
                deadline=(time.time() + args.deadline
                          if args.deadline else None),
                base_dir=os.path.dirname(os.path.abspath(path)) or ".",
                wall_time_budget=args.budget,
                tenant=args.tenant,
            )
    bar = time.time() + args.timeout
    ok = False
    while not drain.is_set():
        ok = eng.wait_all(timeout=0.5)
        if args.fleet_dir:
            # fleet mode serves until the SHARED queue is drained, not
            # just our own submissions (other processes feed it)
            ok = ok and eng.fleet.dir.all_terminal()
        if ok or time.time() > bar:
            break
    stats_obs = eng.metrics_snapshot()
    eng.shutdown(wait=True, mode="drain")
    stats = eng.stats()
    stats["obs"] = {k: v for k, v in stats_obs.items() if k != "stats"}
    stats["jobs"] = [j.to_dict() for j in eng._submitted]
    print(json.dumps(stats, indent=2, default=float))
    if args.stats_out:
        with open(args.stats_out, "w") as f:
            json.dump(stats, f, indent=2, default=float)
    if drain.is_set():
        print(f"sirius-serve: drained ({stats['num_drained']} job(s) left "
              f"in the journal)", file=sys.stderr)
        return 0
    if not ok:
        print("sirius-serve: timed out waiting for jobs", file=sys.stderr)
        return 3
    return 1 if stats["num_failed"] or stats["num_aborted"] else 0


if __name__ == "__main__":
    raise SystemExit(main())
