"""Executable cache: share jitted SCF programs across same-shape jobs.

Two levels:

- **Shape buckets** (`bucket_key`): every executable-relevant static shape
  of a deck — band/sphere/FFT/species dimensions plus the trace constants
  the fused step bakes in. Jobs in one bucket compile nothing after the
  first; `control.ngk_pad_quantum` rounds the |G+k| sphere up so decks
  with slightly different spheres coalesce.
- **Executables** (`get`): named jitted callables keyed by their full
  trace signature (dft/fused.py `_trace_signature`), LRU-evicted. The
  cached value for the fused step is a bound method of the first FusedScf
  in the bucket — its tables are program *inputs*, so reuse is exact.

Hit/miss counters are exported through utils/profiler.py (thread-local,
so each job's result reports its own), aggregated on the cache object
(cross-thread, what the engine's stats report), and mirrored into the
obs metrics registry for the /metrics endpoint. The jax.monitoring
backend-compile listener that "a cache hit means zero new executables"
is asserted against (tests/test_serve.py) now lives in obs/metrics.py,
where it also records trace/lowering duration histograms; the names
below stay as re-exports for existing callers.
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs.metrics import (  # noqa: F401  (back-compat re-exports)
    backend_compiles_this_thread,
    backend_compiles_total,
    install_jax_listeners as install_compile_listener,
)
from sirius_tpu.utils.profiler import counters


def bucket_key(cfg, ctx) -> tuple:
    """Shape bucket of a (config, context): every static dimension and
    trace constant that a jitted SCF program depends on. Two decks with
    equal keys run identical executables."""
    p = cfg.parameters
    uc = ctx.unit_cell
    return (
        ctx.gkvec.num_kpoints,
        ctx.num_spins,
        ctx.num_bands,
        ctx.gkvec.ngk_max,
        ctx.gvec.num_gvec,
        ctx.gvec_coarse.num_gvec,
        tuple(ctx.gvec.fft.dims),
        tuple(ctx.fft_coarse.dims),
        ctx.beta.num_beta_total,
        len(uc.atom_types),
        uc.num_atoms,
        0 if ctx.symmetry is None else ctx.symmetry.num_ops,
        round(float(uc.omega), 10),
        cfg.mixer.type,
        int(cfg.mixer.max_history),
        round(float(cfg.mixer.beta), 12),
        tuple(p.xc_functionals),
        ctx.num_mag_dims,
        p.precision_wf,
        str(cfg.control.device_scf),
    )


class ExecutableCache:
    """Thread-safe LRU of named jitted executables + bucket bookkeeping.

    capacity bounds the number of cached executables; evicting one drops
    the reference to the jitted callable (and, for the fused step, the
    FusedScf instance bound to it), letting XLA free the program.
    """

    def __init__(self, capacity: int = 32):
        self._lock = threading.RLock()
        self._exe: OrderedDict[tuple, object] = OrderedDict()
        self._buckets: dict[tuple, int] = {}
        self.capacity = int(capacity)
        self.hits = 0          # executable-level get() hits
        self.misses = 0
        self.job_hits = 0      # job/bucket-level (note_job)
        self.job_misses = 0
        install_compile_listener()
        self._m_exec = obs_metrics.REGISTRY.counter(
            "serve_cache_exec_total", "executable cache lookups")
        self._m_job = obs_metrics.REGISTRY.counter(
            "serve_cache_jobs_total", "job-level bucket lookups")

    # -- executable level ------------------------------------------------

    def get(self, sig: tuple, builder):
        """Return the cached executable for ``sig``, building (and
        caching) it with ``builder()`` on a miss."""
        with self._lock:
            if sig in self._exe:
                self._exe.move_to_end(sig)
                self.hits += 1
                counters["serve.cache.exec_hit"] += 1
                self._m_exec.inc(outcome="hit")
                return self._exe[sig]
            self.misses += 1
            counters["serve.cache.exec_miss"] += 1
            self._m_exec.inc(outcome="miss")
            exe = builder()
            self._exe[sig] = exe
            while len(self._exe) > self.capacity:
                self._exe.popitem(last=False)
                counters["serve.cache.evictions"] += 1
            return exe

    # -- job / bucket level ----------------------------------------------

    def note_job(self, key: tuple) -> bool:
        """Record a job landing in shape bucket ``key``; True when the
        bucket is warm (a previous job already compiled for it)."""
        with self._lock:
            warm = key in self._buckets
            self._buckets[key] = self._buckets.get(key, 0) + 1
            if warm:
                self.job_hits += 1
                counters["serve.cache.job_hit"] += 1
                self._m_job.inc(outcome="hit")
            else:
                self.job_misses += 1
                counters["serve.cache.job_miss"] += 1
                self._m_job.inc(outcome="miss")
            return warm

    def stats(self) -> dict:
        with self._lock:
            total = self.job_hits + self.job_misses
            return {
                "exec_hits": self.hits,
                "exec_misses": self.misses,
                "job_hits": self.job_hits,
                "job_misses": self.job_misses,
                "hit_rate": (self.job_hits / total) if total else 0.0,
                "num_buckets": len(self._buckets),
                "num_executables": len(self._exe),
                "backend_compiles": backend_compiles_total(),
            }
