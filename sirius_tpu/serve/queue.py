"""Priority job queue with deadlines and per-job lifecycle events.

Jobs carry the same JSON deck dict that cli.py consumes. Lifecycle:
queued -> compiling -> running -> done | failed | aborted; every
transition is appended to ``job.events`` as (timestamp, status, detail)
so a client can reconstruct what happened to its job. Higher ``priority``
pops first; among equal priorities the earlier ``deadline`` (then FIFO
order) wins. A job whose deadline has already passed when it reaches the
front is aborted instead of run — serving semantics: a late answer is a
wrong answer.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time

from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics

_TRANSITIONS = obs_metrics.REGISTRY.counter(
    "serve_job_transitions_total", "job lifecycle transitions by status")
_STATE_SECONDS = obs_metrics.REGISTRY.histogram(
    "serve_job_state_seconds", "time spent in each job state")
_LATENCY = obs_metrics.REGISTRY.histogram(
    "serve_job_latency_seconds", "submit-to-terminal job latency")
_DEPTH = obs_metrics.REGISTRY.gauge(
    "serve_queue_depth", "jobs waiting in the queue")
_DEPTH_HW = obs_metrics.REGISTRY.gauge(
    "serve_queue_depth_high_water", "max queue depth seen this process")


class JobStatus:
    QUEUED = "queued"
    COMPILING = "compiling"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"
    ABORTED = "aborted"


class Job:
    """One SCF request: a deck dict plus scheduling metadata."""

    def __init__(self, deck: dict, job_id: str | None = None,
                 base_dir: str = ".", priority: int = 0,
                 deadline: float | None = None, max_retries: int = 2):
        self.id = job_id or f"job-{id(self):x}"
        self.deck = deck
        self.base_dir = base_dir
        self.priority = int(priority)
        self.deadline = deadline  # absolute time.time() bar, None = none
        self.max_retries = int(max_retries)
        self.status = JobStatus.QUEUED
        self.events: list[tuple[float, str, str]] = []
        self.result: dict | None = None
        self.error: str | None = None
        self.permanent = False  # classified non-retryable (bad input)
        self.attempts = 0
        self.resume_path: str | None = None  # autosave to resume from
        self.submitted_at: float | None = None
        self.started_at: float | None = None
        self.finished_at: float | None = None
        self._done = threading.Event()

    def _transition(self, status: str, detail: str = "") -> None:
        now = time.time()
        if self.events:
            prev_t, prev_status, _ = self.events[-1]
            _STATE_SECONDS.observe(now - prev_t, state=prev_status)
        self.status = status
        self.events.append((now, status, detail))
        _TRANSITIONS.inc(status=status)
        obs_events.emit("job_transition", job_id=self.id, status=status,
                        detail=detail, attempt=self.attempts)
        if status in (JobStatus.DONE, JobStatus.FAILED, JobStatus.ABORTED):
            self.finished_at = now
            if self.submitted_at is not None:
                _LATENCY.observe(now - self.submitted_at, outcome=status)
            self._done.set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the job reaches a terminal status."""
        return self._done.wait(timeout)

    @property
    def latency(self) -> float | None:
        """Submit-to-terminal wall time (the serving latency metric)."""
        if self.submitted_at is None or self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "status": self.status,
            "priority": self.priority,
            "attempts": self.attempts,
            "latency_s": self.latency,
            "error": self.error,
            "permanent": self.permanent,
            "events": [
                {"t": t, "status": s, "detail": d} for t, s, d in self.events
            ],
        }


class JobQueue:
    """Thread-safe priority queue (highest priority first, then earliest
    deadline, then submit order)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._heap: list[tuple] = []
        self._seq = itertools.count()
        self._closed = False
        self.jobs: dict[str, Job] = {}
        self.high_water = 0

    def _depth_changed_locked(self) -> None:
        depth = len(self._heap)
        if depth > self.high_water:
            self.high_water = depth
        _DEPTH.set(depth)
        _DEPTH_HW.max(depth)

    def submit(self, job: Job) -> Job:
        with self._not_empty:
            if self._closed:
                raise RuntimeError("queue is closed")
            job.submitted_at = time.time()
            job._transition(JobStatus.QUEUED)
            self.jobs[job.id] = job
            heapq.heappush(self._heap, (
                -job.priority,
                job.deadline if job.deadline is not None else float("inf"),
                next(self._seq),
                job,
            ))
            self._depth_changed_locked()
            self._not_empty.notify()
        return job

    def requeue(self, job: Job, detail: str = "") -> None:
        """Put a transiently-failed job back (retry/resume path)."""
        with self._not_empty:
            if self._closed:
                job._transition(JobStatus.ABORTED, "queue closed")
                return
            job._transition(JobStatus.QUEUED, detail)
            heapq.heappush(self._heap, (
                -job.priority,
                job.deadline if job.deadline is not None else float("inf"),
                next(self._seq),
                job,
            ))
            self._depth_changed_locked()
            self._not_empty.notify()

    def pop(self, timeout: float | None = None) -> Job | None:
        """Next runnable job; None on timeout or when closed and drained.
        Deadline-expired jobs are aborted here, never returned."""
        deadline = None if timeout is None else time.time() + timeout
        with self._not_empty:
            while True:
                while self._heap:
                    _, _, _, job = heapq.heappop(self._heap)
                    self._depth_changed_locked()
                    if (job.deadline is not None
                            and time.time() > job.deadline):
                        job._transition(
                            JobStatus.ABORTED, "deadline expired in queue")
                        continue
                    return job
                if self._closed:
                    return None
                if deadline is None:
                    self._not_empty.wait()
                else:
                    remaining = deadline - time.time()
                    if remaining <= 0 or not self._not_empty.wait(remaining):
                        return None

    def close(self) -> None:
        """Stop accepting work; blocked pop() calls drain then return
        None."""
        with self._not_empty:
            self._closed = True
            self._not_empty.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)
