"""Priority job queue with deadlines, admission control, retry backoff
and per-job lifecycle events.

Jobs carry the same JSON deck dict that cli.py consumes. Lifecycle:
queued -> compiling -> running -> done | failed | aborted; every
transition is appended to ``job.events`` as (timestamp, status, detail)
so a client can reconstruct what happened to its job. Higher ``priority``
pops first; among equal priorities the earlier ``deadline`` (then FIFO
order) wins. A job whose deadline has already passed when it reaches the
front is aborted instead of run — serving semantics: a late answer is a
wrong answer.

Fault-tolerance semantics (ISSUE 8):

- **Backoff.** ``job.not_before`` is an absolute wall-clock bar that
  ``pop()`` honors: a retried job sleeps *in the queue* (the worker is
  free to run other jobs) until its backoff expires. The scheduler
  clamps ``not_before`` to the job deadline, so backoff can never push a
  job past the point where it would be aborted unrun.
- **Admission control.** ``JobQueue(maxsize=N)`` bounds the number of
  queued entries; ``submit`` either rejects immediately with
  ``QueueFullError`` or, with ``block=True``, waits up to ``timeout``
  for space. ``requeue`` (retries, watchdog hand-backs, journal replays)
  bypasses the bound — work the engine already accepted is never
  rejected.
- **Deterministic close.** ``close()`` stops admissions; blocked
  ``pop()`` calls drain then return None. ``abort_pending()`` empties
  the heap and transitions every entry terminally — the engine calls it
  on ``drain``/``abort`` shutdown and again after the workers have
  exited, so a close racing a worker's exit can never strand a job in
  QUEUED with ``wait_all()`` blocked on it.
- **Terminal transitions are final.** ``Job._transition`` ignores any
  transition after done/failed/aborted — a hung worker abandoned by the
  watchdog cannot resurrect or clobber a job that was already requeued,
  quarantined, or drained.

Campaign DAG semantics (ISSUE 10):

- **Dependency-aware admission.** A job with ``parents`` becomes
  poppable only once every parent is terminal-DONE. ``pop()`` defers
  dependency-blocked entries exactly like backoff-deferred ones; a
  terminal transition on any job notifies ``_not_empty`` so a worker
  promptly re-scans the heap for newly-unblocked children.
- **Upstream-failure propagation.** A parent that ends failed, aborted
  or skipped transitions the child to the terminal
  ``SKIPPED_UPSTREAM`` status inside ``pop()`` — the cascade is lazy
  (evaluated when the child reaches the front) and transitive: a
  skipped parent skips its own children in turn.
- **External parents.** After a journal replay, a child's parent may
  have finished in a previous process and so never re-enters
  ``jobs``. ``external_parent_status`` (job_id -> terminal status,
  populated by the engine from the journal) resolves those edges; an
  unknown parent is treated as satisfied rather than deadlocking the
  child forever.

Multi-tenant fair share (ISSUE 19):

- **Tenant identity.** Every job carries a ``tenant`` id (defaulting to
  ``"default"``); ``set_tenant`` registers a weight and an optional
  per-tenant queue quota.
- **Per-tenant admission control.** A tenant at its ``max_queued``
  quota is rejected with ``QueueFullError`` naming the tenant — one
  tenant flooding the queue can exhaust its own quota but never the
  global bound for everyone else. Quota rejections are immediate
  (admission control is a per-tenant verdict, not a capacity wait);
  ``block=True`` only ever waits on the global bound.
- **Weighted deficit round robin.** With ``fair_share=True``, ``pop``
  picks among the front-runnable job of each tenant by deficit round
  robin: a round-robin pointer grants each tenant its weight in service
  quantum on arrival and keeps serving that tenant while it has at
  least one quantum banked, so a weight-2 tenant gets twice the pops of
  a weight-1 tenant under contention while an idle tenant banks
  nothing. Within a tenant the existing priority/deadline/FIFO order is
  untouched; with ``fair_share=False`` (the default) cross-tenant order
  is the existing global priority order, bit-for-bit.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
import uuid

from sirius_tpu.obs import events as obs_events
from sirius_tpu.obs import metrics as obs_metrics
from sirius_tpu.obs.log import get_logger

logger = get_logger("serve")

_TRANSITIONS = obs_metrics.REGISTRY.counter(
    "serve_job_transitions_total", "job lifecycle transitions by status")
_STATE_SECONDS = obs_metrics.REGISTRY.histogram(
    "serve_job_state_seconds", "time spent in each job state")
_LATENCY = obs_metrics.REGISTRY.histogram(
    "serve_job_latency_seconds", "submit-to-terminal job latency")
_DEPTH = obs_metrics.REGISTRY.gauge(
    "serve_queue_depth", "jobs waiting in the queue")
_DEPTH_HW = obs_metrics.REGISTRY.gauge(
    "serve_queue_depth_high_water", "max queue depth seen this process")
_REJECTED = obs_metrics.REGISTRY.counter(
    "serve_queue_rejected_total", "submissions rejected by admission control")
_TENANT_DEPTH = obs_metrics.REGISTRY.gauge(
    "serve_tenant_queue_depth", "jobs waiting in the queue per tenant")


class QueueFullError(RuntimeError):
    """The bounded queue rejected a submission (admission control)."""


class JobStatus:
    QUEUED = "queued"
    COMPILING = "compiling"
    RUNNING = "running"
    DONE = "done"
    FAILED = "failed"
    ABORTED = "aborted"
    # terminal state of a campaign node whose upstream dependency ended
    # failed/aborted/skipped: the node never ran and never will
    SKIPPED_UPSTREAM = "skipped_upstream"


TERMINAL = (JobStatus.DONE, JobStatus.FAILED, JobStatus.ABORTED,
            JobStatus.SKIPPED_UPSTREAM)


class Job:
    """One SCF request: a deck dict plus scheduling metadata."""

    def __init__(self, deck: dict, job_id: str | None = None,
                 base_dir: str = ".", priority: int = 0,
                 deadline: float | None = None, max_retries: int = 2,
                 wall_time_budget: float | None = None,
                 parents: list[str] | None = None,
                 campaign_id: str | None = None,
                 node_id: str | None = None,
                 handoff_in: dict | None = None,
                 handoff_out: str | None = None,
                 trace_id: str | None = None,
                 tenant: str = "default",
                 canon_hash: str | None = None):
        # uuid, NOT id(self): default ids must be unique across the
        # engine *processes* of a fleet sharing one work directory —
        # id() is a heap address, reused within a process after GC and
        # trivially colliding between processes, which would cross-wire
        # job-scoped autosave files
        self.id = job_id or f"job-{uuid.uuid4().hex[:12]}"
        self.deck = deck
        self.base_dir = base_dir
        self.priority = int(priority)
        self.deadline = deadline  # absolute time.time() bar, None = none
        self.max_retries = int(max_retries)
        # per-attempt wall-time budget enforced by the supervisor watchdog
        # (None falls back to the scheduler default; 0/None = unbounded)
        self.wall_time_budget = wall_time_budget
        # campaign DAG metadata: this job is poppable only once every id
        # in ``parents`` is terminal-DONE; a failed parent skips it
        self.parents = list(parents) if parents else []
        self.campaign_id = campaign_id
        self.node_id = node_id
        # handoff_in: {"path", "displaced", "adopt_positions"} — load the
        # parent artifact at ``path`` as run_scf(initial_guess=);
        # handoff_out: artifact path this job writes on DONE
        self.handoff_in = dict(handoff_in) if handoff_in else None
        self.handoff_out = handoff_out
        # end-to-end trace identity (obs/tracing.py): assigned by the
        # engine before journaling so SIGKILL+replay keeps the same trace;
        # campaigns pass one id for the whole DAG
        self.trace_id = trace_id
        # fair-share identity: which tenant's quota/weight this job
        # counts against (ISSUE 19)
        self.tenant = tenant or "default"
        # content address of the deck (fleet/canon.py), set by the
        # engine when dedup is on: keys the result store and in-flight
        # watcher attachment
        self.canon_hash = canon_hash
        self.status = JobStatus.QUEUED
        self.events: list[tuple[float, str, str]] = []
        self.result: dict | None = None
        self.error: str | None = None
        self.permanent = False  # classified non-retryable (bad input)
        self.quarantined = False  # poisoned: killed/stalled its workers
        self.attempts = 0
        self.poison_strikes = 0  # watchdog strikes (crash/hang) against it
        # OOM degradation level (utils/devfail.py apply_oom_hint): bumped
        # by the scheduler when an attempt dies of HBM exhaustion below
        # the in-run ladder's reach; the next attempt starts pre-degraded
        self.oom_degrade = 0
        self.resume_path: str | None = None  # autosave to resume from
        self.not_before: float | None = None  # backoff bar honored by pop()
        self.submitted_at: float | None = None
        self.started_at: float | None = None
        self.finished_at: float | None = None
        # drained jobs are terminal in-process but deliberately left
        # non-terminal in the journal so a restart re-runs them
        self.leave_in_journal = False
        # bumped when the watchdog takes the job away from a worker;
        # workers capture the epoch at pickup and discard stale results
        self._epoch = 0
        self._cfg = None  # parsed Config cached by the scheduler (retries)
        # fired in order on the terminal transition (journal record,
        # queue dependency wakeup, engine wait_all notify, ...)
        self._terminal_hooks: list = []
        self._done = threading.Event()

    def add_terminal_hook(self, hook) -> None:
        """Register ``hook(job)`` to fire once on the terminal transition
        (idempotent: re-registering the same hook is a no-op). A hook
        added AFTER the job settled fires immediately — a watcher
        attaching to an in-flight leader must not miss the answer to a
        race it cannot see."""
        if hook in self._terminal_hooks:
            return
        self._terminal_hooks.append(hook)
        if self.status in TERMINAL:
            try:
                hook(self)
            except Exception:
                logger.exception(
                    "job %s late terminal hook failed", self.id)

    def _transition(self, status: str, detail: str = "") -> None:
        if self.status in TERMINAL:
            # final means final: an abandoned worker finishing late, or a
            # drain racing a retry, must not resurrect a settled job
            logger.debug("job %s: ignoring %s after terminal %s",
                         self.id, status, self.status)
            return
        now = time.time()
        if self.events:
            prev_t, prev_status, _ = self.events[-1]
            _STATE_SECONDS.observe(now - prev_t, state=prev_status)
        self.status = status
        self.events.append((now, status, detail))
        _TRANSITIONS.inc(status=status)
        extra = {"campaign_id": self.campaign_id} if self.campaign_id else {}
        if self.trace_id:
            extra["trace_id"] = self.trace_id
        obs_events.emit("job_transition", job_id=self.id, status=status,
                        detail=detail, attempt=self.attempts, **extra)
        if status in TERMINAL:
            self.finished_at = now
            if self.submitted_at is not None:
                _LATENCY.observe(now - self.submitted_at, outcome=status)
            for hook in list(self._terminal_hooks):
                try:
                    hook(self)
                except Exception as e:
                    # deliberately broad: the remaining hooks and
                    # _done.set() below MUST still run (a raising hook
                    # would strand wait_all() forever) — but a
                    # device-class error surfacing in a hook is hardware
                    # news, escalated instead of drowned in a traceback
                    from sirius_tpu.utils import devfail

                    cls = devfail.classify(e)
                    if cls in ("oom", "device_lost"):
                        logger.critical(
                            "job %s terminal hook hit a device-class "
                            "failure (%s): %s", self.id, cls, e)
                    else:
                        logger.exception(
                            "job %s terminal hook failed", self.id)
            self._done.set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the job reaches a terminal status."""
        return self._done.wait(timeout)

    @property
    def terminal(self) -> bool:
        return self.status in TERMINAL

    @property
    def latency(self) -> float | None:
        """Submit-to-terminal wall time (the serving latency metric)."""
        if self.submitted_at is None or self.finished_at is None:
            return None
        return self.finished_at - self.submitted_at

    def to_dict(self) -> dict:
        return {
            "id": self.id,
            "status": self.status,
            "trace_id": self.trace_id,
            "tenant": self.tenant,
            "canon_hash": self.canon_hash,
            "campaign_id": self.campaign_id,
            "node_id": self.node_id,
            "parents": list(self.parents),
            "priority": self.priority,
            "attempts": self.attempts,
            "poison_strikes": self.poison_strikes,
            "oom_degrade": self.oom_degrade,
            "latency_s": self.latency,
            "error": self.error,
            "permanent": self.permanent,
            "quarantined": self.quarantined,
            "events": [
                {"t": t, "status": s, "detail": d} for t, s, d in self.events
            ],
        }


class JobQueue:
    """Thread-safe priority queue (highest priority first, then earliest
    deadline, then submit order), with optional bounded admission."""

    def __init__(self, maxsize: int = 0, fair_share: bool = False,
                 tenants: dict[str, dict] | None = None):
        # reentrant: a terminal transition inside pop() (deadline abort,
        # upstream-skip propagation) fires hooks that may re-enter the
        # queue lock to wake dependency waiters
        self._lock = threading.RLock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._heap: list[tuple] = []
        self._seq = itertools.count()
        self._closed = False
        self.maxsize = int(maxsize)  # 0 = unbounded
        self.jobs: dict[str, Job] = {}
        # journal-replay edge resolution: terminal statuses of jobs that
        # finished in a previous process and are not in ``jobs``
        self.external_parent_status: dict[str, str] = {}
        self.high_water = 0
        # -- multi-tenant fair share (all guarded by self._lock) --------
        self.fair_share = bool(fair_share)
        # tenant -> {"weight": float, "max_queued": int|None}
        self._tenants: dict[str, dict] = {}
        self._queued_by_tenant: dict[str, int] = {}
        # DRR state: banked service quantum per tenant, the tenant the
        # pointer is currently spending on, and the last tenant the
        # pointer visited (ring position for the next advance)
        self._drr_deficit: dict[str, float] = {}
        self._drr_current: str | None = None
        self._drr_last: str | None = None
        for name, policy in (tenants or {}).items():
            if isinstance(policy, (int, float)):
                policy = {"weight": policy}  # bare-weight shorthand
            self.set_tenant(name, **dict(policy))

    def set_tenant(self, name: str, weight: float = 1.0,
                   max_queued: int | None = None) -> None:
        """Register (or update) a tenant's fair-share weight and queue
        quota. Unregistered tenants serve at weight 1 with no quota."""
        with self._lock:
            self._tenants[str(name)] = {
                "weight": max(float(weight), 1e-9),
                "max_queued": int(max_queued) if max_queued else None,
            }

    @property
    def closed(self) -> bool:
        """True once close() was called (no further admissions)."""
        return self._closed

    def _depth_changed_locked(self) -> None:
        depth = len(self._heap)
        if depth > self.high_water:
            self.high_water = depth
        _DEPTH.set(depth)
        _DEPTH_HW.max(depth)

    def _wake_on_terminal(self, job: Job) -> None:
        """Job terminal hook: a terminal transition may unblock
        dependency-deferred children, so re-wake every pop() waiter."""
        with self._lock:
            self._not_empty.notify_all()

    def _dep_state_locked(self, job: Job):
        """None when every parent is DONE (or unknown — resolved as
        satisfied so a half-replayed graph cannot deadlock); otherwise
        ``("wait"|"skip", parent_id, parent_status)``."""
        for pid in job.parents:
            parent = self.jobs.get(pid)
            status = (parent.status if parent is not None
                      else self.external_parent_status.get(pid))
            if status is None or status == JobStatus.DONE:
                continue
            if status in TERMINAL:
                return ("skip", pid, status)
            return ("wait", pid, status)
        return None

    def _tenant_count_locked(self, tenant: str, delta: int) -> None:
        n = self._queued_by_tenant.get(tenant, 0) + delta
        self._queued_by_tenant[tenant] = max(n, 0)
        _TENANT_DEPTH.set(max(n, 0), tenant=tenant)

    def _push_locked(self, job: Job) -> None:
        heapq.heappush(self._heap, (
            -job.priority,
            job.deadline if job.deadline is not None else float("inf"),
            next(self._seq),
            job,
        ))
        self._tenant_count_locked(job.tenant, +1)
        self._depth_changed_locked()
        self._not_empty.notify()

    def submit(self, job: Job, block: bool = False,
               timeout: float | None = None) -> Job:
        """Admit a new job. A bounded queue that is full rejects with
        QueueFullError immediately (``block=False``) or after waiting up
        to ``timeout`` seconds for space (``block=True``)."""
        bar = None if timeout is None else time.time() + timeout
        with self._not_empty:
            if self._closed:
                raise RuntimeError("queue is closed")
            # per-tenant quota first, and never blocking: the verdict is
            # about THIS tenant's backlog, which global space cannot fix
            policy = self._tenants.get(job.tenant)
            quota = policy.get("max_queued") if policy else None
            if quota and self._queued_by_tenant.get(job.tenant, 0) >= quota:
                _REJECTED.inc(mode="tenant")
                raise QueueFullError(
                    f"tenant {job.tenant!r} over quota "
                    f"({self._queued_by_tenant[job.tenant]}/{quota} queued)")
            while self.maxsize and len(self._heap) >= self.maxsize:
                if not block:
                    _REJECTED.inc(mode="immediate")
                    raise QueueFullError(
                        f"queue full ({len(self._heap)}/{self.maxsize})")
                remaining = None if bar is None else bar - time.time()
                if remaining is not None and remaining <= 0:
                    _REJECTED.inc(mode="timeout")
                    raise QueueFullError(
                        f"queue full ({len(self._heap)}/{self.maxsize}) "
                        f"after {timeout}s")
                self._not_full.wait(remaining)
                if self._closed:
                    raise RuntimeError("queue is closed")
            job.submitted_at = time.time()
            job.add_terminal_hook(self._wake_on_terminal)
            job._transition(JobStatus.QUEUED)
            self.jobs[job.id] = job
            self._push_locked(job)
        return job

    def requeue(self, job: Job, detail: str = "") -> None:
        """Put a transiently-failed job back (retry/resume/replay path).
        Exempt from the admission bound: this work was already accepted."""
        if job.terminal:
            return  # quarantined/drained while the retry was in flight
        with self._not_empty:
            if self._closed:
                job._transition(JobStatus.ABORTED, "queue closed")
                return
            job.add_terminal_hook(self._wake_on_terminal)
            job._transition(JobStatus.QUEUED, detail)
            self.jobs.setdefault(job.id, job)
            self._push_locked(job)

    def pop(self, timeout: float | None = None) -> Job | None:
        """Next runnable job; None on timeout or when closed and drained.
        Deadline-expired jobs are aborted here, never returned; jobs whose
        backoff bar (``not_before``) is still in the future stay queued.
        Dependency-blocked jobs (non-DONE parents) likewise stay queued
        until a parent's terminal transition wakes the waiters; a parent
        that ended failed/aborted/skipped terminally skips the child with
        ``SKIPPED_UPSTREAM`` instead of ever running it."""
        bar = None if timeout is None else time.time() + timeout
        with self._not_empty:
            while True:
                now = time.time()
                deferred: list[tuple] = []
                picked: Job | None = None
                next_ready: float | None = None
                # fair-share mode gathers the front-runnable entry of
                # EACH tenant (heap order within a tenant is preserved —
                # later same-tenant entries are deferred), then lets DRR
                # choose between tenants
                candidates: dict[str, tuple] = {}
                while self._heap:
                    entry = heapq.heappop(self._heap)
                    job = entry[3]
                    if (job.deadline is not None and now > job.deadline):
                        self._tenant_count_locked(job.tenant, -1)
                        self._depth_changed_locked()
                        self._not_full.notify()
                        job._transition(
                            JobStatus.ABORTED, "deadline expired in queue")
                        continue
                    if job.not_before is not None and job.not_before > now:
                        deferred.append(entry)
                        if next_ready is None or job.not_before < next_ready:
                            next_ready = job.not_before
                        continue
                    if job.parents:
                        dep = self._dep_state_locked(job)
                        if dep is not None:
                            state, pid, pstatus = dep
                            if state == "skip":
                                self._tenant_count_locked(job.tenant, -1)
                                self._depth_changed_locked()
                                self._not_full.notify()
                                job._transition(
                                    JobStatus.SKIPPED_UPSTREAM,
                                    f"parent {pid} {pstatus}")
                                continue
                            # parent still pending/running: stays queued
                            # until a terminal transition wakes us
                            deferred.append(entry)
                            continue
                    if not self.fair_share:
                        picked = job
                        break
                    if job.tenant in candidates:
                        deferred.append(entry)
                        continue
                    candidates[job.tenant] = entry
                if picked is None and candidates:
                    chosen = self._drr_pick_locked(candidates)
                    for tenant, entry in candidates.items():
                        if tenant == chosen:
                            picked = entry[3]
                        else:
                            deferred.append(entry)
                for entry in deferred:
                    heapq.heappush(self._heap, entry)
                if picked is not None:
                    self._tenant_count_locked(picked.tenant, -1)
                    self._depth_changed_locked()
                    self._not_full.notify()
                    return picked
                if self._closed and not self._heap:
                    return None
                # nothing runnable: wait for a submit, a backoff expiry,
                # or the caller's timeout — whichever comes first
                wait_until = bar
                if next_ready is not None:
                    wait_until = (next_ready if wait_until is None
                                  else min(wait_until, next_ready))
                if wait_until is None:
                    self._not_empty.wait()
                else:
                    remaining = wait_until - time.time()
                    expired = remaining <= 0 or not self._not_empty.wait(
                        remaining)
                    if expired and bar is not None and time.time() >= bar:
                        return None

    def _drr_pick_locked(self, candidates: dict[str, tuple]) -> str:
        """Weighted deficit round robin over the tenants that have a
        runnable job right now.

        The pointer grants a tenant ``weight`` service quantum when it
        ARRIVES there (not per pop) and keeps picking that tenant while
        it has >= 1 quantum banked, paying 1 per pop — so weight 2 vs 1
        yields a 2:1 pop ratio under sustained contention. Tenants with
        nothing runnable are dropped from the bank first: an idle tenant
        must not save up quantum and then starve everyone on return
        (classic DRR active-list semantics). Deficits are capped so
        fractional weights accumulate across visits without unbounded
        banking."""
        for tenant in list(self._drr_deficit):
            if tenant not in candidates:
                del self._drr_deficit[tenant]
        if self._drr_current not in candidates:
            self._drr_current = None
        ring = sorted(candidates)
        guard = 0
        while True:
            if self._drr_current is None:
                after = [t for t in ring if t > (self._drr_last or "")]
                tenant = after[0] if after else ring[0]
                self._drr_last = self._drr_current = tenant
                weight = (self._tenants.get(tenant) or {}).get("weight", 1.0)
                self._drr_deficit[tenant] = min(
                    self._drr_deficit.get(tenant, 0.0) + weight,
                    max(weight, 1.0) + 1.0)
            tenant = self._drr_current
            if self._drr_deficit.get(tenant, 0.0) >= 1.0:
                self._drr_deficit[tenant] -= 1.0
                return tenant
            self._drr_current = None
            guard += 1
            if guard > 1000 * len(ring):
                # unreachable with weights floored at 1e-9 in
                # set_tenant, but a scheduler must never spin forever
                logger.error("DRR failed to accumulate quantum; "
                             "falling back to first tenant")
                return ring[0]

    def abort_pending(self, detail: str,
                      leave_in_journal: bool = False) -> list[Job]:
        """Pop and terminally abort every queued entry (drain/abort
        shutdown, and the post-join safety net against close/worker-exit
        races). With ``leave_in_journal`` the jobs stay non-terminal in
        the engine journal so a restart re-runs them."""
        with self._not_empty:
            entries = self._heap
            self._heap = []
            for tenant in list(self._queued_by_tenant):
                self._tenant_count_locked(
                    tenant, -self._queued_by_tenant[tenant])
            self._depth_changed_locked()
            self._not_full.notify_all()
        out = []
        for entry in sorted(entries):
            job = entry[3]
            job.leave_in_journal = leave_in_journal
            job._transition(JobStatus.ABORTED, detail)
            out.append(job)
        return out

    def close(self) -> None:
        """Stop accepting work; blocked pop() calls drain then return
        None, blocked submit() calls fail."""
        with self._not_empty:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def __len__(self) -> int:
        with self._lock:
            return len(self._heap)
