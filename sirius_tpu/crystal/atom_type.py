"""Atom species: pseudopotential data parsed from the reference's JSON format.

The reference parses UPF-converted JSON species files in
src/unit_cell/atom_type.cpp:376-490 (read_pseudo_uspp / read_pseudo_paw);
the same files (verification/test*/ *.UPF.json) load here unchanged.

Structure of a species file:
  pseudo_potential:
    header: {element, z_valence, mesh_size, number_of_proj, l_max,
             pseudo_type: NC|US|USPP|PAW, core_correction, ...}
    radial_grid: [r_i]                    (bohr)
    local_potential: [V_loc(r_i)]         (Ha; UPF stores Ry -> converter halves)
    beta_projectors: [{angular_momentum, radial_function (r*beta),
                       cutoff_radius, ...}]
    D_ion: flattened (nbeta x nbeta)      (Ha)
    augmentation: [{i, j, angular_momentum, radial_function}]  (US/PAW)
    atomic_wave_functions: [{angular_momentum, occupation, radial_function,
                             label}]
    total_charge_density: [4 pi r^2 rho(r)]-like; see rho_at handling
    core_charge_density: [rho_core(r)]
    paw_data: {...}                        (PAW only)
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np


@dataclasses.dataclass
class BetaProjector:
    l: int
    rbeta: np.ndarray  # r * beta(r) on the (possibly truncated) radial grid
    nr: int  # number of grid points carried
    j: float | None = None  # total angular momentum (relativistic pseudos)


@dataclasses.dataclass
class AtomicWf:
    l: int
    occupation: float
    chi: np.ndarray  # chi(r) (UPF convention: r * phi(r))
    label: str = ""


@dataclasses.dataclass
class AugmentationChannel:
    i: int  # beta index
    j: int  # beta index (j >= i)
    l: int  # angular momentum of the expansion channel
    qr: np.ndarray  # Q_ij^l(r) radial function


@dataclasses.dataclass
class AtomType:
    label: str
    symbol: str
    zn: float  # valence charge z_valence
    pseudo_type: str  # NC | US | PAW
    r: np.ndarray  # radial grid
    vloc: np.ndarray  # local potential V_loc(r) [Ha]
    beta: list[BetaProjector]
    d_ion: np.ndarray  # (nbeta, nbeta) [Ha]
    augmentation: list[AugmentationChannel]
    atomic_wfs: list[AtomicWf]
    rho_total: np.ndarray | None  # free-atom valence charge (UPF: 4 pi r^2 rho)
    rho_core: np.ndarray | None  # core charge density rho_core(r)
    core_correction: bool
    paw: dict | None = None
    paw_core_energy: float = 0.0
    cutoff_radius_index: int | None = None  # PAW partial-wave truncation
    mass: float = 0.0  # atomic mass [amu] from the species file (0 = unset)

    @property
    def mass_amu(self) -> float:
        """Atomic mass [amu] for dynamics: the species-file value when
        present, else the standard atomic weight of the element symbol
        (reference atom_type mass handling: UPF header mass with the
        periodic-table fallback)."""
        if self.mass > 0.0:
            return float(self.mass)
        from sirius_tpu.lapw.free_atom import MASSES, SYMBOLS

        sym = self.symbol.strip()
        if sym in SYMBOLS:
            return float(MASSES[SYMBOLS.index(sym)])
        raise ValueError(
            f"atom type '{self.label}': no mass in the species file and "
            f"symbol '{sym}' is not a known element — set "
            "pseudo_potential.header.mass"
        )

    @property
    def spin_orbit(self) -> bool:
        """Relativistic (j-resolved) projectors present (reference
        atom_type spin_orbit_coupling, set from the UPF header)."""
        return any(b.j is not None for b in self.beta)

    @property
    def num_beta(self) -> int:
        return len(self.beta)

    @property
    def lmax_beta(self) -> int:
        return max((b.l for b in self.beta), default=-1)

    @property
    def num_beta_lm(self) -> int:
        """Total projectors counting m-degeneracy: the xi index."""
        return sum(2 * b.l + 1 for b in self.beta)

    def beta_lm_table(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Flattened xi -> (radial index, l, m) maps, ordered per projector
        then m = -l..l (reference basis_functions_index convention)."""
        idxrf, ls, ms = [], [], []
        for i, b in enumerate(self.beta):
            for m in range(-b.l, b.l + 1):
                idxrf.append(i)
                ls.append(b.l)
                ms.append(m)
        return np.asarray(idxrf), np.asarray(ls), np.asarray(ms)

    @property
    def num_atomic_wf_lm(self) -> int:
        return sum(2 * w.l + 1 for w in self.atomic_wfs)

    @staticmethod
    def from_file(label: str, path: str) -> "AtomType":
        if path.lower().endswith(".upf"):
            # raw UPF v2: convert in-process (same code path as the
            # sirius-upf-to-json CLI); deck dirs may be read-only, so the
            # converted dict stays in memory
            from sirius_tpu.io.upf import upf2_to_json

            return AtomType.from_dict(label, upf2_to_json(path))
        with open(path) as f:
            data = json.load(f)
        return AtomType.from_dict(label, data)

    @staticmethod
    def from_dict(label: str, data: dict) -> "AtomType":
        pp = data["pseudo_potential"]
        h = pp["header"]
        r = np.asarray(pp["radial_grid"], dtype=np.float64)
        nr = len(r)
        vloc = np.asarray(pp["local_potential"], dtype=np.float64)
        betas = []
        for b in pp.get("beta_projectors", []):
            rb = np.asarray(b["radial_function"], dtype=np.float64)
            betas.append(
                BetaProjector(
                    l=int(b["angular_momentum"]), rbeta=rb, nr=len(rb),
                    j=(
                        float(b["total_angular_momentum"])
                        if "total_angular_momentum" in b
                        else None
                    ),
                )
            )
        nb = len(betas)
        d_ion = np.asarray(pp.get("D_ion", np.zeros(nb * nb)), dtype=np.float64).reshape(nb, nb) if nb else np.zeros((0, 0))
        aug = []
        for a in pp.get("augmentation", []):
            aug.append(
                AugmentationChannel(
                    i=int(a["i"]),
                    j=int(a["j"]),
                    l=int(a["angular_momentum"]),
                    qr=np.asarray(a["radial_function"], dtype=np.float64)[:nr],
                )
            )
        wfs = []
        for w in pp.get("atomic_wave_functions", []):
            wfs.append(
                AtomicWf(
                    l=int(w["angular_momentum"]),
                    occupation=float(w.get("occupation", 0.0)),
                    chi=np.asarray(w["radial_function"], dtype=np.float64)[:nr],
                    label=w.get("label", ""),
                )
            )
        ptype = h.get("pseudo_type", "NC")
        if ptype in ("US", "USPP", "SL", "1/r"):
            ptype = "US" if aug else "NC"
        rho_tot = pp.get("total_charge_density")
        rho_core = pp.get("core_charge_density")
        return AtomType(
            label=label,
            symbol=h.get("element", label).strip(),
            zn=float(h["z_valence"]),
            pseudo_type="PAW" if h.get("pseudo_type") == "PAW" else ptype,
            r=r,
            vloc=vloc,
            beta=betas,
            d_ion=d_ion,
            augmentation=aug,
            atomic_wfs=wfs,
            rho_total=np.asarray(rho_tot, dtype=np.float64) if rho_tot is not None else None,
            rho_core=np.asarray(rho_core, dtype=np.float64)[:nr] if rho_core is not None else None,
            core_correction=bool(h.get("core_correction", False)),
            mass=float(h.get("mass", data.get("mass", 0.0)) or 0.0),
            paw=pp.get("paw_data"),
            paw_core_energy=float(h.get("paw_core_energy", 0.0)),
            cutoff_radius_index=(
                int(h["cutoff_radius_index"]) if "cutoff_radius_index" in h else None
            ),
        )
