"""Unit cell: lattice, atoms, species (reference: src/unit_cell/unit_cell.cpp).

Positions are fractional; lattice rows are a_i in bohr. Construction from the
reference JSON deck format (unit_cell section of sirius.json) is supported
directly, including per-atom initial magnetic moments encoded as positions
with 6 entries [x, y, z, mx, my, mz].
"""

from __future__ import annotations

import dataclasses
import os

import numpy as np

from sirius_tpu.config.schema import UnitCellConfig
from sirius_tpu.crystal.atom_type import AtomType


@dataclasses.dataclass
class UnitCell:
    lattice: np.ndarray  # (3,3) rows a_i [bohr]
    atom_types: list[AtomType]
    type_of_atom: np.ndarray  # (natom,) index into atom_types
    positions: np.ndarray  # (natom, 3) fractional
    moments: np.ndarray  # (natom, 3) initial magnetic moment (mu_B, cartesian)

    @property
    def num_atoms(self) -> int:
        return len(self.positions)

    @property
    def omega(self) -> float:
        return float(abs(np.linalg.det(self.lattice)))

    @property
    def num_valence_electrons(self) -> float:
        return float(sum(self.atom_types[t].zn for t in self.type_of_atom))

    def atoms_of_type(self, it: int) -> np.ndarray:
        return np.nonzero(self.type_of_atom == it)[0]

    def positions_cart(self) -> np.ndarray:
        return self.positions @ self.lattice

    @staticmethod
    def from_config(uc: UnitCellConfig, base_dir: str = ".") -> "UnitCell":
        lattice = np.asarray(uc.lattice_vectors, dtype=np.float64) * uc.lattice_vectors_scale
        types: list[AtomType] = []
        type_index: dict[str, int] = {}
        for lbl in uc.atom_types:
            if lbl in getattr(uc, "atom_data", {}):
                # array-built in-memory species (C API construction path)
                types.append(AtomType.from_dict(lbl, uc.atom_data[lbl]))
                type_index[lbl] = len(types) - 1
                continue
            fname = uc.atom_files.get(lbl, "")
            path = fname if os.path.isabs(fname) else os.path.join(base_dir, fname)
            if (not path.lower().endswith(".json")) and os.path.exists(path + ".json"):
                # decks may reference a raw UPF name with a converted
                # <name>.json alongside; prefer the JSON (the converter in
                # io/upf.py produces the same layout)
                path = path + ".json"
            elif not os.path.exists(path) and os.path.exists(path + ".json"):
                path = path + ".json"
            # raw .UPF paths with no converted sibling fall through:
            # AtomType.from_file converts them in-process
            types.append(AtomType.from_file(lbl, path))
            type_index[lbl] = len(types) - 1
        t_of_a, pos, mom = [], [], []
        unknown = [l for l in uc.atoms if l not in type_index]
        if unknown:
            raise ValueError(
                f"atom label(s) {unknown} in unit_cell.atoms have no entry "
                "in unit_cell.atom_types / atom_files"
            )
        if len(set(uc.atom_types)) != len(uc.atom_types):
            raise ValueError(
                f"duplicate label(s) in unit_cell.atom_types: {uc.atom_types}"
            )
        # reference atom enumeration follows the atom_types list order, not
        # the "atoms" dict insertion order (forces/moments are reported per
        # atom in that order)
        for lbl in [l for l in uc.atom_types if l in uc.atoms]:
            plist = uc.atoms[lbl]
            for p in plist:
                p = list(p)
                t_of_a.append(type_index[lbl])
                pos.append(p[:3])
                mom.append(p[3:6] if len(p) >= 6 else [0.0, 0.0, 0.0])
        if uc.atom_coordinate_units.startswith("au"):
            pos = (np.asarray(pos, dtype=np.float64) @ np.linalg.inv(lattice)).tolist()
        elif uc.atom_coordinate_units.startswith("A"):
            pos = (np.asarray(pos, dtype=np.float64) / 0.52917721067 @ np.linalg.inv(lattice)).tolist()
        return UnitCell(
            lattice=lattice,
            atom_types=types,
            type_of_atom=np.asarray(t_of_a, dtype=np.int32),
            positions=np.mod(np.asarray(pos, dtype=np.float64), 1.0),
            moments=np.asarray(mom, dtype=np.float64),
        )
