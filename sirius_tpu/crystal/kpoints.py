"""Monkhorst-Pack k-mesh generation and symmetry reduction to the IBZ.

Reference: K_point_set::create_k_mesh (src/k_point/k_point_set.cpp:77) via
spglib's get_irreducible_reciprocal_mesh. Here the orbit reduction is done
with exact integer arithmetic: k_i = (2 g_i + s_i) / (2 n_i) is represented
on the common denominator D = 2 lcm(n) as the integer vector
J_i = (2 g_i + s_i) L / n_i (L = lcm(n)); the reciprocal rotations
W_k = (W^{-1})^T (integer) and time reversal (-J) then act exactly, and a
rotated point participates in the reduction only when it lands back on the
grid (anisotropic grids may break some lattice ops).
"""

from __future__ import annotations

import math

import numpy as np

from sirius_tpu.crystal.symmetry import CrystalSymmetry


def irreducible_kmesh(
    ngridk: list[int],
    shiftk: list[int],
    sym: CrystalSymmetry | None,
    use_symmetry: bool = True,
    time_reversal: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Returns (kpoints [nk_irr, 3] fractional in [0,1), weights summing to 1)."""
    n = np.asarray(ngridk, dtype=np.int64)
    s = np.asarray(shiftk, dtype=np.int64)
    L = math.lcm(*[int(x) for x in n])
    D = 2 * L
    ii, jj, kk = np.meshgrid(*[np.arange(m) for m in n], indexing="ij")
    grid_i = np.stack([ii.ravel(), jj.ravel(), kk.ravel()], axis=1)  # (nk, 3)
    J = (2 * grid_i + s[None, :]) * (L // n)[None, :]  # scaled ints mod D
    nk = len(J)
    index = {tuple(v): i for i, v in enumerate(np.mod(J, D))}

    rots = [np.eye(3, dtype=np.int64)]
    if use_symmetry and sym is not None:
        rots = [op.w_k for op in sym.ops]
    if time_reversal:
        rots = rots + [-r for r in rots]

    images = np.stack([np.mod(J @ r.T, D) for r in rots])  # (nrot, nk, 3)

    rep = np.full(nk, -1, dtype=np.int64)
    weights = []
    reps = []
    for i in range(nk):
        if rep[i] >= 0:
            continue
        # BFS over the orbit of i
        orbit = {i}
        stack = [i]
        while stack:
            p = stack.pop()
            for r in range(len(rots)):
                q = index.get(tuple(images[r, p]))
                if q is not None and q not in orbit:
                    orbit.add(q)
                    stack.append(q)
        for q in orbit:
            rep[q] = i
        reps.append(i)
        weights.append(len(orbit) / nk)
    kpts = (J[np.asarray(reps)] / float(D)) % 1.0
    return kpts, np.asarray(weights)
