from sirius_tpu.crystal.atom_type import AtomType, BetaProjector, AtomicWf
from sirius_tpu.crystal.unit_cell import UnitCell
from sirius_tpu.crystal.symmetry import CrystalSymmetry, SymmetryOp
from sirius_tpu.crystal.kpoints import irreducible_kmesh
