"""Crystal symmetry, found natively (no spglib dependency).

The reference delegates to spglib (src/symmetry/crystal_symmetry.cpp:210
spg_get_dataset) and then filters magnetic symmetry. Here the space-group
operations are found directly with the textbook algorithm spglib itself uses:

  1. candidate rotations = integer matrices W (fractional basis) with
     det W = +-1 that preserve the lattice metric  W M W^T = M,  M = A A^T;
  2. for each W, candidate translations t = x_j - W x_0 against atoms of the
     least-abundant species; (W, t) is kept if it permutes every atom onto an
     atom of the same species (mod lattice) within tolerance;
  3. collinear/non-collinear magnetic structures filter ops that do not
     preserve the initial moments (reference magnetization symmetry check).

Each op also records the induced atom permutation (needed to symmetrize
forces and on-site matrices) and the integer reciprocal rotation
W_k = (W^{-1})^T acting on fractional k / G vectors.
"""

from __future__ import annotations

import dataclasses

import numpy as np

_TOL = 1e-6
_ROTATION_CACHE: dict = {}


@dataclasses.dataclass(frozen=True)
class SymmetryOp:
    w: np.ndarray  # (3,3) int rotation, fractional (real space): x' = W x + t
    t: np.ndarray  # (3,) translation, fractional
    perm: np.ndarray  # (natom,) atom a maps onto atom perm[a]
    w_k: np.ndarray  # (3,3) int reciprocal rotation (W^{-1})^T
    rot_cart: np.ndarray  # (3,3) cartesian rotation matrix
    # collinear spin action (reference spin_rotation S(2,2)): magnetization
    # is an axial vector, m'_z = det(R) R_zz m_z = spin_sign * m_z. +-1 for
    # ops kept by the magnetic filter; +1 for nonmagnetic systems. AFM
    # sublattice-swap ops carry -1 — symmetrizing m_z without it averages
    # the staggered field to zero (NiO, verification/test05).
    spin_sign: float = 1.0


def _lattice_rotations(lattice: np.ndarray) -> np.ndarray:
    """All integer fractional rotations preserving the metric (point group of
    the empty lattice, up to 48 ops for cubic).

    Returned matrices are COLUMN-acting on fractional coordinates
    (x' = W x): basis rows transform as A' = W^T A, so the metric condition
    is W^T (A A^T) W = A A^T."""
    m = lattice @ lattice.T
    scale = max(1.0, np.abs(m).max())
    key = hash(np.round(m / scale, 9).tobytes())
    cached = _ROTATION_CACHE.get(key)
    if cached is not None:
        return cached
    # per-column candidates first: column j of W maps basis direction e_j to
    # an integer vector c with c^T M c = M_jj (norm preservation) — typically
    # a few dozen candidates each — then assemble triples and check the
    # off-diagonal metric entries and |det| = 1. Orders of magnitude cheaper
    # than enumerating all 5^9 integer matrices.
    base = np.arange(5**3, dtype=np.int64)
    cols = np.stack([(base // 5**p) % 5 - 2 for p in range(3)], axis=1)  # (125,3)
    norms = np.einsum("ni,ij,nj->n", cols, m, cols)
    cand_j = [cols[np.abs(norms - m[j, j]) < _TOL * scale] for j in range(3)]
    c0, c1, c2 = cand_j
    # pairwise off-diagonal filter before the triple product
    d01 = np.abs(np.einsum("ai,ij,bj->ab", c0, m, c1) - m[0, 1]) < _TOL * scale
    out = []
    for i0, i1 in zip(*np.nonzero(d01)):
        v0, v1 = c0[i0], c1[i1]
        ok2 = (
            (np.abs(c2 @ (m @ v0) - m[0, 2]) < _TOL * scale)
            & (np.abs(c2 @ (m @ v1) - m[1, 2]) < _TOL * scale)
        )
        for v2 in c2[ok2]:
            w = np.stack([v0, v1, v2], axis=1)  # columns
            if abs(round(np.linalg.det(w))) == 1:
                out.append(w)
    out = np.asarray(out, dtype=np.int64).reshape(-1, 3, 3)
    _ROTATION_CACHE[key] = out
    return out


def find_symmetry(
    lattice: np.ndarray,
    positions: np.ndarray,
    species: np.ndarray,
    moments: np.ndarray | None = None,
    num_mag_dims: int = 0,
    tol: float = _TOL,
) -> list[SymmetryOp]:
    positions = np.asarray(positions, dtype=np.float64)
    species = np.asarray(species)
    natom = len(positions)
    rots = _lattice_rotations(np.asarray(lattice, dtype=np.float64))
    inv_lat_t = np.linalg.inv(lattice.T)
    ops: list[SymmetryOp] = []
    # pivot species: least abundant
    counts = {s: int(np.sum(species == s)) for s in set(species.tolist())}
    pivot_s = min(counts, key=counts.get)
    pivot_atoms = np.nonzero(species == pivot_s)[0]
    x0 = positions[pivot_atoms[0]]
    for w in rots:
        wx = positions @ w.T  # (natom, 3): W x_a
        seen_t: list[np.ndarray] = []
        for j in pivot_atoms:
            t = np.mod(positions[j] - w @ x0, 1.0)
            if any(np.all(np.minimum(d := np.abs(t - ts), 1 - d) < tol) for ts in seen_t):
                continue
            mapped = np.mod(wx + t, 1.0)
            # distance to every atom, on the torus
            d = np.abs(mapped[:, None, :] - positions[None, :, :])
            d = np.minimum(d, 1.0 - d)
            match = np.all(d < tol, axis=2)  # (a, b): W x_a + t == x_b
            perm = np.full(natom, -1, dtype=np.int64)
            ok = True
            for a in range(natom):
                hits = np.nonzero(match[a])[0]
                if len(hits) != 1 or species[hits[0]] != species[a]:
                    ok = False
                    break
                perm[a] = hits[0]
            if not ok or len(set(perm.tolist())) != natom:
                continue
            rot_cart = lattice.T @ w @ inv_lat_t
            detr = np.linalg.det(rot_cart)
            spin_sign = float(np.sign(round(detr * rot_cart[2, 2]))) or 1.0
            if moments is not None and num_mag_dims > 0:
                # moments are axial vectors: m' = det(R) R m; collinear case
                # requires preservation up to the filter below
                mrot = (moments @ rot_cart.T) * detr
                if num_mag_dims == 1:
                    keep_op = np.allclose(mrot[:, 2], moments[perm][:, 2], atol=1e-4)
                    # the collinear field transforms with det(R)*R_zz; for a
                    # kept op on a magnetic system this must be exactly +-1
                    if keep_op and np.any(np.abs(moments[:, 2]) > 1e-12):
                        keep_op = abs(abs(detr * rot_cart[2, 2]) - 1.0) < 1e-6
                        spin_sign = float(np.sign(detr * rot_cart[2, 2]))
                    else:
                        # zero starting moments: the reference decouples spin
                        # from space and picks the identity spin rotation
                        # (crystal_symmetry.cpp jsym loop) — never flip the
                        # (about-to-develop) polarization with a snapped sign
                        spin_sign = 1.0
                else:
                    keep_op = np.allclose(mrot, moments[perm], atol=1e-4)
                if not keep_op:
                    continue
            w_k = np.linalg.inv(w).T.round().astype(np.int64)
            ops.append(
                SymmetryOp(
                    w=w, t=t, perm=perm, w_k=w_k, rot_cart=rot_cart,
                    spin_sign=spin_sign,
                )
            )
            seen_t.append(t)
    return ops


@dataclasses.dataclass
class CrystalSymmetry:
    ops: list[SymmetryOp]
    lattice: np.ndarray

    @staticmethod
    def find(
        lattice: np.ndarray,
        positions: np.ndarray,
        species: np.ndarray,
        moments: np.ndarray | None = None,
        num_mag_dims: int = 0,
        tol: float = _TOL,
    ) -> "CrystalSymmetry":
        return CrystalSymmetry(
            ops=find_symmetry(lattice, positions, species, moments, num_mag_dims, tol),
            lattice=np.asarray(lattice, dtype=np.float64),
        )

    @property
    def num_ops(self) -> int:
        return len(self.ops)

    @property
    def has_inversion(self) -> bool:
        return any(np.array_equal(op.w, -np.eye(3, dtype=np.int64)) for op in self.ops)
