"""sirius_tpu — a TPU-native Kohn-Sham DFT framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
electronic-structure/SIRIUS (plane-wave + LAPW Kohn-Sham DFT): pseudopotential
plane-wave SCF with norm-conserving / ultrasoft / PAW pseudopotentials,
magnetism, Hubbard corrections, forces/stress, and distributed execution over
TPU meshes via jax.sharding + shard_map collectives.

Design stance (vs. the reference, see SURVEY.md):
  - fields and wave functions are pytrees of jnp arrays; the SCF step is a
    pure function, jit-compiled end to end;
  - parallelism is a jax.sharding.Mesh with axes ("k", "b", "g") instead of
    MPI communicator grids; collectives are lax.psum / all_to_all / all_gather;
  - hot ops (H·psi local part, beta projections, density accumulation) are
    batched MXU-friendly einsums + batched FFTs instead of per-band loops.

Precision: double precision is enabled at import (DFT energies need f64
accumulation); the wave-function hot path dtype is configurable (complex64
for TPU MXU throughput, complex128 for strict verification).
"""

from jax import config as _jax_config

_jax_config.update("jax_enable_x64", True)

__version__ = "0.1.0"
