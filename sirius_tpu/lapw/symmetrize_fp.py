"""Symmetrization of full-potential fields: PW + muffin-tin parts.

Reference: src/symmetry/symmetrize_pw_function.hpp (plane-wave part) and
src/symmetry/symmetrize_mt_function.hpp (muffin-tin real-harmonic part,
rotated per l-block with atom permutation).

Real-harmonic rotation matrices are built by exact quadrature projection
  D(W)[lm, l'm'] = sum_p w_p R_lm(p) R_l'm'(W^{-1} p)
(degree-2*lmax product, exact on the product quadrature) instead of the
Ivanic-Ruedenberg recurrence the reference uses (sht/sht.hpp rotation) —
same matrices, parity of improper rotations included automatically.
"""

from __future__ import annotations

import numpy as np

from sirius_tpu.core.sht import _sphere_quadrature, ylm_real


_DCACHE: dict = {}


def rlm_rotation_matrix(lmax: int, rot_cart: np.ndarray) -> np.ndarray:
    """D[lmmax, lmmax] for (O_W f)(r) = f(W^{-1} r) in real harmonics.

    Cached per (lmax, rotation) — the ops are fixed for a whole SCF run."""
    key = (lmax, np.asarray(rot_cart).tobytes())
    hit = _DCACHE.get(key)
    if hit is not None:
        return hit
    pts, w = _sphere_quadrature(2 * lmax + 1)
    y1 = ylm_real(lmax, pts)
    inv = np.linalg.inv(rot_cart)
    y2 = ylm_real(lmax, pts @ inv.T)
    D = (y1 * w[:, None]).T @ y2
    if len(_DCACHE) < 4096:
        _DCACHE[key] = D
    return D


def symmetrize_mt(f_mt_by_atom, ops, lmax: int, axial_z: bool = False):
    """(1/N) sum_S D(W) f_{S^{-1}(a)} per atom; ops carry perm/rot_cart.

    axial_z: the field is collinear magnetization — each op's contribution
    carries its spin_sign (det(R) R_zz), as in the PW symmetrizer."""
    nat = len(f_mt_by_atom)
    out = [np.zeros_like(f) for f in f_mt_by_atom]
    for op in ops:
        D = rlm_rotation_matrix(lmax, op.rot_cart)
        if axial_z:
            D = D * op.spin_sign
        invperm = np.argsort(op.perm)  # ja = invperm[ia]: op maps ja -> ia
        for ia in range(nat):
            out[ia] += np.einsum(
                "ab,br->ar", D, f_mt_by_atom[invperm[ia]], optimize=True
            )
    return [f / len(ops) for f in out]


def symmetrize_pw_fp(
    f_g: np.ndarray, ops, millers: np.ndarray, axial_z: bool = False
) -> np.ndarray:
    """f'(g') += f(g) e^{-2 pi i g'.t} / N over g' = (W^{-1})^T g.

    axial_z: multiply each op's contribution by its spin_sign (collinear
    magnetization is the z-component of an axial vector; without the sign
    AFM sublattice-swap ops average the staggered field to zero).

    Vectorized miller lookup via linear keys + searchsorted (the fine FP
    G set is ~1e5 vectors; a dict LUT would dominate)."""
    K = int(np.abs(millers).max()) + 1
    span = 2 * K + 1

    def key(m):
        return ((m[:, 0] + K) * span + (m[:, 1] + K)) * span + (m[:, 2] + K)

    k0 = key(millers)
    order = np.argsort(k0)
    k0s = k0[order]
    out = np.zeros_like(f_g)
    for op in ops:
        gm = millers @ op.w_k.T
        km = key(gm)
        pos = np.searchsorted(k0s, km)
        pos = np.clip(pos, 0, len(k0s) - 1)
        idx = order[pos]
        ok = k0s[pos] == km
        phase = np.exp(-2j * np.pi * (gm @ op.t))
        if axial_z:
            phase = phase * op.spin_sign
        np.add.at(out, idx[ok], (f_g * phase)[ok])
    return out / len(ops)
