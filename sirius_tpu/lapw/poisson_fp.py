"""Full-potential Poisson solver: Weinert pseudocharge method.

Reference: src/potential/poisson.cpp (Potential::poisson). The interstitial
problem is solved in plane waves for a PSEUDO-density that (a) equals the
true interstitial PW density outside the spheres and (b) carries the exact
muffin-tin multipole moments via smooth in-sphere polynomials
rho ~ (r/R)^l (1 - (r/R)^2)^n; the MT potential is then the interior
solution of the true MT density (+ nucleus) with the boundary value taken
from the interstitial solution (homogeneous r^l correction).

All angular expansions use REAL harmonics R_lm; multipoles are
q_lm = int rho(r) r^l R_lm(r-hat) d^3r; the nucleus contributes
-Z R_00 = -Z/sqrt(4 pi) to q_00.
"""

from __future__ import annotations

from math import gamma

import numpy as np

from sirius_tpu.lapw.quad import rint

from sirius_tpu.core.sht import lm_index, num_lm, ylm_real
from sirius_tpu.lapw.basis import sph_bessel

Y00 = 1.0 / np.sqrt(4.0 * np.pi)


def mt_multipoles(rho_lm: np.ndarray, r: np.ndarray) -> np.ndarray:
    """q_lm = int rho_lm(r) r^{l+2} dr for a real-lm expansion [lmmax, nr]."""
    lmax = int(np.sqrt(rho_lm.shape[0])) - 1
    l_of = np.concatenate([[l] * (2 * l + 1) for l in range(lmax + 1)])
    return rint(rho_lm * r[None, :] ** (l_of[:, None] + 2), r)


def pw_sphere_multipoles(rho_g, millers, gcart, pos_frac, R, lmax):
    """Multipoles of the PW density continued inside a sphere at pos:
    q_lm^PW = sum_G rho(G) e^{iG.r_a} 4 pi i^l R_lm(G-hat) R^{l+2} j_{l+1}(GR)/G."""
    glen = np.linalg.norm(gcart, axis=1)
    ghat = np.where(glen[:, None] > 1e-12, gcart / np.maximum(glen, 1e-12)[:, None], 0.0)
    ghat[glen < 1e-12] = [0, 0, 1]
    rlm = ylm_real(lmax, ghat)
    jl = sph_bessel(lmax + 1, glen * R)
    phase = np.exp(2j * np.pi * (millers @ pos_frac))
    lmmax = num_lm(lmax)
    q = np.zeros(lmmax, dtype=np.complex128)
    nz = glen > 1e-12
    for l in range(lmax + 1):
        rad = np.zeros_like(glen)
        rad[nz] = R ** (l + 2) * jl[l + 1][nz] / glen[nz]
        if l == 0:
            rad[~nz] = R**3 / 3.0
        c = (1j**l) * 4.0 * np.pi * rho_g * phase * rad
        for m in range(-l, l + 1):
            lm = lm_index(l, m)
            q[lm] = np.sum(c * rlm[:, lm])
    return np.real(q)


def pseudo_density_g(rho_i_g, millers, gcart, omega, positions, rmt, dq_by_atom,
                     lmax, nw: int | None = None):
    """Add the Weinert smooth compensators carrying the multipole deficits
    dq (q_MT - q_PW per atom) to the interstitial PW density."""
    out = rho_i_g.astype(np.complex128).copy()
    glen = np.linalg.norm(gcart, axis=1)
    if nw is None:
        # reference pseudo_density_order_ = 9 (potential.hpp:79) — FIXED,
        # even when the compensator's spectral peak (GR ~ l + n + 1) pushes
        # against the represented G set: the truncation systematics are part
        # of the reference's numerical definition (clamping to lower order
        # shifts the l=0 boundary potential by ~mHa; test12 graphite)
        nw = 9
    nz = glen > 1e-12
    ghat = np.where(nz[:, None], gcart / np.maximum(glen, 1e-12)[:, None], 0.0)
    ghat[~nz] = [0, 0, 1]
    rlm = ylm_real(lmax, ghat)
    fact2n = float(2.0**nw * gamma(nw + 1.0))
    for ia in range(len(positions)):
        R = rmt[ia]
        dq = dq_by_atom[ia]
        jl = sph_bessel(lmax + nw + 1, glen * R)
        phase = np.exp(-2j * np.pi * (millers @ positions[ia]))
        gr = glen * R
        for l in range(lmax + 1):
            # a_lm normalization: int x^{2l+2}(1-x^2)^n dx = B(l+3/2, n+1)/2
            i_ln = 0.5 * gamma(l + 1.5) * gamma(nw + 1.0) / gamma(l + nw + 2.5)
            for m in range(-l, l + 1):
                lm = lm_index(l, m)
                if abs(dq[lm]) < 1e-16:
                    continue
                a = dq[lm] / (R ** (l + 3) * i_ln)
                radial = np.zeros_like(glen)
                radial[nz] = (
                    R**3 * fact2n * jl[l + nw + 1][nz] / gr[nz] ** (nw + 1)
                )
                if l == 0:
                    # G=0: integral of the smooth bump = R^3 I(0,n)
                    radial[~nz] = R**3 * i_ln
                out += (
                    (4.0 * np.pi / omega)
                    * (-1j) ** l
                    * rlm[:, lm]
                    * a
                    * radial
                    * phase
                )
    return out


def interstitial_potential_g(rho_pseudo_g, glen2, molecule_rcut: float = 0.0):
    """V(G) = 4 pi rho(G) / G^2, V(0) = 0 (charge-neutral cell).

    molecule_rcut > 0 switches to the cutoff-Coulomb kernel
    4 pi rho / G^2 * (1 - cos(G R_cut)) that removes spurious periodic-
    image interactions for molecules-in-a-box (reference poisson.cpp:204,
    Jarvis/White/Godby/Payne PRB 56, 14972; R_cut = Omega^{1/3}/2)."""
    out = np.zeros_like(rho_pseudo_g)
    nz = glen2 > 1e-12
    out[nz] = 4.0 * np.pi * rho_pseudo_g[nz] / glen2[nz]
    if molecule_rcut > 0.0:
        out[nz] *= 1.0 - np.cos(np.sqrt(glen2[nz]) * molecule_rcut)
    return out


def sphere_boundary_lm(v_g, millers, gcart, pos_frac, R, lmax):
    """Real-lm expansion of a PW field on the sphere surface:
    v_lm(R) = sum_G V(G) e^{iG.r_a} 4 pi i^l j_l(GR) R_lm(G-hat)."""
    glen = np.linalg.norm(gcart, axis=1)
    ghat = np.where(glen[:, None] > 1e-12, gcart / np.maximum(glen, 1e-12)[:, None], 0.0)
    ghat[glen < 1e-12] = [0, 0, 1]
    rlm = ylm_real(lmax, ghat)
    jl = sph_bessel(lmax, glen * R)
    phase = np.exp(2j * np.pi * (millers @ pos_frac))
    lmmax = num_lm(lmax)
    out = np.zeros(lmmax, dtype=np.complex128)
    for l in range(lmax + 1):
        c = (1j**l) * 4.0 * np.pi * v_g * phase * jl[l]
        for m in range(-l, l + 1):
            lm = lm_index(l, m)
            out[lm] = np.sum(c * rlm[:, lm])
    return np.real(out)


def mt_coulomb_potential(rho_lm, r, zn, v_boundary_lm):
    """Interior Coulomb potential of the MT density + nucleus with the
    given boundary values: particular (free-space) solution per lm plus
    the homogeneous r^l term matching v_boundary at R.

    Returns (v_lm [lmmax, nr], vh_el_at_nucleus): the regular part of the
    potential at r -> 0 (nuclear -Z/r excluded) for the enuc energy."""
    from sirius_tpu.dft.paw import poisson_onsite

    lmax = int(np.sqrt(rho_lm.shape[0])) - 1

    class _T:  # poisson_onsite only touches .r and .l_by_lm3
        pass

    t = _T()
    t.r = r
    t.l_by_lm3 = np.concatenate([[l] * (2 * l + 1) for l in range(lmax + 1)])
    v = poisson_onsite(t, rho_lm)
    R = r[-1]
    l_of = t.l_by_lm3
    # nuclear potential in the lm=0 channel: -Z/r -> component -Z/(r Y00)
    v[0] += -zn / (r * Y00) * 1.0
    vR = v[:, -1]
    v += ((v_boundary_lm - vR)[:, None]) * (r[None, :] / R) ** (l_of[:, None])
    # regular part at nucleus: v_00(r->0) R_00 with nuclear part removed
    v00_reg = (v[0, 0] + zn / (r[0] * Y00)) * Y00
    return v, float(v00_reg)
