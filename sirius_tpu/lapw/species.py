"""Full-potential (LAPW) species: muffin-tin grids, linearization recipes.

Reference format (e.g. verification/test02/He.json, produced by the
reference's apps/atoms tool; parsed in src/unit_cell/atom_type.cpp
read_input_data): nrmt points from rmin to rmt (exponential grid), a
free-atom density on its own grid, `valence` APW descriptors (per-l basis
of (enu, dme, auto) linearization entries), `lo` local-orbital descriptors
and a `core` string like '1s2 2s2' (empty = no core)."""

from __future__ import annotations

import dataclasses
import json

import numpy as np


@dataclasses.dataclass
class BasisEntry:
    enu: float  # linearization energy (guess if auto)
    dme: int  # energy-derivative order (0 = u, 1 = udot)
    auto: int  # 0 = fixed enu, 1+ = search enu from band structure
    n: int = 0  # principal quantum number (for auto search)


@dataclasses.dataclass
class LoDescriptor:
    l: int
    basis: list  # [BasisEntry]


@dataclasses.dataclass
class FpSpecies:
    label: str
    symbol: str
    zn: int
    mass: float
    rmt: float
    nrmt: int
    rmin: float
    rinf: float
    r: np.ndarray  # muffin-tin exponential grid [nrmt], r[-1] = rmt
    free_atom_r: np.ndarray
    free_atom_density: np.ndarray
    aw_default: list  # default APW basis (l not covered by aw_specific)
    aw_specific: dict  # l -> [BasisEntry]
    lo: list  # [LoDescriptor]
    core: str  # e.g. "1s2 2s2"

    @staticmethod
    def from_file(label: str, path: str) -> "FpSpecies":
        with open(path) as f:
            d = json.load(f)
        nrmt = int(d["nrmt"])
        rmin, rmt = float(d["rmin"]), float(d["rmt"])
        # exponential grid like the reference default (atom_type.cpp
        # init radial grid): r_i = rmin (rmt/rmin)^{i/(n-1)}
        r = rmin * (rmt / rmin) ** (np.arange(nrmt) / (nrmt - 1.0))
        aw_default, aw_specific = [], {}
        for v in d.get("valence", []):
            # the principal quantum number sits at the VALENCE-ENTRY level
            # ({"l": 0, "n": 4, "basis": [...]}, reference
            # atom_type.cpp read_input aw descriptors); missing it made
            # auto-enu resolve l+1 = CORE bands (NiO: O 1s as the l=0 APW)
            n_v = int(v.get("n", 0))
            basis = [
                BasisEntry(
                    enu=float(b.get("enu", 0.15)),
                    dme=int(b.get("dme", 0)),
                    auto=int(b.get("auto", 0)),
                    n=int(b.get("n", n_v)),
                )
                for b in v["basis"]
            ]
            if "l" in v:
                aw_specific[int(v["l"])] = basis
            else:
                aw_default = basis
        lo = [
            LoDescriptor(
                l=int(e["l"]),
                basis=[
                    BasisEntry(
                        enu=float(b.get("enu", 0.15)),
                        dme=int(b.get("dme", 0)),
                        auto=int(b.get("auto", 0)),
                        n=int(b.get("n", int(e.get("n", 0)))),
                    )
                    for b in e["basis"]
                ],
            )
            for e in d.get("lo", [])
        ]
        return FpSpecies(
            label=label,
            symbol=d.get("symbol", label),
            zn=int(d["number"]),
            mass=float(d.get("mass", 0.0)),
            rmt=rmt,
            nrmt=nrmt,
            rmin=rmin,
            rinf=float(d.get("rinf", 50.0)),
            r=r,
            free_atom_r=np.asarray(d["free_atom"]["radial_grid"], float),
            free_atom_density=np.asarray(d["free_atom"]["density"], float),
            aw_default=aw_default,
            aw_specific=aw_specific,
            lo=lo,
            core=d.get("core", ""),
        )

    def aw_basis(self, l: int) -> list:
        return self.aw_specific.get(l, self.aw_default)

    def core_states(self) -> list:
        """[(n, l, occupancy)] from the core string '1s2s2p' — pairs of
        (n, l-letter), each a FULL shell (reference read_input_core,
        atom_type.cpp:376)."""
        s = self.core.strip().replace(" ", "")
        if len(s) % 2:
            raise ValueError(f"wrong core configuration string: {self.core}")
        lmap = {"s": 0, "p": 1, "d": 2, "f": 3}
        out = []
        for j in range(0, len(s), 2):
            n = int(s[j])
            l = lmap[s[j + 1]]
            out.append((n, l, 2.0 * (2 * l + 1)))
        return out


def step_function_g(lattice: np.ndarray, positions: np.ndarray,
                    rmt: np.ndarray, gcart: np.ndarray,
                    millers: np.ndarray) -> np.ndarray:
    """PW coefficients of the unit-step (characteristic) function
    Theta(r) = 1 in the interstitial, 0 inside any muffin-tin sphere
    (reference src/unit_cell/unit_cell.cpp generate step function):

      Theta(G) = delta_{G,0} - sum_a e^{-i G r_a} (4 pi / Omega G^3)
                 (sin(G R_a) - G R_a cos(G R_a)).
    """
    omega = abs(np.linalg.det(lattice))
    glen = np.linalg.norm(gcart, axis=1)
    out = np.zeros(len(gcart), dtype=np.complex128)
    out[glen < 1e-12] = 1.0
    for ia in range(len(positions)):
        R = rmt[ia]
        gr = glen * R
        w = np.empty_like(glen)
        small = glen < 1e-12
        w[~small] = (
            4.0 * np.pi / (omega * glen[~small] ** 3)
            * (np.sin(gr[~small]) - gr[~small] * np.cos(gr[~small]))
        )
        w[small] = 4.0 * np.pi * R**3 / (3.0 * omega)
        phase = np.exp(-2j * np.pi * (millers @ positions[ia]))
        out -= w * phase
    return out
