"""Matrix-free first-variational LAPW operator + iterative Davidson solve.

Re-design of the reference's apply_fv_h_o (hamiltonian.hpp:217-349) and the
iterative FP diagonalization (diagonalize_fp.hpp:271): H and O are applied
to trial-vector blocks without ever forming the (nG+nlo)^2 matrices.

TPU-shaped decomposition of the dense assembly (lapw/fv.py assemble_fv):

  interstitial  theta / V.theta / ZORA-kinetic convolutions -> FFT pairs
                (the kinetic (G+k).(G'+k) factor splits over 3 cartesian
                gradient components exactly like the mGGA tau operator)
  MT spherical  C ov C^H and C hs C^H sandwiches -> einsums over the
                matching coefficients C [nG, lmmax, 2]
  MT nonsph.    conj(W) V W^T with the small per-atom V [nidx, nidx]
  apw-lo / lo-lo  small dense couplings

Everything is jnp inside one stable apply function driven by the SAME
generalized-Davidson driver as the plane-wave path (solvers/davidson.py),
so the dense diagonalize_fv becomes the verification fallback
(VERDICT r4 item 9). The overlap's near-singular APW directions are handled
by the driver's rank-revealing orthogonalization — the iterative analogue
of the reference's num_singular guard (diagonalize_fp.hpp:238).
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from sirius_tpu.core.sht import lm_index, num_lm


class FvParams(NamedTuple):
    """Per-k matrix-free fv operator data (pytree of jnp arrays)."""

    # interstitial real-space boxes
    theta_r: jax.Array       # [n1,n2,n3] step function
    vtheta_r: jax.Array      # [n1,n2,n3] veff * theta
    kin_r: jax.Array         # [n1,n2,n3] theta (or theta/M for ZORA/IORA)
    fft_index: jax.Array     # [nG] int32 into the flat box
    gkc: jax.Array           # [nG, 3] cartesian G+k
    # per-atom MT data, stacked over atoms with a common lmmax
    C: jax.Array             # [nat, nG, lmmax, 2] matching coefficients
    ovl: jax.Array           # [nat, lmmax, 2, 2] radial overlaps per lm
    hsl: jax.Array           # [nat, lmmax, 2, 2] spherical-H per lm
    # nonspherical MT sandwich, W maps basis -> MT expansion entries
    V: jax.Array             # [nat, nidx, nidx] (zero-padded)
    Wlo: jax.Array           # [nat, nlo_tot, nidx] lo rows of W
    # apw-lo spherical couplings: value at the lo's (lm) for each lo col
    lo_lm: jax.Array         # [nlo_tot] int lm of each lo column
    lo_atom: jax.Array       # [nlo_tot] int atom of each lo column
    lo_ou: jax.Array         # [nlo_tot] <u|lo>, <udot|lo>, h analogues
    lo_od: jax.Array
    lo_hu: jax.Array
    lo_hd: jax.Array
    lo_o: jax.Array          # [nlo_tot, nlo_tot] lo-lo overlap (same atom/lm)
    lo_h: jax.Array          # [nlo_tot, nlo_tot]


def build_fv_params(gk_millers, k_frac, lattice, positions, rmt_by_atom,
                    basis_by_atom, v_mt_lm_by_atom, theta_r, veff_r,
                    kin_r, dims, omega) -> FvParams:
    """Assemble the small per-atom pieces (host, numpy) — the same
    ingredients the dense assemble_fv consumes, kept unreduced."""
    from sirius_tpu.lapw.basis import matching_coefficients
    from sirius_tpu.lapw.density_fp import mt_index
    from sirius_tpu.lapw.fv import gaunt_hybrid
    from sirius_tpu.lapw.quad import radial_weights

    recip = 2.0 * np.pi * np.linalg.inv(lattice).T
    gk_cart = (gk_millers + k_frac) @ recip
    ng = len(gk_millers)
    nat = len(positions)

    # the stacked layout (C, ovl, W slots) assumes ONE lmax_apw across
    # atoms — true for every caller (parameters.lmax_apw is global); the
    # dense assemble_fv would support per-atom sizes, so fail loudly here
    # rather than silently truncating if that ever changes
    lmaxes = {b.lmax_apw for b in basis_by_atom}
    if len(lmaxes) != 1:
        raise NotImplementedError(
            f"matrix-free fv needs a common lmax_apw, got {sorted(lmaxes)}; "
            "use the dense solver (iterative_solver.type=exact)"
        )
    lmax = basis_by_atom[0].lmax_apw
    lmmax = num_lm(lmax)

    lo_index = []
    for ia in range(nat):
        for ilo, lof in enumerate(basis_by_atom[ia].lo):
            for m in range(-lof.l, lof.l + 1):
                lo_index.append((ia, ilo, lof.l, m))
    nlo = len(lo_index)

    C = np.zeros((nat, ng, lmmax, 2), dtype=np.complex128)
    ovl = np.zeros((nat, lmmax, 2, 2))
    hsl = np.zeros((nat, lmmax, 2, 2))
    nidx_max = 0
    per_atom_nidx = []
    for ia in range(nat):
        b = basis_by_atom[ia]
        _, lm_of, _ = mt_index(b, lmax)
        per_atom_nidx.append(len(lm_of))
        nidx_max = max(nidx_max, len(lm_of))
    V = np.zeros((nat, nidx_max, nidx_max), dtype=np.complex128)
    Wlo = np.zeros((nat, nlo, nidx_max), dtype=np.complex128)
    lo_lm = np.zeros(nlo, dtype=np.int32)
    lo_atom = np.zeros(nlo, dtype=np.int32)
    lo_ou = np.zeros(nlo)
    lo_od = np.zeros(nlo)
    lo_hu = np.zeros(nlo)
    lo_hd = np.zeros(nlo)
    lo_o = np.zeros((nlo, nlo))
    lo_h = np.zeros((nlo, nlo))

    for ia in range(nat):
        b = basis_by_atom[ia]
        r = b.r
        A, B = matching_coefficients(
            gk_cart, positions[ia], gk_millers, k_frac, rmt_by_atom[ia],
            b, omega,
        )
        C[ia] = np.stack([A, B], axis=2)
        ov = np.zeros((lmax + 1, 2, 2))
        hs = np.zeros((lmax + 1, 2, 2))
        for l in range(lmax + 1):
            for i, fi in enumerate(b.aw[l]):
                for jj, fj in enumerate(b.aw[l]):
                    ov[l, i, jj] = b.overlap(fi, fj)
                    hs[l, i, jj] = b.h_sph(fi, fj)
        l_of_lm = np.concatenate([[l] * (2 * l + 1) for l in range(lmax + 1)])
        ovl[ia] = ov[l_of_lm]
        hsl[ia] = hs[l_of_lm]

        v_lm = v_mt_lm_by_atom[ia]
        if v_lm is not None and np.abs(v_lm[1:]).max() > 1e-14:
            lmax_pot = int(np.sqrt(v_lm.shape[0])) - 1
            gh = gaunt_hybrid(lmax, lmax_pot, lmax)
            rf, lm_of, rf_of = mt_index(b, lmax)
            nidx = len(lm_of)
            wr2 = radial_weights(r) * r * r
            F = np.stack(rf)
            RI = np.einsum("ax,Lx,bx,x->abL", F, v_lm, F, wr2, optimize=True)
            RI[:, :, 0] = 0.0
            GG = gh[lm_of[:, None], :, lm_of[None, :]]
            V[ia, :nidx, :nidx] = np.einsum(
                "pqL,pqL->pq", GG, RI[rf_of[:, None], rf_of[None, :], :]
            )
        # lo rows of W (APW rows are handled through C in the apply)
        kk = 2 * lmmax
        for col, (ja, ilo, l, m) in enumerate(lo_index):
            if ja == ia:
                Wlo[ia, col, kk] = 1.0
                kk += 1

    for col, (ja, ilo, l, m) in enumerate(lo_index):
        b = basis_by_atom[ja]
        lof = b.lo[ilo]
        lo_lm[col] = lm_index(l, m)
        lo_atom[col] = ja
        lo_ou[col] = b.overlap(b.aw[l][0], lof)
        lo_od[col] = b.overlap(b.aw[l][1], lof)
        lo_hu[col] = b.h_sph(b.aw[l][0], lof)
        lo_hd[col] = b.h_sph(b.aw[l][1], lof)
        for col2, (ja2, ilo2, l2, m2) in enumerate(lo_index):
            if ja2 == ja and l2 == l and m2 == m:
                lof2 = b.lo[ilo2]
                lo_o[col, col2] = b.overlap(lof, lof2)
                lo_h[col, col2] = b.h_sph(lof, lof2)

    # flat index of each G-vector in the FFT box
    i0 = np.mod(gk_millers[:, 0], dims[0])
    i1 = np.mod(gk_millers[:, 1], dims[1])
    i2 = np.mod(gk_millers[:, 2], dims[2])
    fft_index = (i0 * dims[1] + i1) * dims[2] + i2
    asx = lambda a: jnp.asarray(a)
    return FvParams(
        theta_r=asx(theta_r), vtheta_r=asx(veff_r * theta_r),
        kin_r=asx(kin_r if kin_r is not None else theta_r),
        fft_index=jnp.asarray(fft_index.astype(np.int32)),
        gkc=asx(gk_cart),
        C=asx(C), ovl=asx(ovl), hsl=asx(hsl), V=asx(V), Wlo=asx(Wlo),
        lo_lm=jnp.asarray(lo_lm), lo_atom=jnp.asarray(lo_atom),
        lo_ou=asx(lo_ou), lo_od=asx(lo_od), lo_hu=asx(lo_hu),
        lo_hd=asx(lo_hd), lo_o=asx(lo_o), lo_h=asx(lo_h),
    )


def apply_fv_h_o(p: FvParams, x: jax.Array):
    """(H x, O x) for a trial block x [nb, nG + nlo] — matrix-free."""
    dims = p.theta_r.shape
    n = dims[0] * dims[1] * dims[2]
    ng = p.gkc.shape[0]
    nlo = p.lo_lm.shape[0]
    nat, _, lmmax, _ = p.C.shape
    cg = x[:, :ng]
    clo = x[:, ng:]
    batch = cg.shape[:-1]

    def conv(field_r, c):
        box = jnp.zeros(batch + (n,), dtype=c.dtype).at[..., p.fft_index].add(c)
        fr = jnp.fft.ifftn(box.reshape(batch + dims), axes=(-3, -2, -1))
        return (
            jnp.fft.fftn(fr * field_r, axes=(-3, -2, -1))
            .reshape(batch + (n,))[..., p.fft_index]
        )

    # interstitial: O += theta conv; H += V.theta conv + kinetic
    ox_g = conv(p.theta_r, cg)
    hx_g = conv(p.vtheta_r, cg)
    for c in range(3):
        hx_g = hx_g + 0.5 * p.gkc[:, c] * conv(p.kin_r, p.gkc[:, c] * cg)

    # MT spherical sandwiches: O = conj(C) ov C^T over (m, i) blocks, so the
    # column contraction is UNconjugated and the row map conjugated
    # (dense: O[g,h] = conj(C)[g,m,i] ovl[m,i,j] C[h,m,j])
    F = jnp.einsum("agmj,bg->bamj", p.C, cg)
    ox_g = ox_g + jnp.einsum("agmi,amij,bamj->bg", jnp.conj(p.C), p.ovl, F)
    hx_g = hx_g + jnp.einsum("agmi,amij,bamj->bg", jnp.conj(p.C), p.hsl, F)

    # nonspherical MT: y = conj(W) V W^T x with W = [C-part | lo rows]
    # MT expansion vector per atom: t[b, a, p] with p = (2*lmmax APW slots,
    # then lo slots); APW slots interleave (u, udot) per lm
    t_apw = F.reshape(F.shape[0], nat, lmmax * 2)  # (m, i) -> 2m+i order
    # reorder (m, i) from [m, i] blocks: F is [b, a, m, i] with i fastest ->
    # matches W's interleaved layout [2m, 2m+1]
    t_lo = jnp.einsum("alp,bl->bap", p.Wlo, clo)
    t = jnp.concatenate([t_apw, t_lo[..., 2 * lmmax:]], axis=-1) \
        if p.V.shape[-1] > 2 * lmmax else t_apw[..., : p.V.shape[-1]]
    vt = jnp.einsum("apq,baq->bap", p.V, t)
    # back: APW part via conj(C), lo part via conj(Wlo)
    vt_apw = vt[..., : 2 * lmmax].reshape(F.shape[0], nat, lmmax, 2)
    hx_g = hx_g + jnp.einsum("agmi,bami->bg", jnp.conj(p.C), vt_apw)
    hx_lo_ns = jnp.einsum("alp,bap->bl", jnp.conj(p.Wlo), vt)

    # apw-lo spherical couplings
    # column side: (H x)_G += conj(A[:,lm]) hu clo + conj(B[:,lm]) hd clo
    Asel = jnp.take_along_axis(
        p.C[p.lo_atom, :, :, 0], p.lo_lm[:, None, None], axis=2
    )[..., 0]  # [nlo, nG]
    Bsel = jnp.take_along_axis(
        p.C[p.lo_atom, :, :, 1], p.lo_lm[:, None, None], axis=2
    )[..., 0]
    ox_g = ox_g + jnp.einsum(
        "lg,l,bl->bg", jnp.conj(Asel), p.lo_ou, clo
    ) + jnp.einsum("lg,l,bl->bg", jnp.conj(Bsel), p.lo_od, clo)
    hx_g = hx_g + jnp.einsum(
        "lg,l,bl->bg", jnp.conj(Asel), p.lo_hu, clo
    ) + jnp.einsum("lg,l,bl->bg", jnp.conj(Bsel), p.lo_hd, clo)
    # row side (conjugate transpose)
    ox_lo = jnp.einsum("lg,l,bg->bl", Asel, p.lo_ou, cg) + jnp.einsum(
        "lg,l,bg->bl", Bsel, p.lo_od, cg
    )
    hx_lo = jnp.einsum("lg,l,bg->bl", Asel, p.lo_hu, cg) + jnp.einsum(
        "lg,l,bg->bl", Bsel, p.lo_hd, cg
    )
    # lo-lo
    ox_lo = ox_lo + clo @ p.lo_o.T
    hx_lo = hx_lo + clo @ p.lo_h.T + hx_lo_ns

    return (
        jnp.concatenate([hx_g, hx_lo], axis=-1),
        jnp.concatenate([ox_g, ox_lo], axis=-1),
    )


def fv_diag(p: FvParams):
    """(h_diag, o_diag) preconditioner diagonals for the davidson driver."""
    ng = p.gkc.shape[0]
    ekin = 0.5 * jnp.sum(p.gkc * p.gkc, axis=1)
    th0 = jnp.real(jnp.mean(p.theta_r))
    v0 = jnp.real(jnp.mean(p.vtheta_r))
    # MT diagonal contribution of the spherical sandwiches
    mt_o = jnp.einsum("agmi,amij,agmj->g", jnp.conj(p.C), p.ovl, p.C).real
    mt_h = jnp.einsum("agmi,amij,agmj->g", jnp.conj(p.C), p.hsl, p.C).real
    h_g = ekin * th0 + v0 + mt_h
    o_g = th0 + mt_o
    o_lo = jnp.diag(p.lo_o)
    h_lo = jnp.diag(p.lo_h)
    return (
        jnp.concatenate([h_g, h_lo]),
        jnp.concatenate([o_g, jnp.maximum(o_lo, 1e-8)]),
    )


def davidson_fv(p: FvParams, nev: int, num_steps: int = 30,
                res_tol: float = 1e-8, x0=None, seed: int = 7):
    """Iterative lowest-nev solve of the matrix-free fv problem.

    Returns (evals [nev], X [nev, ntot], res_norms). The dense
    diagonalize_fv is the verification fallback for this path."""
    from sirius_tpu.solvers.davidson import davidson

    ng = p.gkc.shape[0]
    ntot = ng + p.lo_lm.shape[0]
    if x0 is None:
        rng = np.random.default_rng(seed)
        x0 = rng.standard_normal((nev, ntot)) + 1j * rng.standard_normal(
            (nev, ntot)
        )
        # damp high-G components
        damp = 1.0 / (1.0 + np.asarray(0.5 * np.sum(np.asarray(p.gkc) ** 2, axis=1)))
        x0[:, :ng] *= damp
        x0 = jnp.asarray(x0)
    h_diag, o_diag = fv_diag(p)
    mask = jnp.ones(ntot)
    ev, x, rn = davidson(
        apply_fv_h_o, p, x0, h_diag, o_diag, mask,
        num_steps=num_steps, res_tol=res_tol,
    )
    return ev, x, rn
