"""Radial quadrature weights for muffin-tin grids.

The reference integrates radial functions with C^3 splines
(src/core/radial_grid + Spline::integrate); trapezoid on the same grids
loses ~1e-5 relative accuracy — visible at the 1e-5 Ha verification bar.
For the (exactly geometric) MT grids used here, substituting x = ln r maps
the grid to uniform spacing, where composite Simpson (+ a 3/8 tail when the
interval count is odd) gives O(h^4) accuracy: int f dr = int f(r(x)) r dx.
Non-geometric grids (free-atom grids from species files) fall back to
trapezoid weights.
"""

from __future__ import annotations

import numpy as np


def _uniform_composite(n: int) -> np.ndarray:
    """Weights for int over n uniformly spaced points, unit spacing."""
    if n < 2:
        return np.zeros(n)
    if n == 2:
        return np.array([0.5, 0.5])
    if n == 3:
        return np.array([1.0, 4.0, 1.0]) / 3.0
    w = np.zeros(n)
    nint = n - 1
    if nint % 2 == 0:
        w[0] = w[-1] = 1.0 / 3.0
        w[1:-1:2] = 4.0 / 3.0
        w[2:-2:2] = 2.0 / 3.0
    else:
        m = n - 3  # Simpson over first m points (m-1 intervals, even)
        ws = _uniform_composite(m)
        w[:m] += ws
        w[m - 1 :] += np.array([3.0, 9.0, 9.0, 3.0]) / 8.0
    return w


def radial_weights(r: np.ndarray) -> np.ndarray:
    """w such that int f dr ~= w . f on this grid."""
    r = np.asarray(r, float)
    n = len(r)
    if n < 2:
        return np.zeros(n)
    ratio = r[1:] / r[:-1]
    if r[0] > 0 and np.allclose(ratio, ratio[0], rtol=1e-9, atol=0):
        h = float(np.log(ratio[0]))
        return _uniform_composite(n) * h * r
    # fallback: trapezoid
    w = np.zeros(n)
    d = np.diff(r)
    w[:-1] += 0.5 * d
    w[1:] += 0.5 * d
    return w


def rint(f: np.ndarray, r: np.ndarray) -> float | np.ndarray:
    """int f dr along the LAST axis with spline-grade weights."""
    return np.asarray(f) @ radial_weights(r)
