"""APW(+lo) radial basis and plane-wave matching coefficients.

Reference: src/unit_cell/atom_symmetry_class.cpp (radial function
generation), src/lapw/matching_coefficients.hpp:42 (A_lm coefficients).

Every MT radial function f is stored together with hf := (T + V_sph) f
evaluated THROUGH the radial ODE (no numerical second derivative):
for u at linearization energy E, hu = E u; for udot, hud = E udot + u;
for a local orbital c1 u + c2 udot, hf = E f + c2 u. Spherical-potential
Hamiltonian integrals then become plain radial overlaps, symmetrized as
(1/2)(<g|hf> + <hg|f>) — the Hermitian LAPW assembly on the truncated
sphere domain.

LAPW order-2 matching at the sphere boundary: the interstitial plane wave
(1/sqrt(Omega)) e^{i(G+k).r} expands around atom a as

  (4 pi / sqrt(Omega)) e^{i(G+k).r_a} sum_lm i^l j_l(|G+k| r)
      Y*_lm(G+k-hat) Y_lm(r-hat)

and the MT function a u_l(r) + b udot_l(r) matches value AND slope.

Local orbitals combine two radial functions with zero value at R and unit
norm (reference lo descriptors with p(R) = 0 boundary condition)."""

from __future__ import annotations

import dataclasses

import numpy as np

from sirius_tpu.lapw.quad import rint

from sirius_tpu.core.sht import lm_index, num_lm, ylm_complex
from sirius_tpu.lapw.radial_solver import (
    find_bound_state,
    find_enu_band,
    radial_solution_with_edot,
)


@dataclasses.dataclass
class MtRadial:
    """One MT radial function with its spherical-Hamiltonian image."""

    l: int
    f: np.ndarray  # u(r)
    hf: np.ndarray  # (T + V_sph) u via the ODE
    fR: float  # u(R)
    fpR: float  # u'(R)


@dataclasses.dataclass
class AtomRadialBasis:
    """Per-atom-type radial functions at the current spherical potential.

    aw[l] = [MtRadial u, MtRadial udot] (the LAPW pair); lo = [MtRadial]
    with zero boundary value."""

    lmax_apw: int
    r: np.ndarray
    aw: list
    lo: list
    enu: list
    lo_enu: list = dataclasses.field(default_factory=list)  # resolved, per lo
    minv_R: float = 1.0  # 1/M(R) of the valence relativity (ZORA/IORA)
    # per-l APW matching order: 2 = LAPW (value + slope with u, udot),
    # 1 = APW (value only; aw[l][1] is a zero pad). Default 2 everywhere.
    aw_order: list = dataclasses.field(default_factory=list)

    def order(self, l: int) -> int:
        return self.aw_order[l] if self.aw_order else 2

    def overlap(self, f1: MtRadial, f2: MtRadial) -> float:
        return float(rint(f1.f * f2.f * self.r**2, self.r))

    def h_sph(self, f1: MtRadial, f2: MtRadial) -> float:
        """Symmetrized spherical-Hamiltonian integral INCLUDING the kinetic
        surface term: the interstitial matrix elements use the gradient
        (weak) form, so the MT side must too; converting the volume
        Laplacian form (what the ODE images hf encode) to the gradient form
        adds (1/4) R^2 M^-1(R) (f1(R) f2'(R) + f1'(R) f2(R)) after
        symmetrization (reference: the weak-form h_spherical_integrals of
        atom_symmetry_class.cpp:616-640 carry 1/M inside the integral; the
        boundary term of the ZORA kinetic operator -1/2 div(M^-1 grad)
        carries the same factor)."""
        r2 = self.r**2
        vol = 0.5 * float(
            rint(f1.f * f2.hf * r2, self.r)
            + rint(f1.hf * f2.f * r2, self.r)
        )
        R = self.r[-1]
        surf = 0.25 * R * R * self.minv_R * (
            f1.fR * f2.fpR + f1.fpR * f2.fR
        )
        return vol + surf


def find_enu(r, v_sph, l: int, n: int, rel: str = "none") -> float:
    """Linearization energy: band center (ebot + etop)/2 of the (n, l)
    muffin-tin band (reference Enu_finder, radial_solver.hpp:1172,
    auto_enu = 1)."""
    try:
        e, _, _ = find_enu_band(r, v_sph, l, n, rel)
        return float(e)
    except Exception:
        return 0.15


def build_radial_basis(sp, v_sph: np.ndarray, lmax_apw: int,
                       rel: str = "none") -> AtomRadialBasis:
    r = sp.r
    aw, enu_l, aw_order = [], [], []
    for l in range(lmax_apw + 1):
        basis = sp.aw_basis(l)
        e0 = basis[0].enu
        if basis[0].auto:
            n = basis[0].n if basis[0].n > 0 else l + 1
            e0 = find_enu(r, v_sph, l, n, rel)
        u, ud, uR, upR, udR, udpR = radial_solution_with_edot(r, v_sph, l, e0, rel)
        if len(basis) == 1:
            # true APW species (one radial function per l, value-only
            # boundary matching; reference atom_type aw_default_l with a
            # single descriptor — test17/test19 class). The second slot is
            # zero-padded so every consumer (fv blocks, mt_index layout,
            # density accumulation) keeps the fixed (u, udot) shape; the
            # matching coefficient B of this channel is exactly zero so the
            # pad never contributes.
            z = np.zeros_like(u)
            aw.append([
                MtRadial(l=l, f=u, hf=e0 * u, fR=uR, fpR=upR),
                MtRadial(l=l, f=z, hf=z, fR=0.0, fpR=0.0),
            ])
            aw_order.append(1)
        else:
            aw.append([
                MtRadial(l=l, f=u, hf=e0 * u, fR=uR, fpR=upR),
                MtRadial(l=l, f=ud, hf=e0 * ud + u, fR=udR, fpR=udpR),
            ])
            aw_order.append(2)
        enu_l.append(e0)
    lo = []
    lo_enu = []
    from sirius_tpu.lapw.radial_solver import radial_dme_chain

    for d in sp.lo:
        l = d.l
        # per-entry (enu, dme) solutions; entries at the same resolved
        # energy share one derivative chain
        chains: dict = {}
        comps = []  # (u, hu, uR, upR) per basis entry
        e_res = []
        for be in d.basis:
            e0 = be.enu
            if be.auto:
                n = be.n if be.n > 0 else l + 1
                e0 = find_enu(r, v_sph, l, n, rel)
            e_res.append(e0)
            key = round(e0, 12)
            need = be.dme
            if key not in chains or len(chains[key]) <= need:
                chains[key] = radial_dme_chain(r, v_sph, l, e0, rel, max_m=need)
            comps.append(chains[key][be.dme])
        lo_enu.append(min(e_res))
        ncomp = len(comps)
        if ncomp > 3:
            raise NotImplementedError(
                f"lo with {ncomp} radial components (1-3 supported)"
            )
        if ncomp == 2:
            # zero-boundary combination WITHOUT division: (ca, cb) =
            # (u1R, -u0R) gives f(R) = 0 exactly and stays stable when an
            # auto enu lands on a bound state with u(R) -> 0
            cvec = np.array([comps[1][2], -comps[0][2]])
            if np.abs(cvec).sum() < 1e-14:
                cvec = np.array([1.0, 0.0])
        elif ncomp == 1:
            cvec = np.array([1.0])
        else:
            # n-component lo (reference generate_lo_radial_functions,
            # atom_symmetry_class.cpp:206-226): surface derivatives up to
            # order n-2 vanish, the (n-1)-th is pinned to 1 —
            # A[i][j] = d^i u_j/dr^i |_R, solve A c = e_{n-1}
            def surf_d2(u):
                k = 7  # local cubic fit near the boundary
                c = np.polyfit(r[-k:] - r[-1], u[-k:], 3)
                return 2.0 * c[1]

            A = np.zeros((3, 3))
            for j, (uj, _, uRj, upRj) in enumerate(comps):
                A[0, j] = uRj
                A[1, j] = upRj
                A[2, j] = surf_d2(uj)
            rhs = np.array([0.0, 0.0, 1.0])
            try:
                cvec = np.linalg.solve(A, rhs)
            except np.linalg.LinAlgError:
                # degenerate surface matrix: drop the last component
                cvec = np.zeros(3)
                cvec[:2] = [comps[1][2], -comps[0][2]]
                if np.abs(cvec).sum() < 1e-14:
                    cvec = np.array([1.0, 0.0, 0.0])
        f = sum(c * u for c, (u, _, _, _) in zip(cvec, comps))
        hf = sum(c * hu for c, (_, hu, _, _) in zip(cvec, comps))
        fR = sum(c * uR for c, (_, _, uR, _) in zip(cvec, comps))
        fpR = sum(c * upR for c, (_, _, _, upR) in zip(cvec, comps))
        nrm = np.sqrt(rint(f * f * r * r, r))
        lo.append(
            MtRadial(l=l, f=f / nrm, hf=hf / nrm, fR=fR / nrm, fpR=fpR / nrm)
        )
    minv_R = 1.0
    # ZORA/IORA only: their interstitial kinetic carries the matching
    # theta/M correction (scf_fp kin_box); KH's mass is energy-dependent
    # and the reference treats KH interstitials non-relativistically
    if rel in ("zora", "iora"):
        from sirius_tpu.lapw.radial_solver import SQ_ALPHA_HALF

        minv_R = 1.0 / (1.0 - SQ_ALPHA_HALF * float(v_sph[-1]))
    return AtomRadialBasis(
        lmax_apw=lmax_apw, r=r, aw=aw, lo=lo, enu=enu_l, lo_enu=lo_enu,
        minv_R=minv_R, aw_order=aw_order,
    )


def sph_bessel(lmax: int, x: np.ndarray) -> np.ndarray:
    """j_l(x) for l = 0..lmax: upward recurrence where stable (x > l),
    downward (Miller) normalization elsewhere."""
    x = np.asarray(x, dtype=float)
    out = np.zeros((lmax + 1,) + x.shape)
    small = x < 1e-8
    xs = np.where(small, 1.0, x)
    j0 = np.where(small, 1.0 - x * x / 6.0, np.sin(xs) / xs)
    out[0] = j0
    if lmax >= 1:
        out[1] = np.where(small, x / 3.0, np.sin(xs) / xs**2 - np.cos(xs) / xs)
    for l in range(2, lmax + 1):
        out[l] = (2 * l - 1) / xs * out[l - 1] - out[l - 2]
    if lmax >= 2:
        bad = x < (lmax + 2.0)
        if np.any(bad):
            xb = np.where(x < 1e-8, 1e-8, x)
            nstart = lmax + 20
            jm = np.zeros((nstart + 2,) + x.shape)
            jm[nstart] = 1e-30
            for l in range(nstart - 1, -1, -1):
                jm[l] = (2 * l + 3) / xb * jm[l + 1] - jm[l + 2]
                # renormalize on the fly to avoid overflow of the downward
                # recurrence for large lmax
                big = np.abs(jm[l]) > 1e250
                if np.any(big):
                    s = np.where(big, 1e-250, 1.0)
                    jm[l:] = jm[l:] * s
            # normalize by whichever of j0/j1 is larger: j0 vanishes at
            # x = n pi (e.g. |G| R = pi for cubic-lattice stars) and
            # dividing by it there poisons every l of that shell
            j1ref = out[1] if lmax >= 1 else np.where(
                small, x / 3.0, np.sin(xs) / xs**2 - np.cos(xs) / xs
            )
            use0 = np.abs(j0) >= np.abs(j1ref)
            den = np.where(
                use0,
                np.where(np.abs(jm[0]) > 1e-280, jm[0], 1.0),
                np.where(np.abs(jm[1]) > 1e-280, jm[1], 1.0),
            )
            scale = np.where(use0, j0, j1ref) / den
            for l in range(2, lmax + 1):
                out[l] = np.where(bad, jm[l] * scale, out[l])
    return out


def sph_bessel_dx(lmax: int, x: np.ndarray) -> np.ndarray:
    """j_l'(x): j_0' = -j_1; j_l' = j_{l-1} - (l+1)/x j_l."""
    j = sph_bessel(lmax + 1, x)
    out = np.zeros_like(j[: lmax + 1])
    out[0] = -j[1]
    xs = np.where(np.asarray(x) < 1e-8, 1.0, x)
    for l in range(1, lmax + 1):
        out[l] = j[l - 1] - (l + 1) / xs * j[l]
    return out


def matching_coefficients(gkvec_cart: np.ndarray, pos_frac: np.ndarray,
                          millers: np.ndarray, k_frac: np.ndarray,
                          rmt: float, basis: AtomRadialBasis, omega: float):
    """(A, B) matching coefficients [nG, lmmax] for one atom: A multiplies
    u_l Y_lm, B multiplies udot_l Y_lm inside the sphere."""
    lmax = basis.lmax_apw
    lmmax = num_lm(lmax)
    g = np.linalg.norm(gkvec_cart, axis=1)
    ghat = gkvec_cart / np.maximum(g, 1e-12)[:, None]
    ghat[g < 1e-12] = np.array([0.0, 0.0, 1.0])
    ylm = ylm_complex(lmax, ghat)  # [nG, lmmax]
    jl = sph_bessel(lmax, g * rmt)
    djl = sph_bessel_dx(lmax, g * rmt)
    phase = np.exp(2j * np.pi * ((millers + k_frac) @ pos_frac))
    pref = 4.0 * np.pi / np.sqrt(omega) * phase
    A = np.zeros((len(g), lmmax), dtype=np.complex128)
    B = np.zeros_like(A)
    for l in range(lmax + 1):
        u, ud = basis.aw[l]
        rhs1 = jl[l]
        rhs2 = g * djl[l]
        if basis.order(l) == 1:
            # APW: match the plane-wave VALUE only with the single radial
            # function (reference matching_coefficients.hpp order-1 branch)
            a = rhs1 / u.fR
            b = np.zeros_like(rhs2)
        else:
            det = u.fR * ud.fpR - u.fpR * ud.fR
            a = (rhs1 * ud.fpR - rhs2 * ud.fR) / det
            b = (rhs2 * u.fR - rhs1 * u.fpR) / det
        il = 1j**l
        for m in range(-l, l + 1):
            lm = lm_index(l, m)
            c = pref * il * np.conj(ylm[:, lm])
            A[:, lm] = a * c
            B[:, lm] = b * c
    return A, B
