"""Full-potential density generation: muffin-tin + interstitial parts.

Reference: src/density/density.cpp (generate_valence + add_k_point_contribution_dm
for the MT density matrices, generate_rho_aug-free FP branch for the
interstitial), src/unit_cell/atom_symmetry_class.cpp for the radial-function
pair products.

MT density: inside sphere a the wave function is
  psi(r) = sum_{lm,i} W_{lm,i} f_i(r) Y_lm(r-hat),
with W from the APW matching coefficients (A, B) contracted against the
plane-wave eigenvector plus the explicit lo columns. The real-harmonic
density components are
  rho_{lm3}(r) = sum_{(lm1,i),(lm2,j)} D[(lm1,i),(lm2,j)]
                 <Y_lm1|R_lm3|Y_lm2> f_i(r) f_j(r),
  D = sum_{k,b} w_k occ_b conj(W_1) W_2.

Interstitial density: FFT of the APW plane-wave part over the fine grid,
rho_i(r) = sum_kb w occ |psi_PW(r)|^2 (valid in the interstitial; inside
spheres it is overridden by the MT expansion).
"""

from __future__ import annotations

import numpy as np

from sirius_tpu.lapw.quad import rint

from sirius_tpu.core.sht import lm_index, num_lm
from sirius_tpu.lapw.fv import gaunt_hybrid


def mt_index(basis, lmax_apw: int):
    """Flat MT expansion index for one atom.

    Returns (rf, lm_of, rf_of) where rf is the list of radial-function
    arrays [nrf][nr], lm_of[nidx] the lm of each expansion entry and
    rf_of[nidx] its radial-function index. Ordering matches the fv
    eigenvector layout: APW (u, udot) per lm first, then the atom's lo
    entries in fv.assemble_fv's lo_index order."""
    rf = []
    rf_l = []
    for l in range(lmax_apw + 1):
        for f in basis.aw[l]:
            rf.append(f.f)
            rf_l.append(l)
    lo_rf0 = len(rf)
    for f in basis.lo:
        rf.append(f.f)
        rf_l.append(f.l)
    lm_of, rf_of = [], []
    for l in range(lmax_apw + 1):
        for m in range(-l, l + 1):
            lm = lm_index(l, m)
            lm_of += [lm, lm]
            rf_of += [2 * l, 2 * l + 1]
    for ilo, f in enumerate(basis.lo):
        for m in range(-f.l, f.l + 1):
            lm_of.append(lm_index(f.l, m))
            rf_of.append(lo_rf0 + ilo)
    return rf, np.asarray(lm_of), np.asarray(rf_of)


def mt_expansion_coeffs(C, A, B, lo_cols, basis, lmax_apw: int):
    """W[nidx, nev]: MT expansion coefficients of the fv eigenvectors.

    C: [ng+nlo_total, nev] eigenvectors; A, B: [ng, lmmax] matching
    coefficients of this atom; lo_cols: list of eigenvector rows for this
    atom's lo entries in (ilo, m) order."""
    ng = A.shape[0]
    lmmax = num_lm(lmax_apw)
    nev = C.shape[1]
    wa = A.T @ C[:ng]  # [lmmax, nev]
    wb = B.T @ C[:ng]
    # interleave (u, udot) per lm
    w_apw = np.empty((2 * lmmax, nev), dtype=np.complex128)
    w_apw[0::2] = wa
    w_apw[1::2] = wb
    if lo_cols:
        w_lo = C[np.asarray(lo_cols)]
        return np.concatenate([w_apw, w_lo], axis=0)
    return w_apw


def atom_lo_cols(lo_index, ia: int, ng: int):
    """Eigenvector rows of atom ia's local orbitals, in fv column order."""
    return [ng + col for col, (ja, _, _, _) in enumerate(lo_index) if ja == ia]


def mt_density_from_dm(D, lm_of, rf_of, rf, lmax_rho: int, lmax_apw: int):
    """rho_lm[lmmax_rho, nr] (real harmonics) from the MT density matrix.

    D: [nidx, nidx] hermitian; gaunt G[lm1, lm3, lm2] = <Y1|R3|Y2>."""
    gh = gaunt_hybrid(lmax_apw, lmax_rho, lmax_apw)  # [lm1, lm3, lm2]
    nrf = len(rf)
    lmmax_rho = num_lm(lmax_rho)
    # T[rf1, rf2, lm3] = sum over entries with those radial functions
    gg = gh[lm_of[:, None], :, lm_of[None, :]]  # [nidx, nidx, lm3]
    x = D[:, :, None] * gg
    T = np.zeros((nrf, nrf, lmmax_rho), dtype=np.complex128)
    np.add.at(T, (rf_of[:, None], rf_of[None, :]), x)
    F = np.stack(rf)  # [nrf, nr]
    rho = np.einsum("abL,ar,br->Lr", T, F, F, optimize=True)
    return np.ascontiguousarray(rho.real)


def interstitial_density_box(C_k_list, gkmill_list, occ, kweights, dims, omega):
    """rho(r) on the fine FFT grid from the APW plane-wave parts.

    C_k_list[ik]: [ng_k + nlo, nev]; gkmill_list[ik]: [ng_k, 3];
    occ: [nk, nev] (already includes max_occupancy); kweights: [nk]."""
    n = dims[0] * dims[1] * dims[2]
    rho_r = np.zeros(dims)
    for ik, (C, mill) in enumerate(zip(C_k_list, gkmill_list)):
        ng = len(mill)
        i0 = np.mod(mill[:, 0], dims[0])
        i1 = np.mod(mill[:, 1], dims[1])
        i2 = np.mod(mill[:, 2], dims[2])
        for ib in range(C.shape[1]):
            f = kweights[ik] * occ[ik, ib]
            if f < 1e-12:
                continue
            box = np.zeros(dims, dtype=np.complex128)
            box[i0, i1, i2] = C[:ng, ib]
            psi = np.fft.ifftn(box) * n / np.sqrt(omega)
            rho_r += f * np.abs(psi) ** 2
    return rho_r


def free_atom_rho_mt(sp, lmax_rho: int) -> np.ndarray:
    """Initial MT density: the species' free-atom density interpolated on
    the MT grid, in the lm=0 real-harmonic channel."""
    lmmax = num_lm(lmax_rho)
    rho = np.zeros((lmmax, sp.nrmt))
    rho_sph = np.interp(sp.r, sp.free_atom_r, sp.free_atom_density)
    rho[0] = rho_sph * np.sqrt(4.0 * np.pi)
    return rho


def free_atom_rho_g(species_by_atom, positions, millers, gcart, omega):
    """Superposition of free-atom densities in plane waves over the fine
    G set: rho(G) = (1/Omega) sum_a e^{-i G r_a} 4 pi
    int rho_a(r) j0(Gr) r^2 dr (reference density.cpp initial density)."""
    glen = np.linalg.norm(gcart, axis=1)
    shells, inv = np.unique(np.round(glen, 10), return_inverse=True)
    out = np.zeros(len(gcart), dtype=np.complex128)
    cache = {}
    for ia, sp in enumerate(species_by_atom):
        key = id(sp)
        if key not in cache:
            r = sp.free_atom_r
            rho = sp.free_atom_density
            ff = np.empty(len(shells))
            for i, g in enumerate(shells):
                if g < 1e-12:
                    ff[i] = 4.0 * np.pi * rint(rho * r * r, r)
                else:
                    ff[i] = 4.0 * np.pi * rint(
                        rho * np.sinc(g * r / np.pi) * r * r, r
                    )
            cache[key] = ff
        phase = np.exp(-2j * np.pi * (millers @ positions[ia]))
        out += cache[key][inv] * phase / omega
    return out
