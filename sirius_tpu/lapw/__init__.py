"""FP-LAPW subsystem: radial solvers, APW matching, first-variational
Hamiltonian (reference src/radial, src/lapw, src/hamiltonian/diagonalize_fp)."""
